"""int8 KV cache (ISSUE 11): per-head absmax quantization behind
Engine(kv_dtype='int8'), in both KV layouts, for all three families.

The contract is the attn_impl parity-TOLERANCE pattern: int8 KV is
numerically close to the bf16 cache, never bitwise — so the pins here
are (a) the elementwise round-trip error bound the scheme guarantees
(<= scale/2 per element), (b) logits closeness of prefill + decode
through `_forward_cached` with the quantized kv_ops vs the dense path,
per family x layout, and (c) interpret-mode closeness of the fused
Pallas int8 kernels (slab decode + paged decode) against the dequant
reference. Engine-level e2e (drain clean, audits pass, knobs compose
with spec decoding) rides the same file.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import _attend_cached, _forward_cached, \
    init_cache
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.models.llama import Llama, LlamaConfig
from avenir_tpu.models.mixtral import Mixtral, MixtralConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.ops.kv_quant import QuantKV, dequantize, init_quant_kv, \
    quant_slab_kv_ops, quantize
from avenir_tpu.serve import Engine
from avenir_tpu.serve.pages import paged_kv_ops

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
LLAMA_KW = dict(block_size=64, vocab_size=64, n_layer=1, n_head=4,
                n_kv_head=2, n_embd=32, ffn_hidden=64, dropout=0.0,
                attn_impl="xla")
# absmax-int8 error: <= scale/2 per element pre-softmax; through one
# attention layer + lm head on these tiny models the measured logits
# drift is ~1e-2 — the tolerance pins 5x that, tight enough that a
# broken scale layout (per-tensor, transposed heads) fails loudly
LOGITS_ATOL = 5e-2


def _family(name):
    if name == "gpt":
        return GPT(GPT_TINY, rngs=nnx.Rngs(0)), 2, 16
    if name == "llama":
        return Llama(LlamaConfig(**LLAMA_KW), rngs=nnx.Rngs(0)), 2, 8
    return Mixtral(MixtralConfig(n_experts=4, n_experts_per_tok=2,
                                 capacity_factor=2.0, **LLAMA_KW),
                   rngs=nnx.Rngs(0)), 2, 8


def test_quantize_roundtrip_error_bound():
    """The scheme's guarantee: per-element |dequant - x| <= scale/2,
    scale = amax/127 per (position, head)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 3.0, (4, 9, 2, 16)).astype(np.float32))
    data, scale = quantize(x)
    assert data.dtype == jnp.int8 and scale.shape == (4, 9, 2)
    err = np.abs(np.asarray(dequantize(QuantKV(data, scale), jnp.float32))
                 - np.asarray(x))
    bound = np.asarray(scale)[..., None] / 2 + 1e-6
    assert (err <= bound).all()
    # zero rows stay exactly zero through the scale floor
    z = jnp.zeros((1, 3, 2, 16))
    zd, zs = quantize(z)
    assert np.asarray(dequantize(QuantKV(zd, zs), jnp.float32)).max() == 0.0


def test_quant_slab_write_attend_close():
    """Write random K/V through the quantized slab ops and attend;
    output must be close to the dense write+attend on the same data."""
    rng = np.random.default_rng(1)
    B, T, Hkv, D, H = 3, 12, 2, 16, 4
    k = jnp.asarray(rng.normal(0, 1, (B, T, Hkv, D)).astype(np.float32))
    v = jnp.asarray(rng.normal(0, 1, (B, T, Hkv, D)).astype(np.float32))
    q = jnp.asarray(rng.normal(0, 1, (B, 1, H, D)).astype(np.float32))
    write, attend = quant_slab_kv_ops(jnp.float32)
    kc = init_quant_kv((B, 16, Hkv, D))
    vc = init_quant_kv((B, 16, Hkv, D))
    # per-row writes at position 0 (the (B,) vector form)
    kc, vc = write(kc, vc, k, v, jnp.zeros((B,), jnp.int32))
    q_pos = jnp.full((B, 1), T - 1, jnp.int32)
    got = attend(q, kc, vc, q_pos)
    kd = jnp.zeros((B, 16, Hkv, D)).at[:, :T].set(k)
    vd = jnp.zeros((B, 16, Hkv, D)).at[:, :T].set(v)
    want = _attend_cached(q, kd, vd, q_pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=3e-2, rtol=0)


@pytest.mark.parametrize("family", ["gpt", "llama", "mixtral"])
@pytest.mark.parametrize("layout", ["slab", "paged"])
def test_int8_forward_logits_tolerance(family, layout):
    """The parity-tolerance pin (the attn_impl contract split): prefill
    + one decode step through `_forward_cached` with int8 kv_ops vs the
    dense cache — logits within LOGITS_ATOL, per family x layout.
    Eager, engine-free: one test covers the whole quantize-on-write /
    dequant-on-attend path the engines route through."""
    model, n_kv, hd = _family(family)
    prompt = jnp.asarray([5, 7, 11, 13, 17, 19], jnp.int32)[None]
    T0 = prompt.shape[1]

    dense = init_cache(n_layer=1, batch=1, max_t=16, n_kv_head=n_kv,
                       head_dim=hd, dtype=jnp.float32)
    ref_logits, dense = _forward_cached(model, prompt, dense, 0)

    if layout == "slab":
        shape = (1, 1, 16, n_kv, hd)
        qcache = type(dense)(init_quant_kv(shape), init_quant_kv(shape))
        kv = quant_slab_kv_ops(jnp.float32)
    else:
        # one sequence across 4-token pages, identity-ish table
        shape = (1, 4, 4, n_kv, hd)
        qcache = type(dense)(init_quant_kv(shape), init_quant_kv(shape))
        kv = paged_kv_ops(jnp.asarray([[0, 1, 2, 3]], jnp.int32),
                          n_pages=4, page_size=4, kv_dtype="int8",
                          compute_dtype=jnp.float32, n_real=T0)
    got_logits, qcache = _forward_cached(model, prompt, qcache, 0,
                                         kv_ops=kv, last_index=T0 - 1)
    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(ref_logits), atol=LOGITS_ATOL,
                               rtol=0)
    # one decode step at per-row positions over the quantized cache
    nxt = jnp.asarray([[23]], jnp.int32)
    ref_step, _ = _forward_cached(model, nxt, dense,
                                  jnp.asarray([T0], jnp.int32))
    if layout == "paged":
        kv = paged_kv_ops(jnp.asarray([[0, 1, 2, 3]], jnp.int32),
                          n_pages=4, page_size=4, kv_dtype="int8",
                          compute_dtype=jnp.float32)
    got_step, _ = _forward_cached(model, nxt, qcache,
                                  jnp.asarray([T0], jnp.int32), kv_ops=kv)
    np.testing.assert_allclose(np.asarray(got_step),
                               np.asarray(ref_step), atol=LOGITS_ATOL,
                               rtol=0)


def test_int8_engine_e2e_both_layouts():
    """Engine-level smoke in both layouts: int8 engines serve mixed
    requests to completion, greedy streams match the bf16 engine on
    this (comfortably-gapped) tiny model, and the paged allocator
    audits clean — plus the kv_dtype gauge reads 8."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [11, 12, 13, 14, 15, 16, 17]]

    def run(**kw):
        reg = MetricsRegistry()
        eng = Engine(model, n_slots=2, max_seq_len=32, registry=reg,
                     **kw)
        ids = {}
        for i, p in enumerate(prompts):
            ids[eng.submit(p, max_new_tokens=6, temperature=1.0,
                           top_k=1, rng=jax.random.key(100 + i))] = i
        out = {ids[f.req_id]: f for f in eng.drain()}
        return eng, reg, [out[i].tokens for i in range(len(prompts))]

    _, _, ref = run()
    eng_s, reg_s, got_s = run(kv_dtype="int8")
    assert got_s == ref
    assert reg_s.snapshot()["gauges"]["kv_dtype"] == 8
    eng_p, _, got_p = run(kv_dtype="int8", kv_impl="paged", page_size=4)
    assert got_p == ref
    eng_p._paged.audit(expect_empty=True)


@pytest.mark.slow
def test_int8_composes_with_spec_decode():
    """All ISSUE 11 knobs on at once (paged + int8 + spec): requests
    finish, greedy output matches the bf16 sequential engine on the
    tiny model, one spec-step compile."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    draft = GPT(GPT_TINY, rngs=nnx.Rngs(5))
    from avenir_tpu.infer.decode import generate_cached

    prompt = [1, 2, 3, 4, 5]
    ref = np.asarray(generate_cached(
        model, jax.random.key(3), jnp.asarray(prompt, jnp.int32)[None],
        6, temperature=1.0, top_k=1))[0]
    eng = Engine(model, n_slots=2, max_seq_len=32,
                 registry=MetricsRegistry(), kv_impl="paged", page_size=4,
                 kv_dtype="int8", spec_decode="draft", spec_k=3,
                 draft_model=draft)
    eng.submit(prompt, max_new_tokens=6, temperature=1.0, top_k=1,
               rng=jax.random.key(3))
    done = eng.drain()
    assert done[0].tokens == [int(t) for t in ref]
    assert len(eng.traces["step"]) == 1
    eng._paged.audit(expect_empty=True)


def test_pallas_decode_attention_int8_interpret():
    """The fused slab int8 decode kernel (interpret mode) vs the
    dequant + dense reference — same numerics contract as attn_impl."""
    from avenir_tpu.ops.pallas.flash_attention import decode_attention_int8

    rng = np.random.default_rng(2)
    B, T, Hkv, D, G = 3, 24, 2, 16, 2
    H = Hkv * G
    k = rng.normal(0, 1, (B, T, Hkv, D)).astype(np.float32)
    v = rng.normal(0, 1, (B, T, Hkv, D)).astype(np.float32)
    q = rng.normal(0, 1, (B, H, D)).astype(np.float32)
    lengths = np.asarray([5, 24, 13], np.int32)
    kd, ks = quantize(jnp.asarray(k))
    vd, vs = quantize(jnp.asarray(v))
    got = decode_attention_int8(jnp.asarray(q), kd, ks, vd, vs,
                                jnp.asarray(lengths), block_t=8,
                                interpret=True)
    kq = np.asarray(dequantize(QuantKV(kd, ks), jnp.float32))
    vq = np.asarray(dequantize(QuantKV(vd, vs), jnp.float32))
    want = _attend_cached(jnp.asarray(q)[:, None], jnp.asarray(kq),
                          jnp.asarray(vq),
                          jnp.asarray(lengths - 1)[:, None])[:, 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_pallas_paged_attention_int8_interpret():
    """The fused paged int8 kernel (interpret mode) vs the dequant
    gather reference."""
    from avenir_tpu.ops.pallas.paged_attention import paged_attention_int8

    rng = np.random.default_rng(3)
    n_pages, ps, Hkv, D, G = 8, 4, 2, 16, 2
    H = Hkv * G
    B, P = 2, 4
    kp = rng.normal(0, 1, (n_pages, ps, Hkv, D)).astype(np.float32)
    vp = rng.normal(0, 1, (n_pages, ps, Hkv, D)).astype(np.float32)
    q = rng.normal(0, 1, (B, H, D)).astype(np.float32)
    tables = jnp.asarray([[6, 1, 3, 0], [2, 7, 0, 0]], jnp.int32)
    lengths = jnp.asarray([14, 6], jnp.int32)
    kd, ks = quantize(jnp.asarray(kp))
    vd, vs = quantize(jnp.asarray(vp))
    got = paged_attention_int8(jnp.asarray(q), kd, ks, vd, vs, tables,
                               lengths, interpret=True)
    kq = np.asarray(dequantize(QuantKV(kd, ks), jnp.float32))
    vq = np.asarray(dequantize(QuantKV(vd, vs), jnp.float32))
    kg = kq[np.asarray(tables)].reshape(B, P * ps, Hkv, D)
    vg = vq[np.asarray(tables)].reshape(B, P * ps, Hkv, D)
    want = _attend_cached(jnp.asarray(q)[:, None], jnp.asarray(kg),
                          jnp.asarray(vg),
                          (lengths - 1)[:, None])[:, 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)