"""Live weight lifecycle tests (serve/rollout.py + the router /
cache-map / supervisor wiring, ISSUE 20): a rolling rollout converges
with zero lost requests and actually serves the NEW weights, the
canary analysis auto-rolls-back a poisoned version before it reaches
the fleet, a SIGKILL'd canary mid-swap cannot stall (or version-split)
the campaign, the mixing-window bound is a real backstop, and — the
KV-safety pin — a weight swap fences the replica's old cache-map
advertisement so no chain is ever reused across a version boundary.

Budget notes: driven clocks everywhere tier-1 (deterministic, no
sleeps); one module-scoped tiny GPT pair (v1/v2 = different init
seeds). The process-backend SIGKILL-mid-swap drill and the wall-clock
bench ride the slow lane; the tier-1 bench smoke is the same two
campaigns at reduced load.
"""

import os
import signal

import jax
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.obs.trace import Tracer
from avenir_tpu.serve import Router
from avenir_tpu.serve.cache_map import FleetCacheMap
from avenir_tpu.serve.pages import chain_digest
from avenir_tpu.serve.rollout import canary_detectors, version_number

GPT_TINY = GPTConfig(block_size=64, vocab_size=128, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
_SILENT = lambda _s: None  # noqa: E731 — decisions stay in ro.decisions


@pytest.fixture(scope="module")
def model():
    return GPT(GPT_TINY, rngs=nnx.Rngs(0))


@pytest.fixture(scope="module")
def model2():
    """The target generation: same config, different weights — a swap
    that actually landed is observable in the served tokens."""
    return GPT(GPT_TINY, rngs=nnx.Rngs(1))


@pytest.fixture(scope="module")
def state2(model2):
    return nnx.split(model2)[1]


class FakeFin:
    """Synthetic terminal record for the canary analysis feed — the
    public `observe()` contract reads exactly these attrs."""

    def __init__(self, replica, ttft_ms, tpot_ms=10.0, n_out=4):
        self.replica = replica
        self.ttft_ms = ttft_ms
        self.tpot_ms = tpot_ms
        self.n_out = n_out
        self.finish_reason = "length"


# ---------------------------------------------------------------------
# 1. pure pieces
# ---------------------------------------------------------------------


def test_version_number_parses_and_ordinals():
    assert version_number("iter-00000120") == 120
    assert version_number("v2") == 2
    assert version_number(7) == 7
    a, b = version_number("alpha"), version_number("bravo")
    assert a != b and version_number("alpha") == a  # stable ordinals


def test_canary_detector_panel():
    dets = {d.name: d for d in canary_detectors()}
    assert set(dets) == {"ttft_drift", "tpot_drift",
                        "accept_rate_collapse"}
    # rebalancing bias: a just-swapped canary rejoins empty and takes a
    # fair-share burst — tenths of relative rise are mechanics, not
    # weights — and nothing re-fires after the verdict, so no cooldown
    assert dets["ttft_drift"].min_rel == 0.5
    assert dets["tpot_drift"].min_rel == 0.5
    assert all(d.cooldown_s == 0.0 for d in dets.values())
    tuned = {d.name: d for d in canary_detectors(
        {"ttft_drift": {"sustain": 3, "min_windows": 5}})}
    assert tuned["ttft_drift"].sustain == 3
    assert tuned["ttft_drift"].min_windows == 5
    assert tuned["tpot_drift"].sustain == 2  # others untouched


def test_rollout_guards(model, state2):
    t = [0.0]
    router = Router(model, n_replicas=2, n_slots=2,
                    registry=MetricsRegistry(), seed=0,
                    clock=lambda: t[0])
    with pytest.raises(ValueError):  # inproc needs the target state
        router.rollout("v2", echo=_SILENT)
    with pytest.raises(AssertionError):  # fleet already serves "0"
        router.rollout("0", state=state2, echo=_SILENT)
    ro = router.rollout("v2", state=state2, echo=_SILENT,
                        baseline_min_requests=0, canary_min_requests=0)
    assert router.rollout_active
    with pytest.raises(RuntimeError):  # one campaign at a time
        router.rollout("v3", state=state2, echo=_SILENT)
    while ro.active:
        t[0] += 0.1
        router.step()
    assert not router.rollout_active


# ---------------------------------------------------------------------
# 2. cross-version KV safety (the satellite pin: these FAIL on a
#    version-blind map)
# ---------------------------------------------------------------------


def test_cache_map_version_fencing():
    """An advertisement recorded under one weight version must score 0
    against a fleet view where that replica now serves another — KV
    only attaches under the exact weights that produced it."""
    cm = FleetCacheMap(clock=lambda: 0.0)
    prompt = list(range(16))
    nodes = {chain_digest(prompt[:8]): [8, 1, 0, 0, 0.0]}
    cm.update(0, nodes, version="v1")
    assert cm.version(0) == "v1"
    # version-blind callers (telemetry) keep the old behavior
    assert cm.match(prompt) == {0: 8}
    # same version: matches
    assert cm.match(prompt, versions={0: "v1"}) == {0: 8}
    # the replica swapped since advertising: fenced to zero
    assert cm.match(prompt, versions={0: "v2"}) == {0: 0}
    assert cm.best_match(prompt, versions={0: "v2"}) == (None, 0)
    # unknown current version (not in the live view): fenced too
    assert cm.match(prompt, versions={}) == {0: 0}
    # a swap's drop() forgets the advertisement outright
    cm.drop(0)
    assert cm.match(prompt) == {} and cm.version(0) is None


def test_router_fleet_version_view_fences_stale_advertisement(model):
    """Router-level: prime a replica's chain advertisement, then flip
    the weights under it (the swap race: map updated before the swap,
    match after) — the router's live version view must zero it so
    affinity placement / peer pulls can never cross the boundary."""
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=2, registry=reg,
                    seed=0, cache_telescope=True, affinity=True,
                    engine_kwargs=dict(kv_impl="paged", page_size=8,
                                       n_pages=48, prefill_chunk=16))
    prefix = [int(x) for x in
              np.random.default_rng(7).integers(0, 128, 24)]
    router.submit(prefix + [1, 2], max_new_tokens=4, temperature=1.0,
                  top_k=8)
    done = router.drain()
    assert len(done) == 1
    cm = router._cache_map
    warm = cm.match(prefix, versions=router._fleet_versions())
    warm_rid, depth = max(warm.items(), key=lambda kv: kv[1])
    assert depth >= 16, warm  # the chain is advertised and matchable
    # the swap lands; the map has not refreshed yet
    rep = router._rep(warm_rid)
    rep.engine.weight_version = "v2"
    fenced = cm.match(prefix, versions=router._fleet_versions())
    assert fenced[warm_rid] == 0, (
        "a post-swap replica's old advertisement won a match across "
        "the weight-version boundary")
    router.close()


# ---------------------------------------------------------------------
# 3. the campaigns (driven clock, deterministic)
# ---------------------------------------------------------------------


def _pump(router, t, n=1, dt=0.05):
    out = []
    for _ in range(n):
        t[0] += dt
        out.extend(router.step())
    return out


def test_forward_rollout_converges_zero_lost(model, model2, state2,
                                             tmp_path):
    """The tentpole forward path under live load: baseline -> canary ->
    rolling, zero requests lost, bounded mixing window, every replica
    converged on the target — and the fleet then actually SERVES the
    new weights (parity vs one-shot generation on the v2 module)."""
    t = [0.0]
    reg = MetricsRegistry()
    tracer = Tracer(registry=reg, clock=lambda: t[0],
                    out_dir=str(tmp_path))
    router = Router(model, n_replicas=3, n_slots=2, registry=reg,
                    seed=0, clock=lambda: t[0], tracer=tracer)
    rng = np.random.default_rng(0)
    done, submitted = [], 0

    def load(n=1):
        nonlocal submitted
        for _ in range(n):
            router.submit([int(x) for x in rng.integers(0, 128, 6)],
                          max_new_tokens=6, temperature=1.0, top_k=None)
            submitted += 1

    load(6)
    done.extend(_pump(router, t, 10))
    ro = router.rollout("v2", state=state2, window_s=0.25,
                        baseline_min_requests=6, canary_min_requests=4,
                        max_mixing_s=60.0, echo=_SILENT)
    for i in range(2000):
        if not ro.active:
            break
        if i % 2 == 0:
            load(1)
        done.extend(_pump(router, t, 1))
    assert not ro.active, f"campaign never converged: {ro.status()}"
    done.extend(router.drain())

    st = ro.status()
    assert st["phase"] == "done" and not st["rolled_back"], st
    assert all(r.weight_version == "v2" for r in router.replicas)
    assert ro.mixing_s is not None and 0 < ro.mixing_s <= 60.0
    # zero lost: every submit reached exactly one terminal record
    assert len(done) == submitted
    assert {f.finish_reason for f in done} <= {"length", "stop"}
    snap = reg.snapshot()
    assert snap["counters"]["rollouts"] == 1
    assert snap["counters"].get("rollbacks", 0) == 0
    assert snap["gauges"]["weight_version"] == version_number("v2")
    # the auditable decision trail, trace-event side (flat attrs)
    evs = [e for e in tracer.events() if e.get("ev") == "rollout"]
    actions = [e["action"] for e in evs]
    assert actions[0] == "begin" and actions[-1] == "done"
    assert "canary_start" in actions and "canary_pass" in actions
    assert actions.count("swap_done") == 3  # canary + two rolling
    d0 = next(e for e in evs if e["action"] == "done")
    assert d0["from_version"] == "0" and d0["to_version"] == "v2"
    assert d0["swaps"] == 3 and d0["mixing_s"] == ro.mixing_s
    # the swap landed for real: served tokens match the v2 module
    key = jax.random.key(1234)
    prompt = [int(x) for x in rng.integers(0, 128, 6)]
    router.submit(prompt, max_new_tokens=6, temperature=1.0, top_k=8,
                  rng=key)
    (f,) = router.drain()
    import jax.numpy as jnp

    ref = [int(x) for x in np.asarray(generate_cached(
        model2, key, jnp.asarray(prompt, jnp.int32)[None], 6,
        temperature=1.0, top_k=8))[0]]
    assert f.tokens == ref, "fleet is not serving the target weights"


def test_poisoned_canary_auto_rollback(model, state2, tmp_path):
    """The canary verdict: feed the campaign a fleet baseline, let the
    canary swap land, then stream 10x-TTFT canary records through the
    public observe() — the drift detector fires, the campaign
    rolls back before the version ever reaches a second replica, and
    the fleet converges back on the old generation."""
    t = [0.0]
    reg = MetricsRegistry()
    tracer = Tracer(registry=reg, clock=lambda: t[0],
                    out_dir=str(tmp_path))
    router = Router(model, n_replicas=3, n_slots=2, registry=reg,
                    seed=0, clock=lambda: t[0], tracer=tracer)
    ro = router.rollout("v2", state=state2, window_s=0.25,
                        baseline_min_requests=8, canary_min_requests=4,
                        canary_hold_s=30.0, echo=_SILENT)
    # fleet baseline under the old weights: ~90 ms TTFT, all replicas
    for _ in range(40):
        t[0] += 0.1
        ro.observe([FakeFin(r, 90.0 + (r - 1)) for r in range(3)],
                   now=t[0])
        ro.poll(t[0])
    assert ro.phase == "canary", ro.status()
    canary = ro.canary_replica
    assert router._rep(canary).weight_version == "v2"
    # only ONE replica ever saw the target version
    on_target = [r.replica_id for r in router.replicas
                 if r.weight_version == "v2"]
    assert on_target == [canary]
    t[0] += ro.settle_s + 0.1  # past the post-swap blackout
    for _ in range(60):
        if ro.rolled_back:
            break
        t[0] += 0.1
        ro.observe([FakeFin(canary, 900.0)], now=t[0])
        ro.poll(t[0])
    assert ro.rolled_back and ro.rollback_reason == "canary_anomaly", \
        ro.status()
    for _ in range(50):
        if not ro.active:
            break
        t[0] += 0.1
        ro.poll(t[0])
    st = ro.status()
    assert st["phase"] == "done" and not ro.active
    assert all(r.weight_version == "0" for r in router.replicas), (
        "rollback did not converge the fleet back to the old version")
    snap = reg.snapshot()["counters"]
    assert snap["rollbacks"] == 1
    assert snap["canary_anomalies"] >= 1
    # the rollback decision carries the detector evidence, flat attrs
    rb = next(e for e in tracer.events()
              if e.get("ev") == "rollout"
              and e["action"] == "rollback_begin")
    assert rb["reason"] == "canary_anomaly"
    assert rb["anomaly"]["detector"] == "ttft_drift"
    assert rb["anomaly"]["value"] > rb["anomaly"]["baseline"]


def test_swap_transient_records_are_blacked_out(model, state2):
    """The settle blackout: records produced while a swap is in flight
    — or within settle_s after it lands — never reach the detectors
    (the campaign's own capacity transient must not read as a weight
    regression; observed live as a z 8.6 self-rollback)."""
    t = [0.0]
    router = Router(model, n_replicas=3, n_slots=2,
                    registry=MetricsRegistry(), seed=0,
                    clock=lambda: t[0])
    ro = router.rollout("v2", state=state2, window_s=0.25,
                        baseline_min_requests=4, canary_min_requests=4,
                        canary_hold_s=30.0, echo=_SILENT)
    for _ in range(30):
        t[0] += 0.1
        ro.observe([FakeFin(r, 90.0) for r in range(3)], now=t[0])
        ro.poll(t[0])
    assert ro.phase == "canary"
    canary = ro.canary_replica
    # inside the blackout: even grotesque records are ignored
    for _ in range(8):
        t[0] += 0.05
        assert t[0] < ro._t_settle
        ro.observe([FakeFin(canary, 5000.0)], now=t[0])
        ro.poll(t[0])
    assert not ro.rolled_back and ro._canary_seen == 0
    # past it: clean canary records accumulate, no false fire
    t[0] = ro._t_settle + 0.01
    for _ in range(20):
        t[0] += 0.1
        ro.observe([FakeFin(canary, 95.0)], now=t[0])
        ro.poll(t[0])
    assert not ro.rolled_back and ro._canary_seen == 20


def test_kill_canary_mid_swap_rollout_resumes(model, state2):
    """Chaos twin (tier-1, driven clock): the canary dies mid-drain.
    Inproc nobody respawns it — the campaign must log swap_dead,
    re-pick a canary from the survivors, and still converge with zero
    accepted requests lost (the corpse's work fails over normally)."""
    t = [0.0]
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, registry=reg,
                    seed=0, clock=lambda: t[0])
    rng = np.random.default_rng(3)
    done, submitted = [], 0

    def load(n=1, long=False):
        nonlocal submitted
        for _ in range(n):
            router.submit([int(x) for x in rng.integers(0, 128, 6)],
                          max_new_tokens=24 if long else 6,
                          temperature=1.0, top_k=None)
            submitted += 1

    load(6, long=True)  # long streams keep every replica busy
    done.extend(_pump(router, t, 3))
    assert all(r.busy for r in router.replicas)
    # mins=0: the canary drain starts on the first poll, while the
    # canary is still mid-stream on its long requests — the kill below
    # lands genuinely mid-swap, deterministically
    ro = router.rollout("v2", state=state2, window_s=0.25,
                        baseline_min_requests=0, canary_min_requests=0,
                        detectors=[], max_mixing_s=120.0, echo=_SILENT)
    for _ in range(200):
        if ro.phase == "canary_swap":
            break
        done.extend(_pump(router, t, 1))
    assert ro.phase == "canary_swap"
    victim = ro.canary_replica
    assert router._rep(victim).state == "draining"
    assert router._rep(victim).busy  # genuinely mid-swap
    router.kill_replica(victim)  # SIGKILL's inproc twin
    for i in range(3000):
        if not ro.active:
            break
        if i % 3 == 0 and submitted < 40:
            load(1)
        done.extend(_pump(router, t, 1))
    assert not ro.active and not ro.rolled_back, ro.status()
    done.extend(router.drain())
    assert len(done) == submitted  # zero lost, failover included
    assert {f.finish_reason for f in done} <= {"length", "stop"}
    assert any(f.failovers > 0 for f in done)
    by_action = [d["action"] for d in ro.decisions]
    assert "swap_dead" in by_action  # the death was adjudicated
    assert by_action.count("canary_start") == 2  # re-picked
    survivors = [r for r in router.replicas
                 if r.replica_id != victim]
    assert router._rep(victim).state == "dead"
    assert all(r.weight_version == "v2" for r in survivors), (
        "a survivor was left behind on the old version")


def test_mixing_window_exceeded_rolls_back(model, state2):
    """The version-mixing bound is a backstop, not telemetry: a fleet
    that cannot finish rolling (here: the SLO gate never opens) rolls
    BACK at max_mixing_s rather than serving two versions forever."""

    class Burn:
        def burn_rate(self):
            return 9.9  # forward swaps gated shut forever

    t = [0.0]
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, registry=reg,
                    seed=0, clock=lambda: t[0])
    ro = router.rollout("v2", state=state2, window_s=0.25,
                        baseline_min_requests=0, canary_min_requests=0,
                        detectors=[], slo=Burn(), hold_burn=1.0,
                        max_mixing_s=1.0, echo=_SILENT)
    for _ in range(100):
        if not ro.active:
            break
        t[0] += 0.1
        router.step()
    assert not ro.active
    assert ro.rolled_back
    assert ro.rollback_reason == "mixing_window_exceeded"
    assert all(r.weight_version == "0" for r in router.replicas)
    assert reg.snapshot()["counters"]["rollbacks"] == 1
    # only the canary ever carried the target — the gate held
    swaps = [d for d in ro.decisions if d["action"] == "swap_done"]
    fwd = [d for d in swaps if d.get("version") == "v2"]
    assert len(fwd) == 1


# ---------------------------------------------------------------------
# 4. benches
# ---------------------------------------------------------------------


def test_rollout_bench_smoke(tmp_path):
    """serve_bench --rollout --smoke, the tier-1 acceptance twin: a
    clean campaign and a poisoned one under real paced load — zero
    lost, rollback on the poison, artifact ok=true."""
    import json

    from tools.serve_bench import rollout_bench

    out = tmp_path / "BENCH_rollout_smoke.json"
    rc = rollout_bench({"rollout": "1", "smoke": "1",
                        "out": str(out)})
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["ok"] is True
    assert art["requests"]["lost"] == 0
    assert art["campaigns"]["clean"]["rolled_back"] is False
    assert art["campaigns"]["poisoned"]["rolled_back"] is True
    assert art["campaigns"]["poisoned"]["rollback_reason"] \
        == "canary_anomaly"
    # the decision log renders through fleet_report
    assert art["fleet_report"]["rollout_decisions"]


@pytest.mark.slow
def test_process_sigkill_mid_swap_respawns_on_target(model, model2):
    """THE chaos drill, process backend: a REAL SIGKILL to the canary
    worker mid-swap. Its respawn spec was retargeted before the drain
    began, so the supervisor brings it back ON THE TARGET VERSION and
    the rollout resumes — old weights cannot be resurrected
    mid-campaign, and nothing is lost."""
    from avenir_tpu.serve.proc import model_spec_from_model

    reg = MetricsRegistry()
    router = Router(model, backend="process", supervise=True,
                    n_replicas=2, n_slots=2, max_seq_len=32,
                    registry=reg, seed=0)
    try:
        rng = np.random.default_rng(5)
        submitted = 0
        done = []
        for _ in range(4):
            router.submit([int(x) for x in rng.integers(0, 128, 6)],
                          max_new_tokens=20, temperature=1.0,
                          top_k=None)
            submitted += 1
        for _ in range(3):
            done.extend(router.step())
        assert all(r.busy for r in router.replicas)
        ro = router.rollout("v2", spec=model_spec_from_model(model2),
                            baseline_min_requests=0,
                            canary_min_requests=0, detectors=[],
                            max_mixing_s=600.0, echo=_SILENT)
        for _ in range(50):
            if ro.phase == "canary_swap":
                break
            done.extend(router.step())
        assert ro.phase == "canary_swap"
        victim = router._rep(ro.canary_replica)
        assert victim.state == "draining" and victim.busy
        os.kill(victim.pid, signal.SIGKILL)
        import time as _time

        deadline = _time.monotonic() + 240.0
        while ro.active and _time.monotonic() < deadline:
            done.extend(router.step())
        assert not ro.active and not ro.rolled_back, ro.status()
        done.extend(router.drain())
        assert len(done) == submitted  # zero lost through the kill
        assert victim.deaths == 1  # it really died and came back
        assert all(r.weight_version == "v2" for r in router.replicas), (
            "the respawn resurrected the old weights")
        assert reg.snapshot()["counters"]["replica_respawns"] >= 1
        assert not any(d["action"] == "swap_dead" for d in ro.decisions)
    finally:
        router.close()
