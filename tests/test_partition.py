"""Partition-rule and mesh unit tests (SURVEY.md §4 "Unit: sharding"):
fail-loud on unmatched params (SNIPPETS.md:31 policy), full rule coverage
per model family, divisibility sanitization, mesh-spec parsing, and the
mesh-gated constrain()."""

import numpy as np
import pytest

import jax
from flax import nnx
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.parallel.mesh import AXES, make_mesh, parse_mesh_shape
from avenir_tpu.parallel.partition import (
    NO_QUANT,
    QUANT,
    PrecisionPolicy,
    constrain,
    has_scan_segment,
    match_partition_rules,
    match_precision_rules,
    precision_for,
    rules_for_model,
    sanitize_specs,
)


def test_unmatched_param_raises():
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(rules_for_model("gpt"),
                              [("mystery", "kernel")])


@pytest.mark.parametrize("family,ctor_info", [
    ("gpt", None), ("llama", None), ("mixtral", None),
])
def test_rules_cover_every_param(family, ctor_info):
    if family == "gpt":
        from avenir_tpu.models.gpt import GPT, GPTConfig

        model = nnx.eval_shape(lambda: GPT(
            GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                      n_embd=32), rngs=nnx.Rngs(0)))
    elif family == "llama":
        from avenir_tpu.models.llama import Llama, LlamaConfig

        model = nnx.eval_shape(lambda: Llama(
            LlamaConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                        n_kv_head=1, n_embd=32, ffn_hidden=64),
            rngs=nnx.Rngs(0)))
    else:
        from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

        model = nnx.eval_shape(lambda: Mixtral(
            MixtralConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                          n_kv_head=1, n_embd=32, ffn_hidden=64,
                          n_experts=4), rngs=nnx.Rngs(0)))
    paths = [p for p, _ in nnx.state(model, nnx.Param).flat_state()]
    specs = match_partition_rules(rules_for_model(family), paths)
    assert set(specs) == set(paths)


# ---------------------------------------------------------------------------
# the unified rules table (ISSUE 15 refactor)
# ---------------------------------------------------------------------------

# The pre-refactor hand-wired per-family tables, kept VERBATIM as test
# fixtures: the unified table's resolved specs must be bit-equal to
# these for every param of every family (the bf16 path through the new
# table is the old path).
LEGACY_GPT_RULES = (
    (r"wte/embedding$", P("tensor", "fsdp")),
    (r"wpe/embedding$", P(None, "fsdp")),
    (r"attn/c_attn/kernel$", P("fsdp", "tensor")),
    (r"attn/c_attn/bias$", P("tensor")),
    (r"attn/c_proj/kernel$", P("tensor", "fsdp")),
    (r"attn/c_proj/bias$", P()),
    (r"mlp/c_fc/kernel$", P("fsdp", "tensor")),
    (r"mlp/c_fc/bias$", P("tensor")),
    (r"mlp/c_proj/kernel$", P("tensor", "fsdp")),
    (r"mlp/c_proj/bias$", P()),
    (r"(ln_1|ln_2|ln_f)/(scale|bias)$", P()),
)
LEGACY_LLAMA_RULES = (
    (r"embed_tokens/embedding$", P("tensor", "fsdp")),
    (r"(q_proj|k_proj|v_proj)/kernel$", P("fsdp", "tensor")),
    (r"o_proj/kernel$", P("tensor", "fsdp")),
    (r"(gate_proj|up_proj)/kernel$", P("fsdp", "tensor")),
    (r"down_proj/kernel$", P("tensor", "fsdp")),
    (r"lm_head/kernel$", P("fsdp", "tensor")),
    (r"(input_layernorm|post_attention_layernorm|norm)/scale$", P()),
)
LEGACY_MIXTRAL_RULES = (
    (r"experts/(w1|w3)$", P("expert", "fsdp", "tensor")),
    (r"experts/w2$", P("expert", "tensor", "fsdp")),
    (r"block_sparse_moe/gate/kernel$", P(None, None)),
) + LEGACY_LLAMA_RULES


def _family_model(family):
    if family == "gpt":
        from avenir_tpu.models.gpt import GPT, GPTConfig

        return nnx.eval_shape(lambda: GPT(
            GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                      n_embd=32), rngs=nnx.Rngs(0)))
    if family == "llama":
        from avenir_tpu.models.llama import Llama, LlamaConfig

        return nnx.eval_shape(lambda: Llama(
            LlamaConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                        n_kv_head=1, n_embd=32, ffn_hidden=64),
            rngs=nnx.Rngs(0)))
    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

    return nnx.eval_shape(lambda: Mixtral(
        MixtralConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                      n_kv_head=1, n_embd=32, ffn_hidden=64,
                      n_experts=4), rngs=nnx.Rngs(0)))


_LEGACY = {"gpt": LEGACY_GPT_RULES, "llama": LEGACY_LLAMA_RULES,
           "mixtral": LEGACY_MIXTRAL_RULES}


@pytest.mark.parametrize("family", ["gpt", "llama", "mixtral"])
def test_unified_rules_match_legacy_specs(family):
    """bf16 acceptance pin: the ONE unified table resolves every family
    to specs BIT-EQUAL to the old hand-wired per-family tables."""
    model = _family_model(family)
    paths = [p for p, _ in nnx.state(model, nnx.Param).flat_state()]
    new = match_partition_rules(rules_for_model(family), paths)
    old = match_partition_rules(_LEGACY[family], paths)
    assert new == old
    assert set(new) == set(paths)


@pytest.mark.parametrize("family", ["gpt", "llama", "mixtral"])
def test_precision_round_trip_every_param(family):
    """Every param path resolves a PrecisionPolicy through the SAME
    table walk: matmul kernels (incl. the tied/untied heads and the
    stacked experts) are int8-eligible with delayed scaling; norms,
    biases, the position table and the MoE router gate never are."""
    model = _family_model(family)
    flat = nnx.state(model, nnx.Param).flat_state()
    paths = [p for p, _ in flat]
    shapes = {p: tuple(v.get_value().shape) for p, v in flat}
    pols = match_precision_rules(rules_for_model(family), paths, shapes)
    assert set(pols) == set(paths)
    for p in paths:
        s = "/".join(str(seg) for seg in p)
        pol = pols[p]
        if any(k in s for k in ("ln_", "layernorm", "/norm/", "bias",
                                "wpe", "gate/kernel")) or s.endswith(
                                    "norm/scale"):
            assert not pol.quantize, s
        elif s.endswith(("kernel", "w1", "w2", "w3", "embedding")) \
                and len(shapes[p]) >= 2 and "wpe" not in s:
            assert pol.quantize, s
            assert pol.scaling == "delayed", s


def test_rule_ordering_wins():
    """First matching row decides — for BOTH halves of the policy."""
    rules = (
        (r"special/kernel$", P("tensor"), NO_QUANT),
        (r"kernel$", P("fsdp"), QUANT),
    )
    specs = match_partition_rules(rules, ["a/special/kernel", "b/kernel"])
    assert tuple(specs["a/special/kernel"]) == ("tensor",)
    assert tuple(specs["b/kernel"]) == ("fsdp",)
    pols = match_precision_rules(rules, ["a/special/kernel", "b/kernel"])
    assert not pols["a/special/kernel"].quantize
    assert pols["b/kernel"].quantize


def test_precision_scalar_skip_and_fail_loud():
    """A 1-d param coerces to NO_QUANT even when its row says QUANT
    (no contraction axis to carry a per-channel scale); an unmatched
    path fails loud like the partition half."""
    rules = ((r"kernel$", P("fsdp"), QUANT),)
    pols = match_precision_rules(rules, ["a/kernel", "b/kernel"],
                                 {"a/kernel": (8, 8), "b/kernel": (8,)})
    assert pols["a/kernel"].quantize and not pols["b/kernel"].quantize
    with pytest.raises(ValueError, match="no precision rule"):
        match_precision_rules(rules, ["mystery/scale"])
    with pytest.raises(ValueError, match="no precision rule"):
        precision_for("gpt", "mystery/thing")


def test_precision_for_call_site_keys():
    """The canonical call-site keys the models use must resolve, with
    the policies the docstring promises."""
    for fam, key in [("gpt", "attn/c_attn/kernel"),
                     ("gpt", "mlp/c_proj/kernel"),
                     ("gpt", "wte/embedding"),
                     ("llama", "q_proj/kernel"),
                     ("llama", "lm_head/kernel"),
                     ("mixtral", "experts/w1"),
                     ("mixtral", "experts/w2")]:
        assert precision_for(fam, key).quantize, (fam, key)
    assert not precision_for("mixtral",
                             "block_sparse_moe/gate/kernel").quantize
    assert not precision_for("gpt", "wpe/embedding").quantize
    assert isinstance(precision_for("gpt", "wte/embedding"),
                      PrecisionPolicy)


def test_legacy_two_tuple_rules_still_accepted():
    """match_partition_rules consumes (regex, spec) pairs (external
    callers, these fixtures); their precision resolves to NO_QUANT."""
    rules = ((r"kernel$", P("fsdp")),)
    assert tuple(match_partition_rules(rules, ["x/kernel"])["x/kernel"]) \
        == ("fsdp",)
    assert not match_precision_rules(rules, ["x/kernel"])["x/kernel"].quantize


def test_sanitize_drops_nondivisible_axes():
    mesh = make_mesh("tensor:2,fsdp:4")
    specs = {("wte", "embedding"): P("tensor", "fsdp")}
    # vocab 25 not divisible by tensor:2 -> replicated; 32 % 4 == 0 stays.
    # Non-strict mode REPORTS every drop (VERDICT r2 weak #2: silence here
    # replicates a 1.5B wte with zero indication) through the log hook.
    logged = []
    out = sanitize_specs(specs, {("wte", "embedding"): (25, 32)}, mesh,
                         log=logged.append)
    assert tuple(out[("wte", "embedding")]) == (None, "fsdp")
    assert len(logged) == 1 and "wte/embedding" in logged[0]
    assert "tensor" in logged[0]


def test_sanitize_strict_raises_and_clean_is_silent():
    mesh = make_mesh("tensor:2,fsdp:4")
    specs = {("wte", "embedding"): P("tensor", "fsdp")}
    with pytest.raises(ValueError, match="allow_unsharded_fallback"):
        sanitize_specs(specs, {("wte", "embedding"): (25, 32)}, mesh,
                       strict=True)
    # divisible shapes: no log, no raise, spec untouched in both modes
    logged = []
    out = sanitize_specs(specs, {("wte", "embedding"): (26, 32)}, mesh,
                         strict=True, log=logged.append)
    assert tuple(out[("wte", "embedding")]) == ("tensor", "fsdp")
    assert not logged


def test_parse_mesh_shape():
    assert parse_mesh_shape("", 8)["data"] == 8
    sizes = parse_mesh_shape("data:2,fsdp:-1", 8)
    assert sizes["fsdp"] == 4 and sizes["data"] == 2
    with pytest.raises(ValueError, match="unknown mesh axis"):
        parse_mesh_shape("bogus:2", 8)
    with pytest.raises(ValueError, match="needs"):
        parse_mesh_shape("data:16", 8)
    assert tuple(parse_mesh_shape("tensor:2", 8)) == AXES


def test_has_scan_segment():
    assert has_scan_segment(("h_scan", "attn", "kernel"))
    assert has_scan_segment("layers_scan/mlp/kernel")
    assert not has_scan_segment(("h", 0, "attn", "kernel"))


def test_constrain_noop_without_mesh_live_with_mesh():
    x = jax.numpy.ones((8, 4))
    # no mesh installed: no-op, any spec accepted
    y = constrain(x, P("data", None))
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    # mesh installed (context-manager form): the constraint is LIVE inside
    # jit — a valid spec applies, a bogus axis fails loud instead of being
    # swallowed (VERDICT r1 weak item 4)
    mesh = make_mesh("data:2")
    with jax.set_mesh(mesh):  # jax.set_mesh is a context manager too
        # place the input on the mesh (an array committed to one device
        # before the context would fail jit's device-compatibility check)
        xs = jax.device_put(np.ones((8, 4), np.float32),
                            NamedSharding(mesh, P()))
        y = jax.jit(lambda a: constrain(a, P("data", None)))(xs)
        np.testing.assert_array_equal(np.asarray(y), np.ones((8, 4)))
        with pytest.raises(Exception, match="nonexistent_axis"):
            jax.jit(lambda a: constrain(a, P("nonexistent_axis", None)))(xs)
