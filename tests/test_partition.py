"""Partition-rule and mesh unit tests (SURVEY.md §4 "Unit: sharding"):
fail-loud on unmatched params (SNIPPETS.md:31 policy), full rule coverage
per model family, divisibility sanitization, mesh-spec parsing, and the
mesh-gated constrain()."""

import numpy as np
import pytest

import jax
from flax import nnx
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.parallel.mesh import AXES, make_mesh, parse_mesh_shape
from avenir_tpu.parallel.partition import (
    constrain,
    has_scan_segment,
    match_partition_rules,
    rules_for_model,
    sanitize_specs,
)


def test_unmatched_param_raises():
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(rules_for_model("gpt"),
                              [("mystery", "kernel")])


@pytest.mark.parametrize("family,ctor_info", [
    ("gpt", None), ("llama", None), ("mixtral", None),
])
def test_rules_cover_every_param(family, ctor_info):
    if family == "gpt":
        from avenir_tpu.models.gpt import GPT, GPTConfig

        model = nnx.eval_shape(lambda: GPT(
            GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                      n_embd=32), rngs=nnx.Rngs(0)))
    elif family == "llama":
        from avenir_tpu.models.llama import Llama, LlamaConfig

        model = nnx.eval_shape(lambda: Llama(
            LlamaConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                        n_kv_head=1, n_embd=32, ffn_hidden=64),
            rngs=nnx.Rngs(0)))
    else:
        from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

        model = nnx.eval_shape(lambda: Mixtral(
            MixtralConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                          n_kv_head=1, n_embd=32, ffn_hidden=64,
                          n_experts=4), rngs=nnx.Rngs(0)))
    paths = [p for p, _ in nnx.state(model, nnx.Param).flat_state()]
    specs = match_partition_rules(rules_for_model(family), paths)
    assert set(specs) == set(paths)


def test_sanitize_drops_nondivisible_axes():
    mesh = make_mesh("tensor:2,fsdp:4")
    specs = {("wte", "embedding"): P("tensor", "fsdp")}
    # vocab 25 not divisible by tensor:2 -> replicated; 32 % 4 == 0 stays.
    # Non-strict mode REPORTS every drop (VERDICT r2 weak #2: silence here
    # replicates a 1.5B wte with zero indication) through the log hook.
    logged = []
    out = sanitize_specs(specs, {("wte", "embedding"): (25, 32)}, mesh,
                         log=logged.append)
    assert tuple(out[("wte", "embedding")]) == (None, "fsdp")
    assert len(logged) == 1 and "wte/embedding" in logged[0]
    assert "tensor" in logged[0]


def test_sanitize_strict_raises_and_clean_is_silent():
    mesh = make_mesh("tensor:2,fsdp:4")
    specs = {("wte", "embedding"): P("tensor", "fsdp")}
    with pytest.raises(ValueError, match="allow_unsharded_fallback"):
        sanitize_specs(specs, {("wte", "embedding"): (25, 32)}, mesh,
                       strict=True)
    # divisible shapes: no log, no raise, spec untouched in both modes
    logged = []
    out = sanitize_specs(specs, {("wte", "embedding"): (26, 32)}, mesh,
                         strict=True, log=logged.append)
    assert tuple(out[("wte", "embedding")]) == ("tensor", "fsdp")
    assert not logged


def test_parse_mesh_shape():
    assert parse_mesh_shape("", 8)["data"] == 8
    sizes = parse_mesh_shape("data:2,fsdp:-1", 8)
    assert sizes["fsdp"] == 4 and sizes["data"] == 2
    with pytest.raises(ValueError, match="unknown mesh axis"):
        parse_mesh_shape("bogus:2", 8)
    with pytest.raises(ValueError, match="needs"):
        parse_mesh_shape("data:16", 8)
    assert tuple(parse_mesh_shape("tensor:2", 8)) == AXES


def test_has_scan_segment():
    assert has_scan_segment(("h_scan", "attn", "kernel"))
    assert has_scan_segment("layers_scan/mlp/kernel")
    assert not has_scan_segment(("h", 0, "attn", "kernel"))


def test_constrain_noop_without_mesh_live_with_mesh():
    x = jax.numpy.ones((8, 4))
    # no mesh installed: no-op, any spec accepted
    y = constrain(x, P("data", None))
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
    # mesh installed (context-manager form): the constraint is LIVE inside
    # jit — a valid spec applies, a bogus axis fails loud instead of being
    # swallowed (VERDICT r1 weak item 4)
    mesh = make_mesh("data:2")
    with jax.set_mesh(mesh):  # jax.set_mesh is a context manager too
        # place the input on the mesh (an array committed to one device
        # before the context would fail jit's device-compatibility check)
        xs = jax.device_put(np.ones((8, 4), np.float32),
                            NamedSharding(mesh, P()))
        y = jax.jit(lambda a: constrain(a, P("data", None)))(xs)
        np.testing.assert_array_equal(np.asarray(y), np.ones((8, 4)))
        with pytest.raises(Exception, match="nonexistent_axis"):
            jax.jit(lambda a: constrain(a, P("nonexistent_axis", None)))(xs)
