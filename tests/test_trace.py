"""Request-tracing + flight-recorder tests (avenir_tpu/obs/trace.py,
ISSUE 10): bounded-ring drop accounting, cross-frame restamp
monotonicity, Chrome-trace well-formedness, one-terminal-event-per-
finish_reason over router+engine, crash hooks, and the tracing-disabled
near-zero-overhead micro-assert. All CPU tier-1.

Budget notes: one module-scoped tiny GPT; every prompt shares one
power-of-2 bucket and one MAX_NEW so the engines pay one prefill + one
decode compile each (the test_serve_router discipline)."""

import json
import time

import numpy as np
import pytest
from flax import nnx

from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import JsonlSink, MetricsRegistry
from avenir_tpu.obs.trace import (
    TERMINAL,
    TraceBuffer,
    Tracer,
    chrome_trace,
    install_crash_hooks,
    disarm_crash_hooks,
    event_record,
    record_event,
    request_segments,
    ttft_attribution,
)
from avenir_tpu.serve import Engine, Router

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
MAX_NEW = 4


@pytest.fixture(scope="module")
def model():
    return GPT(GPT_TINY, rngs=nnx.Rngs(0))


def _prompt(rng, n=5):
    return [int(t) for t in rng.integers(0, 64, (n,))]


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# ring / buffer accounting
# ---------------------------------------------------------------------------


def test_ring_overflow_drops_oldest_and_counts():
    reg = MetricsRegistry()
    tr = Tracer(capacity=8, registry=reg, clock=lambda: 0.0)
    for i in range(20):
        tr.emit(i, "submit", t=float(i))
    evs = tr.events()
    assert len(evs) == 8
    # oldest dropped: the survivors are the LAST 8 emissions
    assert [e["rid"] for e in evs] == list(range(12, 20))
    assert tr.dropped == 12
    assert reg.snapshot()["counters"]["trace_events_dropped"] == 12


def test_trace_buffer_bounded_and_drain_resets():
    buf = TraceBuffer(clock=lambda: 0.0, cap=4)
    for i in range(7):
        buf.emit(i, "submit", t=float(i))
    assert len(buf.events) == 4 and buf.dropped == 3
    evs = buf.drain()
    assert [e["rid"] for e in evs] == [3, 4, 5, 6]
    assert buf.events == []


def test_unknown_event_name_fails_loud():
    tr = Tracer(registry=MetricsRegistry())
    with pytest.raises(AssertionError):
        tr.emit(0, "not_an_event")


# ---------------------------------------------------------------------------
# restamp monotonicity (the cross-process clock contract)
# ---------------------------------------------------------------------------


def test_absorbed_age_deltas_restamp_monotone_within_a_trace():
    """Worker events arrive as age deltas across several replies; even
    with pipe-latency jitter pushing a restamped time BEFORE an already
    appended event, the per-rid clamp keeps each trace monotone."""
    reg = MetricsRegistry()
    clk = _Clock()
    tr = Tracer(registry=reg, clock=clk)
    clk.t = 10.0
    tr.emit(7, "dispatch", replica=0)
    # reply arrives at t=10.1 carrying an event whose age claims it
    # happened at 9.95 — before the parent-side dispatch stamp
    tr.absorb([{"rid": 7, "ev": "engine_admit", "age_s": 0.15}],
              rid_map={7: 7}, replica=0, now=10.1)
    tr.absorb([{"rid": 7, "ev": "first_token", "age_s": 0.05}],
              rid_map={7: 7}, replica=0, now=10.2)
    clk.t = 10.3
    tr.emit(7, "finish", reason="length", n_out=1)
    ts = [e["t"] for e in tr.events_for(7)]
    assert ts == sorted(ts), ts
    assert ts[0] == 10.0 and ts[1] == 10.0  # clamped, not reordered


def test_absorb_translates_engine_rids_and_counts_drops():
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=lambda: 5.0)
    tr.absorb([{"rid": 0, "ev": "engine_admit", "age_s": 0.0},
               {"rid": 99, "ev": "engine_admit", "age_s": 0.0}],
              rid_map={0: 41}, replica=3, dropped=2)
    evs = tr.events()
    assert evs[0]["rid"] == 41 and evs[0]["replica"] == 3
    # an unmapped engine rid is kept visibly, never miscredited
    assert evs[1]["rid"] is None and evs[1]["eng_rid"] == 99
    assert reg.snapshot()["counters"]["trace_events_dropped"] == 2


def test_event_record_round_trip():
    e = {"rid": 3, "ev": "submit", "t": 1.5, "priority": "batch"}
    rec = event_record(e)
    assert rec["kind"] == "trace" and rec["ts"] == 1.5 and "t" not in rec
    assert record_event(json.loads(json.dumps(rec))) == e


# ---------------------------------------------------------------------------
# segmentation / attribution
# ---------------------------------------------------------------------------


def test_segments_partition_ttft_across_failover():
    evs = [
        {"rid": 1, "ev": "submit", "t": 0.0},
        {"rid": 1, "ev": "dispatch", "t": 1.0},
        {"rid": 1, "ev": "failover", "t": 3.0},
        {"rid": 1, "ev": "requeue", "t": 3.0},
        {"rid": 1, "ev": "dispatch", "t": 4.0},
        {"rid": 1, "ev": "first_token", "t": 6.0},
        {"rid": 1, "ev": "finish", "t": 8.0, "reason": "length"},
    ]
    segs = request_segments(evs)
    assert segs == [("queue", 0.0, 1.0), ("failover", 1.0, 3.0),
                    ("queue", 3.0, 4.0), ("prefill", 4.0, 6.0),
                    ("decode", 6.0, 8.0)]
    a = ttft_attribution(evs)
    assert a["ttft_s"] == 6.0
    assert a["queue_s"] + a["prefill_s"] + a["failover_s"] == \
        pytest.approx(a["ttft_s"])
    assert a == {"ttft_s": 6.0, "queue_s": 2.0, "prefill_s": 2.0,
                 "transfer_s": 0.0, "failover_s": 2.0}


def test_attribution_counts_dead_decode_attempt_as_failover():
    """A replica that died AFTER the request's first token: the
    discarded attempt's time is failover loss, and the surviving
    attempt's first token anchors the TTFT."""
    evs = [
        {"rid": 2, "ev": "submit", "t": 0.0},
        {"rid": 2, "ev": "dispatch", "t": 1.0},
        {"rid": 2, "ev": "first_token", "t": 2.0},
        {"rid": 2, "ev": "failover", "t": 5.0},
        {"rid": 2, "ev": "requeue", "t": 5.0},
        {"rid": 2, "ev": "dispatch", "t": 5.5},
        {"rid": 2, "ev": "first_token", "t": 7.0},
        {"rid": 2, "ev": "finish", "t": 9.0, "reason": "length"},
    ]
    a = ttft_attribution(evs)
    assert a["ttft_s"] == 7.0
    assert a["queue_s"] + a["prefill_s"] + a["failover_s"] == \
        pytest.approx(7.0)
    assert a["failover_s"] == pytest.approx(4.0)  # 1->2 prefill + 2->5
    #   decode of the dead attempt are both discarded work


# ---------------------------------------------------------------------------
# Chrome trace export
# ---------------------------------------------------------------------------


def test_chrome_trace_is_well_formed():
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=lambda: 0.0)
    tr.emit(0, "submit", t=1.0, priority="interactive")
    tr.emit(0, "dispatch", t=2.0, replica=1)
    tr.emit(0, "first_token", t=3.0)
    tr.emit(0, "finish", t=4.0, reason="length", n_out=2)
    tr.emit(None, "decode_tick", t=3.5, n_live=1)
    tr.span("serve_decode", 2.5, 100.0)
    tr.emit(None, "scale", t=3.7, action="up", reason="burn_rate",
            from_size=1, to_size=2, burn_rate=2.5)
    j = tr.chrome()
    # round-trips through JSON (the file Perfetto actually loads)
    j = json.loads(json.dumps(j))
    assert set(j) == {"traceEvents", "displayTimeUnit"}
    for e in j["traceEvents"]:
        assert e["ph"] in ("X", "i", "M", "C")
        assert "name" in e and "pid" in e
        if e["ph"] in ("X", "i", "C"):
            assert "ts" in e and "tid" in e
            assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # the request produced queue/prefill/decode slices on its track
    slices = [e["name"] for e in j["traceEvents"]
              if e["ph"] == "X" and e["pid"] == 1]
    assert slices == ["queue", "prefill", "decode"]
    # the span landed on the phase pid with its duration in us
    sp = [e for e in j["traceEvents"]
          if e["ph"] == "X" and e["pid"] == 2]
    assert len(sp) == 1 and sp[0]["name"] == "serve_decode"
    assert sp[0]["dur"] == pytest.approx(100.0 * 1e3)
    # the scale decision gets its OWN track (pid 4): an instant with
    # the evidence in args plus a fleet_size counter series (ISSUE 12)
    sc = [e for e in j["traceEvents"] if e["pid"] == 4 and e["ph"] == "i"]
    assert len(sc) == 1 and sc[0]["name"] == "scale up"
    assert sc[0]["args"]["burn_rate"] == 2.5
    assert sc[0]["args"]["to_size"] == 2
    ctr = [e for e in j["traceEvents"] if e["ph"] == "C"]
    assert len(ctr) == 1 and ctr[0]["name"] == "fleet_size"
    assert ctr[0]["args"]["replicas"] == 2


# ---------------------------------------------------------------------------
# every finish_reason path emits exactly ONE terminal event
# ---------------------------------------------------------------------------


def test_every_finish_reason_path_emits_one_terminal_event(model):
    """Lint over router+engine: drive every terminal path — stop,
    length, queued timeout, live-slot timeout, door reject, shed,
    failover-past-deadline timeout — and assert exactly one `finish`
    trace event per request, with the reason the finished record
    carries."""
    rng = np.random.default_rng(0)
    reg = MetricsRegistry()
    clk = _Clock()
    tr = Tracer(registry=reg, clock=clk)
    router = Router(model, n_replicas=2, n_slots=2, max_seq_len=32,
                    registry=reg, seed=0, clock=clk, tracer=tr,
                    queue_limits={"interactive": 3, "batch": 3})
    done = []
    # length (no stop token fires on a random stream of stop=())
    r_len = router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
    # stop: probe one token, then replay the SAME prompt + rng with
    # that token as the stop token — it fires on the first emission
    import jax

    probe_rng = jax.random.key(42)
    probe_prompt = _prompt(rng, 4)
    probe = router.submit(probe_prompt, max_new_tokens=1, rng=probe_rng)
    done += router.drain()
    first_tok = next(f for f in done if f.req_id == probe).tokens[-1]
    r_stop = router.submit(probe_prompt, max_new_tokens=MAX_NEW,
                           stop_tokens=(first_tok,), rng=probe_rng)
    # door reject: impossible shape
    r_rej = router.submit(_prompt(rng, 30), max_new_tokens=10)
    # queued timeout: deadline already unmeetable once we advance time
    r_to = router.submit(_prompt(rng), max_new_tokens=MAX_NEW,
                         deadline_ms=1.0)
    clk.t += 10.0
    done += router.drain()
    # shed: fill the class queue past its limit with no stepping
    shed_rids = [router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
                 for _ in range(5)]
    done += router.drain()
    by_rid = {f.req_id: f for f in done}
    assert by_rid[r_len].finish_reason == "length"
    assert by_rid[r_stop].finish_reason == "stop"
    assert by_rid[r_rej].finish_reason == "rejected"
    assert by_rid[r_to].finish_reason == "timeout"
    assert any(by_rid[r].finish_reason == "shed" for r in shed_rids)
    # THE pin: one terminal event per request, reason matching
    for f in done:
        terms = [e for e in tr.events_for(f.req_id)
                 if e["ev"] == TERMINAL]
        assert len(terms) == 1, (
            f"rid {f.req_id} ({f.finish_reason}): {len(terms)} terminal "
            f"events — every finish_reason path must emit exactly one")
        assert terms[0]["reason"] == f.finish_reason


def test_live_eviction_and_failover_timeout_terminals(model):
    """The two remaining terminal paths: deadline eviction from a HELD
    slot, and a failover surfacing an already-expired deadline."""
    rng = np.random.default_rng(1)
    reg = MetricsRegistry()
    clk = _Clock()
    tr = Tracer(registry=reg, clock=clk)
    router = Router(model, n_replicas=1, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0, clock=clk, tracer=tr)
    # live eviction: generous enough to take a slot and emit a token,
    # then the clock jumps past the deadline mid-decode
    rid = router.submit(_prompt(rng), max_new_tokens=20,
                        deadline_ms=5_000.0)
    router.step()
    router.step()
    clk.t += 10.0
    done = router.drain()
    f = next(x for x in done if x.req_id == rid)
    assert f.finish_reason == "timeout" and f.n_out >= 1
    terms = [e for e in tr.events_for(rid) if e["ev"] == TERMINAL]
    assert len(terms) == 1 and terms[0]["reason"] == "timeout"
    assert any(e["ev"] == "evict" for e in tr.events_for(rid))
    # failover past deadline: dispatched work dies after expiry
    rid2 = router.submit(_prompt(rng), max_new_tokens=20,
                         deadline_ms=5_000.0)
    router.step()
    clk.t += 10.0
    router.kill_replica(0)
    router.revive_replica(0)
    done = router.drain()
    f2 = next(x for x in done if x.req_id == rid2)
    assert f2.finish_reason == "timeout"
    terms2 = [e for e in tr.events_for(rid2) if e["ev"] == TERMINAL]
    assert len(terms2) == 1 and terms2[0]["reason"] == "timeout"
    assert any(e["ev"] == "failover" for e in tr.events_for(rid2))


def test_failover_trace_monotone_and_attribution_matches_ttft(model):
    """The acceptance shape in-miniature: kill the replica holding a
    request, let it fail over, and check the trace tree is monotone
    with queue+prefill+failover summing to the measured TTFT."""
    rng = np.random.default_rng(2)
    reg = MetricsRegistry()
    tr = Tracer(registry=reg)
    router = Router(model, n_replicas=2, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0, tracer=tr)
    rids = [router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
            for _ in range(3)]
    router.step()
    router.kill_replica(0)
    done = router.drain()
    assert len(done) == 3
    fo = [f for f in done if f.failovers > 0]
    assert fo, "the kill must have failed something over"
    for f in done:
        evs = tr.events_for(f.req_id)
        ts = [e["t"] for e in evs]
        assert ts == sorted(ts)
        a = ttft_attribution(evs)
        assert a is not None
        assert a["queue_s"] + a["prefill_s"] + a["failover_s"] == \
            pytest.approx(a["ttft_s"], abs=1e-9)
        assert a["ttft_s"] * 1e3 == pytest.approx(f.ttft_ms, abs=1.0)
    assert any(e["ev"] == "failover"
               for e in tr.events_for(fo[0].req_id))


# ---------------------------------------------------------------------------
# flight recorder + crash hooks
# ---------------------------------------------------------------------------


def test_flight_dump_writes_ring_and_counts(tmp_path):
    reg = MetricsRegistry()
    tr = Tracer(capacity=4, registry=reg, clock=lambda: 0.0,
                out_dir=str(tmp_path))
    for i in range(6):
        tr.emit(i, "submit", t=float(i))
    path = tr.flight_dump("test-incident")
    assert path is not None and "flight-test-incident" in path
    lines = [json.loads(x) for x in open(path)]
    assert lines[0]["kind"] == "flight_meta"
    assert lines[0]["reason"] == "test-incident"
    assert lines[0]["dropped_before_ring"] == 2
    assert [r["rid"] for r in lines[1:]] == [2, 3, 4, 5]
    assert all(r["kind"] == "trace" for r in lines[1:])
    assert reg.snapshot()["counters"]["flight_dumps"] == 1
    # no out_dir -> silent no-op, never a crash in an incident path
    assert Tracer(registry=reg).flight_dump("x") is None


def test_replica_death_triggers_flight_dump(model, tmp_path):
    rng = np.random.default_rng(3)
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, out_dir=str(tmp_path))
    router = Router(model, n_replicas=2, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0, tracer=tr)
    for _ in range(2):
        router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
    router.step()
    router.kill_replica(0)
    router.drain()
    dumps = list(tmp_path.glob("flight-replica0-death-*.jsonl"))
    assert len(dumps) == 1
    assert reg.snapshot()["counters"]["flight_dumps"] == 1


def test_crash_hooks_write_run_end_and_flight_dump(tmp_path):
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=lambda: 0.0, out_dir=str(tmp_path))
    tr.emit(0, "submit", t=0.0)
    path = tmp_path / "metrics.jsonl"
    sink = JsonlSink(str(path))
    sink.write({"kind": "run_meta", "t": time.time()})
    install_crash_hooks(sink=sink, registry=reg, tracer=tr)
    try:
        import sys

        # simulate the interpreter's unhandled-exception path (the
        # installed hook chains to the previous excepthook)
        sys.excepthook(RuntimeError, RuntimeError("boom"), None)
    finally:
        disarm_crash_hooks()
    sink.close()
    recs = [json.loads(x) for x in open(path)]
    end = [r for r in recs if r["kind"] == "run_end"]
    assert len(end) == 1
    assert end[0]["crashed"] is True and "boom" in end[0]["error"]
    assert "counters" in end[0]
    assert list(tmp_path.glob("flight-crash-*.jsonl"))


def test_crash_hooks_disarmed_emit_nothing(tmp_path):
    reg = MetricsRegistry()
    path = tmp_path / "metrics.jsonl"
    sink = JsonlSink(str(path))
    install_crash_hooks(sink=sink, registry=reg)
    disarm_crash_hooks()
    from avenir_tpu.obs.trace import _final_flush

    _final_flush()  # the atexit path after a clean shutdown
    sink.close()
    assert [json.loads(x) for x in open(path)] == []


def test_watchdog_fire_dumps_flight_when_tracer_armed(tmp_path):
    from avenir_tpu.obs import StallWatchdog, set_tracer

    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=lambda: 0.0, out_dir=str(tmp_path))
    tr.emit(0, "submit", t=0.0)
    prev = set_tracer(tr)
    wd = StallWatchdog(floor_secs=1000.0, registry=reg,
                       dump_stacks=False, echo=lambda *a: None)
    try:
        wd._fire(1234.0, 1000.0)  # the watchdog tests' direct-fire idiom
    finally:
        wd.stop()
        set_tracer(prev)
    assert list(tmp_path.glob("flight-watchdog-*.jsonl"))
    assert reg.snapshot()["counters"]["flight_dumps"] == 1


# ---------------------------------------------------------------------------
# overhead: the tracing-disabled path must stay near-zero
# ---------------------------------------------------------------------------


def test_disabled_tracing_emission_guard_is_nanoseconds():
    """The per-site cost with tracing off is ONE attribute load + `is
    not None` branch. Budget-guarded like the slow guard: generous
    absolute ceiling, because CI wall clocks are noisy — but a schema
    change that put real work on the disabled path (a dict lookup, a
    function call chain) would blow 1 us/op by orders of magnitude."""
    class _Holder:
        _tr = None

    h = _Holder()
    n = 200_000
    t0 = time.perf_counter()
    acc = 0
    for _ in range(n):
        tr = h._tr
        if tr is not None:  # the exact emission-site shape
            acc += 1
    per_op_us = (time.perf_counter() - t0) / n * 1e6
    assert acc == 0
    assert per_op_us < 1.0, (
        f"disabled-tracing guard costs {per_op_us:.3f} us/op — the "
        "disabled path must stay a bare None check")


def test_disabled_tracing_adds_no_measurable_tick_overhead(model):
    """Engine-level pin: decode ticks with tracer=None are not slower
    than the SAME engine's ticks were before tracing existed — proxied
    by comparing against ticks with tracing ENABLED (which do strictly
    more work). Median-of-ticks keeps compile spikes out; the budget is
    relative (3x + 2ms) so a loaded CI harness cannot flake it."""
    import statistics

    def median_tick(tracer):
        reg = MetricsRegistry()
        eng = Engine(model, n_slots=2, max_seq_len=32, registry=reg,
                     tracer=tracer, seed=0)
        rng = np.random.default_rng(4)
        durs = []
        for burst in range(3):
            for _ in range(2):
                eng.submit(_prompt(rng), max_new_tokens=16)
            while eng.open_work:
                t0 = time.perf_counter()
                eng.step()
                durs.append(time.perf_counter() - t0)
        return statistics.median(durs)

    base = median_tick(None)           # the production default
    traced = median_tick(TraceBuffer(decode_sample=1))
    assert base <= 3.0 * traced + 2e-3, (
        f"tracing-disabled tick ({base * 1e3:.2f} ms) is slower than "
        f"3x a fully-traced tick ({traced * 1e3:.2f} ms) + 2 ms — the "
        "disabled path regressed")


# ---------------------------------------------------------------------------
# obs_report torn-line satellite
# ---------------------------------------------------------------------------


def test_obs_report_skips_torn_final_line_and_notes_it(tmp_path):
    from avenir_tpu.obs.report import (
        format_report,
        load_records_with_skips,
        summarize,
    )

    path = tmp_path / "metrics.jsonl"
    with open(path, "wb") as f:
        f.write(json.dumps({"kind": "run_meta", "t": 1.0,
                            "model_type": "gpt"}).encode() + b"\n")
        f.write(json.dumps({"kind": "iter", "t": 2.0, "iter": 1,
                            "loss": 3.0, "dt_ms": 1.0,
                            "counters": {}}).encode() + b"\n")
        # a SIGKILL mid-write: truncated record ending INSIDE a
        # multi-byte utf-8 character (the case that used to raise
        # UnicodeDecodeError out of text-mode iteration)
        torn = json.dumps({"kind": "iter", "t": 3.0,
                           "note": "café"}).encode()[:-3]
        f.write(torn)
    records, skipped = load_records_with_skips(str(path))
    assert len(records) == 2
    assert skipped == [3]
    rep = format_report(summarize(records, skipped_lines=skipped))
    assert "skipped 1 unparseable log line(s)" in rep
    assert "torn write" in rep
