"""Process-isolated serve fleet tests (ISSUE 8): the frame protocol
fails loud on every corruption mode (fast, tier-1), the supervisor's
backoff schedule is exact (fast), and the full router semantics — the
parity/failover/fair-share suite of tests/test_serve_router.py —
survive REAL worker processes, real SIGKILLs, silent hangs and pipe
corruption (slow: every case spawns worker processes that pay a jax
import and their own compiles).

Budget notes: the slow cases share one module-scoped model + reference
set (same construction as test_serve_router's, so a worker rebuilt
from the shipped state is bit-identical); every prompt stays in one
power-of-2 bucket so each worker pays one prefill + one decode
compile.
"""

import os
import signal
import struct
import time

import pytest

from avenir_tpu.serve.frames import (
    HEADER_SIZE,
    MAGIC,
    PROTO_VERSION,
    PT_JSON,
    PT_PICKLE,
    FrameCRCError,
    FrameEOF,
    FrameProtocolError,
    FrameStream,
    FrameTimeout,
    encode_frame,
)
from avenir_tpu.utils.faults import FaultInjector, set_injector
from avenir_tpu.utils.retry import RetryPolicy


def _pipe_pair():
    """Two FrameStreams talking to each other over two os.pipe()s."""
    r1, w1 = os.pipe()
    r2, w2 = os.pipe()
    return FrameStream(r1, w2), FrameStream(r2, w1), (r1, w1, r2, w2)


def _close_all(fds):
    for fd in fds:
        try:
            os.close(fd)
        except OSError:
            pass


# ---------------------------------------------------------------------
# frame protocol (fast, tier-1: stdlib only, no processes)
# ---------------------------------------------------------------------


def test_frame_roundtrip_json_and_pickle():
    a, b, fds = _pipe_pair()
    try:
        msg = {"op": "step", "n": 3, "nested": {"x": [1, 2, 3]},
               "text": "héllo"}
        a.write(msg, ptype=PT_JSON)
        assert b.read(timeout_s=5.0) == msg
        import numpy as np

        obj = {"op": "hello", "arr": np.arange(7, dtype=np.uint32),
               "cfg": ("tuple", 1)}
        b.write(obj, ptype=PT_PICKLE)
        got = a.read(timeout_s=5.0)
        assert got["op"] == "hello" and got["cfg"] == ("tuple", 1)
        assert (got["arr"] == obj["arr"]).all()
        # several frames back to back stay framed (no desync)
        for i in range(5):
            a.write({"i": i})
        assert [b.read(timeout_s=5.0)["i"] for _ in range(5)] \
            == list(range(5))
    finally:
        _close_all(fds)


def test_frame_crc_trip_fails_loud_and_is_distinct():
    """An armed frame_corrupt flips a payload byte AFTER the CRC is
    computed — the reader must raise FrameCRCError, not garbage-parse
    (and not any other FrameError: the fleet treats CRC as corruption,
    which is never retried)."""
    a, b, fds = _pipe_pair()
    prev = set_injector(FaultInjector("frame_corrupt:n=1", seed=0))
    try:
        a.write({"op": "step", "payload": "x" * 200})
        with pytest.raises(FrameCRCError):
            b.read(timeout_s=5.0)
        # the injector is one-shot: the stream pair itself still works
        a.write({"ok": True})
        assert b.read(timeout_s=5.0) == {"ok": True}
    finally:
        set_injector(prev)
        _close_all(fds)


def test_frame_version_mismatch_fails_loud():
    """A peer speaking a different frame version is refused at the
    HEADER — no payload is interpreted, no guess is made."""
    a, b, fds = _pipe_pair()
    try:
        frame = bytearray(encode_frame({"op": "hello"}))
        assert frame[:4] == MAGIC
        frame[4] = PROTO_VERSION + 1  # the version byte
        os.write(fds[3], bytes(frame))
        with pytest.raises(FrameProtocolError, match="version mismatch"):
            b.read(timeout_s=5.0)
    finally:
        _close_all(fds)


def test_frame_bad_magic_and_oversize_fail_loud():
    a, b, fds = _pipe_pair()
    try:
        os.write(fds[3], b"NOPE" + b"\x00" * (HEADER_SIZE - 4))
        with pytest.raises(FrameProtocolError, match="magic"):
            b.read(timeout_s=5.0)
    finally:
        _close_all(fds)
    a, b, fds = _pipe_pair()
    try:
        hdr = struct.pack(">4sBBII", MAGIC, PROTO_VERSION, PT_JSON,
                          (1 << 30) + 1, 0)
        os.write(fds[3], hdr)
        with pytest.raises(FrameProtocolError, match="MAX_FRAME_BYTES"):
            b.read(timeout_s=5.0)
    finally:
        _close_all(fds)


def test_frame_timeout_and_eof():
    a, b, fds = _pipe_pair()
    try:
        t0 = time.monotonic()
        with pytest.raises(FrameTimeout):
            b.read(timeout_s=0.05)
        assert time.monotonic() - t0 < 2.0
        # half a frame then EOF: the kill-mid-write case
        full = encode_frame({"op": "step"})
        os.write(fds[3], full[: len(full) // 2])
        os.close(fds[3])
        with pytest.raises(FrameEOF):
            b.read(timeout_s=5.0)
    finally:
        _close_all(fds)


def test_frame_split_across_timed_writes_is_recoverable():
    """ISSUE 13 satellite: a frame split across timed writes — the
    deadline landing mid-HEADER or mid-payload — must surface as the
    RECOVERABLE FrameTimeout with the buffer intact, so a later read
    resumes at the right offset and decodes the frame. (The old reader
    consumed the header before the payload arrived; a retry then
    parsed leftover payload bytes as a new header — a slow peer
    surfacing as the unrecoverable FrameProtocolError/FrameCRCError.)"""
    a, b, fds = _pipe_pair()
    try:
        full = encode_frame({"op": "ping", "n": 7})
        # 1) deadline mid-HEADER: only 3 of 14 header bytes arrive
        os.write(fds[3], full[:3])
        with pytest.raises(FrameTimeout):
            b.read(timeout_s=0.05)
        # 2) the rest of the header + half the payload, another timeout
        os.write(fds[3], full[3:HEADER_SIZE + 4])
        with pytest.raises(FrameTimeout):
            b.read(timeout_s=0.05)
        # 3) the tail lands: the SAME stream decodes the frame whole
        os.write(fds[3], full[HEADER_SIZE + 4:])
        assert b.read(timeout_s=1.0) == {"op": "ping", "n": 7}
        # and the stream is still aligned for the next frame
        os.write(fds[3], encode_frame({"op": "step"}))
        assert b.read(timeout_s=1.0) == {"op": "step"}
    finally:
        _close_all(fds)


def test_frame_kvpages_roundtrip_over_pipe():
    """The PT_KVPAGES tensor frame (ISSUE 13) rides the same pipe
    protocol: meta + raw page bytes round-trip exactly, and the CRC
    still covers the whole payload."""
    import numpy as np

    from avenir_tpu.serve.frames import PT_KVPAGES

    a, b, fds = _pipe_pair()
    try:
        arrays = [np.arange(24, dtype=np.float32).reshape(2, 3, 4),
                  np.arange(6, dtype=np.int8)]
        a.write(({"op": "import_pages", "seq": 3,
                  "records": [{"tokens": [[1, 2]], "n_prefix": 0,
                               "kv_dtype": "bf16"}]}, arrays),
                ptype=PT_KVPAGES)
        out = b.read(timeout_s=2.0)
        assert out["op"] == "import_pages" and out["seq"] == 3
        assert np.array_equal(out["arrays"][0], arrays[0])
        assert np.array_equal(out["arrays"][1], arrays[1])
    finally:
        _close_all(fds)


# ---------------------------------------------------------------------
# respawn supervisor schedule (fast: fake replicas, fake clock)
# ---------------------------------------------------------------------


class _FakeRep:
    def __init__(self):
        self.replica_id = 0
        self.state = "healthy"
        self.deaths = 0
        self.last_error = None
        self.pid = 123
        self.revives = 0
        self.fail_next_revive = False

    def die(self):
        self.state = "dead"
        self.deaths += 1

    def revive(self):
        if self.fail_next_revive:
            raise RuntimeError("spawn failed")
        self.state = "healthy"
        self.revives += 1


def test_supervisor_backoff_schedule_and_exhaustion():
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve.proc import RespawnSupervisor

    reg = MetricsRegistry()
    rep = _FakeRep()
    sup = RespawnSupervisor(
        policy=RetryPolicy(attempts=9, base_s=1.0, cap_s=4.0, jitter=0.0),
        max_respawns=2, clock=lambda: 0.0, registry=reg,
        echo=lambda *a: None).attach([rep])

    rep.die()
    sup.poll(0.0)            # death observed: next attempt at +1.0
    assert rep.state == "dead" and sup.pending()
    sup.poll(0.5)            # inside the backoff window: nothing
    assert rep.revives == 0
    sup.poll(1.0)            # due: respawn #1
    assert rep.revives == 1 and rep.state == "healthy"

    rep.die()
    sup.poll(1.1)            # second consecutive death: delay doubles
    sup.poll(2.9)
    assert rep.revives == 1  # 1.1 + 2.0 = 3.1 not reached yet
    sup.poll(3.2)
    assert rep.revives == 2

    rep.die()                # third consecutive death: budget (2) blown
    sup.poll(3.3)
    assert not sup.pending() and sup.exhausted(rep)
    sup.poll(99.0)           # given up: never respawned again
    assert rep.revives == 2
    assert reg.snapshot()["counters"]["replica_respawns"] == 2.0


def test_supervisor_failed_respawn_counts_and_budget_resets():
    from avenir_tpu.obs import MetricsRegistry
    from avenir_tpu.serve.proc import RespawnSupervisor

    reg = MetricsRegistry()
    rep = _FakeRep()
    sup = RespawnSupervisor(
        policy=RetryPolicy(attempts=9, base_s=1.0, cap_s=4.0, jitter=0.0),
        max_respawns=3, reset_after_s=10.0, clock=lambda: 0.0,
        registry=reg, echo=lambda *a: None).attach([rep])
    rep.die()
    sup.poll(0.0)
    rep.fail_next_revive = True
    sup.poll(1.0)            # attempt raises -> another backoff step
    assert rep.revives == 0 and sup.pending()
    rep.fail_next_revive = False
    sup.poll(1.5)            # 1.0 + delay(2)=2.0 -> due at 3.0
    assert rep.revives == 0
    sup.poll(3.0)
    assert rep.revives == 1
    # healthy long enough: the failure budget is refunded
    sup.poll(4.0)
    sup.poll(14.1)
    rep.die()
    sup.poll(14.2)           # first failure again -> base delay (1.0)
    sup.poll(15.2)
    assert rep.revives == 2


# ---------------------------------------------------------------------
# process-backend fleet (slow: real workers, real kills)
# ---------------------------------------------------------------------

import tests.test_serve_router as trs  # noqa: E402  (helpers + cases)


@pytest.fixture(scope="module")
def pfix():
    import numpy as np
    from flax import nnx

    from avenir_tpu.models.gpt import GPT

    model = GPT(trs.GPT_TINY, rngs=nnx.Rngs(0))
    return model, trs._mk_requests(model, np.random.default_rng(3), 6)


@pytest.fixture()
def _close_routers():
    """Reap every process-backend router a test creates — leaked worker
    processes would outlive the suite."""
    created = []
    yield created
    for router in created:
        try:
            router.close()
        except Exception:
            pass


def _mk_router(created, model, **kw):
    from avenir_tpu.serve import Router

    kw.setdefault("backend", "process")
    router = Router(model, **kw)
    created.append(router)
    return router


@pytest.mark.slow
def test_process_sigkill_mid_decode_bit_parity(pfix, _close_routers):
    """THE tentpole oracle: a REAL SIGKILL to a worker process
    mid-decode loses nothing — the parent sees pipe EOF, requeues the
    corpse's work, re-prefills on the survivor, and every completed
    stream is bit-identical to one-shot generation."""
    from avenir_tpu.obs import MetricsRegistry

    model, reqs = pfix
    reg = MetricsRegistry()
    router = _mk_router(_close_routers, model, n_replicas=2, n_slots=2,
                        max_seq_len=32, registry=reg, seed=0)
    refs = trs._submit_all(router, reqs[:4])
    for _ in range(3):
        router.step()  # dispatched + first tokens on both workers
    victim = next(r for r in router.replicas if r.busy)
    os.kill(victim.pid, signal.SIGKILL)
    done = router.drain()
    assert len(done) == 4
    trs._assert_parity(done, refs)
    assert victim.state == "dead" and victim.deaths == 1
    moved = [f for f in done if f.failovers > 0]
    assert moved and all(f.replica != victim.replica_id for f in moved)
    assert reg.snapshot()["counters"]["serve_failovers"] == len(moved)


@pytest.mark.slow
def test_process_hang_detected_by_rpc_timeout(pfix, _close_routers):
    """A wedged worker (worker_hang: alive, silent) is detected by the
    per-op RPC timeout, SIGKILLed, and its work moves — parity holds."""
    from avenir_tpu.obs import MetricsRegistry

    model, reqs = pfix
    reg = MetricsRegistry()
    router = _mk_router(_close_routers, model, n_replicas=2, n_slots=1,
                        max_seq_len=32, registry=reg, seed=0,
                        stall_floor_secs=0.5,
                        proc_kwargs={"rpc_slack_secs": 1.0})
    # warm both workers past the compile grace first
    warm = trs._submit_all(router, reqs[4:6])
    done = router.drain()
    trs._assert_parity(done, warm)
    assert all(r._n_busy_steps >= 2 for r in router.replicas)
    refs = trs._submit_all(router, reqs[:2])
    router.step()  # both dispatched
    victim = next(r for r in router.replicas if r.busy)
    victim.arm_fault("worker_hang:n=1", seed=0)
    done = router.drain()
    assert len(done) == 2
    trs._assert_parity(done, refs)
    assert victim.state == "dead"
    snap = reg.snapshot()["counters"]
    assert snap["rpc_timeouts"] == 1
    assert snap["serve_failovers"] >= 1


@pytest.mark.slow
def test_process_frame_corruption_is_death_not_retry(pfix, _close_routers):
    """An armed frame_corrupt trips the parent's CRC check on a real
    step reply: counted, fatal for the replica, work failed over with
    parity — and never retried."""
    from avenir_tpu.obs import MetricsRegistry

    model, reqs = pfix
    reg = MetricsRegistry()
    router = _mk_router(_close_routers, model, n_replicas=2, n_slots=1,
                        max_seq_len=32, registry=reg, seed=0)
    refs = trs._submit_all(router, reqs[:2])
    router.step()
    victim = next(r for r in router.replicas if r.busy)
    victim.arm_fault("frame_corrupt:n=1", seed=0)
    done = router.drain()
    assert len(done) == 2
    trs._assert_parity(done, refs)
    assert victim.state == "dead"
    assert "CRC" in str(victim.last_error)
    assert reg.snapshot()["counters"]["frame_crc_errors"] == 1


@pytest.mark.slow
def test_process_disagg_prefill_sigkill_mid_transfer_bit_parity(
        _close_routers):
    """ISSUE 13 satellite: a REAL SIGKILL to the prefill-class worker
    after k of n KV pages shipped over PT_KVPAGES frames. The parent
    sees pipe EOF, the corpse's in-flight transfers are discarded with
    its attempts, the requests requeue and re-prefill from prompt+rng
    on the decode class — 0 requests lost, every completed stream
    bit-identical to one-shot generate_cached."""
    import numpy as np
    from flax import nnx

    import tests.test_disagg as td
    from avenir_tpu.models.gpt import GPT
    from avenir_tpu.obs import MetricsRegistry

    model = GPT(td.GPT_TINY, rngs=nnx.Rngs(0))
    reqs = td._mk_requests(model, np.random.default_rng(11), 4)
    reg = MetricsRegistry()
    router = _mk_router(_close_routers, model, n_replicas=3, n_slots=2,
                        max_seq_len=64, registry=reg, seed=0,
                        n_prefill=1, engine_kwargs=dict(td.EKW))
    victim = router.replicas[0]
    assert victim.role == "prefill"
    refs = td._submit_all(router, reqs)
    # step until pages have crossed the class boundary (k of n shipped:
    # long prompts span several chunks, so the first import lands while
    # later chunks are still computing) — THEN the kill
    for _ in range(60):
        router.step()
        if reg.snapshot()["counters"].get("kv_pages_imported", 0):
            break
    assert reg.snapshot()["counters"].get("kv_pages_imported", 0) > 0, (
        "the kill must land MID-transfer, after some pages shipped")
    os.kill(victim.pid, signal.SIGKILL)
    done = router.drain()
    assert len(done) == len(reqs)           # 0 requests lost
    td._assert_parity(done, refs)
    assert victim.state == "dead" and victim.deaths == 1
    assert not router._transfer, "transfer state leaked past failover"
    snap = reg.snapshot()["counters"]
    assert snap["serve_failovers"] >= 1
    # survivors (decode class) finished everything
    assert all(f.replica != victim.replica_id for f in done)


@pytest.mark.slow
def test_drain_waits_out_respawn_backoff_then_fails_loud(
        pfix, _close_routers):
    """ISSUE 8 satellite: drain() with zero healthy replicas but a
    respawn pending waits out the backoff window (bounded) and
    completes; with the budget exhausted it fails loud instead."""
    from avenir_tpu.obs import MetricsRegistry

    model, reqs = pfix
    reg = MetricsRegistry()
    router = _mk_router(
        _close_routers, model, n_replicas=1, n_slots=2, max_seq_len=32,
        registry=reg, seed=0, supervise=True, max_respawns=3,
        respawn_policy=RetryPolicy(attempts=4, base_s=0.2, cap_s=1.0,
                                   jitter=0.0))
    kw, ref = reqs[0]
    rid = router.submit(**kw)
    router.step()
    os.kill(router.replicas[0].pid, signal.SIGKILL)
    done = {f.req_id: f for f in router.drain()}  # waits, respawns, serves
    assert done[rid].tokens == ref
    assert reg.snapshot()["counters"]["replica_respawns"] == 1

    # budget exhausted -> all-dead is FINAL and loud
    reg2 = MetricsRegistry()
    router2 = _mk_router(
        _close_routers, model, n_replicas=1, n_slots=2, max_seq_len=32,
        registry=reg2, seed=0, supervise=True, max_respawns=0)
    router2.submit(**reqs[1][0])
    router2.step()
    os.kill(router2.replicas[0].pid, signal.SIGKILL)
    with pytest.raises(RuntimeError, match="all replicas dead"):
        router2.drain()


@pytest.mark.slow
def test_respawn_backoff_soak_repeated_kills(pfix, _close_routers):
    """A worker SIGKILLed again and again keeps coming back on the
    growing backoff schedule until the killing stops — then the queue
    drains with bit parity. The restart loop, soaked."""
    from avenir_tpu.obs import MetricsRegistry

    model, reqs = pfix
    reg = MetricsRegistry()
    router = _mk_router(
        _close_routers, model, n_replicas=1, n_slots=2, max_seq_len=32,
        registry=reg, seed=0, supervise=True, max_respawns=6,
        respawn_policy=RetryPolicy(attempts=7, base_s=0.1, cap_s=0.5,
                                   jitter=0.0))
    refs = trs._submit_all(router, reqs[:3])
    kills = 0
    finished = []
    for _ in range(3000):
        rep = router.replicas[0]
        if kills < 3 and rep.state == "healthy" and rep.busy:
            os.kill(rep.pid, signal.SIGKILL)
            kills += 1
        finished.extend(router.step())
        if kills >= 3 and not router.open_requests:
            break
        time.sleep(0.005)
    finished.extend(router.drain())
    done = {f.req_id: f for f in finished}
    assert kills == 3
    for rid, ref in refs.items():
        assert done[rid].tokens == ref
    snap = reg.snapshot()["counters"]
    assert snap["replica_respawns"] >= 3
    assert router.replicas[0].deaths == 3


_ROUTER_CASES = [
    trs.test_router_parity_across_replicas,
    trs.test_router_failover_bit_parity_step_fault,
    trs.test_router_stall_detected_and_failed_over,
    trs.test_router_fair_share_no_starvation,
    trs.test_router_admission_control_sheds,
    trs.test_router_sheds_on_projected_wait_vs_deadline,
    trs.test_router_rejects_overlong_without_crashing,
    trs.test_router_failover_past_deadline_times_out_not_lost,
    trs.test_replica_state_machine_drain_and_revive,
]


@pytest.mark.slow
@pytest.mark.parametrize("case", _ROUTER_CASES, ids=lambda c: c.__name__)
def test_router_suite_over_process_backend(case, pfix, _close_routers,
                                           monkeypatch):
    """The ISSUE 8 acceptance bar: the ENTIRE router semantics suite —
    parity, failover, stall detection, fair-share, shedding, rejection,
    deadline orphaning, the state machine — passes UNCHANGED over
    `backend='process'`. Same assertions, real worker processes."""
    from avenir_tpu.serve import Router

    class _ProcessRouter(Router):
        def __init__(self, model, **kw):
            kw.setdefault("backend", "process")
            super().__init__(model, **kw)
            _close_routers.append(self)

    monkeypatch.setattr(trs, "Router", _ProcessRouter)
    case(pfix)
