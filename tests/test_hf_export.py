"""HF export round-trip tests (VERDICT r3 item 3): export_hf's output must
load back bit-faithfully through BOTH consumers — our own hf_import dir
loaders AND `transformers.*ForCausalLM.from_pretrained` (the file's core
claim) — for all three families. Covers the GPT-2 Conv1D re-transpose,
the Llama/Mixtral config-field reconstruction, Mixtral expert unstacking,
and the ckpt.pt entry point after a real (tiny) training run.
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp
from flax import nnx

from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.tools.hf_export import export_hf, export_hf_from_ckpt

GPT_TINY = dict(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                n_embd=32, dropout=0.0, bias=True)
LLAMA_TINY = dict(block_size=32, vocab_size=96, n_layer=2, n_head=4,
                  n_kv_head=2, n_embd=64, ffn_hidden=128,
                  rope_theta=10000.0)


def _gpt_model_args():
    ma = dict(GPT_TINY)
    ma.pop("dropout")
    return ma


def _logits(m, idx):
    # pass targets so the model returns FULL-sequence logits (with
    # targets=None the nanoGPT convention returns the last position only)
    out, _ = m(jnp.asarray(idx), jnp.asarray(idx))
    return np.asarray(out)


def test_gpt_roundtrip_through_importer(tmp_path):
    """export → raw safetensors → hf_import's GPT-2 loader into a fresh
    model: logits identical. The Conv1D transpose pair (export T, import
    T back) must be exactly inverse."""
    from safetensors.numpy import load_file

    from avenir_tpu.tools.hf_import import load_hf_gpt2_sd

    m1 = GPT(GPTConfig(**GPT_TINY, attn_impl="xla"), rngs=nnx.Rngs(0))
    dest = str(tmp_path / "hf")
    export_hf(dest, params_or_model=m1, model_args=_gpt_model_args(),
              model_family="gpt")

    m2 = GPT(GPTConfig(**GPT_TINY, attn_impl="xla"), rngs=nnx.Rngs(1))
    load_hf_gpt2_sd(m2, load_file(f"{dest}/model.safetensors"))

    idx = np.random.default_rng(0).integers(0, 64, (2, 16))
    np.testing.assert_array_equal(_logits(m1, idx), _logits(m2, idx))


def test_gpt_transformers_from_pretrained(tmp_path):
    """The core claim: `GPT2LMHeadModel.from_pretrained(dest)` loads the
    export directly (config.json + safetensors, tied head re-derived)
    and produces the same logits."""
    from transformers import GPT2LMHeadModel

    m1 = GPT(GPTConfig(**GPT_TINY, attn_impl="xla"), rngs=nnx.Rngs(0))
    dest = str(tmp_path / "hf")
    export_hf(dest, params_or_model=m1, model_args=_gpt_model_args(),
              model_family="gpt")

    hf = GPT2LMHeadModel.from_pretrained(dest, local_files_only=True)
    hf.eval()
    idx = np.random.default_rng(0).integers(0, 64, (2, 16))
    with torch.no_grad():
        t_logits = hf(torch.from_numpy(idx)).logits
    np.testing.assert_allclose(_logits(m1, idx), t_logits.numpy(),
                               atol=2e-4, rtol=2e-4)


def test_llama_roundtrip_both_consumers(tmp_path):
    from transformers import LlamaForCausalLM

    from avenir_tpu.models.llama import Llama, LlamaConfig
    from avenir_tpu.tools.hf_import import llama_from_hf

    m1 = Llama(LlamaConfig(**LLAMA_TINY, attn_impl="xla"), rngs=nnx.Rngs(0))
    ma = dict(LLAMA_TINY, norm_eps=1e-5)
    dest = str(tmp_path / "hf")
    export_hf(dest, params_or_model=m1, model_args=ma, model_family="llama")

    idx = np.random.default_rng(0).integers(0, 96, (2, 24))
    # our dir loader reconstructs the config from config.json
    m2 = llama_from_hf(dest, attn_impl="xla")
    np.testing.assert_array_equal(_logits(m1, idx), _logits(m2, idx))
    # transformers
    hf = LlamaForCausalLM.from_pretrained(
        dest, local_files_only=True, attn_implementation="eager"
    )
    hf.eval()
    with torch.no_grad():
        t_logits = hf(torch.from_numpy(idx)).logits
    np.testing.assert_allclose(_logits(m1, idx), t_logits.numpy(),
                               atol=2e-4, rtol=2e-4)


def test_mixtral_roundtrip_both_consumers(tmp_path):
    from transformers import MixtralForCausalLM

    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig
    from avenir_tpu.tools.hf_import import mixtral_from_hf

    tiny = dict(LLAMA_TINY, n_experts=4, n_experts_per_tok=2)
    # capacity E/K → nothing drops, so logits match HF exactly
    cap = tiny["n_experts"] / tiny["n_experts_per_tok"]
    m1 = Mixtral(MixtralConfig(**tiny, capacity_factor=cap, attn_impl="xla"),
                 rngs=nnx.Rngs(0))
    ma = dict(tiny, norm_eps=1e-5)
    dest = str(tmp_path / "hf")
    export_hf(dest, params_or_model=m1, model_args=ma,
              model_family="mixtral")

    idx = np.random.default_rng(0).integers(0, 96, (2, 16))
    m2 = mixtral_from_hf(dest, attn_impl="xla", capacity_factor=cap)
    np.testing.assert_array_equal(_logits(m1, idx), _logits(m2, idx))
    hf = MixtralForCausalLM.from_pretrained(
        dest, local_files_only=True, attn_implementation="eager"
    )
    hf.eval()
    with torch.no_grad():
        t_logits = hf(torch.from_numpy(idx)).logits
    np.testing.assert_allclose(_logits(m1, idx), t_logits.numpy(),
                               atol=5e-4, rtol=5e-4)


@pytest.mark.slow  # ~11-13s on this harness (trains a loop + exports);
# far over the tier-1 budget test_zz_slow_guard enforces
def test_export_from_trained_ckpt(tmp_path, char_dataset):
    """The CLI entry point: train 2 iters, convert out_dir/ckpt.pt, load
    the export back — logits match the checkpoint-restored model."""
    from safetensors.numpy import load_file

    from avenir_tpu.checkpoint.bridge import load_torch_state_dict
    from avenir_tpu.checkpoint.io import load_checkpoint
    from avenir_tpu.tools.hf_import import load_hf_gpt2_sd
    from avenir_tpu.train.loop import run_training
    from tests.test_train_tpu import make_cfg

    out = str(tmp_path / "out")
    cfg = make_cfg(char_dataset["dir"], out, max_iters=2, eval_interval=2,
                   mesh_shape="data:1", bias=True)
    run_training(cfg)
    dest = str(tmp_path / "hf")
    export_hf_from_ckpt(out, dest)

    ckpt = load_checkpoint(out)
    vocab = ckpt["model_args"]["vocab_size"]
    gcfg = GPTConfig(
        block_size=32, vocab_size=vocab, n_layer=2, n_head=2, n_embd=32,
        dropout=0.0, bias=True, attn_impl="xla",
    )
    ref = GPT(gcfg, rngs=nnx.Rngs(0))
    load_torch_state_dict(ref, {k: np.asarray(v)
                                for k, v in ckpt["model"].items()})
    got = GPT(gcfg, rngs=nnx.Rngs(1))
    load_hf_gpt2_sd(got, load_file(f"{dest}/model.safetensors"))

    idx = np.random.default_rng(0).integers(0, vocab, (2, 16))
    np.testing.assert_array_equal(_logits(ref, idx), _logits(got, idx))
