"""Family-aware sampling (SURVEY.md §3.5; VERDICT r2 item 6): a Llama or
Mixtral ckpt.pt written by the trainer must be sampleable through the same
`sample.py --backend=tpu` CLI as a GPT one — model_from_checkpoint
dispatches on the checkpoint's `model_family` field."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _train_tiny(char_dataset, out, family_args, max_iters=4):
    cmd = [
        sys.executable, "train.py", "--backend=tpu", "--mesh_shape=data:1",
        f"--dataset={char_dataset['dir']}", f"--out_dir={out}",
        "--compile=False", "--eval_interval=4", "--eval_iters=1",
        "--log_interval=2", "--batch_size=2", "--block_size=32",
        "--dropout=0.0", "--gradient_accumulation_steps=1",
        "--always_save_checkpoint=True", "--warmup_iters=1",
        "--lr_decay_iters=4", "--learning_rate=1e-3", "--dtype=float32",
        f"--max_iters={max_iters}", "--use_pallas=False",
    ] + family_args
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr


def _sample(out):
    cmd = [
        sys.executable, "sample.py", "--backend=tpu", f"--out_dir={out}",
        "--num_samples=1", "--max_new_tokens=8", "--top_k=5", "--start=ab",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(cmd, cwd=REPO, env=env, capture_output=True,
                       text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


@pytest.mark.slow
def test_sample_cli_llama_ckpt(char_dataset, tmp_path):
    out = str(tmp_path / "llama")
    _train_tiny(char_dataset, out, [
        "--model_type=llama", "--n_layer=2", "--n_head=2", "--n_kv_head=1",
        "--n_embd=32", "--ffn_hidden=64",
    ])
    stdout = _sample(out)
    # one sample separator + a decoded string beginning with the prompt
    assert "---------------" in stdout
    body = stdout.split("---------------")[0].strip().splitlines()[-1]
    assert body.startswith("ab") and len(body) == 2 + 8


@pytest.mark.slow
def test_sample_cli_mixtral_ckpt(char_dataset, tmp_path):
    out = str(tmp_path / "mixtral")
    _train_tiny(char_dataset, out, [
        "--model_type=mixtral", "--n_layer=2", "--n_head=2", "--n_kv_head=1",
        "--n_embd=32", "--ffn_hidden=64", "--n_experts=4",
        "--n_experts_per_tok=2",
    ])
    stdout = _sample(out)
    assert "---------------" in stdout
    body = stdout.split("---------------")[0].strip().splitlines()[-1]
    assert body.startswith("ab") and len(body) == 2 + 8
