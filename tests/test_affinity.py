"""Fleet KV CDN tests (avenir_tpu/serve/affinity.py + the router/
engine/proc wiring, ISSUE 17): the policy math is exact (pure, fast),
affinity placement routes shared prefixes to the replica that holds
them, peer pulls ship real KV pages and keep bit parity, and the
fallback contract holds under every pull failure mode — a SIGKILLed
pull source mid-transfer and a CRC-tripped PT_KVPAGES frame both
degrade to local re-prefill with outputs bit-identical to one-shot
generate_cached, zero requests lost, counters telling the truth.

Budget notes: one module-scoped GPT; shared-prefix prompts stay in one
power-of-2 bucket (len 25..31) and one MAX_NEW so each engine pays one
prefill-chunk ladder + one decode compile; process cases are slow
(worker processes pay a jax import + their own compiles).
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.obs.trace import Tracer
from avenir_tpu.serve import PageAllocator, Router
from avenir_tpu.serve.affinity import (
    AffinityPolicy,
    affinity_bonus,
    pull_plan,
    resolve_affinity,
)
from avenir_tpu.utils.faults import FaultInjector, set_injector

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
PAGED_KW = dict(kv_impl="paged", page_size=8, n_pages=48,
                prefill_chunk=16)
MAX_NEW = 5
PS = PAGED_KW["page_size"]


@pytest.fixture(scope="module")
def model():
    return GPT(GPT_TINY, rngs=nnx.Rngs(0))


def _mk_shared_requests(model, rng, n, prefix, key_base=7000):
    """n requests sharing `prefix` (+ short random tails — one prompt
    bucket) with one-shot reference streams; explicit rng keys pin the
    parity oracle across placements, pulls, and failovers."""
    reqs = []
    for i in range(n):
        tail = [int(t) for t in
                rng.integers(0, 64, int(rng.integers(1, 8)))]
        prompt = list(prefix) + tail
        key = jax.random.key(key_base + i)
        y = np.asarray(generate_cached(
            model, key, jnp.asarray(prompt, jnp.int32)[None], MAX_NEW,
            temperature=1.0, top_k=8))[0]
        reqs.append((dict(prompt=prompt, max_new_tokens=MAX_NEW,
                          temperature=1.0, top_k=8, rng=key),
                     [int(t) for t in y]))
    return reqs


def _prefix(seed, n_tokens=3 * PS):
    rng = np.random.default_rng(seed)
    return [int(t) for t in rng.integers(0, 64, n_tokens)]


def _assert_all_parity(done, refs):
    assert len(done) == len(refs)
    for f in done:
        assert f.finish_reason == "length", f.finish_reason
        assert f.tokens == refs[f.req_id], (
            f"request {f.req_id} diverged:\n ref {refs[f.req_id]}\n "
            f"got {f.tokens}")


# ---------------------------------------------------------------------
# 1. policy math (pure, no fleet)
# ---------------------------------------------------------------------


def test_resolve_affinity_forms():
    assert resolve_affinity(False) is None
    assert resolve_affinity(None) is None
    pol = resolve_affinity(True)
    assert isinstance(pol, AffinityPolicy) and pol.pull
    pol = resolve_affinity({"weight": 0.5, "pull": False})
    assert pol.weight == 0.5 and not pol.pull
    assert resolve_affinity(pol) is pol
    with pytest.raises(TypeError):
        resolve_affinity(3)
    with pytest.raises(AssertionError):
        AffinityPolicy(weight=-1.0)
    with pytest.raises(AssertionError):
        AffinityPolicy(staleness_s=0.0)
    with pytest.raises(AssertionError):
        AffinityPolicy(pull_min_tokens=0)


def test_affinity_bonus_capped_by_free_fraction():
    pol = AffinityPolicy(weight=1.0)
    # full shared prefix on an empty replica: the full weight
    assert affinity_bonus(pol, 32, 32, 1.0) == 1.0
    # the free-slot cap: a loaded replica's cache gravity shrinks
    assert affinity_bonus(pol, 32, 32, 0.25) == 0.25
    assert affinity_bonus(pol, 32, 32, 0.0) == 0.0
    # partial share scales linearly below the cap
    assert affinity_bonus(pol, 8, 32, 1.0) == pytest.approx(0.25)
    # no share, no bonus — and never negative
    assert affinity_bonus(pol, 0, 32, 1.0) == 0.0
    assert affinity_bonus(pol, 8, 32, -0.5) == 0.0
    assert affinity_bonus(AffinityPolicy(weight=2.0), 8, 32, 1.0) \
        == pytest.approx(0.5)


def test_shard_home_is_stable_and_spreads():
    from avenir_tpu.serve.affinity import shard_home

    pol = AffinityPolicy()
    prompts = [[t] * 16 + [99] for t in range(32)]
    homes = [shard_home(pol, p, 16, [0, 1, 2]) for p in prompts]
    # deterministic: same first page -> same home, tail irrelevant
    assert homes == [shard_home(pol, p[:16] + [7], 16, [0, 1, 2])
                     for p in prompts]
    # spreads: 32 distinct prefix families do not herd on one replica
    assert len(set(homes)) == 3
    # candidate-set dependent, still deterministic after a death
    assert all(shard_home(pol, p, 16, [0, 2]) in (0, 2)
               for p in prompts)
    assert shard_home(pol, prompts[0], 16, []) is None
    assert shard_home(AffinityPolicy(shard_weight=0.0), prompts[0], 16,
                      [0, 1]) is None
    with pytest.raises(AssertionError):
        AffinityPolicy(shard_weight=-0.1)


def test_pull_plan_threshold_and_tiebreak():
    pol = AffinityPolicy()  # pull_min_tokens None -> 2 x page_size
    # peer 24 tokens deeper than chosen's 0, threshold 16: pull from 1
    assert pull_plan(pol, {0: 0, 1: 24}, 0, 8) == (1, 24, 0)
    # advantage below threshold: no pull
    assert pull_plan(pol, {0: 16, 1: 24}, 0, 8) is None
    # chosen already fleet-best: no pull
    assert pull_plan(pol, {0: 24, 1: 8}, 0, 8) is None
    # local anchors ride the plan: pull only the delta beyond 8
    assert pull_plan(pol, {0: 8, 1: 32}, 0, 8) == (1, 32, 8)
    # deterministic tie-break on replica id (str sort, cache_map rule)
    assert pull_plan(pol, {1: 24, 2: 24}, 0, 8) == (1, 24, 0)
    # pull disabled -> placement-only affinity
    assert pull_plan(AffinityPolicy(pull=False), {0: 0, 1: 64}, 0, 8) \
        is None
    # explicit threshold overrides the page-size default
    tight = AffinityPolicy(pull_min_tokens=25)
    assert pull_plan(tight, {0: 0, 1: 24}, 0, 8) is None
    assert pull_plan(tight, {0: 0, 1: 32}, 0, 8) == (1, 32, 0)


def test_lookup_chain_walks_registered_prefix():
    a = PageAllocator(n_pages=8, page_size=4, prefix_sharing=True)
    chain = [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]]
    pairs = a.import_chain(chain)
    assert [is_new for _, is_new in pairs] == [True] * 3
    pages = [p for p, _ in pairs]
    assert a.lookup_chain(chain) == pages
    # partial walk: diverging tail stops the match (a valid answer)
    assert a.lookup_chain(chain[:2] + [[0, 0, 0, 0]]) == pages[:2]
    assert a.lookup_chain([[0, 0, 0, 0]]) == []
    # a short page has no chain identity
    assert a.lookup_chain([[1, 2, 3]]) == []
    # the walk touched hits + recency (pull reuse feeds the summary)
    assert a._meta[pages[0]][0] > 0


def test_affinity_requires_telescope_and_paged(model):
    with pytest.raises(AssertionError, match="cache_telescope"):
        Router(model, n_replicas=2, affinity=True,
               engine_kwargs=dict(PAGED_KW))
    with pytest.raises(AssertionError, match="paged"):
        Router(model, n_replicas=2, affinity=True, cache_telescope=True)


# ---------------------------------------------------------------------
# 2. inproc fleet: placement, pulls, parity
# ---------------------------------------------------------------------


def test_affinity_places_on_warm_replica(model):
    """A second request sharing the first's prefix routes to the
    replica already holding the chain — and the audit now counts those
    tokens reused instead of missed."""
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=2, registry=reg,
                    seed=0, cache_telescope=True, affinity=True,
                    engine_kwargs=dict(PAGED_KW))
    prefix = _prefix(0)
    reqs = _mk_shared_requests(model, np.random.default_rng(1), 3,
                               prefix)
    done = []
    refs = {}
    for kw, ref in reqs:
        refs[router.submit(**kw)] = ref
        done.extend(router.drain())  # serialize: each placement sees
        #                              the previous request's chain
    assert len(done) == len(reqs)
    for f in done:
        assert f.finish_reason == "length"
        assert f.tokens == refs[f.req_id], f"request {f.req_id} diverged"
    assert len({f.replica for f in done}) == 1  # cache gravity held
    snap = reg.snapshot()["counters"]
    assert snap["affinity_hits"] >= 2, snap.get("affinity_hits")
    # all three landed on ONE replica (cache gravity) with reuse
    assert snap["prefix_tokens_reused"] >= 2 * len(prefix)
    assert snap.get("prefix_pull_fallbacks", 0) == 0
    router.close()


def test_peer_pull_ships_pages_with_parity(model):
    """The miss path: the warm replica is out of slots, so placement
    lands on the cold one and the router brokers a pull — real pages
    move, prefill starts beyond them, output stays bit-identical."""
    reg = MetricsRegistry()
    tracer = Tracer(capacity=2048)
    router = Router(model, n_replicas=2, n_slots=2, registry=reg,
                    seed=0, cache_telescope=True, affinity=True,
                    tracer=tracer, engine_kwargs=dict(PAGED_KW))
    prefix = _prefix(2)
    reqs = _mk_shared_requests(model, np.random.default_rng(3), 4,
                               prefix, key_base=7100)
    refs = {}
    done = []
    # request 0 primes a replica with the chain
    kw, ref = reqs[0]
    refs[router.submit(**kw)] = ref
    done.extend(router.drain())
    warm = max(router._cache_map.match(prefix).items(),
               key=lambda kv: kv[1])[0]
    # two long-running requests fill the warm replica's slots
    for kw, _ in reqs[1:3]:
        long_kw = dict(kw, max_new_tokens=30)
        rid = router.submit(**long_kw)
        key = long_kw["rng"]
        refs[rid] = [int(t) for t in np.asarray(generate_cached(
            model, key, jnp.asarray(long_kw["prompt"], jnp.int32)[None],
            30, temperature=1.0, top_k=8))[0]]
    router.step()
    warm_rep = next(r for r in router.replicas if r.replica_id == warm)
    assert warm_rep.dispatchable_slots == 0
    # the shared-prefix request must go COLD -> pull brokered
    kw, ref = reqs[3]
    refs[router.submit(**kw)] = ref
    done.extend(router.drain())
    assert len(done) == len(refs)
    for f in done:
        assert f.finish_reason == "length"
        assert f.tokens == refs[f.req_id], f"request {f.req_id} diverged"
    snap = reg.snapshot()["counters"]
    assert snap["prefix_pull_pages"] >= len(prefix) // PS
    assert snap["prefix_pull_bytes"] > 0
    assert snap.get("prefix_pull_fallbacks", 0) == 0
    pulls = [e for e in tracer.events() if e["ev"] == "prefix_pull"]
    assert len(pulls) == 1 and pulls[0]["outcome"] == "ok"
    assert pulls[0]["src"] == warm and pulls[0]["dst"] != warm
    assert pulls[0]["pages"] == snap["prefix_pull_pages"]
    router.close()


def test_randomized_parity_oracle_with_death_inproc(model):
    """The acceptance oracle, inproc half: randomized multi-tenant
    arrivals with affinity+pull on and a replica killed mid-run —
    every completed stream is bit-identical to one-shot generation."""
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, registry=reg,
                    seed=0, cache_telescope=True, affinity=True,
                    engine_kwargs=dict(PAGED_KW))
    rng = np.random.default_rng(9)
    tenants = [_prefix(10), _prefix(11)]
    reqs = []
    for i in range(8):
        reqs.extend(_mk_shared_requests(
            model, rng, 1, tenants[i % 2], key_base=7200 + 10 * i))
    refs = {}
    submitted = 0
    # the 3rd fleet step kills whichever replica steps 8th — mid-run,
    # with shared chains already advertised and pulls in flight
    prev = set_injector(FaultInjector("serve_step_fail:after=7:n=1"))
    try:
        done = []
        while len(done) < len(reqs):
            while submitted < len(reqs) and submitted - len(done) < 4:
                kw, ref = reqs[submitted]
                refs[router.submit(**kw)] = ref
                submitted += 1
            done.extend(router.step())
    finally:
        set_injector(prev)
    assert len(done) == len(reqs)
    for f in done:
        assert f.finish_reason == "length"
        assert f.tokens == refs[f.req_id], f"request {f.req_id} diverged"
    snap = reg.snapshot()["counters"]
    assert snap["serve_failovers"] >= 1  # the death actually happened
    assert snap["affinity_hits"] >= 1
    router.close()


def test_stale_map_entries_are_ignored(model):
    """An advertised chain older than `staleness_s` stops feeding
    placement: the affinity match drops it and routing falls back to
    pure load placement (no hits, no pulls, no errors)."""
    clock = [0.0]
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=2, registry=reg,
                    seed=0, clock=lambda: clock[0],
                    cache_telescope=True,
                    affinity={"staleness_s": 5.0},
                    engine_kwargs=dict(PAGED_KW))
    prefix = _prefix(4)
    reqs = _mk_shared_requests(model, np.random.default_rng(5), 1,
                               prefix, key_base=7300)
    kw, ref = reqs[0]
    rid = router.submit(**kw)
    done = router.drain()
    assert done[0].req_id == rid and done[0].tokens == ref
    probe = type("R", (), {"prompt": kw["prompt"]})()
    assert max(router._affinity_match(probe).values()) >= len(prefix)
    clock[0] += 60.0  # every advertised summary is now stale
    assert router._affinity_match(probe) == {}
    router.close()


# ---------------------------------------------------------------------
# 3. process fleet: the fallback contract (slow — real workers)
# ---------------------------------------------------------------------


def _proc_router(created, model, reg, **kw):
    router = Router(model, backend="process", registry=reg, seed=0,
                    cache_telescope=True, affinity=True,
                    engine_kwargs=dict(PAGED_KW), **kw)
    created.append(router)
    return router


@pytest.fixture()
def _close_routers():
    created = []
    yield created
    for router in created:
        try:
            router.close()
        except Exception:
            pass


def _prime_and_occupy(router, model, reqs, refs, done):
    """Land the chain on one replica, then fill its slots with two
    long-running requests so the NEXT shared-prefix dispatch must go
    to the other replica and broker a pull from the warm (busy) one."""
    kw, ref = reqs[0]
    refs[router.submit(**kw)] = ref
    done.extend(router.drain())
    prefix = kw["prompt"][:3 * PS]
    warm = max(router._cache_map.match(prefix).items(),
               key=lambda kv: kv[1])[0]
    for kw, _ in reqs[1:3]:
        long_kw = dict(kw, max_new_tokens=30)
        rid = router.submit(**long_kw)
        refs[rid] = [int(t) for t in np.asarray(generate_cached(
            model, long_kw["rng"],
            jnp.asarray(long_kw["prompt"], jnp.int32)[None],
            30, temperature=1.0, top_k=8))[0]]
    for _ in range(2):
        router.step()
    warm_rep = next(r for r in router.replicas if r.replica_id == warm)
    assert warm_rep.dispatchable_slots == 0
    return warm_rep


@pytest.mark.slow
def test_process_pull_roundtrip_parity(model, _close_routers):
    """Happy path over REAL worker processes: the pull_chain RPC moves
    a PT_KVPAGES frame peer->parent->peer and the pulled request's
    output is bit-identical to one-shot generation."""
    reg = MetricsRegistry()
    router = _proc_router(_close_routers, model, reg, n_replicas=2,
                          n_slots=2)
    prefix = _prefix(20)
    reqs = _mk_shared_requests(model, np.random.default_rng(21), 4,
                               prefix, key_base=7400)
    refs = {}
    done = []
    _prime_and_occupy(router, model, reqs, refs, done)
    kw, ref = reqs[3]
    refs[router.submit(**kw)] = ref
    done.extend(router.drain())
    _assert_all_parity(done, refs)
    snap = reg.snapshot()["counters"]
    assert snap["prefix_pull_pages"] >= len(prefix) // PS
    assert snap.get("prefix_pull_fallbacks", 0) == 0
    assert snap.get("serve_failovers", 0) == 0


@pytest.mark.slow
def test_process_pull_source_sigkill_falls_back(model, _close_routers):
    """The fallback contract, death mode: SIGKILL the pull SOURCE so
    the pull_chain RPC dies mid-transfer (pipe EOF partway through the
    tensor frame). The pulled request must complete via local
    re-prefill, bit-identical; the corpse's own work fails over; the
    fallback counter tells the truth."""
    reg = MetricsRegistry()
    router = _proc_router(_close_routers, model, reg, n_replicas=2,
                          n_slots=2)
    prefix = _prefix(22)
    reqs = _mk_shared_requests(model, np.random.default_rng(23), 4,
                               prefix, key_base=7500)
    refs = {}
    done = []
    warm_rep = _prime_and_occupy(router, model, reqs, refs, done)
    os.kill(warm_rep.pid, signal.SIGKILL)
    kw, ref = reqs[3]
    refs[router.submit(**kw)] = ref
    done.extend(router.drain())
    _assert_all_parity(done, refs)
    assert warm_rep.state == "dead"
    snap = reg.snapshot()["counters"]
    assert snap["prefix_pull_fallbacks"] == 1
    assert snap["prefix_pull_pages"] == 0  # nothing landed
    assert snap["serve_failovers"] >= 2    # the corpse's two requests


@pytest.mark.slow
def test_process_pull_frame_corrupt_falls_back(model, _close_routers):
    """The fallback contract, corruption mode: arm frame_corrupt on
    the pull source so the PT_KVPAGES pull reply CRC-trips (dispatch
    runs before replica stepping, so the pull reply IS the armed
    worker's next frame). CRC is death, never retry: the source dies,
    the pulled request re-prefills locally bit-identical, and both the
    CRC and fallback counters record it."""
    reg = MetricsRegistry()
    router = _proc_router(_close_routers, model, reg, n_replicas=2,
                          n_slots=2)
    prefix = _prefix(24)
    reqs = _mk_shared_requests(model, np.random.default_rng(25), 4,
                               prefix, key_base=7600)
    refs = {}
    done = []
    warm_rep = _prime_and_occupy(router, model, reqs, refs, done)
    warm_rep.arm_fault("frame_corrupt:n=1", seed=0)
    kw, ref = reqs[3]
    refs[router.submit(**kw)] = ref
    done.extend(router.drain())
    _assert_all_parity(done, refs)
    assert warm_rep.state == "dead"
    snap = reg.snapshot()["counters"]
    assert snap["frame_crc_errors"] == 1
    assert snap["prefix_pull_fallbacks"] == 1
    assert snap["prefix_pull_pages"] == 0
    assert snap["serve_failovers"] >= 2
