"""Hardening tests (SURVEY.md §4 "Multi-process" + §5 "Race detection /
Failure detection"): SIGKILL-mid-run resume (fault injection), 2-process
jax.distributed rendezvous, 2-process gloo DDP for the torch branch,
checkify over the train step, and the NaN guard."""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _tpu_cli(char_dataset, out, **over):
    args = dict(
        dataset=char_dataset["dir"], out_dir=out, backend="tpu",
        device="cpu", compile=False, eval_interval=5, eval_iters=2,
        log_interval=1, batch_size=4, block_size=32, n_layer=2, n_head=2,
        n_embd=32, dropout=0.0, gradient_accumulation_steps=2,
        always_save_checkpoint=True, warmup_iters=2, lr_decay_iters=60,
        learning_rate=1e-3, use_pallas=False, mesh_shape="data:1",
    )
    args.update(over)
    return [sys.executable, "train.py"] + [f"--{k}={v}" for k, v in args.items()]


@pytest.mark.slow
def test_sigkill_mid_run_resume(char_dataset, tmp_path):
    """Fault injection (SURVEY.md §5 'Failure detection'): SIGKILL the
    trainer after a checkpoint lands, resume, training completes."""
    out = str(tmp_path / "out")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    proc = subprocess.Popen(
        _tpu_cli(char_dataset, out, max_iters=500),
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    # wait for the iter-5 checkpoint ("saving checkpoint" printed at eval
    # cadence), then kill hard mid-step
    deadline = time.time() + 300
    saved = False
    for line in proc.stdout:
        if "saving checkpoint" in line:
            saved = True
        if saved and "iter 7" in line:
            break
        assert time.time() < deadline, "trainer never reached iter 7"
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=60)
    assert os.path.exists(os.path.join(out, "ckpt.pt"))

    r = subprocess.run(
        _tpu_cli(char_dataset, out, max_iters=12, init_from="resume"),
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "resuming" in r.stdout
    assert "iter 12" in r.stdout


@pytest.mark.slow
def test_two_process_jax_distributed_smoke():
    """SURVEY.md §4 'Multi-process': 2-process jax.distributed.initialize
    rendezvous on localhost via the env contract initialize_distributed
    reads (the branch no in-process test can reach)."""
    port = _free_port()
    script = (
        "import os, jax\n"
        "os.environ.pop('XLA_FLAGS', None)\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from avenir_tpu.parallel.mesh import initialize_distributed\n"
        "initialize_distributed()\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "assert jax.device_count() == 2, jax.device_count()\n"
        "from jax.experimental import multihost_utils\n"
        "got = multihost_utils.process_allgather("
        "jax.numpy.asarray([jax.process_index()]))\n"
        "assert sorted(got.ravel().tolist()) == [0, 1], got\n"
        "print('OK', jax.process_index())\n"
    )
    procs = []
    for pid in range(2):
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid),
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
        assert "OK" in o, o


@pytest.mark.slow
def test_two_process_tpu_trainer(char_dataset, tmp_path):
    """The FULL tpu trainer over 2 processes (1 CPU device each, mesh
    data:2): multi-process loader shards (disjoint per-process streams +
    make_array_from_process_local_data), the windowed dispatch loop's
    flush/boundary ordering under real cross-process collectives, the
    collective save with coordinator-only write, and coordinator-only
    logging. The 2-process smoke above only proves rendezvous; this
    proves the product loop."""
    port = _free_port()
    out = str(tmp_path / "out")
    procs = []
    for pid in range(2):
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid),
        )
        env.pop("XLA_FLAGS", None)  # 1 device per process
        procs.append(subprocess.Popen(
            _tpu_cli(char_dataset, out, max_iters=6, eval_interval=3,
                     mesh_shape="data:2", batch_size=2,
                     gradient_accumulation_steps=2),
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
    # coordinator logs; the other process stays quiet
    assert "iter 6" in outs[0], outs[0]
    assert "step 3" in outs[0], outs[0]
    assert "iter 6" not in outs[1], outs[1]
    # the collective save landed exactly once, written by the coordinator
    assert os.path.exists(os.path.join(out, "ckpt.pt"))
    assert "saving checkpoint" in outs[0]
    assert "saving checkpoint" not in outs[1]


@pytest.mark.slow
def test_two_process_sigterm_saves_and_resumes(char_dataset, tmp_path):
    """Coordinated pod preemption (r5, VERDICT r4 missing #3): SIGTERM
    one of two processes mid-run; the flag is exchanged at the next
    window boundary, BOTH processes run the collective save at the SAME
    agreed iteration, and both exit 0. r4 exited without saving here. A
    resume run then continues from the preemption checkpoint."""
    port = _free_port()
    out = str(tmp_path / "out")
    procs = []
    for pid in range(2):
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid),
        )
        env.pop("XLA_FLAGS", None)
        procs.append(subprocess.Popen(
            _tpu_cli(char_dataset, out, max_iters=400, eval_interval=500,
                     mesh_shape="data:2", batch_size=2, dispatch_steps=8,
                     gradient_accumulation_steps=2),
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    # wait until training is demonstrably under way on the coordinator,
    # then SIGTERM the OTHER process only — the coordination must carry
    # the signal across. select()-gated reads: a coordination deadlock
    # (the bug class under test) must FAIL the test at the deadline, not
    # hang the suite on a blocking readline
    import select

    deadline = time.time() + 300
    buf = ""
    while "iter 8" not in buf:
        assert time.time() < deadline, f"trainer never reached iter 8:\n{buf}"
        r, _, _ = select.select([procs[0].stdout], [], [], 5.0)
        if r:
            buf += procs[0].stdout.readline()
    procs[1].send_signal(signal.SIGTERM)
    out0 = buf + procs[0].communicate(timeout=300)[0]
    out1 = procs[1].communicate(timeout=300)[0]
    assert procs[0].returncode == 0, out0
    assert procs[1].returncode == 0, out1
    assert "SIGTERM: saving checkpoint" in out0, out0
    assert os.path.exists(os.path.join(out, "ckpt.pt")), out0
    # both processes left the loop at the same agreed iteration: the
    # resumed pair continues from it without deadlock or restart
    port2 = _free_port()
    procs2 = []
    for pid in range(2):
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port2}",
            JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid),
        )
        env.pop("XLA_FLAGS", None)
        procs2.append(subprocess.Popen(
            _tpu_cli(char_dataset, out, max_iters=40, eval_interval=500,
                     mesh_shape="data:2", batch_size=2, dispatch_steps=8,
                     gradient_accumulation_steps=2, init_from="resume"),
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs2 = [p.communicate(timeout=600)[0] for p in procs2]
    for p, o in zip(procs2, outs2):
        assert p.returncode == 0, o
    assert "resuming from" in outs2[0], outs2[0]
    assert "iter 40" in outs2[0], outs2[0]


@pytest.mark.slow
def test_two_process_async_sharded_checkpoint(char_dataset, tmp_path):
    """Multi-process ASYNC checkpointing (r5): with async_checkpoint=True
    on a 2-process mesh, eval-cadence saves write per-host shard files
    from background threads (zero collectives in the writer), and a
    resume run restores from the sharded set — r4 hard-asserted
    process_count==1 here."""
    port = _free_port()
    out = str(tmp_path / "out")

    def launch(extra, port):
        procs = []
        for pid in range(2):
            env = dict(
                os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
                JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                JAX_NUM_PROCESSES="2", JAX_PROCESS_ID=str(pid),
            )
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                _tpu_cli(char_dataset, out, eval_interval=3,
                         mesh_shape="data:2", batch_size=2,
                         gradient_accumulation_steps=2,
                         async_checkpoint=True, **extra),
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            ))
        return procs

    procs = launch(dict(max_iters=6), port)
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
    # one shard file per process (the async eval-cadence saves), plus the
    # end-of-run portable full ckpt.pt
    assert os.path.exists(os.path.join(out, "ckpt-shard-00000.pkl")), outs[0]
    assert os.path.exists(os.path.join(out, "ckpt-shard-00001.pkl")), outs[1]
    assert os.path.exists(os.path.join(out, "ckpt.pt")), outs[0]
    assert "final checkpoint (full)" in outs[0], outs[0]

    # simulate the preemption window the sharded saves exist for: the pod
    # died after an async save but before any full save — resume must
    # restore from the sharded set
    os.remove(os.path.join(out, "ckpt.pt"))
    procs2 = launch(dict(max_iters=12, init_from="resume"), _free_port())
    outs2 = [p.communicate(timeout=600)[0] for p in procs2]
    for p, o in zip(procs2, outs2):
        assert p.returncode == 0, o
    assert "resuming from" in outs2[0] and "sharded set" in outs2[0], outs2[0]
    assert "iter 12" in outs2[0], outs2[0]


@pytest.mark.slow
def test_two_process_gloo_ddp(char_dataset, tmp_path):
    """The torch DDP branch (train.py:107-119) over gloo on CPU: two ranks,
    three iters, both exit clean and rank0 logs losses."""
    port = _free_port()
    out = str(tmp_path / "out")
    cli = [
        sys.executable, "train.py",
        f"--dataset={char_dataset['dir']}", f"--out_dir={out}",
        "--device=cpu", "--compile=False", "--eval_interval=10",
        "--eval_iters=2", "--log_interval=1", "--batch_size=2",
        "--block_size=32", "--n_layer=2", "--n_head=2", "--n_embd=32",
        "--gradient_accumulation_steps=2", "--max_iters=3",
        "--warmup_iters=1", "--lr_decay_iters=10", "--dtype=float32",
    ]
    procs = []
    for rank in range(2):
        env = dict(
            os.environ, RANK=str(rank), LOCAL_RANK=str(rank),
            WORLD_SIZE="2", MASTER_ADDR="127.0.0.1", MASTER_PORT=str(port),
        )
        procs.append(subprocess.Popen(
            cli, cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, o in zip(procs, outs):
        assert p.returncode == 0, o
    assert "iter 3" in outs[0], outs[0]  # rank 0 is master
    assert "iter 3" not in outs[1]       # non-master stays quiet


def test_checkify_train_step_clean(char_dataset):
    """jax.experimental.checkify over the jit step: no NaN/div-by-zero/OOB
    errors on a healthy config (SURVEY.md §5 'Race detection')."""
    from flax import nnx
    from jax.experimental import checkify

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import make_step_fns

    cfg = GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
    model = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(model, nnx.Param)
    tx, _ = make_optimizer(params, learning_rate=1e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=0, lr_decay_iters=10, min_lr=1e-4)
    opt_state = tx.init(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 64, (1, 2, 16)).astype(np.int32))
    y = jnp.asarray(rng.integers(0, 64, (1, 2, 16)).astype(np.int32))

    checked = checkify.checkify(
        lambda p, o, r, xx, yy: step_fn(p, o, tx, r, xx, yy),
        errors=checkify.float_checks,
    )
    err, (params, opt_state, metrics) = jax.jit(checked)(
        params, opt_state, jax.random.key(0), x, y
    )
    err.throw()  # no error on a healthy step
    assert np.isfinite(float(metrics["loss"]))


def test_loop_raises_on_nonfinite_loss(char_dataset, tmp_path, monkeypatch):
    """The loop's NaN guard: poison the LR to produce a NaN loss fast and
    assert the FloatingPointError fires (rather than silently logging nan)."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=30,
                   learning_rate=1e6, grad_clip=0.0, eval_interval=100,
                   warmup_iters=0, mesh_shape="data:1")
    with pytest.raises(FloatingPointError):
        run_training(cfg)


def test_profile_trace_window(char_dataset, tmp_path):
    """--profile captures a real xplane trace over iters 10-20 and the run
    completes (SURVEY.md §5 tracing; VERDICT r1 weak item 8: the start/stop
    gating must work, not just exist)."""
    import glob

    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=22,
                   profile=True, eval_interval=50, mesh_shape="data:1")
    res = run_training(cfg)
    assert res["iter_num"] >= 22
    traces = glob.glob(
        str(tmp_path / "out" / "profile" / "**" / "*.xplane.pb"),
        recursive=True,
    )
    assert traces, "profile window produced no xplane trace"


def test_profile_trace_stopped_on_early_exit(char_dataset, tmp_path):
    """A trace started at iter 10 must be STOPPED (and written) when the
    loop exits before the iter-20 stop point (VERDICT r2 weak #4: the
    dangling-trace leak). max_iters=15 exits mid-window; the finally block
    must flush the trace so the file exists and a subsequent profiled run
    in the same process doesn't hit 'trace already started'."""
    import glob

    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=15,
                   profile=True, eval_interval=50, mesh_shape="data:1")
    res = run_training(cfg)
    assert res["iter_num"] >= 15
    traces = glob.glob(
        str(tmp_path / "out" / "profile" / "**" / "*.xplane.pb"),
        recursive=True,
    )
    assert traces, "early-exit run left the profile trace dangling"
    # and the profiler is actually released: a new window can start
    cfg2 = make_cfg(char_dataset["dir"], tmp_path / "out2", max_iters=12,
                    profile=True, eval_interval=50, mesh_shape="data:1")
    run_training(cfg2)


def test_sigterm_graceful_save_and_resume(char_dataset, tmp_path):
    """Preemption handling: SIGTERM makes the loop finish the in-flight
    iteration, save a checkpoint, and exit 0; the run then resumes."""
    out = str(tmp_path / "out")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    proc = subprocess.Popen(
        _tpu_cli(char_dataset, out, max_iters=500, eval_interval=1000),
        cwd=REPO, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.time() + 300
        for line in proc.stdout:
            if "iter 3" in line:
                break
            assert time.time() < deadline, "trainer never reached iter 3"
        proc.send_signal(signal.SIGTERM)
        rest = proc.stdout.read()
        proc.wait(timeout=120)
        assert proc.returncode == 0, rest
        assert "SIGTERM: saving checkpoint" in rest
        assert os.path.exists(os.path.join(out, "ckpt.pt"))
    finally:
        if proc.poll() is None:
            proc.kill()

    r = subprocess.run(
        _tpu_cli(char_dataset, out, max_iters=8, init_from="resume"),
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "resuming" in r.stdout


def test_async_checkpoint_capacity_guard(tmp_path, monkeypatch, capsys):
    """When free HBM can't hold the snapshot copy, save_checkpoint_async
    must degrade to a synchronous save (completed handle, file on disk,
    a visible warning) instead of OOMing mid-run (VERDICT r3 weak #5).
    With ample headroom the async path still engages."""
    from flax import nnx

    from avenir_tpu.checkpoint import io as ckpt_io
    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer

    model_args = dict(n_layer=1, n_head=1, n_embd=16, block_size=8,
                      bias=False, vocab_size=64, dropout=0.0)
    model = GPT(GPTConfig(**model_args, attn_impl="xla"), rngs=nnx.Rngs(0))
    params = nnx.split(model, nnx.Param)[1]
    tx, _ = make_optimizer(params, learning_rate=1e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=2, lr_decay_iters=8, min_lr=1e-4)
    opt_state = tx.init(params)
    kw = dict(
        hyper={"lr": 1e-3, "betas": (0.9, 0.95), "eps": 1e-8,
               "weight_decay": 0.1},
        model_args=model_args,
        iter_num=1, best_val_loss=1.0, config={}, model_family="gpt",
    )

    # 1 KB free: the ~32 KB snapshot cannot fit -> sync fallback
    monkeypatch.setattr(ckpt_io, "_device_free_bytes", lambda: 1024)
    h = ckpt_io.save_checkpoint_async(str(tmp_path), params=params,
                                      opt_state=opt_state, **kw)
    assert h.done()  # completed synchronously, before return
    h.join()
    assert os.path.exists(tmp_path / "ckpt.pt")
    assert "falling back to a synchronous save" in capsys.readouterr().out

    # ample headroom -> genuine background save
    monkeypatch.setattr(ckpt_io, "_device_free_bytes", lambda: 10 ** 12)
    h2 = ckpt_io.save_checkpoint_async(str(tmp_path), params=params,
                                       opt_state=opt_state, **kw)
    h2.join()
    assert "falling back" not in capsys.readouterr().out
    assert os.path.exists(tmp_path / "ckpt.pt")


def test_async_checkpoint_resumable(char_dataset, tmp_path):
    """--async_checkpoint=True: saves land from the background thread
    (atomic rename — no .tmp left behind), and the result resumes."""
    out = str(tmp_path / "out")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    r = subprocess.run(
        _tpu_cli(char_dataset, out, max_iters=7, async_checkpoint=True),
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "(async)" in r.stdout
    assert os.path.exists(os.path.join(out, "ckpt.pt"))
    assert not os.path.exists(os.path.join(out, "ckpt.pt.part"))

    r2 = subprocess.run(
        _tpu_cli(char_dataset, out, max_iters=10, init_from="resume",
                 async_checkpoint=True),
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "iter 10" in r2.stdout
