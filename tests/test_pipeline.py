"""Pipeline parallelism (parallel/pipeline.py) on the 8 fake CPU devices.

BEYOND the blueprint: SURVEY.md §2c marks PP as a parity non-goal; it is
implemented anyway as the last missing first-class strategy. The GPipe
schedule must be pure layout like every other axis: loss trajectories on
pipe meshes — alone, composed with data/fsdp/tensor, with remat, with
the pallas kernel, and for Llama — equal the single-device run;
save/resume works with the layer axis sharded.
"""

import dataclasses

import numpy as np
import pytest

import jax

from avenir_tpu.parallel.mesh import make_mesh


def _run(char_dataset, out, mesh_shape, **over):
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    cfg = make_cfg(char_dataset["dir"], out, max_iters=4,
                   gradient_accumulation_steps=4, eval_interval=50,
                   scan_layers=True, mesh_shape=mesh_shape, **over)
    return run_training(cfg)


def _losses(res):
    return np.array([l for _, l in res["loss_history"]])


@pytest.mark.parametrize("mesh_shape,over", [
    ("pipe:2", {}),
    ("pipe:4", dict(n_layer=4)),
    ("data:2,pipe:2", {}),
    ("fsdp:2,pipe:2", {}),
    ("pipe:2,tensor:2", {}),
    ("pipe:2", dict(remat=True)),
    # pallas inside the pipeline's partial-manual region, auto
    # microbatching: M=4 leaves per-micro batch 1, indivisible over
    # data:2, so the wrap stands down and the kernel runs direct under
    # GSPMD (correctness via replication — the graceful fallback)
    ("data:2,pipe:2", dict(attn_impl="pallas")),
    # pallas NESTED inside the pipe region (r5): M=2 keeps the per-micro
    # batch divisible over data:2, so the wrap engages naming only the
    # free axes (partition.free_axis_names) — zero attention all-gathers,
    # exact grads (the HLO + grad assertions live in test_pallas_spmd)
    ("data:2,pipe:2", dict(attn_impl="pallas", pipeline_microbatches=2)),
    # llama: GQA blocks through the pipeline (activation-only carry)
    ("pipe:2", dict(model_type="llama", n_head=4, n_kv_head=2,
                    ffn_hidden=64)),
    # context parallelism UNDER pipeline (r5, VERDICT r4 missing #2):
    # ring/ulysses shard_maps nest inside the pipe region via the same
    # free-axes rule; the sequence axis stays sharded across the region
    ("pipe:2,context:2", {}),
    ("pipe:2,context:2", dict(context_parallel_impl="ulysses")),
    ("data:2,pipe:2,context:2", dict(pipeline_microbatches=2)),
    ("pipe:2,context:2", dict(model_type="llama", n_head=4, n_kv_head=2,
                              ffn_hidden=64)),
], ids=["pipe2", "pipe4", "dp-pp", "fsdp-pp", "pp-tp", "pipe2-remat",
        "dp-pp-pallas", "dp-pp-pallas-nested", "pipe2-llama",
        "pp-cp-ring", "pp-cp-ulysses", "dp-pp-cp", "pp-cp-llama-ring"])
def test_pipeline_trajectory_matches_single_device(char_dataset, tmp_path,
                                                   mesh_shape, over):
    ref = _run(char_dataset, tmp_path / "o1", "data:1", **over)
    got = _run(char_dataset, tmp_path / "o2", mesh_shape, **over)
    np.testing.assert_allclose(_losses(got), _losses(ref),
                               atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("mesh_shape,over", [
    ("pipe:2", {}),
    ("data:2,pipe:2", dict(attn_impl="pallas", pipeline_microbatches=2)),
    ("pipe:2,context:2", {}),
    ("pipe:2", dict(model_type="llama", n_head=4, n_kv_head=2,
                    ffn_hidden=64)),
    ("pipe:2", dict(remat=True)),
    ("pipe:4", dict(n_layer=4)),
], ids=["pipe2", "dp-pp-pallas-nested", "pp-cp-ring", "llama", "remat",
        "pipe4"])
def test_remat_schedule_trajectory_matches_single_device(
        char_dataset, tmp_path, mesh_shape, over):
    """pipeline_schedule='remat' (reverse-tick stage-input stash,
    parallel/pipeline._remat_schedule) must reproduce the single-device
    trajectory across the composition matrix exactly like the gpipe
    schedule — including the nested pallas wrap, ring CP under the
    pipeline, llama GQA, and per-layer remat stacked on top. Tolerance
    covers the recompute's fp reassociation (~1e-6 per step)."""
    ref = _run(char_dataset, tmp_path / "o1", "data:1", **over)
    got = _run(char_dataset, tmp_path / "o2", mesh_shape,
               pipeline_schedule="remat", **over)
    np.testing.assert_allclose(_losses(got), _losses(ref),
                               atol=3e-4, rtol=3e-4)


def test_remat_schedule_memory_win():
    """The point of the remat schedule: compiled fwd+bwd temp bytes must
    be well under the gpipe schedule's (the stash is O(M) stage inputs
    instead of O((M+p)·L/p) per-layer residual sets — measured 3.4-6.9×
    on the harness, BASELINE.md 'Pipeline cost table')."""
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig

    def temp_bytes(schedule):
        cfg = GPTConfig(block_size=128, vocab_size=256, n_layer=8,
                        n_head=4, n_embd=128, dropout=0.0, bias=False,
                        attn_impl="xla", scan_layers=True,
                        pipeline_microbatches=4,
                        pipeline_schedule=schedule)
        mesh = make_mesh("pipe:2")
        with jax.set_mesh(mesh):
            graphdef, params = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)),
                                         nnx.Param)
            x = jax.random.randint(jax.random.key(1), (8, 128), 0, 256)
            y = jax.random.randint(jax.random.key(2), (8, 128), 0, 256)

            def loss_fn(params):
                _, loss = nnx.merge(graphdef, params)(x, targets=y)
                return loss

            comp = jax.jit(jax.grad(loss_fn)).lower(params).compile()
            return comp.memory_analysis().temp_size_in_bytes

    g, r = temp_bytes("gpipe"), temp_bytes("remat")
    assert r < 0.5 * g, (g, r)


def test_remat_schedule_rejects_moe_aux(char_dataset, tmp_path):
    """MoE aux stats are gpipe-only under the pipeline (the remat
    backward does not thread the aux cotangent through the recompute) —
    fail loud, never silently drop router statistics."""
    with pytest.raises(AssertionError, match="gpipe"):
        _run(char_dataset, tmp_path / "o", "pipe:2",
             pipeline_schedule="remat", model_type="mixtral", n_head=4,
             n_kv_head=2, n_embd=32, ffn_hidden=64, n_experts=4,
             n_experts_per_tok=2, capacity_factor=2.0,
             router_aux_loss_coef=0.02)


@pytest.mark.parametrize("mesh_shape", ["pipe:2", "expert:2,pipe:2"])
def test_pipeline_mixtral_trajectory(char_dataset, tmp_path, mesh_shape):
    """MoE through the pipeline: router stats ride the aux carry
    (batch-mean contract — mean of equal micro-means == full mean), and
    EP composes (the dispatch/combine constraints live in the GSPMD
    domain inside the pipe region). capacity E/K admits every token, so
    the trajectory matches the unpipelined run exactly; with drops the
    per-MICRObatch capacity would legitimately differ (documented)."""
    kw = dict(model_type="mixtral", n_kv_head=2, n_head=4, n_embd=32,
              ffn_hidden=64, n_experts=4, n_experts_per_tok=2,
              capacity_factor=2.0, router_aux_loss_coef=0.02)
    ref = _run(char_dataset, tmp_path / "o1", "data:1", **kw)
    got = _run(char_dataset, tmp_path / "o2", mesh_shape, **kw)
    np.testing.assert_allclose(_losses(got), _losses(ref),
                               atol=3e-4, rtol=3e-4)


def test_pipeline_mixtral_drop_semantics_match_microbatched_oracle():
    """VERDICT r4 missing #5: pipeline.py documents that WITH capacity
    drops the pipelined MoE matches 'a micro-batched run' — one
    pipelined forward over batch B with M micros computes exactly the
    mean of M independent forwards over B/M (capacity C derived from the
    MICRO token count, so different tokens drop than in a full-batch
    forward). Pin that on loss AND parameter gradients at
    capacity_factor=0.5 (heavy drops), with router_aux_loss_coef=0: the
    aux loss is NONLINEAR in the batch-aggregated router stats
    (sum_e f_e*p_e), so the pipelined aux — computed once from the
    aggregated stats, which are drop-independent ('on intent') and
    exactly equal the full-batch means — intentionally does NOT equal
    the mean of per-micro aux losses (the docstring states both
    halves of the contract)."""
    import jax.numpy as jnp
    from flax import nnx

    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

    cfg = MixtralConfig(block_size=32, vocab_size=96, n_layer=2, n_head=4,
                        n_kv_head=2, n_embd=32, ffn_hidden=64, n_experts=4,
                        n_experts_per_tok=2, capacity_factor=0.5,
                        router_aux_loss_coef=0.0, scan_layers=True)
    B, M = 8, 2
    x = jax.random.randint(jax.random.key(1), (B, 32), 0, 96)
    y = jax.random.randint(jax.random.key(2), (B, 32), 0, 96)

    def build():
        return nnx.split(Mixtral(cfg, rngs=nnx.Rngs(0)), nnx.Param)

    def loss_fn(params, graphdef, xb, yb):
        _, loss = nnx.merge(graphdef, params)(xb, targets=yb)
        return loss

    # oracle: M independent micro-batched forwards, averaged (no mesh).
    # Micro m is the STRIDED rows b % M == m: the pipeline reshapes
    # (B,)->(B//M, M) keeping dim 0 (the data/fsdp-sharded dim) intact,
    # so consecutive rows stay on their device and the micro axis is
    # the fast-varying one (pipeline.py body)
    graphdef, params = build()
    oracle_l, oracle_g = 0.0, None
    for m in range(M):
        l, g = jax.jit(jax.value_and_grad(loss_fn))(
            params, graphdef, x[m::M], y[m::M])
        oracle_l += float(l) / M
        g = jax.tree.map(lambda a: a / M, g)
        oracle_g = g if oracle_g is None else jax.tree.map(
            jnp.add, oracle_g, g)

    # pipelined: same params, pipe:2 mesh, M=2 micros, one forward
    mesh = make_mesh("pipe:2")
    with jax.set_mesh(mesh):
        cfg_p = dataclasses.replace(cfg, pipeline_microbatches=M)
        graphdef_p, params_p = nnx.split(
            Mixtral(cfg_p, rngs=nnx.Rngs(0)), nnx.Param)
        l_p, g_p = jax.jit(jax.value_and_grad(loss_fn))(
            params_p, graphdef_p, x, y)

    np.testing.assert_allclose(float(l_p), oracle_l, atol=2e-5, rtol=2e-5)
    for (ka, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(
            nnx.to_pure_dict(jax.tree.map(np.asarray, g_p)))[0],
        jax.tree_util.tree_flatten_with_path(
            nnx.to_pure_dict(jax.tree.map(np.asarray, oracle_g)))[0],
    ):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4,
                                   err_msg=jax.tree_util.keystr(ka))


def test_pipeline_bf16_smoke(char_dataset, tmp_path):
    """bf16 activations through the pipeline (the ladder configs' compute
    dtype). XLA:CPU CHECK-crashes on bf16 collectives inside a
    partial-manual region (upstream; repro in parallel/pipeline.py), so
    off-TPU the stage hops transport fp32 — exact for bf16 payloads.
    This smoke pins that the bf16 path compiles and trains at all on the
    harness; fp32 trajectory equivalence is pinned above."""
    res = _run(char_dataset, tmp_path / "o", "data:2,pipe:2",
               dtype="bfloat16")
    losses = _losses(res)
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0] + 0.05  # training, not diverging


def test_pipeline_requires_scan_layers(char_dataset, tmp_path):
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    cfg = make_cfg(char_dataset["dir"], tmp_path / "o", max_iters=2,
                   mesh_shape="pipe:2", scan_layers=False)
    with pytest.raises(AssertionError, match="scan_layers"):
        run_training(cfg)


def test_context_wrap_refuses_manual_context_axis():
    """The one composition that stays impossible: sequence-parallel
    attention cannot nest when 'context' ITSELF is already Manual (there
    is no free axis left to rotate over). Fail loud, not silent."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from avenir_tpu.parallel.ring_attention import context_shard_map

    mesh = make_mesh("context:2")
    jax.set_mesh(mesh)

    def outer(x):
        context_shard_map(lambda q, k, v: q, axis_name="context")(
            x, x, x
        )
        return x

    f = jax.shard_map(outer, in_specs=P(None, "context", None, None),
                      out_specs=P(None, "context", None, None),
                      check_vma=False, axis_names={"context"})
    with pytest.raises(AssertionError, match="already Manual"):
        jax.jit(f)(jnp.ones((2, 4, 2, 2)))


def test_pipeline_layer_axis_is_sharded(char_dataset):
    """The stacked layer params (and their Adam moments) really shard
    their leading axis over 'pipe' — PP's memory win, not just its
    schedule."""
    from flax import nnx

    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import init_sharded_opt_state, setup_state
    from avenir_tpu.train.optimizer import make_optimizer

    mesh = make_mesh("pipe:2")
    cfg = make_cfg("x", "y", mesh_shape="pipe:2", scan_layers=True)
    model_args = dict(n_layer=2, n_head=2, n_embd=32, block_size=32,
                      bias=False, vocab_size=64, dropout=0.0)
    st = setup_state(cfg, mesh, model_args, verbose=False)
    params = jax.jit(
        lambda: nnx.split(st["ctor"](0), nnx.Param)[1],
        out_shardings=st["shard_tree"],
    )()
    stacked = [(p, v) for p, v in params.flat_state()
               if any(str(s).endswith("_scan") for s in p)]
    assert stacked, "no scan-stacked params found"
    for path, leaf in stacked:
        arr = leaf.get_value()
        assert arr.sharding.spec[0] == "pipe", (path, arr.sharding.spec)
        assert arr.addressable_shards[0].data.shape[0] * 2 == arr.shape[0]
    tx, _ = make_optimizer(params, learning_rate=1e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=2, lr_decay_iters=8, min_lr=1e-4)
    opt_state = init_sharded_opt_state(tx, params, st["shard_tree"])
    from avenir_tpu.checkpoint.io import _find_adam_state

    mu = _find_adam_state(opt_state).mu
    for path, leaf in mu.flat_state():
        if any(str(s).endswith("_scan") for s in path):
            arr = leaf.get_value() if hasattr(leaf, "get_value") else leaf
            assert arr.sharding.spec[0] == "pipe", (path, arr.sharding.spec)


def test_pipeline_save_resume(char_dataset, tmp_path):
    """Checkpoint round-trip with the layer axis pipe-sharded: save at
    iter 4, resume to 8, loss keeps falling."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    out = tmp_path / "out"
    common = dict(gradient_accumulation_steps=4, eval_interval=4,
                  scan_layers=True, mesh_shape="pipe:2")
    res = run_training(make_cfg(char_dataset["dir"], out, max_iters=4,
                                **common))
    res2 = run_training(make_cfg(char_dataset["dir"], out, max_iters=8,
                                 init_from="resume", **common))
    assert res2["iter_num"] >= 8
    l1 = _losses(res)
    l2 = _losses(res2)
    # the resumed run must CONTINUE the first trajectory, not restart: a
    # silent reinit would log its first loss back near the scratch start
    assert abs(l2[0] - l1[-1]) < 0.05, (l1, l2)
    assert l2[-1] < l1[-1], (l1, l2)


@pytest.mark.parametrize("mesh_shape,over", [
    ("pipe:2", {}),
    ("pipe:4", dict(n_layer=4)),
    ("data:2,pipe:2", {}),
    ("pipe:2,tensor:2", {}),
    ("pipe:2,context:2", {}),
    ("pipe:2", dict(model_type="llama", n_head=4, n_kv_head=2,
                    ffn_hidden=64)),
], ids=["pipe2", "pipe4", "dp-pp", "pp-tp", "pp-cp-ring", "llama"])
def test_1f1b_trajectory_matches_gpipe(char_dataset, tmp_path, mesh_shape,
                                       over):
    """pipeline_schedule='1f1b' (true interleaved 1F1B, loss tail inside
    the pipeline region — parallel/pipeline.pipeline_1f1b_loss) must
    reproduce the gpipe trajectory across the composition matrix: pure
    pipe at both depths, pipe×{data,tensor,context}, and llama GQA.
    gpipe itself is pinned against the single-device run above, so this
    chains 1f1b to single-device too; the pipe2 case also re-checks the
    single-device reference directly (the eval cadence exercises the
    forward-only no-grad staircase as well). Tolerance covers the fp
    reassociation of per-micro loss sums + the blocked in-region tail
    vs the reference full-logits tail."""
    gp = _run(char_dataset, tmp_path / "o1", mesh_shape, **over)
    got = _run(char_dataset, tmp_path / "o2", mesh_shape,
               pipeline_schedule="1f1b", **over)
    np.testing.assert_allclose(_losses(got), _losses(gp),
                               atol=3e-4, rtol=3e-4)
    if mesh_shape == "pipe:2" and not over:
        ref = _run(char_dataset, tmp_path / "o3", "data:1", **over)
        np.testing.assert_allclose(_losses(got), _losses(ref),
                                   atol=3e-4, rtol=3e-4)


@pytest.mark.parametrize("pipe", [2, 4])
def test_1f1b_grad_parity_vs_single_device(pipe):
    """Direct loss AND parameter-gradient parity of one 1f1b step vs the
    unpipelined single-device model — every leaf (incl. the tied wte,
    whose grad is the in-region head dw PLUS the embedding-lookup
    contribution, and ln_f/wpe through the region's dx) within fp
    tolerance. pipe:4 uses M=2p=8 > W=7, so the stage-input ring
    actually wraps."""
    import jax.numpy as jnp
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(block_size=32, vocab_size=96, n_layer=4, n_head=4,
                    n_embd=32, dropout=0.0, bias=True, attn_impl="xla",
                    scan_layers=True)
    B = 16
    x = jax.random.randint(jax.random.key(1), (B, 32), 0, 96)
    y = jax.random.randint(jax.random.key(2), (B, 32), 0, 96)

    def loss_fn(params, graphdef):
        return nnx.merge(graphdef, params)(x, targets=y)[1]

    gd0, p0 = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)
    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_fn),
                           static_argnums=1)(p0, gd0)
    with jax.set_mesh(make_mesh(f"pipe:{pipe}")):
        cfg_p = dataclasses.replace(cfg, pipeline_schedule="1f1b",
                                    pipeline_microbatches=2 * pipe)
        gdp, pp_ = nnx.split(GPT(cfg_p, rngs=nnx.Rngs(0)), nnx.Param)
        l_p, g_p = jax.jit(jax.value_and_grad(loss_fn),
                           static_argnums=1)(pp_, gdp)
    np.testing.assert_allclose(float(l_p), float(l_ref), atol=3e-5,
                               rtol=3e-5)
    fa, fb = dict(g_p.flat_state()), dict(g_ref.flat_state())
    for k in fb:
        a = np.asarray(fa[k].get_value())
        b = np.asarray(fb[k].get_value())
        err = np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-8)
        assert err < 3e-4, (k, err)


def test_1f1b_mixtral_matches_microbatched_oracle():
    """MoE under 1f1b: router stats ride the ppermute payload per-micro
    and the aux loss is computed PER MICRO at the last stage — so with
    coef != 0 the pipelined loss/grads equal the micro-batched oracle
    (mean of M independent strided B/M forwards, aux INCLUDED), which is
    intentionally NOT gpipe's aggregate-stats aux (nonlinear in the
    stats; both contracts documented in pipeline_1f1b_loss). Capacity
    2.0 admits every token so the CE part is drop-free."""
    import jax.numpy as jnp
    from flax import nnx

    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

    cfg = MixtralConfig(block_size=32, vocab_size=96, n_layer=2, n_head=4,
                        n_kv_head=2, n_embd=32, ffn_hidden=64, n_experts=4,
                        n_experts_per_tok=2, capacity_factor=2.0,
                        router_aux_loss_coef=0.02, scan_layers=True)
    B, M = 8, 2
    x = jax.random.randint(jax.random.key(1), (B, 32), 0, 96)
    y = jax.random.randint(jax.random.key(2), (B, 32), 0, 96)

    def loss_fn(params, graphdef, xb, yb):
        return nnx.merge(graphdef, params)(xb, targets=yb)[1]

    gd0, p0 = nnx.split(Mixtral(cfg, rngs=nnx.Rngs(0)), nnx.Param)
    oracle_l, oracle_g = 0.0, None
    for m in range(M):
        l, g = jax.jit(jax.value_and_grad(loss_fn), static_argnums=1)(
            p0, gd0, x[m::M], y[m::M])
        oracle_l += float(l) / M
        g = jax.tree.map(lambda a: a / M, g)
        oracle_g = g if oracle_g is None else jax.tree.map(
            jnp.add, oracle_g, g)
    with jax.set_mesh(make_mesh("pipe:2")):
        cfg_p = dataclasses.replace(cfg, pipeline_microbatches=M,
                                    pipeline_schedule="1f1b")
        gdp, pp_ = nnx.split(Mixtral(cfg_p, rngs=nnx.Rngs(0)), nnx.Param)
        l_p, g_p = jax.jit(jax.value_and_grad(loss_fn),
                           static_argnums=1)(pp_, gdp, x, y)
    np.testing.assert_allclose(float(l_p), oracle_l, atol=3e-5, rtol=3e-5)
    fa = dict(g_p.flat_state())
    fb = dict(oracle_g.flat_state())
    for k in fb:
        a = np.asarray(fa[k].get_value())
        b = np.asarray(fb[k].get_value())
        err = np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-8)
        assert err < 3e-4, (k, err)


def test_1f1b_save_resume_across_schedules(char_dataset, tmp_path):
    """Mid-run schedule swap: the checkpoint is schedule-agnostic (same
    params, moments, rng stream), so a run saved under gpipe resumes
    under 1f1b and CONTINUES the trajectory — and vice versa."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    common = dict(gradient_accumulation_steps=4, eval_interval=4,
                  scan_layers=True, mesh_shape="pipe:2")
    for first, second in (("gpipe", "1f1b"), ("1f1b", "gpipe")):
        out = tmp_path / f"{first}-{second}"
        res = run_training(make_cfg(char_dataset["dir"], out, max_iters=4,
                                    pipeline_schedule=first, **common))
        res2 = run_training(make_cfg(char_dataset["dir"], out, max_iters=8,
                                     init_from="resume",
                                     pipeline_schedule=second, **common))
        l1, l2 = _losses(res), _losses(res2)
        assert res2["iter_num"] >= 8
        assert abs(l2[0] - l1[-1]) < 0.05, (first, second, l1, l2)
        assert l2[-1] < l1[-1], (first, second, l1, l2)


def test_1f1b_steady_state_never_retraces():
    """Compile pin: after the first jitted grad step, further same-shape
    steps add ZERO new traces of the 1f1b region (ledger idiom shared
    with ops/fused_ce and infer/decode), and the no-grad eval path uses
    the forward-only body without touching the interleaved one."""
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.parallel import pipeline as pp

    cfg = GPTConfig(block_size=32, vocab_size=96, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=False, attn_impl="xla",
                    scan_layers=True, pipeline_schedule="1f1b")
    x = jax.random.randint(jax.random.key(1), (4, 32), 0, 96)
    y = jax.random.randint(jax.random.key(2), (4, 32), 0, 96)
    with jax.set_mesh(make_mesh("pipe:2")):
        graphdef, params = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)

        def loss_fn(params):
            return nnx.merge(graphdef, params)(x, targets=y)[1]

        step = jax.jit(jax.value_and_grad(loss_fn))
        ev = jax.jit(loss_fn)
        step(params)
        ev(params)
        n_inter = pp.trace_count("1f1b")
        for _ in range(3):
            step(params)
            ev(params)
        assert pp.trace_count("1f1b") == n_inter, (
            "1f1b region retraced on same-shape steps"
        )


@pytest.mark.slow
def test_1f1b_memory_bounded_in_M():
    """The acceptance frontier (BASELINE.md "Pipeline cost table"): at a
    realistic-vocab tail, 1f1b's compiled temp bytes at M=2p are BELOW
    remat's at M=2p, and at M=4p they FALL further (M-independent stash,
    Bm-sized tail slab) while gpipe at M=4p stays several times larger.
    Measured margins are ~1.6x/5.9x (tools/pipeline_bench.py); asserted
    with slack for XLA scheduling noise."""
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig

    def temp_bytes(schedule, M):
        cfg = GPTConfig(block_size=128, vocab_size=8192, n_layer=8,
                        n_head=4, n_embd=128, dropout=0.0, bias=False,
                        attn_impl="xla", scan_layers=True,
                        loss_impl="" if schedule == "1f1b" else "blocked",
                        pipeline_microbatches=M,
                        pipeline_schedule=schedule)
        with jax.set_mesh(make_mesh("pipe:2")):
            graphdef, params = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)),
                                         nnx.Param)
            x = jax.random.randint(jax.random.key(1), (16, 128), 0, 8192)
            y = jax.random.randint(jax.random.key(2), (16, 128), 0, 8192)

            def loss_fn(params):
                return nnx.merge(graphdef, params)(x, targets=y)[1]

            comp = jax.jit(jax.grad(loss_fn)).lower(params).compile()
            return comp.memory_analysis().temp_size_in_bytes

    r_2p = temp_bytes("remat", 4)
    f_2p = temp_bytes("1f1b", 4)
    f_4p = temp_bytes("1f1b", 8)
    g_4p = temp_bytes("gpipe", 8)
    assert f_2p <= r_2p, (f_2p, r_2p)          # acceptance: <= remat @ 2p
    assert f_4p < 0.8 * f_2p, (f_4p, f_2p)     # memory FALLS with M
    assert f_4p < 0.33 * g_4p, (f_4p, g_4p)    # gpipe @ 4p can't follow


def test_1f1b_multichunk_tail_on_mixed_mesh():
    """The in-region blocked tail with MULTIPLE T-chunks (nc > 1) on a
    mesh with a live non-pipe axis: on the legacy harness the chunk
    loop must unroll instead of lax.scan (fused_ce.blocked_ce_terms,
    same partial-auto partitioner gate as pipeline._use_psum_hop — a
    scan there CHECK-aborts the whole process), and loss+grads must
    still match the unpipelined single-device run. Every other 1f1b
    case happens to land on nc == 1, which is why this config gets its
    own pin."""
    import jax.numpy as jnp
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(block_size=64, vocab_size=96, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=False, attn_impl="xla",
                    scan_layers=True, loss_chunk=16)  # nc = 4 chunks
    B = 8
    x = jax.random.randint(jax.random.key(1), (B, 64), 0, 96)
    y = jax.random.randint(jax.random.key(2), (B, 64), 0, 96)

    def loss_fn(params, graphdef):
        return nnx.merge(graphdef, params)(x, targets=y)[1]

    gd0, p0 = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)
    l_ref, g_ref = jax.jit(jax.value_and_grad(loss_fn),
                           static_argnums=1)(p0, gd0)
    with jax.set_mesh(make_mesh("data:2,pipe:2")):
        cfg_p = dataclasses.replace(cfg, pipeline_schedule="1f1b",
                                    pipeline_microbatches=4)
        gdp, pp_ = nnx.split(GPT(cfg_p, rngs=nnx.Rngs(0)), nnx.Param)
        l_p, g_p = jax.jit(jax.value_and_grad(loss_fn),
                           static_argnums=1)(pp_, gdp)
    np.testing.assert_allclose(float(l_p), float(l_ref), atol=3e-5,
                               rtol=3e-5)
    fa, fb = dict(g_p.flat_state()), dict(g_ref.flat_state())
    for k in fb:
        a = np.asarray(fa[k].get_value())
        b = np.asarray(fb[k].get_value())
        err = np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-8)
        assert err < 3e-4, (k, err)
