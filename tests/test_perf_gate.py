"""Perf-regression gate (ISSUE 14 tentpole, part 3): the tier-1 smoke
— the committed BENCH trajectory must pass the ledger, and a synthetic
20%-regressed copy of ANY gated artifact must fail with a message
naming the metric and the band. Pure JSON reads; no model runs."""

import copy
import json
import os

import pytest

from tools.perf_gate import (
    LEDGER,
    check_entry,
    dig,
    load_json,
    main,
    run_check,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def ledger():
    return load_json(LEDGER)


def test_ledger_shape(ledger):
    """Every entry carries the committed contract: artifact, path,
    headline value, direction, and a noise band WITH its source (a
    band someone cannot audit is a band someone will fudge)."""
    assert ledger["kind"] == "perf_ledger"
    assert ledger["benches"], "empty ledger gates nothing"
    for name, e in ledger["benches"].items():
        assert {"artifact", "path", "value", "noise_frac",
                "noise_source"} <= set(e), name
        assert 0.0 < e["noise_frac"] < 0.8, name
        assert os.path.exists(os.path.join(REPO, e["artifact"])), (
            f"{name}: ledger names a missing artifact")


def test_gate_passes_on_committed_trajectory():
    """The HEAD invariant: the committed artifacts satisfy their own
    ledger. A PR that regresses a committed bench artifact (or deletes
    one) fails tier-1 here."""
    assert main(["--check"]) == 0


def test_ledger_values_match_artifacts(ledger):
    for name, e in ledger["benches"].items():
        art = load_json(os.path.join(REPO, e["artifact"]))
        assert dig(art, e["path"]) == pytest.approx(e["value"]), (
            f"{name}: ledger value drifted from the artifact — rerun "
            "tools/perf_gate.py --update")


def _set_path(obj, path, value):
    for k in path[:-1]:
        obj = obj[k]
    obj[path[-1]] = value


def test_gate_fails_on_synthetic_20pct_regression(ledger, tmp_path,
                                                  capsys):
    """EVERY gated metric: a regressed copy exits non-zero and the
    failure message names the metric and the band. Every perf-
    trajectory entry must catch a plain 20% regression (bands < 20%);
    only wall-clock-paced stats may carry wider bands — the anomaly-
    lead fraction, the affinity missed-reuse fraction and the ISSUE 18
    spec-compose speedups, whose semantic floors are pinned separately
    below — and each is regressed past its OWN band instead."""
    wide = {n for n, e in ledger["benches"].items()
            if e["noise_frac"] >= 0.2}
    assert wide <= {"anomaly_wedge_lead_frac",
                    "missed_reuse_frac_affinity",
                    "spec_compose_decode_speedup",
                    "spec_ngram_decode_speedup",
                    "rollout_rollback_latency_s"}, (
        "a perf-trajectory band grew past 20% — a silent 20% "
        "regression would ship clean again")
    # The spec rows' wide bands (shared-host scheduling noise on the
    # wall-paced decode spans) must never let the gate FLOOR sink
    # below the ISSUE 18 acceptance bars: a rerun that loses the
    # composed speedup outright has to fail regardless of band width.
    for name, bar in (("spec_compose_decode_speedup", 1.5),
                      ("spec_ngram_decode_speedup", 1.3)):
        e = ledger["benches"].get(name)
        if e is not None:
            floor = e["value"] * (1.0 - e["noise_frac"])
            assert floor >= bar, (
                f"{name} band floor sank below the {bar}x acceptance "
                "bar — the compose win is no longer gated")
    # The affinity row's wide band must never let the KV CDN quietly
    # decay back to affinity-blind scattering: its gate CEILING stays
    # materially below the blind baseline row's committed headline.
    aff = ledger["benches"].get("missed_reuse_frac_affinity")
    if aff is not None:
        blind = ledger["benches"]["missed_reuse_frac"]["value"]
        ceiling = aff["value"] * (1.0 + aff["noise_frac"])
        assert ceiling < 0.6 * blind, (
            "missed_reuse_frac_affinity band ceiling crept toward the "
            "affinity-blind baseline — the CDN win is no longer gated")
    # The rollback-latency row's wide band (wall-paced drill on a
    # shared host) must never let the gate CEILING creep toward the
    # bench's own 20s rollback_bound_s: a rollback that stops arriving
    # in seconds has to fail regardless of host weather.
    rb = ledger["benches"].get("rollout_rollback_latency_s")
    if rb is not None:
        ceiling = rb["value"] * (1.0 + rb["noise_frac"])
        assert ceiling < 8.0, (
            "rollout_rollback_latency_s band ceiling crept toward the "
            "bench's 20s bound — slow rollbacks would ship clean")
    for name, e in ledger["benches"].items():
        art = copy.deepcopy(load_json(os.path.join(REPO,
                                                   e["artifact"])))
        frac = max(0.2, e["noise_frac"] + 0.05)
        worse = (e["value"] * (1.0 - frac)
                 if e.get("direction", "higher") == "higher"
                 else e["value"] * (1.0 + frac))
        _set_path(art, e["path"], worse)
        cand = tmp_path / f"regressed_{name}.json"
        cand.write_text(json.dumps(art))
        rc = main([f"--candidate={cand}", f"--bench={name}"])
        out = capsys.readouterr().out
        assert rc == 1, f"{name}: 20% regression passed the gate\n{out}"
        assert "REGRESSION" in out and name in out and "band" in out, (
            f"{name}: failure must name the metric and the band\n{out}")


def test_gate_fails_loud_on_missing_artifact(ledger, tmp_path, capsys):
    name = next(iter(ledger["benches"]))
    rc = main([f"--candidate={tmp_path / 'nope.json'}",
               f"--bench={name}"])
    assert rc == 2  # deleting a bench must not pass the gate
    assert "cannot read" in capsys.readouterr().out


def test_gate_refuses_false_ok_flag(ledger, tmp_path, capsys):
    """An artifact whose own acceptance flag went false fails the gate
    even when the headline metric is inside the band."""
    e = ledger["benches"]["paged_vs_slab_concurrency_ratio"]
    art = copy.deepcopy(load_json(os.path.join(REPO, e["artifact"])))
    art["ok"] = False
    cand = tmp_path / "not_ok.json"
    cand.write_text(json.dumps(art))
    rc = main([f"--candidate={cand}",
               "--bench=paged_vs_slab_concurrency_ratio"])
    assert rc == 1
    assert "ok flag" in capsys.readouterr().out


def test_check_entry_directions():
    higher = {"value": 100.0, "noise_frac": 0.1, "direction": "higher"}
    assert check_entry("m", higher, 95.0)[0]
    assert not check_entry("m", higher, 85.0)[0]
    lower = {"value": 100.0, "noise_frac": 0.1, "direction": "lower"}
    assert check_entry("m", lower, 105.0)[0]
    assert not check_entry("m", lower, 115.0)[0]


def test_unknown_bench_is_an_error(ledger, capsys):
    assert run_check(ledger, only="no_such_bench") == 2
