"""DataLoader unit tests (SURVEY.md §2b T8): shapes, target alignment,
sharded placement on the batch axes, determinism, and the per-process
disjoint-stream contract."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

from avenir_tpu.data.loader import DataLoader
from avenir_tpu.parallel.mesh import make_mesh
from avenir_tpu.parallel.partition import batch_pspec


@pytest.fixture()
def loader_dir(char_dataset):
    return char_dataset["dir"]


def test_shapes_and_target_alignment(loader_dir):
    dl = DataLoader(loader_dir, block_size=32, batch_size=4, grad_accum=3,
                    seed=0)
    x, y = dl.get_batch("train")
    assert x.shape == (3, 4, 32) and y.shape == (3, 4, 32)
    # y is x shifted by one (next-token targets), from the same crop
    np.testing.assert_array_equal(np.asarray(x)[..., 1:],
                                  np.asarray(y)[..., :-1])


def test_flat_eval_batches(loader_dir):
    dl = DataLoader(loader_dir, block_size=16, batch_size=8, grad_accum=1,
                    seed=1, flat=True)
    x, y = dl.get_batch("val")
    assert x.shape == (8, 16)
    with pytest.raises(AssertionError):
        DataLoader(loader_dir, block_size=16, batch_size=8, grad_accum=2,
                   flat=True)


def test_sharded_batch_placement(loader_dir):
    mesh = make_mesh("data:4,fsdp:2")
    sh = NamedSharding(mesh, batch_pspec())
    dl = DataLoader(loader_dir, block_size=32, batch_size=8, grad_accum=2,
                    sharding=sh, seed=0)
    x, _ = dl.get_batch("train")
    assert x.shape == (2, 8, 32)
    assert x.sharding == sh
    # batch dim sharded over data*fsdp = 8 devices -> 1 sequence per shard
    shard_shapes = {s.data.shape for s in x.addressable_shards}
    assert shard_shapes == {(2, 1, 32)}


def test_deterministic_given_seed(loader_dir):
    a = DataLoader(loader_dir, block_size=32, batch_size=4, seed=7)
    b = DataLoader(loader_dir, block_size=32, batch_size=4, seed=7)
    xa, _ = a.get_batch("train")
    xb, _ = b.get_batch("train")
    np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
    c = DataLoader(loader_dir, block_size=32, batch_size=4, seed=8)
    xc, _ = c.get_batch("train")
    assert not np.array_equal(np.asarray(xa), np.asarray(xc))


def test_prefetch_preserves_stream_order(loader_dir):
    """The background prefetch (ISSUE 3 satellite) must not change the
    CONSUMED batch stream: a windowed run with prefetch engaged yields
    bit-identical windows to a fresh unprefetched loader of the same
    seed, across varying window lengths and a trailing get_batch."""
    from avenir_tpu.data import loader as loader_mod

    a = DataLoader(loader_dir, block_size=16, batch_size=2, grad_accum=2,
                   seed=11)
    b = DataLoader(loader_dir, block_size=16, batch_size=2, grad_accum=2,
                   seed=11)
    ks = [3, 3, 1, 4, 2]  # varying K: leftovers + top-ups both exercised
    got = [a.get_batch_window("train", k) for k in ks]
    # the reference stream: sample synchronously with prefetch disabled
    ref = []
    for k in ks:
        chunks = [b._sample_local("train") for _ in range(k)]
        xs, ys = zip(*chunks)
        ref.append((np.stack(xs), np.stack(ys)))
    for (xa, ya), (xr, yr) in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(xa), xr)
        np.testing.assert_array_equal(np.asarray(ya), yr)
    # a trailing single batch consumes the staged buffer in order too
    xa, _ = a.get_batch("train")
    xr, _ = b._sample_local("train")
    np.testing.assert_array_equal(np.asarray(xa), xr)


def test_prefetch_counts_hits(loader_dir):
    """Steady-state windows (same K) are served from the staged buffer
    and counted in data_prefetch_hit."""
    from avenir_tpu.obs import get_registry, reset_registry

    reset_registry()
    dl = DataLoader(loader_dir, block_size=16, batch_size=2, seed=5)
    for _ in range(4):
        dl.get_batch_window("train", 2)
    dl._join_prefetch()  # deterministic read of the counters
    c = get_registry().snapshot()["counters"]
    # first window is a cold miss; the 3 steady-state ones hit
    assert c.get("data_prefetch_hit", 0) == 3
    reset_registry()


def test_prefetch_thread_error_fails_loud(loader_dir, monkeypatch):
    """A failure on the prefetch thread has already advanced the rng for
    its partial draws — the next consume must raise, not silently
    continue on a desynced stream."""
    dl = DataLoader(loader_dir, block_size=16, batch_size=2, seed=5)
    dl.get_batch_window("train", 2)
    dl._join_prefetch()  # drain the healthy first prefetch
    monkeypatch.setattr(
        dl, "_sample_local",
        lambda split: (_ for _ in ()).throw(OSError("disk gone")))
    dl._spawn_prefetch("train", 2)
    with pytest.raises(RuntimeError, match="prefetch failed"):
        dl.get_batch_window("train", 2)


def test_prefetch_split_mixing_fails_loud(loader_dir):
    """One prefetching DataLoader serves one split: consuming a different
    split than the staged one would silently desync the rng stream, so
    it must raise instead."""
    dl = DataLoader(loader_dir, block_size=16, batch_size=2, seed=5)
    dl.get_batch_window("train", 2)  # engages prefetch for 'train'
    with pytest.raises(AssertionError, match="single split"):
        dl.get_batch_window("val", 2)


def test_process_streams_disjoint(loader_dir, monkeypatch):
    """Each process seeds its own rng stream (seed + 1000*index): simulate
    two processes and check their crop sequences differ (the multi-host
    disjoint-sampling contract; true multi-process covered by the
    2-process smoke test)."""
    dl0 = DataLoader(loader_dir, block_size=32, batch_size=4, seed=3)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    dl1 = DataLoader(loader_dir, block_size=32, batch_size=4, seed=3)
    x0, _ = dl0.get_batch("train")
    x1, _ = dl1.get_batch("train")
    assert not np.array_equal(np.asarray(x0), np.asarray(x1))
