"""DataLoader unit tests (SURVEY.md §2b T8): shapes, target alignment,
sharded placement on the batch axes, determinism, and the per-process
disjoint-stream contract."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

from avenir_tpu.data.loader import DataLoader
from avenir_tpu.parallel.mesh import make_mesh
from avenir_tpu.parallel.partition import batch_pspec


@pytest.fixture()
def loader_dir(char_dataset):
    return char_dataset["dir"]


def test_shapes_and_target_alignment(loader_dir):
    dl = DataLoader(loader_dir, block_size=32, batch_size=4, grad_accum=3,
                    seed=0)
    x, y = dl.get_batch("train")
    assert x.shape == (3, 4, 32) and y.shape == (3, 4, 32)
    # y is x shifted by one (next-token targets), from the same crop
    np.testing.assert_array_equal(np.asarray(x)[..., 1:],
                                  np.asarray(y)[..., :-1])


def test_flat_eval_batches(loader_dir):
    dl = DataLoader(loader_dir, block_size=16, batch_size=8, grad_accum=1,
                    seed=1, flat=True)
    x, y = dl.get_batch("val")
    assert x.shape == (8, 16)
    with pytest.raises(AssertionError):
        DataLoader(loader_dir, block_size=16, batch_size=8, grad_accum=2,
                   flat=True)


def test_sharded_batch_placement(loader_dir):
    mesh = make_mesh("data:4,fsdp:2")
    sh = NamedSharding(mesh, batch_pspec())
    dl = DataLoader(loader_dir, block_size=32, batch_size=8, grad_accum=2,
                    sharding=sh, seed=0)
    x, _ = dl.get_batch("train")
    assert x.shape == (2, 8, 32)
    assert x.sharding == sh
    # batch dim sharded over data*fsdp = 8 devices -> 1 sequence per shard
    shard_shapes = {s.data.shape for s in x.addressable_shards}
    assert shard_shapes == {(2, 1, 32)}


def test_deterministic_given_seed(loader_dir):
    a = DataLoader(loader_dir, block_size=32, batch_size=4, seed=7)
    b = DataLoader(loader_dir, block_size=32, batch_size=4, seed=7)
    xa, _ = a.get_batch("train")
    xb, _ = b.get_batch("train")
    np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
    c = DataLoader(loader_dir, block_size=32, batch_size=4, seed=8)
    xc, _ = c.get_batch("train")
    assert not np.array_equal(np.asarray(xa), np.asarray(xc))


def test_process_streams_disjoint(loader_dir, monkeypatch):
    """Each process seeds its own rng stream (seed + 1000*index): simulate
    two processes and check their crop sequences differ (the multi-host
    disjoint-sampling contract; true multi-process covered by the
    2-process smoke test)."""
    dl0 = DataLoader(loader_dir, block_size=32, batch_size=4, seed=3)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    dl1 = DataLoader(loader_dir, block_size=32, batch_size=4, seed=3)
    x0, _ = dl0.get_batch("train")
    x1, _ = dl1.get_batch("train")
    assert not np.array_equal(np.asarray(x0), np.asarray(x1))
