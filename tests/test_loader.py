"""DataLoader unit tests (SURVEY.md §2b T8): shapes, target alignment,
sharded placement on the batch axes, determinism, and the per-process
disjoint-stream contract."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

from avenir_tpu.data.loader import DataLoader
from avenir_tpu.parallel.mesh import make_mesh
from avenir_tpu.parallel.partition import batch_pspec


@pytest.fixture()
def loader_dir(char_dataset):
    return char_dataset["dir"]


def test_shapes_and_target_alignment(loader_dir):
    dl = DataLoader(loader_dir, block_size=32, batch_size=4, grad_accum=3,
                    seed=0)
    x, y = dl.get_batch("train")
    assert x.shape == (3, 4, 32) and y.shape == (3, 4, 32)
    # y is x shifted by one (next-token targets), from the same crop
    np.testing.assert_array_equal(np.asarray(x)[..., 1:],
                                  np.asarray(y)[..., :-1])


def test_flat_eval_batches(loader_dir):
    dl = DataLoader(loader_dir, block_size=16, batch_size=8, grad_accum=1,
                    seed=1, flat=True)
    x, y = dl.get_batch("val")
    assert x.shape == (8, 16)
    with pytest.raises(AssertionError):
        DataLoader(loader_dir, block_size=16, batch_size=8, grad_accum=2,
                   flat=True)


def test_sharded_batch_placement(loader_dir):
    mesh = make_mesh("data:4,fsdp:2")
    sh = NamedSharding(mesh, batch_pspec())
    dl = DataLoader(loader_dir, block_size=32, batch_size=8, grad_accum=2,
                    sharding=sh, seed=0)
    x, _ = dl.get_batch("train")
    assert x.shape == (2, 8, 32)
    assert x.sharding == sh
    # batch dim sharded over data*fsdp = 8 devices -> 1 sequence per shard
    shard_shapes = {s.data.shape for s in x.addressable_shards}
    assert shard_shapes == {(2, 1, 32)}


def test_deterministic_given_seed(loader_dir):
    a = DataLoader(loader_dir, block_size=32, batch_size=4, seed=7)
    b = DataLoader(loader_dir, block_size=32, batch_size=4, seed=7)
    xa, _ = a.get_batch("train")
    xb, _ = b.get_batch("train")
    np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))
    c = DataLoader(loader_dir, block_size=32, batch_size=4, seed=8)
    xc, _ = c.get_batch("train")
    assert not np.array_equal(np.asarray(xa), np.asarray(xc))


def test_prefetch_preserves_stream_order(loader_dir):
    """The background prefetch (ISSUE 3 satellite) must not change the
    CONSUMED batch stream: a windowed run with prefetch engaged yields
    bit-identical windows to a fresh unprefetched loader of the same
    seed, across varying window lengths and a trailing get_batch."""
    from avenir_tpu.data import loader as loader_mod

    a = DataLoader(loader_dir, block_size=16, batch_size=2, grad_accum=2,
                   seed=11)
    b = DataLoader(loader_dir, block_size=16, batch_size=2, grad_accum=2,
                   seed=11)
    ks = [3, 3, 1, 4, 2]  # varying K: leftovers + top-ups both exercised
    got = [a.get_batch_window("train", k) for k in ks]
    # the reference stream: sample synchronously with prefetch disabled
    ref = []
    for k in ks:
        chunks = [b._sample_local("train") for _ in range(k)]
        xs, ys = zip(*chunks)
        ref.append((np.stack(xs), np.stack(ys)))
    for (xa, ya), (xr, yr) in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(xa), xr)
        np.testing.assert_array_equal(np.asarray(ya), yr)
    # a trailing single batch consumes the staged buffer in order too
    xa, _ = a.get_batch("train")
    xr, _ = b._sample_local("train")
    np.testing.assert_array_equal(np.asarray(xa), xr)


def test_prefetch_counts_hits(loader_dir):
    """Steady-state windows (same K) are served from the staged buffer
    and counted in data_prefetch_hit."""
    from avenir_tpu.obs import get_registry, reset_registry

    reset_registry()
    dl = DataLoader(loader_dir, block_size=16, batch_size=2, seed=5)
    for _ in range(4):
        dl.get_batch_window("train", 2)
    dl._join_prefetch()  # deterministic read of the counters
    c = get_registry().snapshot()["counters"]
    # first window is a cold miss; the 3 steady-state ones hit
    assert c.get("data_prefetch_hit", 0) == 3
    reset_registry()


def test_prefetch_thread_error_fails_loud(loader_dir, monkeypatch):
    """A failure on the prefetch thread has already advanced the rng for
    its partial draws — the next consume must raise, not silently
    continue on a desynced stream."""
    dl = DataLoader(loader_dir, block_size=16, batch_size=2, seed=5)
    dl.get_batch_window("train", 2)
    dl._join_prefetch()  # drain the healthy first prefetch
    monkeypatch.setattr(
        dl, "_sample_local",
        lambda split: (_ for _ in ()).throw(OSError("disk gone")))
    dl._spawn_prefetch("train", 2)
    with pytest.raises(RuntimeError, match="prefetch failed"):
        dl.get_batch_window("train", 2)


def test_prefetch_split_mixing_fails_loud(loader_dir):
    """One prefetching DataLoader serves one split: consuming a different
    split than the staged one would silently desync the rng stream, so
    it must raise instead."""
    dl = DataLoader(loader_dir, block_size=16, batch_size=2, seed=5)
    dl.get_batch_window("train", 2)  # engages prefetch for 'train'
    with pytest.raises(AssertionError, match="single split"):
        dl.get_batch_window("val", 2)


def test_process_streams_disjoint(loader_dir, monkeypatch):
    """Each process seeds its own rng stream (seed + 1000*index): simulate
    two processes and check their crop sequences differ (the multi-host
    disjoint-sampling contract; true multi-process covered by the
    2-process smoke test)."""
    dl0 = DataLoader(loader_dir, block_size=32, batch_size=4, seed=3)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    dl1 = DataLoader(loader_dir, block_size=32, batch_size=4, seed=3)
    x0, _ = dl0.get_batch("train")
    x1, _ = dl1.get_batch("train")
    assert not np.array_equal(np.asarray(x0), np.asarray(x1))


# ---------------------------------------------------------------------------
# v2 uint32 wire format (ISSUE 15 satellite: the >65536-vocab path)
# ---------------------------------------------------------------------------


@pytest.fixture()
def u32_dir(tmp_path):
    """A v2 uint32 corpus with token ids past the uint16 cap (the
    Llama-3 128k-vocab shape), train + val."""
    from avenir_tpu.data.loader import write_token_file

    rng = np.random.default_rng(0)
    vocab = 128_256
    for split, n in (("train", 20_000), ("val", 4_000)):
        toks = rng.integers(0, vocab, n).astype(np.uint32)
        # guarantee ids beyond the uint16 wire in both splits
        toks[::7] = rng.integers(70_000, vocab, toks[::7].shape)
        dt = write_token_file(str(tmp_path / f"{split}.bin"), toks,
                              vocab_size=vocab)
        assert dt == np.dtype(np.uint32)
    return str(tmp_path)


def test_write_token_file_picks_narrowest_form(tmp_path):
    from avenir_tpu.data.loader import (
        WIRE_HEADER_BYTES,
        read_wire_format,
        write_token_file,
    )

    small = tmp_path / "small.bin"
    assert write_token_file(str(small), np.arange(100), 50_000) \
        == np.dtype(np.uint16)
    # legacy form is headerless raw uint16 — bit-compatible with every
    # existing .bin consumer
    dt, off = read_wire_format(str(small))
    assert (dt, off) == (np.dtype(np.uint16), 0)
    np.testing.assert_array_equal(
        np.fromfile(small, dtype=np.uint16), np.arange(100))

    big = tmp_path / "big.bin"
    assert write_token_file(str(big), np.arange(100), 128_256) \
        == np.dtype(np.uint32)
    dt, off = read_wire_format(str(big))
    assert (dt, off) == (np.dtype(np.uint32), WIRE_HEADER_BYTES)
    np.testing.assert_array_equal(
        np.fromfile(big, dtype=np.uint32, offset=off), np.arange(100))


def test_u32_loader_serves_wide_ids(u32_dir):
    """The 128k vocab passes the construction gate against a v2 file,
    batches arrive uint32, and ids beyond 65535 survive the wire."""
    dl = DataLoader(u32_dir, block_size=32, batch_size=4, grad_accum=2,
                    seed=0, vocab_size=128_256)
    x, y = dl.get_batch("train")
    assert x.shape == (2, 4, 32)
    assert np.asarray(x).dtype == np.uint32
    assert int(np.asarray(x).max()) > 65_535  # really past the old wire
    np.testing.assert_array_equal(np.asarray(x)[..., 1:],
                                  np.asarray(y)[..., :-1])


def test_legacy_wire_still_rejects_oversized_vocab(loader_dir):
    """The uint16 fail-loud is unchanged for legacy files — only the v2
    uint32 form opens the gate."""
    with pytest.raises(AssertionError, match="wire"):
        DataLoader(loader_dir, block_size=32, batch_size=4,
                   vocab_size=128_256)


def test_u32_fast_forward_bit_identical_resume(u32_dir):
    """The deterministic-resume contract over the NEW form: a fresh
    loader fast-forwarded past k consumed draws reproduces the
    uninterrupted loader's stream BIT-identically (the bound-aware rng
    replay must use the v2 header-offset bound, not the raw file
    size)."""
    a = DataLoader(u32_dir, block_size=16, batch_size=2, grad_accum=2,
                   seed=9, vocab_size=128_256)
    stream = [a.get_batch("train") for _ in range(4)]
    b = DataLoader(u32_dir, block_size=16, batch_size=2, grad_accum=2,
                   seed=9, vocab_size=128_256)
    b.fast_forward([("train", 3)])
    xb, yb = b.get_batch("train")
    np.testing.assert_array_equal(np.asarray(stream[3][0]), np.asarray(xb))
    np.testing.assert_array_equal(np.asarray(stream[3][1]), np.asarray(yb))


def test_u32_windowed_prefetch_stream_order(u32_dir):
    """The windowed/prefetch path over the v2 form stays bit-identical
    to fresh single draws (the uint16 twin of
    test_prefetch_preserves_stream_order)."""
    a = DataLoader(u32_dir, block_size=16, batch_size=2, seed=4)
    xw, _ = a.get_batch_window("train", 3)
    b = DataLoader(u32_dir, block_size=16, batch_size=2, seed=4)
    singles = np.stack([np.asarray(b.get_batch("train")[0])
                        for _ in range(3)])
    np.testing.assert_array_equal(np.asarray(xw), singles)


def test_unknown_header_fails_loud(tmp_path):
    from avenir_tpu.data.loader import WIRE_MAGIC, read_wire_format

    p = tmp_path / "bad.bin"
    p.write_bytes(WIRE_MAGIC + bytes([9, 2, 0, 0]) + b"\x00" * 64)
    with pytest.raises(AssertionError, match="version"):
        read_wire_format(str(p))
    p2 = tmp_path / "bad2.bin"
    p2.write_bytes(WIRE_MAGIC + bytes([2, 9, 0, 0]) + b"\x00" * 64)
    with pytest.raises(AssertionError, match="dtype code"):
        read_wire_format(str(p2))


def test_u32_batch_widens_on_device_like_uint16(u32_dir):
    """train/step._i32 widens whatever the wire delivers: a uint32
    batch through the jitted cast lands int32 with values intact."""
    import jax.numpy as jnp

    dl = DataLoader(u32_dir, block_size=16, batch_size=2, grad_accum=1,
                    seed=1, vocab_size=128_256)
    x, _ = dl.get_batch("train")
    widened = jax.jit(lambda t: t.astype(jnp.int32))(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(widened),
                                  np.asarray(x).astype(np.int32))
