"""Ring attention (context parallelism) tests on the 8 fake CPU devices:
op-level equivalence to dense causal attention, and a full GPT training
trajectory on a context-sharded mesh matching the single-device run."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from avenir_tpu.ops.attention import causal_attention_reference
from avenir_tpu.parallel.mesh import make_mesh
from avenir_tpu.parallel.ring_attention import ring_causal_attention


@pytest.mark.parametrize("ctx", [2, 4, 8])
@pytest.mark.parametrize("h_kv", [2, 1])  # MHA and GQA (H=2, group=2)
def test_ring_matches_dense(ctx, h_kv):
    """Forward AND grads vs the dense oracle — the kv stripes rotate at
    H_kv heads (never expanded); the oracle sees explicitly repeated KV,
    and its dk/dv fold back over the group for comparison."""
    mesh = make_mesh(f"context:{ctx}")
    jax.set_mesh(mesh)
    B, T, H, D = 2, 64, 2, 16
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, h_kv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, h_kv, D), jnp.float32)

    def loss_ring(q, k, v):
        o = ring_causal_attention(q, k, v, mesh=mesh)
        return jnp.sum(o * o), o

    (dq, dk, dv), out = jax.jit(
        jax.grad(loss_ring, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)

    rep = lambda x: jnp.repeat(x, H // h_kv, axis=2)

    def loss_ref(q, k, v):
        o = causal_attention_reference(q, rep(k), rep(v))
        return jnp.sum(o * o), o

    (dq_r, dk_r, dv_r), ref = jax.jit(
        jax.grad(loss_ref, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_r),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_r),
                               atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_r),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("model_kw", [
    dict(),  # GPT (MHA)
    # Llama GQA: the kv stripes ride the ring at H_kv=2 heads while the
    # model runs 4 q heads (the round-4 GQA-native rotation, end to end)
    dict(model_type="llama", n_head=4, n_kv_head=2, ffn_hidden=64),
], ids=["gpt", "llama-gqa"])
def test_ring_trajectory_matches_single_device(char_dataset, tmp_path,
                                               model_kw):
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    common = dict(max_iters=5, gradient_accumulation_steps=4,
                  eval_interval=50, block_size=32, **model_kw)
    ref = run_training(
        make_cfg(char_dataset["dir"], tmp_path / "o1", mesh_shape="data:1",
                 **common)
    )
    got = run_training(
        make_cfg(char_dataset["dir"], tmp_path / "o2",
                 mesh_shape="data:2,context:4", **common)
    )
    ref_l = np.array([l for _, l in ref["loss_history"]])
    got_l = np.array([l for _, l in got["loss_history"]])
    np.testing.assert_allclose(got_l, ref_l, atol=3e-4, rtol=3e-4)


def test_ring_blockwise_padding_interior_stripe():
    """T/c not a multiple of the streaming block: the pad's phantom
    positions alias the NEXT stripe's global positions on interior
    stripes and must stay masked (review r5 — unmasked zero keys
    inflated the softmax denominator by 0.24 max-abs). Exercised by
    shrinking the block so Tk=16 pads to 2 blocks of 12."""
    import avenir_tpu.parallel.ring_attention as ra

    from avenir_tpu.ops.attention import causal_attention_reference

    ctx = 2
    mesh = make_mesh(f"context:{ctx}")
    jax.set_mesh(mesh)
    B, T, H, D = 2, 32, 4, 8
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    # block_k=12: Tk = 16 → nb=2, pad=8 — interior-stripe aliasing
    ring = lambda q, k, v: ra.ring_causal_attention(q, k, v, block_k=12)

    def loss(f, q, k, v):
        return jnp.sum(f(q, k, v) ** 2)

    ref_g = jax.jit(jax.grad(
        lambda q, k, v: loss(causal_attention_reference, q, k, v),
        argnums=(0, 1, 2)))(q, k, v)
    got_g = jax.jit(jax.grad(
        lambda q, k, v: loss(ring, q, k, v),
        argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(got_g, ref_g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)
