"""Ring attention (context parallelism) tests on the 8 fake CPU devices:
op-level equivalence to dense causal attention, and a full GPT training
trajectory on a context-sharded mesh matching the single-device run."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from avenir_tpu.ops.attention import causal_attention_reference
from avenir_tpu.parallel.mesh import make_mesh
from avenir_tpu.parallel.ring_attention import ring_causal_attention


@pytest.mark.parametrize("ctx", [2, 4, 8])
def test_ring_matches_dense(ctx):
    mesh = make_mesh(f"context:{ctx}")
    jax.set_mesh(mesh)
    B, T, H, D = 2, 64, 2, 16
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, H, D), jnp.float32)

    out = jax.jit(
        lambda q, k, v: ring_causal_attention(q, k, v, mesh=mesh)
    )(q, k, v)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_trajectory_matches_single_device(char_dataset, tmp_path):
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    common = dict(max_iters=5, gradient_accumulation_steps=4,
                  eval_interval=50, block_size=32)
    ref = run_training(
        make_cfg(char_dataset["dir"], tmp_path / "o1", mesh_shape="data:1",
                 **common)
    )
    got = run_training(
        make_cfg(char_dataset["dir"], tmp_path / "o2",
                 mesh_shape="data:2,context:4", **common)
    )
    ref_l = np.array([l for _, l in ref["loss_history"]])
    got_l = np.array([l for _, l in got["loss_history"]])
    np.testing.assert_allclose(got_l, ref_l, atol=3e-4, rtol=3e-4)
