"""Streaming (sharded-read/write) checkpoint I/O (SURVEY.md §5 "each host
materializes only its FSDP shard"; VERDICT r1 item 4): peak host memory
during save/restore must be far below the full fp32 tree, while the on-disk
.pt stays byte-compatible with torch in both directions."""

import dataclasses
import os
import tracemalloc

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.checkpoint.io import (
    _find_adam_state,
    load_checkpoint,
    restore_opt_state,
    restore_params,
    save_checkpoint,
)
from avenir_tpu.checkpoint.torch_pt import LazyArray, lazy_unstack, load_pt, save_pt
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.train.optimizer import make_optimizer
from avenir_tpu.train.step import jit_train_step, make_step_fns

# ~8M params (~32MB fp32); big enough that per-tensor streaming is clearly
# distinguishable from whole-tree gathers under tracemalloc
BIGGISH = GPTConfig(block_size=64, vocab_size=2048, n_layer=6, n_head=4,
                    n_embd=256, dropout=0.0, bias=True, attn_impl="xla")

MODEL_ARGS = dict(n_layer=6, n_head=4, n_embd=256, block_size=64, bias=True,
                  vocab_size=2048, dropout=0.0)
HYPER = {"lr": 1e-3, "betas": (0.9, 0.95), "eps": 1e-8, "weight_decay": 0.1}


def _trained_state(cfg=BIGGISH):
    model = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(model, nnx.Param)
    tx, _ = make_optimizer(params, learning_rate=1e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=0, lr_decay_iters=100, min_lr=1e-4)
    opt_state = tx.init(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    step = jit_train_step(step_fn, tx)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 2048, (1, 2, 64)).astype(np.int32))
    y = jnp.asarray(rng.integers(0, 2048, (1, 2, 64)).astype(np.int32))
    params, opt_state, _ = step(params, opt_state, jax.random.key(0), x, y)
    jax.block_until_ready(jax.tree.leaves(params)[0])
    return graphdef, params, opt_state, tx


def _tree_bytes(params):
    return sum(v.get_value().size * 4 for _, v in params.flat_state())


def test_streaming_save_peak_memory(tmp_path):
    graphdef, params, opt_state, _ = _trained_state()
    total = _tree_bytes(params) * 3  # params + mu + nu
    tracemalloc.start()
    save_checkpoint(str(tmp_path), params=params, opt_state=opt_state,
                    hyper=HYPER, model_args=MODEL_ARGS, iter_num=1,
                    best_val_loss=9.9, config={}, model_family="gpt")
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # full-tree gather would hold >= total (~96MB); streaming holds one
    # tensor (largest: wte 2048x256 fp32 = 2MB) + zip buffers
    assert peak < total / 4, (peak, total)
    assert os.path.exists(tmp_path / "ckpt.pt")


def test_streaming_restore_peak_memory(tmp_path):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    graphdef, params, opt_state, tx = _trained_state()
    total = _tree_bytes(params) * 3
    save_checkpoint(str(tmp_path), params=params, opt_state=opt_state,
                    hyper=HYPER, model_args=MODEL_ARGS, iter_num=1,
                    best_val_loss=9.9, config={}, model_family="gpt")

    abs_model = nnx.eval_shape(lambda: GPT(BIGGISH, rngs=nnx.Rngs(0)))
    _, abs_state = nnx.split(abs_model, nnx.Param)
    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    shardings = {p: NamedSharding(mesh, P())
                 for p, _ in abs_state.flat_state()}

    # contrast against the eager path in the SAME process so jit-compile
    # and allocator noise from earlier tests cancels out
    tracemalloc.start()
    ckpt_eager = load_checkpoint(str(tmp_path))
    restore_params(ckpt_eager, abs_state, shardings)
    _, peak_eager = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    del ckpt_eager

    tracemalloc.start()
    ckpt = load_checkpoint(str(tmp_path), lazy=True)
    restored = restore_params(ckpt, abs_state, shardings)
    opt2 = restore_opt_state(ckpt, tx.init(restored), restored, shardings)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    # eager load alone holds >= the full model tree; lazy restore of
    # params AND moments must stay well under the eager params-only peak
    assert peak < peak_eager / 2, (peak, peak_eager, total)

    # and the values are right
    want = {p: np.asarray(v.get_value()) for p, v in params.flat_state()}
    for p, v in restored.flat_state():
        np.testing.assert_allclose(np.asarray(v.get_value()), want[p],
                                   atol=1e-7, err_msg=str(p))
    mu_want = {p: np.asarray(v.get_value())
               for p, v in _find_adam_state(opt_state).mu.flat_state()}
    for p, v in _find_adam_state(opt2).mu.flat_state():
        np.testing.assert_allclose(np.asarray(v.get_value()), mu_want[p],
                                   atol=1e-7, err_msg=str(p))


def test_streamed_pt_matches_eager_pt_and_torch_reads_it(tmp_path):
    """A lazily-streamed .pt must decode identically to the eager one, and
    real torch.load must accept it (cross-backend contract intact)."""
    import torch

    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    lazy = LazyArray(arr.shape, arr.dtype, lambda: arr)
    obj_lazy = {"model": {"w": lazy, "tied": lazy}, "iter_num": 3}
    obj_eager = {"model": {"w": arr, "tied": arr}, "iter_num": 3}
    save_pt(obj_lazy, str(tmp_path / "lazy.pt"))
    save_pt(obj_eager, str(tmp_path / "eager.pt"))

    a = load_pt(str(tmp_path / "lazy.pt"))
    b = load_pt(str(tmp_path / "eager.pt"))
    np.testing.assert_array_equal(a["model"]["w"], b["model"]["w"])

    t = torch.load(str(tmp_path / "lazy.pt"), weights_only=False)
    np.testing.assert_array_equal(t["model"]["w"].numpy(), arr)
    # tied entries share one storage in the streamed file too
    assert t["model"]["w"].data_ptr() == t["model"]["tied"].data_ptr()


def test_lazy_load_roundtrip_matches_eager(tmp_path):
    graphdef, params, opt_state, _ = _trained_state(
        dataclasses.replace(BIGGISH, n_layer=2, n_embd=64, vocab_size=256)
    )
    save_checkpoint(str(tmp_path), params=params, opt_state=opt_state,
                    hyper=HYPER,
                    model_args={**MODEL_ARGS, "n_layer": 2, "n_embd": 64,
                                "vocab_size": 256},
                    iter_num=1, best_val_loss=9.9, config={},
                    model_family="gpt")
    eager = load_checkpoint(str(tmp_path))
    lazy = load_checkpoint(str(tmp_path), lazy=True)
    assert eager["iter_num"] == lazy["iter_num"] == 1
    for k, v in eager["model"].items():
        got = lazy["model"][k]
        assert isinstance(got, LazyArray), k
        np.testing.assert_array_equal(np.asarray(got), v)


def test_lazy_unstack_materializes_base_once():
    calls = []

    def provider():
        calls.append(1)
        return np.arange(12.0).reshape(3, 2, 2)

    base = LazyArray((3, 2, 2), np.float64, provider)
    slices = lazy_unstack(base, 3)
    for i, s in enumerate(slices):
        np.testing.assert_array_equal(
            np.asarray(s), np.arange(12.0).reshape(3, 2, 2)[i]
        )
    assert len(calls) == 1


def test_sharded_async_save_load_roundtrip(tmp_path):
    """Per-host sharded checkpoint (r5): save a trained sharded state on
    a data:2,fsdp:2,tensor:2 mesh from the background writer, reassemble
    it with load_sharded_checkpoint, and restore onto a DIFFERENT mesh —
    params, moments, and count all bit-exact. Single-process here, but
    the code path is the pod one: only addressable replica-0 shards are
    written, no collectives."""
    from avenir_tpu.checkpoint.io import (
        load_sharded_checkpoint,
        restore_opt_state_sharded,
        restore_params_sharded,
        save_checkpoint_sharded_async,
    )
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.parallel.partition import (
        match_partition_rules,
        path_str,
        rules_for_model,
        sanitize_specs,
    )

    mesh = make_mesh("data:2,fsdp:2,tensor:2")
    jax.set_mesh(mesh)
    graphdef, params, opt_state, tx = _trained_state()
    paths = [p for p, _ in params.flat_state()]
    specs = match_partition_rules(rules_for_model("gpt"), paths)
    shapes = {p: tuple(v.get_value().shape) for p, v in params.flat_state()}
    specs = sanitize_specs(specs, shapes, mesh)
    shardings = {p: jax.sharding.NamedSharding(mesh, specs[p])
                 for p in paths}
    params = nnx.State.from_flat_path({
        p: v.replace(jax.device_put(v.get_value(), shardings[p]))
        for p, v in params.flat_state()
    })
    handle = save_checkpoint_sharded_async(
        str(tmp_path), params=params, opt_state=opt_state, hyper=HYPER,
        model_args=MODEL_ARGS, iter_num=7, best_val_loss=1.5, config={},
        model_family="gpt")
    handle.join()
    assert os.path.exists(tmp_path / "ckpt-shard-00000.pkl")

    sh = load_sharded_checkpoint(str(tmp_path))
    assert sh is not None and sh["iter_num"] == 7
    for p, v in params.flat_state():
        np.testing.assert_array_equal(
            sh["params"][path_str(p)], np.asarray(v.get_value()),
            err_msg=path_str(p))

    # restore onto a different mesh layout
    mesh2 = make_mesh("data:2,tensor:2")
    jax.set_mesh(mesh2)
    specs2 = sanitize_specs(match_partition_rules(rules_for_model("gpt"),
                                                  paths), shapes, mesh2)
    shardings2 = {p: jax.sharding.NamedSharding(mesh2, specs2[p])
                  for p in paths}
    abs_state = nnx.eval_shape(
        lambda: nnx.split(GPT(BIGGISH, rngs=nnx.Rngs(0)), nnx.Param)[1]
    )
    got = restore_params_sharded(sh["params"], abs_state, shardings2)
    for (p, a), (_, b) in zip(got.flat_state(), params.flat_state()):
        np.testing.assert_array_equal(np.asarray(a.get_value()),
                                      np.asarray(b.get_value()),
                                      err_msg=path_str(p))
    opt2 = tx.init(got)
    opt2 = restore_opt_state_sharded(sh, opt2, got, shardings2)
    a1, a2 = _find_adam_state(opt_state), _find_adam_state(opt2)
    assert int(np.asarray(a2.count)) == int(np.asarray(a1.count))
    for (p, m1), (_, m2) in zip(a1.mu.flat_state(), a2.mu.flat_state()):
        np.testing.assert_array_equal(np.asarray(m1.get_value()),
                                      np.asarray(m2.get_value()),
                                      err_msg=path_str(p))


def test_sharded_load_rejects_torn_set(tmp_path):
    """A torn sharded set (crash mid-save: files from different
    iterations, or fewer files than process_count) must be rejected so
    resume falls back to ckpt.pt instead of loading mixed state."""
    import pickle

    from avenir_tpu.checkpoint.io import load_sharded_checkpoint

    base = {"format": "avenir_sharded_v1", "process_count": 2,
            "best_val_loss": 1.0, "count": 3, "hyper": HYPER,
            "model_args": MODEL_ARGS, "config": {}, "model_family": "gpt"}
    body = {"params": {}, "mu": {}, "nu": {}}

    def write(i, header):
        with open(tmp_path / f"ckpt-shard-{i:05d}.pkl", "wb") as f:
            pickle.dump(header, f)
            pickle.dump(body, f)

    write(0, {**base, "process_index": 0, "iter_num": 5})
    # missing second file → incomplete
    assert load_sharded_checkpoint(str(tmp_path)) is None
    # second file from a DIFFERENT save → torn
    write(1, {**base, "process_index": 1, "iter_num": 4})
    assert load_sharded_checkpoint(str(tmp_path)) is None
    # a foreign/unknown-schema pickle must fall back, not crash
    write(1, {"something": "else"})
    assert load_sharded_checkpoint(str(tmp_path)) is None
    # matching iterations → accepted, headers readable without bodies
    write(1, {**base, "process_index": 1, "iter_num": 5})
    assert load_sharded_checkpoint(str(tmp_path))["iter_num"] == 5
    meta = load_sharded_checkpoint(str(tmp_path), meta_only=True)
    assert meta["iter_num"] == 5 and "params" not in meta


def test_local_shard_ranges_covers_every_addressable_index():
    """`local_shard_ranges` must return, per tensor, exactly the index
    boxes this process's devices hold under the given shardings — the
    input the locality-aware restore intersects shard-file headers
    against. Single-process on the 8-fake-device harness means every
    device is addressable, so the union of boxes must tile each FULL
    global shape (and replicated tensors must yield the one full box)."""
    from avenir_tpu.checkpoint.io import local_shard_ranges
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.parallel.partition import (
        match_partition_rules,
        path_str,
        rules_for_model,
        sanitize_specs,
    )

    mesh = make_mesh("data:2,fsdp:2,tensor:2")
    abs_state = nnx.eval_shape(
        lambda: nnx.split(GPT(BIGGISH, rngs=nnx.Rngs(0)), nnx.Param)[1]
    )
    paths = [p for p, _ in abs_state.flat_state()]
    shapes = {p: tuple(v.get_value().shape)
              for p, v in abs_state.flat_state()}
    specs = sanitize_specs(
        match_partition_rules(rules_for_model("gpt"), paths), shapes, mesh)
    shardings = {p: jax.sharding.NamedSharding(mesh, specs[p])
                 for p in paths}
    ranges = local_shard_ranges(abs_state, shardings)
    assert set(ranges) == {path_str(p) for p in paths}
    n_sharded = 0
    for p in paths:
        shape = shapes[p]
        boxes = ranges[path_str(p)]
        assert boxes, path_str(p)
        for box in boxes:
            assert len(box) == len(shape)
            assert all(0 <= a < b <= d for (a, b), d in zip(box, shape)), (
                path_str(p), box, shape)
        covered = np.zeros(shape, bool)
        for box in boxes:
            covered[tuple(slice(a, b) for a, b in box)] = True
        assert covered.all(), (path_str(p), boxes)
        if len(boxes) > 1:
            n_sharded += 1
    assert n_sharded > 0  # the mesh really shards something


def test_sharded_restore_locality_skips_nonlocal_files(tmp_path):
    """Locality-aware sharded restore (advisor r5): given `local_ranges`,
    load_sharded_checkpoint must open ONLY the shard files whose header
    index ranges intersect them. File 0 here holds rows 0:2 of 'w' and
    has NO body record at all — if the filter ever opens it, the body
    unpickle raises EOFError — while file 1 holds rows 2:4 plus the
    replica-0-owned replicated 'g'. A process addressing only rows 2:4
    must restore from file 1 alone; ranges matching NO file (a config
    mismatch) must fail loud instead of returning unfilled garbage."""
    import pickle

    from avenir_tpu.checkpoint.io import load_sharded_checkpoint

    base = {"format": "avenir_sharded_v1", "process_count": 2,
            "iter_num": 5, "best_val_loss": 1.0, "count": 3,
            "hyper": HYPER, "model_args": MODEL_ARGS, "config": {},
            "model_family": "gpt"}
    w = np.arange(8.0, dtype=np.float32).reshape(4, 2)
    g = np.array([3.0, 4.0], np.float32)

    hdr0 = {**base, "process_index": 0,
            "index_ranges": {"params": {"w": [((0, 2), (0, 2))]},
                             "mu": {}, "nu": {}}}
    with open(tmp_path / "ckpt-shard-00000.pkl", "wb") as f:
        pickle.dump(hdr0, f)  # header only: a body read would EOFError

    body1 = {"params": {
        "w": {"global_shape": (4, 2), "dtype": "float32",
              "shards": [(((2, 4), (0, 2)), w[2:4])]},
        "g": {"global_shape": (2,), "dtype": "float32",
              "shards": [(((0, 2),), g)]},
    }, "mu": {}, "nu": {}}
    hdr1 = {**base, "process_index": 1,
            "index_ranges": {"params": {"w": [((2, 4), (0, 2))],
                                        "g": [((0, 2),)]},
                             "mu": {}, "nu": {}}}
    with open(tmp_path / "ckpt-shard-00001.pkl", "wb") as f:
        pickle.dump(hdr1, f)
        pickle.dump(body1, f)

    local = {"w": [((2, 4), (0, 2))], "g": [((0, 2),)]}
    out = load_sharded_checkpoint(str(tmp_path), local_ranges=local)
    assert out is not None and out["iter_num"] == 5
    np.testing.assert_array_equal(out["params"]["w"][2:4], w[2:4])
    np.testing.assert_array_equal(out["params"]["g"], g)

    # an unfiltered read opens file 0 and must crash on its missing
    # body — guarding that the filter was the reason the load above lived
    with pytest.raises(EOFError):
        load_sharded_checkpoint(str(tmp_path))

    # ranges intersecting NOTHING (e.g. a different model config's
    # shapes): every file skipped -> fail loud, not empty arrays
    with pytest.raises(AssertionError):
        load_sharded_checkpoint(
            str(tmp_path),
            local_ranges={"w": [((4, 8), (0, 2))], "g": [((2, 4),)]})
