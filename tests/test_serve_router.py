"""Fleet-router tests (avenir_tpu/serve/router.py + replica.py, ISSUE
6): failover keeps every accepted request (completed output bit-
identical to one-shot generation — the engine parity contract extended
across replica deaths), admission control sheds instead of growing,
priority fair-share bounds interactive TTFT under batch overload, and
the health state machine behaves. All CPU tier-1.

Budget notes: one module-scoped GPT + one-shot references; every prompt
lands in the SAME power-of-2 bucket (len <= 8) so each engine pays one
prefill compile + one decode compile, and requests use one MAX_NEW so
references share a scan-length compile."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.serve import DEAD, DRAINING, HEALTHY, Router
from avenir_tpu.utils.faults import FaultInjector, set_injector

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
MAX_NEW = 5


def _mk_requests(model, rng, n):
    """n requests (prompt len 3..8 — ONE bucket) with their one-shot
    reference streams; explicit rng keys pin the parity oracle."""
    reqs = []
    for i in range(n):
        t0 = int(rng.integers(3, 9))
        prompt = [int(t) for t in rng.integers(0, 64, (t0,))]
        key = jax.random.key(5000 + i)
        y = np.asarray(generate_cached(
            model, key, jnp.asarray(prompt, jnp.int32)[None], MAX_NEW,
            temperature=1.0, top_k=8))[0]
        reqs.append((dict(prompt=prompt, max_new_tokens=MAX_NEW,
                          temperature=1.0, top_k=8, rng=key),
                     [int(t) for t in y]))
    return reqs


@pytest.fixture(scope="module")
def fix():
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    return model, _mk_requests(model, np.random.default_rng(3), 6)


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _submit_all(router, reqs, **extra):
    """Submit every request; returns {router rid: reference tokens}."""
    return {router.submit(**kw, **extra): ref for kw, ref in reqs}


def _assert_parity(done, refs):
    for f in done:
        assert f.tokens == refs[f.req_id], (
            f"request {f.req_id} diverged:\n ref {refs[f.req_id]}\n "
            f"got {f.tokens}")
        assert f.finish_reason == "length"


def test_router_parity_across_replicas(fix):
    """Multi-replica dispatch preserves the engine parity contract, and
    the fleet actually spreads load (both replicas serve)."""
    model, reqs = fix
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=2, max_seq_len=32,
                    registry=reg, seed=0)
    refs = _submit_all(router, reqs)
    done = router.drain()
    assert len(done) == len(reqs)
    _assert_parity(done, refs)
    assert {f.replica for f in done} == {0, 1}
    snap = reg.snapshot()
    assert snap["counters"]["serve_requests"] == len(reqs)
    assert snap["gauges"]["replica_healthy"] == 2
    assert snap["gauges"]["router_queue_depth"] == 0


def test_router_failover_bit_parity_step_fault(fix):
    """THE failover oracle (ISSUE 6): a replica killed mid-decode via
    the `serve_step_fail` fault site loses nothing — its in-flight
    requests are requeued, re-prefilled from the original prompt with
    the original rng on the surviving replica, and every completed
    stream is bit-identical to one-shot generation."""
    model, reqs = fix
    reqs = reqs[:4]
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=2, max_seq_len=32,
                    registry=reg, seed=0)
    refs = _submit_all(router, reqs)
    # fires on the 5th consult = replica 0, third router step: both its
    # requests are mid-decode (tokens already emitted, then discarded)
    prev = set_injector(FaultInjector("serve_step_fail:after=4:n=1"))
    try:
        done = router.drain()
    finally:
        set_injector(prev)
    assert len(done) == len(reqs)
    _assert_parity(done, refs)
    dead = [r for r in router.replicas if r.state == DEAD]
    assert len(dead) == 1 and dead[0].replica_id == 0
    moved = [f for f in done if f.failovers > 0]
    assert len(moved) == 2
    assert all(f.replica == 1 for f in moved)
    assert reg.snapshot()["counters"]["serve_failovers"] == 2


def test_router_stall_detected_and_failed_over(fix):
    """A replica that stops heartbeating (the `replica_stall` wedge — no
    exception, just silence) is declared dead by the watchdog-pattern
    threshold and its work moves; an actively-beating replica under the
    same clock is NOT flagged."""
    model, reqs = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0, clock=clk,
                    stall_floor_secs=0.5)
    refs = _submit_all(router, reqs[:2])
    # 2nd consult = replica 1's first step: it wedges holding a request
    prev = set_injector(FaultInjector("replica_stall:after=1:n=1"))
    try:
        done = []
        for _ in range(30):
            done.extend(router.step())
            clk.t += 0.3  # beats refresh per step; the wedge goes stale
            if len(done) == 2:
                break
    finally:
        set_injector(prev)
    assert len(done) == 2
    _assert_parity(done, refs)
    assert router.replicas[1].state == DEAD
    assert router.replicas[0].state == HEALTHY
    assert [f.failovers for f in sorted(done, key=lambda f: f.req_id)] \
        == [0, 1]
    assert reg.snapshot()["gauges"]["replica_healthy"] == 1


def test_router_fair_share_no_starvation(fix):
    """Sustained batch overload cannot starve interactive traffic: with
    4:1 weighted fair-share, interactive TTFT stays within a few ticks
    while a 24-deep batch backlog saturates the fleet — and batch still
    finishes (no reverse starvation)."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=2, max_seq_len=32,
                    registry=reg, seed=0, clock=clk)
    TICK = 0.01
    n_batch, n_inter = 24, 6
    for i in range(n_batch):
        router.submit([1 + i % 8, 2, 3], max_new_tokens=3,
                      priority="batch")
    done, submitted = [], 0
    for step in range(400):
        if submitted < n_inter:
            router.submit([9, 8, 7 - step % 4], max_new_tokens=3,
                          priority="interactive")
            submitted += 1
        done.extend(router.step())
        clk.t += TICK
        if len(done) == n_batch + n_inter:
            break
    assert len(done) == n_batch + n_inter, "fleet failed to drain"
    inter = [f for f in done if f.priority == "interactive"]
    batch = [f for f in done if f.priority == "batch"]
    assert len(inter) == n_inter and len(batch) == n_batch
    inter_ttft = [f.ttft_ms for f in inter]
    batch_ttft = [f.ttft_ms for f in batch]
    # interactive p99 (= max of 6) bounded at a few ticks despite the
    # 24-deep batch flood; the flood itself waits much longer
    assert max(inter_ttft) <= 8 * TICK * 1e3, inter_ttft
    assert max(batch_ttft) >= 3 * max(max(inter_ttft), TICK * 1e3)
    # no reverse starvation: every batch request completed
    assert all(f.finish_reason == "length" for f in batch)


def test_router_admission_control_sheds(fix):
    """Bounded queues: past the per-priority depth limit a submit is
    refused with finish_reason='shed' (serve_shed counter) instead of
    growing memory; interactive limits are independent of batch's."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0, clock=clk,
                    queue_limits={"interactive": 8, "batch": 2})
    rids = [router.submit([1, 2, 3], max_new_tokens=2, priority="batch")
            for _ in range(5)]
    assert router.queue_depth == 2  # limit; the other 3 refused
    iid = router.submit([4, 5, 6], max_new_tokens=2,
                        priority="interactive")  # its own limit: accepted
    done = router.drain()
    shed = {f.req_id for f in done if f.finish_reason == "shed"}
    assert shed == set(rids[2:])
    assert reg.snapshot()["counters"]["serve_shed"] == 3
    served = {f.req_id for f in done if f.finish_reason == "length"}
    assert served == {rids[0], rids[1], iid}


def test_router_sheds_on_projected_wait_vs_deadline(fix):
    """Admission-time SLO check: a deadline the projected queue wait
    already exceeds is shed at the door (never queued, never prefilled);
    the same request with a generous deadline is accepted."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0, clock=clk)
    for _ in range(2):
        router.submit([1, 2, 3], max_new_tokens=2, priority="batch")
    router._holds = [1.0]  # a measured 1 s slot-hold time
    assert router.projected_wait_ms("batch") == 2000.0
    tight = router.submit([7, 7, 7], max_new_tokens=2, priority="batch",
                          deadline_ms=100.0)
    loose = router.submit([7, 7, 7], max_new_tokens=2, priority="batch",
                          deadline_ms=60_000.0)
    assert router.queue_depth == 3  # tight never entered the queue
    done = {f.req_id: f for f in router.drain()}
    assert done[tight].finish_reason == "shed"
    assert done[loose].finish_reason == "length"
    assert reg.snapshot()["counters"]["serve_shed"] == 1


def test_router_rejects_overlong_without_crashing(fix):
    """The fleet front door mirrors the engine's clean rejection: an
    impossible shape finishes 'rejected', and the fleet keeps serving."""
    model, reqs = fix
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0)
    bad = router.submit(list(range(30)), max_new_tokens=8)
    kw, ref = reqs[0]
    good = router.submit(**kw)
    done = {f.req_id: f for f in router.drain()}
    assert done[bad].finish_reason == "rejected" and done[bad].n_out == 0
    assert done[good].tokens == ref
    assert reg.snapshot()["counters"]["serve_rejected"] == 1


def test_router_failover_past_deadline_times_out_not_lost(fix):
    """A request orphaned by a replica death AFTER its deadline passed
    finishes 'timeout' (accounted, never silently dropped) — the
    zero-lost guarantee's other branch."""
    model, reqs = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0, clock=clk)
    kw, ref = reqs[0]
    sid = router.submit(**kw)                      # replica 0
    tid = router.submit([5, 5, 5], max_new_tokens=MAX_NEW,
                        deadline_ms=50.0)          # replica 1
    router.step()  # both dispatched + first tokens
    clk.t = 0.2    # past tid's deadline
    router.kill_replica(1)
    done = {f.req_id: f for f in router.drain()}
    assert done[tid].finish_reason == "timeout"
    assert done[tid].failovers == 1 and done[tid].n_out == 0
    assert done[sid].tokens == ref  # survivor untouched, bit-identical
    snap = reg.snapshot()["counters"]
    assert snap["serve_timeouts"] == 1
    # NOT a serve_failover: nothing was re-prefilled — the death just
    # surfaced an already-expired deadline (the record's `failovers`
    # attribute still says a death touched it)
    assert snap.get("serve_failovers", 0) == 0


def test_replica_state_machine_drain_and_revive(fix):
    """draining stops NEW dispatch while in-flight work finishes;
    revive un-drains without dropping anything; a dead replica revives
    empty and serves again."""
    model, reqs = fix
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=1, max_seq_len=32,
                    registry=reg, seed=0)
    kw, ref = reqs[1]
    rid = router.submit(**kw)
    router.step()  # dispatched + first token
    router.drain_replica(0)
    assert router.replicas[0].state == DRAINING
    rid2 = router.submit(**reqs[2][0])
    for _ in range(MAX_NEW + 2):
        done = {f.req_id: f for f in router.step()}
        if rid in done:
            break
    assert done[rid].tokens == ref      # in-flight work finished...
    assert router.queue_depth == 1      # ...new work was NOT dispatched
    router.revive_replica(0)            # un-drain
    assert router.replicas[0].state == HEALTHY
    done2 = {f.req_id: f for f in router.drain()}
    assert done2[rid2].tokens == reqs[2][1]
    # dead -> revive: rejoins empty and healthy
    router.kill_replica(0)
    assert router.replicas[0].state == DEAD
    rid3 = router.submit(**reqs[3][0])
    with pytest.raises(RuntimeError, match="all replicas dead"):
        router.drain()
    router.revive_replica(0)
    done3 = {f.req_id: f for f in router.drain()}
    assert done3[rid3].tokens == reqs[3][1]
