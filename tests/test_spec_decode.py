"""Speculative decoding (ISSUE 11): batched one-step verify + rejection
sampling, behind Engine(spec_decode='draft', spec_k=, draft_model=).

The contracts pinned here:

  - GREEDY BIT-PARITY: with top_k=1 every emitted stream is
    bit-identical to sequential `generate_cached`, for ANY draft model
    (rejection sampling over a one-hot target distribution is
    deterministic — infer/spec.py docstring) — slab and paged layouts,
    randomized arrivals, stop tokens, co-tenancy.
  - DISTRIBUTION EXACTNESS: with real sampling, emitted-token
    frequencies match target-only sampling (seeded, tolerance-bounded;
    the first token is bit-identical by construction — it is sampled
    from the prefill logits with the same rng split sequential uses).
  - VERIFY == STEPWISE: the k-token verify forward's per-position
    logits match single-token cached forwards across all three model
    families (the cheap, engine-free family pin).
  - NO RETRACE: one spec-step compile for the engine's lifetime across
    variable accepted counts and page churn (fixed-width token block +
    accepted-count vector as traced outputs; the page-table
    traced-arg discipline).
  - FAIL-LOUD: a draft/target vocab or width mismatch refuses Engine
    construction — which IS the worker's hello (docs/OPERATIONS.md).

Budget notes: spec-step compiles are the expensive part, so the slab
and paged spec engines are WARMED module fixtures shared across tests
(sampling params are traced pool state — reuse never recompiles), and
the trace/obs tests swap `engine._tr`/`engine._reg` on the shared
engine instead of building fresh ones.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import first_stop_index, generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.models.llama import Llama, LlamaConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.serve import Engine

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
MAX_NEW = 6


@pytest.fixture(scope="module")
def gpt_pair():
    """Target + an INDEPENDENT random draft (different init seed): the
    draft is wrong about the target almost everywhere, which is exactly
    the regime greedy parity must survive."""
    return (GPT(GPT_TINY, rngs=nnx.Rngs(0)),
            GPT(GPT_TINY, rngs=nnx.Rngs(5)))


def _warm(engine):
    """Pay every compile (both prompt buckets + the spec step) in
    fixture setup, not in a test's call budget."""
    for p in ([1, 2, 3], list(range(2, 14))):  # buckets 8 and 16
        engine.submit(p, max_new_tokens=2, rng=jax.random.key(0))
    engine.drain()
    return engine


@pytest.fixture(scope="module")
def slab_spec(gpt_pair):
    model, draft = gpt_pair
    return _warm(Engine(model, n_slots=3, max_seq_len=32,
                        registry=MetricsRegistry(), spec_decode="draft",
                        spec_k=2, draft_model=draft))


@pytest.fixture(scope="module")
def paged_spec(gpt_pair):
    model, draft = gpt_pair
    return _warm(Engine(model, n_slots=3, max_seq_len=32,
                        registry=MetricsRegistry(), kv_impl="paged",
                        page_size=4, prefill_chunk=8,
                        spec_decode="draft", spec_k=3,
                        draft_model=draft))


@pytest.fixture(scope="module")
def seq_engine(gpt_pair):
    model, _ = gpt_pair
    return _warm(Engine(model, n_slots=8, max_seq_len=32,
                        registry=MetricsRegistry()))


def _greedy_requests(model, rng, n, *, max_prompt=12):
    """n top_k=1 requests with mixed prompt lengths/temperatures and
    mid-stream stop tokens, each with its one-shot greedy reference."""
    reqs = []
    for i in range(n):
        t0 = int(rng.integers(3, max_prompt + 1))
        prompt = [int(t) for t in rng.integers(0, 64, (t0,))]
        kw = dict(prompt=prompt, max_new_tokens=MAX_NEW,
                  temperature=(0.8, 1.0, 1.3)[i % 3], top_k=1,
                  rng=jax.random.key(1000 + i))
        y = np.asarray(generate_cached(
            model, kw["rng"], jnp.asarray(prompt, jnp.int32)[None],
            MAX_NEW, temperature=kw["temperature"], top_k=1))[0]
        stop = (int(y[t0 + 1]),) if i % 2 == 0 else ()
        n_keep = first_stop_index(y[t0:], stop) if stop else MAX_NEW
        reqs.append((kw | {"stop_tokens": stop},
                     [int(t) for t in y[:t0 + n_keep]]))
    return reqs


def _run_all(engine, reqs, bursts):
    ids, results, pending = {}, {}, list(range(len(reqs)))
    bursts = list(bursts)
    while pending or engine.open_work:
        take = bursts.pop(0) if bursts else len(pending)
        for _ in range(min(take, len(pending))):
            i = pending.pop(0)
            kw, _ = reqs[i]
            ids[engine.submit(**kw)] = i
        for f in engine.step():
            results[ids[f.req_id]] = f
    return results


def _assert_parity(results, reqs):
    assert len(results) == len(reqs)
    for i, (kw, ref) in enumerate(reqs):
        got = results[i].tokens
        assert got == ref, f"request {i} diverged:\n ref {ref}\n got {got}"


def test_spec_greedy_bit_parity_slab(gpt_pair, slab_spec):
    """The acceptance case: greedy spec output is BIT-identical to
    generate_cached across randomized arrivals, queueing, stop tokens
    and co-tenancy — with an adversarially wrong (independent random)
    draft. Plus the no-retrace pin: ONE spec-step compile while
    accepted counts vary tick to tick."""
    model, _ = gpt_pair
    reqs = _greedy_requests(model, np.random.default_rng(1), 6)
    results = _run_all(slab_spec, reqs, bursts=[2, 1, 0, 3])
    _assert_parity(results, reqs)
    assert len(slab_spec.traces["step"]) == 1, (
        "the spec verify step retraced — variable accepted counts must "
        "ride as traced outputs")


def test_spec_greedy_bit_parity_paged(gpt_pair, paged_spec):
    """Same parity over the paged engine: chunked prefill + page churn
    + spec verify writes (with the scratch-tail write limit) keep
    greedy output bit-identical; the allocator audit passes on drain."""
    model, _ = gpt_pair
    reqs = _greedy_requests(model, np.random.default_rng(2), 5)
    results = _run_all(paged_spec, reqs, bursts=[2, 2, 1])
    _assert_parity(results, reqs)
    assert len(paged_spec.traces["step"]) == 1
    # spec now COMPOSES with paged prefix sharing (the draft re-prefills
    # shared spans through draft-only chunks; docs/SERVING.md) — the old
    # forced-off wall is gone.
    assert paged_spec._paged.alloc.prefix_sharing is True
    paged_spec._paged.audit(expect_empty=True)


@pytest.mark.slow
@pytest.mark.parametrize("spec_k", [4, 8])
def test_spec_greedy_bit_parity_deeper_k(gpt_pair, spec_k):
    """Deeper speculation depths keep the same bit-parity (the tier-1
    fixtures run k=2/3; the bench's k=4/8 grid is pinned here)."""
    model, draft = gpt_pair
    reqs = _greedy_requests(model, np.random.default_rng(11), 4)
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(), spec_decode="draft",
                    spec_k=spec_k, draft_model=draft)
    results = _run_all(engine, reqs, bursts=[2, 2])
    _assert_parity(results, reqs)


@pytest.mark.slow
def test_spec_greedy_parity_llama(gpt_pair):
    """Family coverage at engine depth: a GQA/RoPE target with its own
    tiny draft (the fast family pin is the stepwise-verify test)."""
    kw = dict(block_size=64, vocab_size=64, n_layer=1, n_head=4,
              n_kv_head=2, n_embd=32, ffn_hidden=64, dropout=0.0,
              attn_impl="xla")
    model = Llama(LlamaConfig(**kw), rngs=nnx.Rngs(0))
    draft = Llama(LlamaConfig(**kw), rngs=nnx.Rngs(9))
    reqs = _greedy_requests(model, np.random.default_rng(3), 3)
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(), spec_decode="draft",
                    spec_k=2, draft_model=draft)
    results = _run_all(engine, reqs, bursts=[2, 1])
    _assert_parity(results, reqs)


@pytest.mark.slow
def test_self_draft_accepts_everything(gpt_pair):
    """draft == target (same weights) + greedy: every proposal is the
    target's own argmax, so the verify accepts all spec_k drafts every
    tick — accept rate exactly 1.0. The upper bound the accept-rate
    math in docs/PERFORMANCE.md is anchored on."""
    model, _ = gpt_pair
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=1, max_seq_len=32, registry=reg,
                    spec_decode="draft", spec_k=3, draft_model=model)
    ref = np.asarray(generate_cached(
        model, jax.random.key(77), jnp.asarray([1, 2, 3], jnp.int32)[None],
        8, temperature=1.0, top_k=1))[0]
    engine.submit([1, 2, 3], max_new_tokens=8, temperature=1.0, top_k=1,
                  rng=jax.random.key(77))
    done = engine.drain()
    assert done[0].tokens == [int(t) for t in ref]
    c = reg.snapshot()["counters"]
    assert c["spec_proposed"] > 0
    assert c["spec_accepted"] == c["spec_proposed"]
    assert reg.snapshot()["gauges"]["spec_accept_rate"] == 1.0


def test_rejection_sampling_matches_target_distribution(
        gpt_pair, slab_spec, seq_engine):
    """Seeded distributional pin: spec emissions vs (a) the analytic
    target distribution at the first position — where they are also
    BIT-identical to the sequential engine, because the tail sample
    consumes the same rng split — and (b) the sequential engine's
    empirical frequencies at later positions (TV-bounded; measured
    ~0.09-0.12 at this N, pinned at 0.2)."""
    model, _ = gpt_pair
    V, N, TOPK = 64, 192, 4
    prompt = [3, 1, 4, 1, 5]

    def collect(eng):
        ids = {}
        for i in range(N):
            ids[eng.submit(prompt, max_new_tokens=3, temperature=1.0,
                           top_k=TOPK, rng=jax.random.key(9000 + i))] = i
        out = {}
        while eng.open_work:
            for f in eng.step():
                out[ids[f.req_id]] = f.tokens[len(prompt):]
        return [out[i] for i in range(N)]

    seq, spec = collect(seq_engine), collect(slab_spec)
    # position 0: bit-identical (same key split, same prefill logits)
    assert [s[0] for s in seq] == [s[0] for s in spec]
    # position 0 vs the analytic top-k-masked softmax
    from avenir_tpu.infer.decode import _forward_cached, init_cache

    logits, _ = _forward_cached(
        model, jnp.asarray(prompt, jnp.int32)[None],
        init_cache(n_layer=1, batch=1, max_t=16, n_kv_head=2,
                   head_dim=16, dtype=jnp.float32), 0)
    l = np.asarray(logits[0])
    kth = np.sort(l)[-TOPK]
    l = np.where(l < kth, -np.inf, l)
    p = np.exp(l - l.max())
    p /= p.sum()
    emp = np.bincount([s[0] for s in spec], minlength=V) / N
    assert 0.5 * np.abs(emp - p).sum() < 0.15
    # later positions: rejection-sampled spec vs sequential frequencies
    for pos in (1, 2):
        a = np.bincount([s[pos] for s in seq], minlength=V) / N
        b = np.bincount([s[pos] for s in spec], minlength=V) / N
        assert 0.5 * np.abs(a - b).sum() < 0.2, f"position {pos} drifted"


@pytest.mark.parametrize("family", ["gpt", "llama", "mixtral"])
def test_verify_forward_matches_stepwise(family):
    """The k-token verify forward IS k cached single-token forwards:
    per-position logits from ONE (B, k+1)-wide `return_all` pass equal
    the step-by-step cached path, for all three families (eager — no
    engine, no extra compiles; this is the cheap family pin behind the
    greedy-parity contract)."""
    from avenir_tpu.infer.decode import _forward_cached, init_cache
    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

    kw = dict(block_size=64, vocab_size=64, n_layer=1, n_head=4,
              n_kv_head=2, n_embd=32, ffn_hidden=64, dropout=0.0,
              attn_impl="xla")
    if family == "gpt":
        model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
        n_kv, hd = 2, 16
    elif family == "llama":
        model = Llama(LlamaConfig(**kw), rngs=nnx.Rngs(0))
        n_kv, hd = 2, 8
    else:
        # cf*K >= E: capacity can never bind, so the (k+1)-wide verify
        # routes exactly like single-token steps (the parity-safe MoE
        # regime, docs/SERVING.md)
        model = Mixtral(MixtralConfig(n_experts=4, n_experts_per_tok=2,
                                      capacity_factor=2.0, **kw),
                        rngs=nnx.Rngs(0))
        n_kv, hd = 2, 8
    prompt = jnp.asarray([5, 7, 11, 13], jnp.int32)[None]
    block = jnp.asarray([17, 19, 23], jnp.int32)[None]  # tail + 2 drafts

    def fresh():
        return init_cache(n_layer=1, batch=1, max_t=16, n_kv_head=n_kv,
                          head_dim=hd, dtype=jnp.float32)

    # stepwise: prefill, then one token at a time at per-row positions
    _, cache = _forward_cached(model, prompt, fresh(), 0)
    step_logits = []
    for i in range(block.shape[1]):
        lg, cache = _forward_cached(model, block[:, i:i + 1], cache,
                                    jnp.asarray([4 + i], jnp.int32))
        step_logits.append(np.asarray(lg))
    # verify: the same tokens in ONE multi-token pass
    _, cache2 = _forward_cached(model, prompt, fresh(), 0)
    all_logits, _ = _forward_cached(model, block, cache2,
                                    jnp.asarray([4], jnp.int32),
                                    return_all=True)
    all_logits = np.asarray(all_logits)
    for i in range(block.shape[1]):
        np.testing.assert_allclose(all_logits[0, i], step_logits[i][0],
                                   rtol=2e-5, atol=2e-5)


def test_spec_paged_no_retrace_across_page_churn(paged_spec):
    """Serial waves of admissions/releases churn the page tables and
    accepted counts; the spec step and COW must each stay at ONE
    compile (the engine asserts this every step too — this makes the
    pin explicit for the spec fn's extra traced args)."""
    engine = paged_spec
    for wave in range(3):
        for i in range(3):
            engine.submit([1 + wave, 2 + i, 3], max_new_tokens=4,
                          temperature=1.0, rng=jax.random.key(wave * 10 + i))
        engine.drain()
    assert len(engine.traces["step"]) == 1
    assert len(engine.traces["prefill"]) <= len(engine._paged.chunk_ladder)


def test_draft_target_mismatch_fails_loud(gpt_pair):
    """A mismatched draft refuses Engine construction with the reason —
    in a process worker this is the hello, so the parent's handshake
    fails loud instead of serving garbage (OPERATIONS.md matrix row)."""
    model, _ = gpt_pair
    bad_vocab = GPT(dataclasses.replace(GPT_TINY, vocab_size=32),
                    rngs=nnx.Rngs(1))
    with pytest.raises(ValueError, match="vocab mismatch"):
        Engine(model, n_slots=1, registry=MetricsRegistry(),
               spec_decode="draft", draft_model=bad_vocab)
    narrow = GPT(dataclasses.replace(GPT_TINY, block_size=16),
                 rngs=nnx.Rngs(1))
    with pytest.raises(ValueError, match="block_size"):
        Engine(model, n_slots=1, max_seq_len=64,
               registry=MetricsRegistry(), spec_decode="draft",
               draft_model=narrow)
    with pytest.raises(ValueError, match="draft_model"):
        Engine(model, n_slots=1, registry=MetricsRegistry(),
               spec_decode="draft")


def test_spec_obs_counters_and_report(slab_spec):
    """spec_proposed/spec_accepted/spec_accept_rate flow through the
    schema-checked registry, and obs_report grows the accept: line."""
    import time

    from avenir_tpu.obs.report import format_report, summarize

    engine = slab_spec
    reg = MetricsRegistry()
    old_reg, engine._reg = engine._reg, reg
    try:
        for i in range(3):
            engine.submit([1, 2, 3 + i], max_new_tokens=4,
                          rng=jax.random.key(i))
        engine.drain()
    finally:
        engine._reg = old_reg
    snap = reg.snapshot()
    assert snap["counters"]["spec_proposed"] > 0
    assert 0.0 <= snap["gauges"]["spec_accept_rate"] <= 1.0
    records = [
        {"kind": "run_meta", "t": time.time(), "model_type": "gpt"},
        {"kind": "request", "t": time.time(), "id": 0, "n_prompt": 3,
         "n_out": 4, "finish_reason": "length", "ttft_ms": 1.0,
         "tpot_ms": 0.5},
        {"kind": "run_end", "t": time.time(),
         "counters": snap["counters"],
         "gauges": {"kv_dtype": 16.0,
                    "spec_accept_rate":
                        snap["gauges"]["spec_accept_rate"]}},
    ]
    report = format_report(summarize(records))
    assert "accept:" in report


def test_spec_trace_events(slab_spec):
    """spec_verify rides the trace buffer at the decode_tick cadence
    and carries proposed/accepted counts."""
    from avenir_tpu.obs.trace import TraceBuffer

    engine = slab_spec
    buf = TraceBuffer(decode_sample=1, clock=engine._clock)
    old_tr, engine._tr = engine._tr, buf
    try:
        engine.submit([1, 2, 3], max_new_tokens=4, rng=jax.random.key(0))
        engine.drain()
    finally:
        engine._tr = old_tr
    evs = [e for e in buf.drain() if e["ev"] == "spec_verify"]
    assert evs and all("proposed" in e and "accepted" in e for e in evs)


def test_spec_ttft_attribution_exact_over_traced_run(slab_spec):
    """ISSUE 12 satellite regression: spec decoding samples the FIRST
    token inside admission prefill (_Live.pending), so the trace
    partition must anchor TTFT at the sample — the prefill that
    produced it — not at the verify tick that harvests the pending
    token (which would silently fold a decode tick, compile included,
    into 'prefill'). Pins: (1) exactly one first_token event per
    request, stamped at admission; (2) it precedes every decode tick;
    (3) queue + prefill + failover still PARTITIONS the measured
    ttft_ms exactly."""
    from avenir_tpu.obs.trace import (
        TraceBuffer,
        Tracer,
        ttft_attribution,
    )

    engine = slab_spec
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=engine._clock)
    buf = TraceBuffer(clock=engine._clock, decode_sample=1)
    old_tr, engine._tr = engine._tr, buf
    try:
        rids = []
        for i in range(3):
            t_sub = engine._clock()
            rid = engine.submit([1, 2, 3 + i], max_new_tokens=4,
                                rng=jax.random.key(100 + i))
            # the router normally emits these fleet events; driving the
            # engine directly, the test stamps them itself
            tr.emit(rid, "submit", t=t_sub)
            tr.emit(rid, "dispatch", t=t_sub)
            rids.append(rid)
        fins = engine.drain()
    finally:
        engine._tr = old_tr
    tr.absorb(buf.drain(), rid_map={r: r for r in rids})
    ticks = [e["t"] for e in tr.events() if e["ev"] == "decode_tick"]
    assert ticks, "decode ticks must have been sampled (sample=1)"
    for f in fins:
        evs = tr.events_for(f.req_id)
        ft = [e for e in evs if e["ev"] == "first_token"]
        assert len(ft) == 1, "exactly one first_token per attempt"
        assert ft[0].get("admission") is True, (
            "spec first token must be stamped at admission prefill")
        assert ft[0]["t"] <= min(ticks) + 1e-9, (
            "the admission-sampled first token must precede the verify "
            "tick that harvests it")
        a = ttft_attribution(evs)
        assert a is not None
        assert a["queue_s"] + a["prefill_s"] + a["failover_s"] == \
            pytest.approx(a["ttft_s"], abs=1e-9)
        assert a["ttft_s"] * 1e3 == pytest.approx(f.ttft_ms, abs=2.0)
        assert f.n_out == 4 and f.finish_reason == "length"


@pytest.mark.slow
def test_spec_process_worker_parity(gpt_pair):
    """Draft weights ship in the worker hello like target weights: a
    process-backend fleet with spec on serves greedy output
    bit-identical to generate_cached — router/proc semantics untouched
    (ISSUE 11 'zero semantic changes')."""
    from avenir_tpu.serve import Router

    model, draft = gpt_pair
    reqs = _greedy_requests(model, np.random.default_rng(4), 3)
    router = Router(model, n_replicas=1, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(), backend="process",
                    draft_model=draft,
                    engine_kwargs={"spec_decode": "draft", "spec_k": 2})
    try:
        ids = {router.submit(**kw): i for i, (kw, _) in enumerate(reqs)}
        results = {}
        while router.open_requests or router._pending:
            for f in router.step():
                results[ids[f.req_id]] = f
        for i, (kw, ref) in enumerate(reqs):
            assert results[i].tokens == ref
    finally:
        router.close()
