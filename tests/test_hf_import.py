"""HF GPT-2 import (avenir_tpu/tools/hf_import.py) — offline tests.

The real HF cache is absent in CI, so the mapping is exercised against a
synthetic HF-style state dict built from the torch reference model
(model.py), whose Conv1D/prefix conventions from_pretrained documents
(model.py:210-254): keys unprefixed, Conv1D projections stored (in, out),
mask buffers present, lm_head alias present.
"""

import numpy as np
import pytest
import torch

import model as torch_model
from avenir_tpu.tools.hf_import import (
    HF_CONFIGS,
    gpt2_config,
    gpt2_from_hf,
    hf_sd_to_torch_layout,
    load_hf_gpt2_sd,
)

_CONV1D = ("attn.c_attn.weight", "attn.c_proj.weight",
           "mlp.c_fc.weight", "mlp.c_proj.weight")


def _fake_hf_sd(tmodel):
    """torch reference state_dict → the raw HF on-hub layout."""
    sd = {}
    for k, v in tmodel.state_dict().items():
        if k.endswith(".attn.causal_mask"):
            continue
        arr = v.detach().numpy()
        if k.startswith("transformer."):
            k = k[len("transformer."):]
        if any(k.endswith(s) for s in _CONV1D):
            arr = np.ascontiguousarray(arr.T)  # HF Conv1D stores (in, out)
        sd[k] = arr
    # HF checkpoints carry mask buffers the importer must skip
    sd["h.0.attn.bias"] = np.tril(np.ones((1, 1, 8, 8), np.uint8))
    return sd


def test_hf_import_logits_match_torch():
    cfg = torch_model.GPTConfig(block_size=8, vocab_size=32, n_layer=2,
                                n_head=2, n_embd=16, dropout=0.0, bias=True)
    tm = torch_model.GPT(cfg)
    tm.eval()

    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig

    jm = GPT(GPTConfig(block_size=8, vocab_size=32, n_layer=2, n_head=2,
                       n_embd=16, dropout=0.0, bias=True, attn_impl="xla"),
             rngs=nnx.Rngs(0))
    load_hf_gpt2_sd(jm, _fake_hf_sd(tm))

    idx = np.random.default_rng(0).integers(0, 32, (2, 8))
    with torch.no_grad():
        tl, _ = tm(torch.from_numpy(idx), torch.from_numpy(idx))
    jl, _ = jm(idx, idx)
    np.testing.assert_allclose(np.asarray(jl), tl.numpy(), atol=2e-5)


def test_hf_layout_normalization():
    sd = {
        "wte.weight": np.zeros((4, 2)),
        "h.0.attn.c_attn.weight": np.zeros((2, 6)),  # Conv1D (in, out)
        "h.0.attn.bias": np.zeros((1, 1, 4, 4)),     # mask buffer → dropped
        "lm_head.weight": np.zeros((4, 2)),          # tied alias → dropped
        "transformer.ln_f.weight": np.zeros((2,)),   # prefixed variant kept
    }
    out = hf_sd_to_torch_layout(sd)
    assert set(out) == {"transformer.wte.weight",
                        "transformer.h.0.attn.c_attn.weight",
                        "transformer.ln_f.weight"}
    assert out["transformer.h.0.attn.c_attn.weight"].shape == (6, 2)


def test_gpt2_config_table_matches_torch_reference():
    for name, args in HF_CONFIGS.items():
        cfg = gpt2_config(name)
        assert cfg.vocab_size == 50257 and cfg.block_size == 1024 and cfg.bias
        assert (cfg.n_layer, cfg.n_head, cfg.n_embd) == (
            args["n_layer"], args["n_head"], args["n_embd"])


def test_gpt2_from_hf_reaches_weight_load_or_skips():
    """With a cold HF cache the loader must fail with the clear egress
    message, not an ImportError/ModuleNotFoundError (VERDICT r1 item 4)."""
    try:
        gpt2_from_hf("gpt2")
    except RuntimeError as e:
        assert "local HF cache" in str(e)
        pytest.skip("HF cache not populated (expected in sandbox)")


def test_train_loop_init_from_gpt2(char_dataset, tmp_path, monkeypatch):
    """run_training(init_from=gpt2*) must load HF weights through the
    bridge and then train (the loop branch, not just sample.py). Uses a
    monkeypatched tiny 'gpt2' so no HF cache is needed."""
    import avenir_tpu.tools.hf_import as hfi
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    tiny = dict(n_layer=2, n_head=2, n_embd=16)
    monkeypatch.setitem(hfi.HF_CONFIGS, "gpt2", tiny)

    cfg_t = torch_model.GPTConfig(block_size=1024, vocab_size=50257,
                                  dropout=0.0, bias=True, **tiny)
    tm = torch_model.GPT(cfg_t)
    fake_sd = _fake_hf_sd(tm)
    monkeypatch.setattr(hfi, "_load_hf_numpy_sd", lambda name: fake_sd)

    # block_size=1024 (== the HF table): exercises the NO-crop branch;
    # the crop branch is pinned by the next test
    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=3,
                   init_from="gpt2", block_size=1024, batch_size=2,
                   gradient_accumulation_steps=1, mesh_shape="data:1",
                   eval_iters=1, eval_interval=50, **tiny)
    res = run_training(cfg)
    assert res["iter_num"] >= 3
    assert res["loss_history"], "no losses logged"


def test_train_loop_gpt2_init_crops_block_size(char_dataset, tmp_path,
                                               monkeypatch):
    """cfg block_size < the HF 1024 must crop the position table (parity
    with the torch path's crop_block_size)."""
    import avenir_tpu.tools.hf_import as hfi
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train import loop as loop_mod

    tiny = dict(n_layer=1, n_head=2, n_embd=16)
    monkeypatch.setitem(hfi.HF_CONFIGS, "gpt2", tiny)
    cfg_t = torch_model.GPTConfig(block_size=1024, vocab_size=50257,
                                  dropout=0.0, bias=True, **tiny)
    fake_sd = _fake_hf_sd(torch_model.GPT(cfg_t))
    monkeypatch.setattr(hfi, "_load_hf_numpy_sd", lambda name: fake_sd)

    seen = {}
    orig = loop_mod.setup_state

    def spy(cfg, mesh, model_args, **kw):
        seen.update(model_args)
        return orig(cfg, mesh, model_args, **kw)

    monkeypatch.setattr(loop_mod, "setup_state", spy)
    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=2,
                   init_from="gpt2", block_size=32, mesh_shape="data:1",
                   eval_iters=1, eval_interval=50, **tiny)
    res = loop_mod.run_training(cfg)
    assert seen["block_size"] == 32
    assert res["iter_num"] >= 2


# ---------------------------------------------------------------------------
# Llama / Mixtral HF import (VERDICT r2 missing #7): end-to-end through a
# save_pretrained directory — config.json parse, safetensors read, bridge
# load — asserting logits parity against the HF torch model.
# ---------------------------------------------------------------------------


def test_llama_from_hf_dir_logits_parity(tmp_path):
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    hf_cfg = HFConfig(
        vocab_size=64, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rms_norm_eps=1e-5, rope_theta=10000.0,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    tm = LlamaForCausalLM(hf_cfg)
    tm.eval()
    tm.save_pretrained(tmp_path / "llama", safe_serialization=True)

    from avenir_tpu.tools.hf_import import llama_from_hf

    jm = llama_from_hf(str(tmp_path / "llama"))
    idx = np.random.default_rng(0).integers(0, 64, (2, 16))
    with torch.no_grad():
        t_logits = tm(torch.from_numpy(idx)).logits
    import jax.numpy as jnp

    j_logits, _ = jm(jnp.asarray(idx), jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(j_logits), t_logits.numpy(),
                               atol=2e-4, rtol=2e-4)


def test_llama_from_hf_tied_embeddings(tmp_path):
    """Tied HF checkpoints (e.g. Llama-3.2-1B) omit lm_head.weight; the
    importer materializes it from embed_tokens into our untied head."""
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    hf_cfg = HFConfig(
        vocab_size=64, hidden_size=32, intermediate_size=56,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rms_norm_eps=1e-5,
        tie_word_embeddings=True, attention_bias=False, mlp_bias=False,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    tm = LlamaForCausalLM(hf_cfg)
    tm.eval()
    tm.save_pretrained(tmp_path / "tied", safe_serialization=True)

    from avenir_tpu.tools.hf_import import llama_from_hf

    jm = llama_from_hf(str(tmp_path / "tied"))
    idx = np.random.default_rng(1).integers(0, 64, (1, 12))
    with torch.no_grad():
        t_logits = tm(torch.from_numpy(idx)).logits
    import jax.numpy as jnp

    j_logits, _ = jm(jnp.asarray(idx), jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(j_logits), t_logits.numpy(),
                               atol=2e-4, rtol=2e-4)


def test_mixtral_from_hf_dir_logits_parity(tmp_path):
    from transformers import MixtralConfig as HFConfig, MixtralForCausalLM

    hf_cfg = HFConfig(
        vocab_size=64, hidden_size=32, intermediate_size=56,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rms_norm_eps=1e-5, rope_theta=10000.0,
        num_local_experts=4, num_experts_per_tok=2,
        tie_word_embeddings=False, attn_implementation="eager",
        router_aux_loss_coef=0.0,
    )
    torch.manual_seed(0)
    tm = MixtralForCausalLM(hf_cfg)
    tm.eval()
    tm.save_pretrained(tmp_path / "mixtral", safe_serialization=True)

    from avenir_tpu.tools.hf_import import mixtral_from_hf

    jm = mixtral_from_hf(str(tmp_path / "mixtral"))
    # capacity high enough that nothing drops (same regime as
    # tests/test_mixtral.py HF-parity tests)
    import dataclasses

    jm.config = dataclasses.replace(jm.config, capacity_factor=8.0)
    for lyr in jm.layers:
        lyr.block_sparse_moe.capacity_factor = 8.0
    idx = np.random.default_rng(0).integers(0, 64, (2, 16))
    with torch.no_grad():
        t_logits = tm(torch.from_numpy(idx)).logits
    import jax.numpy as jnp

    j_logits, _ = jm(jnp.asarray(idx), jnp.asarray(idx))
    np.testing.assert_allclose(np.asarray(j_logits), t_logits.numpy(),
                               atol=3e-4, rtol=3e-4)


def _synthetic_hf_gpt2_sd(n_layer=2, n_embd=32, n_ctx=1024, vocab=50257,
                          seed=0):
    """A hub-layout GPT-2 state dict (numpy) at tiny dims: no
    'transformer.' prefix, Conv1D (in, out) weight layout, mask buffers
    and the tied lm_head alias present (the import must drop both)."""
    rng = np.random.default_rng(seed)
    f = lambda *s: (rng.standard_normal(s) * 0.02).astype(np.float32)
    C = n_embd
    sd = {"wte.weight": f(vocab, C), "wpe.weight": f(n_ctx, C),
          "lm_head.weight": f(vocab, C)}
    for i in range(n_layer):
        b = f"h.{i}."
        sd[b + "ln_1.weight"] = np.ones(C, np.float32)
        sd[b + "ln_1.bias"] = f(C)
        sd[b + "attn.c_attn.weight"] = f(C, 3 * C)
        sd[b + "attn.c_attn.bias"] = f(3 * C)
        sd[b + "attn.c_proj.weight"] = f(C, C)
        sd[b + "attn.c_proj.bias"] = f(C)
        sd[b + "attn.bias"] = np.ones((1, 1, n_ctx, n_ctx), np.float32)
        sd[b + "ln_2.weight"] = np.ones(C, np.float32)
        sd[b + "ln_2.bias"] = f(C)
        sd[b + "mlp.c_fc.weight"] = f(C, 4 * C)
        sd[b + "mlp.c_fc.bias"] = f(4 * C)
        sd[b + "mlp.c_proj.weight"] = f(4 * C, C)
        sd[b + "mlp.c_proj.bias"] = f(C)
    sd["ln_f.weight"] = np.ones(C, np.float32)
    sd["ln_f.bias"] = f(C)
    return sd


def test_finetune_init_from_gpt2_offline(char_dataset, tmp_path, monkeypatch):
    """VERDICT r4 weak #7: the `--init_from=gpt2` finetune entry
    (loop.py), previously only testable with a populated HF cache, driven
    fully offline with a synthetic hub-layout state dict. Covers the wpe
    block-size crop, the Conv1D transposes, mask-buffer/lm_head-alias
    dropping, and 2 finite training iterations. lr=0 makes AdamW a
    no-op, so the checkpoint the run saves must carry EXACTLY the
    synthetic weights mapped through the independent hf_import bridge —
    init parity without network or torch."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.checkpoint.io import load_checkpoint
    from avenir_tpu.tools import hf_import
    from avenir_tpu.train.loop import run_training

    sd = _synthetic_hf_gpt2_sd()
    monkeypatch.setattr(hf_import, "_load_hf_numpy_sd",
                        lambda mt: dict(sd))
    monkeypatch.setitem(hf_import.HF_CONFIGS, "gpt2",
                        dict(n_layer=2, n_head=2, n_embd=32))
    out = tmp_path / "out"
    cfg = make_cfg(char_dataset["dir"], out, init_from="gpt2",
                   mesh_shape="data:1",
                   block_size=32, max_iters=2, eval_interval=2,
                   learning_rate=0.0, min_lr=0.0, decay_lr=False,
                   weight_decay=0.0, warmup_iters=0)
    res = run_training(cfg)
    losses = np.array([l for _, l in res["loss_history"]])
    assert losses.size and np.all(np.isfinite(losses))

    ck = load_checkpoint(str(out))
    # wpe cropped 1024 -> block_size
    assert ck["model"]["transformer.wpe.weight"].shape == (32, 32)
    expected = hf_import.hf_sd_to_torch_layout(dict(sd))
    expected["transformer.wpe.weight"] = \
        expected["transformer.wpe.weight"][:32]
    # our save exports the tied head explicitly (torch schema)
    expected["lm_head.weight"] = expected["transformer.wte.weight"]
    got = {k: np.asarray(v) for k, v in ck["model"].items()}
    assert set(got) == set(expected), (
        sorted(set(got) ^ set(expected))[:6])
    for k in expected:
        np.testing.assert_allclose(got[k], expected[k], atol=1e-6,
                                   err_msg=k)
