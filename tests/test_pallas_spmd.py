"""Pallas flash attention under multi-device SPMD (VERDICT r3 item 1).

GSPMD has no partitioning rule for a pallas_call custom call: without the
dispatcher's shard_map wrap, the jitted kernel on an 8-device mesh compiles
with ~33 all-gathers and a REPLICATED output (measured; see
ops/attention._flash_shard_specs). These tests pin the wrap's three
contracts on the 8-fake-CPU-device harness (interpret-mode kernels, real
meshes, real GSPMD):

  1. the compiled HLO around the kernel contains NO all-gather and the
     output keeps the input sharding (batch over data/fsdp, heads over
     tensor) — MHA and GQA;
  2. numerics match the jnp reference (fwd and grads) under the mesh;
  3. the full product training loop (attn_impl="pallas") follows the
     single-device trajectory on data/fsdp and fsdp/tensor meshes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.ops.attention import (
    _flash_shard_specs,
    causal_attention,
    causal_attention_reference,
)
from avenir_tpu.parallel.mesh import make_mesh


def _sharded_qkv(mesh, B, H, H_kv, T, D, dtype=np.float32):
    rng = np.random.default_rng(0)
    sh_q = NamedSharding(mesh, P(("data", "fsdp"), "tensor", None, None))
    mk = lambda h: jax.device_put(
        jnp.asarray(rng.standard_normal((B, h, T, D)).astype(dtype)), sh_q
    )
    return mk(H), mk(H_kv), mk(H_kv)


@pytest.mark.parametrize("H,H_kv", [(4, 4), (4, 2)])
def test_pallas_spmd_partitioned_and_correct(H, H_kv):
    """data:2,fsdp:2,tensor:2 — the product GPT mesh shape. The custom
    call must stay partitioned (zero all-gathers in the whole fwd+bwd
    module) and fwd/grads must match the jnp reference."""
    mesh = make_mesh("data:2,fsdp:2,tensor:2")
    jax.set_mesh(mesh)
    B, T, D = 8, 128, 32
    q, k, v = _sharded_qkv(mesh, B, H, H_kv, T, D)

    def loss(q, k, v):
        o = causal_attention(q, k, v, impl="pallas", layout="bhtd")
        return jnp.sum(o * o), o

    f = jax.jit(jax.grad(loss, argnums=(0, 1, 2), has_aux=True))
    hlo = f.lower(q, k, v).compile().as_text()
    assert hlo.count("all-gather") == 0, (
        "pallas custom call was not partitioned — GSPMD inserted "
        f"{hlo.count('all-gather')} all-gathers"
    )
    (dq, dk, dv), o = f(q, k, v)
    assert o.sharding.spec == P(("data", "fsdp"), "tensor", None, None)
    assert dq.sharding.spec == P(("data", "fsdp"), "tensor", None, None)

    # numerics vs the jnp oracle (bthd layout, GQA repeated explicitly)
    tr = lambda x: x.transpose(0, 2, 1, 3)
    rep = lambda x: jnp.repeat(x, H // H_kv, axis=2)

    def loss_ref(q, k, v):
        o = causal_attention_reference(tr(q), rep(tr(k)), rep(tr(v)))
        return jnp.sum(o * o), o

    (dq_r, dk_r, dv_r), o_r = jax.jit(
        jax.grad(loss_ref, argnums=(0, 1, 2), has_aux=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(tr(o_r)),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_r),
                               atol=2e-3, rtol=2e-3)
    # the repeat sits inside loss_ref, so autodiff already folds the GQA
    # group sum: dk_r/dv_r are (B, H_kv, T, D) like ours
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_r),
                               atol=2e-3, rtol=2e-3)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_r),
                               atol=2e-3, rtol=2e-3)


def test_flash_shard_specs_fallbacks():
    """Axis selection degrades gracefully: indivisible batch drops batch
    axes, indivisible heads drop 'tensor', nothing shardable → None.
    The wrap names ALL free axes (all six on a top-level mesh)."""
    from avenir_tpu.parallel.mesh import AXES

    mesh = make_mesh("data:2,fsdp:2,tensor:2")
    jax.set_mesh(mesh)
    all_free = frozenset(AXES)
    # everything divides → full spec
    assert _flash_shard_specs("bhtd", (8, 4, 64, 16), 4, 4) == \
        (P(("data", "fsdp"), "tensor", None, None), all_free)
    # bthd layout puts heads third
    assert _flash_shard_specs("bthd", (8, 64, 4, 16), 4, 4) == \
        (P(("data", "fsdp"), None, "tensor", None), all_free)
    # B=6: divisible by data(2) but not data*fsdp(4) → fsdp dropped
    assert _flash_shard_specs("bhtd", (6, 4, 64, 16), 4, 4) == \
        (P(("data",), "tensor", None, None), all_free)
    # odd H_kv → tensor dropped (GQA group map must stay shard-local)
    assert _flash_shard_specs("bhtd", (8, 4, 64, 16), 4, 1) == \
        (P(("data", "fsdp"), None, None, None), all_free)
    # nothing divides → no wrap
    assert _flash_shard_specs("bhtd", (3, 3, 64, 16), 3, 3) is None


def test_flash_shard_specs_no_mesh():
    """No ambient mesh (single-device use) → no wrap (conftest resets the
    ambient mesh to empty before each test)."""
    assert _flash_shard_specs("bhtd", (8, 4, 64, 16), 4, 4) is None


def test_flash_shard_specs_none_inside_full_manual():
    """Inside an enclosing shard_map body that is manual over EVERY mesh
    axis (ulysses's local kernel runs there) no free axis remains — the
    dispatcher must not nest another wrap."""
    mesh = make_mesh("data:2,tensor:2")
    jax.set_mesh(mesh)
    seen = []

    def body(x):
        seen.append(_flash_shard_specs("bhtd", (8, 4, 64, 16), 4, 4))
        return x

    f = jax.shard_map(
        body, in_specs=P(("data",), None), out_specs=P(("data",), None),
        check_vma=False,
    )
    jax.jit(f)(jnp.ones((8, 4)))
    assert seen == [None]


def test_flash_shard_specs_partial_manual_names_free_axes_only():
    """Inside a PARTIAL manual region (the GPipe body: manual over 'pipe'
    only) the wrap must engage over the remaining free axes and must NOT
    name the Manual axis — naming it would claim the inputs replicated
    over 'pipe' and the transpose would psum cotangents over it
    (partition.free_axis_names; measured 2.8e-3 grad corruption)."""
    mesh = make_mesh("pipe:2,data:2,tensor:2")
    jax.set_mesh(mesh)
    seen = []

    def body(x):
        seen.append(_flash_shard_specs("bhtd", (8, 4, 64, 16), 4, 4))
        return x

    f = jax.shard_map(
        body, in_specs=P(None, None), out_specs=P(None, None),
        check_vma=False, axis_names={"pipe"},
    )
    jax.jit(f)(jnp.ones((8, 4)))
    (spec, names), = seen
    assert spec == P(("data",), "tensor", None, None)
    assert "pipe" not in names and {"data", "tensor"} <= names


def test_pallas_nested_in_pipe_partitioned_and_exact(char_dataset):
    """VERDICT r4 item 1 'Done' criterion: with the flash wrap nesting
    inside the GPipe partial-manual region (pipeline_microbatches=2 so
    the per-micro batch divides data:2), the compiled whole-model
    fwd+bwd HLO contains ZERO all-gathers — attention stays partitioned
    over 'data' instead of the r4 replicate-inside-pipe fallback — and
    the model gradients match the single-device oracle to fp32 noise
    (the r4 nested wrap corrupted them by ~7e-3)."""
    from flax import nnx

    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import setup_state

    model_args = dict(n_layer=2, n_head=4, n_embd=32, block_size=64,
                      bias=False, vocab_size=96, dropout=0.0)
    x = jax.random.randint(jax.random.key(1), (8, 64), 0, 96)
    y = jax.random.randint(jax.random.key(2), (8, 64), 0, 96)

    def grads(mesh_shape, attn_impl, want_hlo=False):
        cfg = make_cfg("x", "y", mesh_shape=mesh_shape, scan_layers=True,
                       attn_impl=attn_impl, allow_unsharded_fallback=True,
                       pipeline_microbatches=2)
        mesh = make_mesh(mesh_shape)
        st = setup_state(cfg, mesh, model_args, verbose=False)
        graphdef = st["graphdef"]

        def loss_fn(params):
            _, loss = nnx.merge(graphdef, params)(x, targets=y)
            return loss

        with jax.set_mesh(mesh):
            params = jax.jit(
                lambda: nnx.split(st["ctor"](0), nnx.Param)[1],
                out_shardings=st["shard_tree"],
            )()
            f = jax.jit(jax.grad(loss_fn))
            hlo = (f.lower(params).compile().as_text() if want_hlo else "")
            g = f(params)
        return jax.tree.map(np.asarray, nnx.to_pure_dict(g)), hlo

    g_pipe, hlo = grads("pipe:2,data:2", "pallas", want_hlo=True)
    assert hlo.count("all-gather") == 0, (
        f"attention was gathered inside the pipe region: "
        f"{hlo.count('all-gather')} all-gathers"
    )
    g_ref, _ = grads("data:1", "pallas")
    for (ka, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(g_pipe)[0],
        jax.tree_util.tree_flatten_with_path(g_ref)[0],
    ):
        np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5,
                                   err_msg=jax.tree_util.keystr(ka))


@pytest.mark.parametrize("model_kw", [
    dict(),  # GPT (MHA)
    # Llama GQA: n_head=4 over tensor:2 → 2 q heads + 1 kv head per shard
    dict(model_type="llama", n_head=4, n_kv_head=2, ffn_hidden=64),
    # remat wraps each block — the rematerialized bwd re-enters the
    # shard_map'd kernel; scan stacks the layers around it (the deep-rung
    # product config: scan+remat+pallas under fsdp)
    dict(remat=True, scan_layers=True),
], ids=["gpt", "llama-gqa", "gpt-remat-scan"])
@pytest.mark.parametrize("mesh_shape", ["data:2,fsdp:2", "fsdp:2,tensor:2"])
def test_spmd_trajectory_pallas(char_dataset, tmp_path, mesh_shape, model_kw):
    """The PRODUCT configuration (training loop + pallas hot path) under a
    mesh: loss trajectory must equal the single-device pallas trajectory
    (same seeds, same global batch) — pallas-under-SPMD is pure layout.
    The llama-gqa case puts GQA K/V head-sharding over 'tensor' through
    the whole stack (kernel index maps + the wrap's head split)."""
    from tests.test_train_tpu import make_cfg
    from avenir_tpu.train.loop import run_training

    cfg1 = make_cfg(char_dataset["dir"], tmp_path / "o1", max_iters=4,
                    gradient_accumulation_steps=4, mesh_shape="data:1",
                    attn_impl="pallas", **model_kw)
    ref = run_training(cfg1)
    cfgN = make_cfg(char_dataset["dir"], tmp_path / "o2", max_iters=4,
                    gradient_accumulation_steps=4, mesh_shape=mesh_shape,
                    attn_impl="pallas", **model_kw)
    got = run_training(cfgN)
    ref_l = np.array([l for _, l in ref["loss_history"]])
    got_l = np.array([l for _, l in got["loss_history"]])
    np.testing.assert_allclose(got_l, ref_l, atol=2e-4, rtol=2e-4)
