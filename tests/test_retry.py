"""Retry/backoff + fault-injector unit tests (ISSUE 5): the backoff
sequence is asserted with an injected sleep (no real waiting), retries
are counted and logged, corruption is never retried, and the injector's
seeded schedule is reproducible."""

import pytest

from avenir_tpu.checkpoint.manifest import CorruptCheckpoint
from avenir_tpu.obs.metrics import MetricsRegistry
from avenir_tpu.utils.faults import FaultInjected, FaultInjector
from avenir_tpu.utils.retry import RetryPolicy, call_with_retry


class _Sink:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(record)


class _ZeroRng:
    def random(self):
        return 0.0


def _policy(attempts=4, **kw):
    sleeps = []
    p = RetryPolicy(attempts=attempts, base_s=0.1, cap_s=0.4, jitter=0.0,
                    sleep=sleeps.append, rng=_ZeroRng(), **kw)
    return p, sleeps


def test_backoff_sequence_capped_exponential():
    p, _ = _policy()
    assert [p.delay_s(n) for n in (1, 2, 3, 4)] == [0.1, 0.2, 0.4, 0.4]


def test_jitter_scales_delay():
    class Half:
        def random(self):
            return 0.5

    p = RetryPolicy(attempts=2, base_s=0.1, cap_s=1.0, jitter=0.5,
                    sleep=lambda s: None, rng=Half())
    assert p.delay_s(1) == pytest.approx(0.1 * 1.25)


def test_retries_then_succeeds_counts_and_logs():
    p, sleeps = _policy()
    reg, sink = MetricsRegistry(), _Sink()
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("EIO simulated")
        return "ok"

    out = call_with_retry(flaky, what="unit test", policy=p, registry=reg,
                          sink=sink, echo=lambda m: None)
    assert out == "ok" and len(calls) == 3
    assert sleeps == [0.1, 0.2]
    assert reg.snapshot()["counters"]["io_retries"] == 2
    assert [r["kind"] for r in sink.records] == ["retry", "retry"]
    assert sink.records[0]["attempt"] == 1
    assert sink.records[0]["max_attempts"] == 4
    assert "EIO simulated" in sink.records[0]["error"]


def test_exhausted_attempts_reraise_original():
    p, sleeps = _policy(attempts=3)
    err = OSError("always down")

    def dead():
        raise err

    with pytest.raises(OSError) as ei:
        call_with_retry(dead, what="t", policy=p,
                        registry=MetricsRegistry(), sink=_Sink(),
                        echo=lambda m: None)
    assert ei.value is err
    assert len(sleeps) == 2  # attempts-1 backoffs, then the raise


@pytest.mark.parametrize("exc", [ValueError("garbage"),
                                 CorruptCheckpoint("crc mismatch")])
def test_non_transient_errors_never_retried(exc):
    """Garbage bytes must surface as corruption immediately — burning
    the retry budget on a deterministic failure masks the real event."""
    p, sleeps = _policy()
    calls = []

    def corrupt():
        calls.append(1)
        raise exc

    with pytest.raises(type(exc)):
        call_with_retry(corrupt, what="t", policy=p,
                        registry=MetricsRegistry(), sink=_Sink(),
                        echo=lambda m: None)
    assert len(calls) == 1 and sleeps == []


# ---- fault injector ----


def test_injector_inert_without_spec():
    inj = FaultInjector("")
    assert not inj.enabled("ckpt_write_fail")
    inj.fail("ckpt_write_fail")  # no-op
    assert inj.corrupt("read_corrupt", b"abc") == b"abc"
    assert inj.report() == {}


def test_injector_spec_parse_and_budget():
    inj = FaultInjector("ckpt_write_fail:p=1.0:n=2,data_read_fail:after=1",
                        seed=0)
    with pytest.raises(FaultInjected):
        inj.fail("ckpt_write_fail")
    with pytest.raises(FaultInjected):
        inj.fail("ckpt_write_fail")
    inj.fail("ckpt_write_fail")  # n=2 budget exhausted -> no-op
    inj.fail("data_read_fail")   # after=1 skips the first consult
    with pytest.raises(FaultInjected):
        inj.fail("data_read_fail")
    rep = inj.report()
    assert rep["ckpt_write_fail"] == {"consults": 3, "fired": 2}
    assert rep["data_read_fail"]["fired"] == 1
    # injected failures are OSError: the production retry path catches
    # them exactly like real EIO
    assert issubclass(FaultInjected, OSError)


def test_injector_corrupt_flips_one_byte_deterministically():
    data = bytes(range(64))
    got1 = FaultInjector("read_corrupt:p=1.0", seed=7).corrupt(
        "read_corrupt", data)
    got2 = FaultInjector("read_corrupt:p=1.0", seed=7).corrupt(
        "read_corrupt", data)
    assert got1 == got2 != data
    diff = [i for i in range(64) if got1[i] != data[i]]
    assert len(diff) == 1 and got1[diff[0]] == data[diff[0]] ^ 0xFF


def test_injector_probability_is_seeded():
    fires = [FaultInjector("x:p=0.5", seed=3).should_fire("x")
             for _ in range(1)]
    again = [FaultInjector("x:p=0.5", seed=3).should_fire("x")
             for _ in range(1)]
    assert fires == again
