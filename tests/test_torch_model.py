"""Unit tests for the PyTorch reference model (SURVEY.md §4, unit tier)."""

import math

import numpy as np
import torch

from model import GPT, GPTConfig

TINY = GPTConfig(
    block_size=32, vocab_size=65, n_layer=2, n_head=2, n_embd=64,
    dropout=0.0, bias=True,
)


def test_forward_shapes_and_loss():
    torch.manual_seed(0)
    model = GPT(TINY)
    x = torch.randint(0, 65, (3, 32))
    y = torch.randint(0, 65, (3, 32))
    logits, loss = model(x, y)
    assert logits.shape == (3, 32, 65)
    assert loss.ndim == 0
    # untrained loss should be ~ln(vocab)
    assert abs(loss.item() - math.log(65)) < 0.5


def test_inference_logits_last_position_only():
    torch.manual_seed(0)
    model = GPT(TINY).eval()
    x = torch.randint(0, 65, (2, 16))
    logits, loss = model(x)
    assert logits.shape == (2, 1, 65)
    assert loss is None


def test_weight_tying():
    model = GPT(TINY)
    assert model.lm_head.weight is model.transformer.wte.weight


def test_causality():
    """Changing a future token must not change past logits."""
    torch.manual_seed(0)
    model = GPT(TINY).eval()
    x1 = torch.randint(0, 65, (1, 16))
    x2 = x1.clone()
    x2[0, -1] = (x2[0, -1] + 1) % 65
    with torch.no_grad():
        l1, _ = model(x1, x1)
        l2, _ = model(x2, x2)
    assert torch.allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert not torch.allclose(l1[0, -1], l2[0, -1], atol=1e-5)


def test_optimizer_decay_split():
    model = GPT(TINY)
    opt = model.configure_optimizers(0.1, 1e-3, (0.9, 0.95), "cpu")
    assert len(opt.param_groups) == 2
    decay, nodecay = opt.param_groups
    assert decay["weight_decay"] == 0.1
    assert nodecay["weight_decay"] == 0.0
    for p in decay["params"]:
        assert p.dim() >= 2
    for p in nodecay["params"]:
        assert p.dim() < 2
    n_opt = sum(p.numel() for g in opt.param_groups for p in g["params"])
    n_model = sum(p.numel() for p in model.parameters())
    assert n_opt == n_model


def test_generate_extends_sequence():
    torch.manual_seed(0)
    model = GPT(TINY).eval()
    x = torch.randint(0, 65, (1, 4))
    y = model.generate(x, 8, temperature=1.0, top_k=10)
    assert y.shape == (1, 12)
    assert (y[:, :4] == x).all()


def test_training_reduces_loss():
    """A few steps of AdamW on a fixed batch must reduce the loss."""
    torch.manual_seed(0)
    model = GPT(TINY)
    opt = model.configure_optimizers(0.0, 1e-3, (0.9, 0.95), "cpu")
    x = torch.randint(0, 65, (8, 32))
    y = torch.roll(x, -1, dims=1)
    _, loss0 = model(x, y)
    for _ in range(20):
        opt.zero_grad()
        _, loss = model(x, y)
        loss.backward()
        opt.step()
    _, loss1 = model(x, y)
    assert loss1.item() < loss0.item() - 0.5


def test_mfu_positive():
    model = GPT(TINY)
    mfu = model.estimate_mfu(fwdbwd_per_iter=8, dt=0.1)
    assert 0 < mfu < 10  # sanity only; tiny model on the A100 denominator


def test_crop_block_size():
    model = GPT(TINY)
    model.crop_block_size(16)
    x = torch.randint(0, 65, (1, 16))
    logits, _ = model(x, x)
    assert logits.shape == (1, 16, 65)
