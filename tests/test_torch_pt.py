"""torch .pt container round-trip tests (SURVEY.md §4; §7 names
"T7 torch-.pt-without-torch" the highest-risk item). Real torch is
available in the sandbox, so both directions are tested against it."""

import collections
import os

import ml_dtypes
import numpy as np
import pytest
import torch

from avenir_tpu.checkpoint.torch_pt import BFLOAT16, load_pt, save_pt


@pytest.fixture()
def ckpt_obj():
    tied = np.random.randn(5, 3).astype(np.float32)
    return {
        "model": collections.OrderedDict([
            ("w", np.random.randn(4, 4).astype(np.float32)),
            ("b", np.arange(3, dtype=np.int64)),
            ("bf", np.random.randn(2, 2).astype(ml_dtypes.bfloat16)),
            ("tied_a", tied),
            ("tied_b", tied),
        ]),
        "iter_num": 123,
        "best_val_loss": 1.5,
        "config": {"lr": 3e-4, "name": "x", "flag": True, "none": None,
                   "lst": [1, 2.5, "s"], "tup": (1, 2, 3, 4),
                   "big": 2 ** 40},
    }


def test_our_writer_torch_reader(tmp_path, ckpt_obj):
    p = tmp_path / "ckpt.pt"
    save_pt(ckpt_obj, p)
    loaded = torch.load(p, map_location="cpu", weights_only=False)
    assert loaded["iter_num"] == 123
    assert loaded["best_val_loss"] == 1.5
    assert loaded["config"]["lst"] == [1, 2.5, "s"]
    assert loaded["config"]["big"] == 2 ** 40
    assert tuple(loaded["config"]["tup"]) == (1, 2, 3, 4)
    np.testing.assert_array_equal(
        loaded["model"]["w"].numpy(), ckpt_obj["model"]["w"]
    )
    np.testing.assert_array_equal(
        loaded["model"]["b"].numpy(), ckpt_obj["model"]["b"]
    )
    assert loaded["model"]["bf"].dtype == torch.bfloat16
    # tied tensors share one storage, exactly like torch's own save
    assert (loaded["model"]["tied_a"].data_ptr()
            == loaded["model"]["tied_b"].data_ptr())


def test_torch_writer_our_reader(tmp_path):
    obj = {
        "model": collections.OrderedDict([
            ("w", torch.randn(4, 4)),
            ("h", torch.randn(6).to(torch.bfloat16)),
            ("i", torch.arange(5)),
        ]),
        "iter_num": 7,
        "cfg": {"a": 1},
    }
    p = tmp_path / "t.pt"
    torch.save(obj, p)
    back = load_pt(p)
    np.testing.assert_array_equal(back["model"]["w"], obj["model"]["w"].numpy())
    assert back["model"]["h"].dtype == BFLOAT16
    assert back["iter_num"] == 7
    assert back["cfg"] == {"a": 1}


def test_self_round_trip(tmp_path, ckpt_obj):
    p = tmp_path / "ckpt.pt"
    save_pt(ckpt_obj, p)
    back = load_pt(p)
    np.testing.assert_array_equal(back["model"]["w"], ckpt_obj["model"]["w"])
    assert back["model"]["bf"].dtype == BFLOAT16
    assert back["config"]["tup"] == (1, 2, 3, 4)


def test_weights_only_load(tmp_path):
    """torch.load(weights_only=True) — the hardened loader — must accept
    a pure state_dict written by us."""
    sd = collections.OrderedDict(
        [("w", np.random.randn(3, 3).astype(np.float32))]
    )
    p = tmp_path / "sd.pt"
    save_pt(sd, p)
    loaded = torch.load(p, weights_only=True)
    np.testing.assert_array_equal(loaded["w"].numpy(), sd["w"])


def test_reader_rejects_unknown_globals(tmp_path):
    """Fail-loud policy: arbitrary callables must not unpickle."""
    import pickle as pkl
    import zipfile

    evil = b"\x80\x02cos\nsystem\nX\x04\x00\x00\x00echo\x85R."
    p = tmp_path / "evil.pt"
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("archive/data.pkl", evil)
        zf.writestr("archive/version", "3\n")
    with pytest.raises(pkl.UnpicklingError):
        load_pt(p)
