"""Speed-knob composition tests (ISSUE 18): spec decode × prefix
sharing × disaggregation × the KV CDN all stack, the n-gram self-draft
serves without a second model, and adaptive spec_k walks the compiled
k ladder without new traces. The oracle everywhere: greedy engine
output is BIT-identical to sequential `generate_cached` for ANY draft
— model or ngram — whatever other knobs are on; a desynced/garbage
draft costs speed, never correctness.

Budget notes (the test_serve_router discipline): one module-scoped
tiny GPT + one-shot references; the tier-1 set keeps engines small and
shares fixtures; full-stack router fleets + the process backend are
slow-marked."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import generate_cached
from avenir_tpu.infer.spec import ngram_propose, ngram_q_logits
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.serve import Engine, Router

GPT_TINY = GPTConfig(block_size=128, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
MAX_NEW = 4
PAGE = 4
# router fleets use the test_disagg geometry: a "long" prompt is ~2
# chunks, several exportable pages
RPAGE, RCHUNK = 8, 16
REKW = {"kv_impl": "paged", "page_size": RPAGE, "prefill_chunk": RCHUNK}


@pytest.fixture(scope="module")
def models():
    return (GPT(GPT_TINY, rngs=nnx.Rngs(0)),
            GPT(GPT_TINY, rngs=nnx.Rngs(5)))


def _greedy_reqs(model, rng, n, *, prefix=(), lo=3, hi=10, key_base=3000,
                 max_new=MAX_NEW):
    """n top_k=1 requests (optionally sharing `prefix`) with one-shot
    greedy references."""
    reqs = []
    for i in range(n):
        tail = [int(t) for t in rng.integers(0, 64, (int(rng.integers(
            lo, hi)),))]
        prompt = list(prefix) + tail
        kw = dict(prompt=prompt, max_new_tokens=max_new, temperature=1.0,
                  top_k=1, rng=jax.random.key(key_base + i))
        y = np.asarray(generate_cached(
            model, kw["rng"], jnp.asarray(prompt, jnp.int32)[None],
            max_new, temperature=1.0, top_k=1))[0]
        reqs.append((kw, [int(t) for t in y]))
    return reqs


def _run_all(engine, reqs, bursts):
    ids, results, pending = {}, {}, list(range(len(reqs)))
    bursts = list(bursts)
    while pending or engine.open_work:
        take = bursts.pop(0) if bursts else len(pending)
        for _ in range(min(take, len(pending))):
            i = pending.pop(0)
            kw, _ = reqs[i]
            ids[engine.submit(**kw)] = i
        for f in engine.step():
            results[ids[f.req_id]] = f
    return results


def _assert_parity(results, reqs):
    assert len(results) == len(reqs)
    for i, (kw, ref) in enumerate(reqs):
        got = results[i].tokens
        assert got == ref, f"request {i} diverged:\n ref {ref}\n got {got}"


def _submit_all(router, reqs):
    return {router.submit(**kw): ref for kw, ref in reqs}


def _assert_router_parity(done, refs):
    for f in done:
        assert f.tokens == refs[f.req_id], (
            f"request {f.req_id} diverged:\n ref {refs[f.req_id]}\n "
            f"got {f.tokens}")
        assert f.finish_reason == "length"


# ---------------------------------------------------------------------------
# host-side units: the n-gram proposer and its point-mass q
# ---------------------------------------------------------------------------


def test_ngram_propose_lookup_and_fallback():
    # suffix [1, 2] recurred at position 0 -> propose its continuation
    drafts, hit = ngram_propose([1, 2, 3, 1, 2], 2)
    assert (drafts, hit) == ([3, 1], True)
    # longest n wins: suffix [2, 3] (n=2) beats the n=1 match
    drafts, hit = ngram_propose([2, 3, 9, 2, 3], 1)
    assert (drafts, hit) == ([9], True)
    # most RECENT earlier occurrence wins when the n-gram repeats
    drafts, hit = ngram_propose([1, 5, 1, 7, 1], 1)
    assert (drafts, hit) == ([7], True)
    # a match whose continuation runs off the end pads with ctx[-1]
    drafts, hit = ngram_propose([4, 8, 4, 8], 3)
    assert hit is True and drafts == [4, 8, 8]
    # no recurrence -> last-token repeats, no hit
    drafts, hit = ngram_propose([1, 2, 3], 2)
    assert (drafts, hit) == ([3, 3], False)


def test_ngram_q_logits_is_point_mass():
    q = ngram_q_logits(jnp.asarray([[3, 7]], jnp.int32), 16)
    p = np.asarray(jax.nn.softmax(q, axis=-1))
    assert p.shape == (1, 2, 16)
    assert p[0, 0, 3] == pytest.approx(1.0)
    assert p[0, 1, 7] == pytest.approx(1.0)
    assert np.count_nonzero(p) == 2


def test_unknown_draft_model_string_fails_loud(models):
    model, _ = models
    with pytest.raises(ValueError, match="ngram"):
        Engine(model, n_slots=1, max_seq_len=32,
               registry=MetricsRegistry(), spec_decode="draft",
               draft_model="bogus")


# ---------------------------------------------------------------------------
# tier-1 compose smoke: spec × prefix sharing on one paged engine
# ---------------------------------------------------------------------------


def test_compose_smoke_spec_sharing_parity(models):
    """The CI compose cell: a paged engine with spec AND prefix sharing
    on serves 8 requests — half sharing a multi-page prefix — with
    greedy output bit-identical to `generate_cached`. The prefix HITS
    must actually happen, and the draft's catch-up chunks must fire
    (the draft-only re-prefill of the shared span)."""
    model, draft = models
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=4, max_seq_len=32, registry=reg,
                    kv_impl="paged", page_size=PAGE, prefill_chunk=8,
                    spec_decode="draft", spec_k=2, draft_model=draft)
    rng = np.random.default_rng(7)
    prefix = [int(t) for t in rng.integers(0, 64, (9,))]
    reqs = (_greedy_reqs(model, rng, 4, prefix=prefix, lo=2, hi=5)
            + _greedy_reqs(model, rng, 4, key_base=3100))
    results = _run_all(engine, reqs, bursts=[3, 2, 1, 2])
    _assert_parity(results, reqs)
    assert engine._paged.alloc.prefix_sharing is True
    assert engine._paged.alloc.prefix_hits >= 1, "no prefix hit landed"
    assert len(engine.traces["draft_prefill"]) >= 1, (
        "a prefix hit with spec on must run the draft-only catch-up "
        "chunk")
    assert len(engine.traces["step"]) <= len(engine._k_ladder)
    engine._paged.audit(expect_empty=True)


# ---------------------------------------------------------------------------
# the n-gram self-draft: parity, zero model-draft state, obs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kv_impl", ["slab", "paged"])
def test_ngram_greedy_parity_both_layouts(models, kv_impl):
    """draft_model='ngram' serves greedy output bit-identical to
    `generate_cached` on both KV layouts with NO second model: no draft
    pool, no draft state, zero model-draft traces — and under the paged
    layout prefix sharing stays on and hits compose for free."""
    model, _ = models
    reg = MetricsRegistry()
    kw = ({"kv_impl": "paged", "page_size": PAGE, "prefill_chunk": 8}
          if kv_impl == "paged" else {})
    engine = Engine(model, n_slots=4, max_seq_len=32, registry=reg,
                    spec_decode="draft", spec_k=3, draft_model="ngram",
                    **kw)
    rng = np.random.default_rng(11)
    prefix = [int(t) for t in rng.integers(0, 64, (9,))]
    reqs = (_greedy_reqs(model, rng, 3, prefix=prefix, lo=2, hi=5,
                         key_base=4000)
            + _greedy_reqs(model, rng, 3, key_base=4100))
    results = _run_all(engine, reqs, bursts=[2, 2, 1])
    _assert_parity(results, reqs)
    # draft-free means draft-free: no pool, no split state, no traces
    assert engine._dstate is None and engine._dgraphdef is None
    assert engine._dpool is None
    assert engine.traces["draft_prefill"] == []
    assert len(engine.traces["seed"]) == 1
    assert len(engine.traces["step"]) <= len(engine._k_ladder)
    snap = reg.snapshot()["counters"]
    assert "ngram_hits" in snap        # registered at construction
    if kv_impl == "paged":
        assert engine._paged.alloc.prefix_sharing is True
        assert engine._paged.alloc.prefix_hits >= 1
        engine._paged.audit(expect_empty=True)


def test_ngram_sampled_distribution_matches_sequential(models):
    """Distribution exactness for the point-mass q: ngram-drafted
    sampled emissions match the sequential engine's frequencies
    (TV-bounded like the model-draft pin; the first token is
    bit-identical by construction — seeded from the prefill logits
    with the sequential rng split)."""
    model, _ = models
    V, N, TOPK = 64, 192, 4
    prompt = [3, 1, 4, 1, 5]
    seq_eng = Engine(model, n_slots=8, max_seq_len=32,
                     registry=MetricsRegistry())
    ng_eng = Engine(model, n_slots=8, max_seq_len=32,
                    registry=MetricsRegistry(), spec_decode="draft",
                    spec_k=2, draft_model="ngram")

    def collect(eng):
        ids = {}
        for i in range(N):
            ids[eng.submit(prompt, max_new_tokens=3, temperature=1.0,
                           top_k=TOPK, rng=jax.random.key(9000 + i))] = i
        out = {}
        while eng.open_work:
            for f in eng.step():
                out[ids[f.req_id]] = f.tokens[len(prompt):]
        return [out[i] for i in range(N)]

    seq, ng = collect(seq_eng), collect(ng_eng)
    # position 0: bit-identical (same key split, same prefill logits)
    assert [s[0] for s in seq] == [s[0] for s in ng]
    for pos in (1, 2):
        a = np.bincount([s[pos] for s in seq], minlength=V) / N
        b = np.bincount([s[pos] for s in ng], minlength=V) / N
        assert 0.5 * np.abs(a - b).sum() < 0.2, f"position {pos} drifted"


def test_report_accept_line_names_draft_source_and_k_eff():
    """obs_report's accept: line grows the draft source and the
    effective depth — `ngram_hits` presence (registered at engine
    construction) names the source, `spec_k_effective` the depth."""
    from avenir_tpu.obs.report import format_report, summarize

    def mk(counters, gauges):
        return [
            {"kind": "run_meta", "t": 1.0, "model_type": "gpt"},
            {"kind": "request", "t": 1.5, "id": 0, "n_prompt": 3,
             "n_out": 4, "finish_reason": "length", "ttft_ms": 1.0,
             "tpot_ms": 0.5},
            {"kind": "run_end", "t": 2.0, "counters": counters,
             "gauges": gauges},
        ]

    rep = format_report(summarize(mk(
        {"spec_proposed": 40.0, "spec_accepted": 30.0, "ngram_hits": 7.0},
        {"spec_k_effective": 2.5})))
    assert "ngram draft (7 lookup hits)" in rep
    assert "k_eff 2.5" in rep
    rep = format_report(summarize(mk(
        {"spec_proposed": 40.0, "spec_accepted": 30.0},
        {"spec_k_effective": 4.0})))
    assert "model draft" in rep and "ngram" not in rep


# ---------------------------------------------------------------------------
# adaptive spec_k: the EWMA rung walk + the no-retrace pin
# ---------------------------------------------------------------------------


def test_adaptive_k_walks_down_and_never_retraces(models):
    """spec_k='auto' against an adversarial (independent random) draft:
    greedy accept is near zero, so the per-request EWMA walks every
    slot down the k ladder to the floor (k=1 — speculation never turns
    off). Every rung is a pre-declared bucket: the step-trace count is
    bounded by the ladder, and a SECOND wave of requests compiles
    NOTHING new (zero steady-state traces)."""
    model, draft = models
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=3, max_seq_len=48, registry=reg,
                    spec_decode="draft", spec_k="auto",
                    draft_model=draft)
    assert engine.spec_k_auto and engine._k_ladder == (1, 2, 4)
    rng = np.random.default_rng(13)
    reqs = _greedy_reqs(model, rng, 3, key_base=5000, max_new=12)
    results = _run_all(engine, reqs, bursts=[3])
    _assert_parity(results, reqs)
    n_traces = len(engine.traces["step"])
    assert n_traces <= len(engine._k_ladder)
    # the collapsed accept rate walked the fleet down the ladder
    assert reg.snapshot()["gauges"]["spec_k_effective"] <= 2.0, (
        "adaptive k never shrank against a draft with ~zero greedy "
        "accept")
    # steady state: a fresh wave re-walks the SAME rungs — zero compiles
    reqs2 = _greedy_reqs(model, rng, 3, key_base=5100, max_new=12)
    results = _run_all(engine, reqs2, bursts=[3])
    _assert_parity(results, reqs2)
    assert len(engine.traces["step"]) == n_traces, (
        "adaptive k retraced at steady state")


def test_spec_k_auto_rides_the_worker_kwarg_filter(models):
    """spec_k='auto' is a string: it must survive the process worker's
    hello kwarg filter and the router's engine_kwargs plumbing — pinned
    cheaply at the Engine ctor (the hello IS the ctor)."""
    model, draft = models
    engine = Engine(model, n_slots=1, max_seq_len=32,
                    registry=MetricsRegistry(), spec_decode="draft",
                    spec_k="auto", draft_model=draft)
    assert engine.spec_k == 4 and engine.spec_k_auto


# ---------------------------------------------------------------------------
# draft desync injection: a wrong draft NEVER costs correctness
# ---------------------------------------------------------------------------


def test_draft_desync_injection_keeps_greedy_parity(models):
    """Mid-flight, scribble garbage over the ENTIRE draft KV slab (the
    desync a lost page-transfer or stale splice would cause): proposals
    collapse, greedy output stays bit-identical — the verify step only
    ever trusts the target."""
    model, draft = models
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(), spec_decode="draft",
                    spec_k=2, draft_model=draft)
    rng = np.random.default_rng(17)
    reqs = _greedy_reqs(model, rng, 2, key_base=6000, max_new=8)
    ids = {engine.submit(**kw): i for i, (kw, _) in enumerate(reqs)}
    results = {}
    for f in engine.step():          # admission + first verify tick
        results[ids[f.req_id]] = f
    engine._dpool = engine._dpool._replace(
        k=jnp.full_like(engine._dpool.k, 3.0),
        v=jnp.full_like(engine._dpool.v, -3.0))
    while engine.open_work:
        for f in engine.step():
            results[ids[f.req_id]] = f
    _assert_parity(results, reqs)


def test_ngram_ctx_desync_keeps_greedy_parity(models):
    """Same contract for the self-draft: corrupt every live request's
    lookup context mid-flight — proposals go garbage, emissions stay
    bit-identical (the ctx feeds ONLY the proposer, never the output
    stream)."""
    model, _ = models
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(), spec_decode="draft",
                    spec_k=2, draft_model="ngram")
    rng = np.random.default_rng(19)
    reqs = _greedy_reqs(model, rng, 2, key_base=6100, max_new=8)
    ids = {engine.submit(**kw): i for i, (kw, _) in enumerate(reqs)}
    results = {}
    for f in engine.step():
        results[ids[f.req_id]] = f
    for live in engine._live.values():
        live.ctx[:] = [1] * len(live.ctx)
    while engine.open_work:
        for f in engine.step():
            results[ids[f.req_id]] = f
    _assert_parity(results, reqs)


# ---------------------------------------------------------------------------
# the full stack: spec × sharing × disagg × affinity, both backends
# ---------------------------------------------------------------------------


def _mk_fleet_reqs(model, rng, n, *, prefix, key_base=7000):
    """Mixed fleet load: every other request is LONG (>= RCHUNK, so it
    disagg-handoffs) and shares `prefix` (so affinity/pull engage);
    the rest are short decode-class requests."""
    reqs = []
    for i in range(n):
        if i % 2 == 0:
            tail = [int(t) for t in rng.integers(0, 64, (
                int(rng.integers(3, 8)),))]
            prompt = list(prefix) + tail
        else:
            prompt = [int(t) for t in rng.integers(0, 64, (
                int(rng.integers(3, 9)),))]
        key = jax.random.key(key_base + i)
        y = np.asarray(generate_cached(
            model, key, jnp.asarray(prompt, jnp.int32)[None], MAX_NEW,
            temperature=1.0, top_k=1))[0]
        reqs.append((dict(prompt=prompt, max_new_tokens=MAX_NEW,
                          temperature=1.0, top_k=1, rng=key),
                     [int(t) for t in y]))
    return reqs


@pytest.mark.slow
@pytest.mark.parametrize("draft_kind", ["model", "ngram"])
def test_compose_full_stack_inproc_parity(models, draft_kind):
    """THE composition oracle: spec decode + prefix sharing + disagg +
    the KV CDN (affinity routing) ALL on, randomized arrivals, greedy
    output bit-identical to one-shot generation for BOTH draft kinds.
    Handoffs must actually happen (long prompts splice prefill-class
    chains into decode-class pools and the draft seeds from the
    shipped prompt)."""
    model, draft = models
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, n_prefill=1,
                    cache_telescope=True, affinity=True,
                    draft_model=(draft if draft_kind == "model"
                                 else "ngram"),
                    engine_kwargs=dict(REKW, spec_decode="draft",
                                       spec_k=2))
    rng = np.random.default_rng(23)
    prefix = [int(t) for t in rng.integers(0, 64, (34,))]
    reqs = _mk_fleet_reqs(model, rng, 6, prefix=prefix)
    refs = {}
    done = []
    for i, (kw, ref) in enumerate(reqs):    # randomized arrivals
        refs[router.submit(**kw)] = ref
        if i % 2 == 1:
            done.extend(router.step())
    done.extend(router.drain())
    assert len(done) == len(reqs)
    _assert_router_parity(done, refs)
    counters = reg.snapshot()["counters"]
    assert counters["kv_transfers"] >= 1, "no disagg handoff happened"
    assert counters["spec_proposed"] > 0, "spec never ran on the fleet"
    # every terminal record comes from a DECODE replica (0 is prefill)
    assert all(f.replica != 0 for f in done)
    router.close()


@pytest.mark.slow
def test_compose_sigkill_mid_splice_inproc(models):
    """A prefill-class replica dies AFTER pages shipped, mid-splice,
    with spec + sharing + affinity on: the requests requeue, re-prefill
    from prompt+rng on the decode class, and every output is
    bit-identical — spec state (draft pool, k_eff EWMA) resets with the
    re-prefill and re-adapts."""
    model, draft = models
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, n_prefill=1,
                    cache_telescope=True, affinity=True,
                    draft_model=draft,
                    engine_kwargs=dict(REKW, spec_decode="draft",
                                       spec_k=2))
    rng = np.random.default_rng(29)
    prefix = [int(t) for t in rng.integers(0, 64, (34,))]
    reqs = _mk_fleet_reqs(model, rng, 4, prefix=prefix, key_base=7500)
    refs = _submit_all(router, reqs)
    done = []
    for _ in range(2):
        done.extend(router.step())
    exported = reg.snapshot()["counters"].get("kv_pages_exported", 0)
    assert exported > 0, "the kill must land MID-transfer"
    router.kill_replica(0)
    done.extend(router.drain())
    assert len(done) == len(reqs)
    _assert_router_parity(done, refs)
    assert reg.snapshot()["counters"]["serve_failovers"] >= 1
    assert not router._transfer, "transfer state leaked past failover"
    router.close()


@pytest.mark.slow
def test_compose_full_stack_process_backend(models):
    """The process-backend twin: REAL worker processes with spec +
    sharing + disagg + affinity on and the n-gram self-draft (no draft
    weights in any hello), plus a REAL SIGKILL to the prefill-class
    worker mid-stream — parity holds end to end."""
    model, _ = models
    reg = MetricsRegistry()
    router = Router(model, backend="process", n_replicas=3, n_slots=2,
                    max_seq_len=64, registry=reg, seed=0, n_prefill=1,
                    cache_telescope=True, affinity=True,
                    draft_model="ngram", supervise=False,
                    engine_kwargs=dict(REKW, spec_decode="draft",
                                       spec_k=2))
    try:
        rng = np.random.default_rng(31)
        prefix = [int(t) for t in rng.integers(0, 64, (34,))]
        reqs = _mk_fleet_reqs(model, rng, 4, prefix=prefix,
                              key_base=7800)
        refs = _submit_all(router, reqs)
        done = []
        for _ in range(2):
            done.extend(router.step())
        os.kill(router.replicas[0].pid, signal.SIGKILL)
        done.extend(router.drain())
        assert len(done) == len(reqs)
        _assert_router_parity(done, refs)
        counters = reg.snapshot()["counters"]
        assert counters["spec_proposed"] > 0
    finally:
        router.close()
