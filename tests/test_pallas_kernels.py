"""Pallas kernel vs jnp-oracle tests (SURVEY.md §4 "Unit: kernels"):
interpret=True runs the kernels on CPU with identical semantics to the
Mosaic compilation, so fwd AND grads are checked without TPU hardware."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import importlib

# the package __init__ re-exports the function under the module's name, so a
# plain `import ... as` would bind the function; importlib gets the module
_fa_mod = importlib.import_module("avenir_tpu.ops.pallas.flash_attention")

from avenir_tpu.ops.attention import causal_attention_reference
from avenir_tpu.ops.pallas.flash_attention import flash_attention
from avenir_tpu.ops.pallas.rmsnorm import rmsnorm_pallas
from avenir_tpu.ops.rmsnorm import rmsnorm_reference


@pytest.fixture(params=["fast", "blocked"])
def fa_path(request, monkeypatch):
    """Run flash-attention tests on both dispatch paths: the single-KV-block
    fast path and the online-softmax blocked path (normally long-T only)."""
    if request.param == "blocked":
        monkeypatch.setattr(_fa_mod, "_FAST_PATH_MAX_T", 0)
    return request.param


def _qkv(B=2, T=128, H=2, D=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (B, T, H, D)
    q = jax.random.normal(ks[0], shape, dtype)
    k = jax.random.normal(ks[1], shape, dtype)
    v = jax.random.normal(ks[2], shape, dtype)
    return q, k, v


@pytest.mark.parametrize("T,block", [(128, 64), (96, 64), (256, 128)])
def test_flash_attention_forward(T, block, fa_path):
    q, k, v = _qkv(T=T)
    out = flash_attention(q, k, v, causal=True, block_q=block, block_k=block,
                          interpret=True)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("bq,bk", [(64, 64), (64, 128), (128, 64)])
def test_flash_attention_grads(bq, bk, fa_path):
    q, k, v = _qkv(T=128)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk,
                            interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = causal_attention_reference(q, k, v)
        return jnp.sum(o * o)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


@pytest.mark.parametrize("H,H_kv", [(4, 1), (4, 2), (6, 3)])
def test_flash_attention_gqa_unrepeated_kv(H, H_kv, fa_path):
    """GQA: the kernels take (B, T, H_kv, D) K/V directly — shared-head
    index maps, grouped dk/dv accumulation — and must match the oracle on
    repeated KV for both fwd and grads (VERDICT r2 item 2)."""
    ks = jax.random.split(jax.random.key(3), 3)
    B, T, D = 2, 128, 64
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, H_kv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, H_kv, D), jnp.float32)
    rep = H // H_kv

    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = causal_attention_reference(q, jnp.repeat(k, rep, axis=2),
                                     jnp.repeat(v, rep, axis=2))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                            interpret=True)
        return jnp.sum(o * o)

    def loss_ref(q, k, v):
        o = causal_attention_reference(q, jnp.repeat(k, rep, axis=2),
                                       jnp.repeat(v, rep, axis=2))
        return jnp.sum(o * o)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        assert gf.shape == gr.shape, f"d{name} shape"
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_attention_bf16_close_to_fp32_oracle():
    q, k, v = _qkv(T=128, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = causal_attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32)
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )


@pytest.mark.parametrize("D", [64, 128])
def test_flash_attention_default_blocks_odd_seq(fa_path, D):
    """Regression: with the production default block sizes and a sequence
    length in (block_q, block_k) — e.g. 600 — every q row must be written
    (round-2 bug: Tp was not padded to a multiple of both block sizes, so
    rows past nq*block_q came back uninitialized/NaN). D=128 additionally
    exercises the D-adaptive 256-row default branch."""
    q, k, v = _qkv(B=1, T=600, H=1, D=D)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_padding_mask():
    """T not a multiple of the block: padded kv columns must not leak."""
    q, k, v = _qkv(T=100)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                          interpret=True)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_rmsnorm_forward_and_grads():
    key = jax.random.key(0)
    x = jax.random.normal(key, (4, 96, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(1), (64,)) * 0.1 + 1.0

    out = rmsnorm_pallas(x, w, interpret=True)
    ref = rmsnorm_reference(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-6, rtol=1e-6)

    def loss_k(x, w):
        return jnp.sum(jnp.sin(rmsnorm_pallas(x, w, interpret=True)))

    def loss_r(x, w):
        return jnp.sum(jnp.sin(rmsnorm_reference(x, w)))

    gx_k, gw_k = jax.grad(loss_k, argnums=(0, 1))(x, w)
    gx_r, gw_r = jax.grad(loss_r, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_k), np.asarray(gx_r),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_k), np.asarray(gw_r),
                               atol=1e-5, rtol=1e-5)
