"""int8 quantized-training tests (ISSUE 15 tentpole): op-level bounds,
STE gradient sanity, the loss-trajectory parity acceptance (int8 tracks
bf16 over 128 steps on the tiny-GPT config, CPU blocked-oracle path),
bit-identical step purity (the kill-resume contract under the knob),
no-retrace ledger pins, and pallas int8-stripe vs blocked fake-quant
oracle parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.ops.quant import (
    Q_MAX,
    SCALE_FLOOR,
    audit_quantization,
    dequantize,
    fake_quant,
    int8_matmul,
    matmul_bits,
    quantize_channelwise,
    quantize_tensorwise,
    resolve_compute_dtype,
)

# ---------------------------------------------------------------------------
# op level
# ---------------------------------------------------------------------------


def test_roundtrip_error_bounded_by_half_scale(rng):
    x = jnp.asarray(rng.normal(size=(16, 64)).astype(np.float32))
    q, s = quantize_channelwise(x, -1)
    back = dequantize(q, s, -1)
    err = np.abs(np.asarray(back) - np.asarray(x))
    assert (err <= np.asarray(s)[:, None] / 2 + 1e-7).all()
    # per-channel: each row's max maps to +-127 exactly
    assert np.abs(np.asarray(q)).max() == 127


def test_zero_channel_scale_floor_and_exact_zeros():
    x = jnp.zeros((4, 8))
    q, s = quantize_channelwise(x, -1)
    assert np.allclose(np.asarray(s), SCALE_FLOOR / Q_MAX)
    assert np.asarray(dequantize(q, s, -1)).sum() == 0.0


def test_tensorwise_is_one_scale():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)) * 100)
    q, s = quantize_tensorwise(x)
    assert np.ndim(np.asarray(s)) == 0
    assert np.abs(np.asarray(q)).max() == 127


def test_int8_matmul_forward_matches_dequantized_reference(rng):
    x = jnp.asarray(rng.normal(size=(3, 5, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    y = int8_matmul(x, w)
    qx, sx = quantize_channelwise(x, -1)
    qw, sw = quantize_channelwise(w, 0)
    ref = dequantize(qx, sx, -1) @ dequantize(qw, sw, 0)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # and the quantized grid is CLOSE to the dense product (absmax bound)
    dense = np.asarray(x) @ np.asarray(w)
    assert np.abs(np.asarray(y) - dense).max() < 0.15 * np.abs(dense).max()


def test_int8_matmul_oi_layout_matches_io_on_transpose(rng):
    x = jnp.asarray(rng.normal(size=(4, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(int8_matmul(x, w, w_layout="io")),
        np.asarray(int8_matmul(x, w.T, w_layout="oi")),
        rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("scaling", ["delayed", "dynamic"])
def test_int8_matmul_ste_gradients_track_dense(rng, scaling):
    """STE backward: grads of the quantized matmul must be close to the
    dense matmul's grads (the quantization error is bounded, and round
    is identity-through). Both backward calibration modes."""
    x = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))

    def f(fn):
        return jax.grad(lambda a, b: jnp.sum(jnp.sin(fn(a, b))),
                        argnums=(0, 1))(x, w)

    gx_q, gw_q = f(lambda a, b: int8_matmul(a, b, scaling=scaling))
    gx_d, gw_d = f(lambda a, b: a @ b)
    for gq, gd in ((gx_q, gx_d), (gw_q, gw_d)):
        gq, gd = np.asarray(gq), np.asarray(gd)
        denom = np.abs(gd).max() + 1e-9
        assert np.abs(gq - gd).max() / denom < 0.1, (
            np.abs(gq - gd).max() / denom)


def test_int8_matmul_vmaps_like_the_expert_stack(rng):
    """The Mixtral experts path: vmap over the stacked E axis of both
    operands, forward AND grad (custom_vjp batching)."""
    x = jnp.asarray(rng.normal(size=(4, 6, 16)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(4, 16, 8)).astype(np.float32))
    mm = jax.vmap(lambda a, b: int8_matmul(a, b))
    y = mm(x, w)
    for e in range(4):
        np.testing.assert_allclose(
            np.asarray(y[e]), np.asarray(int8_matmul(x[e], w[e])),
            rtol=1e-6, atol=1e-6)
    g = jax.grad(lambda a, b: jnp.sum(mm(a, b)), argnums=1)(x, w)
    assert np.isfinite(np.asarray(g)).all() and np.abs(np.asarray(g)).sum() > 0


def test_fake_quant_is_ste_and_lands_on_grid(rng):
    w = jnp.asarray(rng.normal(size=(8, 16)).astype(np.float32))
    wq = fake_quant(w, 1)
    q, s = quantize_channelwise(w, 1)
    np.testing.assert_allclose(np.asarray(wq),
                               np.asarray(dequantize(q, s, 1)), rtol=1e-6)
    g = jax.grad(lambda a: jnp.sum(fake_quant(a, 1) * 3.0))(w)
    np.testing.assert_allclose(np.asarray(g), 3.0)  # identity-through


def test_resolvers_and_bits():
    assert resolve_compute_dtype("int8") == "int8"
    assert resolve_compute_dtype("bfloat16") == "bf16"
    assert matmul_bits("int8") == 8
    assert matmul_bits("bfloat16") == 16
    assert matmul_bits("float32") == 32


def test_audit_counts_floor_channels_and_bumps_counter():
    from avenir_tpu.obs.metrics import get_registry, reset_registry

    reset_registry()
    arrs = [("a/kernel", np.random.default_rng(0).normal(size=(4, 8))),
            ("b/kernel", np.zeros((3, 8))),  # 3 dead channels (last-axis
                                             # reduce -> per-row scales)
            ("c/scale", np.zeros((8,)))]     # vector: structurally skipped
    out = audit_quantization(arrs)
    assert out == {"a/kernel": 0, "b/kernel": 3}
    assert get_registry().counter("quant_scale_clip").total == 3
    reset_registry()


# ---------------------------------------------------------------------------
# the trajectory-parity acceptance (tiny-GPT, 128 steps, blocked oracle)
# ---------------------------------------------------------------------------

# THE documented tolerance budget (docs/PERFORMANCE.md "Past the bf16
# plateau"): per-channel absmax int8 perturbs each matmul by ~0.4% of
# its dynamic range; over 128 optimizer steps of the tiny-GPT config the
# measured trajectory gap stays ~3e-3 peak / ~2e-4 final (both orders of
# magnitude inside the band). The band is deliberately loose enough to
# survive XLA re-lowerings and tight enough that a broken STE (gradient
# mis-scaled by even 10%) blows through it within 20 steps.
PARITY_MAX_ABS = 0.05
PARITY_FINAL_ABS = 0.02
PARITY_STEPS = 128


def _parity_data(steps, B=2, T=16, vocab=64, seed=0):
    """Learnable synthetic stream (noisy periodic tokens): loss must FALL
    well below ln(vocab) so the parity claim covers a moving trajectory,
    not a flat one."""
    rng = np.random.default_rng(seed)
    base = np.arange(steps * B * (T + 1)) % 7
    toks = (base * 9 + rng.integers(0, 2, base.shape)) % vocab
    toks = toks.reshape(steps, 1, B, T + 1)
    return toks[..., :-1].astype(np.int32), toks[..., 1:].astype(np.int32)


def _train_tiny_gpt(compute_dtype, steps=PARITY_STEPS):
    """One jitted multi-step dispatch of the tiny-GPT config over the
    blocked CE tail — the CPU oracle path the acceptance names."""
    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_multi_train_step, make_step_fns

    cfg = GPTConfig(block_size=16, vocab_size=64, n_layer=1, n_head=2,
                    n_embd=32, dropout=0.0, bias=True,
                    compute_dtype=compute_dtype, attn_impl="xla",
                    loss_impl="blocked")
    m = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(m, nnx.Param)
    tx, _ = make_optimizer(params, learning_rate=3e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=10, lr_decay_iters=200,
                           min_lr=3e-4)
    opt = jax.jit(tx.init)(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    step = jit_multi_train_step(step_fn, tx)
    xs, ys = _parity_data(steps)
    p, o, mtr = step(params, opt, jax.random.key(0), jnp.asarray(xs),
                     jnp.asarray(ys))
    return np.asarray(mtr["loss"]), jax.tree.map(np.asarray, p)


@pytest.fixture(scope="module")
def parity_runs():
    """Both 128-step trajectories, built once for the module (the PR 10
    warmed-fixture idiom: the two ~3s compiles charge setup, and every
    assertion below reads the same runs)."""
    lb, pb = _train_tiny_gpt("bfloat16")
    li, pi = _train_tiny_gpt("int8")
    return {"bf16": (lb, pb), "int8": (li, pi)}


def test_int8_loss_trajectory_tracks_bf16(parity_runs):
    """THE acceptance pin: int8 training tracks the bf16 loss curve
    within the documented tolerance band over >=128 steps, and both
    curves actually LEARN (final loss far below the ln(64) start)."""
    lb, _ = parity_runs["bf16"]
    li, _ = parity_runs["int8"]
    assert len(lb) == PARITY_STEPS
    d = np.abs(lb - li)
    assert d.max() <= PARITY_MAX_ABS, (d.max(), d.argmax())
    assert d[-1] <= PARITY_FINAL_ABS, d[-1]
    assert lb[-1] < 1.2 and li[-1] < 1.2, (lb[-1], li[-1])
    assert lb[0] > 3.5  # started near ln(64): the curve moved


@pytest.fixture(scope="module")
def resume_win():
    """Warmed int8 windowed-step closure + state for the resume pin
    (compile charges setup — the PR 10 warmed-fixture idiom)."""
    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_windowed_train_step, make_step_fns

    cfg = GPTConfig(block_size=8, vocab_size=32, n_layer=1, n_head=2,
                    n_embd=16, dropout=0.0, bias=True,
                    compute_dtype="int8", attn_impl="xla",
                    loss_impl="blocked")
    m = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params0 = nnx.split(m, nnx.Param)
    tx, _ = make_optimizer(params0, learning_rate=3e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=2, lr_decay_iters=20, min_lr=3e-4)
    opt0 = jax.jit(tx.init)(params0)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    win = jit_windowed_train_step(step_fn, tx)
    xs, ys = _parity_data(8, T=8, vocab=32, seed=3)
    # warm the ONE window-length compile (state not donated from these
    # throwaway copies' originals: fresh trees below)
    _ = win(jax.tree.map(jnp.array, params0),
            jax.tree.map(jnp.array, opt0), jax.random.key(7), 0,
            jnp.asarray(xs[:4]), jnp.asarray(ys[:4]))
    return dict(win=win, params0=jax.tree.map(np.asarray, params0),
                opt0=jax.tree.map(np.asarray, opt0), xs=xs, ys=ys)


def test_int8_step_is_pure_and_resume_bit_identical(resume_win):
    """The BENCH_chaos contract under the knob: the int8 step is a pure
    function of (params, opt, rng, batch) — running two 4-step windows
    with a host round-trip of the state between them (the resume shape)
    reproduces the uninterrupted pair BIT-identically."""
    win, xs, ys = resume_win["win"], resume_win["xs"], resume_win["ys"]
    params0, opt0 = resume_win["params0"], resume_win["opt0"]
    key = jax.random.key(7)

    def host(t):  # the resume round-trip: device -> numpy -> device
        return jax.tree.map(lambda a: jnp.asarray(np.asarray(a)), t)

    def run(round_trip):
        """Two 4-step windows; `round_trip` bounces the state through
        host numpy between them (the restore shape). One compiled
        window length either way — windowed==single equivalence is
        already pinned generically by test_train_tpu."""
        p, o = host(params0), host(opt0)
        p, o, m1 = win(p, o, key, 0, jnp.asarray(xs[:4]),
                       jnp.asarray(ys[:4]))
        if round_trip:
            p, o = host(p), host(o)  # "kill" + restore
        p, o, m2 = win(p, o, key, 4, jnp.asarray(xs[4:]),
                       jnp.asarray(ys[4:]))
        losses = np.concatenate([np.asarray(m1["loss"]),
                                 np.asarray(m2["loss"])])
        return losses, jax.tree.map(np.asarray, p)

    la, pa = run(round_trip=False)
    lb, pb = run(round_trip=True)
    np.testing.assert_array_equal(la, lb)
    for a, b in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
        np.testing.assert_array_equal(a, b)


def test_no_retrace_and_bf16_never_touches_the_quant_ledger(parity_runs):
    """No-retrace pins over the quant trace ledger: (1) a second
    identical int8 dispatch adds ZERO traces (steady state never
    retraces); (2) a bf16 model adds zero quant traces; (3) flipping the
    knob to int8 adds exactly one compile's worth of traces — the trace
    delta of the flip is the new jit, nothing else."""
    from avenir_tpu.ops import quant

    from avenir_tpu.models.gpt import GPT, GPTConfig

    def logits_fn(compute_dtype):
        cfg = GPTConfig(block_size=16, vocab_size=64, n_layer=1, n_head=2,
                        n_embd=32, dropout=0.0, bias=True,
                        compute_dtype=compute_dtype, attn_impl="xla")
        m = GPT(cfg, rngs=nnx.Rngs(0))
        gd, p = nnx.split(m, nnx.Param)
        f = jax.jit(lambda pp, x: nnx.merge(gd, pp)(x)[0])
        x = jnp.zeros((2, 16), jnp.int32)
        return f, p, x

    f16, p16, x = logits_fn("bfloat16")
    before = quant.trace_count()
    f16(p16, x)
    assert quant.trace_count() == before, "bf16 path touched the ledger"

    f8, p8, _ = logits_fn("int8")
    f8(p8, x)
    first_compile = quant.trace_count() - before
    assert first_compile > 0
    f8(p8, x)  # steady state: same shapes, no retrace
    assert quant.trace_count() == before + first_compile
    # flipping the knob again (a second int8 jit of the same shape)
    # adds exactly the one compile's traces — no hidden extras
    f8b, p8b, _ = logits_fn("int8")
    f8b(p8b, x)
    assert quant.trace_count() == before + 2 * first_compile


# ---------------------------------------------------------------------------
# fused CE: blocked fake-quant oracle vs pallas int8 stripes
# ---------------------------------------------------------------------------


def _ce_case(rng, B=2, T=12, C=16, V=40):
    x = jnp.asarray(rng.normal(size=(B, T, C)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, V, (B, T)).astype(np.int32))
    y = y.at[0, 0].set(-1)  # an ignore_index row rides along
    return x, y


@pytest.mark.parametrize("w_layout", ["cv", "vc"])
def test_pallas_int8_stripes_match_blocked_fake_quant_oracle(rng, w_layout):
    """The kernels consuming int8 weight stripes (fused dequant) must
    reproduce the blocked STE fake-quant oracle: same grid, same loss,
    same dx/dw — tight tolerance, both weight layouts."""
    from avenir_tpu.ops.fused_ce import _blocked_ce
    from avenir_tpu.ops.pallas.fused_ce import fused_ce_pallas

    x, y = _ce_case(rng)
    V, C = 40, 16
    w = jnp.asarray(rng.normal(
        size=(C, V) if w_layout == "cv" else (V, C)).astype(np.float32))

    def blocked(xx, ww):
        return _blocked_ce(xx, ww, y, ignore_index=-1, w_layout=w_layout,
                           t_chunk=4, w_dtype="int8")

    def pallas(xx, ww):
        return fused_ce_pallas(xx, ww, y, ignore_index=-1,
                               w_layout=w_layout, interpret=True,
                               w_dtype="int8")

    lb, (gxb, gwb) = jax.value_and_grad(blocked, argnums=(0, 1))(x, w)
    lp, (gxp, gwp) = jax.value_and_grad(pallas, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(float(lb), float(lp), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gxb), np.asarray(gxp),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gwb), np.asarray(gwp),
                               rtol=2e-4, atol=2e-5)


def test_int8_ce_close_to_dense_and_reference_tail_matches_blocked(rng):
    """Weight-only int8 CE stays close to the dense CE (error budget),
    and the models' reference fake-quant tail IS the blocked tail's
    numerics (same grid → near-exact agreement)."""
    from avenir_tpu.models.common import cross_entropy_loss
    from avenir_tpu.ops.fused_ce import _blocked_ce

    x, y = _ce_case(rng)
    w = jnp.asarray(rng.normal(size=(40, 16)).astype(np.float32))  # vc
    dense = cross_entropy_loss(jnp.einsum("btc,vc->btv", x, w), y,
                               ignore_index=-1)
    blocked_q = _blocked_ce(x, w, y, ignore_index=-1, w_layout="vc",
                            t_chunk=4, w_dtype="int8")
    ref_q = cross_entropy_loss(
        jnp.einsum("btc,vc->btv", x, fake_quant(w, 1)), y, ignore_index=-1)
    assert abs(float(dense) - float(blocked_q)) < 0.05
    np.testing.assert_allclose(float(ref_q), float(blocked_q),
                               rtol=1e-5, atol=1e-5)


@pytest.fixture(scope="module")
def family_losses():
    """loss + grad-finiteness per (family, compute_dtype) — four small
    jit(value_and_grad) compiles, charged to setup once."""
    from avenir_tpu.models.llama import Llama, LlamaConfig
    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

    rng = np.random.default_rng(0)
    out = {}
    for family, cls, ccls, kw in (
            ("llama", Llama, LlamaConfig, {}),
            ("mixtral", Mixtral, MixtralConfig, dict(n_experts=4))):
        for cd in ("bfloat16", "int8"):
            cfg = ccls(block_size=8, vocab_size=32, n_layer=1, n_head=2,
                       n_kv_head=1, n_embd=16, ffn_hidden=32,
                       compute_dtype=cd, attn_impl="xla", **kw)
            m = cls(cfg, rngs=nnx.Rngs(0))
            gd, p = nnx.split(m, nnx.Param)
            x = jnp.asarray(rng.integers(0, 32, (2, 8)).astype(np.int32))
            loss, g = jax.jit(jax.value_and_grad(
                lambda pp: nnx.merge(gd, pp)(x, x)[1]))(p)
            out[(family, cd)] = (
                float(loss),
                all(np.isfinite(np.asarray(l)).all()
                    for l in jax.tree.leaves(g)))
    return out


@pytest.mark.parametrize("family", ["llama", "mixtral"])
def test_llama_and_mixtral_int8_close_to_bf16(family_losses, family):
    """One forward+grad per family under the knob: loss within the op
    error budget of the bf16 run, grads finite — the family wiring pin
    (the GPT trajectory test above carries the deep coverage)."""
    l_bf, ok_bf = family_losses[(family, "bfloat16")]
    l_i8, ok_i8 = family_losses[(family, "int8")]
    assert ok_bf and ok_i8
    assert abs(l_i8 - l_bf) < 0.06, (family, l_bf, l_i8)
