"""Crash-window tests (ISSUE 5): manifest commit protocol, checksum
verification, the generation ring with restore fallback, deterministic
resume (loader rng fast-forward), injected IO faults absorbed by the
retry layer, and the slow chaos kill-resume soak driven by
tools/chaos_train.py."""

import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from avenir_tpu.checkpoint import io as ckpt_io
from avenir_tpu.checkpoint.manifest import (
    CorruptCheckpoint,
    build_manifest,
    file_checksum,
    load_manifest,
    verify_files,
    write_manifest,
)
from avenir_tpu.obs.metrics import get_registry, reset_registry
from avenir_tpu.utils.faults import FaultInjector, set_injector
from avenir_tpu.utils.retry import RetryPolicy, set_default_policy

from tests.test_sharded_ckpt import BIGGISH, HYPER, MODEL_ARGS, _trained_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = dataclasses.replace(BIGGISH, n_layer=2, n_embd=64, vocab_size=256)
TINY_ARGS = {**MODEL_ARGS, "n_layer": 2, "n_embd": 64, "vocab_size": 256}


@pytest.fixture(scope="module")
def tiny_state():
    """One trained (params, opt_state) shared read-only by every save
    test here — the jit'd train step behind _trained_state is the
    expensive part, not the saves under test."""
    _, params, opt_state, _ = _trained_state(TINY)
    return params, opt_state


@pytest.fixture()
def no_sleep_retries():
    """Swap the process retry policy for a non-sleeping one and hand the
    test a fresh registry; restore both afterwards."""
    prev = set_default_policy(RetryPolicy(attempts=4, base_s=0.0, cap_s=0.0,
                                          jitter=0.0, sleep=lambda s: None))
    reset_registry()
    yield get_registry()
    set_default_policy(prev)
    set_injector(None)
    reset_registry()


def _flip_byte(path, pos=None):
    size = os.path.getsize(path)
    pos = size // 2 if pos is None else pos
    with open(path, "r+b") as f:
        f.seek(pos)
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ 0xFF]))


def _save_full(tmp_path, params, opt_state, iter_num, keep=2):
    ckpt_io.save_checkpoint(
        str(tmp_path), params=params, opt_state=opt_state, hyper=HYPER,
        model_args=TINY_ARGS, iter_num=iter_num, best_val_loss=9.9,
        config={}, model_family="gpt", keep_checkpoints=keep)


# ---- manifest unit coverage ----


def test_manifest_roundtrip_and_corruption_detection(tmp_path):
    a, b = tmp_path / "a.bin", tmp_path / "b.bin"
    a.write_bytes(b"hello checkpoint body")
    b.write_bytes(bytes(range(256)) * 4)
    files = {p.name: file_checksum(str(p)) for p in (a, b)}
    man = build_manifest(iter_num=7, form="sharded", files=files)
    write_manifest(str(tmp_path), man)
    got = load_manifest(str(tmp_path), "sharded")
    assert got["iter_num"] == 7 and set(got["files"]) == {"a.bin", "b.bin"}
    verify_files(str(tmp_path), got)  # clean set passes

    _flip_byte(str(b))  # same size, different bits -> CRC must catch it
    with pytest.raises(CorruptCheckpoint, match="b.bin.*CRC"):
        verify_files(str(tmp_path), got)
    b.write_bytes(b"short")  # truncation reads as a size mismatch
    with pytest.raises(CorruptCheckpoint, match="b.bin.*bytes"):
        verify_files(str(tmp_path), got)
    os.remove(b)
    with pytest.raises(CorruptCheckpoint, match="b.bin: missing"):
        verify_files(str(tmp_path), got)
    # an uncommitted (absent/unparseable) manifest is None, not a crash
    assert load_manifest(str(tmp_path), "full") is None
    (tmp_path / "MANIFEST.json").write_text("{torn json")
    assert load_manifest(str(tmp_path), "sharded") is None


# ---- full-file commit + generation ring + fallback ----


def test_full_save_commits_sidecar_and_ring(tmp_path, tiny_state):
    reset_registry()
    params, opt_state = tiny_state
    for it in (1, 2, 3):
        _save_full(tmp_path, params, opt_state, it, keep=2)
    man = load_manifest(str(tmp_path), "full")
    assert man is not None and man["iter_num"] == 3
    verify_files(str(tmp_path), man)
    gens = ckpt_io.list_generations(str(tmp_path))
    assert [(it, form) for it, form, _ in gens] == \
        [(3, "full"), (2, "full")], gens  # pruned to keep=2, newest first

    src = ckpt_io.select_checkpoint_source(str(tmp_path),
                                           echo=lambda m: None)
    assert src["kind"] == "full" and src["iter_num"] == 3
    assert src["skipped_bad"] == 0
    assert int(src["meta"]["iter_num"]) == 3

    # bit rot in the live file ALSO rots the newest generation (hard
    # link, same inode — exactly how storage corruption behaves): the
    # selection must fall back to the iter-2 generation and say so
    _flip_byte(str(tmp_path / "ckpt.pt"))
    reset_registry()
    src = ckpt_io.select_checkpoint_source(str(tmp_path),
                                           echo=lambda m: None)
    assert src["kind"] == "full" and src["iter_num"] == 2
    # live + the hard-linked newest generation both rotted (a flip that
    # breaks the zip structure is refused at parse instead of at CRC —
    # either way both newer candidates are counted corrupt)
    assert src["skipped_bad"] >= 1
    assert int(src["meta"]["iter_num"]) == 2
    counters = get_registry().snapshot()["counters"]
    assert counters["ckpt_corrupt_detected"] == 2
    assert counters["ckpt_fallback"] == 1

    # every surviving candidate corrupted -> fail loud, never garbage
    _flip_byte(os.path.join(src["dir"], "ckpt.pt"))
    with pytest.raises(RuntimeError, match="no restorable checkpoint"):
        ckpt_io.select_checkpoint_source(str(tmp_path),
                                         echo=lambda m: None)


def test_sizes_mode_still_falls_back_on_parse_garbage(tmp_path, tiny_state,
                                                      monkeypatch):
    """AVENIR_RESTORE_VERIFY=sizes waives the CRC read, so size-
    preserving rot surfaces as a PARSE error (BadZipFile class, not
    OSError) — the candidate walk must still degrade to the older
    generation instead of dying on the exception."""
    reset_registry()
    params, opt_state = tiny_state
    for it in (1, 2):
        _save_full(tmp_path, params, opt_state, it, keep=2)
    # corrupt the zip end-of-central-directory: deterministic parse
    # failure, byte size unchanged (live + newest gen share the inode)
    size = os.path.getsize(tmp_path / "ckpt.pt")
    _flip_byte(str(tmp_path / "ckpt.pt"), pos=size - 10)
    monkeypatch.setenv("AVENIR_RESTORE_VERIFY", "sizes")
    src = ckpt_io.select_checkpoint_source(str(tmp_path),
                                           echo=lambda m: None)
    assert src["kind"] == "full" and src["iter_num"] == 1
    assert src["skipped_bad"] >= 1
    assert get_registry().snapshot()["counters"]["ckpt_fallback"] == 1


def test_foreign_ckpt_overwrite_accepted_as_legacy(tmp_path, tiny_state):
    """The torch trainer writes ckpt.pt whole with no sidecar: a stale
    sidecar whose SIZE disagrees means a foreign atomic overwrite, not
    corruption — resume must accept it (cross-backend contract)."""
    reset_registry()
    params, opt_state = tiny_state
    _save_full(tmp_path, params, opt_state, 1, keep=0)
    # simulate a torch save: replace the file wholesale, new size
    ckpt = ckpt_io.load_checkpoint(str(tmp_path))
    from avenir_tpu.checkpoint.torch_pt import save_pt

    ckpt["iter_num"] = 9
    save_pt(ckpt, str(tmp_path / "ckpt.pt.part"))
    os.replace(tmp_path / "ckpt.pt.part", tmp_path / "ckpt.pt")
    src = ckpt_io.select_checkpoint_source(str(tmp_path),
                                           echo=lambda m: None)
    assert src["iter_num"] == 9 and src["skipped_bad"] == 0


def test_kill_between_rename_and_sidecar_accepts_new_body(tmp_path,
                                                          tiny_state):
    """ckpt.pt size is iteration-invariant, so the crash window between
    the body rename and the sidecar write must read as legacy-unverified
    (the committer removes the stale sidecar BEFORE renaming) — never as
    'bit corruption' of a perfectly good body."""
    reset_registry()
    params, opt_state = tiny_state
    _save_full(tmp_path, params, opt_state, 1, keep=2)
    # emulate the window: a later save removed the sidecar and renamed
    # its new body in, then was SIGKILLed before write_manifest
    os.remove(tmp_path / "ckpt.pt.manifest.json")
    src = ckpt_io.select_checkpoint_source(str(tmp_path),
                                           echo=lambda m: None)
    assert src["kind"] == "full" and src["skipped_bad"] == 0
    counters = get_registry().snapshot()["counters"]
    assert counters.get("ckpt_corrupt_detected", 0) == 0


def test_ring_keeps_distinct_iterations_not_directories(tmp_path,
                                                        tiny_state):
    """A full and a sharded save can land at the SAME iteration (final
    sync save on the eval cadence): keep=K must count iterations, not
    generation directories, or the ring silently loses restore points."""
    reset_registry()
    params, opt_state = tiny_state
    _save_sharded(tmp_path, params, opt_state, 4)
    _save_full(tmp_path, params, opt_state, 4, keep=2)
    _save_full(tmp_path, params, opt_state, 8, keep=2)
    gens = {(it, form) for it, form, _ in
            ckpt_io.list_generations(str(tmp_path))}
    assert gens == {(8, "full"), (4, "full"), (4, "sharded")}


# ---- sharded commit protocol ----


def _save_sharded(tmp_path, params, opt_state, iter_num, keep=2):
    h = ckpt_io.save_checkpoint_sharded_async(
        str(tmp_path), params=params, opt_state=opt_state, hyper=HYPER,
        model_args=TINY_ARGS, iter_num=iter_num, best_val_loss=1.0,
        config={}, model_family="gpt", keep_checkpoints=keep)
    h.join()


def test_sharded_save_commits_manifest_and_ring(tmp_path, tiny_state):
    reset_registry()
    params, opt_state = tiny_state
    _save_sharded(tmp_path, params, opt_state, 5)
    man = load_manifest(str(tmp_path), "sharded")
    assert man is not None and man["iter_num"] == 5
    assert set(man["files"]) == {"ckpt-shard-00000.pkl"}
    verify_files(str(tmp_path), man)
    import glob
    assert not glob.glob(str(tmp_path / "ckpt-shard-*.pkl.crc-*.json"))
    assert ckpt_io.verify_sharded_set(str(tmp_path)) == "verified"
    gens = ckpt_io.list_generations(str(tmp_path))
    assert [(it, form) for it, form, _ in gens] == [(5, "sharded")]
    # the committed set loads and checksums clean
    sh = ckpt_io.load_sharded_checkpoint(str(tmp_path))
    assert sh is not None and sh["iter_num"] == 5 and sh["params"]


def test_uncommitted_sharded_set_refused_with_fallback(tmp_path, tiny_state):
    """SIGKILL between the body renames and the MANIFEST rename leaves
    an uncommitted v2 set: restore must refuse it and fall back to the
    older full checkpoint instead of assembling a maybe-torn set."""
    reset_registry()
    params, opt_state = tiny_state
    _save_full(tmp_path, params, opt_state, 3)
    _save_sharded(tmp_path, params, opt_state, 6, keep=0)
    os.remove(tmp_path / "MANIFEST.json")  # the commit never happened

    with pytest.raises(CorruptCheckpoint, match="never committed"):
        ckpt_io.verify_sharded_set(str(tmp_path), echo=lambda m: None)
    # body loads refuse it outright (counted), meta reads still work so
    # selection can rank the candidate before verification rejects it
    reset_registry()
    assert ckpt_io.load_sharded_checkpoint(str(tmp_path)) is None
    assert get_registry().snapshot()["counters"]["ckpt_corrupt_detected"] == 1
    assert ckpt_io.load_sharded_checkpoint(
        str(tmp_path), meta_only=True)["iter_num"] == 6

    reset_registry()
    src = ckpt_io.select_checkpoint_source(str(tmp_path),
                                           echo=lambda m: None)
    assert src["kind"] == "full" and src["iter_num"] == 3
    assert src["skipped_bad"] == 1
    assert get_registry().snapshot()["counters"]["ckpt_fallback"] == 1


@pytest.mark.parametrize("where", ["header", "body"])
def test_corrupted_shard_bytes_detected(tmp_path, tiny_state, where):
    """A flipped byte anywhere in a shard file — the pickled header at
    the front or the tensor body behind it — must fail verification;
    the body-read path additionally refuses to assemble the bytes."""
    reset_registry()
    params, opt_state = tiny_state
    _save_sharded(tmp_path, params, opt_state, 5, keep=0)
    shard = str(tmp_path / "ckpt-shard-00000.pkl")
    _flip_byte(shard, pos=10 if where == "header" else None)
    with pytest.raises(CorruptCheckpoint):
        ckpt_io.verify_sharded_set(str(tmp_path), echo=lambda m: None)
    # the body-read path checksums the bytes AS READ too: corrupt bytes
    # must never be assembled into weights even if selection was skipped
    # (a header flip may already fail the pickle parse -> refused as an
    # unreadable set, which is None, never garbage)
    if where == "body":
        with pytest.raises(CorruptCheckpoint):
            ckpt_io.load_sharded_checkpoint(str(tmp_path))
    else:
        try:
            out = ckpt_io.load_sharded_checkpoint(str(tmp_path))
        except CorruptCheckpoint:
            out = None
        assert out is None


def test_injected_read_corruption_caught_by_manifest(tmp_path, tiny_state,
                                                     no_sleep_retries):
    """`read_corrupt` corrupts bytes in TRANSIT (disk content stays
    good): only the read-path checksum can catch this class."""
    params, opt_state = tiny_state
    _save_sharded(tmp_path, params, opt_state, 5, keep=0)
    assert ckpt_io.verify_sharded_set(str(tmp_path)) == "verified"
    set_injector(FaultInjector("read_corrupt:p=1.0:n=1", seed=3))
    with pytest.raises(CorruptCheckpoint, match="refusing to assemble"):
        ckpt_io.load_sharded_checkpoint(str(tmp_path))
    set_injector(None)
    sh = ckpt_io.load_sharded_checkpoint(str(tmp_path))
    assert sh is not None and sh["iter_num"] == 5


def test_faulty_read_wrapper_survives_large_pickle_frames():
    """pickle's C unpickler uses readinto for large frames — every real
    tensor body. An ARMED but not-yet-firing read_corrupt injector must
    be invisible: same parse, same checksum path."""
    import io as stdio
    import pickle

    from avenir_tpu.checkpoint.io import _FaultyRead
    from avenir_tpu.checkpoint.manifest import ChecksumReader

    arr = np.arange(2_000_000, dtype=np.float32)  # ~8 MB frame
    buf = stdio.BytesIO()
    pickle.dump({"x": arr}, buf, protocol=4)
    buf.seek(0)
    inj = FaultInjector("read_corrupt:p=1.0:after=1000000000", seed=0)
    out = pickle.load(ChecksumReader(_FaultyRead(buf, inj)))
    np.testing.assert_array_equal(out["x"], arr)


def test_torn_mixed_iteration_set_is_counted_and_falls_back(tmp_path,
                                                            tiny_state):
    """SIGKILL between two processes' body renames leaves shards at
    MIXED iterations: the refusal must be visible (ckpt_corrupt_detected)
    and the restore of anything else recorded as a fallback."""
    reset_registry()
    params, opt_state = tiny_state
    _save_full(tmp_path, params, opt_state, 3)
    _save_sharded(tmp_path, params, opt_state, 6, keep=0)
    # fake the kill window of a 2-process save: one shard landed at the
    # new iteration, the other still holds the previous save's, and the
    # MANIFEST rename never happened
    import pickle

    src = tmp_path / "ckpt-shard-00000.pkl"
    with open(src, "rb") as f:
        h = pickle.load(f)
        body = pickle.load(f)
    h = {**h, "iter_num": 2, "process_index": 1, "process_count": 2}
    with open(tmp_path / "ckpt-shard-00001.pkl", "wb") as f:
        pickle.dump(h, f, protocol=4)
        pickle.dump(body, f, protocol=4)
    os.remove(tmp_path / "MANIFEST.json")

    reset_registry()
    assert ckpt_io.load_sharded_checkpoint(str(tmp_path),
                                           meta_only=True) is None
    assert get_registry().snapshot()["counters"]["ckpt_corrupt_detected"] == 1

    reset_registry()
    src_sel = ckpt_io.select_checkpoint_source(str(tmp_path),
                                               echo=lambda m: None)
    assert src_sel["kind"] == "full" and src_sel["iter_num"] == 3
    assert src_sel["skipped_bad"] >= 1
    assert get_registry().snapshot()["counters"]["ckpt_fallback"] == 1


def test_injected_write_faults_absorbed_by_retry(tmp_path, tiny_state,
                                                 no_sleep_retries):
    """Transient write failures (EIO-class) must be retried with
    backoff and counted — the save lands, nothing raises."""
    reg = no_sleep_retries
    params, opt_state = tiny_state
    set_injector(FaultInjector("ckpt_write_fail:p=1.0:n=2", seed=0))
    _save_full(tmp_path, params, opt_state, 1)
    assert reg.snapshot()["counters"]["io_retries"] >= 2
    man = load_manifest(str(tmp_path), "full")
    assert man is not None
    verify_files(str(tmp_path), man)
    src = ckpt_io.select_checkpoint_source(str(tmp_path),
                                           echo=lambda m: None)
    assert src["iter_num"] == 1 and src["skipped_bad"] == 0


def test_injected_data_read_faults_absorbed(char_dataset,
                                            no_sleep_retries):
    """Loader file reads retry transient faults, and the rng stream the
    run consumes is UNAFFECTED by how flaky the storage was (the crops
    are drawn once, before the retryable read)."""
    from avenir_tpu.data.loader import DataLoader

    reg = no_sleep_retries
    clean = DataLoader(char_dataset["dir"], 32, 4, seed=3)
    want = [clean._sample_local("train") for _ in range(3)]
    set_injector(FaultInjector("data_read_fail:p=1.0:n=2", seed=1))
    flaky = DataLoader(char_dataset["dir"], 32, 4, seed=3)
    got = [flaky._sample_local("train") for _ in range(3)]
    assert reg.snapshot()["counters"]["io_retries"] >= 2
    for (xw, yw), (xg, yg) in zip(want, got):
        np.testing.assert_array_equal(xg, xw)
        np.testing.assert_array_equal(yg, yw)


# ---- deterministic resume ----


def test_loader_fast_forward_is_bit_exact(char_dataset):
    from avenir_tpu.data.loader import DataLoader

    a = DataLoader(char_dataset["dir"], 32, 4, seed=11)
    stream = [a._sample_local("train") for _ in range(10)]
    b = DataLoader(char_dataset["dir"], 32, 4, seed=11)
    b.fast_forward([("train", 4)])
    for i in range(4, 10):
        x, y = b._sample_local("train")
        np.testing.assert_array_equal(x, stream[i][0], err_msg=str(i))
        np.testing.assert_array_equal(y, stream[i][1], err_msg=str(i))


@pytest.mark.slow
def test_resume_trajectory_bit_identical(char_dataset, tmp_path):
    """THE chaos property, in-process: a run killed after its iter-3
    checkpoint and resumed must replay iters 3..6 with EXACTLY the
    losses of an uninterrupted run — same params (save/restore is
    bit-exact at fp32), same batches (loader fast-forward), same step
    rngs (iteration-indexed fold_in)."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    common = dict(max_iters=6, eval_interval=3, mesh_shape="data:1")
    base = run_training(make_cfg(char_dataset["dir"], tmp_path / "base",
                                 **common))
    base_hist = dict(base["loss_history"])

    out = tmp_path / "killed"
    run_training(make_cfg(char_dataset["dir"], out, **{**common,
                                                       "max_iters": 3}))
    res = run_training(make_cfg(char_dataset["dir"], out,
                                init_from="resume", **common))
    resumed_hist = dict(res["loss_history"])
    assert res["iter_num"] >= 6
    overlap = sorted(set(base_hist) & set(resumed_hist))
    assert overlap and overlap[0] == 3
    for it in overlap:
        assert resumed_hist[it] == base_hist[it], (
            it, resumed_hist[it], base_hist[it])
    # the resumed segment's run log carries the restore decision
    records = [json.loads(line) for line in
               open(out / "metrics.jsonl") if line.strip()]
    restores = [r for r in records if r.get("kind") == "restore"]
    assert restores and restores[-1]["source_kind"] == "full"
    assert restores[-1]["skipped_bad"] == 0


@pytest.mark.slow
def test_resume_falls_back_to_generation_end_to_end(char_dataset,
                                                    tmp_path):
    """Corrupt the live checkpoint of a real run: the resume must
    restore from the generation ring, log ckpt_fallback in the JSONL
    run log, and keep training (the acceptance-criteria drill,
    in-process)."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    out = tmp_path / "out"
    run_training(make_cfg(char_dataset["dir"], out, max_iters=6,
                          eval_interval=3, mesh_shape="data:1"))
    # saves landed at iters 3 and 6; ring keeps both generations.
    # Flip a byte in the live ckpt.pt — the newest generation shares
    # the inode, so both rot (realistic storage corruption)
    _flip_byte(str(out / "ckpt.pt"))
    res = run_training(make_cfg(char_dataset["dir"], out, max_iters=9,
                                eval_interval=3, mesh_shape="data:1",
                                init_from="resume"))
    assert res["iter_num"] >= 9
    records = [json.loads(line) for line in
               open(out / "metrics.jsonl") if line.strip()]
    restore = [r for r in records if r.get("kind") == "restore"][-1]
    assert restore["iter"] == 3  # fell back to the iter-3 generation
    assert restore["skipped_bad"] >= 1
    assert restore["counters"]["ckpt_fallback"] == 1
    assert restore["counters"]["ckpt_corrupt_detected"] == 2
    run_end = [r for r in records if r.get("kind") == "run_end"][-1]
    assert run_end["counters"]["ckpt_fallback"] == 1


# ---- chaos soak (subprocess, slow) ----


@pytest.mark.slow
def test_chaos_harness_subprocess(tmp_path):
    """tools/chaos_train.py end to end: seeded SIGKILLs (incl. the
    mid-save window) + the corruption drill, asserting the bit-identical
    verdict and the fallback evidence in its JSON report."""
    report_path = tmp_path / "chaos.json"
    r = subprocess.run(
        [sys.executable, "tools/chaos_train.py", "--seed=1", "--kills=3",
         "--max_iters=9", "--eval_interval=3", "--drill=all",
         f"--workdir={tmp_path / 'work'}", f"--out={report_path}"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=1800,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(report_path.read_text())
    assert report["ok"] is True
    assert report["bit_identical"] is True
    assert report["iters_compared"] >= 9
    assert len(report["kills"]) == 3
    assert report["corruption_drill"]["fell_back"] is True
