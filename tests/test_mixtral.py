"""Mixtral MoE tests: routing/logits parity vs transformers' torch
MixtralForCausalLM (capacity set high enough that no tokens drop — HF
never drops), EP sharding on the 8 fake devices, and all-to-all presence
in the EP HLO (SURVEY.md §4; BASELINE.json:11)."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.checkpoint.bridge import load_torch_state_dict
from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

TINY = dict(
    block_size=32, vocab_size=96, n_layer=2, n_head=4, n_kv_head=2,
    n_embd=64, ffn_hidden=128, rope_theta=10000.0, n_experts=4,
    n_experts_per_tok=2,
)


def _hf_mixtral():
    from transformers import MixtralConfig as HFConfig, MixtralForCausalLM

    hf_cfg = HFConfig(
        vocab_size=TINY["vocab_size"], hidden_size=TINY["n_embd"],
        intermediate_size=TINY["ffn_hidden"],
        num_hidden_layers=TINY["n_layer"],
        num_attention_heads=TINY["n_head"],
        num_key_value_heads=TINY["n_kv_head"],
        max_position_embeddings=TINY["block_size"],
        rms_norm_eps=1e-5, rope_theta=TINY["rope_theta"],
        num_local_experts=TINY["n_experts"],
        num_experts_per_tok=TINY["n_experts_per_tok"],
        tie_word_embeddings=False, attention_bias=False,
        attn_implementation="eager", output_router_logits=False,
    )
    torch.manual_seed(0)
    m = MixtralForCausalLM(hf_cfg)
    m.eval()
    return m


def test_logits_parity_no_drop():
    tm = _hf_mixtral()
    # capacity_factor = E/K → C = N: nothing can drop, matches HF exactly
    jm = Mixtral(
        MixtralConfig(capacity_factor=TINY["n_experts"] / TINY["n_experts_per_tok"],
                      **TINY),
        rngs=nnx.Rngs(0),
    )
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    load_torch_state_dict(jm, sd, tied_lm_head=False)

    rng = np.random.default_rng(0)
    idx = rng.integers(0, TINY["vocab_size"], (2, 16))
    with torch.no_grad():
        t_logits = tm(torch.from_numpy(idx)).logits
    j_logits, _ = jm(jnp.asarray(idx), jnp.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(j_logits), t_logits.numpy(), atol=5e-4, rtol=5e-4
    )


def test_capacity_drops_are_graceful():
    """With a tight capacity factor, outputs stay finite and overflow
    tokens degrade to the residual path (combine weight 0)."""
    jm = Mixtral(MixtralConfig(capacity_factor=0.5, **TINY), rngs=nnx.Rngs(0))
    idx = jnp.zeros((2, 16), jnp.int32)  # all identical → heavy overflow
    logits, loss = jm(idx, idx)
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(logits)).all()


def test_mixtral_trains_and_resumes(char_dataset, tmp_path):
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    kw = dict(model_type="mixtral", n_kv_head=2, n_head=4, n_embd=32,
              ffn_hidden=64, n_experts=4, eval_interval=5, mesh_shape="data:1")
    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=10, **kw)
    res = run_training(cfg)
    losses = [l for _, l in res["loss_history"]]
    assert losses[-1] < losses[0], losses
    cfg2 = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=12,
                    init_from="resume", **kw)
    res2 = run_training(cfg2)
    assert res2["iter_num"] >= 12


def test_ep_trajectory_matches_and_hlo_has_all_to_all(char_dataset, tmp_path):
    """expert:4 mesh must reproduce the single-device trajectory (EP is
    pure layout) and the compiled step must contain an all-to-all."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    kw = dict(model_type="mixtral", n_kv_head=2, n_head=4, n_embd=32,
              ffn_hidden=64, n_experts=4, eval_interval=50,
              gradient_accumulation_steps=4)
    ref = run_training(
        make_cfg(char_dataset["dir"], tmp_path / "o1", max_iters=5,
                 mesh_shape="data:1", **kw)
    )
    got = run_training(
        make_cfg(char_dataset["dir"], tmp_path / "o2", max_iters=5,
                 mesh_shape="expert:4", **kw)
    )
    ref_l = np.array([l for _, l in ref["loss_history"]])
    got_l = np.array([l for _, l in got["loss_history"]])
    np.testing.assert_allclose(got_l, ref_l, atol=3e-4, rtol=3e-4)


_EP_HLO_FRESH = []  # first lowering of the session, cached for the
# isolation-order pin (one ~6s SPMD compile instead of two)


def _lower_ep_step_hlo():
    """Compile the expert:4 train step and return its final HLO text —
    the shared lowering for the EP-exchange tests below."""
    from flax import nnx as _nnx
    from jax.sharding import NamedSharding

    from tests.test_train_tpu import make_cfg

    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.parallel.partition import batch_pspec
    from avenir_tpu.train.loop import setup_state
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import make_step_fns

    mesh = make_mesh("expert:4")
    cfg = make_cfg("x", "y", model_type="mixtral")
    model_args = dict(n_layer=1, n_head=4, n_embd=32, block_size=32,
                      bias=False, vocab_size=64, dropout=0.0)
    st = setup_state(cfg, mesh, model_args, verbose=False)

    params = jax.jit(
        lambda: _nnx.split(st["ctor"](0), _nnx.Param)[1],
        out_shardings=st["shard_tree"],
    )()
    tx, _ = make_optimizer(
        params, learning_rate=1e-3, weight_decay=0.1, beta1=0.9, beta2=0.95,
        grad_clip=1.0, warmup_iters=2, lr_decay_iters=8, min_lr=1e-4,
    )
    opt_state = jax.jit(tx.init)(params)
    train_step, _ = make_step_fns(st["graphdef"], dropout=0.0)
    bsh = NamedSharding(mesh, batch_pspec())
    x = jax.device_put(np.zeros((1, 8, 32), np.int32), bsh)
    return jax.jit(
        lambda p, o, r, xx, yy: train_step(p, o, tx, r, xx, yy)
    ).lower(params, opt_state, jax.random.key(0), x, x).compile().as_text()


def _ep_exchange_kind(hlo):
    """Classify how the compiled EP step exchanges tokens over the
    expert axis. On an expert:4 mesh every other axis has size 1, so ANY
    cross-device collective in the module runs over the expert groups:

      'all-to-all'  the canonical EP dispatch (what GSPMD emits on
                    modern partitioners / TPU — the ICI economics the
                    docstring claims)
      'gathered'    this container's legacy XLA:CPU partitioner instead
                    decomposes the gather-based dispatch into expert-
                    group all-gathers of the token rows + a collective-
                    permute chain (verified from the post-SPMD dump:
                    the (N, d) rows are gathered to each expert shard,
                    which then gathers its C tokens locally) — same
                    exchange, different (chattier) lowering
      None          NO collective at all: the dispatch silently
                    unpartitioned / fully replicated — the regression
                    this test exists to catch on every runtime
    """
    if "all-to-all" in hlo:
        return "all-to-all"
    if "all-gather" in hlo or "collective-permute" in hlo:
        return "gathered"
    return None


def _legacy_partitioner():
    from avenir_tpu import compat

    return getattr(jax, "shard_map", None) is compat.shard_map


def test_ep_hlo_contains_all_to_all(char_dataset):
    """The EP dispatch must EXCHANGE tokens over the expert axis in the
    compiled step. Strict all-to-all where the partitioner forms it
    (modern jax); the legacy jax-0.4.x CPU partitioner in this container
    never forms one for the gather-based dispatch (it decomposes into
    expert-group all-gathers — see _ep_exchange_kind), which is the
    environment drift that made this assertion an unconditional failure
    for three PRs. Either way a module with NO expert collective fails:
    that would mean the dispatch silently stopped being partitioned."""
    hlo = _lower_ep_step_hlo()
    if not _EP_HLO_FRESH:
        _EP_HLO_FRESH.append(_ep_exchange_kind(hlo))
    kind = _ep_exchange_kind(hlo)
    assert kind is not None, (
        "EP step compiled with no expert-axis collective at all — the "
        "dispatch is no longer partitioned over 'expert'"
    )
    if not _legacy_partitioner():
        assert kind == "all-to-all", (
            f"EP dispatch lowered to {kind!r} on a modern partitioner — "
            "expected the canonical all-to-all"
        )


@pytest.mark.slow
def test_ep_hlo_classification_is_order_independent(char_dataset):
    """Isolation-order pin for the fix above: the exchange
    classification must not depend on what compiled before it (the old
    assertion was reported as order-dependent across PRs 12-14). Lower
    once fresh (reusing the in-session cache when the tier-1 test
    already lowered first — that ordering is itself part of the pin),
    then again after unrelated SPMD work on a different mesh has
    populated caches and ambient state, and require the SAME
    classification."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.parallel.mesh import make_mesh

    first = (_EP_HLO_FRESH[0] if _EP_HLO_FRESH
             else _ep_exchange_kind(_lower_ep_step_hlo()))
    # unrelated SPMD compilation on a different mesh (the kind of
    # neighbor the full tier-1 run interleaves before this file)
    mesh = make_mesh("data:2,fsdp:2")
    sh = NamedSharding(mesh, P(("data", "fsdp")))
    arr = jax.device_put(np.ones((8, 16), np.float32), sh)
    jax.jit(lambda a: (a * 2).sum())(arr).block_until_ready()
    second = _ep_exchange_kind(_lower_ep_step_hlo())
    assert first == second, (
        f"EP exchange classification flipped with compile order: "
        f"{first!r} fresh vs {second!r} after unrelated SPMD work"
    )


def test_expert_opt_state_sharded(char_dataset):
    """The Mixtral 'optimizer wall' fix, demonstrated (VERDICT r3 item 5):
    Adam mu/nu for stacked expert weights must shard over
    expert×fsdp×tensor exactly like their params (BASELINE.md "optimizer
    wall" — AdamW is O(params) VPU work, so per-device moment bytes must
    shrink by the full mesh factor), and one real optimizer step must
    PRESERVE that layout (no silent re-replication through the update).
    Trajectory equivalence of the sharded-moments path is pinned
    separately by test_ep_trajectory_matches_and_hlo_has_all_to_all
    (run_training routes through the same init_sharded_opt_state)."""
    from flax import nnx as _nnx
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tests.test_train_tpu import make_cfg

    from avenir_tpu.checkpoint.io import _find_adam_state
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.parallel.partition import batch_pspec
    from avenir_tpu.train.loop import init_sharded_opt_state, setup_state
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import jit_train_step, make_step_fns

    mesh = make_mesh("fsdp:2,expert:2")
    cfg = make_cfg("x", "y", model_type="mixtral", mesh_shape="fsdp:2,expert:2")
    model_args = dict(n_layer=1, n_head=4, n_embd=32, block_size=32,
                      bias=False, vocab_size=64, dropout=0.0)
    st = setup_state(cfg, mesh, model_args, verbose=False)
    params = jax.jit(
        lambda: _nnx.split(st["ctor"](0), _nnx.Param)[1],
        out_shardings=st["shard_tree"],
    )()
    tx, _ = make_optimizer(
        params, learning_rate=1e-3, weight_decay=0.1, beta1=0.9, beta2=0.95,
        grad_clip=1.0, warmup_iters=2, lr_decay_iters=8, min_lr=1e-4,
    )
    opt_state = init_sharded_opt_state(tx, params, st["shard_tree"])

    def expert_mu_leaves(state):
        adam = _find_adam_state(state)
        return [(p, v) for p, v in adam.mu.flat_state()
                if "experts" in [str(s) for s in p]]

    def check(state):
        leaves = expert_mu_leaves(state)
        assert leaves, "no expert moment leaves found"
        for path, leaf in leaves:
            arr = leaf.get_value() if hasattr(leaf, "get_value") else leaf
            spec = arr.sharding.spec
            assert spec[0] == "expert", (path, spec)
            assert "fsdp" in spec, (path, spec)
            # per-device bytes shrink by the full expert×fsdp factor
            local = arr.addressable_shards[0].data.nbytes
            assert local * 4 == arr.nbytes, (path, local, arr.nbytes)

    check(opt_state)
    # one real step: donated update must keep the moments sharded
    step_fn, _ = make_step_fns(st["graphdef"], dropout=0.0)
    step = jit_train_step(step_fn, tx)
    bsh = NamedSharding(mesh, batch_pspec())
    rng = np.random.default_rng(0)
    x = jax.device_put(rng.integers(0, 64, (1, 8, 32)).astype(np.int32), bsh)
    params, opt_state, m = step(params, opt_state, jax.random.key(0), x, x)
    assert np.isfinite(float(m["loss"]))
    check(opt_state)


def test_router_aux_loss_matches_hf_formula():
    """The load-balancing loss added to the training loss must equal HF's
    load_balancing_loss_func on the same router outputs (coef * mean over
    layers), and vanish when the knob is 0."""
    rng = np.random.default_rng(0)
    idx = rng.integers(0, TINY["vocab_size"], (2, 16))
    tgt = rng.integers(0, TINY["vocab_size"], (2, 16))

    def loss_with(coef):
        jm = Mixtral(
            MixtralConfig(capacity_factor=2.0, router_aux_loss_coef=coef,
                          **TINY),
            rngs=nnx.Rngs(0),
        )
        _, loss = jm(jnp.asarray(idx), jnp.asarray(tgt))
        return jm, float(loss)

    jm, base = loss_with(0.0)
    _, with_aux = loss_with(0.02)
    assert with_aux > base  # aux is nonnegative and generically > 0

    # recompute HF load_balancing_loss_func by hand: router outputs of ALL
    # layers CONCATENATED, then E * sum(tokens_per_expert * prob_per_expert)
    all_oh, all_probs = [], []
    h = jm.embed_tokens(jnp.asarray(idx))
    E, K = TINY["n_experts"], TINY["n_experts_per_tok"]
    for layer in jm.layers:
        pre = layer.input_layernorm(h).astype(jnp.float32)
        h = h + layer.self_attn(pre)
        moe_in = layer.post_attention_layernorm(h).astype(jnp.float32)
        N = moe_in.shape[0] * moe_in.shape[1]
        logits = layer.block_sparse_moe.gate(
            moe_in.reshape(N, -1)).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        _, topk_idx = jax.lax.top_k(probs, K)
        all_oh.append(jax.nn.one_hot(topk_idx, E))
        all_probs.append(probs)
        moe_out, _ = layer.block_sparse_moe(moe_in.astype(h.dtype))
        h = h + moe_out
    oh_cat = jnp.concatenate(all_oh, axis=0)       # (L*N, K, E)
    probs_cat = jnp.concatenate(all_probs, axis=0)  # (L*N, E)
    hf_aux = E * jnp.sum(
        jnp.mean(oh_cat, axis=0) * jnp.mean(probs_cat, axis=0)[None, :]
    )
    expect = 0.02 * float(hf_aux)
    np.testing.assert_allclose(with_aux - base, expect, rtol=1e-4)
