"""Serve-engine tests (avenir_tpu/serve/, ISSUE 2): continuous-batching
output must be token-for-token identical to per-request one-shot
`generate_cached`, regardless of arrival order, slot eviction or
bucketing — plus slot-recycling, stop-token, compile-budget and
metrics/JSONL coverage. All CPU tier-1 except the load-bench soak.

Budget notes: the GPT model + one-shot references are module-scoped
(references share decode compiles), every request uses ONE max_new so
references need one scan-length compile per sampling combo, and stop
tokens are engine-host-side so they add no compiles here (the one-shot
stop path has its own parity tests in test_decode.py)."""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import first_stop_index, generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.models.llama import Llama, LlamaConfig
from avenir_tpu.models.mixtral import Mixtral, MixtralConfig
from avenir_tpu.obs import JsonlSink, MetricsRegistry
from avenir_tpu.serve import Engine

# single-layer models: engine scheduling/parity logic is depth-blind
# (multi-layer forwards are pinned by test_decode.py) and every layer
# multiplies compile time inside the tier-1 budget
GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
LLAMA_KW = dict(block_size=64, vocab_size=64, n_layer=1, n_head=4,
                n_kv_head=2, n_embd=32, ffn_hidden=64, dropout=0.0,
                attn_impl="xla")
MAX_NEW = 6  # one scan length -> one decode compile per sampling combo
COMBOS = ((0.8, None), (1.0, 5), (1.3, 16))  # (temperature, top_k)


def _mk_requests(model, rng, n, *, max_prompt=12, combos=COMBOS):
    """n requests with mixed prompt lengths / sampling params, each with
    its one-shot reference tokens. Stop tokens are picked FROM the
    reference stream (so they really fire mid-flight for every other
    request) and the reference is truncated host-side with
    first_stop_index — the same rule the engine applies."""
    reqs = []
    for i in range(n):
        t0 = int(rng.integers(3, max_prompt + 1))
        prompt = [int(t) for t in rng.integers(0, 64, (t0,))]
        temp, top_k = combos[i % len(combos)]
        kw = dict(
            prompt=prompt, max_new_tokens=MAX_NEW,
            temperature=temp, top_k=top_k,
            rng=jax.random.key(1000 + i),
        )
        y = np.asarray(generate_cached(
            model, kw["rng"], jnp.asarray(prompt, jnp.int32)[None],
            MAX_NEW, temperature=kw["temperature"], top_k=kw["top_k"]))[0]
        stop = (int(y[t0 + 1]),) if i % 2 == 0 else ()
        n_keep = first_stop_index(y[t0:], stop) if stop else MAX_NEW
        reqs.append((kw | {"stop_tokens": stop},
                     [int(t) for t in y[:t0 + n_keep]]))
    return reqs


@pytest.fixture(scope="module")
def gpt_fix():
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    return model, _mk_requests(model, np.random.default_rng(0), 9)


def _run_schedule(engine, reqs, bursts):
    """Submit requests in bursts (one burst before each step), then
    drain. Returns {original request index: FinishedRequest}."""
    ids, results, pending = {}, {}, list(range(len(reqs)))
    bursts = list(bursts)
    while pending or engine.sched.queue_depth or engine._live:
        take = bursts.pop(0) if bursts else len(pending)
        for _ in range(min(take, len(pending))):
            i = pending.pop(0)
            kw, _ = reqs[i]
            ids[engine.submit(**kw)] = i
        for f in engine.step():
            results[ids[f.req_id]] = f
    return results


def _assert_parity(results, reqs, perm=None):
    perm = list(perm) if perm is not None else list(range(len(reqs)))
    assert len(results) == len(perm)
    for j, i in enumerate(perm):
        kw, ref = reqs[i]
        got = results[j].tokens
        assert got == ref, f"request {i} diverged:\n ref {ref}\n got {got}"
        want_reason = ("stop" if kw["stop_tokens"]
                       and ref[-1] in kw["stop_tokens"] else "length")
        assert results[j].finish_reason == want_reason


def test_engine_parity_randomized_arrivals(gpt_fix):
    """The acceptance case: >= 8 requests, mixed prompt lengths and stop
    tokens, randomized arrival bursts, fewer slots than requests (forced
    queueing + eviction + recycling) — bit-identical per request to
    one-shot generate_cached, in (n buckets + 1) compiles."""
    model, reqs = gpt_fix
    engine = Engine(model, n_slots=3, max_seq_len=32,
                    registry=MetricsRegistry())
    results = _run_schedule(engine, reqs, bursts=[3, 0, 2, 1, 0, 3])
    _assert_parity(results, reqs)
    n_buckets = len(engine.sched.seen_buckets)
    assert n_buckets >= 2, "schedule was meant to span multiple buckets"
    assert len(engine.traces["prefill"]) == n_buckets
    assert len(engine.traces["step"]) == 1
    assert engine.sched.n_recycled == len(reqs)


def test_engine_parity_arrival_order_invariance(gpt_fix):
    """A permuted arrival order with different slot pressure still
    reproduces every per-request reference stream (slot assignment and
    co-tenancy don't leak between requests)."""
    model, reqs = gpt_fix
    perm = [4, 2, 5, 0, 3, 1]
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry())
    results = _run_schedule(engine, [reqs[i] for i in perm],
                            bursts=[1, 1, 2, 0, 2])
    _assert_parity(results, reqs, perm=perm)


@pytest.mark.parametrize("family", ["llama", "mixtral", "gpt_scan"])
def test_engine_parity_families(family):
    """All three model families, including the scan-stacked layout."""
    if family == "llama":
        model = Llama(LlamaConfig(**LLAMA_KW), rngs=nnx.Rngs(0))
    elif family == "mixtral":
        # cf*K >= E: decode capacity >= batch, so MoE dropping can never
        # depend on batch composition (the parity-safe regime,
        # docs/SERVING.md)
        model = Mixtral(MixtralConfig(n_experts=4, n_experts_per_tok=2,
                                      capacity_factor=2.0, **LLAMA_KW),
                        rngs=nnx.Rngs(0))
    else:
        model = GPT(dataclasses.replace(GPT_TINY, scan_layers=True),
                    rngs=nnx.Rngs(0))
    # one sampling combo: family coverage is about the forward path, not
    # the sampler matrix (the GPT tests cover that) — one decode compile
    reqs = _mk_requests(model, np.random.default_rng(2), 3,
                        combos=((1.0, 8),))
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry())
    results = _run_schedule(engine, reqs, bursts=[2, 1])
    _assert_parity(results, reqs)


def test_slot_recycling_reuses_slots(gpt_fix):
    model, _ = gpt_fix
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry())
    for i in range(6):
        engine.submit([1 + i, 2, 3], max_new_tokens=3,
                      rng=jax.random.key(i))
    occupancies = []
    done = []
    while engine.sched.queue_depth or engine._live:
        done += engine.step()
        occupancies.append(len(engine._live))
    assert len(done) == 6
    assert max(occupancies) <= 2  # never more live than slots
    assert engine.sched.n_recycled == 6
    assert engine.sched.free_slots == 2


def test_engine_stop_vs_length(gpt_fix):
    model, reqs = gpt_fix
    # reuse a fixture request whose stop token fires mid-stream
    kw, ref = next(r for r in reqs if r[0]["stop_tokens"])
    stop = kw["stop_tokens"][0]
    engine = Engine(model, n_slots=1, max_seq_len=32,
                    registry=MetricsRegistry())
    engine.submit(**kw)
    engine.submit(**(kw | {"stop_tokens": ()}))
    done = engine.drain()
    assert [f.finish_reason for f in done] == ["stop", "length"]
    assert done[0].tokens == ref and done[0].tokens[-1] == stop
    assert done[1].n_out == MAX_NEW


def test_engine_rejects_overlong_cleanly(gpt_fix):
    """Bad user input (prompt + budget > max_seq_len) must not crash a
    shared engine (ISSUE 6 satellite): the request finishes with
    finish_reason='rejected' and the `serve_rejected` counter, no slot
    or prefill spent — and the engine keeps serving afterwards. An
    empty prompt is still a caller bug (assert)."""
    model, reqs = gpt_fix
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=1, max_seq_len=16, registry=reg)
    rid = engine.submit(list(range(12)), max_new_tokens=8)
    done = engine.drain()
    assert [f.req_id for f in done] == [rid]
    assert done[0].finish_reason == "rejected"
    assert done[0].n_out == 0 and done[0].tokens == list(range(12))
    assert reg.snapshot()["counters"]["serve_rejected"] == 1
    assert len(engine.traces["prefill"]) == 0  # no prefill ever paid
    with pytest.raises(AssertionError):
        engine.submit([], max_new_tokens=2)


def test_dispatch_expiry_hopeless_request_never_takes_slot(gpt_fix):
    """ISSUE 6 satellite: deadline expiry also runs with one decode-tick
    of lookahead at dispatch time — a queued request whose remaining
    deadline cannot cover even one tick expires WITHOUT burning a
    prefill or a slot, instead of being admitted and evicted a tick
    later."""
    model, _ = gpt_fix
    clk = _Clock()
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=1, max_seq_len=32, registry=reg,
                    clock=clk)
    engine._tick_s = [2.0]  # one sample = possibly the compile spike
    assert engine.tick_estimate_s() == 0.0  # ignored: no lookahead yet
    engine._tick_s = [0.1, 0.1]  # steady-state: 100 ms decode ticks
    assert engine.tick_estimate_s() == 0.1
    tid = engine.submit([1, 2, 3], max_new_tokens=4, deadline_ms=50.0)
    done = engine.step()  # 0 ms elapsed, but 100 ms to a first token
    assert [f.req_id for f in done] == [tid]
    assert done[0].finish_reason == "timeout" and done[0].n_out == 0
    assert len(engine.traces["prefill"]) == 0
    assert reg.snapshot()["counters"]["serve_timeouts"] == 1
    # a deadline that DOES cover a tick is untouched by the lookahead
    ok = engine.submit([1, 2, 3], max_new_tokens=2, deadline_ms=5000.0)
    out = {f.req_id: f for f in engine.drain()}
    assert out[ok].finish_reason == "length"


def test_engine_metrics_and_jsonl(gpt_fix, tmp_path):
    """Serving metrics flow through the schema-checked registry and the
    JSONL sink; obs_report summarizes the log (TTFT/TPOT percentiles)."""
    import time

    from avenir_tpu.obs.report import format_report, load_records, summarize

    model, _ = gpt_fix
    reg = MetricsRegistry()
    path = tmp_path / "metrics.jsonl"
    sink = JsonlSink(str(path))
    sink.write({"kind": "run_meta", "t": time.time(), "model_type": "gpt"})
    engine = Engine(model, n_slots=2, max_seq_len=32, registry=reg,
                    sink=sink, detokenize=lambda ts: "".join(
                        chr(97 + t % 26) for t in ts))
    for i in range(4):
        engine.submit([1, 2, 3 + i], max_new_tokens=4, top_k=8)
    done = engine.drain()
    sink.write({"kind": "run_end", "t": time.time(),
                "counters": reg.snapshot()["counters"]})
    sink.close()

    snap = reg.snapshot()
    assert snap["counters"]["serve_requests"] == 4
    assert snap["counters"]["tokens_out"] == 16
    assert snap["counters"]["serve_prefill_ms"] > 0
    assert snap["counters"]["serve_decode_ms"] > 0
    assert snap["gauges"]["queue_depth"] == 0
    assert snap["gauges"]["slot_occupancy"] == 0.0
    assert snap["hists"]["ttft_ms"]["count"] == 4
    assert snap["hists"]["tpot_ms"]["count"] == 4
    assert all(len(f.text) == f.n_out for f in done)  # incremental detok

    recs = load_records(str(path))
    assert sum(r["kind"] == "request" for r in recs) == 4
    s = summarize(recs)
    assert s["serve"]["n_requests"] == 4
    assert s["serve"]["ttft_p50_ms"] is not None
    assert "-- serving --" in format_report(s)


class _Clock:
    """Injectable engine clock: the deadline tests drive time forward
    instead of sleeping through real wall time."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_deadline_evicts_live_slot_survivors_bit_identical(gpt_fix):
    """A request that exceeds deadline_ms mid-decode is evicted with
    finish_reason='timeout' and its partial tokens; the co-tenant that
    survives stays BIT-IDENTICAL to its one-shot reference (eviction is
    the same slot-recycling path the stop-token tests pin)."""
    model, reqs = gpt_fix
    clk = _Clock()
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=2, max_seq_len=32, registry=reg,
                    clock=clk)
    kw_survivor, ref = reqs[1]  # plain length-terminated reference
    sid = engine.submit(**kw_survivor)
    tid = engine.submit([5, 6, 7], max_new_tokens=MAX_NEW,
                        deadline_ms=50.0)
    done = engine.step()  # both admitted, first token each
    assert done == []
    clk.t = 0.2  # 200 ms >> the 50 ms deadline
    done = engine.step()
    assert [f.req_id for f in done] == [tid]
    assert done[0].finish_reason == "timeout"
    assert done[0].n_out == 2  # kept its partial output
    assert done[0].ttft_ms is not None  # it did emit before timing out
    rest = {f.req_id: f for f in engine.drain()}
    assert rest[sid].tokens == ref, (rest[sid].tokens, ref)
    assert rest[sid].finish_reason in ("stop", "length")
    snap = reg.snapshot()["counters"]
    assert snap["serve_timeouts"] == 1
    assert snap["serve_requests"] == 2


def test_deadline_expires_queued_request_before_prefill(gpt_fix):
    """A request whose deadline passes while QUEUED is dropped before
    admission: no prefill dispatch, n_out=0, the slot-holder is
    untouched."""
    model, reqs = gpt_fix
    clk = _Clock()
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=1, max_seq_len=32, registry=reg,
                    clock=clk)
    kw_survivor, ref = reqs[1]
    sid = engine.submit(**kw_survivor)          # takes the only slot
    tid = engine.submit([9, 8, 7], max_new_tokens=MAX_NEW,
                        deadline_ms=50.0)       # queued behind it
    engine.step()
    n_prefills = len(engine.traces["prefill"])
    clk.t = 0.2
    done = engine.step()
    assert [f.req_id for f in done] == [tid]
    assert done[0].finish_reason == "timeout"
    assert done[0].n_out == 0 and done[0].ttft_ms is None
    assert done[0].tokens == [9, 8, 7]  # prompt only
    assert len(engine.traces["prefill"]) == n_prefills  # no prefill paid
    rest = {f.req_id: f for f in engine.drain()}
    assert rest[sid].tokens == ref
    snap = reg.snapshot()["counters"]
    assert snap["serve_timeouts"] == 1
    # the request record says timeout and omits ttft (percentile honesty)
    assert engine.sched.queue_depth == 0


def test_no_deadline_requests_never_time_out(gpt_fix):
    model, reqs = gpt_fix
    clk = _Clock()
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(), clock=clk)
    kw, ref = reqs[1]
    rid = engine.submit(**kw)
    clk.t = 1e6  # a million seconds of "wall time"
    out = {f.req_id: f for f in engine.drain()}
    assert out[rid].tokens == ref
    assert out[rid].finish_reason != "timeout"


def test_scheduler_bucket_ladder_bound():
    from avenir_tpu.infer.decode import bucket_ladder
    from avenir_tpu.serve.scheduler import FCFSScheduler

    sched = FCFSScheduler(2, 48)
    assert bucket_ladder(48) == (8, 16, 32, 48)
    for n in (1, 8, 9, 16, 17, 40, 48):
        assert sched.bucket(n) in sched.ladder
        assert sched.bucket(n) >= n
    assert sched.seen_buckets <= set(sched.ladder)


@pytest.mark.slow
def test_serve_bench_soak(tmp_path):
    """End-to-end load test through tools/serve_bench.py: seeded Poisson
    arrivals, metrics.jsonl out, obs_report-compatible."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log = tmp_path / "serve_metrics.jsonl"
    r = subprocess.run(
        [sys.executable, "tools/serve_bench.py", "--n_requests=12",
         "--rate=200", "--n_slots=3", "--max_new_tokens=8", "--seed=0",
         f"--metrics_log={log}"],
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ttft" in r.stdout and "p99" in r.stdout
    recs = [json.loads(l) for l in open(log)]
    assert sum(x["kind"] == "request" for x in recs) == 12
    assert recs[0]["kind"] == "run_meta" and recs[-1]["kind"] == "run_end"
