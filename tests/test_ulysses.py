"""Ulysses (all-to-all) sequence parallelism tests on the 8 fake CPU
devices: op-level equivalence to dense causal attention (fwd + grads, GQA
via the dispatch's repeat), and a GPT training trajectory on a
context-sharded mesh matching the single-device run — the same contract
the ring tests pin (tests/test_ring_attention.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from avenir_tpu.ops.attention import causal_attention, causal_attention_reference
from avenir_tpu.parallel.mesh import make_mesh
from avenir_tpu.parallel.ulysses import ulysses_causal_attention


@pytest.mark.parametrize("ctx", [2, 4, 8])
def test_ulysses_matches_dense(ctx):
    mesh = make_mesh(f"context:{ctx}")
    jax.set_mesh(mesh)
    B, T, H, D = 2, 64, 8, 16
    ks = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, H, D), jnp.float32)

    out = jax.jit(
        lambda q, k, v: ulysses_causal_attention(q, k, v, mesh=mesh)
    )(q, k, v)
    ref = causal_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_grads_match_dense():
    mesh = make_mesh("context:4")
    jax.set_mesh(mesh)
    B, T, H, D = 1, 32, 4, 8
    ks = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, H, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, H, D), jnp.float32)

    def loss_u(q, k, v):
        return ulysses_causal_attention(q, k, v, mesh=mesh).sum()

    def loss_r(q, k, v):
        return causal_attention_reference(q, k, v).sum()

    gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("ctx,Hkv", [
    (2, 2),   # c | H_kv: KV rides the all-to-all UNREPEATED (native GQA)
    (4, 2),   # c ∤ H_kv: minimal-repeat fallback (to H_kv=4 here)
])
def test_ulysses_gqa_through_dispatch(ctx, Hkv):
    """causal_attention(impl='ulysses') keeps GQA KV unrepeated whenever
    the context axis divides the KV head count (the local kernel resolves
    shared heads); otherwise it repeats by the smallest restoring factor."""
    mesh = make_mesh(f"context:{ctx}")
    jax.set_mesh(mesh)
    B, T, H, D = 1, 32, 8, 8
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)

    out = jax.jit(
        lambda q, k, v: causal_attention(q, k, v, impl="ulysses")
    )(q, k, v)
    kr = jnp.repeat(k, H // Hkv, axis=2)
    vr = jnp.repeat(v, H // Hkv, axis=2)
    ref = causal_attention_reference(q, kr, vr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_trajectory_matches_single_device(char_dataset, tmp_path):
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    common = dict(max_iters=5, gradient_accumulation_steps=4,
                  eval_interval=50, block_size=32)
    cfg1 = make_cfg(char_dataset["dir"], tmp_path / "o1",
                    mesh_shape="data:1", **common)
    ref = run_training(cfg1)
    cfg2 = make_cfg(char_dataset["dir"], tmp_path / "o2",
                    mesh_shape="data:2,context:2",
                    context_parallel_impl="ulysses", **common)
    got = run_training(cfg2)
    for (i1, l1), (i2, l2) in zip(ref["loss_history"], got["loss_history"]):
        assert i1 == i2
        np.testing.assert_allclose(l1, l2, atol=2e-3)
