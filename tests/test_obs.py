"""Observability layer tests (ISSUE 1 satellite): registry semantics,
JSONL sink round-trip, watchdog stall/healthy behavior, and a CPU
one-process run_training smoke asserting the metrics.jsonl contract."""

import json
import threading
import time

import numpy as np
import pytest

from avenir_tpu.obs import (
    METRIC_SCHEMA,
    JsonlSink,
    MetricsRegistry,
    NullSink,
    StallWatchdog,
    reset_registry,
)


# ---- registry ----

def test_registry_counter_gauge_histogram_semantics():
    reg = MetricsRegistry()
    c = reg.counter("compile_ms")
    c.add(10)
    c.add(2.5)
    assert c.total == 12.5 and c.events == 2
    assert reg.counter("compile_ms") is c  # get-or-create

    g = reg.gauge("loss")
    assert g.value is None
    g.set(3.0)
    g.set(2.5)
    assert g.value == 2.5

    h = reg.hist("window_dt_ms")
    for v in [5.0, 1.0, 3.0, 2.0, 4.0]:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 5 and s["sum"] == 15.0
    assert s["min"] == 1.0 and s["max"] == 5.0
    assert s["p50"] == 3.0

    snap = reg.snapshot()
    assert snap["counters"]["compile_ms"] == 12.5
    assert snap["gauges"]["loss"] == 2.5
    assert snap["hists"]["window_dt_ms"]["count"] == 5
    json.dumps(snap)  # snapshot must be JSON-serializable


def test_registry_rejects_undocumented_keys():
    reg = MetricsRegistry()
    with pytest.raises(AssertionError):
        reg.counter("not_a_documented_metric")
    with pytest.raises(AssertionError):
        reg.gauge("also_not_documented")
    # kind mismatch is as much schema drift as a missing key
    with pytest.raises(AssertionError):
        reg.gauge("compile_ms")  # declared as a counter


def test_registry_histogram_ring_bounds_memory():
    reg = MetricsRegistry()
    h = reg.hist("window_dt_ms")
    for i in range(5000):
        h.observe(float(i))
    assert len(h._ring) <= h.RING
    s = h.summary()
    assert s["count"] == 5000 and s["min"] == 0.0 and s["max"] == 4999.0


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("data_batches")

    def hammer():
        for _ in range(1000):
            c.add(1)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.total == 8000


# ---- sink ----

def test_jsonl_sink_round_trip(tmp_path):
    path = tmp_path / "metrics.jsonl"
    sink = JsonlSink(str(path))
    recs = [
        {"kind": "run_meta", "t": 1.0, "schema": 1, "iter": 0},
        {"kind": "iter", "t": 2.0, "iter": 0, "loss": 3.25,
         "counters": {"compile_ms": 12.0}},
        {"kind": "run_end", "t": 3.0, "iter": 5, "counters": {}},
    ]
    for r in recs:
        sink.write(r)
    sink.close()
    sink.write({"kind": "iter", "t": 9.0})  # post-close write: dropped, no raise
    back = [json.loads(line) for line in open(path)]
    assert back == recs  # every record parses, keys stable

    with pytest.raises(AssertionError):
        JsonlSink(str(tmp_path / "x.jsonl")).write({"kind": "nonsense"})

    ns = NullSink()  # the non-coordinator interface
    ns.write({"kind": "iter"})
    ns.close()


# ---- watchdog ----

def test_watchdog_fires_on_artificial_stall(capsys):
    reg = MetricsRegistry()
    wd = StallWatchdog(floor_secs=0.08, factor=2.0, poll_secs=0.02,
                       registry=reg, dump_stacks=False)
    try:
        wd.notify(window_secs=0.01, iter_num=3)
        time.sleep(0.5)  # no progress: well past the 0.08s floor
    finally:
        wd.stop()
    assert reg.counter("watchdog_stalls").total >= 1
    out = capsys.readouterr().out
    assert "no training window completed" in out
    assert "iter 3" in out


def test_watchdog_silent_on_healthy_loop(capsys):
    reg = MetricsRegistry()
    wd = StallWatchdog(floor_secs=0.2, factor=10.0, poll_secs=0.02,
                       registry=reg, dump_stacks=False)
    try:
        for i in range(20):
            wd.notify(window_secs=0.01, iter_num=i)
            time.sleep(0.02)
    finally:
        wd.stop()
    assert reg.counter("watchdog_stalls").total == 0
    assert "no training window" not in capsys.readouterr().out


def test_watchdog_pause_suppresses_firing_during_boundaries():
    """Declared host boundaries (eval, sync saves, expected compiles)
    must not fire the watchdog; a stall after the boundary still does."""
    reg = MetricsRegistry()
    wd = StallWatchdog(floor_secs=0.05, factor=2.0, poll_secs=0.01,
                       registry=reg, dump_stacks=False)
    try:
        wd.notify(window_secs=0.01, iter_num=1)
        with wd.pause():
            time.sleep(0.3)  # would fire several times without the pause
        assert reg.counter("watchdog_stalls").total == 0
        time.sleep(0.3)  # a real stall, outside any boundary
        assert reg.counter("watchdog_stalls").total >= 1
    finally:
        wd.stop()


def test_watchdog_fatal_escalation_exits_after_n_fires(capsys):
    """--watchdog_fatal_count (ISSUE 5 satellite): after N consecutive
    stall warnings with no progress, the watchdog dumps stacks one last
    time and calls the (injected) exit with the fatal code — a pod
    supervisor restarts the job from the last committed checkpoint."""
    reg = MetricsRegistry()

    class _Sink:
        records = []

        def write(self, r):
            self.records.append(r)

    exits = []
    wd = StallWatchdog(floor_secs=0.03, factor=2.0, poll_secs=0.01,
                       registry=reg, sink=_Sink(), dump_stacks=False,
                       fatal_count=3, exit_fn=exits.append)
    try:
        wd.notify(window_secs=0.01, iter_num=2)
        deadline = time.time() + 5.0
        while not exits and time.time() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert exits and exits[0] == StallWatchdog.FATAL_EXIT_CODE
    assert reg.counter("watchdog_stalls").total >= 3
    fatal_recs = [r for r in _Sink.records if r.get("fatal")]
    assert fatal_recs and fatal_recs[0]["kind"] == "stall"
    out = capsys.readouterr().out
    assert "FATAL" in out and "python stacks" in out


def test_watchdog_fatal_counter_resets_on_progress():
    """Progress between warnings resets the consecutive count: a loop
    that stalls, recovers, and stalls again must NOT accumulate toward
    the fatal exit across recoveries."""
    reg = MetricsRegistry()
    exits = []
    # fire _fire directly (floor/poll park the real thread): the poll
    # cadence is load-sensitive, and a stretched sleep on a busy CI box
    # could legitimately accumulate fatal_count fires in ONE gap — the
    # reset property needs deterministic driving
    wd = StallWatchdog(floor_secs=100.0, factor=2.0, poll_secs=100.0,
                       registry=reg, dump_stacks=False, fatal_count=4,
                       exit_fn=exits.append, echo=lambda m: None)
    try:
        for _ in range(3):
            wd._fire(1.0, 0.5)
        assert not exits  # 3 consecutive < fatal_count
        wd.notify(window_secs=0.01, iter_num=1)  # progress resets
        for _ in range(3):
            wd._fire(1.0, 0.5)
        assert not exits  # reset worked: 3 again, not 6
        wd._fire(1.0, 0.5)  # 4th consecutive without progress
        assert exits == [StallWatchdog.FATAL_EXIT_CODE]
        assert reg.counter("watchdog_stalls").total == 7
    finally:
        wd.stop()


def test_watchdog_threshold_tracks_median():
    wd = StallWatchdog(floor_secs=1.0, factor=10.0, poll_secs=10.0,
                       dump_stacks=False)
    try:
        assert wd.threshold_secs() == 1.0  # floor until windows land
        for _ in range(9):
            wd.notify(window_secs=2.0)
        assert wd.threshold_secs() == pytest.approx(20.0)  # 10x median
    finally:
        wd.stop()


# ---- training smoke: the metrics.jsonl contract ----

def _smoke_cfg(data_dir, out_dir, **over):
    cfg = dict(
        out_dir=str(out_dir), eval_interval=50, log_interval=1, eval_iters=2,
        eval_only=False, always_save_checkpoint=True, init_from="scratch",
        wandb_log=False, wandb_project="t", wandb_run_name="t",
        dataset=str(data_dir), gradient_accumulation_steps=8, batch_size=4,
        block_size=32, model_type="gpt", n_layer=2, n_head=2, n_embd=32,
        dropout=0.0, bias=False, n_kv_head=0, ffn_hidden=0,
        rope_theta=10000.0, n_experts=8, n_experts_per_tok=2,
        capacity_factor=1.25,
        learning_rate=1e-3, max_iters=15, weight_decay=0.1, beta1=0.9,
        beta2=0.95, grad_clip=1.0, decay_lr=True, warmup_iters=2,
        lr_decay_iters=15, min_lr=1e-4, backend="tpu", device="cpu",
        dtype="float32", compile=False, seed=1337, mesh_shape="data:1",
        remat=False, scan_layers=False, use_pallas=False, fused_adamw=False,
        profile=False, allow_unsharded_fallback=True,
        metrics_log=True, watchdog_secs=60.0,
    )
    cfg.update(over)
    return cfg


def test_run_training_writes_metrics_jsonl(char_dataset, tmp_path):
    """Acceptance: a CPU run with --metrics_log=True produces a parseable
    metrics.jsonl whose iter records exactly match loss_history, and the
    goodput components sum to within 5% of loop wall time."""
    from avenir_tpu.obs.report import format_report, load_records, summarize
    from avenir_tpu.train.loop import run_training

    reset_registry()  # counters from other tests must not leak in
    out = tmp_path / "out"
    res = run_training(_smoke_cfg(char_dataset["dir"], out))

    path = out / "metrics.jsonl"
    assert path.exists()
    records = load_records(str(path))
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "run_meta" and kinds[-1] == "run_end"

    iters = [r for r in records if r["kind"] == "iter"]
    it_nums = [r["iter"] for r in iters]
    assert it_nums == sorted(it_nums) and len(set(it_nums)) == len(it_nums)
    assert all(np.isfinite(r["loss"]) for r in iters)
    # per-iter loss values EXACTLY match the returned loss_history
    assert [(r["iter"], r["loss"]) for r in iters] == res["loss_history"]
    # cumulative counters ride along on every iter record
    assert all("counters" in r for r in iters)

    s = summarize(records)
    report = format_report(s)
    assert "goodput" in report and "device" in report
    # the acceptance bound: tracked components sum to within 5% of total
    assert s["coverage"] is not None
    assert abs(s["tracked_ms"] - s["total_ms"]) <= 0.05 * s["total_ms"], (
        f"goodput components cover {100 * s['coverage']:.1f}% of wall time: "
        f"{s['components']} vs total {s['total_ms']:.1f}ms"
    )
    # healthy run: the watchdog stayed silent
    assert not [r for r in records if r["kind"] == "stall"]


def test_metrics_log_off_writes_nothing(char_dataset, tmp_path):
    from avenir_tpu.train.loop import run_training

    out = tmp_path / "out"
    run_training(_smoke_cfg(char_dataset["dir"], out, max_iters=3,
                            metrics_log=False, watchdog_secs=0.0))
    assert not (out / "metrics.jsonl").exists()


def test_loader_rejects_oversized_vocab(char_dataset):
    """ADVICE r5: a Llama-3-sized 128k vocab must fail loud at loader
    construction, not wrap token ids modulo 65536 on the uint16 wire."""
    from avenir_tpu.data.loader import DataLoader

    DataLoader(char_dataset["dir"], 32, 4, vocab_size=65536)  # fits
    with pytest.raises(AssertionError, match="wire"):
        DataLoader(char_dataset["dir"], 32, 4, vocab_size=128_256)
