"""Test harness config (SURVEY.md §4): force jax onto CPU with 8 virtual
devices so Mesh/SPMD/collective tests run without TPU hardware. Must happen
before anything imports jax."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The axon sandbox sets JAX_PLATFORMS=axon and imports jax from a
# sitecustomize before this conftest runs, so the env var alone is not
# enough — pin the platform through the live config too.
import jax

jax.config.update("jax_platforms", "cpu")

# NB: do NOT enable the persistent compilation cache here — measured on
# this runtime (jax 0.4.37, XLA:CPU, 8 virtual devices), re-loading
# cached SPMD executables segfaults the interpreter partway through the
# suite. Recompiling every program is slower but correct.

# repo root on sys.path so `import model`, `import train` etc. work from tests/
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: multi-subprocess integration tests")


# multi-minute end-to-end trajectory files; everything else first so a
# time-capped CI window (the tier-1 870s budget) reports the broad suite
# before the heaviest integration runs start
_HEAVY_FILES = ("test_pipeline.py", "test_pallas_spmd.py")


def pytest_collection_modifyitems(config, items):
    items.sort(key=lambda it: it.fspath.basename in _HEAVY_FILES)


# call-phase wall time of every completed non-slow test, keyed by nodeid
# — consumed by tests/test_zz_slow_guard.py (which sorts after every
# normal file and before the _HEAVY_FILES block) to assert that new
# >5s cases carry the `slow` mark, so the 870s tier-1 budget survives
# the growing suite (ISSUE 3 satellite).
TEST_DURATIONS = {}


def pytest_runtest_logreport(report):
    if report.when == "call" and report.passed:
        TEST_DURATIONS[report.nodeid] = (
            report.duration, "slow" in report.keywords)


def pytest_sessionfinish(session, exitstatus):
    """Per-FILE duration report artifact (ISSUE 12 satellite): tier-1
    on this container is timeout-bound, so every run leaves a JSON
    ranking of where the 870s budget went — the first thing to read
    when the suite creeps toward the wall. Path override:
    AVENIR_TEST_DURATIONS (set empty to disable)."""
    import json
    import tempfile

    path = os.environ.get(
        "AVENIR_TEST_DURATIONS",
        os.path.join(tempfile.gettempdir(),
                     "avenir_test_file_durations.json"))
    if not path or not TEST_DURATIONS:
        return
    per_file = {}
    for nodeid, (dur, _slow) in TEST_DURATIONS.items():
        f = per_file.setdefault(nodeid.split("::")[0],
                                {"calls": 0, "secs": 0.0})
        f["calls"] += 1
        f["secs"] += dur
    ranked = sorted(per_file.items(), key=lambda kv: -kv[1]["secs"])
    try:
        with open(path, "w") as fh:
            json.dump({
                "total_call_secs": round(
                    sum(v["secs"] for v in per_file.values()), 2),
                "n_tests": len(TEST_DURATIONS),
                "files": [{"file": k, "secs": round(v["secs"], 2),
                           "calls": v["calls"]} for k, v in ranked],
            }, fh, indent=1)
    except OSError:
        pass  # a read-only tmpdir must not fail the suite


from avenir_tpu.compat import get_mesh, install_jax_compat, set_mesh  # noqa: E402

install_jax_compat()  # legacy runtimes: give tests the modern jax.set_mesh API

_DEFAULT_MESH = get_mesh()  # the empty mesh, captured pre-tests


@pytest.fixture(autouse=True)
def _reset_ambient_mesh():
    """The training loop and some tests install a global context mesh via
    set_mesh and never unset it (there is no public unset); a leaked
    1-device mesh makes any later jit over a different mesh fail with
    'incompatible devices'. Restore the empty default around every test so
    ordering never matters."""
    yield
    set_mesh(_DEFAULT_MESH)


@pytest.fixture(scope="session")
def char_dataset(tmp_path_factory):
    """Tiny deterministic char-level dataset in the nanoGPT on-disk layout."""
    from avenir_tpu.utils.corpus import synthetic_corpus, write_char_dataset

    root = tmp_path_factory.mktemp("data") / "shakespeare_char"
    text = synthetic_corpus(n_chars=60_000, seed=7)
    meta = write_char_dataset(str(root), text)
    return {"dir": str(root), "meta": meta}


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
