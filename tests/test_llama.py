"""Llama parity tests: our nnx Llama vs transformers' torch
LlamaForCausalLM on shared random weights (the strongest available oracle
— the HF implementation defines the reference RoPE/GQA/RMSNorm
semantics). SURVEY.md §4 "Unit: model parity"."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.checkpoint.bridge import load_torch_state_dict
from avenir_tpu.models.llama import Llama, LlamaConfig

TINY = dict(
    block_size=32, vocab_size=96, n_layer=2, n_head=4, n_kv_head=2,
    n_embd=64, ffn_hidden=128, rope_theta=10000.0,
)


def _hf_llama():
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    hf_cfg = HFConfig(
        vocab_size=TINY["vocab_size"], hidden_size=TINY["n_embd"],
        intermediate_size=TINY["ffn_hidden"],
        num_hidden_layers=TINY["n_layer"],
        num_attention_heads=TINY["n_head"],
        num_key_value_heads=TINY["n_kv_head"],
        max_position_embeddings=TINY["block_size"],
        rms_norm_eps=1e-5, rope_theta=TINY["rope_theta"],
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    m = LlamaForCausalLM(hf_cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def pair():
    tm = _hf_llama()
    jm = Llama(LlamaConfig(**TINY), rngs=nnx.Rngs(0))
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()}
    load_torch_state_dict(jm, sd, tied_lm_head=False)
    return tm, jm


def test_logits_parity(pair):
    tm, jm = pair
    rng = np.random.default_rng(0)
    idx = rng.integers(0, TINY["vocab_size"], (2, 24))
    with torch.no_grad():
        t_logits = tm(torch.from_numpy(idx)).logits
    j_logits, _ = jm(jnp.asarray(idx), jnp.asarray(idx))
    np.testing.assert_allclose(
        np.asarray(j_logits), t_logits.numpy(), atol=2e-4, rtol=2e-4
    )


def test_loss_matches_torch_ce(pair):
    tm, jm = pair
    rng = np.random.default_rng(1)
    idx = rng.integers(0, TINY["vocab_size"], (2, 16))
    tgt = rng.integers(0, TINY["vocab_size"], (2, 16))
    tgt[0, :3] = -1
    with torch.no_grad():
        t_logits = tm(torch.from_numpy(idx)).logits
        t_loss = torch.nn.functional.cross_entropy(
            t_logits.reshape(-1, TINY["vocab_size"]),
            torch.from_numpy(tgt).reshape(-1), ignore_index=-1,
        )
    _, j_loss = jm(jnp.asarray(idx), jnp.asarray(tgt))
    np.testing.assert_allclose(float(j_loss), float(t_loss), atol=2e-5,
                               rtol=2e-5)


def test_gqa_head_counts(pair):
    _, jm = pair
    att = jm.layers[0].self_attn
    assert att.q_proj.kernel[...].shape == (64, 64)
    assert att.k_proj.kernel[...].shape == (64, 32)  # 2 kv heads × 16


def test_llama_trains_end_to_end(char_dataset, tmp_path):
    """model_type=llama through the real training loop (tiny)."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=10,
                   mesh_shape="data:1", model_type="llama", n_kv_head=2,
                   n_head=4, n_embd=32, ffn_hidden=64, eval_interval=5)
    res = run_training(cfg)
    losses = [l for _, l in res["loss_history"]]
    assert losses[-1] < losses[0], losses
    # resume from the saved checkpoint (avenir_adamw optimizer schema)
    cfg2 = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=12,
                    mesh_shape="data:1", model_type="llama", n_kv_head=2,
                    n_head=4, n_embd=32, ffn_hidden=64, eval_interval=5,
                    init_from="resume")
    res2 = run_training(cfg2)
    assert res2["iter_num"] >= 12
