"""TPU-backend training tests (SURVEY.md §4): optimizer parity vs torch
AdamW, single-device training, multi-device SPMD trajectory equivalence on
the 8 fake CPU devices, HLO collective assertions, and cross-backend
checkpoint resume through subprocesses."""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cfg(data_dir, out_dir, **over):
    cfg = dict(
        out_dir=str(out_dir), eval_interval=50, log_interval=1, eval_iters=2,
        eval_only=False, always_save_checkpoint=True, init_from="scratch",
        wandb_log=False, wandb_project="t", wandb_run_name="t",
        dataset=str(data_dir), gradient_accumulation_steps=2, batch_size=4,
        block_size=32, model_type="gpt", n_layer=2, n_head=2, n_embd=32,
        dropout=0.0, bias=False, n_kv_head=0, ffn_hidden=0,
        rope_theta=10000.0, n_experts=8, n_experts_per_tok=2,
        capacity_factor=1.25,
        learning_rate=1e-3, max_iters=8, weight_decay=0.1, beta1=0.9,
        beta2=0.95, grad_clip=1.0, decay_lr=True, warmup_iters=2,
        lr_decay_iters=8, min_lr=1e-4, backend="tpu", device="cpu",
        dtype="float32", compile=False, seed=1337, mesh_shape="",
        remat=False, scan_layers=False, use_pallas=False, fused_adamw=False,
        profile=False,
        # tiny char vocab (65) doesn't divide tensor:2 meshes; tests accept
        # the replication fallback (strict-mode behavior is unit-tested in
        # test_partition.py)
        allow_unsharded_fallback=True,
    )
    cfg.update(over)
    return cfg


def test_optimizer_matches_torch_adamw():
    """Our optax chain must implement exactly torch AdamW + clip + the
    decay mask + cosine schedule (model.py:255-271, train.py:233-240)."""
    import torch

    from avenir_tpu.train.optimizer import make_optimizer

    w0 = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
    b0 = np.random.default_rng(1).normal(size=(4,)).astype(np.float32)
    grads_seq = [
        {
            "w": np.random.default_rng(10 + i).normal(size=(4, 4)).astype(np.float32) * 3,
            "b": np.random.default_rng(20 + i).normal(size=(4,)).astype(np.float32) * 3,
        }
        for i in range(5)
    ]
    hp = dict(learning_rate=1e-2, weight_decay=0.1, beta1=0.9, beta2=0.95,
              grad_clip=1.0, warmup_iters=2, lr_decay_iters=5, min_lr=1e-3)

    # --- torch ---
    tw = torch.nn.Parameter(torch.from_numpy(w0.copy()))
    tb = torch.nn.Parameter(torch.from_numpy(b0.copy()))
    opt = torch.optim.AdamW(
        [{"params": [tw], "weight_decay": 0.1},
         {"params": [tb], "weight_decay": 0.0}],
        lr=1e-2, betas=(0.9, 0.95), eps=1e-8,
    )
    import math

    def get_lr(it):
        if it < hp["warmup_iters"]:
            return hp["learning_rate"] * (it + 1) / (hp["warmup_iters"] + 1)
        if it > hp["lr_decay_iters"]:
            return hp["min_lr"]
        r = (it - hp["warmup_iters"]) / (hp["lr_decay_iters"] - hp["warmup_iters"])
        c = 0.5 * (1.0 + math.cos(math.pi * r))
        return hp["min_lr"] + c * (hp["learning_rate"] - hp["min_lr"])

    for i, g in enumerate(grads_seq):
        for pg in opt.param_groups:
            pg["lr"] = get_lr(i)
        tw.grad = torch.from_numpy(g["w"].copy())
        tb.grad = torch.from_numpy(g["b"].copy())
        torch.nn.utils.clip_grad_norm_([tw, tb], hp["grad_clip"])
        opt.step()
        opt.zero_grad()

    # --- ours ---
    params = {"w": jnp.asarray(w0), "b": jnp.asarray(b0)}
    tx, _ = make_optimizer(params, decay_lr=True, **hp)
    state = tx.init(params)
    import optax

    for g in grads_seq:
        gj = {"w": jnp.asarray(g["w"]), "b": jnp.asarray(g["b"])}
        updates, state = tx.update(gj, state, params)
        params = optax.apply_updates(params, updates)

    np.testing.assert_allclose(np.asarray(params["w"]), tw.detach().numpy(),
                               atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(params["b"]), tb.detach().numpy(),
                               atol=1e-6, rtol=1e-5)


def test_single_device_training_reduces_loss(char_dataset, tmp_path):
    from avenir_tpu.train.loop import run_training

    cfg = make_cfg(char_dataset["dir"], tmp_path / "out", max_iters=15,
                   mesh_shape="data:1")
    res = run_training(cfg)
    losses = [l for _, l in res["loss_history"]]
    assert losses[0] > 3.0  # ~ln(vocab)
    assert losses[-1] < losses[0] - 0.1, f"no learning: {losses}"


def test_multi_step_dispatch_matches_single_steps():
    """jit_multi_train_step (K optimizer steps per dispatch, lax.scan over
    the step axis — bench.py's dispatch mode) must reproduce K single-step
    calls bit-for-bit with dropout=0: same params, same per-step losses."""
    from flax import nnx

    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import (
        jit_multi_train_step, jit_train_step, make_step_fns,
    )

    K = 3
    cfg = GPTConfig(block_size=16, vocab_size=64, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=False, attn_impl="xla")
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.integers(0, 64, (K, 2, 2, 16)).astype(np.int32))
    ys = jnp.asarray(rng.integers(0, 64, (K, 2, 2, 16)).astype(np.int32))

    def fresh():
        model = GPT(cfg, rngs=nnx.Rngs(0))
        graphdef, params = nnx.split(model, nnx.Param)
        tx, _ = make_optimizer(params, learning_rate=1e-3, weight_decay=0.1,
                               beta1=0.9, beta2=0.95, grad_clip=1.0,
                               warmup_iters=2, lr_decay_iters=10, min_lr=1e-4)
        opt_state = jax.jit(tx.init)(params)
        step_fn, _ = make_step_fns(graphdef, dropout=0.0)
        return params, opt_state, step_fn, tx

    key = jax.random.key(0)
    # K single dispatches (rng split mirrors the multi path's)
    params, opt_state, step_fn, tx = fresh()
    single = jit_train_step(step_fn, tx)
    step_rngs = jax.random.split(key, K)
    losses_single = []
    for i in range(K):
        params, opt_state, m = single(params, opt_state, step_rngs[i],
                                      xs[i], ys[i])
        losses_single.append(float(m["loss"]))
    # one multi dispatch
    params2, opt_state2, step_fn2, tx2 = fresh()
    multi = jit_multi_train_step(step_fn2, tx2)
    params2, opt_state2, ms = multi(params2, opt_state2, key, xs, ys)
    np.testing.assert_allclose(np.asarray(ms["loss"]),
                               np.asarray(losses_single), rtol=1e-6)
    for (pa, a), (pb, b) in zip(params.flat_state(), params2.flat_state()):
        assert pa == pb
        np.testing.assert_allclose(np.asarray(a.get_value()),
                                   np.asarray(b.get_value()), rtol=1e-6,
                                   atol=1e-7)


def test_windowed_loop_matches_single_dispatch(char_dataset, tmp_path):
    """--dispatch_steps is pure dispatch granularity: the windowed loop
    (auto windows, fold_in rngs inside the scan) must reproduce the
    single-dispatch loop's loss history EXACTLY — same iters logged, same
    values (identical rng and batch streams; VERDICT r3 item 2). The
    window cap of 3 forces windows to split mid-eval-interval, covering
    remainder windows too."""
    from avenir_tpu.train.loop import run_training

    cfg1 = make_cfg(char_dataset["dir"], tmp_path / "o1", max_iters=7,
                    eval_interval=5, dispatch_steps=1, mesh_shape="data:1")
    ref = run_training(cfg1)
    cfg3 = make_cfg(char_dataset["dir"], tmp_path / "o2", max_iters=7,
                    eval_interval=5, dispatch_steps=3, mesh_shape="data:1")
    got = run_training(cfg3)
    assert [i for i, _ in ref["loss_history"]] == \
        [i for i, _ in got["loss_history"]]
    np.testing.assert_allclose(
        np.array([l for _, l in got["loss_history"]]),
        np.array([l for _, l in ref["loss_history"]]), rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("mesh_shape", ["data:8", "data:2,fsdp:4",
                                        "data:2,fsdp:2,tensor:2"])
def test_spmd_trajectory_matches_single_device(char_dataset, tmp_path, mesh_shape):
    """DP/FSDP/TP must be pure layout: the loss trajectory on any mesh must
    equal the single-device trajectory to fp32 tolerance (same seeds, same
    global batch)."""
    from avenir_tpu.train.loop import run_training

    cfg1 = make_cfg(char_dataset["dir"], tmp_path / "o1", max_iters=6,
                    gradient_accumulation_steps=8, mesh_shape="data:1")
    ref = run_training(cfg1)
    cfgN = make_cfg(char_dataset["dir"], tmp_path / "o2", max_iters=6,
                    gradient_accumulation_steps=8, mesh_shape=mesh_shape)
    got = run_training(cfgN)
    ref_l = np.array([l for _, l in ref["loss_history"]])
    got_l = np.array([l for _, l in got["loss_history"]])
    np.testing.assert_allclose(got_l, ref_l, atol=2e-4, rtol=2e-4)


def test_fsdp_hlo_contains_collectives(char_dataset):
    """FSDP layout must actually emit gather/scatter collectives
    (SURVEY.md §4 'HLO contains expected collectives')."""
    from flax import nnx
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.models.gpt import GPTConfig
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.train.loop import setup_state
    from avenir_tpu.train.optimizer import make_optimizer
    from avenir_tpu.train.step import make_step_fns

    mesh = make_mesh("fsdp:8")
    cfg = make_cfg("x", "y", mesh_shape="fsdp:8")
    model_args = dict(n_layer=2, n_head=2, n_embd=32, block_size=32,
                      bias=False, vocab_size=64, dropout=0.0)
    st = setup_state(cfg, mesh, model_args, verbose=False)

    def init_fn():
        return nnx.split(st["ctor"](0), nnx.Param)[1]

    params = jax.jit(init_fn, out_shardings=st["shard_tree"])()
    tx, _ = make_optimizer(
        params, learning_rate=1e-3, weight_decay=0.1, beta1=0.9, beta2=0.95,
        grad_clip=1.0, warmup_iters=2, lr_decay_iters=8, min_lr=1e-4,
    )
    opt_state = jax.jit(tx.init)(params)
    train_step, _ = make_step_fns(st["graphdef"], dropout=0.0)

    bs = NamedSharding(mesh, P(None, ("data", "fsdp"), None))
    x = jax.device_put(np.zeros((1, 8, 32), np.int32), bs)
    lowered = jax.jit(
        lambda p, o, r, xx, yy: train_step(p, o, tx, r, xx, yy)
    ).lower(params, opt_state, jax.random.key(0), x, x)
    hlo = lowered.compile().as_text()
    assert ("all-gather" in hlo or "all-reduce" in hlo
            or "reduce-scatter" in hlo), "no collectives in FSDP HLO"


def test_resume_restores_schedule_count(char_dataset, tmp_path):
    """Resume must restore the LR schedule position, not just adam moments
    — otherwise warmup silently replays (regression test for the
    ScaleByScheduleState count)."""
    from avenir_tpu.train.loop import run_training

    out = tmp_path / "out"
    cfg = make_cfg(char_dataset["dir"], out, max_iters=6, eval_interval=3,
                   mesh_shape="data:1")
    run_training(cfg)
    cfg2 = make_cfg(char_dataset["dir"], out, max_iters=6, eval_interval=3,
                    mesh_shape="data:1", init_from="resume")
    # run 0 extra iters — just restore and verify counts
    from avenir_tpu.checkpoint.io import _find_adam_state, load_checkpoint

    ckpt = load_checkpoint(str(out))
    saved_iters = ckpt["iter_num"]
    assert saved_iters > 0

    import jax
    from flax import nnx

    from avenir_tpu.checkpoint.io import restore_opt_state, restore_params
    from avenir_tpu.parallel.mesh import make_mesh
    from avenir_tpu.train.loop import setup_state
    from avenir_tpu.train.optimizer import make_optimizer

    mesh = make_mesh("data:1")
    model_args = dict(ckpt["model_args"])
    model_args["dropout"] = 0.0
    st = setup_state(cfg2, mesh, model_args, verbose=False)
    params = restore_params(ckpt, st["abs_state"], st["shardings"])
    tx, _ = make_optimizer(
        params, learning_rate=1e-3, weight_decay=0.1, beta1=0.9, beta2=0.95,
        grad_clip=1.0, warmup_iters=2, lr_decay_iters=8, min_lr=1e-4,
    )
    opt_state = restore_opt_state(
        ckpt, jax.jit(tx.init)(params), params, st["shardings"]
    )
    adam = _find_adam_state(opt_state)
    assert int(adam.count) == saved_iters
    # every count-bearing node (incl. the schedule state) agrees
    def collect(node, acc):
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            if "count" in node._fields:
                acc.append(int(np.asarray(node.count)))
            for c in node:
                collect(c, acc)
        elif isinstance(node, tuple):
            for c in node:
                collect(c, acc)
        return acc

    counts = collect(opt_state, [])
    assert counts and all(c == saved_iters for c in counts), counts


@pytest.mark.slow
def test_cross_backend_checkpoint_resume(char_dataset, tmp_path):
    """train 10 iters torch → resume tpu → resume torch again; loss keeps
    falling and nothing crashes (SURVEY.md §4 'Integration: ckpt
    round-trip')."""
    out = str(tmp_path / "out")
    common = [
        sys.executable, "train.py",
        f"--dataset={char_dataset['dir']}", f"--out_dir={out}",
        "--device=cpu", "--compile=False", "--eval_interval=10",
        "--eval_iters=2", "--log_interval=5", "--batch_size=4",
        "--block_size=32", "--n_layer=2", "--n_head=2", "--n_embd=32",
        "--dropout=0.0", "--gradient_accumulation_steps=2",
        "--always_save_checkpoint=True", "--warmup_iters=2",
        "--lr_decay_iters=30", "--learning_rate=1e-3",
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(extra):
        r = subprocess.run(common + extra, cwd=REPO, env=env,
                           capture_output=True, text=True, timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr
        return r.stdout

    run(["--max_iters=10"])  # torch from scratch
    out2 = run(["--max_iters=20", "--backend=tpu", "--init_from=resume",
                "--mesh_shape=data:1"])
    assert "resuming" in out2
    out3 = run(["--max_iters=30", "--init_from=resume"])
    # torch resumed from the jax-written ckpt at iter 20
    assert "iter 25" in out3 or "iter 30" in out3
