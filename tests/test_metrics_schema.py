"""Schema lint (ISSUE 1 satellite): the metrics.jsonl contract lives in
exactly two places — METRIC_SCHEMA in avenir_tpu/obs/metrics.py (enforced
at metric creation) and the docs/OBSERVABILITY.md tables (what operators
read). This fast test pins the two against each other AND walks the
instrumented source for registry calls, so neither an undocumented metric
nor a stale doc row can land silently."""

import os
import re

from avenir_tpu.obs.metrics import METRIC_SCHEMA, MetricsRegistry
from avenir_tpu.obs.sink import RECORD_KINDS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")


def _doc_table_keys(text, header_key):
    """Backticked keys from first column of the table whose header row
    starts with `| header_key |`."""
    keys = []
    in_table = False
    for line in text.splitlines():
        if line.replace(" ", "").startswith(f"|{header_key}|"):
            in_table = True
            continue
        if in_table:
            if not line.startswith("|"):
                break
            m = re.match(r"\|\s*`([^`]+)`\s*\|", line)
            if m:
                keys.append(m.group(1))
    return keys


def test_doc_metric_table_matches_schema():
    text = open(DOC).read()
    doc_keys = _doc_table_keys(text, "key")
    assert doc_keys, "metric-key table not found in docs/OBSERVABILITY.md"
    assert set(doc_keys) == set(METRIC_SCHEMA), (
        "docs/OBSERVABILITY.md metric table drifted from METRIC_SCHEMA:\n"
        f"  undocumented: {sorted(set(METRIC_SCHEMA) - set(doc_keys))}\n"
        f"  stale doc rows: {sorted(set(doc_keys) - set(METRIC_SCHEMA))}"
    )
    assert len(doc_keys) == len(set(doc_keys)), "duplicate doc rows"


def test_doc_kind_table_matches_record_kinds():
    text = open(DOC).read()
    doc_kinds = _doc_table_keys(text, "kind")
    assert doc_kinds, "record-kind table not found in docs/OBSERVABILITY.md"
    assert set(doc_kinds) == RECORD_KINDS, (
        f"docs kinds {sorted(doc_kinds)} != RECORD_KINDS {sorted(RECORD_KINDS)}"
    )


def test_doc_unit_types_match_schema():
    """Each doc row's type column must agree with the schema kind."""
    text = open(DOC).read()
    rows = re.findall(r"\|\s*`([^`]+)`\s*\|\s*(counter|gauge|hist)\s*\|", text)
    assert rows
    for key, kind in rows:
        assert METRIC_SCHEMA[key][0] == kind, (
            f"{key}: documented as {kind}, schema says {METRIC_SCHEMA[key][0]}"
        )


_REG_CALL = re.compile(
    r"""(?:reg|registry|self\._reg|get_registry\(\))\s*
        \.\s*(counter|gauge|hist)\s*\(\s*(?:f?["']([^"']+)["'])""",
    re.VERBOSE,
)


def test_source_emits_only_documented_keys():
    """Every literal metric key the instrumented source passes to
    registry.counter/gauge/hist must be in METRIC_SCHEMA with the right
    kind (the registry also enforces this at runtime; here it is caught
    without running a training loop)."""
    found = {}
    for dirpath, _, files in os.walk(os.path.join(REPO, "avenir_tpu")):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            src = open(os.path.join(dirpath, fn)).read()
            for kind, key in _REG_CALL.findall(src):
                found.setdefault(key, set()).add(kind)
    assert found, "no registry calls found — did the instrumentation move?"
    for key, kinds in sorted(found.items()):
        assert key in METRIC_SCHEMA, f"undocumented metric key {key!r} in source"
        for kind in kinds:
            assert METRIC_SCHEMA[key][0] == kind, (
                f"{key}: source uses .{kind}(), schema says "
                f"{METRIC_SCHEMA[key][0]}"
            )


def test_trace_schema_keys_pinned():
    """ISSUE 10: the tracing/flight-recorder keys and the `trace`
    record kind are part of the pinned contract (the set-equality tests
    above enforce the doc mirror; this names them explicitly so a
    future schema prune cannot drop them silently)."""
    assert METRIC_SCHEMA["trace_events_dropped"][0] == "counter"
    assert METRIC_SCHEMA["flight_dumps"][0] == "counter"
    assert "trace" in RECORD_KINDS
    from avenir_tpu.obs.trace import TERMINAL, TRACE_EVENTS

    assert TERMINAL in TRACE_EVENTS
    # the doc's event table mirrors TRACE_EVENTS (same policy as the
    # metric table)
    text = open(DOC).read()
    doc_events = _doc_table_keys(text, "event")
    assert set(doc_events) == TRACE_EVENTS, (
        f"docs/OBSERVABILITY.md event table drifted from TRACE_EVENTS:\n"
        f"  undocumented: {sorted(TRACE_EVENTS - set(doc_events))}\n"
        f"  stale doc rows: {sorted(set(doc_events) - TRACE_EVENTS)}"
    )


def test_anomaly_schema_keys_pinned():
    """ISSUE 14: the fleet health engine's keys, the `anomaly` record
    kind and trace event are part of the pinned contract (the
    set-equality tests above enforce the doc mirror; named explicitly
    so a schema prune cannot drop them silently)."""
    assert METRIC_SCHEMA["anomaly"][0] == "counter"
    assert METRIC_SCHEMA["anomalies_suppressed"][0] == "counter"
    assert METRIC_SCHEMA["step_time_ms"][0] == "hist"
    assert METRIC_SCHEMA["queue_wait_ms"][0] == "hist"
    for g in ("step_time_p99_ms", "ttft_p99_ms", "tpot_p99_ms",
              "queue_wait_p99_ms"):
        assert METRIC_SCHEMA[g][0] == "gauge"
    assert "anomaly" in RECORD_KINDS
    from avenir_tpu.obs.trace import TRACE_EVENTS

    assert "anomaly" in TRACE_EVENTS


def test_doc_detector_table_matches_schema():
    """The detector table is schema-pinned exactly like METRIC_SCHEMA:
    docs/OBSERVABILITY.md's "Anomaly detection & perf gate" table must
    mirror anomaly.DETECTOR_SCHEMA, and every detector's series key
    must itself be a declared metric."""
    from avenir_tpu.obs.anomaly import DETECTOR_SCHEMA

    text = open(DOC).read()
    doc_rows = _doc_table_keys(text, "detector")
    assert doc_rows, "detector table not found in docs/OBSERVABILITY.md"
    assert set(doc_rows) == set(DETECTOR_SCHEMA), (
        "docs detector table drifted from DETECTOR_SCHEMA:\n"
        f"  undocumented: {sorted(set(DETECTOR_SCHEMA) - set(doc_rows))}\n"
        f"  stale doc rows: {sorted(set(doc_rows) - set(DETECTOR_SCHEMA))}"
    )
    for name, (key, method, _desc) in DETECTOR_SCHEMA.items():
        assert key in METRIC_SCHEMA, (
            f"detector {name} watches undeclared series key {key!r}")
        assert method in ("drift", "trend", "collapse", "level")


def test_span_counter_keys_resolve():
    """span() derives `{name}_ms` from the annotation name unless given
    an explicit counter; both paths must land on schema keys."""
    reg = MetricsRegistry()
    from avenir_tpu.obs.spans import span

    for name in ("host_batch", "eval", "checkpoint"):
        with span(name, registry=reg):
            pass
    snap = reg.snapshot()["counters"]
    for key in ("host_batch_ms", "eval_ms", "checkpoint_ms"):
        assert key in snap
