"""Fleet cache telescope tests (ISSUE 16).

Four layers, mirroring the subsystem:

  1. the allocator's chain telemetry as PURE HOST CODE — summary wire
     form, digest identity, top-K hotness bound, the delta/merge pin
     (replaying every `take_chain_delta` onto {} reproduces the direct
     `chain_summary` EXACTLY through admit/COW/evict/import churn),
     and the incremental `imported_live` counter vs the audit scan;
  2. the FleetCacheMap — digest matching, deterministic best_match,
     staleness, corpse drop;
  3. the router auditor over the INPROC backend — the token-partition
     identity (reused + missed + cold == every dispatched prompt
     token), per-event partition on `missed_reuse`, the weighted
     `prefix_hit_rate` gauge, and the disabled path pinned to a bare
     pointer check (micro-pin + relative fleet-step budget);
  4. the PROCESS backend (slow: real workers) — heartbeat-delta-merged
     mirrors equal the direct summary RPC, the partition holds across
     the pipe, and a SIGKILLed replica's summary leaves the map.

`tools/cache_report.py --smoke` runs in tier-1 like the disagg bench
smoke; the obs_report paging line grows the reuse partition.
"""

import time

import numpy as np
import pytest
from flax import nnx

from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.obs.trace import Tracer
from avenir_tpu.serve import PageAllocator, Router
from avenir_tpu.serve.cache_map import FleetCacheMap, merge_chain_delta
from avenir_tpu.serve.pages import chain_digest

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
PAGED_KW = dict(kv_impl="paged", page_size=8, n_pages=48,
                prefill_chunk=8)


@pytest.fixture(scope="module")
def model():
    return GPT(GPT_TINY, rngs=nnx.Rngs(0))


# ---------------------------------------------------------------------
# 1. allocator chain telemetry (pure host)
# ---------------------------------------------------------------------


def _admit_register(a, rid, prompt, max_new=4):
    """Admit + cover every FULL prompt page the way the engine's
    chunked prefill does: alloc an owned page, register it the moment
    its tokens are fully prompt-covered."""
    prompt = [int(t) for t in prompt]
    plan = a.admit(rid, prompt, max_new=max_new)
    assert plan is not None
    ps = a.page_size
    slot = len(a.table(rid))
    for i in range(len(plan.shared_pages), len(prompt) // ps):
        a.alloc(rid)
        a.register(rid, slot, prompt[i * ps:(i + 1) * ps])
        slot += 1
    return plan


def test_chain_digest_is_stable_and_distinct():
    d = chain_digest([1, 2, 3])
    assert d == chain_digest((1, 2, 3))          # type-insensitive
    assert isinstance(d, str) and len(d) == 16   # blake2b-64 hex: wire-safe
    assert d != chain_digest([1, 2, 4])
    assert d != chain_digest([1, 2])


def test_chain_summary_wire_form_and_digest_identity():
    a = PageAllocator(n_pages=8, page_size=4, prefix_sharing=True)
    prompt = list(range(1, 13))                  # 3 full pages
    _admit_register(a, 0, prompt)
    s = a.chain_summary()
    assert set(s) == {chain_digest(prompt[:4]), chain_digest(prompt[:8]),
                      chain_digest(prompt[:12])}
    node = s[chain_digest(prompt[:8])]
    assert isinstance(node, list) and len(node) == 5
    n_tok, depth, ref, hits, last = node
    assert (n_tok, depth) == (8, 2)
    assert ref == 1 and hits == 0                # live under rid 0, no attach yet
    # a second request attaching the shared prefix bumps hotness and ref
    _admit_register(a, 1, prompt[:8] + [90, 91, 92, 93])
    s2 = a.chain_summary()
    n2 = s2[chain_digest(prompt[:8])]
    assert n2[2] == 2 and n2[3] == 1 and n2[4] > last
    a.free_seq(0)
    a.free_seq(1)
    a.audit()


def test_chain_summary_topk_keeps_hottest():
    a = PageAllocator(n_pages=16, page_size=2, prefix_sharing=True)
    roots = [[10 * i + 1, 10 * i + 2, 10 * i + 3, 10 * i + 4]
             for i in range(5)]
    for i, p in enumerate(roots):
        _admit_register(a, i, p, max_new=1)
    for i in range(5):
        a.free_seq(i)
    # two attaches make chain 0's root the hottest node
    for j in range(2):
        _admit_register(a, 10 + j, roots[0][:2] + [70 + j, 80 + j],
                        max_new=1)
    top = a.chain_summary(top_k=2)
    assert len(top) == 2
    assert chain_digest(roots[0][:2]) in top
    assert a.chain_summary(top_k=0) == {}
    full = a.chain_summary(top_k=64)
    assert len(full) == len(a._node)             # bound, not padding
    a.audit()


def test_take_chain_delta_merge_equals_direct_under_churn():
    """THE merge pin: replaying every delta in order onto {} equals the
    direct summary after every churn phase — admits, prefix attach,
    frees, a cross-allocator import, COW, and pressure eviction."""
    a = PageAllocator(n_pages=8, page_size=4, prefix_sharing=True)
    shadow = {}
    K = 16

    def sync():
        d = a.take_chain_delta(K)
        if d is not None:
            merge_chain_delta(shadow, d)
        assert shadow == a.chain_summary(K)
        a.audit()

    sync()                                       # empty start
    p0 = list(range(1, 13))
    _admit_register(a, 0, p0)                    # 3 registered nodes
    sync()
    _admit_register(a, 1, p0[:8] + [91, 92, 93, 94])   # attach + extend
    sync()
    a.free_seq(0)
    sync()
    a.import_chain([(70, 71, 72, 73), (74, 75, 76, 77)])
    sync()
    a.free_seq(1)
    sync()
    # pressure: a big admit must evict LRU cached chains to stage pages
    plan = a.admit(2, prompt=list(range(200, 216)), max_new=8)
    assert plan is not None
    for _ in range(4):
        a.alloc(2)
    sync()
    a.free_seq(2)
    sync()
    # quiet allocator: the dirty flag short-circuits to None
    assert a.take_chain_delta(K) is None


def test_imported_live_incremental_vs_audit_scan():
    a = PageAllocator(n_pages=6, page_size=2, prefix_sharing=True)
    out = a.import_chain([(1, 2), (3, 4)])
    assert [fresh for _, fresh in out] == [True, True]
    page_a = out[0][0]
    assert a.stats()["imported_live"] == 0       # cached, ref 0
    a.audit()
    # attaching both imported pages makes them live
    plan = a.admit(0, prompt=[1, 2, 3, 4, 9], max_new=1)
    assert len(plan.shared_pages) == 2
    assert a.stats()["imported_live"] == 2
    a.audit()
    # COW on the root entry: the imported page leaves the live set
    assert a.ensure_writable(0, 0) is not None
    assert a.stats()["imported_live"] == 1
    a.audit()
    # evicting the now-cached root deregisters its LIVE imported child
    # — the incremental counter must follow the subtree teardown
    a._evict(page_a)
    assert a.stats()["imported_live"] == 0
    a.audit()
    a.free_seq(0)
    assert a.stats()["imported_live"] == 0
    a.audit()


# ---------------------------------------------------------------------
# 2. FleetCacheMap
# ---------------------------------------------------------------------


def test_cache_map_match_best_match_and_drop():
    T = list(range(1, 13))
    m = FleetCacheMap(clock=lambda: 9.0)
    m.update("A", {chain_digest(T[:4]): [4, 1, 1, 2, 7],
                   chain_digest(T[:8]): [8, 2, 0, 1, 6]}, now=1.0)
    m.update("B", {chain_digest(T[:4]): [4, 1, 0, 0, 1]}, now=2.0)
    assert m.match(T) == {"A": 8, "B": 4}
    assert m.best_match(T) == ("A", 8)
    # a depth past the prompt can never match (reused <= len(prompt))
    assert m.match(T[:6]) == {"A": 4, "B": 4}
    assert m.best_match(T[:6]) == ("A", 4)       # deterministic tie-break
    assert m.best_match([99, 98]) == (None, 0)
    assert m.staleness_s("B") == pytest.approx(7.0)
    assert m.staleness_s("B", now=5.0) == pytest.approx(3.0)
    assert m.staleness_s("nope") is None
    m.drop("A")
    assert m.best_match(T) == ("B", 4)
    assert m.replicas() == ["B"]
    m.update("A", {}, now=3.0)                   # empty advert is fine
    assert m.match(T)["A"] == 0


def test_merge_chain_delta_is_the_one_rule():
    s = {}
    merge_chain_delta(s, {"upd": {"d1": [4, 1, 1, 0, 1]}, "gone": []})
    merge_chain_delta(s, {"upd": {"d2": [8, 2, 0, 0, 2]},
                          "gone": ["d1", "never_seen"]})
    assert s == {"d2": [8, 2, 0, 0, 2]}


# ---------------------------------------------------------------------
# 3. the router auditor, inproc
# ---------------------------------------------------------------------


def _shared_prefix_reqs(rng, n, *, n_tenants=2, prefix_len=16,
                        tail_lo=3, tail_hi=7):
    prefixes = [[int(t) for t in rng.integers(0, 64, prefix_len)]
                for _ in range(n_tenants)]
    out = []
    for _ in range(n):
        tenant = int(rng.integers(0, n_tenants))
        tail = [int(t) for t in rng.integers(
            0, 64, int(rng.integers(tail_lo, tail_hi + 1)))]
        out.append(prefixes[tenant] + tail)
    return out


def _drive(router, prompts, *, n_conc=4, max_new=4):
    rid_prompt = {}
    submitted, done = 0, []
    while len(done) < len(prompts):
        while (submitted < len(prompts)
               and submitted - len(done) < n_conc):
            p = prompts[submitted]
            rid = router.submit(p, max_new_tokens=max_new,
                                temperature=1.0, top_k=None)
            rid_prompt[rid] = p
            submitted += 1
        done.extend(router.step())
    router.drain()
    return done, rid_prompt


def test_partition_identity_and_missed_reuse_events_inproc(model):
    reg = MetricsRegistry()
    tracer = Tracer(registry=reg)
    router = Router(model, n_replicas=2, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, tracer=tracer,
                    cache_telescope=True, engine_kwargs=dict(PAGED_KW))
    prompts = _shared_prefix_reqs(np.random.default_rng(7), 12,
                                  n_tenants=3, prefix_len=24)
    done, rid_prompt = _drive(router, prompts, n_conc=3)
    assert len(done) == len(prompts)
    assert all(f.finish_reason == "length" for f in done)
    c = reg.snapshot()["counters"]
    total = sum(len(p) for p in prompts)
    # THE partition identity: every dispatched prompt token in exactly
    # one bucket (no failovers here, so dispatches == submissions)
    assert (c["prefix_tokens_reused"] + c["prefix_tokens_missed"]
            + c["prefix_tokens_cold"]) == total
    # affinity-blind placement over 2 replicas sharing tenant prefixes
    # must both reuse locally and miss cross-replica
    assert c["prefix_tokens_missed"] > 0
    assert c["prefix_tokens_reused"] > 0
    evs = [e for e in tracer.events() if e["ev"] == "missed_reuse"]
    assert evs and sum(e["missed"] for e in evs) \
        == c["prefix_tokens_missed"]
    for e in evs:
        assert e["missed"] > 0                    # emitted only on a miss
        assert e["best_replica"] != e["replica"]
        assert e["reused"] + e["missed"] + e["cold"] \
            == len(rid_prompt[e["rid"]])
        assert e["est_ms_saved"] >= 0.0
    # satellite 1: the fleet gauge is attempt-WEIGHTED across replicas
    rates = [(r.engine._paged.prefix_hit_rate(),
              r.engine._paged.prompt_tokens) for r in router.replicas]
    w = sum(n for _, n in rates)
    assert w > 0
    assert reg.snapshot()["gauges"]["prefix_hit_rate"] == pytest.approx(
        sum(rate * n for rate, n in rates) / w)
    # the map tracked both replicas' content
    assert sorted(router._cache_map.replicas()) \
        == sorted(r.replica_id for r in router.replicas)
    router.close()


def test_telescope_off_router_has_no_map_and_no_counters(model):
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, engine_kwargs=dict(PAGED_KW))
    assert router._cache_map is None
    prompts = _shared_prefix_reqs(np.random.default_rng(3), 3)
    done, _ = _drive(router, prompts, n_conc=2)
    assert len(done) == 3
    assert "prefix_tokens_missed" not in reg.snapshot()["counters"]
    router.close()


def test_disabled_telescope_guard_is_nanoseconds():
    """The per-dispatch cost with the telescope off is ONE attribute
    load + `is not None` branch — the tracer's micro-pin applied to
    `self._cache_map` (a real audit behind the guard would blow this
    by orders of magnitude)."""
    class _Holder:
        _cache_map = None

    h = _Holder()
    n = 200_000
    t0 = time.perf_counter()
    acc = 0
    for _ in range(n):
        cm = h._cache_map
        if cm is not None:                        # the exact site shape
            acc += 1
    per_op_us = (time.perf_counter() - t0) / n * 1e6
    assert acc == 0
    assert per_op_us < 1.0, (
        f"disabled-telescope guard costs {per_op_us:.3f} us/op — the "
        "disabled path must stay a bare None check")


@pytest.mark.slow
def test_disabled_telescope_adds_no_measurable_step_overhead(model):
    """Fleet-step pin, relative like the tracing one: steps with the
    telescope OFF are not slower than the SAME workload's steps with
    it ON (which do strictly more work — audits, summary reads, map
    refresh). Median-of-steps keeps compile spikes out; the budget is
    3x + 2ms so a loaded CI harness cannot flake it. Slow lane: two
    full fleet drives (~7s) blow the zz_slow_guard tier-1 budget; the
    nanoseconds micro-pin above keeps the disabled path covered in
    tier-1."""
    import statistics

    def median_step(telescope):
        reg = MetricsRegistry()
        router = Router(model, n_replicas=2, n_slots=2, max_seq_len=64,
                        registry=reg, seed=0, cache_telescope=telescope,
                        engine_kwargs=dict(PAGED_KW))
        prompts = _shared_prefix_reqs(np.random.default_rng(5), 4)
        rid = 0
        durs = []
        done = []
        while len(done) < len(prompts):
            while (rid < len(prompts)
                   and rid - len(done) < 4):
                router.submit(prompts[rid], max_new_tokens=2,
                              temperature=1.0, top_k=None)
                rid += 1
            t0 = time.perf_counter()
            done.extend(router.step())
            durs.append(time.perf_counter() - t0)
        router.close()
        return statistics.median(durs)

    on = median_step(True)
    off = median_step(False)
    assert off <= 3.0 * on + 2e-3, (
        f"telescope-off steps ({off * 1e3:.2f} ms) slower than 3x "
        f"telescope-on ({on * 1e3:.2f} ms) — the disabled path grew "
        "real work")


def test_cache_report_smoke_runs_in_ci():
    from tools.cache_report import cache_report

    rc = cache_report({"smoke": "1"})
    assert rc == 0


def test_obs_report_paging_line_shows_reuse_partition():
    from avenir_tpu.obs.report import format_report, summarize

    records = [
        {"kind": "run_meta", "t": 0.0},
        {"kind": "request", "t": 1.0, "ttft_ms": 5.0, "tpot_ms": 1.0,
         "n_out": 4, "finish_reason": "length"},
        {"kind": "run_end", "t": 2.0,
         "counters": {"prefix_tokens_reused": 10.0,
                      "prefix_tokens_missed": 5.0,
                      "prefix_tokens_cold": 5.0,
                      "serve_prefill_ms": 100.0,
                      "prefill_chunks": 3.0}},
    ]
    s = summarize(records)
    sv = s["serve"]
    assert sv["prefix_tokens_missed"] == 5.0
    # est saved = missed x (prefill ms / tokens prefill computed)
    assert sv["est_prefill_ms_saved"] == pytest.approx(50.0)
    out = format_report(s)
    assert "reused 10/missed 5/cold 5 tok" in out
    assert "est saved 50.0 ms" in out


# ---------------------------------------------------------------------
# 4. process backend (slow: real workers)
# ---------------------------------------------------------------------


@pytest.fixture()
def _close_routers():
    created = []
    yield created
    for router in created:
        try:
            router.close()
        except Exception:
            pass


@pytest.mark.slow
def test_process_chain_mirror_matches_direct_and_partition(
        model, _close_routers):
    """Satellite 3 + the tentpole wire pin over REAL worker processes:
    the parent-side mirror rebuilt purely from step-reply heartbeat
    deltas equals the worker allocator's direct `chain_summary()` —
    after admit / attach / free churn — and the audit partition
    identity holds across the pipe."""
    reg = MetricsRegistry()
    router = Router(model, backend="process", n_replicas=2, n_slots=2,
                    max_seq_len=64, registry=reg, seed=0,
                    cache_telescope=True, engine_kwargs=dict(PAGED_KW))
    _close_routers.append(router)
    prompts = _shared_prefix_reqs(np.random.default_rng(11), 8)
    done, _ = _drive(router, prompts)
    assert len(done) == len(prompts)
    c = reg.snapshot()["counters"]
    assert (c["prefix_tokens_reused"] + c["prefix_tokens_missed"]
            + c["prefix_tokens_cold"]) == sum(len(p) for p in prompts)
    saw_chains = 0
    for r in router.replicas:
        direct = r.chain_summary()               # debug RPC: allocator truth
        mirror = r.engine.chains or {}
        assert mirror == direct, (
            f"replica {r.replica_id}: heartbeat-delta mirror diverged "
            f"from the direct summary\n mirror {mirror}\n direct {direct}")
        assert router._cache_map.nodes(r.replica_id) == mirror
        saw_chains += len(direct)
    assert saw_chains > 0                        # the telescope saw content


@pytest.mark.slow
def test_process_sigkill_drops_corpse_from_cache_map(
        model, _close_routers):
    """A SIGKILLed worker's advertised cache content leaves the
    FleetCacheMap with it — a corpse must never win best_match — while
    failover serves every request on the survivor."""
    import os
    import signal

    reg = MetricsRegistry()
    router = Router(model, backend="process", n_replicas=2, n_slots=2,
                    max_seq_len=64, registry=reg, seed=0,
                    cache_telescope=True, engine_kwargs=dict(PAGED_KW))
    _close_routers.append(router)
    prompts = _shared_prefix_reqs(np.random.default_rng(13), 6)
    rids = [router.submit(p, max_new_tokens=8, temperature=1.0,
                          top_k=None) for p in prompts]
    done = []
    while len(router._cache_map.replicas()) < 2:
        done.extend(router.step())
        assert len(done) < len(rids), "served out before both replicas advertised"
    victim = next(r for r in router.replicas if r.busy)
    os.kill(victim.pid, signal.SIGKILL)
    done.extend(router.drain())
    assert len(done) == len(prompts)
    assert all(f.finish_reason == "length" for f in done)
    assert victim.state == "dead"
    assert victim.replica_id not in router._cache_map.replicas()
    survivor = next(r for r in router.replicas if r is not victim)
    assert router._cache_map.replicas() == [survivor.replica_id]
    # the corpse's engine mirror was cleared with the rest of its state
    assert victim.engine.chains is None
