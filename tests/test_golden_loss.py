"""Golden-loss parity (BASELINE.json:2 "loss@N-tokens vs PyTorch ref";
SURVEY.md §4 "Integration: golden loss"): train the torch reference and the
TPU backend on the IDENTICAL batch sequence from identical weights and
assert the loss curves overlay. The recorded GOLDEN_FINAL_LOSS value (also
in BASELINE.md) pins the curve across refactors."""

import math

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp
from flax import nnx

import model as torch_model
from avenir_tpu.checkpoint.bridge import load_torch_state_dict
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.train.optimizer import make_optimizer
from avenir_tpu.train.step import jit_train_step, make_step_fns

# recorded 2026-07-30 (round-2 golden run, 200 iters of the config below on
# the seed-7 synthetic char corpus): torch 1.7418, jax 1.7418 — identical
# to 4 decimals. Both backends must land within GOLDEN_BAND of this;
# re-record deliberately if training semantics change.
GOLDEN_FINAL_LOSS = 1.7418
GOLDEN_BAND = 0.05

HP = dict(learning_rate=1e-3, weight_decay=0.1, beta1=0.9, beta2=0.95,
          grad_clip=1.0, warmup_iters=10, lr_decay_iters=200, min_lr=1e-4)
N_ITERS = 200
B, T = 8, 64
ARCH = dict(block_size=T, vocab_size=None, n_layer=2, n_head=2, n_embd=64,
            dropout=0.0, bias=True)


def _batches(char_dataset, vocab_size):
    data = np.fromfile(f"{char_dataset['dir']}/train.bin", dtype=np.uint16)
    rng = np.random.default_rng(1234)
    out = []
    for _ in range(N_ITERS):
        ix = rng.integers(0, len(data) - T - 1, B)
        x = np.stack([data[i:i + T] for i in ix]).astype(np.int64)
        y = np.stack([data[i + 1:i + 1 + T] for i in ix]).astype(np.int64)
        out.append((x, y))
    return out


def _get_lr(it):
    if it < HP["warmup_iters"]:
        return HP["learning_rate"] * (it + 1) / (HP["warmup_iters"] + 1)
    if it > HP["lr_decay_iters"]:
        return HP["min_lr"]
    r = (it - HP["warmup_iters"]) / (HP["lr_decay_iters"] - HP["warmup_iters"])
    c = 0.5 * (1.0 + math.cos(math.pi * r))
    return HP["min_lr"] + c * (HP["learning_rate"] - HP["min_lr"])


def _train_torch(tm, batches):
    opt = tm.configure_optimizers(HP["weight_decay"], HP["learning_rate"],
                                  (HP["beta1"], HP["beta2"]), "cpu")
    losses = []
    for it, (x, y) in enumerate(batches):
        for pg in opt.param_groups:
            pg["lr"] = _get_lr(it)
        _, loss = tm(torch.from_numpy(x), torch.from_numpy(y))
        opt.zero_grad(set_to_none=True)
        loss.backward()
        torch.nn.utils.clip_grad_norm_(tm.parameters(), HP["grad_clip"])
        opt.step()
        losses.append(float(loss.item()))
    return losses


def _train_jax(jm, batches):
    graphdef, params = nnx.split(jm, nnx.Param)
    tx, _ = make_optimizer(params, **HP)
    opt_state = tx.init(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    step = jit_train_step(step_fn, tx)
    key = jax.random.key(0)
    losses = []
    for x, y in batches:
        xb = jnp.asarray(x.astype(np.int32))[None]
        yb = jnp.asarray(y.astype(np.int32))[None]
        params, opt_state, m = step(params, opt_state, key, xb, yb)
        losses.append(float(m["loss"]))
    return losses


@pytest.mark.slow
def test_golden_loss_curves_overlay(char_dataset):
    vocab = char_dataset["meta"]["vocab_size"]
    arch = dict(ARCH, vocab_size=vocab)
    torch.manual_seed(0)
    tm = torch_model.GPT(torch_model.GPTConfig(**arch))
    jm = GPT(GPTConfig(**arch, attn_impl="xla"), rngs=nnx.Rngs(0))
    sd = {k: v.detach().numpy() for k, v in tm.state_dict().items()
          if not k.endswith(".attn.causal_mask")}
    load_torch_state_dict(jm, sd)  # identical initial weights

    batches = _batches(char_dataset, vocab)
    tl = _train_torch(tm, batches)
    jl = _train_jax(jm, batches)

    tl, jl = np.asarray(tl), np.asarray(jl)
    # identical data order + weights + optimizer semantics → the curves
    # must overlay. fp32 round-off compounds over 200 steps; the band is
    # loose late, tight early.
    np.testing.assert_allclose(jl[:50], tl[:50], atol=5e-3)
    assert np.max(np.abs(jl - tl)) < 0.05, np.max(np.abs(jl - tl))

    # the curve went somewhere real
    assert tl[-1] < tl[0] - 0.5, (tl[0], tl[-1])
    # golden pin: BASELINE.md records this value
    print(f"GOLDEN torch final loss: {np.mean(tl[-10:]):.4f}, "
          f"jax final loss: {np.mean(jl[-10:]):.4f}")
    if GOLDEN_FINAL_LOSS is not None:
        assert abs(np.mean(jl[-10:]) - GOLDEN_FINAL_LOSS) < GOLDEN_BAND
