"""Paged-KV subsystem tests (avenir_tpu/serve/pages.py, ISSUE 9).

Three layers, mirroring the subsystem:

  1. the allocator as PURE HOST CODE — alloc/free/refcount/COW/prefix-
     chain/eviction/reservation edge cases and the leak audit, no jax;
  2. the device ops — paged scatter/gather vs the dense cache, bitwise;
     the Pallas decode kernel in interpret mode vs the reference;
  3. the paged ENGINE — the unchanged correctness oracle: per-request
     bit-parity with one-shot `generate_cached` across GPT/Llama/
     Mixtral, randomized arrivals, prefix sharing ON and OFF, chunked
     prefill crossing page boundaries, compile counts pinned (no
     retrace as pages allocate/free), budget-aware rejection, and
     mid-chunked-prefill failover through the router.

The prefix-sharing soak and the chaos-mid-prefill load test are marked
slow. Like test_serve.py, models are single-layer (engine logic is
depth-blind) and every request uses ONE max_new so one-shot references
share decode compiles.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import _attend_cached, first_stop_index, \
    generate_cached, trace_count
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.models.llama import Llama, LlamaConfig
from avenir_tpu.models.mixtral import Mixtral, MixtralConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.serve import Engine, PageAllocator, Router
from avenir_tpu.serve.pages import paged_kv_ops

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
LLAMA_KW = dict(block_size=64, vocab_size=64, n_layer=1, n_head=4,
                n_kv_head=2, n_embd=32, ffn_hidden=64, dropout=0.0,
                attn_impl="xla")
MAX_NEW = 6
PAGED_KW = dict(kv_impl="paged", page_size=8, n_pages=24,
                prefill_chunk=8)


# ---------------------------------------------------------------------------
# 1. the allocator as pure host code
# ---------------------------------------------------------------------------


def test_allocator_alloc_free_refcount():
    a = PageAllocator(n_pages=6, page_size=4, prefix_sharing=False)
    plan = a.admit(0, prompt=range(9), max_new=3)   # 12 tokens = 3 pages
    assert plan is not None and plan.new_pages == 3
    assert a.available() == 3                        # 6 - 3 reserved
    p0, p1, p2 = a.alloc(0), a.alloc(0), a.alloc(0)
    assert len({p0, p1, p2}) == 3
    assert a.stats()["live"] == 3 and a.stats()["reserved"] == 0
    with pytest.raises(AssertionError):              # reservation spent
        a.alloc(0)
    a.audit()
    a.free_seq(0)
    st = a.audit()
    assert st["live"] == 0 and st["free"] == 6 and st["cached"] == 0


def test_allocator_reservation_blocks_admission_and_reuse_is_exact():
    """Token-budget admission: a request whose WORST CASE is not covered
    is refused; interleaved alloc/free of odd sizes never strands a
    page (fragmentation-free by construction — any page serves any
    request)."""
    a = PageAllocator(n_pages=8, page_size=4, prefix_sharing=False)
    assert a.admit(0, prompt=range(10), max_new=2) is not None  # 3 pages
    assert a.admit(1, prompt=range(17), max_new=3) is not None  # 5 pages
    assert a.admit(2, prompt=range(2), max_new=1) is None       # over
    # finish 0 early (stop token): only 1 of its 3 pages was used
    a.alloc(0)
    a.free_seq(0)
    assert a.available() == 3
    assert a.admit(2, prompt=range(9), max_new=3) is not None   # 3 pages
    for _ in range(5):
        a.alloc(1)
    for _ in range(3):
        a.alloc(2)
    a.audit()
    a.free_seq(1)
    a.free_seq(2)
    assert a.audit()["free"] == 8


def test_allocator_prefix_full_and_partial_match():
    a = PageAllocator(n_pages=10, page_size=4)
    # request 0: 11-token prompt -> pages [0:4) [4:8) full, [8:11) tail
    prompt = list(range(11))
    assert a.admit(0, prompt, max_new=1).shared_len == 0
    for slot in range(3):
        a.alloc(0)
    a.register(0, 0, prompt[0:4])
    a.register(0, 1, prompt[4:8])
    # request 1: identical first 10 tokens -> two full pages shared
    plan = a.plan(prompt[:10] + [99], max_new=1)
    assert len(plan.shared_pages) == 2 and plan.shared_len == 8
    assert plan.partial is None  # tail [8:10] was never registered
    # request 2: prompt is a PREFIX of request 0's (ends mid-page-1):
    # full page 0 + a partial attach of page 1 (divergent tail masked)
    plan = a.plan(prompt[:7], max_new=1)
    assert len(plan.shared_pages) == 1 and plan.partial is not None
    assert plan.shared_len == 6  # capped at len(prompt)-1
    # request 3: diverges inside page 0 -> partial match of page 0
    plan = a.plan([0, 1, 77, 78], max_new=1)
    assert plan.shared_pages == () and plan.partial is not None
    assert plan.shared_len == 2
    # request 4: no common prefix at all
    plan = a.plan([50, 51, 52, 53, 54], max_new=1)
    assert plan.shared_pages == () and plan.partial is None
    a.audit()


def test_allocator_prefix_dedup_and_temporal_reuse():
    """Two identical prompts register once (dedup chains through the
    existing node); pages freed by their owner stay CACHED and match
    later prompts until evicted."""
    a = PageAllocator(n_pages=8, page_size=4)
    prompt = list(range(8))
    a.admit(0, prompt, max_new=1)
    a.alloc(0), a.alloc(0), a.alloc(0)
    a.register(0, 0, prompt[0:4])
    a.register(0, 1, prompt[4:8])
    first_pages = [e.page for e in a.table(0)][:2]
    # a racing identical prompt that computed privately registers dup
    a.admit(1, [7] * 9, max_new=1)   # no match (different tokens)
    a.alloc(1), a.alloc(1), a.alloc(1)
    a.register(1, 0, prompt[0:4])    # same tokens as 0's page 0: dedup
    assert a._chain[1] == first_pages[0]
    a.free_seq(0)
    st = a.stats()
    assert st["cached"] == 2         # 0's registered pages linger
    plan = a.plan(prompt + [60], max_new=1)
    assert list(plan.shared_pages) == first_pages  # temporal hit
    a.free_seq(1)
    a.audit()


def test_allocator_cow_bookkeeping():
    a = PageAllocator(n_pages=6, page_size=4)
    prompt = list(range(9))
    a.admit(0, prompt, max_new=3)
    a.alloc(0), a.alloc(0), a.alloc(0)
    a.register(0, 0, prompt[0:4])
    a.register(0, 1, prompt[4:8])
    shared_page = a.table(0)[1].page
    # request 1 ends inside page 1 -> partial attach, COW on write
    plan = a.admit(1, prompt[:7], max_new=2)
    assert plan.partial == shared_page
    assert not a.table(1)[1].owned
    assert a._ref[shared_page] == 2
    cow = a.ensure_writable(1, 1)
    assert cow is not None and cow[0] == shared_page
    assert a.table(1)[1].owned and a.table(1)[1].page == cow[1]
    assert a._ref[shared_page] == 1 and a.cow_copies == 1
    assert a.ensure_writable(1, 1) is None   # second write: owned
    a.audit()
    a.free_seq(0)
    a.free_seq(1)
    a.audit()


def test_allocator_eviction_cascades_through_the_chain():
    """Evicting a cached chain node deregisters its whole subtree —
    a chain with a hole must never match past it — and frees cached
    descendants; LIVE descendants just lose their registration."""
    a = PageAllocator(n_pages=4, page_size=2)
    prompt = [1, 2, 3, 4, 5, 6]
    a.admit(0, prompt, max_new=2)   # 4 pages
    for _ in range(4):
        a.alloc(0)
    for s in range(3):
        a.register(0, s, prompt[2 * s:2 * s + 2])
    a.free_seq(0)                   # 3 registered pages -> cached
    assert a.stats()["cached"] == 3 and a.stats()["free"] == 1
    # a new 4-page request must evict: LRU pops the chain ROOT page,
    # whose whole subtree deregisters -> all 3 cached pages free
    assert a.admit(1, [9, 9, 9, 9, 9], max_new=3) is not None
    for _ in range(4):
        a.alloc(1)
    assert a._node == {} or all(p not in a._node for p in range(4)
                                if a._ref.get(p, 0) == 0)
    assert a.plan(prompt, max_new=1).shared_pages == ()  # chain gone
    a.free_seq(1)
    a.audit()


def test_allocator_stale_chain_parent_never_resurrects():
    """A dedup hop can land a request's chain on a CACHED page; if
    eviction reclaims it mid-prefill, later registrations must STOP
    (conservative miss) rather than chain under the stale id — which a
    reused page could otherwise resurrect as a wrong-prefix match."""
    a = PageAllocator(n_pages=8, page_size=2)
    # request 1 is admitted BEFORE anything is registered (no match)
    a.admit(1, [1, 2, 3, 4, 5, 6], max_new=2)
    a.alloc(1)
    # request 0 races ahead: registers [1,2], finishes -> node cached
    a.admit(0, [1, 2, 9], max_new=1)
    a.alloc(0), a.alloc(0)
    a.register(0, 0, [1, 2])
    cached_node = a.table(0)[0].page
    a.free_seq(0)
    # request 1's own [1,2] registration dedups onto the cached node
    # (which its table does NOT reference -> not ref-held by it)
    a.register(1, 0, [1, 2])
    assert a._chain[1] == cached_node
    # pool pressure evicts the cached node mid-prefill of request 1
    a._evict(cached_node)
    # request 1's next registration must refuse the stale parent
    a.alloc(1)
    a.register(1, 1, [3, 4])
    assert a.table(1)[1].page not in a._node
    assert a.plan([1, 2, 3, 4, 5], max_new=1).shared_pages == ()
    a.free_seq(1)
    a.audit()


def test_allocator_admission_charges_for_cached_attaches():
    """Attaching a CACHED prefix page revives it to live, shrinking the
    reclaimable pool without consuming a reservation — admission must
    charge for that, or a co-tenant's already-granted reservation
    becomes unbackable (review finding: audit tripped 'reservations
    exceed reclaimable pages' and alloc() crashed the engine)."""
    a = PageAllocator(n_pages=3, page_size=4)
    # request X registers 2 prefix pages, finishes -> 2 cached, 1 free
    prompt = list(range(9))
    a.admit(0, prompt, max_new=3)
    a.alloc(0), a.alloc(0), a.alloc(0)
    a.register(0, 0, prompt[0:4])
    a.register(0, 1, prompt[4:8])
    a.free_seq(0)
    assert a.audit() == a.stats()  # 1 free + 2 cached, nothing live
    # A reserves the 1 reclaimable page beyond the cached pair
    assert a.admit(1, [9, 9, 9], max_new=1) is not None   # 1 page
    # B shares X's prefix: new_pages=1 but it would ALSO revive both
    # cached pages — 1 + 2 > available, so admission must refuse
    assert a.admit(2, prompt[:8] + [7], max_new=3) is None
    a.alloc(1)          # A's reservation must still be backable
    a.audit()
    a.free_seq(1)
    # with A gone there is room: B admits, attaches, and runs clean
    assert a.admit(2, prompt[:8] + [7], max_new=3) is not None
    a.alloc(2)
    a.audit()
    a.free_seq(2)
    a.audit()


def test_proxy_clear_drops_kv_mirror():
    """A dead worker's last heartbeat must not keep feeding the fleet
    paging gauges: _EngineProxy.clear() drops the kv mirror with the
    rest of the heartbeat state (review finding)."""
    from avenir_tpu.serve.proc import _EngineProxy

    proxy = _EngineProxy(owner=None)
    proxy.update({"n_slots": 2, "free": 1, "queue": 0,
                  "kv": {"impl": "paged", "pages_free": 24,
                         "page_util": 0.5, "prefix_hit_rate": 0.3}})
    assert proxy.kv["pages_free"] == 24
    proxy.clear()
    assert proxy.kv is None


def test_allocator_audit_catches_a_leak():
    a = PageAllocator(n_pages=4, page_size=4, prefix_sharing=False)
    a.admit(0, range(6), max_new=2)
    a.alloc(0)
    a.audit()
    a._ref[3] = 1  # a refcount with no table reference = leak
    with pytest.raises(AssertionError, match="leak"):
        a.audit()


def test_scheduler_budget_admission_blocks_fcfs_head():
    from avenir_tpu.serve.scheduler import FCFSScheduler, Request

    sched = FCFSScheduler(4, 64)
    for i in range(3):
        sched.enqueue(Request(req_id=i, prompt=(1, 2, 3),
                              max_new_tokens=4))
    admitted = sched.take_admissions(
        can_admit=lambda r: r.req_id == 0)
    assert [r.req_id for r, _ in admitted] == [0]
    assert sched.queue_depth == 2  # head 1 blocked, 2 NOT skipped past
    admitted = sched.take_admissions(can_admit=lambda r: True)
    assert [r.req_id for r, _ in admitted] == [1, 2]


# ---------------------------------------------------------------------------
# 2. device ops: paged scatter/gather + the Pallas kernel
# ---------------------------------------------------------------------------


def test_paged_ops_bitwise_match_dense_cache():
    """Writing K/V through a shuffled page table and attending through
    the gather view is BIT-identical to the dense cache — the device
    half of the parity argument."""
    from avenir_tpu.infer.decode import _write_cache

    rng = np.random.default_rng(0)
    B, Hkv, D, ps, P, n_pages = 3, 2, 8, 8, 4, 16
    pos = jnp.asarray([5, 17, 30])
    kd = jnp.asarray(rng.standard_normal((B, P * ps, Hkv, D)), jnp.float32)
    vd = jnp.asarray(rng.standard_normal((B, P * ps, Hkv, D)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((B, 1, 4, D)), jnp.float32)
    knew = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), jnp.float32)
    vnew = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)), jnp.float32)
    # dense: write then attend
    kdw, vdw = _write_cache(kd, vd, knew, vnew, pos)
    ref = _attend_cached(q, kdw, vdw, pos[:, None])
    # paged: scatter rows into a shuffled page layout, same ops
    perm = rng.permutation(n_pages)[:B * P]
    tables = np.zeros((B, P), np.int32)
    kp = np.zeros((n_pages, ps, Hkv, D), np.float32)
    vp = np.zeros((n_pages, ps, Hkv, D), np.float32)
    for b in range(B):
        for p in range(P):
            pg = int(perm[b * P + p])
            tables[b, p] = pg
            kp[pg] = np.asarray(kd[b, p * ps:(p + 1) * ps])
            vp[pg] = np.asarray(vd[b, p * ps:(p + 1) * ps])
    write, attend = paged_kv_ops(jnp.asarray(tables), n_pages=n_pages,
                                 page_size=ps,
                                 write_mask=jnp.ones((B,), bool))
    kpw, vpw = write(jnp.asarray(kp), jnp.asarray(vp), knew, vnew, pos)
    got = attend(q, kpw, vpw, pos[:, None])
    assert jnp.all(ref == got)
    # masked write: an inactive row's scatter is dropped entirely
    write2, _ = paged_kv_ops(jnp.asarray(tables), n_pages=n_pages,
                             page_size=ps,
                             write_mask=jnp.asarray([True, False, True]))
    kp2, _ = write2(jnp.asarray(kp), jnp.asarray(vp), knew, vnew, pos)
    assert jnp.all(kp2[tables[1, int(pos[1]) // ps]]
                   == kp[tables[1, int(pos[1]) // ps]])


@pytest.mark.parametrize("heads", [(4, 4), (4, 2)])
def test_pallas_paged_attention_interpret(heads):
    """The TPU paged-attention kernel (interpret mode) vs the gather
    reference: MHA and GQA, partial last pages, garbage in unattended
    pages."""
    from avenir_tpu.ops.pallas.paged_attention import paged_attention

    H, Hkv = heads
    rng = np.random.default_rng(1)
    B, D, ps, P, n_pages = 3, 16, 8, 4, 12
    pos = jnp.asarray([0, 12, 30])  # incl. a single-token row
    kp = jnp.asarray(rng.standard_normal((n_pages, ps, Hkv, D)),
                     jnp.float32)
    vp = jnp.asarray(rng.standard_normal((n_pages, ps, Hkv, D)),
                     jnp.float32)
    tables = jnp.asarray(rng.integers(0, n_pages, (B, P)), jnp.int32)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    kg = kp[tables].reshape(B, P * ps, Hkv, D)
    vg = vp[tables].reshape(B, P * ps, Hkv, D)
    ref = _attend_cached(q, kg, vg, pos[:, None])[:, 0]
    got = paged_attention(q[:, 0], kp, vp, tables, pos + 1,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# 3. the paged engine: the unchanged bit-parity oracle
# ---------------------------------------------------------------------------


def _mk_requests(model, rng, n, *, max_prompt=20, shared_prefix=None,
                 combos=((0.8, None), (1.0, 5), (1.3, 16))):
    """Requests with one-shot reference streams (the test_serve.py
    recipe); `shared_prefix` prepends a common system prompt to every
    other request so prefix sharing genuinely engages."""
    reqs = []
    for i in range(n):
        t0 = int(rng.integers(3, max_prompt + 1))
        prompt = [int(t) for t in rng.integers(0, 64, (t0,))]
        if shared_prefix is not None and i % 2 == 0:
            prompt = list(shared_prefix) + prompt[:6]
        temp, top_k = combos[i % len(combos)]
        kw = dict(prompt=prompt, max_new_tokens=MAX_NEW, temperature=temp,
                  top_k=top_k, rng=jax.random.key(1000 + i))
        y = np.asarray(generate_cached(
            model, kw["rng"], jnp.asarray(prompt, jnp.int32)[None],
            MAX_NEW, temperature=temp, top_k=top_k))[0]
        stop = (int(y[len(prompt) + 1]),) if i % 3 == 0 else ()
        n_keep = (first_stop_index(y[len(prompt):], stop) if stop
                  else MAX_NEW)
        reqs.append((kw | {"stop_tokens": stop},
                     [int(t) for t in y[:len(prompt) + n_keep]]))
    return reqs


@pytest.fixture(scope="module")
def gpt_fix():
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    return model, _mk_requests(model, np.random.default_rng(0), 8)


@pytest.fixture(scope="module")
def prefix_fix(gpt_fix):
    """Shared-prefix request mix + references (module-scoped so the
    reference decode compiles stay out of per-test call time — the
    tier-1 slow-guard budget)."""
    model, _ = gpt_fix
    rng = np.random.default_rng(3)
    prefix = [int(t) for t in rng.integers(0, 64, (17,))]
    return model, _mk_requests(model, rng, 6, max_prompt=12,
                               shared_prefix=prefix, combos=((1.0, 8),))


@pytest.fixture(scope="module", params=["llama", "mixtral", "gpt_scan"])
def family_fix(request):
    """Per-family model + references, module-scoped for the same
    slow-guard reason. Mixtral runs in the non-binding capacity regime
    (cf*K >= E): there prefill NEVER drops tokens, so per-chunk token
    counts cannot shift expert capacity — the chunked-prefill analogue
    of the documented engine caveat."""
    if request.param == "llama":
        model = Llama(LlamaConfig(**LLAMA_KW), rngs=nnx.Rngs(0))
    elif request.param == "mixtral":
        model = Mixtral(MixtralConfig(n_experts=4, n_experts_per_tok=2,
                                      capacity_factor=2.0, **LLAMA_KW),
                        rngs=nnx.Rngs(0))
    else:
        model = GPT(dataclasses.replace(GPT_TINY, scan_layers=True),
                    rngs=nnx.Rngs(0))
    return model, _mk_requests(model, np.random.default_rng(2), 3,
                               combos=((1.0, 8),))


def _run_schedule(engine, reqs, bursts):
    ids, results, pending = {}, {}, list(range(len(reqs)))
    bursts = list(bursts)
    while pending or engine.open_work:
        take = bursts.pop(0) if bursts else len(pending)
        for _ in range(min(take, len(pending))):
            i = pending.pop(0)
            ids[engine.submit(**reqs[i][0])] = i
        for f in engine.step():
            results[ids[f.req_id]] = f
    return results


def _assert_parity(results, reqs):
    assert len(results) == len(reqs)
    for i, (kw, ref) in enumerate(reqs):
        got = results[i].tokens
        assert got == ref, f"request {i} diverged:\n ref {ref}\n got {got}"


def test_engine_paged_parity_randomized_arrivals(gpt_fix):
    """The acceptance case: randomized bursts, fewer slots than
    requests, chunked prefill (chunk < prompt) crossing page
    boundaries, prefix sharing ON — every request bit-identical to its
    one-shot reference; compile count pinned (chunk-ladder prefills +
    ONE decode step + at most one COW copy) and the one-shot decode
    ledger untouched by engine traffic."""
    model, reqs = gpt_fix
    ledger0 = trace_count()
    engine = Engine(model, n_slots=3, max_seq_len=32,
                    registry=MetricsRegistry(), **PAGED_KW)
    results = _run_schedule(engine, reqs, bursts=[3, 0, 2, 1, 0, 2])
    _assert_parity(results, reqs)
    assert trace_count() == ledger0  # engine work never retraces decode
    assert len(engine.traces["prefill"]) <= len(engine._paged.chunk_ladder)
    assert len(engine.traces["step"]) == 1
    assert len(engine.traces["cow"]) <= 1
    assert engine.sched.n_recycled == len(reqs)
    engine._paged.audit(expect_empty=True)


def test_engine_paged_parity_no_sharing(gpt_fix):
    """Same schedule with prefix_sharing OFF — parity must not depend
    on the sharing machinery, and no COW can ever fire."""
    model, reqs = gpt_fix
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(),
                    **(PAGED_KW | {"prefix_sharing": False}))
    results = _run_schedule(engine, reqs, bursts=[2, 1, 2])
    _assert_parity(results, reqs)
    assert engine._paged.alloc.cow_copies == 0
    assert engine._paged.prefix_hit_rate() == 0.0
    engine._paged.audit(expect_empty=True)


def test_engine_paged_prefix_sharing_hits_and_cow(prefix_fix):
    """Requests sharing a long system prefix: later arrivals attach the
    first's registered pages (concurrent AND after it finished —
    temporal reuse through the cached list), COW fires on divergent
    tails, and every stream stays bit-identical to one-shot."""
    model, reqs = prefix_fix
    engine = Engine(model, n_slots=2, max_seq_len=48,
                    registry=MetricsRegistry(),
                    **(PAGED_KW | {"n_pages": 36}))
    # wave 1: two shared-prefix requests concurrently; wave 2 arrives
    # AFTER wave 1 finished (temporal hits via the cached pages)
    results = _run_schedule(engine, reqs, bursts=[2, 0, 0, 0, 0, 0, 0, 0,
                                                  2, 0, 0, 0, 0, 0, 0, 0,
                                                  2])
    _assert_parity(results, reqs)
    assert engine._paged.alloc.prefix_hits >= 2
    assert engine._paged.prefix_hit_rate() > 0.1
    assert len(engine.traces["step"]) == 1
    engine._paged.audit(expect_empty=True)


def test_engine_paged_parity_families(family_fix):
    """All three families over the paged path, chunked prefill and
    GQA included (the Mixtral regime note lives on the fixture)."""
    model, reqs = family_fix
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(), **PAGED_KW)
    results = _run_schedule(engine, reqs, bursts=[2, 1])
    _assert_parity(results, reqs)
    engine._paged.audit(expect_empty=True)


def test_engine_paged_no_retrace_across_alloc_free_cycles(gpt_fix):
    """Many waves through a SMALL pool: pages allocate, free, re-enter
    as cached, get evicted — the decode step must stay at ONE compile
    throughout (tables are traced arguments, never shapes)."""
    model, reqs = gpt_fix
    engine = Engine(model, n_slots=2, max_seq_len=32,
                    registry=MetricsRegistry(),
                    **(PAGED_KW | {"n_pages": 10}))
    for wave in range(3):
        results = _run_schedule(engine, reqs[:4], bursts=[2, 2])
        _assert_parity(results, reqs[:4])
    assert len(engine.traces["step"]) == 1
    assert len(engine.traces["prefill"]) <= len(engine._paged.chunk_ladder)
    engine._paged.audit(expect_empty=True)


def test_budget_aware_rejection_both_impls(gpt_fix):
    """ISSUE 9 satellite: under paged the submit limit is
    max_pages_per_seq*page_size; under slab it stays T_max — and the
    rejection record names which limit fired."""
    model, _ = gpt_fix
    # slab: T_max binds
    reg = MetricsRegistry()
    slab = Engine(model, n_slots=1, max_seq_len=16, registry=reg)
    rid = slab.submit(list(range(12)), max_new_tokens=8)
    done = slab.drain()
    assert done[0].req_id == rid and done[0].finish_reason == "rejected"
    assert done[0].reject_limit == "max_seq_len"
    assert reg.snapshot()["counters"]["serve_rejected"] == 1
    # paged: the page budget binds BELOW T_max
    reg = MetricsRegistry()
    paged = Engine(model, n_slots=1, max_seq_len=32, registry=reg,
                   kv_impl="paged", page_size=8, n_pages=8,
                   max_pages_per_seq=2, prefill_chunk=8)
    assert paged.max_total_tokens == 16
    rid = paged.submit(list(range(12)), max_new_tokens=8)  # 20 > 16
    done = paged.drain()
    assert done[0].finish_reason == "rejected"
    assert done[0].reject_limit == "page_budget"
    # ... while the same shape FITS the budget and serves normally
    ok = paged.submit(list(range(10)), max_new_tokens=6)
    out = {f.req_id: f for f in paged.drain()}
    assert out[ok].finish_reason in ("stop", "length")
    assert len(paged.traces["prefill"]) >= 1


def test_router_budget_aware_rejection_paged():
    """The router's front door uses the ENGINE's effective limit (page
    budget, not T_max) and stamps reject_limit on the refusal."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=1, max_seq_len=32,
                    registry=reg,
                    engine_kwargs=dict(kv_impl="paged", page_size=8,
                                       n_pages=8, max_pages_per_seq=2,
                                       prefill_chunk=8))
    assert router.max_total_tokens == 16
    router.submit(list(range(12)), max_new_tokens=8)
    done = router.drain()
    assert done[0].finish_reason == "rejected"
    assert done[0].reject_limit == "page_budget"


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_page_leak_audit_on_evict_and_deadline(gpt_fix):
    """Every release path returns its pages: deadline eviction of a
    LIVE slot, host-driven evict() of a MID-PREFILL request, and
    drain() — each followed by a clean audit (drain/evict run it
    internally; a poisoned allocator raises instead)."""
    model, reqs = gpt_fix
    clk = _Clock()
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=2, max_seq_len=32, registry=reg,
                    clock=clk, **PAGED_KW)
    kw, ref = reqs[1]
    sid = engine.submit(**kw)
    tid = engine.submit([5, 6, 7], max_new_tokens=MAX_NEW,
                        deadline_ms=50.0)
    engine.step()
    clk.t = 0.2
    done = engine.step()   # deadline evicts tid's live slot
    assert [f.req_id for f in done] == [tid]
    assert done[0].finish_reason == "timeout"
    # a long prompt mid-chunked-prefill, evicted by the host (the
    # process-backend deadline path)
    lid = engine.submit([int(t) for t in range(1, 25)],
                        max_new_tokens=4)
    engine.step()          # first chunk only (prefill_chunk=8 < 24)
    assert engine._paged.prefill, "expected a mid-prefill slot"
    out = engine.evict([lid])   # audits internally
    assert [f.req_id for f in out] == [lid]
    assert out[0].finish_reason == "timeout" and out[0].n_out == 0
    rest = {f.req_id: f for f in engine.drain()}  # audits empty
    assert rest[sid].tokens == ref


def test_router_paged_failover_mid_chunked_prefill():
    """ISSUE 9 acceptance: a replica dies while a request is mid-
    chunked-prefill — the router re-prefills it from scratch elsewhere,
    the completed output is bit-identical to one-shot, and NO parent-
    side bookkeeping leaks (router maps empty; the dead replica's
    allocator resets on revive and audits clean)."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    prompt = [int(t) for t in np.random.default_rng(7).integers(0, 64, 24)]
    rng_key = jax.random.key(42)
    ref = [int(t) for t in np.asarray(generate_cached(
        model, rng_key, jnp.asarray(prompt, jnp.int32)[None], MAX_NEW,
        temperature=1.0, top_k=8))[0]]
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=2, max_seq_len=32,
                    registry=reg,
                    engine_kwargs=dict(**PAGED_KW))
    rid = router.submit(prompt, max_new_tokens=MAX_NEW, temperature=1.0,
                        top_k=8, rng=rng_key)
    router.step()  # dispatched; first chunk ran (prefill_chunk=8 < 24)
    victim = router._where[rid]
    assert router.replicas[victim].engine._paged.prefill, \
        "expected the request to be mid-chunked-prefill"
    router.kill_replica(victim)
    done = {f.req_id: f for f in router.drain()}
    assert done[rid].tokens == ref
    assert done[rid].failovers == 1
    assert reg.snapshot()["counters"]["serve_failovers"] == 1
    # parent-side leak audit (ISSUE 9 satellite)
    assert router._by_replica[victim] == {} and router._where == {} \
        and router._open == {}
    router.revive_replica(victim)   # reset_host_state -> fresh allocator
    router.replicas[victim].engine._paged.audit(expect_empty=True)
    for rep in router.replicas:
        rep.engine._paged.audit(expect_empty=True)


def test_engine_paged_metrics(gpt_fix):
    """The four ISSUE 9 metrics flow through the schema-checked
    registry, and stats() carries the page budget for worker
    heartbeats."""
    model, reqs = gpt_fix
    reg = MetricsRegistry()
    engine = Engine(model, n_slots=2, max_seq_len=32, registry=reg,
                    **PAGED_KW)
    _run_schedule(engine, reqs[:4], bursts=[2, 2])
    snap = reg.snapshot()
    assert snap["counters"]["prefill_chunks"] >= 4
    assert snap["gauges"]["kv_pages_free"] == engine.n_pages
    assert snap["gauges"]["kv_page_util"] == 0.0   # drained
    assert 0.0 <= snap["gauges"]["prefix_hit_rate"] <= 1.0
    s = engine.stats()
    assert s["kv"]["impl"] == "paged"
    assert s["kv"]["n_pages"] == engine.n_pages
    assert s["kv"]["pages_free"] == engine.n_pages
    assert s["prefilling"] == 0


@pytest.mark.slow
def test_prefix_sharing_soak():
    """E2E soak: 24 requests over a small pool, most sharing one system
    prompt, arrivals forcing temporal reuse, eviction cycles and COW —
    sampled bit-parity, clean audit, ONE decode compile."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    rng = np.random.default_rng(11)
    prefix = [int(t) for t in rng.integers(0, 64, (17,))]
    reqs = _mk_requests(model, rng, 24, max_prompt=10,
                        shared_prefix=prefix, combos=((1.0, 8), (0.9, None)))
    engine = Engine(model, n_slots=3, max_seq_len=48,
                    registry=MetricsRegistry(),
                    kv_impl="paged", page_size=8, n_pages=30,
                    prefill_chunk=16)
    results = _run_schedule(engine, reqs,
                            bursts=[3, 0, 2, 0, 0, 1] * 8)
    _assert_parity(results, reqs)
    assert engine._paged.alloc.prefix_hits >= 6
    assert len(engine.traces["step"]) == 1
    engine._paged.audit(expect_empty=True)


@pytest.mark.slow
def test_chaos_kills_during_paged_serving():
    """Chaos: seeded kills through the router while paged replicas hold
    queued, mid-prefill and decoding work — zero lost, all served
    outputs bit-identical, no bookkeeping leaks."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    rng = np.random.default_rng(5)
    reqs = _mk_requests(model, rng, 10, max_prompt=20,
                        combos=((1.0, 8),))
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=2, max_seq_len=32,
                    registry=reg, engine_kwargs=dict(**PAGED_KW))
    ids = {}
    pending = list(range(len(reqs)))
    results = {}
    kill_rng = np.random.default_rng(99)
    steps = 0
    while pending or router.open_requests or router._pending:
        for _ in range(min(2, len(pending))):
            i = pending.pop(0)
            ids[router.submit(**reqs[i][0])] = i
        for f in router.step():
            results[ids[f.req_id]] = f
        steps += 1
        if steps in (3, 9):   # seeded kills mid-flight
            alive = [r for r in router.replicas if r.state != "dead"]
            if len(alive) == 2:
                victim = alive[int(kill_rng.integers(0, 2))]
                router.kill_replica(victim.replica_id)
        if steps in (6, 12):  # revive so the fleet can finish
            for r in router.replicas:
                if r.state == "dead":
                    router.revive_replica(r.replica_id)
    assert len(results) == len(reqs)
    for i, (kw, ref) in enumerate(reqs):
        assert results[i].tokens == ref, f"request {i} diverged"
    assert router._open == {} and router._where == {}
    for r in router.replicas:
        if r.state != "dead":
            r.engine._paged.audit()


@pytest.mark.slow
def test_worker_process_paged_handshake_and_parity():
    """Process backend with kv_impl=paged: the hello carries the page
    knobs out and the page budget back, heartbeats mirror the paging
    pressure parent-side, outputs stay bit-identical, and a dead
    worker's parent-side request bookkeeping is cleared (leak audit)."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    reqs = _mk_requests(model, np.random.default_rng(4), 3,
                        combos=((1.0, 8),))
    reg = MetricsRegistry()
    router = Router(model, n_replicas=1, n_slots=2, max_seq_len=32,
                    registry=reg, backend="process",
                    engine_kwargs=dict(**PAGED_KW))
    try:
        rep = router.replicas[0]
        assert rep.engine.kv_impl == "paged"
        assert rep.engine.max_total_tokens == 32
        ids = {router.submit(**kw): i for i, (kw, _) in enumerate(reqs)}
        done = {ids[f.req_id]: f for f in router.drain()}
        for i, (kw, ref) in enumerate(reqs):
            assert done[i].tokens == ref
        assert rep.engine.kv is not None
        assert rep.engine.kv["impl"] == "paged"
        assert rep.engine.kv["pages_free"] == PAGED_KW["n_pages"]
        rep.mark_dead()
        assert rep._submit_t == {} and rep._deadline == {} \
            and rep._t_first == {}
    finally:
        router.close()


@pytest.mark.slow
def test_serve_bench_sweep_smoke(tmp_path):
    """tools/serve_bench.py --sweep end-to-end on tiny settings: both
    impls swept, the BENCH JSON lands with the expected shape."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "bench_paged.json"
    r = subprocess.run(
        [sys.executable, "tools/serve_bench.py", "--sweep",
         "--block_size=64", "--kv_budget_tokens=256", "--page_size=8",
         "--shared_prefix=24", "--tail_min=4", "--tail_max=12",
         "--max_new_tokens=4", "--sweep_requests=8",
         "--max_concurrency=8", "--n_layer=1", "--n_embd=32",
         f"--out={out}"],
        cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode in (0, 1), r.stdout + r.stderr  # 1 = ratio < 2
    bench = json.loads(out.read_text())
    assert bench["kind"] == "paged_kv_sweep"
    for impl in ("slab", "paged"):
        assert "max_sustainable_concurrency" in bench[impl]
        assert bench[impl]["trials"]
    assert "concurrency_ratio" in bench
