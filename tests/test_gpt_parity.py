"""Backend equivalence tests (SURVEY.md §4 "Unit: model parity"): torch GPT
(model.py) vs nnx GPT (avenir_tpu/models/gpt.py) must produce identical
logits/loss on identical weights — the loss curve IS the acceptance metric
(BASELINE.json:2)."""

import numpy as np
import pytest
import torch

from model import GPT as TorchGPT, GPTConfig as TorchGPTConfig

from flax import nnx
import jax
import jax.numpy as jnp

from avenir_tpu.checkpoint.bridge import (
    export_torch_state_dict,
    load_torch_state_dict,
)
from avenir_tpu.models.gpt import GPT, GPTConfig

TINY = dict(block_size=16, vocab_size=65, n_layer=2, n_head=2, n_embd=32,
            dropout=0.0)


def _torch_model(bias):
    torch.manual_seed(0)
    m = TorchGPT(TorchGPTConfig(bias=bias, **TINY))
    m.eval()
    return m


def _nnx_model(bias):
    return GPT(GPTConfig(bias=bias, **TINY), rngs=nnx.Rngs(0))


def _numpy_sd(torch_model):
    return {k: v.detach().numpy() for k, v in torch_model.state_dict().items()}


@pytest.mark.parametrize("bias", [True, False])
def test_logits_and_loss_parity(bias):
    tm = _torch_model(bias)
    jm = _nnx_model(bias)
    load_torch_state_dict(jm, _numpy_sd(tm))

    rng = np.random.default_rng(0)
    idx = rng.integers(0, TINY["vocab_size"], (3, TINY["block_size"]))
    tgt = rng.integers(0, TINY["vocab_size"], (3, TINY["block_size"]))
    tgt[0, :4] = -1  # exercise ignore_index

    with torch.no_grad():
        t_logits, t_loss = tm(torch.from_numpy(idx), torch.from_numpy(tgt))
    j_logits, j_loss = jm(jnp.asarray(idx), jnp.asarray(tgt))

    np.testing.assert_allclose(
        np.asarray(j_logits), t_logits.numpy(), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(float(j_loss), float(t_loss), atol=1e-5, rtol=1e-5)


def test_inference_path_last_position_only():
    tm = _torch_model(True)
    jm = _nnx_model(True)
    load_torch_state_dict(jm, _numpy_sd(tm))
    idx = np.arange(8, dtype=np.int64)[None, :] % TINY["vocab_size"]
    with torch.no_grad():
        t_logits, _ = tm(torch.from_numpy(idx))
    j_logits, j_loss = jm(jnp.asarray(idx))
    assert j_loss is None
    assert j_logits.shape == (1, 1, TINY["vocab_size"])
    np.testing.assert_allclose(
        np.asarray(j_logits), t_logits.numpy(), atol=2e-5, rtol=2e-5
    )


def test_export_round_trip():
    """nnx → torch state_dict → fresh torch model gives identical logits."""
    jm = _nnx_model(True)
    sd = export_torch_state_dict(jm)
    tm = _torch_model(True)
    tm.load_state_dict({k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()})
    tm.eval()
    idx = np.arange(12, dtype=np.int64)[None, :] % TINY["vocab_size"]
    tgt = np.roll(idx, -1, axis=1)
    with torch.no_grad():
        t_logits, t_loss = tm(torch.from_numpy(idx), torch.from_numpy(tgt))
    j_logits, j_loss = jm(jnp.asarray(idx), jnp.asarray(tgt))
    np.testing.assert_allclose(
        np.asarray(j_logits), t_logits.numpy(), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(float(j_loss), float(t_loss), atol=1e-5, rtol=1e-5)


def test_param_count_matches_torch():
    tm = _torch_model(True)
    jm = _nnx_model(True)
    assert jm.get_num_params() == tm.get_num_params()
    assert jm.get_num_params(False) == tm.get_num_params(False)


def test_grad_flow_through_tied_embedding():
    """Weight tying must route lm_head grads into wte, like torch."""
    jm = _nnx_model(True)
    graphdef, params = nnx.split(jm, nnx.Param)

    idx = jnp.zeros((2, 8), dtype=jnp.int32)
    tgt = jnp.ones((2, 8), dtype=jnp.int32)

    def loss_fn(p):
        m = nnx.merge(graphdef, p)
        _, loss = m(idx, tgt)
        return loss

    grads = jax.grad(loss_fn)(params)
    g_wte = np.asarray(grads["wte"]["embedding"].get_value())
    # tokens 0 and 1 get input-path grads no matter what; the discriminating
    # signal for TYING is the softmax denominator pushing grads into vocab
    # rows that never appear in idx/tgt — check one of those
    unused_row = TINY["vocab_size"] - 1
    assert np.abs(g_wte[unused_row]).sum() > 0, (
        "no grad on an unused vocab row: lm_head grads are not flowing into wte"
    )
