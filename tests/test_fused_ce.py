"""Fused chunked lm-head + cross-entropy tail tests (ISSUE 3).

The contract: loss AND gradients of the blocked (lax.scan) and pallas
(interpret-mode) impls match the reference full-logits path within fp32
tolerance — for the bare op (both weight layouts, ignore_index rows,
non-divisible T/V chunk edges) and through all three model families —
while the (B, T, V) logits array never appears in the train-step jaxpr
and the chunked scan traces once per compile, not once per step."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.models.common import cross_entropy_loss
from avenir_tpu.ops import fused_ce as fce
from avenir_tpu.ops.fused_ce import fused_cross_entropy

B, T, C, V = 2, 19, 32, 37  # deliberately ragged vs every default chunk


def _data(seed=0, vocab=V, t=T):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(B, t, C)).astype(np.float32))
    w_cv = jnp.asarray(rng.normal(size=(C, vocab)).astype(np.float32) * 0.1)
    y = jnp.asarray(rng.integers(0, vocab, (B, t)).astype(np.int32))
    y = y.at[0, 3].set(-1).at[1, t - 1].set(-1)  # masked rows
    return x, w_cv, y


def _ref(x, w, y, w_layout="cv"):
    eq = "btc,cv->btv" if w_layout == "cv" else "btc,vc->btv"
    return cross_entropy_loss(jnp.einsum(eq, x, w), y, ignore_index=-1)


@pytest.mark.parametrize("impl", ["blocked", "pallas"])
@pytest.mark.parametrize("w_layout", ["cv", "vc"])
def test_op_loss_and_grad_parity(impl, w_layout):
    x, w, y = _data()
    if w_layout == "vc":
        w = w.T  # construct (V, C); the op must not transpose it back
    kw = dict(t_chunk=8) if impl == "blocked" else {}

    fused = lambda x, w: fused_cross_entropy(
        x, w, y, impl=impl, w_layout=w_layout, **kw)
    ref = lambda x, w: _ref(x, w, y, w_layout)
    lf, (dxf, dwf) = jax.jit(jax.value_and_grad(fused, argnums=(0, 1)))(x, w)
    lr, (dxr, dwr) = jax.jit(jax.value_and_grad(ref, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(dxr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dwf), np.asarray(dwr), atol=1e-5)


@pytest.mark.parametrize("impl", ["blocked", "pallas"])
def test_op_all_targets_masked(impl):
    """An all-ignore_index batch must give loss 0 and zero grads (the
    n_valid=0 guard), not a division blowup."""
    x, w, _ = _data()
    y = jnp.full((B, T), -1, jnp.int32)
    f = lambda x, w: fused_cross_entropy(x, w, y, impl=impl, w_layout="cv")
    l, (dx, dw) = jax.value_and_grad(f, argnums=(0, 1))(x, w)
    assert float(l) == 0.0
    assert float(jnp.abs(dx).max()) == 0.0
    assert float(jnp.abs(dw).max()) == 0.0


def test_blocked_chunk_edges():
    """Chunk sizes that divide T, don't divide T, and exceed T all agree
    with the reference (the pad-with-ignore_index edge)."""
    x, w, y = _data()
    lr = float(_ref(x, w, y))
    for tc in (4, 19, 64):
        lf = float(fused_cross_entropy(
            x, w, y, impl="blocked", w_layout="cv", t_chunk=tc))
        np.testing.assert_allclose(lf, lr, rtol=1e-6)


@pytest.mark.parametrize("vocab", [37, 64, 130])
def test_pallas_vocab_edges(vocab):
    """Vocab sizes around the kernel's block ladder (divisible and not)
    agree with the reference — the in-kernel column mask."""
    x, w, y = _data(seed=vocab, vocab=vocab)
    f = lambda x, w: fused_cross_entropy(
        x, w, y, impl="pallas", w_layout="cv")
    lf, (dxf, dwf) = jax.jit(
        jax.value_and_grad(f, argnums=(0, 1)))(x, w)
    lr, (dxr, dwr) = jax.jit(jax.value_and_grad(
        lambda x, w: _ref(x, w, y), argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dxf), np.asarray(dxr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(dwf), np.asarray(dwr), atol=1e-5)


# ---- model families ----


def _families():
    from avenir_tpu.models.gpt import GPT, GPTConfig
    from avenir_tpu.models.llama import Llama, LlamaConfig
    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

    return {
        "gpt": (GPT, GPTConfig(block_size=32, vocab_size=61, n_layer=1,
                               n_head=2, n_embd=32, bias=True)),
        "llama": (Llama, LlamaConfig(block_size=32, vocab_size=61,
                                     n_layer=1, n_head=2, n_kv_head=1,
                                     n_embd=32, ffn_hidden=64)),
        "mixtral": (Mixtral, MixtralConfig(block_size=32, vocab_size=61,
                                           n_layer=1, n_head=2, n_kv_head=1,
                                           n_embd=32, ffn_hidden=64,
                                           n_experts=4, n_experts_per_tok=2)),
    }


def _family_tokens():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 61, (2, 19)).astype(np.int32))
    y = jnp.asarray(rng.integers(0, 61, (2, 19)).astype(np.int32))
    return x, y.at[0, 2].set(-1)


def _family_loss_and_grads(family, loss_impl):
    ctor, cfg = _families()[family]
    x, y = _family_tokens()
    c = dataclasses.replace(cfg, loss_impl=loss_impl, loss_chunk=8)
    gd, params = nnx.split(ctor(c, rngs=nnx.Rngs(0)), nnx.Param)
    loss_fn = lambda p: nnx.merge(gd, p)(x, y)[1]
    return jax.jit(jax.value_and_grad(loss_fn))(params)


@pytest.fixture(scope="module")
def family_ref():
    """Reference-path loss+grads per family, computed once per module
    (each is a full fwd+bwd compile — sharing it keeps every test in
    this file under the tier-1 slow budget test_zz_slow_guard pins)."""
    return {f: _family_loss_and_grads(f, "reference") for f in _families()}


@pytest.mark.parametrize("family", ["gpt", "llama", "mixtral"])
@pytest.mark.parametrize("impl", ["blocked", "pallas"])
def test_model_loss_and_grad_parity(family, impl, family_ref):
    """End-to-end through each family: same params, loss and EVERY param
    grad (incl. the GPT tied-wte contribution and the Mixtral router aux
    term on top) match the reference path within fp32 tolerance."""
    lr, gr = family_ref[family]
    lf, gf = _family_loss_and_grads(family, impl)
    np.testing.assert_allclose(float(lf), float(lr), rtol=1e-5)
    flat_r = dict(gr.flat_state())
    flat_f = dict(gf.flat_state())
    assert flat_r.keys() == flat_f.keys()
    for path, vr in flat_r.items():
        np.testing.assert_allclose(
            np.asarray(flat_f[path].get_value()),
            np.asarray(vr.get_value()), atol=2e-5,
            err_msg=f"{family}/{impl}: grad mismatch at {path}",
        )


def test_fused_model_returns_no_logits():
    """The fused tail never materializes logits, so the model returns
    None for them when targets are given — and the inference path
    (targets=None) is untouched."""
    ctor, cfg = _families()["gpt"]
    c = dataclasses.replace(cfg, loss_impl="blocked")
    m = ctor(c, rngs=nnx.Rngs(0))
    x = jnp.zeros((1, 8), jnp.int32)
    logits, loss = m(x, x)
    assert logits is None and loss is not None
    logits, loss = m(x)
    assert logits is not None and logits.shape[-1] == 61 and loss is None


# ---- the memory guarantee + compile discipline ----


def _all_avals(closed_jaxpr):
    """Every aval in the jaxpr, recursing into sub-jaxprs (scan/cond/
    checkpoint bodies, custom_vjp calls)."""
    from jax.extend import core as jex_core  # jax 0.4.x location

    Jaxpr = jex_core.Jaxpr
    ClosedJaxpr = jex_core.ClosedJaxpr

    out = []

    def subs(p):
        if isinstance(p, ClosedJaxpr):
            yield p.jaxpr
        elif isinstance(p, Jaxpr):
            yield p
        elif isinstance(p, (tuple, list)):
            for q in p:
                yield from subs(q)
        elif isinstance(p, dict):
            for q in p.values():
                yield from subs(q)

    def rec(j):
        for eqn in j.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                a = getattr(v, "aval", None)
                if a is not None and getattr(a, "shape", None) is not None:
                    out.append(a)
            for p in eqn.params.values():
                for sub in subs(p):
                    rec(sub)

    rec(closed_jaxpr.jaxpr)
    return out


def _grad_jaxpr(loss_impl):
    from avenir_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(block_size=64, vocab_size=256, n_layer=1, n_head=2,
                    n_embd=32, bias=False, loss_impl=loss_impl,
                    loss_chunk=16)
    gd, params = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)
    x = jnp.zeros((2, 64), jnp.int32)
    loss_fn = lambda p, x, y: nnx.merge(gd, p)(x, y)[1]
    return jax.make_jaxpr(jax.value_and_grad(loss_fn))(params, x, x)


def test_no_full_logits_in_blocked_jaxpr():
    """Acceptance gate: with loss_impl=blocked no (B, T, V)-shaped array
    exists anywhere in the fwd+bwd jaxpr of the step — while the SAME
    scanner run on the reference path does find one (so a scanner bug
    can't silently pass the guard)."""
    full = (2, 64, 256)  # (B, T, V) of _grad_jaxpr's model

    def shapes(loss_impl):
        return {tuple(a.shape) for a in _all_avals(_grad_jaxpr(loss_impl))}

    assert full in shapes("reference"), "scanner lost the reference logits"
    blocked = shapes("blocked")
    assert full not in blocked
    # nor a flattened (B*T, V) spelling of the same array
    assert (2 * 64, 256) not in blocked


def test_chunked_tail_traces_once():
    """Trace-ledger pin: the fused tail appears in the trace exactly when
    the step compiles — repeated calls of the jitted step never retrace
    the chunked scan."""
    from avenir_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(block_size=32, vocab_size=64, n_layer=1, n_head=2,
                    n_embd=32, bias=False, loss_impl="blocked", loss_chunk=8)
    gd, params = nnx.split(GPT(cfg, rngs=nnx.Rngs(0)), nnx.Param)
    x = jnp.zeros((2, 32), jnp.int32)

    @jax.jit
    def step(p, x, y):
        return jax.value_and_grad(lambda p: nnx.merge(gd, p)(x, y)[1])(p)

    step(params, x, x)  # trace + compile
    warm = fce.trace_count()
    for _ in range(3):
        step(params, x, x)
    assert fce.trace_count() == warm, "fused tail retraced on a warm step"


def test_blocked_tensor_parallel_sharded_weight():
    """The blocked tail under a tensor-sharded lm-head weight (the
    partition.py layout) must match the unsharded result: chunk over
    time, psum over tensor — GSPMD inserts the vocab-axis collectives
    for the chunk reductions exactly as on the reference path."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.compat import set_mesh
    from avenir_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 16, C)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(C, 64)).astype(np.float32) * 0.1)
    y = jnp.asarray(rng.integers(0, 64, (4, 16)).astype(np.int32))
    f = lambda x, w: fused_cross_entropy(x, w, y, impl="blocked",
                                         w_layout="cv", t_chunk=8)
    lr, (dxr, dwr) = jax.jit(jax.value_and_grad(f, argnums=(0, 1)))(x, w)

    mesh = make_mesh("data:2,tensor:2")
    set_mesh(mesh)  # conftest restores the empty mesh after the test
    xs = jax.device_put(x, NamedSharding(mesh, P(("data",), None, None)))
    ws = jax.device_put(w, NamedSharding(mesh, P(None, "tensor")))
    ls, (dxs, dws) = jax.jit(jax.value_and_grad(f, argnums=(0, 1)))(xs, ws)
    np.testing.assert_allclose(float(ls), float(lr), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dxs), np.asarray(dxr), atol=1e-6)
    np.testing.assert_allclose(np.asarray(dws), np.asarray(dwr), atol=1e-6)


def test_pallas_spmd_wrap_matches_unsharded():
    """The pallas tail's shard_map wrap (rows over the batch axes, dw
    psum'd in the hand-written backward) must reproduce the unsharded
    loss and grads bit-for-bit-ish on the 8-device CPU harness."""
    from avenir_tpu.compat import set_mesh
    from avenir_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 16, C)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(C, 64)).astype(np.float32) * 0.1)
    y = jnp.asarray(rng.integers(0, 64, (4, 16)).astype(np.int32))
    f = lambda x, w: fused_cross_entropy(x, w, y, impl="pallas",
                                         w_layout="cv")
    lr, (dxr, dwr) = jax.jit(
        jax.value_and_grad(f, argnums=(0, 1)))(x, w)

    mesh = make_mesh("data:2,fsdp:2")
    set_mesh(mesh)  # conftest restores the empty mesh after the test
    ls, (dxs, dws) = jax.jit(
        jax.value_and_grad(f, argnums=(0, 1)))(x, w)
    np.testing.assert_allclose(float(ls), float(lr), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dxs), np.asarray(dxr), atol=1e-6)
    np.testing.assert_allclose(np.asarray(dws), np.asarray(dwr), atol=1e-6)


def test_resolve_loss_impl():
    assert fce.resolve_loss_impl("") == "reference"
    assert fce.resolve_loss_impl(None) == "reference"
    assert fce.resolve_loss_impl("reference") == "reference"
    assert fce.resolve_loss_impl("blocked") == "blocked"
    assert fce.resolve_loss_impl("pallas") == "pallas"
    assert fce.resolve_loss_impl("auto") == "blocked"  # CPU harness
    with pytest.raises(AssertionError):
        fce.resolve_loss_impl("nope")


def test_auto_avoids_pallas_on_tp_mesh():
    """'auto' must not pick the weight-replicating pallas wrap when the
    mesh has a tensor axis > 1 (the _tp_mesh_active gate — on TPU 'auto'
    resolves to 'blocked' there; docs/PERFORMANCE.md)."""
    from avenir_tpu.compat import set_mesh
    from avenir_tpu.parallel.mesh import make_mesh

    assert not fce._tp_mesh_active()
    set_mesh(make_mesh("data:4,tensor:2"))
    assert fce._tp_mesh_active()  # the gate 'auto' consults on TPU
    assert fce.resolve_loss_impl("auto") == "blocked"
    set_mesh(make_mesh("data:8"))
    assert not fce._tp_mesh_active()
