"""Elastic control plane tests (serve/autoscale.py, ISSUE 12): SLO
engine windowing + burn rate, traced wait predictor + predictive
admission, autoscaler hysteresis (the no-flapping pin), scale-to-zero
burst wake, compile pre-warm, dynamic router fleet, and the
triple-audit pin — every scale decision is a counter bump AND a trace
event with evidence AND a fleet_report row.

Budget notes (the test_serve_router discipline): one module-scoped tiny
GPT; serving tests share one prompt bucket and a small MAX_NEW so each
fresh engine pays one prefill + one decode compile; timing-sensitive
and multi-engine-compile cases are marked slow (ISSUE 12 satellite)."""

import json

import numpy as np
import pytest
from flax import nnx

from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.obs.trace import Tracer
from avenir_tpu.serve import Engine, Router
from avenir_tpu.serve.autoscale import (
    Autoscaler,
    SLOEngine,
    WaitPredictor,
    request_met_slo,
)
from avenir_tpu.serve.engine import FinishedRequest

GPT_TINY = GPTConfig(block_size=64, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
MAX_NEW = 3


@pytest.fixture(scope="module")
def model():
    return GPT(GPT_TINY, rngs=nnx.Rngs(0))


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _prompt(rng, n=5):
    return [int(t) for t in rng.integers(0, 64, (n,))]


def _fin(ttft_ms, *, priority="interactive", reason="length", n_out=4,
         tpot_ms=1.0):
    f = FinishedRequest(req_id=0, tokens=[1], n_prompt=1, n_out=n_out,
                        finish_reason=reason, text=None,
                        ttft_ms=ttft_ms, tpot_ms=tpot_ms)
    f.priority = priority
    return f


# ---------------------------------------------------------------------------
# SLO engine
# ---------------------------------------------------------------------------


def test_slo_engine_windowed_attainment_and_burn():
    clk = _Clock()
    reg = MetricsRegistry()
    slo = SLOEngine(slo_ttft_ms=100.0, slo_tpot_ms=50.0,
                    target_attainment=0.9, window_s=10.0, clock=clk,
                    registry=reg)
    assert slo.attainment() is None and slo.burn_rate() is None
    # 3 good + 1 bad interactive, 1 good batch
    slo.observe([_fin(10.0), _fin(10.0), _fin(10.0), _fin(500.0),
                 _fin(10.0, priority="batch")])
    assert slo.attainment("interactive") == pytest.approx(0.75)
    assert slo.attainment("batch") == pytest.approx(1.0)
    assert slo.attainment() == pytest.approx(0.8)
    # burn = worst class: (1 - 0.75) / (1 - 0.9) = 2.5
    assert slo.burn_rate() == pytest.approx(2.5)
    g = reg.snapshot()["gauges"]
    assert g["slo_attainment_interactive"] == pytest.approx(0.75)
    assert g["slo_attainment_batch"] == pytest.approx(1.0)
    assert g["slo_burn_rate"] == pytest.approx(2.5)
    # the window forgets: 11s later the early observations are gone
    clk.t = 11.0
    slo.observe([_fin(10.0)])
    assert slo.attainment("interactive") == pytest.approx(1.0)
    assert slo.burn_rate() == pytest.approx(0.0)


def test_slo_engine_scoring_rules():
    """Shed/timeout are SLO misses (the user-visible symptom of an
    under-provisioned fleet); door rejections are excluded; TPOT only
    binds where defined (n_out > 1) — the serve_bench slo_attainment
    rule, shared via request_met_slo."""
    assert request_met_slo(_fin(10.0), slo_ttft_ms=100, slo_tpot_ms=50)
    assert not request_met_slo(_fin(500.0), slo_ttft_ms=100,
                               slo_tpot_ms=50)
    assert not request_met_slo(_fin(10.0, tpot_ms=80.0),
                               slo_ttft_ms=100, slo_tpot_ms=50)
    assert request_met_slo(_fin(10.0, n_out=1, tpot_ms=0.0),
                           slo_ttft_ms=100, slo_tpot_ms=50)
    assert not request_met_slo(_fin(None, reason="shed"),
                               slo_ttft_ms=100, slo_tpot_ms=50)
    assert not request_met_slo(_fin(None, reason="timeout"),
                               slo_ttft_ms=100, slo_tpot_ms=50)
    slo = SLOEngine(slo_ttft_ms=100.0, slo_tpot_ms=50.0,
                    clock=_Clock(), registry=MetricsRegistry())
    slo.observe([_fin(None, reason="rejected"), _fin(None, reason="shed")])
    assert slo.n_observed == 1  # the rejection never entered the window
    assert slo.attainment() == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# wait predictor + predictive admission
# ---------------------------------------------------------------------------


def test_wait_predictor_fit_and_fallback():
    p = WaitPredictor(min_samples=8)
    assert p.predict_ms(3) is None  # unfit -> router keeps static rule
    for d in range(8):
        p.observe(d, 0.010 + 0.005 * d)  # wait = 10ms + 5ms/depth
    assert p.predict_ms(0) == pytest.approx(10.0, abs=1.0)
    assert p.predict_ms(4) == pytest.approx(30.0, abs=1.0)
    assert p.predict_ms(10) == pytest.approx(60.0, abs=2.0)
    # degenerate fit (every sample at one depth): the mean answers
    # only NEAR that depth — a far-off burst depth falls back to the
    # static rule (None) instead of projecting the calm-period ~0
    p2 = WaitPredictor(min_samples=4)
    for _ in range(4):
        p2.observe(2, 0.050)
    assert p2.predict_ms(2) == pytest.approx(50.0, abs=1.0)
    assert p2.predict_ms(3) == pytest.approx(50.0, abs=1.0)
    assert p2.predict_ms(7) is None
    # a deeper queue never predicts a SHORTER wait (slope clamped to
    # 0), and the resulting FLAT fit abstains outside its observed
    # depth support instead of projecting calm-period waits at a burst
    p3 = WaitPredictor(min_samples=4)
    for d, w in [(0, 0.1), (1, 0.08), (2, 0.06), (3, 0.04)]:
        p3.observe(d, w)
    assert p3.predict_ms(3) >= p3.predict_ms(0) - 1e-6
    assert p3.predict_ms(10) is None


def test_router_predictive_admission_gated_on_tracer(model):
    """With tracing armed the router fits a per-class predictor on its
    dispatch history and projected_wait_ms answers from it; without a
    tracer the static rule stands (wait_predictor is None)."""
    rng = np.random.default_rng(0)
    clk = _Clock()
    reg = MetricsRegistry()
    r_plain = Router(model, n_replicas=1, n_slots=2, max_seq_len=16,
                     registry=reg, seed=0, clock=clk)
    assert r_plain.wait_predictor is None
    tr = Tracer(registry=reg, clock=clk)
    router = Router(model, n_replicas=1, n_slots=2, max_seq_len=16,
                    registry=reg, seed=0, clock=clk, tracer=tr)
    assert set(router.wait_predictor) == {"interactive", "batch"}
    # serve enough requests to fit the interactive predictor; the fake
    # clock advances 50 ms per router step, so queued submits observe
    # real nonzero waits
    rids = []
    for i in range(10):
        rids.append(router.submit(_prompt(rng), max_new_tokens=MAX_NEW))
    while router.open_requests:
        clk.t += 0.05
        router.step()
    p = router.wait_predictor["interactive"]
    assert p.n_samples == 10
    # the predictor now answers projected_wait_ms (depth 0 -> its fit,
    # not the static rule's median-hold estimate)
    assert router.projected_wait_ms("interactive") == pytest.approx(
        p.predict_ms(0))


# ---------------------------------------------------------------------------
# autoscaler decisions
# ---------------------------------------------------------------------------


def _mk_scaler(model, clk, reg, tracer=None, **kw):
    router = Router(model, n_replicas=kw.pop("n_replicas", 1),
                    n_slots=2, max_seq_len=16, registry=reg, seed=0,
                    clock=clk, tracer=tracer)
    slo = SLOEngine(slo_ttft_ms=100.0, slo_tpot_ms=50.0,
                    target_attainment=0.9, window_s=10.0, clock=clk,
                    registry=reg)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("up_stable_s", 2.0)
    kw.setdefault("down_stable_s", 5.0)
    kw.setdefault("cooldown_s", 4.0)
    kw.setdefault("prewarm", False)  # decision tests skip the compiles
    scaler = Autoscaler(router, slo, registry=reg, clock=clk,
                        echo=lambda *a: None, **kw)
    return router, scaler


def test_scale_up_on_sustained_burn_with_cooldown(model):
    clk = _Clock()
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=clk)
    router, scaler = _mk_scaler(model, clk, reg, tracer=tr)
    decisions = []
    for _ in range(12):
        clk.t += 1.0
        scaler.observe([_fin(500.0)])  # every request missing its SLO
        d = scaler.poll()
        if d:
            decisions.append((clk.t, d))
    # sustained burn grows the fleet to max, one cooldown apart, and
    # never past max_replicas
    assert router.fleet_size == 3
    assert [d.action for _, d in decisions] == ["up", "up"]
    assert reg.snapshot()["counters"]["scale_up"] == 2
    t_first, t_second = decisions[0][0], decisions[1][0]
    assert t_second - t_first >= scaler.cooldown_s
    for _, d in decisions:
        ev = d.evidence
        assert ev["burn_rate"] >= scaler.up_burn
        assert d.to_size == d.from_size + 1
    # trace events carry the same evidence (the audit trail)
    evs = [e for e in tr.events() if e["ev"] == "scale"]
    assert len(evs) == 2
    for e in evs:
        assert e["action"] == "up" and e["reason"] == "burn_rate"
        assert e["burn_rate"] >= 1.0 and "attainment_interactive" in e
        assert e["to_size"] == e["from_size"] + 1


def test_no_flapping_under_steady_load(model):
    """THE no-flapping pin: steady in-SLO load on a fleet whose
    utilization justifies its size -> ZERO scale decisions after
    warm-up. The scale-down surplus check requires the SHRUNKEN fleet
    to stay under down_util, which a busy steady fleet fails."""
    rng = np.random.default_rng(1)
    clk = _Clock()
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=clk)
    router, scaler = _mk_scaler(model, clk, reg, tracer=tr,
                                n_replicas=2, down_util=0.6)
    # two requests keep 2 of 4 slots live (one step admits them, then
    # the fleet loop idles): util 0.5; a 1-replica fleet would sit at
    # 1.0 > down_util -> down blocked
    for _ in range(2):
        router.submit(_prompt(rng), max_new_tokens=8)
    router.step()
    assert sum(len(r.engine._live) for r in router.replicas) == 2
    for i in range(60):
        clk.t += 1.0
        scaler.observe([_fin(10.0)])   # healthy traffic, burn 0
        scaler.poll()
    assert scaler.decisions == []
    assert [e for e in tr.events() if e["ev"] == "scale"] == []
    counters = reg.snapshot()["counters"]
    assert counters.get("scale_up", 0) == 0
    assert counters.get("scale_down", 0) == 0


def test_scale_down_on_sustained_surplus(model):
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_scaler(model, clk, reg, n_replicas=3)
    for _ in range(30):
        clk.t += 1.0
        scaler.observe([_fin(10.0)])  # in SLO, fleet idle -> surplus
        scaler.poll()
        router.step()  # the fleet loop: reaps drained retirees
    # down to min_replicas and no further, each down a cooldown apart
    assert router.fleet_size == 1
    assert reg.snapshot()["counters"]["scale_down"] == 2
    assert [d.action for d in scaler.decisions] == ["down", "down"]
    # the retired replicas were drained and REMOVED (processes closed)
    assert len(router.replicas) == 1


def test_retire_waits_for_draining_work(model):
    """A scale-down victim holding live work drains first: no new
    dispatches, in-flight work finishes, THEN the replica is reaped —
    a scale decision never drops an accepted request."""
    rng = np.random.default_rng(2)
    clk = _Clock()
    reg = MetricsRegistry()
    router = Router(model, n_replicas=2, n_slots=1, max_seq_len=16,
                    registry=reg, seed=0, clock=clk)
    rids = [router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
            for _ in range(2)]
    router.step()  # both replicas now hold one live request each
    victim = router.replicas[1]
    assert victim.engine._live
    router.retire_replica(1)
    assert victim.state == "draining"
    done = router.drain()
    assert {f.req_id for f in done} == set(rids)
    assert all(f.finish_reason == "length" for f in done)
    # drained empty -> reaped out of the fleet
    assert [r.replica_id for r in router.replicas] == [0]
    assert router.fleet_size == 1


def test_scale_to_zero_and_burst_wake(model):
    rng = np.random.default_rng(3)
    clk = _Clock()
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=clk)
    router, scaler = _mk_scaler(model, clk, reg, tracer=tr,
                                scale_to_zero=True, idle_to_zero_s=5.0)
    assert scaler.min_replicas == 0
    for _ in range(20):
        clk.t += 1.0
        router.step()
        scaler.poll()
    assert router.fleet_size == 0 and router.replicas == []
    assert any(d.reason == "idle_to_zero" for d in scaler.decisions)
    # burst wake: work arrives on an empty fleet -> immediate spawn
    # (no stability window, no cooldown — an outage, not an
    # oscillation), and the queued request is served
    rid = router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
    clk.t += 0.1
    d = scaler.poll()
    assert d is not None and d.action == "wake" and d.to_size == 1
    done = scaler.drain()
    assert [f.req_id for f in done] == [rid]
    assert done[0].finish_reason == "length"
    wake_evs = [e for e in tr.events()
                if e["ev"] == "scale" and e["action"] == "wake"]
    assert len(wake_evs) == 1


def test_idle_to_zero_retires_whole_fleet_in_one_decision(model):
    """The documented scale-to-zero contract: after idle_to_zero_s the
    WHOLE fleet retires in one decision — not one replica per idle
    window, which would bill ~fleet x (idle + cooldown) extra
    replica-seconds per idle period."""
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_scaler(model, clk, reg, n_replicas=3,
                                scale_to_zero=True, idle_to_zero_s=5.0)
    for _ in range(10):
        clk.t += 1.0
        router.step()
        scaler.poll()
    assert router.fleet_size == 0 and router.replicas == []
    downs = [d for d in scaler.decisions if d.action == "down"]
    assert len(downs) == 1
    assert downs[0].reason == "idle_to_zero"
    assert downs[0].from_size == 3 and downs[0].to_size == 0
    assert len(downs[0].evidence["replica"]) == 3


def test_failed_spawn_paced_by_cooldown_not_poll(model):
    """A persistently failing spawn must not re-fork on every poll:
    the wake branch bypasses the cooldown for OUTAGES, but a failed
    attempt arms the spawn-fail clock so retries come at cooldown
    cadence."""
    rng = np.random.default_rng(7)
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_scaler(model, clk, reg,
                                scale_to_zero=True, idle_to_zero_s=5.0)
    for _ in range(10):
        clk.t += 1.0
        router.step()
        scaler.poll()
    assert router.fleet_size == 0
    attempts = []

    def boom(**kw):
        attempts.append(clk.t)
        raise RuntimeError("fork: resource temporarily unavailable")

    router.add_replica = boom
    router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
    for _ in range(20):
        clk.t += 0.5
        scaler.poll()
    # 10s of polling at 0.5s cadence with cooldown_s=4.0: the first
    # attempt is immediate, then one per cooldown window — not 20
    assert 2 <= len(attempts) <= 1 + int(10.0 / scaler.cooldown_s)
    assert all(b - a >= scaler.cooldown_s
               for a, b in zip(attempts, attempts[1:]))


def test_slot_occupancy_gauge_zeroed_at_fleet_zero(model):
    """The gauge must read 0.0 on a scaled-to-zero fleet, not freeze
    at its last pre-retirement value."""
    rng = np.random.default_rng(9)
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_scaler(model, clk, reg,
                                scale_to_zero=True, idle_to_zero_s=5.0)
    rid = router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
    done = scaler.drain()
    assert [f.req_id for f in done] == [rid]
    for _ in range(10):
        clk.t += 1.0
        router.step()
        scaler.poll()
    assert router.fleet_size == 0
    assert reg.snapshot()["gauges"]["slot_occupancy"] == 0.0


def test_scale_to_zero_wakes_on_deadline_sheds(model):
    """An all-deadline class never QUEUES at fleet zero — every submit
    is shed at the door (projected wait is infinite) — so the shed
    counter movement must arm the wake, or the outage is permanent."""
    rng = np.random.default_rng(5)
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_scaler(model, clk, reg,
                                scale_to_zero=True, idle_to_zero_s=5.0)
    for _ in range(20):
        clk.t += 1.0
        router.step()
        scaler.poll()
    assert router.fleet_size == 0
    rid = router.submit(_prompt(rng), max_new_tokens=MAX_NEW,
                        deadline_ms=500.0)
    assert router.queue_depth == 0  # refused at the door, not queued
    clk.t += 0.1
    d = scaler.poll()
    assert d is not None and d.action == "wake" and d.to_size == 1
    # the shed request itself was already refused; the NEXT one lands
    fins = router.drain()
    assert [f.req_id for f in fins] == [rid]
    assert fins[0].finish_reason == "shed"
    rid2 = router.submit(_prompt(rng), max_new_tokens=MAX_NEW,
                         deadline_ms=5000.0)
    done = scaler.drain()
    assert [f.req_id for f in done] == [rid2]
    assert done[0].finish_reason == "length"


def test_replace_dead_restores_floor(model):
    """Without a respawn supervisor (inproc fleets), the autoscaler
    itself restores the min-replica floor after a death — the
    kill-injected path of the triple-audit test below."""
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_scaler(model, clk, reg, n_replicas=2,
                                min_replicas=2)
    clk.t += 1.0
    scaler.poll()
    router.kill_replica(1)
    assert router.fleet_size == 1
    clk.t += 0.1
    d = scaler.poll()
    assert d is not None and d.action == "replace_dead"
    assert router.fleet_size == 2


# ---------------------------------------------------------------------------
# the triple-audit acceptance pin
# ---------------------------------------------------------------------------


def test_every_scale_decision_is_counter_trace_and_report_row(model):
    """ISSUE 12 acceptance: from ONE kill-injected autoscale run,
    every scale decision is simultaneously (a) a counter bump, (b) a
    trace event with evidence attrs, (c) a row in
    tools/fleet_report.py's output."""
    import sys

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0] + "/tools")
    from fleet_report import format_fleet_report, summarize_fleet

    rng = np.random.default_rng(4)
    clk = _Clock()
    reg = MetricsRegistry()
    tr = Tracer(registry=reg, clock=clk)
    router, scaler = _mk_scaler(model, clk, reg, tracer=tr,
                                n_replicas=2, min_replicas=2,
                                max_replicas=3)
    # real load so the kills have work to fail over
    for _ in range(3):
        router.submit(_prompt(rng), max_new_tokens=MAX_NEW)
    router.step()
    # 1) sustained SLO burn -> scale up (2 -> 3, the max)
    for _ in range(8):
        clk.t += 1.0
        scaler.observe([_fin(500.0)])
        scaler.poll()
    assert router.fleet_size == 3
    # 2) kill-injected: two replicas die under load -> the fleet falls
    # below its floor and the autoscaler replaces a dead one
    router.kill_replica(0)
    router.kill_replica(1)
    clk.t += 0.1
    scaler.poll()
    router.drain()
    decisions = scaler.decisions
    assert len(decisions) >= 2
    assert any(d.action == "replace_dead" for d in decisions)
    counters = reg.snapshot()["counters"]
    # (a) every decision is a counter bump
    ups = sum(1 for d in decisions if d.to_size > d.from_size)
    downs = len(decisions) - ups
    assert counters.get("scale_up", 0) == ups
    assert counters.get("scale_down", 0) == downs
    # (b) every decision is a trace event with evidence attrs
    evs = [e for e in tr.events() if e["ev"] == "scale"]
    assert len(evs) == len(decisions)
    for e, d in zip(evs, decisions):
        assert e["action"] == d.action and e["reason"] == d.reason
        assert e["from_size"] == d.from_size
        assert e["to_size"] == d.to_size
        assert "busy_frac" in e and "window_s" in e
    # (c) every decision is a row in fleet_report (round-tripped
    # through the JSONL record form trace files carry)
    from avenir_tpu.obs.trace import event_record, record_event

    records = [record_event(json.loads(json.dumps(event_record(e))))
               for e in tr.events()]
    s = summarize_fleet(records, {"kind": "run_end",
                                  "counters": counters})
    assert s["n_decisions"] == len(decisions)
    report = format_fleet_report(s)
    for d in decisions:
        assert f"reason={d.reason}" in report
    assert f"decisions: {len(decisions)}" in report


# ---------------------------------------------------------------------------
# compile pre-warm
# ---------------------------------------------------------------------------


def test_prewarm_compiles_every_bucket_without_metric_noise(model):
    reg = MetricsRegistry()
    eng = Engine(model, n_slots=2, max_seq_len=16, registry=reg)
    ticks = eng.prewarm()
    # one prefill compile per ladder bucket (16 -> [8, 16]) + THE one
    # decode-step compile
    assert len(eng.traces["prefill"]) == 2
    assert len(eng.traces["step"]) == 1
    assert ticks >= 2
    snap = reg.snapshot()
    assert snap["counters"]["prewarm_ticks"] == ticks
    # muted: no serving metric moved, no request records
    assert "serve_requests" not in snap["counters"]
    assert "tokens_out" not in snap["counters"]
    assert snap["hists"] == {} or snap["hists"].get(
        "ttft_ms", {"count": 0})["count"] == 0
    # a real request in a warmed bucket adds NO compile — the pre-warm
    # pin: its first dispatch cannot hit a compile-sized outlier
    eng.submit([1, 2, 3], max_new_tokens=2)
    eng.drain()
    assert len(eng.traces["prefill"]) == 2
    assert len(eng.traces["step"]) == 1
    assert reg.snapshot()["counters"]["serve_requests"] == 1


def test_prewarm_restores_request_id_stream(model):
    """Default rngs are fold_in(seed, rid): a prewarmed engine's first
    real request must see the same rid (hence rng stream) as a cold
    engine's — prewarm leaves no trace in the serving story."""
    cold = Engine(model, n_slots=1, max_seq_len=16,
                  registry=MetricsRegistry(), seed=7)
    warm = Engine(model, n_slots=1, max_seq_len=16,
                  registry=MetricsRegistry(), seed=7)
    warm.prewarm()
    rc = cold.submit([1, 2, 3], max_new_tokens=MAX_NEW)
    rw = warm.submit([1, 2, 3], max_new_tokens=MAX_NEW)
    assert rc == rw == 0
    fc = cold.drain()[0]
    fw = warm.drain()[0]
    assert fc.tokens == fw.tokens


@pytest.mark.slow
def test_prewarmed_first_request_has_no_compile_sized_ttft(model):
    """The acceptance pin, timing form: a freshly spawned replica's
    first dispatched request shows no compile-sized TTFT outlier
    compared against the un-warmed path (compile is 10-100x a tick on
    this container; factor 2 absorbs CI noise)."""
    import time as _time

    def first_ttft(prewarm):
        eng = Engine(GPT(GPT_TINY, rngs=nnx.Rngs(0)), n_slots=2,
                     max_seq_len=16, registry=MetricsRegistry())
        if prewarm:
            eng.prewarm()
        t0 = _time.perf_counter()
        eng.submit([1, 2, 3], max_new_tokens=1)
        done = eng.drain()
        assert done[0].ttft_ms is not None
        del t0
        return done[0].ttft_ms

    cold = first_ttft(False)
    warm = first_ttft(True)
    assert warm < cold / 2, (
        f"prewarmed first-request TTFT {warm:.1f} ms is not clearly "
        f"under the cold path's compile-sized {cold:.1f} ms")


@pytest.mark.slow
def test_prewarm_paged_chunk_ladder(model):
    eng = Engine(model, n_slots=2, max_seq_len=32, kv_impl="paged",
                 page_size=8, prefill_chunk=16,
                 registry=MetricsRegistry())
    eng.prewarm()
    # chunk ladder for prefill_chunk=16 is [8, 16]
    assert len(eng.traces["prefill"]) == 2
    assert len(eng.traces["step"]) == 1
    # a long prompt (two chunks of warmed sizes) adds no compile
    eng.submit(list(range(1, 25)), max_new_tokens=2)
    eng.drain()
    assert len(eng.traces["prefill"]) == 2


# ---------------------------------------------------------------------------
# seeded load shapes (serve_bench satellite)
# ---------------------------------------------------------------------------


def test_load_shapes_seeded_and_shaped():
    import sys

    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0] + "/tools")
    from serve_bench import gen_arrivals

    for shape in ("poisson", "bursty", "diurnal"):
        a1, cfg1 = gen_arrivals(shape, np.random.default_rng(5), 200,
                                20.0)
        a2, cfg2 = gen_arrivals(shape, np.random.default_rng(5), 200,
                                20.0)
        assert np.array_equal(a1, a2), f"{shape} not seed-deterministic"
        assert cfg1 == cfg2 and cfg1["load_shape"] == shape
        assert len(a1) == 200 and np.all(np.diff(a1) > 0)
    # bursty: the burst windows are visibly denser than the floor
    a, cfg = gen_arrivals("bursty", np.random.default_rng(6), 400,
                          20.0, burst_mult=8.0, quiet_frac=0.1,
                          burst_period_s=4.0, burst_duty=0.25)
    frac_in_burst = np.mean((a % 4.0) < 1.0)
    assert frac_in_burst > 0.7  # bursts carry most arrivals
    # diurnal: peak-half arrivals dominate trough-half
    a, cfg = gen_arrivals("diurnal", np.random.default_rng(7), 400,
                          20.0, period_s=10.0, amp=0.8)
    phase = (a % 10.0) / 10.0
    peak = np.sum((phase > 0.0) & (phase < 0.5))   # sin > 0
    trough = np.sum(phase >= 0.5)
    assert peak > 2 * trough


# ---------------------------------------------------------------------------
# obs_report fleet line (satellite)
# ---------------------------------------------------------------------------


def test_obs_report_fleet_line_grows_scale_and_replica_seconds():
    import time as _time

    from avenir_tpu.obs.report import format_report, summarize

    records = [
        {"kind": "run_meta", "t": 1.0, "model_type": "gpt"},
        {"kind": "request", "t": 2.0, "id": 0, "n_prompt": 3,
         "n_out": 4, "finish_reason": "length", "ttft_ms": 1.0,
         "tpot_ms": 0.5},
        {"kind": "run_end", "t": _time.time(),
         "counters": {"scale_up": 3.0, "scale_down": 2.0,
                      "fleet_replica_seconds": 42.5,
                      "prewarm_ticks": 6.0, "tokens_out": 4.0}},
    ]
    rep = format_report(summarize(records))
    assert "scale +3/-2" in rep
    assert "replica-seconds 42.5" in rep
    assert "prewarm ticks 6" in rep
