"""Tier-1 budget guard (ISSUE 3 satellite): the 870s tier-1 window must
survive the growing suite, so every multi-second test case has to carry
the `slow` mark (excluded from tier-1) instead of silently eating the
budget of the files that sort after it.

Mechanics: conftest records the call-phase wall time of every completed
test (TEST_DURATIONS); this file's `zz` name sorts it after every normal
test file but BEFORE the conftest._HEAVY_FILES block (which conftest
pushes to the very end precisely because it is known-heavy and runs in
whatever budget remains), so by the time the guard runs it has seen the
whole broad suite. Any unmarked case over the budget that is not in the
measured seed-era grandfather set fails the guard with its duration —
add `@pytest.mark.slow` to the offender, don't grow the list.
"""

BUDGET_SECS = 5.0

# Pre-existing cases measured >= ~3.5s on the 8-virtual-CPU tier-1
# harness in the per-file duration survey that landed with this guard
# (seed era — everything here predates it; CI load can inflate a wall
# time ~2-3x, hence listing the near-budget ones too). Matched by nodeid
# prefix so parametrized ids stay covered. NEW tests do not belong here:
# mark them `slow` or split them instead.
GRANDFATHERED = (
    "tests/test_dcn_mesh.py::test_dcn_training_trajectory_matches_single_device",
    "tests/test_decode.py::test_batched_rng_rows_match_sequential",
    "tests/test_decode.py::test_decode_single_compile_across_positions",
    "tests/test_decode.py::test_gpt_decode_matches_generate",
    "tests/test_decode.py::test_gpt_scan_decode_matches_generate",
    "tests/test_decode.py::test_llama_gqa_decode_matches_generate",
    "tests/test_decode.py::test_mixtral_decode_matches_generate",
    "tests/test_decode.py::test_prompt_bucket_bounds_compiles",
    "tests/test_decode.py::test_stop_tokens_parity_vs_generate",
    "tests/test_gpt_parity.py::test_export_round_trip",
    "tests/test_gpt_parity.py::test_grad_flow_through_tied_embedding",
    "tests/test_gpt_parity.py::test_inference_path_last_position_only",
    "tests/test_gpt_parity.py::test_logits_and_loss_parity",
    "tests/test_graft_entry.py::test_dryrun_multichip_8",
    "tests/test_graft_entry.py::test_entry_is_jittable_tiny",
    "tests/test_hardening.py::test_async_checkpoint_resumable",
    "tests/test_hardening.py::test_checkify_train_step_clean",
    "tests/test_hardening.py::test_loop_raises_on_nonfinite_loss",
    "tests/test_hardening.py::test_profile_trace_stopped_on_early_exit",
    "tests/test_hardening.py::test_profile_trace_window",
    "tests/test_hardening.py::test_sigterm_graceful_save_and_resume",
    "tests/test_hf_export.py::test_gpt_roundtrip_through_importer",
    "tests/test_hf_export.py::test_gpt_transformers_from_pretrained",
    "tests/test_hf_export.py::test_llama_roundtrip_both_consumers",
    "tests/test_hf_export.py::test_mixtral_roundtrip_both_consumers",
    "tests/test_hf_import.py::test_finetune_init_from_gpt2_offline",
    "tests/test_hf_import.py::test_gpt2_from_hf_reaches_weight_load_or_skips",
    "tests/test_hf_import.py::test_hf_import_logits_match_torch",
    "tests/test_hf_import.py::test_llama_from_hf_dir_logits_parity",
    "tests/test_hf_import.py::test_mixtral_from_hf_dir_logits_parity",
    "tests/test_hf_import.py::test_train_loop_gpt2_init_crops_block_size",
    "tests/test_hf_import.py::test_train_loop_init_from_gpt2",
    "tests/test_llama.py::test_llama_trains_end_to_end",
    "tests/test_llama.py::test_logits_parity_with_hf_llama",
    "tests/test_mixtral.py::test_ep_hlo_contains_all_to_all",
    "tests/test_mixtral.py::test_ep_trajectory_matches_and_hlo_has_all_to_all",
    "tests/test_mixtral.py::test_expert_opt_state_sharded",
    "tests/test_mixtral.py::test_logits_parity_no_drop",
    "tests/test_mixtral.py::test_mixtral_trains_and_resumes",
    "tests/test_obs.py::test_metrics_log_off_writes_nothing",
    "tests/test_obs.py::test_run_training_writes_metrics_jsonl",
    "tests/test_pallas_kernels.py::test_flash_attention_gqa_unrepeated_kv",
    "tests/test_pallas_kernels.py::test_flash_attention_grads",
    "tests/test_pallas_kernels.py::test_rmsnorm_forward_and_grads",
    "tests/test_ring_attention.py::test_ring_matches_dense",
    "tests/test_ring_attention.py::test_ring_trajectory_matches_single_device",
    "tests/test_sampling_cli.py::",
    "tests/test_scan_layers.py::test_gpt_scan_logits_match_loop",
    "tests/test_scan_layers.py::test_gpt_scan_remat_matches",
    "tests/test_scan_layers.py::test_gpt_scan_training_trajectory_matches_loop",
    "tests/test_scan_layers.py::test_llama_family_scan_matches_loop",
    "tests/test_scan_layers.py::test_remat_policy_dots_matches_nothing",
    "tests/test_scan_layers.py::test_scan_checkpoint_roundtrip",
    "tests/test_serve.py::test_engine_parity_families",
    "tests/test_sharded_ckpt.py::test_lazy_load_roundtrip_matches_eager",
    "tests/test_sharded_ckpt.py::test_sharded_async_save_load_roundtrip",
    "tests/test_sharded_ckpt.py::test_streamed_pt_matches_eager_pt_and_torch_reads_it",
    "tests/test_sharded_ckpt.py::test_streaming_restore_peak_memory",
    "tests/test_sharded_ckpt.py::test_streaming_save_peak_memory",
    "tests/test_torch_model.py::test_optimizer_decay_split",
    "tests/test_train_tpu.py::test_fsdp_hlo_contains_collectives",
    "tests/test_train_tpu.py::test_multi_step_dispatch_matches_single_steps",
    "tests/test_train_tpu.py::test_optimizer_matches_torch_adamw",
    "tests/test_train_tpu.py::test_resume_restores_schedule_count",
    "tests/test_train_tpu.py::test_single_device_training_reduces_loss",
    "tests/test_train_tpu.py::test_spmd_trajectory_matches_single_device",
    "tests/test_train_tpu.py::test_windowed_loop_matches_single_dispatch",
    "tests/test_ulysses.py::test_ulysses_trajectory_matches_single_device",
)


def test_every_slow_case_is_marked():
    import statistics

    from conftest import _HEAVY_FILES, TEST_DURATIONS

    if not TEST_DURATIONS:
        return  # single-file run of just this guard: nothing to check
    # CI-load tolerance, same shape as the stall watchdog's threshold
    # rule: a loaded harness slows EVERY test, so the budget floats with
    # the run's median before anything is flagged
    median = statistics.median(d for d, _ in TEST_DURATIONS.values())
    budget = max(BUDGET_SECS, 3.0 * median)
    offenders = []
    for nodeid, (dur, is_slow) in sorted(TEST_DURATIONS.items()):
        if is_slow or dur <= budget:
            continue
        fname = nodeid.split("::")[0].rsplit("/", 1)[-1]
        if fname in _HEAVY_FILES:
            continue  # documented end-of-run heavy block (conftest)
        # nodeids are rootdir-relative; normalize a tests/-cwd run so the
        # grandfather prefixes match either way
        nid = nodeid if nodeid.startswith("tests/") else f"tests/{nodeid}"
        if any(nid.startswith(g) for g in GRANDFATHERED):
            continue
        offenders.append(f"  {dur:6.1f}s  {nodeid}")
    assert not offenders, (
        f"unmarked tests over the {budget:.1f}s tier-1 slow budget — mark "
        "them @pytest.mark.slow (or split them) so the 870s window keeps "
        "covering the whole suite:\n" + "\n".join(offenders)
    )
