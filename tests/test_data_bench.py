"""tools/data_bench.py --smoke rides tier-1 (ISSUE 19 satellite): both
bench arms — the seed loader's per-slice staging and the streaming
loader's fused sharded gather + deep prefetch — must run end to end on
every commit, and the committed full artifact must stay in sync with
the PERF_LEDGER row the perf gate bands."""

import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_smoke_runs_green(tmp_path, capsys):
    from tools.data_bench import main

    out = tmp_path / "bench.json"
    assert main(["--smoke", f"--out={out}"]) == 0
    capsys.readouterr()
    rep = json.loads(out.read_text())
    assert rep["smoke"] is True
    assert rep["ok"] is True
    (seed,) = rep["seeds"]
    assert seed["staged_tok_per_s"]["seed_loader"] > 0
    assert seed["staged_tok_per_s"]["streaming"] > 0
    assert 0 <= seed["stall_frac"]["streaming"] <= 1
    assert "staged_tok_per_s_ratio" in rep["headline"]


def test_committed_artifact_carries_the_claims():
    """BENCH_data.json is the PR's evidence: the acceptance headline and
    the mixed-corpus kill-resume verdict must both be present and green
    in the committed artifact (the ledger row pins the exact value)."""
    with open(os.path.join(REPO, "BENCH_data.json")) as f:
        art = json.load(f)
    assert art["smoke"] is False
    assert art["ok"] is True
    assert art["headline"]["meets_acceptance"] is True
    assert art["resume"]["bit_identical"] is True
    assert art["resume"]["kills"] >= 1
    assert len(art["config"]["seeds"]) == 3
