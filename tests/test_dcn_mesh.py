"""Multi-slice (ICI×DCN) mesh layout (SURVEY.md §5 "Distributed
communication backend": expose DCN as an outer mesh axis; VERDICT r2
missing #6). Runs on the 8-fake-CPU-device harness: two emulated slices of
4 devices each."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from avenir_tpu.parallel.mesh import AXES, make_mesh


def test_dcn_outer_device_order():
    """data axis = dcn:2 (outer) × ici:2 (inner): mesh shape data:4, and
    the slice-major convention puts each slice's devices in contiguous
    inner runs — collective groups within a slice stay ICI-contiguous."""
    mesh = make_mesh("data:2,fsdp:2", dcn_spec="data:2")
    assert dict(mesh.shape)["data"] == 4 and dict(mesh.shape)["fsdp"] == 2
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    data_axis = AXES.index("data")
    flat = np.moveaxis(ids, data_axis, 0).reshape(4, -1)
    # rows 0-1 (dcn index 0) must be slice 0's devices {0..3}, rows 2-3
    # slice 1's {4..7}
    assert set(flat[:2].ravel()) == {0, 1, 2, 3}, flat
    assert set(flat[2:].ravel()) == {4, 5, 6, 7}, flat


def test_dcn_mesh_collective_pattern():
    """A gradient psum over the combined data axis on the hybrid mesh must
    lower to an all-reduce whose replica groups span all 8 devices (the
    cross-slice phase exists), and sharded compute must produce the same
    result as unsharded."""
    mesh = make_mesh("data:4", dcn_spec="data:2")
    assert dict(mesh.shape)["data"] == 8
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))

    @jax.jit
    def f(a):
        return a.sum()  # cross-device reduction over the sharded axis

    hlo = f.lower(xs).compile().as_text()
    assert "all-reduce" in hlo
    assert float(f(xs)) == float(x.sum())


def test_dcn_training_trajectory_matches_single_device(char_dataset,
                                                       tmp_path):
    """A 2-slice × 4-device data-parallel run is still pure layout: loss
    trajectory equals the single-device run."""
    from tests.test_train_tpu import make_cfg
    from avenir_tpu.train.loop import run_training

    cfg1 = make_cfg(char_dataset["dir"], tmp_path / "o1", max_iters=5,
                    gradient_accumulation_steps=8, mesh_shape="data:1")
    ref = run_training(cfg1)
    cfg2 = make_cfg(char_dataset["dir"], tmp_path / "o2", max_iters=5,
                    gradient_accumulation_steps=8, mesh_shape="data:4",
                    dcn_mesh_shape="data:2")
    got = run_training(cfg2)
    ref_l = np.array([l for _, l in ref["loss_history"]])
    got_l = np.array([l for _, l in got["loss_history"]])
    np.testing.assert_allclose(got_l, ref_l, atol=2e-4, rtol=2e-4)


def test_dcn_spec_validation():
    with pytest.raises(ValueError, match="unknown mesh axis"):
        make_mesh("data:2", dcn_spec="bogus:2")
