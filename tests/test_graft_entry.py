"""Driver-contract tests: entry() must jit-compile, dryrun_multichip must
partition the full train step over an 8-device mesh (runs on the
conftest-provided 8 fake CPU devices)."""

import jax
import numpy as np


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_entry_is_jittable_tiny():
    """entry() builds GPT-2 124M (slow on CPU) — exercise the same code
    path at tiny scale via the shared helper instead."""
    import __graft_entry__ as ge

    from avenir_tpu.parallel.mesh import make_mesh

    mesh = make_mesh("data:1")
    step, (params, opt_state, rng, x, y) = ge._tiny_train_setup(mesh)
    params, opt_state, metrics = step(params, opt_state, rng, x, y)
    assert np.isfinite(float(metrics["loss"]))
