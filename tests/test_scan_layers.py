"""scan_layers: lax.scan over stacked homogeneous blocks (SURVEY.md §3.3
"nnx.scan over the L blocks"; the three deep ladder configs set it True).

Covers: trajectory equivalence scan vs python-loop (same weights via the
checkpoint bridge — which doubles as a bridge test for the stacked layout),
partition-rule coverage with the leading layer axis, and a full .pt
checkpoint round trip scanned-save → unscanned-restore.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.checkpoint.bridge import (
    export_torch_state_dict,
    load_torch_state_dict,
    restack_scanned_paths,
    unstack_scanned_paths,
)
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.train.optimizer import make_optimizer
from avenir_tpu.train.step import jit_train_step, make_step_fns

TINY = GPTConfig(block_size=16, vocab_size=64, n_layer=3, n_head=2,
                 n_embd=32, dropout=0.0, bias=True, attn_impl="xla")


def _copy_weights(src_model, dst_model, family="gpt", tied=True):
    sd = export_torch_state_dict(src_model, model_family=family,
                                 tied_lm_head=tied)
    load_torch_state_dict(dst_model, sd, tied_lm_head=tied)


def test_unstack_restack_roundtrip():
    flat = {
        ("h_scan", "attn", "kernel"): np.arange(24.0).reshape(3, 2, 4),
        ("ln_f", "scale"): np.ones(4),
    }
    un = unstack_scanned_paths(flat)
    assert ("h", 0, "attn", "kernel") in un and ("h", 2, "attn", "kernel") in un
    assert un[("ln_f", "scale")].shape == (4,)
    re = restack_scanned_paths(un, flat.keys())
    np.testing.assert_array_equal(re[("h_scan", "attn", "kernel")],
                                  flat[("h_scan", "attn", "kernel")])


def test_gpt_scan_logits_match_loop():
    loop_model = GPT(TINY, rngs=nnx.Rngs(0))
    scan_model = GPT(dataclasses.replace(TINY, scan_layers=True),
                     rngs=nnx.Rngs(1))
    _copy_weights(loop_model, scan_model)
    idx = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)))
    tgt = jnp.asarray(np.random.default_rng(1).integers(0, 64, (2, 16)))
    logits_a, loss_a = loop_model(idx, tgt)
    logits_b, loss_b = scan_model(idx, tgt)
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b),
                               atol=1e-5)
    np.testing.assert_allclose(float(loss_a), float(loss_b), atol=1e-6)


def test_gpt_scan_remat_matches():
    scan_model = GPT(dataclasses.replace(TINY, scan_layers=True),
                     rngs=nnx.Rngs(0))
    remat_model = GPT(dataclasses.replace(TINY, scan_layers=True, remat=True),
                      rngs=nnx.Rngs(0))
    idx = jnp.asarray(np.random.default_rng(2).integers(0, 64, (2, 16)))
    tgt = jnp.asarray(np.random.default_rng(3).integers(0, 64, (2, 16)))

    def loss_of(model):
        graphdef, params = nnx.split(model, nnx.Param)

        def f(p):
            m = nnx.merge(graphdef, p)
            return m(idx, tgt)[1]

        loss, grads = jax.value_and_grad(f)(params)
        return loss, grads

    la, ga = loss_of(scan_model)
    lb, gb = loss_of(remat_model)
    np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_gpt_scan_training_trajectory_matches_loop():
    rng = np.random.default_rng(0)
    batches = [
        (jnp.asarray(rng.integers(0, 64, (1, 2, 16)).astype(np.int32)),
         jnp.asarray(rng.integers(0, 64, (1, 2, 16)).astype(np.int32)))
        for _ in range(4)
    ]

    def train(scan):
        cfg = dataclasses.replace(TINY, scan_layers=scan)
        model = GPT(cfg, rngs=nnx.Rngs(0))
        if scan:
            ref = GPT(TINY, rngs=nnx.Rngs(0))
            _copy_weights(ref, model)
        graphdef, params = nnx.split(model, nnx.Param)
        tx, _ = make_optimizer(params, learning_rate=1e-3, weight_decay=0.1,
                               beta1=0.9, beta2=0.95, grad_clip=1.0,
                               warmup_iters=0, lr_decay_iters=100,
                               min_lr=1e-4)
        opt_state = tx.init(params)
        step_fn, _ = make_step_fns(graphdef, dropout=0.0)
        step = jit_train_step(step_fn, tx)
        key = jax.random.key(0)
        losses = []
        for x, y in batches:
            params, opt_state, m = step(params, opt_state, key, x, y)
            losses.append(float(m["loss"]))
        return losses

    np.testing.assert_allclose(train(False), train(True), rtol=2e-5)


def test_scan_partition_rules_have_leading_layer_axis():
    from avenir_tpu.parallel.partition import (
        match_partition_rules, rules_for_model,
    )

    cfg = dataclasses.replace(TINY, scan_layers=True)
    model = nnx.eval_shape(lambda: GPT(cfg, rngs=nnx.Rngs(0)))
    paths = [p for p, _ in nnx.state(model, nnx.Param).flat_state()]
    specs = match_partition_rules(rules_for_model("gpt"), paths)
    scanned = [p for p in paths if any(str(s).endswith("_scan") for s in p)]
    assert scanned, "scan model should have h_scan params"
    for p in scanned:
        spec = tuple(specs[p])
        # the layer axis shards over 'pipe' (pipeline parallelism, r4);
        # on meshes without a pipe axis the size-1 entry is inert
        assert spec[0] == "pipe", (p, spec)
        # the underlying rule still applies to the trailing dims
    # kernel under scan is (L, in, out): spec dim0 is the layer axis
    k = next(p for p in scanned if p[-1] == "kernel" and "c_attn" in p)
    flat = dict(nnx.state(model, nnx.Param).flat_state())
    assert len(flat[k].get_value().shape) == 3


def test_scan_checkpoint_roundtrip(tmp_path):
    """Save a scanned model's full training state as ckpt.pt, restore into
    an UNSCANNED model: params and adam moments must match layer-for-layer."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from avenir_tpu.checkpoint.io import (
        load_checkpoint, restore_opt_state, restore_params, save_checkpoint,
    )

    cfg = dataclasses.replace(TINY, scan_layers=True)
    model = GPT(cfg, rngs=nnx.Rngs(0))
    graphdef, params = nnx.split(model, nnx.Param)
    tx, _ = make_optimizer(params, learning_rate=1e-3, weight_decay=0.1,
                           beta1=0.9, beta2=0.95, grad_clip=1.0,
                           warmup_iters=0, lr_decay_iters=100, min_lr=1e-4)
    opt_state = tx.init(params)
    step_fn, _ = make_step_fns(graphdef, dropout=0.0)
    step = jit_train_step(step_fn, tx)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 64, (1, 2, 16)).astype(np.int32))
    y = jnp.asarray(rng.integers(0, 64, (1, 2, 16)).astype(np.int32))
    params, opt_state, _ = step(params, opt_state, jax.random.key(0), x, y)

    model_args = dict(n_layer=3, n_head=2, n_embd=32, block_size=16,
                      bias=True, vocab_size=64, dropout=0.0)
    save_checkpoint(str(tmp_path), params=params, opt_state=opt_state,
                    hyper={"lr": 1e-3, "betas": (0.9, 0.95), "eps": 1e-8,
                           "weight_decay": 0.1},
                    model_args=model_args, iter_num=1, best_val_loss=9.9,
                    config={}, model_family="gpt")

    # restore into the unscanned layout
    ckpt = load_checkpoint(str(tmp_path))
    loop_model = nnx.eval_shape(lambda: GPT(TINY, rngs=nnx.Rngs(0)))
    _, abs_state = nnx.split(loop_model, nnx.Param)
    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))
    shardings = {p: NamedSharding(mesh, P())
                 for p, _ in abs_state.flat_state()}
    restored = restore_params(ckpt, abs_state, shardings)

    scan_flat = unstack_scanned_paths(
        {p: np.asarray(v.get_value()) for p, v in params.flat_state()}
    )
    for p, v in restored.flat_state():
        np.testing.assert_allclose(np.asarray(v.get_value()), scan_flat[p],
                                   atol=1e-7, err_msg=str(p))

    # moments restore through the torch param-index schema
    tx2, _ = make_optimizer(restored, learning_rate=1e-3, weight_decay=0.1,
                            beta1=0.9, beta2=0.95, grad_clip=1.0,
                            warmup_iters=0, lr_decay_iters=100, min_lr=1e-4)
    opt2 = tx2.init(restored)
    opt2 = restore_opt_state(ckpt, opt2, restored, shardings)
    from avenir_tpu.checkpoint.io import _find_adam_state

    mu_scan = unstack_scanned_paths(
        {p: np.asarray(v.get_value())
         for p, v in _find_adam_state(opt_state).mu.flat_state()}
    )
    for p, v in _find_adam_state(opt2).mu.flat_state():
        np.testing.assert_allclose(np.asarray(v.get_value()), mu_scan[p],
                                   atol=1e-7, err_msg=str(p))


@pytest.mark.parametrize("family", ["llama", "mixtral"])
def test_llama_family_scan_matches_loop(family):
    from avenir_tpu.models.llama import Llama, LlamaConfig
    from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

    kw = dict(block_size=16, vocab_size=64, n_layer=2, n_head=2, n_kv_head=1,
              n_embd=32, ffn_hidden=64, dropout=0.0, attn_impl="xla")
    if family == "llama":
        cfg, ctor = LlamaConfig(**kw), Llama
    else:
        cfg = MixtralConfig(**kw, n_experts=4, n_experts_per_tok=2)
        ctor = Mixtral
    loop_model = ctor(cfg, rngs=nnx.Rngs(0))
    scan_model = ctor(dataclasses.replace(cfg, scan_layers=True),
                      rngs=nnx.Rngs(1))
    _copy_weights(loop_model, scan_model, family="llama", tied=False)
    idx = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)))
    tgt = jnp.asarray(np.random.default_rng(1).integers(0, 64, (2, 16)))
    la, lossa = loop_model(idx, tgt)
    lb, lossb = scan_model(idx, tgt)
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb), atol=2e-5)
    np.testing.assert_allclose(float(lossa), float(lossb), atol=1e-6)


@pytest.mark.parametrize("scan", [False, True])
def test_remat_policy_dots_matches_nothing(char_dataset, tmp_path, scan):
    """remat_policy only changes WHAT the backward recomputes, never the
    math: loss trajectories are identical across policies."""
    from tests.test_train_tpu import make_cfg

    from avenir_tpu.train.loop import run_training

    common = dict(max_iters=4, remat=True, eval_interval=50,
                  mesh_shape="data:1", scan_layers=scan)
    ref = run_training(make_cfg(char_dataset["dir"], tmp_path / "o1",
                                remat_policy="nothing", **common))
    got = run_training(make_cfg(char_dataset["dir"], tmp_path / "o2",
                                remat_policy="dots", **common))
    # not bit-equal: saved-vs-recomputed values land in different XLA
    # fusions whose accumulation order differs in the last ulp
    np.testing.assert_allclose(
        [l for _, l in ref["loss_history"]],
        [l for _, l in got["loss_history"]], rtol=1e-5, atol=1e-5,
    )
