"""Fleet health engine (ISSUE 14): streaming sketches, windowed series,
the detector table, detection-before-the-stall-tier pins, the zero-
anomaly steady pin, disabled-path overhead micro-pins, and the shared
stall-threshold rule. All tier-1 CPU except the timing-sensitive
wall-clock cases (slow lane)."""

import glob
import json
import time

import numpy as np
import pytest
from flax import nnx

from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry, Tracer
from avenir_tpu.obs.anomaly import (
    DETECTOR_SCHEMA,
    AnomalyEngine,
    Detector,
    default_detectors,
    ls_slope,
    robust_z,
)
from avenir_tpu.obs.series import (
    QuantileSketch,
    Series,
    SeriesStore,
    percentile,
    stall_threshold_secs,
)
from avenir_tpu.utils.faults import FaultInjector, set_injector


# ---------------------------------------------------------------------------
# QuantileSketch: error bound, merge, wire deltas
# ---------------------------------------------------------------------------


def test_sketch_vs_exact_within_relative_error_bound():
    """The ISSUE 14 agreement pin: sketch quantiles agree with the
    exact nearest-rank rule within the sketch's alpha relative-error
    bound, across distributions a latency series actually produces."""
    rng = np.random.default_rng(0)
    for xs in (rng.lognormal(3.0, 1.0, 5000),
               rng.uniform(0.5, 500.0, 5000),
               rng.exponential(20.0, 5000) + 1.0):
        sk = QuantileSketch(alpha=0.01)
        for x in xs:
            sk.observe(x)
        for q in (0.5, 0.9, 0.95, 0.99):
            exact = percentile(list(xs), q)
            est = sk.quantile(q)
            assert abs(est - exact) / exact <= sk.alpha + 1e-9, (
                f"q={q}: sketch {est} vs exact {exact}")


def test_sketch_handles_zero_and_tracks_extremes():
    sk = QuantileSketch()
    for v in (0.0, 0.0, 5.0, 10.0):
        sk.observe(v)
    assert sk.quantile(0.25) == 0.0
    assert sk.min == 0.0 and sk.max == 10.0 and sk.count == 4
    assert sk.quantile(1.0) == pytest.approx(10.0, rel=0.02)


def test_sketch_merge_equals_direct_build():
    rng = np.random.default_rng(1)
    xs = rng.lognormal(2.0, 0.7, 4000)
    a, b, direct = QuantileSketch(), QuantileSketch(), QuantileSketch()
    for x in xs[:2000]:
        a.observe(x)
        direct.observe(x)
    for x in xs[2000:]:
        b.observe(x)
        direct.observe(x)
    a.merge(b)
    assert a.bins == direct.bins
    assert a.count == direct.count and a.zero == direct.zero
    assert a.min == direct.min and a.max == direct.max


def test_sketch_delta_shipping_merges_exactly():
    """The process-worker wire form: periodic take_delta() payloads
    merged parent-side rebuild EXACTLY the sketch a single stream
    builds — the counter-delta mirroring contract, for quantiles."""
    rng = np.random.default_rng(2)
    xs = rng.exponential(10.0, 3000)
    worker, parent, direct = (QuantileSketch(), QuantileSketch(),
                              QuantileSketch())
    for i, x in enumerate(xs):
        worker.observe(x)
        direct.observe(x)
        if i % 113 == 0:
            d = worker.take_delta()
            if d:
                parent.merge_dict(d)
    d = worker.take_delta()
    if d:
        parent.merge_dict(d)
    assert parent.bins == direct.bins
    assert parent.count == direct.count
    for q in (0.5, 0.99):
        assert parent.quantile(q) == direct.quantile(q)


def test_sketch_fixed_memory_collapses_low_buckets():
    """Beyond max_bins the LOW buckets fold together: memory stays
    fixed and the operator-facing tail quantiles keep their error
    bound — only the low end degrades."""
    sk = QuantileSketch(alpha=0.01, max_bins=128)
    rng = np.random.default_rng(3)
    xs = rng.lognormal(0.0, 1.0, 20000)
    for x in xs:
        sk.observe(x)
    assert len(sk.bins) <= 128
    exact = percentile(list(xs), 0.99)
    assert abs(sk.quantile(0.99) - exact) / exact <= sk.alpha + 1e-9


def test_sketch_round_trips_via_dict():
    sk = QuantileSketch()
    for v in (1.0, 2.0, 3.0, 100.0):
        sk.observe(v)
    back = QuantileSketch.from_dict(json.loads(json.dumps(sk.to_dict())))
    assert back.bins == sk.bins and back.count == sk.count
    assert back.quantile(0.5) == sk.quantile(0.5)


# ---------------------------------------------------------------------------
# Series / SeriesStore
# ---------------------------------------------------------------------------


def test_series_windows_roll_and_bound_memory():
    t = [0.0]
    s = Series("step_time_ms", window_s=1.0, n_windows=4,
               clock=lambda: t[0])
    for i in range(40):
        t[0] = i * 0.5
        s.observe(float(i), t=t[0])
    means = s.window_means()
    assert len(means) <= 5  # 4 ring windows + the open one
    # windows are (start, mean) with rising means for a rising signal
    assert means[-1][1] > means[0][1]
    assert s.count == 40  # the sketch saw everything the ring evicted


def test_series_store_rejects_undeclared_keys():
    st = SeriesStore(clock=lambda: 0.0)
    with pytest.raises(AssertionError):
        st.series("not_a_metric_key")
    st.series("step_time_ms").observe(1.0, t=0.0)  # declared: fine


def test_registry_series_optin_and_snapshot():
    reg = MetricsRegistry()
    s = reg.series("ttft_ms")
    s.observe(10.0, t=0.0)
    s.observe(20.0, t=0.1)
    snap = reg.series_snapshot()
    assert snap["ttft_ms"]["sketch"]["count"] == 2
    with pytest.raises(AssertionError):
        reg.series("nonexistent_key")


# ---------------------------------------------------------------------------
# The shared stall-threshold rule (consolidation satellite)
# ---------------------------------------------------------------------------


def test_watchdog_and_replica_share_the_threshold_rule():
    """max(floor, factor x median) lives in ONE place; both consumers
    resolve through it (the request_met_slo consolidation pattern)."""
    from avenir_tpu.obs.watchdog import StallWatchdog
    from avenir_tpu.serve.replica import ReplicaHealth

    assert stall_threshold_secs(10.0, 0.5) == 10.0
    assert stall_threshold_secs(1.0, 0.5) == 5.0
    assert stall_threshold_secs(1.0, 0.5, factor=3.0) == 1.5

    wd = StallWatchdog(floor_secs=1.0, dump_stacks=False,
                       echo=lambda *a: None)
    try:
        for _ in range(5):
            wd.notify(window_secs=2.0)
        assert wd.threshold_secs() == stall_threshold_secs(1.0, 2.0)
    finally:
        wd.stop()

    class _Rep(ReplicaHealth):
        busy = False

    r = _Rep(0, clock=lambda: 0.0, stall_floor_secs=1.0,
             stall_factor=10.0)
    r._durs = [2.0, 2.0, 2.0]
    assert r.stall_threshold_secs() == stall_threshold_secs(1.0, 2.0)
    # the anomaly tier's heartbeat factor is strictly below the stall
    # tier's — "fires first" is structural, not tuned
    hb = next(d for d in default_detectors()
              if d.name == "heartbeat_creep")
    assert hb.factor < 10.0


# ---------------------------------------------------------------------------
# Detector statistics + table
# ---------------------------------------------------------------------------


def test_robust_z_resists_outliers_and_flat_baselines():
    base = [10.0] * 20
    assert robust_z(base, 10.2) < 1.0   # MAD floor: jitter is not 100σ
    assert robust_z(base, 20.0) > 4.0
    spiky = [10.0] * 19 + [1000.0]      # one outlier cannot drag it
    assert robust_z(spiky, 10.2) < 1.0


def test_ls_slope():
    assert ls_slope([(0, 0.0), (1, 2.0), (2, 4.0)]) == pytest.approx(2.0)
    assert ls_slope([(0, 5.0)]) == 0.0


def _fed_series(values, window_s=1.0):
    s = Series("step_time_ms", window_s=window_s, clock=lambda: 0.0)
    for i, v in enumerate(values):
        s.observe(v, t=float(i) * window_s)
    return s


def test_drift_detector_fires_on_ramp_not_on_steady():
    det = Detector("step_time_drift", z_thresh=4.0, min_rel=0.35,
                   sustain=1, min_windows=8)
    rng = np.random.default_rng(0)
    steady = _fed_series(list(100.0 + rng.normal(0, 2.0, 32)))
    assert det.evaluate(steady) is None
    # rot beginning mid-run must NOT evade by dragging its own
    # baseline (the oldest-half windows stay pre-rot)
    ramp = _fed_series([100.0] * 16
                       + [100.0 + 8.0 * i for i in range(1, 17)])
    hit = det.evaluate(ramp)
    assert hit is not None and hit["z"] >= 4.0 and hit["rel_rise"] > 0.35


def test_trend_detector_needs_floor_and_projected_growth():
    det = Detector("queue_wait_trend", min_rel=1.0, floor=100.0,
                   horizon_s=10.0, sustain=1, min_windows=4)
    # sub-floor sawtooth: quiet
    low = _fed_series([5.0, 40.0, 5.0, 40.0, 5.0, 40.0])
    assert det.evaluate(low) is None
    # a real backlog ramp above the floor: fires
    ramp = _fed_series([50.0 * i for i in range(8)])
    hit = det.evaluate(ramp)
    assert hit is not None and hit["slope_per_s"] > 0


def test_series_snapshot_stays_strict_json_after_idle_gap():
    """A flush opening an empty window followed by an idle gap used to
    ring a count-0 window whose inf/-inf min/max leaked Infinity into
    the run_end JSONL (review finding) — strict parsers reject that."""
    s = Series("step_time_ms", window_s=1.0, clock=lambda: 0.0)
    s.observe(5.0, t=0.0)
    s.flush(2.0)            # closes the busy window, opens an empty one
    s.observe(7.0, t=10.0)  # idle gap: the empty window must NOT ring
    snap = s.snapshot()
    json.dumps(snap, allow_nan=False)  # raises on Infinity/NaN
    assert all(w[1] > 0 for w in snap["windows"])


def test_io_retry_rate_uses_window_sum_not_mean():
    """The rate is the window SUM / window_s: a fast loop filing many
    small per-check deltas must not divide the true rate away (review
    finding: 10 retries/s over 100 checks/window read as 0.1/s)."""
    det = Detector("io_retry_rate", floor=1.0, sustain=1)
    s = Series("io_retries", window_s=1.0, clock=lambda: 0.0)
    # 100 checks over one window: mostly 0-deltas, 10 retries total
    for i in range(100):
        s.observe(1.0 if i % 10 == 0 else 0.0, t=i * 0.01)
    s.flush(1.5)
    hit = det.evaluate(s)
    assert hit is not None and hit["value"] == pytest.approx(10.0)
    # a genuinely quiet window stays quiet
    q = Series("io_retries", window_s=1.0, clock=lambda: 0.0)
    for i in range(100):
        q.observe(0.0, t=i * 0.01)
    q.flush(1.5)
    assert det.evaluate(q) is None


def test_collapse_detector():
    det = Detector("accept_rate_collapse", collapse_frac=0.5, floor=0.1,
                   sustain=1, min_windows=6, recent=2)
    healthy = _fed_series([0.8] * 12)
    assert det.evaluate(healthy) is None
    collapsed = _fed_series([0.8] * 10 + [0.2, 0.2])
    hit = det.evaluate(collapsed)
    assert hit is not None and hit["baseline"] == pytest.approx(0.8)
    # a signal that never established a baseline cannot collapse
    nobase = _fed_series([0.05] * 12)
    assert det.evaluate(nobase) is None


def test_detector_schema_is_the_gate():
    with pytest.raises(AssertionError):
        Detector("made_up_detector")
    assert {d.name for d in default_detectors()} == set(DETECTOR_SCHEMA)


# ---------------------------------------------------------------------------
# AnomalyEngine: the four-way audit emission + cooldown
# ---------------------------------------------------------------------------


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)


def test_anomaly_emission_is_counter_record_event_and_dump(tmp_path):
    t = [0.0]
    reg = MetricsRegistry()
    sink = _ListSink()
    tracer = Tracer(registry=reg, clock=lambda: t[0],
                    out_dir=str(tmp_path))
    ae = AnomalyEngine(registry=reg, sink=sink, tracer=tracer,
                       clock=lambda: t[0], window_s=1.0,
                       detectors=[Detector("step_time_drift",
                                           sustain=1, min_windows=8)])
    for i in range(16):
        t[0] = float(i)
        ae.observe("step_time_ms", 100.0, t=t[0])
        ae.check(t[0])
    assert reg.snapshot()["counters"].get("anomaly", 0) == 0
    for i in range(16, 22):
        t[0] = float(i)
        ae.observe("step_time_ms", 100.0 + 40.0 * (i - 15), t=t[0])
        ae.check(t[0])
    counters = reg.snapshot()["counters"]
    assert counters["anomaly"] == 1
    # the four-way trail: host log + JSONL record + trace event + dump
    assert ae.fired and ae.fired[0]["detector"] == "step_time_drift"
    recs = [r for r in sink.records if r["kind"] == "anomaly"]
    assert len(recs) == 1 and recs[0]["detector"] == "step_time_drift"
    assert {"value", "baseline", "z", "rel_rise"} <= set(recs[0])
    evs = [e for e in tracer.events() if e["ev"] == "anomaly"]
    assert len(evs) == 1 and evs[0]["detector"] == "step_time_drift"
    dumps = glob.glob(str(tmp_path / "flight-anomaly-*.jsonl"))
    assert len(dumps) == 1 and "step_time_drift" in dumps[0]
    # an ongoing incident re-fires once per cooldown, suppressed counted
    for i in range(22, 60):
        t[0] = float(i)
        ae.observe("step_time_ms", 500.0, t=t[0])
        ae.check(t[0])
    counters = reg.snapshot()["counters"]
    assert counters["anomaly"] >= 2  # re-fired after cooldown_s=30
    assert counters["anomalies_suppressed"] >= 1


def test_anomaly_check_is_paced():
    t = [0.0]
    ae = AnomalyEngine(registry=MetricsRegistry(), clock=lambda: t[0],
                       window_s=1.0)
    ae.observe("step_time_ms", 1.0, t=0.0)
    ae.check(0.0)
    assert ae._last_check == 0.0
    t[0] = 0.5
    assert ae.check(0.5) == []      # inside the interval: one clock
    assert ae._last_check == 0.0    # read, no evaluation pass
    t[0] = 1.5
    ae.check(1.5)
    assert ae._last_check == 1.5    # a due check evaluates


def test_heartbeat_creep_uses_shared_rule_at_smaller_factor():
    t = [0.0]
    reg = MetricsRegistry()
    ae = AnomalyEngine(
        registry=reg, clock=lambda: t[0], window_s=0.5,
        detectors=[Detector("heartbeat_creep", floor=0.25, factor=3.0,
                            sustain=1)])
    # median step 100ms -> creep threshold max(0.25, 0.3) = 0.3s,
    # strictly below the stall tier's 1.0s (10x)
    for i in range(10):
        ae.observe("step_time_ms", 100.0, t=float(i) * 0.1)
    ae.observe("heartbeat_age_s", 0.2, t=1.0)
    assert ae.check(1.0) == []
    ae.observe("heartbeat_age_s", 0.5, t=2.0)
    fired = ae.check(2.0)
    assert fired and fired[0]["detector"] == "heartbeat_creep"
    assert fired[0]["threshold"] == pytest.approx(
        stall_threshold_secs(0.25, 0.1, factor=3.0), rel=0.02)
    assert fired[0]["threshold"] < stall_threshold_secs(1.5, 0.1)


# ---------------------------------------------------------------------------
# Fleet integration: detection strictly before the stall tier; the
# steady zero-anomaly pin. Driven clock — deterministic, tier-1.
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    return GPT(GPTConfig(block_size=64, vocab_size=128, n_layer=1,
                         n_head=2, n_embd=32, dropout=0.0, bias=True,
                         attn_impl="xla"), rngs=nnx.Rngs(0))


def _fleet(tiny_model, tmp_path, t, *, anomaly=True):
    from avenir_tpu.serve import Router

    reg = MetricsRegistry()
    tracer = Tracer(registry=reg, clock=lambda: t[0],
                    out_dir=str(tmp_path))
    ae = None
    if anomaly:
        ae = AnomalyEngine(registry=reg, tracer=tracer,
                           clock=lambda: t[0], window_s=0.25)
    router = Router(tiny_model, n_replicas=2, n_slots=2, registry=reg,
                    seed=0, clock=lambda: t[0], tracer=tracer,
                    anomaly=ae, stall_floor_secs=1.5)
    return router, reg, ae


def test_wedge_anomaly_fires_strictly_before_stall_tier(tiny_model,
                                                        tmp_path):
    """THE detection pin: a wedging replica (replica_stall — the fault
    site the stall tier was built on) trips heartbeat_creep with
    evidence and a flight dump STRICTLY before the stall threshold
    declares death. Driven clock: deterministic at tier-1 speed."""
    t = [0.0]
    router, reg, ae = _fleet(tiny_model, tmp_path, t)
    rng = np.random.default_rng(0)

    def pump(n=1, dt=0.05):
        for _ in range(n):
            t[0] += dt
            router.step()

    for i in range(12):
        router.submit([int(x) for x in rng.integers(0, 128, 6)],
                      max_new_tokens=32, temperature=1.0, top_k=None)
    pump(4)  # both replicas warmed, beating, holding work
    assert all(r.busy for r in router.replicas)
    prev = set_injector(FaultInjector("replica_stall:p=1:n=1"))
    try:
        pump(1)  # the wedge lands on whichever consults first
        assert sum(getattr(r, "_stalled", False)
                   for r in router.replicas) == 1
        t_wedge = t[0]
        t_anom = t_dead = None
        for _ in range(200):
            pump(1)
            if t_anom is None and any(f["detector"] == "heartbeat_creep"
                                      for f in ae.fired):
                t_anom = t[0]
            if t_dead is None and any(r.state == "dead"
                                      for r in router.replicas):
                t_dead = t[0]
            if t_anom is not None and t_dead is not None:
                break
        assert t_anom is not None, "anomaly engine never fired"
        assert t_dead is not None, "stall tier never declared death"
        assert t_anom < t_dead, (
            f"anomaly at +{t_anom - t_wedge:.2f}s must precede the "
            f"stall tier at +{t_dead - t_wedge:.2f}s")
        first = next(f for f in ae.fired
                     if f["detector"] == "heartbeat_creep")
        assert first["value"] > first["threshold"]
        assert glob.glob(str(tmp_path / "flight-anomaly-*.jsonl"))
        assert reg.snapshot()["counters"]["anomaly"] >= 1
    finally:
        set_injector(prev)
        router.close()


def test_steady_fleet_fires_zero_anomalies(tiny_model, tmp_path):
    """The no-flapping pin (test_autoscale style): a steady seeded
    in-SLO run produces ZERO anomalies — firing on a healthy fleet
    would train operators to ignore the tier."""
    t = [0.0]
    router, reg, ae = _fleet(tiny_model, tmp_path, t)
    rng = np.random.default_rng(1)
    done = 0
    submitted = 0
    try:
        while done < 24:
            while submitted < 24 and router.queue_depth < 3:
                router.submit([int(x) for x in rng.integers(0, 128, 6)],
                              max_new_tokens=8, temperature=1.0,
                              top_k=None)
                submitted += 1
            t[0] += 0.05
            done += len(router.step())
        counters = reg.snapshot()["counters"]
        assert counters.get("anomaly", 0) == 0, ae.fired
        assert counters.get("anomalies_suppressed", 0) == 0
        assert not glob.glob(str(tmp_path / "flight-anomaly-*.jsonl"))
        # the per-series gauges refreshed from the sketches
        gauges = reg.snapshot()["gauges"]
        assert gauges.get("ttft_p99_ms") is not None
        assert gauges.get("step_time_p99_ms") is not None
    finally:
        router.close()


def test_committed_anomaly_bench_artifact_pins_the_story():
    """BENCH_anomaly.json (tools/anomaly_bench.py) is committed with
    detection-latency vs watchdog-latency per scenario; its own ok
    flag asserts anomaly-before-stall, watchdog-silent-on-rot, and
    the steady zero."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench = json.load(open(os.path.join(repo, "BENCH_anomaly.json")))
    assert bench["kind"] == "anomaly_bench" and bench["ok"] is True
    sc = bench["scenarios"]
    assert sc["train_step_degrade"]["anomalies"] >= 1
    assert sc["train_step_degrade"]["watchdog_fired"] is False
    assert (sc["serve_replica_wedge"]["anomaly_latency_s"]
            < sc["serve_replica_wedge"]["stall_latency_s"])
    assert sc["steady_serve"]["anomalies"] == 0


# ---------------------------------------------------------------------------
# Overhead: the disabled path must stay near-zero (the PR 9 pins)
# ---------------------------------------------------------------------------


def test_disabled_anomaly_guard_is_nanoseconds():
    """Every wiring site holds `ae = self._anomaly; if ae is not
    None` — the exact shape test_trace pins for tracing."""
    class _Holder:
        _anomaly = None

    h = _Holder()
    n = 200_000
    t0 = time.perf_counter()
    acc = 0
    for _ in range(n):
        ae = h._anomaly
        if ae is not None:
            acc += 1
    per_op_us = (time.perf_counter() - t0) / n * 1e6
    assert acc == 0
    assert per_op_us < 1.0, (
        f"disabled-anomaly guard costs {per_op_us:.3f} us/op")


def test_disabled_anomaly_adds_no_measurable_step_overhead(tiny_model):
    """Fleet-level pin (relative, the test_trace budget idiom): router
    steps with anomaly=None are not slower than steps with the full
    engine armed (which do strictly more work)."""
    import statistics

    from avenir_tpu.serve import Router

    def median_step(arm):
        reg = MetricsRegistry()
        ae = AnomalyEngine(registry=reg, window_s=0.25) if arm else None
        router = Router(tiny_model, n_replicas=1, n_slots=2,
                        registry=reg, seed=0, anomaly=ae)
        rng = np.random.default_rng(4)
        durs = []
        try:
            for _ in range(3):
                for _ in range(2):
                    router.submit(
                        [int(x) for x in rng.integers(0, 128, 6)],
                        max_new_tokens=12, temperature=1.0, top_k=None)
                while router.open_requests:
                    t0 = time.perf_counter()
                    router.step()
                    durs.append(time.perf_counter() - t0)
        finally:
            router.close()
        return statistics.median(durs)

    base = median_step(False)          # the production default
    armed = median_step(True)
    assert base <= 3.0 * armed + 2e-3, (
        f"anomaly-disabled step ({base * 1e3:.2f} ms) slower than 3x "
        f"an armed step ({armed * 1e3:.2f} ms) + 2 ms")


# ---------------------------------------------------------------------------
# run_end sketches: obs_report reads p50/p99 without re-deriving
# ---------------------------------------------------------------------------


def test_obs_report_prefers_run_end_sketches():
    from avenir_tpu.obs.report import format_report, summarize

    sk = QuantileSketch()
    for v in (10.0, 20.0, 30.0, 40.0):
        sk.observe(v)
    records = [
        {"kind": "run_meta", "t": 0.0},
        {"kind": "request", "t": 1.0, "ttft_ms": 999.0, "tpot_ms": 9.0,
         "n_out": 4, "finish_reason": "length"},
        {"kind": "run_end", "t": 2.0, "counters": {"tokens_out": 4.0},
         "series": {"ttft_ms": {"sketch": sk.to_dict()},
                    "tpot_ms": {"sketch": sk.to_dict()}}},
    ]
    s = summarize(records)
    assert s["serve"]["latency_source"] == "sketch"
    # the sketch's p50 (not the 999.0 the raw record claims)
    assert s["serve"]["ttft_p50_ms"] == pytest.approx(20.0, rel=0.02)
    assert "(run_end sketch)" in format_report(s)
    # without sketches, the per-request records still answer
    s2 = summarize(records[:2] + [{"kind": "run_end", "t": 2.0,
                                   "counters": {"tokens_out": 4.0}}])
    assert s2["serve"]["latency_source"] == "records"
    assert s2["serve"]["ttft_p50_ms"] == 999.0


def test_obs_report_anomalies_line():
    from avenir_tpu.obs.report import format_report, summarize

    records = [
        {"kind": "run_meta", "t": 100.0},
        {"kind": "iter", "t": 101.0, "iter": 0, "loss": 1.0,
         "counters": {}},
        {"kind": "anomaly", "t": 103.0, "detector": "step_time_drift",
         "key": "step_time_ms", "value": 50.0, "threshold": 4.0},
        {"kind": "anomaly", "t": 105.0, "detector": "step_time_drift",
         "key": "step_time_ms", "value": 60.0, "threshold": 4.0},
        {"kind": "run_end", "t": 106.0,
         "counters": {"anomaly": 2.0, "anomalies_suppressed": 3.0}},
    ]
    s = summarize(records)
    assert s["anomalies"]["n"] == 2
    assert s["anomalies"]["by_detector"] == {"step_time_drift": 2}
    out = format_report(s)
    assert "ANOMALIES: 2" in out and "step_time_drift=2" in out
    assert "first +3.0s" in out and "last +5.0s" in out
    assert "3 suppressed" in out


def test_fleet_report_links_anomalies_to_decisions():
    from tools.fleet_report import summarize_fleet

    events = [
        {"rid": None, "ev": "anomaly", "t": 10.0,
         "detector": "queue_wait_trend", "key": "queue_wait_ms"},
        {"rid": None, "ev": "scale", "t": 14.0, "action": "up",
         "reason": "queue_wait", "from_size": 1, "to_size": 2,
         "window_s": 6.0},
        {"rid": None, "ev": "scale", "t": 60.0, "action": "down",
         "reason": "surplus", "from_size": 2, "to_size": 1,
         "window_s": 6.0},
    ]
    s = summarize_fleet(events)
    assert s["n_anomalies"] == 1
    up, down = s["decisions"]
    assert up["anomalies_before"] == [
        {"t_rel_s": 0.0, "detector": "queue_wait_trend",
         "key": "queue_wait_ms"}]
    assert down["anomalies_before"] == []


# ---------------------------------------------------------------------------
# Engine health series + process sketch shipping (wire-form fast test;
# the real worker round-trip rides the slow lane in test_serve_proc)
# ---------------------------------------------------------------------------


def test_engine_health_series_collects_and_drains(tiny_model):
    from avenir_tpu.serve import Engine

    eng = Engine(tiny_model, n_slots=2, max_seq_len=32,
                 registry=MetricsRegistry(), health_series=True)
    assert eng.take_series_delta() is None  # nothing yet
    rng = np.random.default_rng(0)
    eng.submit([int(x) for x in rng.integers(0, 128, 4)],
               max_new_tokens=4, temperature=1.0, top_k=None)
    eng.drain()
    d = eng.take_series_delta()
    assert d and d["step_time_ms"]["count"] >= 1
    assert eng.take_series_delta() is None  # drained: nothing new
    eng.submit([int(x) for x in rng.integers(0, 128, 4)],
               max_new_tokens=2, temperature=1.0, top_k=None)
    eng.drain()
    d2 = eng.take_series_delta()
    assert d2 and d2["step_time_ms"]["count"] >= 1
    # parent-side merge through the registry series (the proc path)
    reg = MetricsRegistry()
    reg.series("step_time_ms").sketch.merge_dict(d["step_time_ms"])
    reg.series("step_time_ms").sketch.merge_dict(d2["step_time_ms"])
    assert (reg.series("step_time_ms").sketch.count
            == eng._hs.count)


def test_engine_without_health_series_pays_one_branch(tiny_model):
    from avenir_tpu.serve import Engine

    eng = Engine(tiny_model, n_slots=2, max_seq_len=32,
                 registry=MetricsRegistry())
    assert eng._hs is None and eng.take_series_delta() is None


# ---------------------------------------------------------------------------
# slow lane: real wall clocks + real processes (the conftest
# duration-artifact convention — timing-sensitive cases carry `slow`)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_train_loop_degrade_fires_anomaly_watchdog_stays_silent():
    """The REAL training loop under the train_step_degrade fault site:
    the drift detector fires (with a flight dump), and the watchdog —
    whose contract is total stalls — never does. Wall-clock timing:
    slow lane; the committed BENCH_anomaly.json pins the same run."""
    from tools.anomaly_bench import train_degrade_scenario

    out = train_degrade_scenario(0, degrade_after=4, max_iters=119)
    assert out["anomalies"] >= 1
    assert out["detector"] == "step_time_drift"
    assert out["watchdog_fired"] is False
    assert out["flight_dumps"] >= 1
    assert out["anomaly_latency_s"] is not None


@pytest.mark.slow
def test_process_worker_ships_sketch_deltas_parent_merges(tiny_model):
    """health_series over the process backend: the worker's step-wall
    sketch rides step replies as bucket deltas and merges into the
    PARENT registry's series — the counter-delta mirroring contract,
    for quantiles, across a real pipe."""
    from avenir_tpu.serve import Router

    reg = MetricsRegistry()
    router = Router(tiny_model, n_replicas=1, n_slots=2, registry=reg,
                    seed=0, backend="process",
                    engine_kwargs={"health_series": 1})
    try:
        rng = np.random.default_rng(0)
        for _ in range(3):
            router.submit([int(x) for x in rng.integers(0, 128, 6)],
                          max_new_tokens=6, temperature=1.0, top_k=None)
        done = router.drain()
        assert len(done) == 3
        sk = reg.series("step_time_ms").sketch
        assert sk.count >= 1, "no sketch deltas crossed the pipe"
        assert sk.quantile(0.5) is not None
        snap = reg.series_snapshot()
        assert snap["step_time_ms"]["sketch"]["count"] == sk.count
    finally:
        router.close()


def test_int8_to_bf16_silent_fallback_fires_step_time_drift(tmp_path):
    """ISSUE 15 obs satellite, driven clock: an int8 run whose matmuls
    silently fall back to bf16 roughly DOUBLES its step time — a
    permanent plateau, not a stall, so the watchdog stays quiet by
    design and step_time_drift is the tier that must catch it (pair the
    fire with the matmul_bits gauge to name the cause). 2x is far past
    the detector's 35% min_rel floor: it must fire within a few checks
    of the flip, and never before it."""
    t = [0.0]
    ae = AnomalyEngine(registry=MetricsRegistry(), clock=lambda: t[0],
                       window_s=1.0, check_interval_s=1.0,
                       detectors=[Detector("step_time_drift",
                                           z_thresh=4.0, min_rel=0.35,
                                           sustain=2, min_windows=8)])
    rng = np.random.default_rng(1)
    fired_at = None
    for i in range(48):
        t[0] = float(i)
        # 24 healthy int8 windows at ~110ms, then the silent bf16
        # fallback: ~220ms from one window to the next, permanently
        base = 110.0 if i < 24 else 220.0
        ae.observe("step_time_ms", base + rng.normal(0, 2.0))
        out = ae.check()
        if out and fired_at is None:
            fired_at = i
        if i < 24:
            assert not out, f"fired on healthy int8 steady state at {i}"
    assert fired_at is not None, "2x silent-fallback step time never fired"
    assert fired_at <= 32, f"fired too late ({fired_at}) after the flip at 24"
    ev = ae.fired[0]
    assert ev["detector"] == "step_time_drift"
    assert ev["value"] > 1.8 * ev["baseline"]  # ~2x the int8 baseline
    assert ev["rel_rise"] > 0.35
