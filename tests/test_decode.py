"""KV-cache decoder tests (avenir_tpu/infer/decode.py): token-for-token
parity with the recompute-full-prefix generate() for GPT (MHA), Llama
(GQA+RoPE), Mixtral (MoE), and the scan-stacked layout."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.models.llama import Llama, LlamaConfig
from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

GPT_TINY = GPTConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
LLAMA_KW = dict(block_size=32, vocab_size=64, n_layer=2, n_head=4,
                n_kv_head=2, n_embd=32, ffn_hidden=64, dropout=0.0,
                attn_impl="xla")


def _assert_parity(model, prompt_len=5, new_tokens=10, top_k=8):
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, 64, (2, prompt_len)).astype(np.int32))
    ref = model.generate(jax.random.key(3), idx, new_tokens,
                         temperature=0.9, top_k=top_k)
    got = generate_cached(model, jax.random.key(3), idx, new_tokens,
                          temperature=0.9, top_k=top_k)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


# module-scoped GPT instances: the stop-token tests reuse the parity
# tests' (B, prompt, new, sampling) shapes, so their reference calls are
# compile-cache hits instead of fresh trace+compiles (tier-1 budget)
@pytest.fixture(scope="module")
def gpt_model():
    return GPT(GPT_TINY, rngs=nnx.Rngs(0))


@pytest.fixture(scope="module")
def gpt_scan_model():
    cfg = dataclasses.replace(GPT_TINY, scan_layers=True)
    return GPT(cfg, rngs=nnx.Rngs(0))


def test_gpt_decode_matches_generate(gpt_model):
    _assert_parity(gpt_model)


def test_gpt_scan_decode_matches_generate(gpt_scan_model):
    _assert_parity(gpt_scan_model)


def test_llama_gqa_decode_matches_generate():
    _assert_parity(Llama(LlamaConfig(**LLAMA_KW), rngs=nnx.Rngs(0)))


def test_mixtral_decode_matches_generate():
    cfg = MixtralConfig(n_experts=4, n_experts_per_tok=2,
                        capacity_factor=2.0, **LLAMA_KW)
    _assert_parity(Mixtral(cfg, rngs=nnx.Rngs(0)))


def test_decode_single_compile_across_positions(gpt_model):
    """The per-token step must not retrace per position (pos is traced)."""
    model = gpt_model
    idx = jnp.zeros((1, 4), jnp.int32)
    with jax.log_compiles(False):
        pass  # smoke only; real check below via cache size

    # run twice with different lengths sharing the (B,1) step shape — the
    # second jit of the step fn is a cache hit (same avals). We assert via
    # timing-free proxy: generate works for >1 new token without error and
    # output length is correct.
    out = generate_cached(model, jax.random.key(0), idx, 8)
    assert out.shape == (1, 12)


def test_decode_rejects_overlong(gpt_model):
    idx = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(AssertionError):
        generate_cached(gpt_model, jax.random.key(0), idx, 10)


# ---- ISSUE 2 satellites: stop tokens, prompt bucketing, batched rng ----


def _prompt(rng, shape):
    return jnp.asarray(rng.integers(0, 64, shape).astype(np.int32))


def test_stop_tokens_match_no_stop_prefix(gpt_model):
    """With a stop token, the emitted prefix (through the first stop) is
    unchanged vs no-stop decoding; everything after is pad. Shapes match
    _assert_parity's so the no-stop reference is a compile-cache hit."""
    from avenir_tpu.infer.decode import first_stop_index

    idx = _prompt(np.random.default_rng(0), (2, 5))
    key = jax.random.key(3)
    ref = np.asarray(generate_cached(gpt_model, key, idx, 10,
                                     temperature=0.9, top_k=8))
    # pick a stop token that actually fires mid-stream for row 0
    stop = int(ref[0, 5 + 3])
    got = np.asarray(generate_cached(gpt_model, key, idx, 10,
                                     temperature=0.9, top_k=8,
                                     stop_tokens=(stop,)))
    for r in range(2):
        n = first_stop_index(ref[r, 5:], (stop,))
        np.testing.assert_array_equal(ref[r, :5 + n], got[r, :5 + n])
        assert (got[r, 5 + n:] == stop).all()  # pad defaults to stop id


def test_stop_tokens_parity_vs_generate(gpt_model):
    """Stop-path decode still matches the recompute-full-prefix path on
    the emitted prefix (the satellite's parity requirement)."""
    from avenir_tpu.infer.decode import first_stop_index

    idx = _prompt(np.random.default_rng(0), (2, 5))
    key = jax.random.key(3)
    ref = np.asarray(gpt_model.generate(key, idx, 10, temperature=0.9,
                                        top_k=8))
    stop = int(ref[0, 5 + 2])
    got = np.asarray(generate_cached(gpt_model, key, idx, 10,
                                     temperature=0.9, top_k=8,
                                     stop_tokens=stop))
    n = first_stop_index(ref[0, 5:], (stop,))
    np.testing.assert_array_equal(ref[0, :5 + n], got[0, :5 + n])


def test_stop_on_scan_layout(gpt_scan_model):
    idx = _prompt(np.random.default_rng(0), (2, 5))
    key = jax.random.key(3)
    ref = np.asarray(generate_cached(gpt_scan_model, key, idx, 10,
                                     temperature=0.9, top_k=8))
    stop = int(ref[0, 5])  # first emitted token of row 0: stops at once
    got = np.asarray(generate_cached(gpt_scan_model, key, idx, 10,
                                     temperature=0.9, top_k=8,
                                     stop_tokens=[stop]))
    assert got[0, 5] == stop and (got[0, 6:] == stop).all()


def test_prompt_bucket_bounds_compiles():
    """Nearby prompt lengths share one prefill + one decode compile
    (pad-to-bucket); the trace ledger pins the count."""
    from avenir_tpu.infer import decode

    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    rng = np.random.default_rng(4)
    n0 = decode.trace_count()
    for t0 in (5, 6, 8):  # all bucket to 8; width buckets to 16
        generate_cached(model, jax.random.key(t0), _prompt(rng, (1, t0)),
                        8, top_k=8)
    assert decode.trace_count() - n0 == 2, (
        "expected exactly one prefill + one decode trace across prompt "
        "lengths 5/6/8"
    )


def test_batched_rng_rows_match_sequential():
    """A (N,) key vector decodes each row bit-identically to N separate
    B=1 calls with those keys (sample.py's batched path), in 2 compiles."""
    from avenir_tpu.infer import decode

    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    prompt = _prompt(np.random.default_rng(5), (1, 5))
    keys = [jax.random.key(100 + s) for s in range(3)]
    seq = [np.asarray(generate_cached(model, k, prompt, 8, temperature=0.8,
                                      top_k=8))[0] for k in keys]
    kvec = jax.random.wrap_key_data(
        jnp.stack([jax.random.key_data(k) for k in keys]))
    n0 = decode.trace_count()
    got = np.asarray(generate_cached(model, kvec, jnp.tile(prompt, (3, 1)),
                                     8, temperature=0.8, top_k=8))
    assert decode.trace_count() - n0 == 2, "batched call must be 2 traces"
    for s in range(3):
        np.testing.assert_array_equal(seq[s], got[s])
