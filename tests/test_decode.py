"""KV-cache decoder tests (avenir_tpu/infer/decode.py): token-for-token
parity with the recompute-full-prefix generate() for GPT (MHA), Llama
(GQA+RoPE), Mixtral (MoE), and the scan-stacked layout."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.models.llama import Llama, LlamaConfig
from avenir_tpu.models.mixtral import Mixtral, MixtralConfig

GPT_TINY = GPTConfig(block_size=32, vocab_size=64, n_layer=2, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
LLAMA_KW = dict(block_size=32, vocab_size=64, n_layer=2, n_head=4,
                n_kv_head=2, n_embd=32, ffn_hidden=64, dropout=0.0,
                attn_impl="xla")


def _assert_parity(model, prompt_len=5, new_tokens=10, top_k=8):
    rng = np.random.default_rng(0)
    idx = jnp.asarray(rng.integers(0, 64, (2, prompt_len)).astype(np.int32))
    ref = model.generate(jax.random.key(3), idx, new_tokens,
                         temperature=0.9, top_k=top_k)
    got = generate_cached(model, jax.random.key(3), idx, new_tokens,
                          temperature=0.9, top_k=top_k)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_gpt_decode_matches_generate():
    _assert_parity(GPT(GPT_TINY, rngs=nnx.Rngs(0)))


def test_gpt_scan_decode_matches_generate():
    cfg = dataclasses.replace(GPT_TINY, scan_layers=True)
    _assert_parity(GPT(cfg, rngs=nnx.Rngs(0)))


def test_llama_gqa_decode_matches_generate():
    _assert_parity(Llama(LlamaConfig(**LLAMA_KW), rngs=nnx.Rngs(0)))


def test_mixtral_decode_matches_generate():
    cfg = MixtralConfig(n_experts=4, n_experts_per_tok=2,
                        capacity_factor=2.0, **LLAMA_KW)
    _assert_parity(Mixtral(cfg, rngs=nnx.Rngs(0)))


def test_decode_single_compile_across_positions():
    """The per-token step must not retrace per position (pos is traced)."""
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    idx = jnp.zeros((1, 4), jnp.int32)
    with jax.log_compiles(False):
        pass  # smoke only; real check below via cache size

    # run twice with different lengths sharing the (B,1) step shape — the
    # second jit of the step fn is a cache hit (same avals). We assert via
    # timing-free proxy: generate works for >1 new token without error and
    # output length is correct.
    out = generate_cached(model, jax.random.key(0), idx, 8)
    assert out.shape == (1, 12)


def test_decode_rejects_overlong():
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    idx = jnp.zeros((1, 30), jnp.int32)
    with pytest.raises(AssertionError):
        generate_cached(model, jax.random.key(0), idx, 10)
