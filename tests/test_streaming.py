"""Streaming dataset subsystem (ISSUE 19): sharded corpora with
per-host locality, weighted multi-corpus mixing, deep prefetch, and the
bit-identical kill-resume contract over all of it.

Fast tier-1 coverage here; the SIGKILL-under-mixing soak rides the slow
marker at the bottom (tools/chaos_train.py --mix=1)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from avenir_tpu.data.loader import DataLoader, read_wire_format
from avenir_tpu.data.streaming import (
    MANIFEST_NAME,
    SplitSource,
    load_manifest,
    parse_data_mix,
    write_token_shards,
)
from avenir_tpu.obs.metrics import get_registry, reset_registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tokens(n, seed=0, hi=501):
    return np.random.default_rng(seed).integers(0, hi, n).astype(np.uint16)


def _mk_sharded(dirpath, *, n=40_000, shard_tokens=1_000, seed=0,
                splits=("train",)):
    toks = _tokens(n, seed)
    for split in splits:
        write_token_shards(os.path.join(str(dirpath), f"{split}.shards"),
                           toks, shard_tokens=shard_tokens)
    return toks


def _mk_legacy(dirpath, *, n=40_000, seed=0, splits=("train",)):
    toks = _tokens(n, seed)
    for split in splits:
        toks.tofile(os.path.join(str(dirpath), f"{split}.bin"))
    return toks


# ---- sharded writer + manifest ---------------------------------------------


def test_shard_writer_roundtrip(tmp_path):
    toks = _tokens(10_500, seed=3)
    d = tmp_path / "train.shards"
    dtype = write_token_shards(d, toks, shard_tokens=4_000)
    assert dtype == np.dtype(np.uint16)
    m = load_manifest(str(d))
    assert m["dtype"] == "uint16"
    assert [s["tokens"] for s in m["shards"]] == [4000, 4000, 2500]
    got = []
    for s in m["shards"]:
        f = str(d / s["file"])
        dt, off = read_wire_format(f)
        assert dt == np.dtype(np.uint16) and off == 8  # v2 header
        got.append(np.fromfile(f, dtype=dt, offset=off))
    np.testing.assert_array_equal(np.concatenate(got), toks)


def test_shard_writer_u32_for_big_vocab(tmp_path):
    toks = np.array([0, 70_000, 123, 65_999], dtype=np.uint32)
    d = tmp_path / "train.shards"
    dtype = write_token_shards(d, toks, shard_tokens=2, vocab_size=128_256)
    assert dtype == np.dtype(np.uint32)
    m = load_manifest(str(d))
    assert m["dtype"] == "uint32"
    f = str(d / m["shards"][0]["file"])
    dt, _ = read_wire_format(f)
    assert dt == np.dtype(np.uint32)


def test_manifest_fails_loud_on_foreign_layout(tmp_path):
    d = tmp_path / "train.shards"
    write_token_shards(d, _tokens(100), shard_tokens=50)
    mpath = d / MANIFEST_NAME
    m = json.loads(mpath.read_text())
    m["version"] = 99
    mpath.write_text(json.dumps(m))
    with pytest.raises(AssertionError, match="version"):
        load_manifest(str(d))
    m["version"] = 1
    m["kind"] = "something-else"
    mpath.write_text(json.dumps(m))
    with pytest.raises(AssertionError, match="kind"):
        load_manifest(str(d))


# ---- mix spec --------------------------------------------------------------


def test_parse_data_mix():
    mix = parse_data_mix("owt:0.7,code:0.3")
    assert [n for n, _ in mix] == ["owt", "code"]
    assert sum(w for _, w in mix) == pytest.approx(1.0)
    assert dict(mix)["owt"] == pytest.approx(0.7)
    # unnormalized weights normalize
    mix = parse_data_mix("a:2,b:6")
    assert dict(mix)["b"] == pytest.approx(0.75)
    with pytest.raises(AssertionError, match="twice"):
        parse_data_mix("a:1,a:2")
    with pytest.raises(AssertionError, match="> 0"):
        parse_data_mix("a:0")


# ---- sharded sources: locality + gather fidelity ---------------------------


def test_sharded_gather_matches_token_stream(tmp_path):
    toks = _mk_sharded(tmp_path, n=9_000, shard_tokens=1_000)
    src = SplitSource(str(tmp_path), "train", 64,
                     process_index=0, process_count=1)
    assert src.kind == "sharded"
    assert src.n_positions == 9 * (1_000 - 64)
    rng = np.random.default_rng(0)
    ix = rng.integers(0, src.n_positions, size=32)
    x, y = src.gather(ix)
    # flat position p lives in shard p // (shard_tokens - block) at
    # offset p % (...); shards are contiguous chunks of the stream
    per = 1_000 - 64
    for row, p in enumerate(ix):
        s, off = divmod(int(p), per)
        want = toks[s * 1_000 + off:s * 1_000 + off + 65]
        np.testing.assert_array_equal(x[row], want[:-1])
        np.testing.assert_array_equal(y[row], want[1:])


def test_sharded_locality_disjoint_and_covering(tmp_path):
    _mk_sharded(tmp_path, n=10_000, shard_tokens=1_000)  # 10 shards
    ranges = []
    for p in range(3):
        src = SplitSource(str(tmp_path), "train", 64,
                          process_index=p, process_count=3)
        ranges.append(src.local_range)
        assert src.n_positions > 0
    # disjoint, contiguous, covering — the checkpoint local_shard_ranges
    # arithmetic
    assert ranges == [(0, 3), (3, 6), (6, 10)]


def test_sharded_needs_enough_shards(tmp_path):
    _mk_sharded(tmp_path, n=2_000, shard_tokens=1_000)  # 2 shards
    with pytest.raises(AssertionError, match="disjoint"):
        SplitSource(str(tmp_path), "train", 64,
                    process_index=0, process_count=4)


def test_sharded_vocab_gate_fails_loud(tmp_path):
    _mk_sharded(tmp_path, n=2_000, shard_tokens=1_000)  # uint16 corpus
    with pytest.raises(AssertionError, match="wire"):
        SplitSource(str(tmp_path), "train", 64, vocab_size=128_256,
                    process_index=0, process_count=1)
    with pytest.raises(AssertionError, match="wire"):
        DataLoader(str(tmp_path), 64, 4, grad_accum=1, seed=0,
                   vocab_size=128_256)


def test_legacy_source_bound_is_bit_exact(tmp_path):
    toks = _mk_legacy(tmp_path, n=5_000)
    src = SplitSource(str(tmp_path), "train", 64,
                      process_index=0, process_count=1)
    assert src.kind == "file"
    assert src.n_positions == len(toks) - 64  # the legacy rng bound


def test_fused_gather_matches_per_slice_reference(tmp_path):
    """The legacy layout must keep loading byte-identically: the fused
    fancy-index gather must hand out exactly the crops the seed loader's
    per-slice loop produced for the same rng stream."""
    import jax

    toks = _mk_legacy(tmp_path, n=8_000)
    dl = DataLoader(str(tmp_path), 32, 4, grad_accum=2, seed=11)
    ref_rng = np.random.default_rng(11 + 1000 * jax.process_index())
    for _ in range(3):
        x, y = dl._sample_local("train")
        ix = ref_rng.integers(0, len(toks) - 32, size=8)
        rx = np.stack([toks[i:i + 32] for i in ix]).reshape(2, 4, 32)
        ry = np.stack([toks[i + 1:i + 33] for i in ix]).reshape(2, 4, 32)
        np.testing.assert_array_equal(np.asarray(x), rx)
        np.testing.assert_array_equal(np.asarray(y), ry)


# ---- deep prefetch ---------------------------------------------------------


def test_deep_prefetch_preserves_stream_order(tmp_path):
    """prefetch_depth > 1 stages ahead on a persistent worker, but the
    CONSUMED stream must stay bit-identical to an unprefetched loader's
    (extends test_prefetch_preserves_stream_order to the deep path)."""
    _mk_sharded(tmp_path, n=20_000, shard_tokens=2_000)
    deep = DataLoader(str(tmp_path), 32, 4, grad_accum=1, seed=5,
                      prefetch_depth=4)
    sync = DataLoader(str(tmp_path), 32, 4, grad_accum=1, seed=5)
    got = []
    for _ in range(4):
        x, y = deep.get_batch_window("train", 2)
        for j in range(2):
            got.append((np.asarray(x)[j], np.asarray(y)[j]))
    deep.close()
    for gx, gy in got:
        sx, sy = sync._sample_local("train")
        np.testing.assert_array_equal(gx, sx)
        np.testing.assert_array_equal(gy, sy)


def test_deep_prefetch_error_raises_at_next_get_batch(tmp_path):
    """A worker failure must surface at the NEXT consume — and keep
    raising (sticky): the worker already advanced the rng for its
    partial draws, so continuing would silently desync the stream."""
    import time as _time

    _mk_legacy(tmp_path, n=5_000)
    dl = DataLoader(str(tmp_path), 32, 2, grad_accum=1, seed=0,
                    prefetch_depth=3)
    real = dl._sample_local
    calls = [0]

    def flaky(split):
        calls[0] += 1
        if calls[0] > 2:
            raise OSError("disk pulled mid-run")
        return real(split)

    dl._sample_local = flaky
    dl.get_batch_window("train", 1)  # serves batch 1, worker stages on
    for _ in range(100):  # wait for the worker to hit the failure
        if dl._deep.error is not None:
            break
        _time.sleep(0.02)
    assert dl._deep.error is not None
    with pytest.raises(RuntimeError, match="prefetch failed"):
        dl.get_batch("train")
    with pytest.raises(RuntimeError, match="prefetch failed"):  # sticky
        dl.get_batch_window("train", 1)
    dl.close()


def test_deep_prefetch_counts_windows_and_hits(tmp_path):
    _mk_legacy(tmp_path, n=5_000)
    reset_registry()
    try:
        dl = DataLoader(str(tmp_path), 32, 2, grad_accum=1, seed=0,
                        prefetch_depth=3)
        for _ in range(4):
            dl.get_batch_window("train", 1)
        dl.close()
        c = get_registry().snapshot()["counters"]
        assert c["data_windows"] == 4
        assert 0 <= c.get("data_prefetch_hit", 0) <= 4
    finally:
        reset_registry()


def test_resume_state_counts_popped_not_staged(tmp_path):
    """Prefetch stages rng draws AHEAD of consumption; the checkpointed
    counts must cover only what the caller actually received (a kill
    loses the staged tail, and resume must not replay it)."""
    _mk_sharded(tmp_path, n=20_000, shard_tokens=2_000)
    dl = DataLoader(str(tmp_path), 32, 2, grad_accum=1, seed=0,
                    prefetch_depth=4)
    for _ in range(2):
        dl.get_batch_window("train", 2)
    st = dl.resume_state()
    dl.close()
    assert st["batches"] == {"train": 4}
    assert st["mixed"] is False


# ---- mixing: determinism + kill-resume -------------------------------------


def _mk_mix(tmp_path, *, weights="owt:0.7,code:0.3"):
    """Two corpora (one sharded, one legacy) + a loader factory."""
    owt = tmp_path / "owt"
    code = tmp_path / "code"
    owt.mkdir()
    code.mkdir()
    _mk_sharded(owt, n=20_000, shard_tokens=2_000, seed=1,
                splits=("train", "val"))
    _mk_legacy(code, n=12_000, seed=2, splits=("train", "val"))

    def mk(mix=weights, **kw):
        kw.setdefault("grad_accum", 1)
        kw.setdefault("seed", 9)
        return DataLoader(str(owt), 32, 4, mix=mix, **kw)

    return mk


def test_mixed_draws_from_both_corpora(tmp_path):
    mk = _mk_mix(tmp_path)
    dl = mk()
    for _ in range(6):
        dl.get_batch("train")
    rep = dl.data_report()
    crops = rep["crops"]["train"]
    assert set(crops) == {"owt", "code"}
    assert crops["owt"] + crops["code"] == 6 * 4
    assert crops["owt"] > crops["code"]  # 0.7 vs 0.3 over 24 draws
    assert rep["sources"]["owt/train"]["kind"] == "sharded"
    assert rep["sources"]["code/train"]["kind"] == "file"


def test_mixed_fast_forward_state_bit_identical(tmp_path):
    """The kill-resume contract over a mixture: a fresh loader replayed
    from resume_state must continue the EXACT batch stream."""
    mk = _mk_mix(tmp_path)
    a = mk()
    for _ in range(5):
        a.get_batch("train")
    state = a.resume_state()
    b = mk()
    b.fast_forward_state(state)
    for _ in range(3):
        ax, ay = a.get_batch("train")
        bx, by = b.get_batch("train")
        np.testing.assert_array_equal(np.asarray(ax), np.asarray(bx))
        np.testing.assert_array_equal(np.asarray(ay), np.asarray(by))
    # and the replayed consumption is cumulative for the NEXT checkpoint
    assert b.resume_state()["batches"]["train"] == 8


def test_mixed_plan_fast_forward_bit_identical(tmp_path):
    """The derived (iter-count) replay path — what a pre-data_state
    checkpoint falls back to — must also land on the same stream when
    the weights are unchanged."""
    mk = _mk_mix(tmp_path)
    a = mk()
    for _ in range(4):
        a.get_batch("train")
    b = mk()
    b.fast_forward([("train", 4)])
    ax, _ = a.get_batch("train")
    bx, _ = b.get_batch("train")
    np.testing.assert_array_equal(np.asarray(ax), np.asarray(bx))


def test_mixed_reweight_resume_keeps_corpus_streams(tmp_path):
    """Mixture weights may change across a relaunch without desyncing
    any corpus's stream: replay by checkpointed per-corpus COUNTS must
    land every rng (selection + per-corpus) in exactly the state the
    killed run left it."""
    mk = _mk_mix(tmp_path)
    a = mk("owt:0.7,code:0.3")
    for _ in range(5):
        a.get_batch("train")
    state = a.resume_state()
    b = mk("owt:0.5,code:0.5")  # relaunch re-weighted
    b.fast_forward_state(state)
    assert (b._sel_rng.bit_generator.state
            == a._sel_rng.bit_generator.state)
    for key, rng in a._rngs.items():
        assert b._rngs[key].bit_generator.state == rng.bit_generator.state


def test_mixed_deep_prefetch_stream_order(tmp_path):
    """Mixing composes with the deep pipeline: consumed stream stays
    bit-identical to the synchronous mixed loader's."""
    mk = _mk_mix(tmp_path)
    deep = mk(prefetch_depth=3)
    sync = mk()
    for _ in range(3):
        x, _ = deep.get_batch_window("train", 2)
        for j in range(2):
            sx, _ = sync._sample_local("train")
            np.testing.assert_array_equal(np.asarray(x)[j], sx)
    deep.close()


def test_mixed_state_shape_guards(tmp_path):
    mk = _mk_mix(tmp_path)
    a = mk()
    a.get_batch("train")
    state = a.resume_state()
    # unmixed loader must refuse a mixed state (and vice versa)
    owt = str(tmp_path / "owt")
    plain = DataLoader(owt, 32, 4, grad_accum=1, seed=9)
    with pytest.raises(AssertionError, match="mixed"):
        plain.fast_forward_state(state)
    # a corpus missing from the relaunch mix fails loud
    b = mk("owt:1.0")
    with pytest.raises(AssertionError, match="code"):
        b.fast_forward_state(state)


def test_unmixed_resume_state_roundtrip(tmp_path):
    _mk_sharded(tmp_path, n=20_000, shard_tokens=2_000)
    a = DataLoader(str(tmp_path), 32, 4, grad_accum=1, seed=3)
    for _ in range(4):
        a.get_batch("train")
    b = DataLoader(str(tmp_path), 32, 4, grad_accum=1, seed=3)
    b.fast_forward_state(a.resume_state())
    ax, _ = a.get_batch("train")
    bx, _ = b.get_batch("train")
    np.testing.assert_array_equal(np.asarray(ax), np.asarray(bx))


# ---- chaos soak (subprocess, slow) -----------------------------------------


@pytest.mark.slow
def test_chaos_mixed_subprocess(tmp_path):
    """SIGKILL + resume over a sharded+legacy weighted mixture with deep
    prefetch: trajectory bit-equality end to end through train.py
    (tools/chaos_train.py --mix=1)."""
    report_path = tmp_path / "chaos.json"
    r = subprocess.run(
        [sys.executable, "tools/chaos_train.py", "--mix=1", "--seed=2",
         "--kills=2", "--max_iters=8", "--eval_interval=4",
         f"--workdir={tmp_path / 'work'}", f"--out={report_path}"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=1800,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    report = json.loads(report_path.read_text())
    assert report["ok"] is True
    assert report["bit_identical"] is True
    assert report["config"]["mix"] is True
