"""Disaggregated prefill/decode fleet tests (ISSUE 13): the PT_KVPAGES
tensor-frame codec, PageAllocator.import_chain (the cross-allocator
splice) with its transfer stats + audit, prefill-role engines exporting
finished pages, decode engines importing them, the router's class-aware
placement + streamed handoff, mid-transfer death bit-parity, the
`transfer` TTFT segment (queue + prefill + transfer + failover must
partition measured TTFT exactly), per-class autoscaling hooks, and the
serve_bench --disagg --smoke CI path.

Budget notes (the test_serve_router discipline): one module-scoped tiny
GPT + one-shot references; short prompts share one bucket, long prompts
share a chunk ladder; router tests use page_size=8 / prefill_chunk=16
so a "long" prompt is only ~2 chunks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from avenir_tpu.infer.decode import generate_cached
from avenir_tpu.models.gpt import GPT, GPTConfig
from avenir_tpu.obs import MetricsRegistry
from avenir_tpu.obs.trace import Tracer, request_segments, \
    ttft_attribution
from avenir_tpu.serve import Engine, Router
from avenir_tpu.serve.frames import FrameProtocolError, \
    decode_kv_pages, encode_kv_pages
from avenir_tpu.serve.pages import PageAllocator

GPT_TINY = GPTConfig(block_size=128, vocab_size=64, n_layer=1, n_head=2,
                     n_embd=32, dropout=0.0, bias=True, attn_impl="xla")
MAX_NEW = 4
PAGE = 8
CHUNK = 16
EKW = {"kv_impl": "paged", "page_size": PAGE, "prefill_chunk": CHUNK}


def _mk_requests(model, rng, n, long_every=2):
    """n requests — every `long_every`-th gets a LONG prompt (>= CHUNK,
    multiple chunks, several exportable pages), the rest short (one
    bucket) — with their one-shot reference streams."""
    reqs = []
    for i in range(n):
        t0 = (int(rng.integers(34, 42)) if i % long_every == 0
              else int(rng.integers(3, 9)))
        prompt = [int(t) for t in rng.integers(0, 64, (t0,))]
        key = jax.random.key(7000 + i)
        y = np.asarray(generate_cached(
            model, key, jnp.asarray(prompt, jnp.int32)[None], MAX_NEW,
            temperature=1.0, top_k=8))[0]
        reqs.append((dict(prompt=prompt, max_new_tokens=MAX_NEW,
                          temperature=1.0, top_k=8, rng=key),
                     [int(t) for t in y]))
    return reqs


@pytest.fixture(scope="module")
def fix():
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    return model, _mk_requests(model, np.random.default_rng(5), 6)


def _submit_all(router, reqs):
    return {router.submit(**kw): ref for kw, ref in reqs}


def _assert_parity(done, refs):
    for f in done:
        assert f.tokens == refs[f.req_id], (
            f"request {f.req_id} diverged:\n ref {refs[f.req_id]}\n "
            f"got {f.tokens}")
        assert f.finish_reason == "length"


# ---------------------------------------------------------------------------
# PT_KVPAGES codec
# ---------------------------------------------------------------------------


def test_kvpages_codec_roundtrip():
    rng = np.random.default_rng(0)
    arrays = [rng.standard_normal((2, 3, 8, 2, 4)).astype(np.float32),
              rng.integers(-128, 128, (2, 3, 8, 2, 4)).astype(np.int8)]
    meta = {"op": "import_pages", "records": [
        {"eng_rid": 7, "tokens": [[1, 2, 3, 4]], "kv_dtype": "int8"}]}
    out = decode_kv_pages(encode_kv_pages(meta, arrays))
    assert out["op"] == "import_pages"
    assert out["records"][0]["tokens"] == [[1, 2, 3, 4]]
    assert len(out["arrays"]) == 2
    for a, b in zip(arrays, out["arrays"]):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.array_equal(a, b)


def test_kvpages_codec_bf16_bit_exact():
    """bf16 page data (the serving compute dtype) must round-trip the
    wire bit-for-bit — the transfer parity oracle rests on it."""
    import ml_dtypes

    rng = np.random.default_rng(1)
    a = rng.standard_normal((1, 2, 8, 2, 4)).astype(ml_dtypes.bfloat16)
    out = decode_kv_pages(encode_kv_pages({"x": 1}, [a]))
    b = out["arrays"][0]
    assert b.dtype == a.dtype
    assert np.array_equal(a.view(np.uint16), b.view(np.uint16))


def test_kvpages_codec_torn_payload_fails_loud():
    payload = encode_kv_pages({"x": 1}, [np.zeros((4,), np.float32)])
    with pytest.raises(FrameProtocolError, match="length mismatch"):
        decode_kv_pages(payload[:-2] + b"....")  # longer than manifest
    # the SHORT tear direction must land in the frame-error taxonomy
    # too (not a bare numpy ValueError escaping FrameError handlers)
    with pytest.raises(FrameProtocolError, match="length mismatch"):
        decode_kv_pages(payload[:-3])            # shorter than manifest


# ---------------------------------------------------------------------------
# allocator: import_chain + transfer stats + audit
# ---------------------------------------------------------------------------


def test_import_chain_registers_cached_and_dedupes():
    al = PageAllocator(n_pages=8, page_size=4)
    chain = [(1, 2, 3, 4), (5, 6, 7, 8), (9, 10, 11, 12)]
    pairs = al.import_chain(chain)
    assert [new for _, new in pairs] == [True, True, True]
    assert al.stats()["pages_imported"] == 3
    assert al.stats()["cached"] == 3 and al.stats()["free"] == 5
    # re-import (a retargeted transfer resend): pure dedup, no new pages
    again = al.import_chain(chain)
    assert [new for _, new in again] == [False, False, False]
    assert [p for p, _ in again] == [p for p, _ in pairs]
    assert al.stats()["pages_imported"] == 3
    al.audit()   # the splice left a consistent free/cached/live world
    # available() unchanged by imports: cached pages stay reclaimable
    assert al.available() == 8


def test_import_chain_partial_under_pressure():
    """A pool with no free or evictable pages stops the import early —
    the partial chain is still a valid prefix, never a wrong one."""
    al = PageAllocator(n_pages=2, page_size=4)
    assert al.admit(0, tuple(range(6)), 2) is not None   # 2 pages live
    for _ in range(2):
        al.alloc(0)
    pairs = al.import_chain([(9, 9, 9, 1), (9, 9, 9, 2)])
    assert pairs == []   # everything live: nothing importable
    al.audit()
    al.free_seq(0)
    pairs = al.import_chain([(9, 9, 9, 1), (9, 9, 9, 2), (9, 9, 9, 3)])
    assert len(pairs) == 2   # 2 reclaimable pages -> 2-node prefix
    al.audit()


def test_import_chain_anchoring_blocks_unanchored_segment():
    """A streamed segment's pages are only valid UNDER the prefix that
    produced them: with its anchor present it splices at the right
    depth; with the anchor missing it must be REFUSED — registering it
    at the root would let a different prompt falsely match KV computed
    at other positions (a correctness bug, not a cache miss)."""
    al = PageAllocator(n_pages=8, page_size=4)
    al.import_chain([(1, 2, 3, 4)])
    pairs = al.import_chain([(1, 2, 3, 4), (5, 6, 7, 8)], n_prefix=1)
    assert [n for _, n in pairs] == [False, True]
    assert al.plan((1, 2, 3, 4, 5, 6, 7, 8, 9), 1).shared_len == 8
    # fresh allocator = the anchor segment never landed (evicted, or a
    # retargeted transfer): the unanchored segment imports NOTHING
    al2 = PageAllocator(n_pages=8, page_size=4)
    assert al2.import_chain([(1, 2, 3, 4), (5, 6, 7, 8)],
                            n_prefix=1) == []
    assert al2.plan((5, 6, 7, 8, 9), 1).shared_len == 0
    al2.audit()


def test_imported_chain_attach_and_cow_stats():
    """A prompt equal to an imported chain attaches it (full pages +
    the partial tail) and the first divergent write COWs — counted as
    an imported-chain COW, and audit() stays green through the splice,
    attach, COW and release."""
    al = PageAllocator(n_pages=8, page_size=4)
    prompt = tuple(range(12))
    chain = [prompt[0:4], prompt[4:8], prompt[8:12]]
    al.import_chain(chain)
    plan = al.admit(1, prompt, 4)
    assert plan is not None
    assert plan.shared_len == 11          # capped at len(prompt) - 1
    assert len(plan.shared_pages) == 2 and plan.partial is not None
    al.audit()
    assert al.stats()["imported_live"] == 3
    # the tail write lands INSIDE the partially attached imported page
    cow = al.ensure_writable(1, 2)
    assert cow is not None
    assert al.stats()["imported_cow_copies"] == 1
    al.audit()
    al.free_seq(1)
    al.audit()


# ---------------------------------------------------------------------------
# engine: prefill role exports, decode engine imports
# ---------------------------------------------------------------------------


def test_prefill_role_engine_exports_and_finishes(fix):
    model, reqs = fix
    eng = Engine(model, n_slots=2, max_seq_len=64, role="prefill",
                 registry=MetricsRegistry(), **EKW)
    kw, _ = next(r for r in reqs if len(r[0]["prompt"]) >= 32)
    rid = eng.submit(**kw)
    done = eng.drain()
    assert [f.finish_reason for f in done] == ["prefilled"]
    assert done[0].req_id == rid and done[0].n_out == 0
    recs = eng.take_page_exports()
    n_full = len(kw["prompt"]) // PAGE
    # each record's tokens are the FULL chain; arrays cover the new
    # pages past its n_prefix anchor count — together they tile the
    # prompt's full pages exactly once
    assert sum(len(r["tokens"]) - r["n_prefix"] for r in recs) == n_full
    flat = [t for r in recs
            for pg in r["tokens"][r["n_prefix"]:] for t in pg]
    assert flat == list(kw["prompt"][:n_full * PAGE])
    for r in recs:
        assert r["tokens"][:r["n_prefix"]] == [
            list(kw["prompt"][i * PAGE:(i + 1) * PAGE])
            for i in range(r["n_prefix"])]
    arr = recs[0]["arrays"][0]
    assert arr.shape[2] == PAGE        # (L, n, page_size, H_kv, D)
    eng._paged.audit(expect_empty=True)  # handoff released everything


def test_prefill_role_requires_paged():
    model = GPT(GPT_TINY, rngs=nnx.Rngs(0))
    with pytest.raises(ValueError, match="kv_impl='paged'"):
        Engine(model, n_slots=1, role="prefill",
               registry=MetricsRegistry())


def test_import_then_serve_is_bit_identical_and_skips_prefill(fix):
    """THE transfer exactness oracle at engine level: pages computed by
    a prefill-role engine, shipped through the codec, imported into a
    fresh decode engine — the handoff submit prefix-attaches them,
    computes only the sub-page tail, and the output is bit-identical
    to one-shot generation."""
    model, reqs = fix
    kw, ref = next(r for r in reqs if len(r[0]["prompt"]) >= 32)
    pre = Engine(model, n_slots=2, max_seq_len=64, role="prefill",
                 registry=MetricsRegistry(), **EKW)
    pre.submit(**kw)
    pre.drain()
    recs = pre.take_page_exports()

    from avenir_tpu.serve.frames import ARRAYS_PER_DTYPE

    dec = Engine(model, n_slots=2, max_seq_len=64,
                 registry=MetricsRegistry(), **EKW)
    for r in recs:
        n = ARRAYS_PER_DTYPE[r["kv_dtype"]]
        wrote = dec.import_kv_pages(r["tokens"], r["arrays"][:n],
                                    kv_dtype=r["kv_dtype"],
                                    n_prefix=r["n_prefix"])
        assert wrote == len(r["tokens"]) - r["n_prefix"]
    rid = dec.submit(**kw)
    done = {f.req_id: f for f in dec.drain()}
    assert done[rid].tokens == ref
    # the shared region was ATTACHED, not recomputed
    assert dec._paged.alloc.prefix_hits == 1
    n_full = len(kw["prompt"]) // PAGE
    assert dec._paged.shared_tokens >= n_full * PAGE - 1
    dec._paged.audit()


def test_import_dtype_mismatch_fails_loud(fix):
    model, _ = fix
    dec = Engine(model, n_slots=1, max_seq_len=64,
                 registry=MetricsRegistry(), **EKW)
    with pytest.raises(AssertionError, match="kv_dtype"):
        dec.import_kv_pages([[0] * PAGE], [None] * 4, kv_dtype="int8")


# ---------------------------------------------------------------------------
# router: class placement, handoff, failover
# ---------------------------------------------------------------------------


def test_router_disagg_parity_and_placement(fix):
    """Long prompts prefill on the prefill class and decode on the
    decode class; short prompts skip the handoff entirely; every
    stream is bit-identical to one-shot generation."""
    model, reqs = fix
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, n_prefill=1,
                    engine_kwargs=EKW)
    refs = _submit_all(router, reqs)
    done = router.drain()
    assert len(done) == len(reqs)
    _assert_parity(done, refs)
    # every terminal record comes from a DECODE replica (0 is prefill)
    assert all(f.replica != 0 for f in done)
    counters = reg.snapshot()["counters"]
    n_long = sum(1 for kw, _ in reqs if len(kw["prompt"]) >= CHUNK)
    assert counters["kv_transfers"] == n_long
    assert counters["kv_pages_exported"] >= n_long * (32 // PAGE)
    assert counters["kv_pages_imported"] == counters["kv_pages_exported"]
    assert counters["serve_requests"] == len(reqs)
    # the prefill replica's pool drained clean after its handoffs
    router.replicas[0].engine._paged.audit(expect_empty=True)


def test_router_disagg_mid_transfer_prefill_death_bit_parity(fix):
    """SIGKILL-shape oracle (inproc twin of the process chaos test): a
    prefill replica dies AFTER k of n pages shipped — the requests
    requeue, re-prefill from prompt+rng (on the decode class, the
    degraded-mode fallback), and every output is bit-identical."""
    model, reqs = fix
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, n_prefill=1,
                    engine_kwargs=EKW)
    refs = _submit_all(router, reqs)
    for _ in range(2):
        router.step()
    exported = reg.snapshot()["counters"].get("kv_pages_exported", 0)
    assert exported > 0, "the kill must land MID-transfer"
    router.kill_replica(0)
    done = router.drain()
    assert len(done) == len(reqs)
    _assert_parity(done, refs)
    assert reg.snapshot()["counters"]["serve_failovers"] >= 1
    assert not router._transfer, "transfer state leaked past failover"


def test_router_disagg_decode_target_death_retargets(fix):
    """The pinned decode target dies mid-stream: the retained export
    records re-ship to a fresh target at handoff — no recompute, no
    loss, bit-identical output."""
    model, reqs = fix
    reg = MetricsRegistry()
    router = Router(model, n_replicas=3, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, n_prefill=1,
                    engine_kwargs=EKW)
    longs = [r for r in reqs if len(r[0]["prompt"]) >= CHUNK]
    refs = _submit_all(router, longs[:1])
    router.step()   # first chunk computed, first pages pinned+shipped
    tr = next(iter(router._transfer.values()), None)
    assert tr is not None and tr["target"] is not None, (
        "no transfer pinned after the first step")
    router.kill_replica(tr["target"])
    done = router.drain()
    _assert_parity(done, refs)
    assert len(done) == 1


def test_router_disagg_falls_back_when_prefill_class_dead(fix):
    """No healthy prefill replica -> long prompts dispatch straight to
    the decode class (full local serving), nothing waits forever."""
    model, reqs = fix
    router = Router(model, n_replicas=2, n_slots=2, max_seq_len=64,
                    registry=MetricsRegistry(), seed=0, n_prefill=1,
                    engine_kwargs=EKW)
    router.kill_replica(0)   # the prefill class, before any work
    refs = _submit_all(router, reqs[:3])
    done = router.drain()
    _assert_parity(done, refs)
    assert all(f.replica == 1 for f in done)


# ---------------------------------------------------------------------------
# trace: the `transfer` segment partitions TTFT
# ---------------------------------------------------------------------------


def test_segments_transfer_and_relabel_on_death():
    evs = [
        {"rid": 1, "ev": "submit", "t": 0.0},
        {"rid": 1, "ev": "dispatch", "t": 1.0},        # prefill class
        {"rid": 1, "ev": "kv_transfer", "t": 2.0, "handoff": True},
        {"rid": 1, "ev": "dispatch", "t": 2.5},        # decode class
        {"rid": 1, "ev": "first_token", "t": 3.0},
        {"rid": 1, "ev": "finish", "t": 4.0, "reason": "length"},
    ]
    assert request_segments(evs) == [
        ("queue", 0.0, 1.0), ("prefill", 1.0, 2.0),
        ("transfer", 2.0, 2.5), ("prefill", 2.5, 3.0),
        ("decode", 3.0, 4.0)]
    a = ttft_attribution(evs)
    assert a == {"ttft_s": 3.0, "queue_s": 1.0, "prefill_s": 1.5,
                 "transfer_s": 0.5, "failover_s": 0.0}
    # a death AFTER handoff discards the WHOLE chain: prefill AND
    # transfer AND the post-handoff tail relabel as failover loss
    evs2 = evs[:5] + [
        {"rid": 1, "ev": "failover", "t": 3.5},
        {"rid": 1, "ev": "requeue", "t": 3.5},
        {"rid": 1, "ev": "dispatch", "t": 4.0},
        {"rid": 1, "ev": "first_token", "t": 5.0},
        {"rid": 1, "ev": "finish", "t": 6.0, "reason": "length"},
    ]
    a2 = ttft_attribution(evs2)
    assert a2["ttft_s"] == pytest.approx(5.0)
    assert a2["failover_s"] == pytest.approx(2.5)  # 1.0 -> 3.5 lost
    assert a2["transfer_s"] == 0.0                 # relabeled with it
    assert (a2["queue_s"] + a2["prefill_s"] + a2["transfer_s"]
            + a2["failover_s"]) == pytest.approx(a2["ttft_s"])


def test_segments_handoff_retry_is_not_failover():
    """A handoff-retry requeue (no healthy decode target at handoff
    time) kills no replica and DISCARDS no work — the retained chain
    prefix-hits on retry — so the attempt must NOT relabel as failover
    loss: failover_s in a report whose failover count is 0 would send
    an operator hunting for deaths that never happened. The partition
    still sums exactly."""
    evs = [
        {"rid": 1, "ev": "submit", "t": 0.0},
        {"rid": 1, "ev": "dispatch", "t": 1.0},
        {"rid": 1, "ev": "kv_transfer", "t": 2.0, "handoff": True},
        {"rid": 1, "ev": "requeue", "t": 2.5, "handoff_retry": True},
        {"rid": 1, "ev": "dispatch", "t": 3.0},
        {"rid": 1, "ev": "first_token", "t": 3.5},
        {"rid": 1, "ev": "finish", "t": 4.0, "reason": "length"},
    ]
    assert request_segments(evs) == [
        ("queue", 0.0, 1.0), ("prefill", 1.0, 2.0),
        ("transfer", 2.0, 2.5), ("queue", 2.5, 3.0),
        ("prefill", 3.0, 3.5), ("decode", 3.5, 4.0)]
    a = ttft_attribution(evs)
    assert a["failover_s"] == 0.0
    assert (a["queue_s"] + a["prefill_s"] + a["transfer_s"]
            + a["failover_s"]) == pytest.approx(a["ttft_s"])


def test_live_disagg_trace_partition_matches_measured_ttft(fix):
    """Property (ISSUE 13 satellite): on a traced disagg run, queue +
    prefill + transfer + failover == measured TTFT for EVERY request,
    and handed-off requests carry a kv_transfer handoff marker."""
    model, reqs = fix
    reg = MetricsRegistry()
    tr = Tracer(registry=reg)
    router = Router(model, n_replicas=3, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, n_prefill=1, tracer=tr,
                    engine_kwargs=EKW)
    refs = _submit_all(router, reqs)
    done = router.drain()
    _assert_parity(done, refs)
    n_handoff = 0
    for f in done:
        evs = tr.events_for(f.req_id)
        a = ttft_attribution(evs)
        assert a is not None
        assert (a["queue_s"] + a["prefill_s"] + a["transfer_s"]
                + a["failover_s"]) == pytest.approx(a["ttft_s"],
                                                    abs=1e-9)
        assert a["ttft_s"] * 1e3 == pytest.approx(f.ttft_ms, abs=1.0)
        if any(e["ev"] == "kv_transfer" and e.get("handoff")
               for e in evs):
            n_handoff += 1
    assert n_handoff == sum(1 for kw, _ in reqs
                            if len(kw["prompt"]) >= CHUNK)
    # trace_report surfaces the component + the handoff count
    from tools.trace_report import summarize_traces

    s = summarize_traces([e for e in tr.events()
                          if e.get("rid") is not None])
    assert s["n_handoff"] == n_handoff
    assert "transfer" in s["components_ms"]


# ---------------------------------------------------------------------------
# autoscaler: per-class scaling (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _fin(ttft_ms, *, tpot_ms=1.0, reason="length", n_out=4):
    from avenir_tpu.serve.engine import FinishedRequest

    f = FinishedRequest(req_id=0, tokens=[1], n_prompt=1, n_out=n_out,
                        finish_reason=reason, text=None,
                        ttft_ms=ttft_ms, tpot_ms=tpot_ms)
    f.priority = "interactive"
    return f


def _mk_disagg_scaler(model, clk, reg, **kw):
    from avenir_tpu.serve.autoscale import Autoscaler, SLOEngine

    router = Router(model, n_replicas=3, n_slots=2, max_seq_len=64,
                    registry=reg, seed=0, clock=clk, n_prefill=1,
                    engine_kwargs=EKW)
    slo = SLOEngine(slo_ttft_ms=100.0, slo_tpot_ms=50.0,
                    target_attainment=0.9, window_s=10.0, clock=clk,
                    registry=reg)
    kw.setdefault("min_replicas", 2)
    kw.setdefault("max_replicas", 5)
    kw.setdefault("up_stable_s", 2.0)
    kw.setdefault("down_stable_s", 5.0)
    kw.setdefault("cooldown_s", 4.0)
    kw.setdefault("prewarm", False)
    scaler = Autoscaler(router, slo, registry=reg, clock=clk,
                        echo=lambda *a: None, **kw)
    return router, scaler


def test_slo_engine_component_attainments():
    """TTFT misses point at the prefill class, TPOT misses at the
    decode class — the per-component verdicts a disagg fleet scales
    on. Sheds/timeouts miss BOTH components (an under-provisioned
    fleet, whichever class is short)."""
    from avenir_tpu.serve.autoscale import SLOEngine

    slo = SLOEngine(slo_ttft_ms=100.0, slo_tpot_ms=50.0, clock=_Clock(),
                    registry=MetricsRegistry())
    slo.observe([_fin(10.0), _fin(500.0),               # 1 ttft miss
                 _fin(10.0, tpot_ms=80.0),              # 1 tpot miss
                 _fin(None, reason="shed")])            # misses both
    comp = slo.component_attainments()
    assert comp["ttft"] == pytest.approx(2 / 4)
    assert comp["tpot"] == pytest.approx(2 / 4)
    empty = SLOEngine(slo_ttft_ms=100.0, slo_tpot_ms=50.0,
                      clock=_Clock(), registry=MetricsRegistry())
    assert empty.component_attainments() == {"ttft": None, "tpot": None}


def test_autoscaler_disagg_no_flapping_steady_load(fix):
    """The ISSUE 13 no-flapping pin, disagg form: steady in-SLO load on
    a split fleet whose utilization justifies its size -> ZERO scale
    decisions for EITHER class after warm-up."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_disagg_scaler(model, clk, reg, down_util=0.6)
    # three short (decode-class) requests keep 3 of the decode class's
    # 4 slots live: a one-replica-smaller fleet would sit at 0.75 >
    # down_util -> down blocked; burn 0 -> up never triggers
    rng = np.random.default_rng(21)
    for _ in range(3):
        router.submit([int(t) for t in rng.integers(0, 64, (5,))],
                      max_new_tokens=8)
    router.step()
    assert sum(len(r.engine._live) for r in router.replicas) == 3
    for _ in range(60):
        clk.t += 1.0
        scaler.observe([_fin(10.0)])
        scaler.poll()
    assert scaler.decisions == []
    assert router.fleet_size_by_class() == {"prefill": 1, "decode": 2}
    counters = reg.snapshot()["counters"]
    assert counters.get("scale_up", 0) == 0
    assert counters.get("scale_down", 0) == 0


def test_autoscaler_disagg_ttft_burn_grows_prefill_class(fix):
    """Sustained TTFT misses (queue+prefill latency) grow the PREFILL
    class; the decision's audit evidence carries the per-class sizes +
    component attainments that justified the choice."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_disagg_scaler(model, clk, reg)
    before = router.fleet_size_by_class()
    for _ in range(8):
        clk.t += 1.0
        scaler.observe([_fin(500.0)])        # TTFT miss, TPOT fine
        if scaler.poll():
            break
    after = router.fleet_size_by_class()
    assert after["prefill"] == before["prefill"] + 1
    assert after["decode"] == before["decode"]
    d = scaler.decisions[-1]
    assert d.action == "up" and d.evidence["class"] == "prefill"
    assert d.evidence["prefill_replicas"] == before["prefill"]
    assert d.evidence["attainment_ttft"] == pytest.approx(0.0)
    assert d.evidence["attainment_tpot"] == pytest.approx(1.0)


def test_autoscaler_disagg_tpot_burn_grows_decode_class(fix):
    """Sustained TPOT misses (decode bandwidth) grow the DECODE class —
    a full-lifecycle replica, so the fleet can always finish work."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_disagg_scaler(model, clk, reg)
    before = router.fleet_size_by_class()
    for _ in range(8):
        clk.t += 1.0
        scaler.observe([_fin(10.0, tpot_ms=80.0)])  # TPOT miss only
        if scaler.poll():
            break
    after = router.fleet_size_by_class()
    assert after["decode"] == before["decode"] + 1
    assert after["prefill"] == before["prefill"]
    d = scaler.decisions[-1]
    assert d.action == "up" and "class" not in d.evidence


def test_autoscaler_disagg_up_class_follows_queue_composition(fix):
    """A queue-wait (or TTFT-burn) scale-up must grow the class the
    QUEUED WORK is starved for: a short-prompt flood queues for decode
    slots — growing the prefill class would spend the budget on
    replicas that can never serve the backlog."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_disagg_scaler(model, clk, reg)
    rng = np.random.default_rng(31)
    # short-prompt flood: queued work is decode-class
    for _ in range(8):
        router.submit([int(t) for t in rng.integers(0, 64, (5,))],
                      max_new_tokens=4)
    assert scaler._queued_long_frac() == 0.0
    assert scaler._pick_up_class("queue_wait") == "both"
    scaler.observe([_fin(500.0)])            # TTFT burning, TPOT fine
    assert scaler._pick_up_class("burn_rate") == "both"
    router.drain()
    # long-prompt flood: queued work wants prefill-class capacity
    for _ in range(8):
        router.submit([int(t) for t in rng.integers(0, 64, (40,))],
                      max_new_tokens=4)
    assert scaler._queued_long_frac() == 1.0
    assert scaler._pick_up_class("queue_wait") == "prefill"
    assert scaler._pick_up_class("burn_rate") == "prefill"
    router.drain()
    # empty queue: queue_wait keeps its prefill default (time-to-first-
    # dispatch is a prefill-class resource when nothing names otherwise)
    assert scaler._queued_long_frac() is None
    assert scaler._pick_up_class("queue_wait") == "prefill"


def test_autoscaler_disagg_never_retires_a_class_to_zero(fix):
    """Scale-down on a surplus split fleet retires from the class with
    the safer SLO component and STOPS before either class empties — a
    fleet with prefill replicas but no decode class could prefill
    forever and finish nothing."""
    model, _ = fix
    clk = _Clock()
    reg = MetricsRegistry()
    router, scaler = _mk_disagg_scaler(model, clk, reg, min_replicas=1)
    for _ in range(40):
        clk.t += 1.0
        scaler.observe([_fin(10.0)])         # in SLO, fleet idle
        scaler.poll()
        router.step()                        # reap drained retirees
    by = router.fleet_size_by_class()
    assert by["prefill"] >= 1 and by["decode"] >= 1, (
        f"a class was retired to zero: {by}")


# ---------------------------------------------------------------------------
# serve_bench --disagg --smoke (the tier-1 CI path)
# ---------------------------------------------------------------------------


def test_disagg_bench_smoke_runs_in_ci():
    from tools.serve_bench import disagg_bench

    rc = disagg_bench({
        "smoke": "1", "smoke_splits": "1", "n_replicas": "2",
        "n_slots": "2", "block_size": "128", "max_seq_len": "96",
        "page_size": "8", "prefill_chunk": "16",
        "kv_budget_tokens": "512", "long_lo": "32", "long_hi": "40",
        "short_lo": "3", "short_hi": "8", "max_new_tokens": "3",
        "bench_requests": "6", "max_concurrency": "2", "n_layer": "1",
        "n_embd": "32", "vocab_size": "64",
    })
    assert rc == 0
