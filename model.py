"""PyTorch reference GPT-2 — the CUDA/DDP yardstick (SURVEY.md §2a R1).

The upstream reference (/root/reference, kutieme/avenir @ v0) is empty
(SURVEY.md §0), so this file realizes the north star in BASELINE.json:5,7
directly: a nanoGPT-style single-file decoder-only transformer whose loss
curve defines "correct" for the TPU backend (avenir_tpu/models/gpt.py is
its flax/nnx mirror and must match logits on identical weights).

Design notes (lineage semantics the TPU mirror must reproduce exactly):
  - learned positional embeddings added to token embeddings
  - pre-LayerNorm blocks, residual adds outside the sublayer
  - tanh-approximated GELU in the MLP (gelu_new — what GPT-2 was actually
    trained with, matching HF's activation_function="gelu_new"; also ~35%
    faster than erf on TPU VPUs, BASELINE.md "GELU" note)
  - weight tying between token embedding and lm_head
  - init: normal(0, 0.02) everywhere, residual projections scaled by
    1/sqrt(2 * n_layer), zero biases
  - AdamW with weight decay applied only to >=2-D params
"""

import math
import inspect
from dataclasses import dataclass

import torch
import torch.nn as nn
from torch.nn import functional as F


def strip_compile_prefix(state_dict):
    """Drop the '_orig_mod.' prefix torch.compile puts on state_dict keys so
    compiled and eager checkpoints interchange (used by train.py and
    sample.py)."""
    prefix = "_orig_mod."
    return {
        (k[len(prefix):] if k.startswith(prefix) else k): v
        for k, v in state_dict.items()
    }


@dataclass
class GPTConfig:
    block_size: int = 1024
    vocab_size: int = 50304  # GPT-2 50257 padded up to a multiple of 64
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    dropout: float = 0.0
    bias: bool = True  # True: biases in Linears and LayerNorms, like GPT-2


class LayerNorm(nn.Module):
    """LayerNorm with an optional bias (PyTorch's has no bias=False switch)."""

    def __init__(self, ndim, bias):
        super().__init__()
        self.weight = nn.Parameter(torch.ones(ndim))
        self.bias = nn.Parameter(torch.zeros(ndim)) if bias else None

    def forward(self, x):
        return F.layer_norm(x, self.weight.shape, self.weight, self.bias, 1e-5)


class CausalSelfAttention(nn.Module):
    def __init__(self, config):
        super().__init__()
        assert config.n_embd % config.n_head == 0
        self.c_attn = nn.Linear(config.n_embd, 3 * config.n_embd, bias=config.bias)
        self.c_proj = nn.Linear(config.n_embd, config.n_embd, bias=config.bias)
        self.attn_dropout = nn.Dropout(config.dropout)
        self.resid_dropout = nn.Dropout(config.dropout)
        self.n_head = config.n_head
        self.n_embd = config.n_embd
        self.dropout = config.dropout
        self.flash = hasattr(F, "scaled_dot_product_attention")
        if not self.flash:
            mask = torch.tril(torch.ones(config.block_size, config.block_size))
            # persistent=False: keep checkpoints portable between torch
            # builds with and without SDPA
            self.register_buffer(
                "causal_mask",
                mask.view(1, 1, config.block_size, config.block_size),
                persistent=False,
            )

    def forward(self, x):
        B, T, C = x.size()
        q, k, v = self.c_attn(x).split(self.n_embd, dim=2)
        # (B, n_head, T, head_dim)
        q = q.view(B, T, self.n_head, C // self.n_head).transpose(1, 2)
        k = k.view(B, T, self.n_head, C // self.n_head).transpose(1, 2)
        v = v.view(B, T, self.n_head, C // self.n_head).transpose(1, 2)
        if self.flash:
            y = F.scaled_dot_product_attention(
                q, k, v,
                attn_mask=None,
                dropout_p=self.dropout if self.training else 0.0,
                is_causal=True,
            )
        else:
            att = (q @ k.transpose(-2, -1)) * (1.0 / math.sqrt(k.size(-1)))
            att = att.masked_fill(self.causal_mask[:, :, :T, :T] == 0, float("-inf"))
            att = F.softmax(att, dim=-1)
            att = self.attn_dropout(att)
            y = att @ v
        y = y.transpose(1, 2).contiguous().view(B, T, C)
        return self.resid_dropout(self.c_proj(y))


class MLP(nn.Module):
    def __init__(self, config):
        super().__init__()
        self.c_fc = nn.Linear(config.n_embd, 4 * config.n_embd, bias=config.bias)
        self.c_proj = nn.Linear(4 * config.n_embd, config.n_embd, bias=config.bias)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        return self.dropout(
            self.c_proj(F.gelu(self.c_fc(x), approximate="tanh"))
        )


class Block(nn.Module):
    def __init__(self, config):
        super().__init__()
        self.ln_1 = LayerNorm(config.n_embd, bias=config.bias)
        self.attn = CausalSelfAttention(config)
        self.ln_2 = LayerNorm(config.n_embd, bias=config.bias)
        self.mlp = MLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        x = x + self.mlp(self.ln_2(x))
        return x


class GPT(nn.Module):
    def __init__(self, config):
        super().__init__()
        assert config.vocab_size is not None
        assert config.block_size is not None
        self.config = config

        self.transformer = nn.ModuleDict(
            dict(
                wte=nn.Embedding(config.vocab_size, config.n_embd),
                wpe=nn.Embedding(config.block_size, config.n_embd),
                drop=nn.Dropout(config.dropout),
                h=nn.ModuleList(Block(config) for _ in range(config.n_layer)),
                ln_f=LayerNorm(config.n_embd, bias=config.bias),
            )
        )
        self.lm_head = nn.Linear(config.n_embd, config.vocab_size, bias=False)
        # weight tying: the token embedding IS the output projection
        self.transformer.wte.weight = self.lm_head.weight

        self.apply(self._init_weights)
        # scaled init on residual projections, per GPT-2
        for pn, p in self.named_parameters():
            if pn.endswith("c_proj.weight"):
                torch.nn.init.normal_(p, mean=0.0, std=0.02 / math.sqrt(2 * config.n_layer))

    def _init_weights(self, module):
        if isinstance(module, nn.Linear):
            torch.nn.init.normal_(module.weight, mean=0.0, std=0.02)
            if module.bias is not None:
                torch.nn.init.zeros_(module.bias)
        elif isinstance(module, nn.Embedding):
            torch.nn.init.normal_(module.weight, mean=0.0, std=0.02)

    def get_num_params(self, non_embedding=True):
        n_params = sum(p.numel() for p in self.parameters())
        if non_embedding:
            n_params -= self.transformer.wpe.weight.numel()
        return n_params

    def forward(self, idx, targets=None):
        device = idx.device
        b, t = idx.size()
        assert t <= self.config.block_size, (
            f"sequence length {t} > block_size {self.config.block_size}"
        )
        pos = torch.arange(0, t, dtype=torch.long, device=device)

        tok_emb = self.transformer.wte(idx)
        pos_emb = self.transformer.wpe(pos)
        x = self.transformer.drop(tok_emb + pos_emb)
        for block in self.transformer.h:
            x = block(x)
        x = self.transformer.ln_f(x)

        if targets is not None:
            logits = self.lm_head(x)
            loss = F.cross_entropy(
                logits.view(-1, logits.size(-1)), targets.view(-1), ignore_index=-1
            )
        else:
            # inference: only the last position's logits are needed
            logits = self.lm_head(x[:, [-1], :])
            loss = None
        return logits, loss

    def crop_block_size(self, block_size):
        assert block_size <= self.config.block_size
        self.config.block_size = block_size
        self.transformer.wpe.weight = nn.Parameter(
            self.transformer.wpe.weight[:block_size]
        )
        for block in self.transformer.h:
            if hasattr(block.attn, "causal_mask"):
                block.attn.causal_mask = block.attn.causal_mask[:, :, :block_size, :block_size]

    @classmethod
    def from_pretrained(cls, model_type, override_args=None):
        """Load HF GPT-2 weights. Requires the transformers cache to be
        populated (this sandbox has no network egress)."""
        assert model_type in {"gpt2", "gpt2-medium", "gpt2-large", "gpt2-xl"}
        override_args = override_args or {}
        assert all(k == "dropout" for k in override_args)
        from transformers import GPT2LMHeadModel

        config_args = {
            "gpt2": dict(n_layer=12, n_head=12, n_embd=768),
            "gpt2-medium": dict(n_layer=24, n_head=16, n_embd=1024),
            "gpt2-large": dict(n_layer=36, n_head=20, n_embd=1280),
            "gpt2-xl": dict(n_layer=48, n_head=25, n_embd=1600),
        }[model_type]
        config_args["vocab_size"] = 50257
        config_args["block_size"] = 1024
        config_args["bias"] = True
        if "dropout" in override_args:
            config_args["dropout"] = override_args["dropout"]
        config = GPTConfig(**config_args)
        model = cls(config)
        sd = model.state_dict()
        sd_keys = [k for k in sd if not k.endswith(".attn.causal_mask")]

        model_hf = GPT2LMHeadModel.from_pretrained(model_type)
        sd_hf = model_hf.state_dict()
        sd_keys_hf = [
            k for k in sd_hf
            if not k.endswith(".attn.masked_bias") and not k.endswith(".attn.bias")
        ]
        # HF uses Conv1D (transposed) for these projections
        transposed = ["attn.c_attn.weight", "attn.c_proj.weight",
                      "mlp.c_fc.weight", "mlp.c_proj.weight"]
        assert len(sd_keys_hf) == len(sd_keys)
        for k in sd_keys_hf:
            if any(k.endswith(w) for w in transposed):
                assert sd_hf[k].shape[::-1] == sd[k].shape
                with torch.no_grad():
                    sd[k].copy_(sd_hf[k].t())
            else:
                assert sd_hf[k].shape == sd[k].shape
                with torch.no_grad():
                    sd[k].copy_(sd_hf[k])
        return model

    def configure_optimizers(self, weight_decay, learning_rate, betas, device_type):
        # decay all >=2-D params (matmul weights + embeddings); no decay on
        # biases and norm scales — the TPU optimizer mask must match this set
        param_dict = {pn: p for pn, p in self.named_parameters() if p.requires_grad}
        decay_params = [p for p in param_dict.values() if p.dim() >= 2]
        nodecay_params = [p for p in param_dict.values() if p.dim() < 2]
        optim_groups = [
            {"params": decay_params, "weight_decay": weight_decay},
            {"params": nodecay_params, "weight_decay": 0.0},
        ]
        fused_available = "fused" in inspect.signature(torch.optim.AdamW).parameters
        use_fused = fused_available and device_type == "cuda"
        optimizer = torch.optim.AdamW(
            optim_groups, lr=learning_rate, betas=betas,
            **({"fused": True} if use_fused else {}),
        )
        return optimizer

    def estimate_mfu(self, fwdbwd_per_iter, dt, peak_flops=312e12):
        """Model FLOPs utilisation vs a peak (default A100 bf16 312 TFLOP/s)."""
        N = self.get_num_params()
        cfg = self.config
        L, H, Q, T = cfg.n_layer, cfg.n_head, cfg.n_embd // cfg.n_head, cfg.block_size
        flops_per_token = 6 * N + 12 * L * H * Q * T
        flops_per_iter = flops_per_token * T * fwdbwd_per_iter
        return (flops_per_iter / dt) / peak_flops

    @torch.no_grad()
    def generate(self, idx, max_new_tokens, temperature=1.0, top_k=None):
        for _ in range(max_new_tokens):
            idx_cond = (
                idx if idx.size(1) <= self.config.block_size
                else idx[:, -self.config.block_size:]
            )
            logits, _ = self(idx_cond)
            logits = logits[:, -1, :] / temperature
            if top_k is not None:
                v, _ = torch.topk(logits, min(top_k, logits.size(-1)))
                logits[logits < v[:, [-1]]] = -float("inf")
            probs = F.softmax(logits, dim=-1)
            idx_next = torch.multinomial(probs, num_samples=1)
            idx = torch.cat((idx, idx_next), dim=1)
        return idx
