# ladder config 1 (BASELINE.json:7): char-level shakespeare, single device.
# Works on CPU for both backends; a miniature GPT.
out_dir = "out-shakespeare-char"
eval_interval = 250
eval_iters = 200
log_interval = 10
always_save_checkpoint = False

wandb_log = False
wandb_project = "shakespeare-char"
wandb_run_name = "mini-gpt"

dataset = "shakespeare_char"
gradient_accumulation_steps = 1
batch_size = 64
block_size = 256

n_layer = 6
n_head = 6
n_embd = 384
dropout = 0.2

learning_rate = 1e-3
max_iters = 5000
lr_decay_iters = 5000
min_lr = 1e-4
beta2 = 0.99
warmup_iters = 100
