# ladder config 4 (BASELINE.json:10): Llama-3 8B — RoPE + SwiGLU + RMSNorm
# (Pallas kernels) + GQA, FSDP over ICI. tpu backend only.
backend = "tpu"
model_type = "llama"
mesh_shape = "data:1,fsdp:-1"

dataset = "openwebtext"
batch_size = 4
block_size = 8192
gradient_accumulation_steps = 16

n_layer = 32
n_head = 32
n_kv_head = 8
n_embd = 4096
ffn_hidden = 14336
rope_theta = 500000.0

learning_rate = 3e-4
min_lr = 3e-5
max_iters = 500000
lr_decay_iters = 500000
weight_decay = 1e-1
remat = True
scan_layers = True
