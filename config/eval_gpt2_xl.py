# Evaluate HF GPT-2 XL (1.5B) on the configured dataset (SURVEY.md §2a R3
# "eval configs" — the reference's eval_gpt2* family): load hub weights
# through the bridge key-map, run estimate_loss, exit. Works on either
# backend; in the zero-egress sandbox the HF cache must be warm.
#   python train.py config/eval_gpt2_xl.py --backend=tpu
batch_size = 8
eval_iters = 500
eval_only = True
wandb_log = False
init_from = "gpt2-xl"
