# ladder config 3 (BASELINE.json:9): GPT-2 1.5B (gpt2-xl shape) under FSDP —
# params + optimizer state sharded on the 'fsdp' mesh axis; XLA SPMD emits
# all-gather at use and reduce-scatter of grads over ICI. tpu backend only.
backend = "tpu"
mesh_shape = "data:1,fsdp:-1"  # -1 → all remaining devices

dataset = "openwebtext"
batch_size = 8
block_size = 1024
gradient_accumulation_steps = 8

n_layer = 48
n_head = 25
n_embd = 1600

learning_rate = 2e-4
min_lr = 2e-5
max_iters = 300000
lr_decay_iters = 300000
weight_decay = 1e-1
remat = True
# measured on the 0.57B rung (BASELINE.md): 'dots' (save weight-matmul
# outputs, recompute elementwise only) is +8% over full recompute and the
# activations fit alongside the sharded state
remat_policy = "dots"
# scan-vs-loop measured head-to-head at the 0.57B on-chip rung (L=16,
# d=1600, B=4, v5e): loop 22.5k tok/s vs scan 21.1k (~6% — BASELINE.md
# "scan_layers" section), consistent with the 13% loop win at 124M. Loop
# costs one longer compile (one HLO copy per layer); for a 300k-iter run
# the steady-state 6% dominates. Flip to True if compile time ever
# matters more (e.g. rapid config iteration).
scan_layers = False
