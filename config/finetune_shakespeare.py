# Finetune GPT-2 on BPE-tokenized tiny-shakespeare (data/shakespeare/,
# SURVEY.md §2a R3/R4: the reference's finetuning config shape — short run,
# small LR, no decay, resume-or-hub init). Works on either backend:
#   python train.py config/finetune_shakespeare.py --backend=tpu
# In the zero-egress sandbox init_from="gpt2" needs a warm HF cache; train
# from scratch instead with --init_from=scratch.

out_dir = "out-shakespeare"
eval_interval = 5
eval_iters = 40
wandb_log = False
wandb_project = "shakespeare"
wandb_run_name = "ft-gpt2"

dataset = "shakespeare"
init_from = "gpt2"  # HF GPT-2 124M weights through the bridge key-map

# only save when val improves (finetuning overfits fast)
always_save_checkpoint = False

# 1 batch of 32 grad-accum steps ~ 32k tokens/iter
batch_size = 1
gradient_accumulation_steps = 32
max_iters = 20

# finetune at constant small LR
learning_rate = 3e-5
decay_lr = False
