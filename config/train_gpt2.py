# ladder config 2 (BASELINE.json:8): GPT-2 124M on OpenWebText,
# data-parallel. cuda: torchrun --nproc_per_node=8; tpu: --backend=tpu on a
# v4-8 ('data' mesh over all chips).
wandb_log = False
wandb_project = "owt"
wandb_run_name = "gpt2-124M"

dataset = "openwebtext"
# ~0.5M tokens per iteration = 12 micro-batch * 1024 block * 40 accum
batch_size = 12
block_size = 1024
gradient_accumulation_steps = 5 * 8

n_layer = 12
n_head = 12
n_embd = 768

max_iters = 600000
lr_decay_iters = 600000

# fused loss tail by default on the bench model (tpu backend): the
# (B, T, V) logits — the last big HBM sink at this shape — are never
# materialized (pallas kernel on TPU, blocked scan elsewhere;
# avenir_tpu/ops/fused_ce.py)
loss_impl = "auto"
eval_interval = 1000
eval_iters = 200
log_interval = 10
weight_decay = 1e-1
