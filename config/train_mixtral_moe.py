# ladder config 5 (BASELINE.json:11): Mixtral-8x7B-style MoE — top-2 router,
# expert-parallel all-to-all over ICI ('expert' mesh axis). tpu backend only.
backend = "tpu"
model_type = "mixtral"
mesh_shape = "data:1,expert:-1"

dataset = "openwebtext"
batch_size = 4
block_size = 4096
gradient_accumulation_steps = 16

n_layer = 32
n_head = 32
n_kv_head = 8
n_embd = 4096
ffn_hidden = 14336
rope_theta = 1000000.0
n_experts = 8
n_experts_per_tok = 2
capacity_factor = 1.25

learning_rate = 3e-4
min_lr = 3e-5
max_iters = 500000
lr_decay_iters = 500000
weight_decay = 1e-1
remat = True
scan_layers = True
