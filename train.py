"""Single-file trainer, dual backend (SURVEY.md §2a R2 + §2b T11).

One CLI entrypoint serves both stacks (BASELINE.json:5):

    # CUDA/CPU reference (PyTorch, DDP via torchrun):
    python train.py config/train_shakespeare_char.py
    torchrun --nproc_per_node=8 train.py config/train_gpt2.py

    # TPU-native backend (jax/XLA/Pallas) — same argv + one flag:
    python train.py config/train_shakespeare_char.py --backend=tpu

Import discipline: torch is imported only on the cuda path and jax only on
the tpu path, so a TPU pod with no GPU (and no torch install) runs end to
end (BASELINE.json:5). Config is the globals-override pattern shared by both
backends (configurator.py).
"""

import math
import os
import pickle
import time
from contextlib import nullcontext

import numpy as np

# Snapshot XLA_FLAGS before any jax machinery runs: some PJRT plugin
# environments consume the var during import, which would silently drop
# e.g. --xla_force_host_platform_device_count for CPU multi-device smokes.
_XLA_FLAGS_AT_START = os.environ.get("XLA_FLAGS")

# -----------------------------------------------------------------------------
# defaults — every key here is overridable via config file or --key=value
# I/O
out_dir = "out"
eval_interval = 2000
log_interval = 1
eval_iters = 200
eval_only = False
always_save_checkpoint = True
init_from = "scratch"  # 'scratch' | 'resume' | 'gpt2*'
# wandb logging
wandb_log = False
wandb_project = "avenir"
wandb_run_name = "run"
# data
dataset = "openwebtext"
gradient_accumulation_steps = 5 * 8
batch_size = 12  # micro-batch size per device
block_size = 1024
# streaming loader (jax path): blend corpora per-crop ('owt:0.7,code:0.3',
# names resolved next to `dataset`'s dir) and stage batches deeper than the
# default double buffer (>=2 keeps prefetch_depth x window batches ahead)
data_mix = ""
prefetch_depth = 1
# model
model_type = "gpt"  # 'gpt' | 'llama' | 'mixtral' (llama/mixtral are tpu-only)
n_layer = 12
n_head = 12
n_embd = 768
dropout = 0.0
bias = False
# llama/mixtral extras (ignored by gpt)
n_kv_head = 0  # 0 → = n_head (MHA); <n_head → GQA
ffn_hidden = 0  # 0 → derived (8/3 * n_embd rounded)
rope_theta = 10000.0
n_experts = 8
n_experts_per_tok = 2
capacity_factor = 1.25
router_aux_loss_coef = 0.02  # mixtral load-balancing aux loss (0 disables)
# adamw
learning_rate = 6e-4
max_iters = 600000
weight_decay = 1e-1
beta1 = 0.9
beta2 = 0.95
grad_clip = 1.0
# lr schedule
decay_lr = True
warmup_iters = 2000
lr_decay_iters = 600000
min_lr = 6e-5
# system
backend = "cuda"  # 'cuda' (torch ref incl. CPU) | 'tpu' (jax)
device = "cuda"  # torch device string for the cuda backend; 'cpu' works
dtype = "bfloat16"  # 'float32' | 'bfloat16' | 'float16'
# tpu backend: '' (follow dtype) | 'int8' — quantized hot matmuls (QKV/O,
# MLP/SwiGLU/experts, lm-head+CE) over a bf16 base with per-channel absmax
# scales and delayed backward scaling (avenir_tpu/ops/quant.py); which
# tensors participate is declared per tensor class in the unified
# partition+precision rules table (avenir_tpu/parallel/partition.py)
compute_dtype = ""
compile = True  # torch.compile on the cuda backend; documented no-op on tpu (always jit)
seed = 1337
debug_nans = False  # tpu: raise at the first NaN-producing op (jax_debug_nans)
# tpu-backend parallelism (ignored by cuda backend)
mesh_shape = ""  # e.g. "data:4,fsdp:2"; "" → all devices on 'data'
# multi-slice: per-axis DCN slice counts, e.g. "data:2" for 2 pods with
# mesh_shape the PER-SLICE shape; DCN rides outermost (parallel/mesh.py)
dcn_mesh_shape = ""
remat = False  # rematerialize blocks (activation checkpointing)
# remat recompute granularity: 'nothing' (save block inputs only) or 'dots'
# (save weight-matmul outputs; ~2x activation memory, skips most recompute)
remat_policy = "nothing"
# sequence parallelism when mesh has a context axis: "ring" (ppermute KV
# rotation; O(T/c) memory) or "ulysses" (head/sequence all-to-all; runs the
# single-device flash kernel per head subset) — tradeoffs in
# avenir_tpu/parallel/ulysses.py
context_parallel_impl = "ring"
scan_layers = False  # lax.scan over blocks (fast compiles for deep models)
# GPipe microbatches for a mesh with pipe:N > 1 (requires scan_layers;
# avenir_tpu/parallel/pipeline.py). 0 = auto (2x the pipe size)
pipeline_microbatches = 0
# pipeline schedule: 'gpipe' | 'remat' (reverse-tick stage-input stash)
# | '1f1b' (true interleaved 1F1B — loss tail inside the pipeline
# region, O(p) in-flight micros so M can grow well past 2p)
pipeline_schedule = "gpipe"
use_pallas = True  # pallas flash attention on TPU (auto-falls back off-TPU)
# hard attention-impl override ("pallas"/"xla"/...): unlike use_pallas's
# "auto" it never falls back silently — the CPU-harness SPMD tests force
# "pallas" (interpret mode) through the real mesh dispatch with this
attn_impl = ""
# loss tail (tpu backend): "" / "reference" = full (B, T, V) logits +
# cross_entropy_loss (the oracle); "blocked" = chunked lax.scan tail;
# "pallas" = fused TPU kernel; "auto" = pallas on TPU, blocked elsewhere.
# The fused impls never materialize the logits (avenir_tpu/ops/fused_ce.py,
# docs/PERFORMANCE.md "The loss tail")
loss_impl = ""
loss_chunk = 0  # blocked-tail time chunk in rows; 0 = default (128)
fused_adamw = False  # accepted+ignored: XLA-fused optax IS the hot path (BASELINE.md)
# optimizer steps per XLA dispatch in the tpu loop: 0 = auto (windows of up
# to 32 steps between eval/log/profile boundaries; identical trajectory,
# amortized dispatch latency — train/step.jit_windowed_train_step), 1 = one
# dispatch per step, N>1 = explicit window cap
dispatch_steps = 0
profile = False  # capture a jax.profiler trace window
# save checkpoints from a background thread (single-process only; training
# continues while the snapshot streams to ckpt.pt.part, atomically renamed)
async_checkpoint = False
# generation ring (tpu backend, docs/OPERATIONS.md "Failure / recovery"):
# keep the last K COMMITTED checkpoint generations under out_dir/ckpt-gens/
# (hard links — metadata-cheap). On resume, the newest artifact is verified
# against its manifest checksums and restore falls back generation by
# generation past corruption. 0 disables the ring (no fallback copies).
keep_checkpoints = 2
# accept silent replication of param dims the mesh doesn't divide (e.g. an
# unpadded char vocab on tensor:2); default is a hard error (fail-loud)
allow_unsharded_fallback = False
# structured run telemetry (avenir_tpu/obs, tpu backend): write
# out_dir/metrics.jsonl — per-iter loss/dt/MFU/tokens-per-sec records plus
# goodput counters (docs/OBSERVABILITY.md; tools/obs_report.py summarizes)
metrics_log = True
# stall watchdog floor in seconds; 0 disables. When >0, a daemon thread
# warns (and dumps Python stacks) if no training window completes within
# max(watchdog_secs, 10x median window time) — hung pod collectives freeze
# silently otherwise (avenir_tpu/obs/watchdog.py)
watchdog_secs = 0.0
# watchdog escalation: after N CONSECUTIVE stall warnings with no progress,
# dump stacks one last time and exit non-zero (code 70) so a pod supervisor
# restarts the job from the last committed checkpoint. 0 = warn forever
watchdog_fatal_count = 0
# fleet health engine (avenir_tpu/obs/anomaly.py, docs/OBSERVABILITY.md
# "Anomaly detection"): detect GRADUAL degradation — step-time drift, io
# retry rate — before the watchdog's total-stall tier can. Each anomaly is
# a counter + JSONL record + trace event + flight-recorder dump. Off by
# default (the disabled path is one None check per window).
anomaly_detect = False
# series window width (seconds) for the anomaly detectors' ring aggregates
anomaly_window_s = 1.0
# -----------------------------------------------------------------------------
from configurator import configure

config_keys = [
    k for k, v in globals().items()
    if not k.startswith("_") and isinstance(v, (int, float, bool, str))
]
configure(globals())
config = {k: globals()[k] for k in config_keys}
# -----------------------------------------------------------------------------


def train_cuda():
    """PyTorch reference trainer (R2): DDP/NCCL data parallelism, AMP,
    grad accumulation, cosine LR, checkpoint save/resume."""
    import torch
    from torch.nn.parallel import DistributedDataParallel as DDP
    from torch.distributed import destroy_process_group, init_process_group

    from model import GPT, GPTConfig

    assert model_type == "gpt", "cuda backend implements the GPT-2 reference only"

    ddp = int(os.environ.get("RANK", -1)) != -1
    if ddp:
        init_process_group(backend="nccl" if device.startswith("cuda") else "gloo")
        ddp_rank = int(os.environ["RANK"])
        ddp_local_rank = int(os.environ["LOCAL_RANK"])
        ddp_world_size = int(os.environ["WORLD_SIZE"])
        dev = f"cuda:{ddp_local_rank}" if device.startswith("cuda") else device
        if device.startswith("cuda"):
            torch.cuda.set_device(dev)
        master_process = ddp_rank == 0
        seed_offset = ddp_rank
        assert gradient_accumulation_steps % ddp_world_size == 0
        grad_accum = gradient_accumulation_steps // ddp_world_size
    else:
        master_process = True
        seed_offset = 0
        ddp_world_size = 1
        grad_accum = gradient_accumulation_steps
        dev = device

    tokens_per_iter = grad_accum * ddp_world_size * batch_size * block_size
    if master_process:
        print(f"tokens per iteration: {tokens_per_iter:,}")
        os.makedirs(out_dir, exist_ok=True)
    torch.manual_seed(seed + seed_offset)
    torch.backends.cuda.matmul.allow_tf32 = True
    torch.backends.cudnn.allow_tf32 = True
    device_type = "cuda" if "cuda" in dev else "cpu"
    ptdtype = {
        "float32": torch.float32, "bfloat16": torch.bfloat16, "float16": torch.float16
    }[dtype]
    amp_ctx = (
        nullcontext() if device_type == "cpu"
        else torch.amp.autocast(device_type=device_type, dtype=ptdtype)
    )

    data_dir = dataset if os.path.isabs(dataset) else os.path.join("data", dataset)

    def get_batch(split):
        # recreate np.memmap every call to avoid the memory-leak footgun
        arr = np.memmap(
            os.path.join(data_dir, f"{split}.bin"), dtype=np.uint16, mode="r"
        )
        ix = torch.randint(len(arr) - block_size, (batch_size,))
        x = torch.stack(
            [torch.from_numpy(arr[i : i + block_size].astype(np.int64)) for i in ix]
        )
        y = torch.stack(
            [torch.from_numpy(arr[i + 1 : i + 1 + block_size].astype(np.int64)) for i in ix]
        )
        if device_type == "cuda":
            x = x.pin_memory().to(dev, non_blocking=True)
            y = y.pin_memory().to(dev, non_blocking=True)
        else:
            x, y = x.to(dev), y.to(dev)
        return x, y

    iter_num = 0
    best_val_loss = 1e9

    meta_path = os.path.join(data_dir, "meta.pkl")
    meta_vocab_size = None
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta_vocab_size = pickle.load(f)["vocab_size"]
        if master_process:
            print(f"found vocab_size = {meta_vocab_size} (from {meta_path})")

    model_args = dict(
        n_layer=n_layer, n_head=n_head, n_embd=n_embd, block_size=block_size,
        bias=bias, vocab_size=None, dropout=dropout,
    )
    if init_from == "scratch":
        model_args["vocab_size"] = meta_vocab_size if meta_vocab_size else 50304
        model = GPT(GPTConfig(**model_args))
    elif init_from == "resume":
        ckpt_path = os.path.join(out_dir, "ckpt.pt")
        checkpoint = torch.load(ckpt_path, map_location=dev, weights_only=False)
        for k in ("n_layer", "n_head", "n_embd", "block_size", "bias", "vocab_size"):
            model_args[k] = checkpoint["model_args"][k]
        model = GPT(GPTConfig(**model_args))
        from model import strip_compile_prefix

        model.load_state_dict(strip_compile_prefix(checkpoint["model"]))
        iter_num = checkpoint["iter_num"]
        best_val_loss = checkpoint["best_val_loss"]
    elif init_from.startswith("gpt2"):
        model = GPT.from_pretrained(init_from, dict(dropout=dropout))
        for k in ("n_layer", "n_head", "n_embd", "block_size", "bias", "vocab_size"):
            model_args[k] = getattr(model.config, k)
    else:
        raise ValueError(f"unknown init_from {init_from!r}")

    if block_size < model.config.block_size:
        model.crop_block_size(block_size)
        model_args["block_size"] = block_size
    model.to(dev)

    scaler = torch.amp.GradScaler(device_type, enabled=(dtype == "float16"))
    optimizer = model.configure_optimizers(
        weight_decay, learning_rate, (beta1, beta2), device_type
    )
    if init_from == "resume":
        optimizer.load_state_dict(checkpoint["optimizer"])
    checkpoint = None

    if compile and hasattr(torch, "compile") and device_type == "cuda":
        model = torch.compile(model)
    if ddp:
        model = DDP(model, device_ids=[ddp_local_rank] if device_type == "cuda" else None)
    raw_model = model.module if ddp else model

    @torch.no_grad()
    def estimate_loss():
        out = {}
        model.eval()
        for split in ("train", "val"):
            losses = torch.zeros(eval_iters)
            for k in range(eval_iters):
                X, Y = get_batch(split)
                with amp_ctx:
                    _, loss = model(X, Y)
                losses[k] = loss.item()
            out[split] = losses.mean()
        model.train()
        return out

    def get_lr(it):
        if it < warmup_iters:
            return learning_rate * (it + 1) / (warmup_iters + 1)
        if it > lr_decay_iters:
            return min_lr
        ratio = (it - warmup_iters) / (lr_decay_iters - warmup_iters)
        coeff = 0.5 * (1.0 + math.cos(math.pi * ratio))
        return min_lr + coeff * (learning_rate - min_lr)

    if wandb_log and master_process:
        import wandb

        wandb.init(project=wandb_project, name=wandb_run_name, config=config)

    X, Y = get_batch("train")
    t0 = time.time()
    local_iter_num = 0
    running_mfu = -1.0
    while True:
        lr = get_lr(iter_num) if decay_lr else learning_rate
        for pg in optimizer.param_groups:
            pg["lr"] = lr

        if iter_num % eval_interval == 0 and master_process:
            losses = estimate_loss()
            print(
                f"step {iter_num}: train loss {losses['train']:.4f}, "
                f"val loss {losses['val']:.4f}"
            )
            if wandb_log:
                import wandb

                wandb.log({
                    "iter": iter_num, "train/loss": losses["train"],
                    "val/loss": losses["val"], "lr": lr, "mfu": running_mfu * 100,
                })
            if losses["val"] < best_val_loss or always_save_checkpoint:
                best_val_loss = min(best_val_loss, losses["val"])
                if iter_num > 0:
                    ckpt = {
                        "model": raw_model.state_dict(),
                        "optimizer": optimizer.state_dict(),
                        "model_args": model_args,
                        "iter_num": iter_num,
                        "best_val_loss": best_val_loss,
                        "config": config,
                    }
                    print(f"saving checkpoint to {out_dir}")
                    torch.save(ckpt, os.path.join(out_dir, "ckpt.pt"))
        if iter_num == 0 and eval_only:
            break

        for micro_step in range(grad_accum):
            if ddp:
                # only sync grads on the last micro step
                model.require_backward_grad_sync = micro_step == grad_accum - 1
            with amp_ctx:
                _, loss = model(X, Y)
                loss = loss / grad_accum
            X, Y = get_batch("train")  # prefetch while device is busy
            scaler.scale(loss).backward()
        if grad_clip != 0.0:
            scaler.unscale_(optimizer)
            torch.nn.utils.clip_grad_norm_(model.parameters(), grad_clip)
        scaler.step(optimizer)
        scaler.update()
        optimizer.zero_grad(set_to_none=True)

        t1 = time.time()
        dt = t1 - t0
        t0 = t1
        if iter_num % log_interval == 0 and master_process:
            lossf = loss.item() * grad_accum
            if local_iter_num >= 5:
                mfu = raw_model.estimate_mfu(batch_size * grad_accum, dt)
                running_mfu = mfu if running_mfu == -1.0 else 0.9 * running_mfu + 0.1 * mfu
            print(
                f"iter {iter_num}: loss {lossf:.4f}, time {dt * 1000:.2f}ms, "
                f"mfu {running_mfu * 100:.2f}%"
            )
        iter_num += 1
        local_iter_num += 1
        if iter_num > max_iters:
            break

    if ddp:
        destroy_process_group()


def train_tpu():
    """TPU-native trainer (T5 + friends): delegates to avenir_tpu with the
    same config namespace. jax is imported lazily here so the cuda path never
    needs it (and vice versa)."""
    if _XLA_FLAGS_AT_START and os.environ.get("XLA_FLAGS") != _XLA_FLAGS_AT_START:
        os.environ["XLA_FLAGS"] = _XLA_FLAGS_AT_START
    from avenir_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    from avenir_tpu.train.loop import run_training

    run_training(config)


if __name__ == "__main__":
    if backend == "tpu":
        train_tpu()
    elif backend == "cuda":
        train_cuda()
    else:
        raise ValueError(f"unknown backend {backend!r}")
