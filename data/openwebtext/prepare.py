"""Prepare OpenWebText with the GPT-2 BPE tokenizer (SURVEY.md §2a R4;
ladder config 2, BASELINE.json:8).

Streams the HF `openwebtext` dataset through tiktoken's GPT-2 BPE into
train.bin / val.bin uint16 memmaps. Needs network + disk; in the zero-egress
sandbox use --synthetic to produce a small GPT-2-BPE-compatible stand-in
(ids < 50257) so the training path is exercisable end to end.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

NUM_PROC = 8


def prepare_synthetic(here: str, n_tokens: int = 2_000_000, seed: int = 1337):
    from avenir_tpu.utils.corpus import synthetic_corpus

    try:
        import tiktoken

        enc = tiktoken.get_encoding("gpt2")
        text = synthetic_corpus(n_chars=n_tokens * 4, seed=seed)
        ids = np.array(enc.encode_ordinary(text), dtype=np.uint16)
    except Exception:
        # no tiktoken cache offline: Zipf-distributed ids stand in for BPE
        rng = np.random.default_rng(seed)
        ranks = np.arange(1, 50258, dtype=np.float64)
        probs = (1.0 / ranks) / (1.0 / ranks).sum()
        ids = rng.choice(50257, size=n_tokens, p=probs).astype(np.uint16)
    # keep val comfortably larger than any block_size (the full prep uses
    # 0.0005, but on a small synthetic corpus that is < 1024 tokens and
    # get_batch('val') would underflow)
    n = int(0.95 * len(ids))
    ids[:n].tofile(os.path.join(here, "train.bin"))
    ids[n:].tofile(os.path.join(here, "val.bin"))
    print(f"train tokens={n:,}, val tokens={len(ids) - n:,}")


def prepare_full(here: str):
    import tiktoken
    from datasets import load_dataset  # pip: datasets (not in sandbox image)

    enc = tiktoken.get_encoding("gpt2")
    dataset = load_dataset("openwebtext", num_proc=NUM_PROC)
    split = dataset["train"].train_test_split(test_size=0.0005, seed=2357, shuffle=True)
    split["val"] = split.pop("test")

    def process(example):
        ids = enc.encode_ordinary(example["text"])
        ids.append(enc.eot_token)
        return {"ids": ids, "len": len(ids)}

    tokenized = split.map(process, remove_columns=["text"], num_proc=NUM_PROC)
    for name, dset in tokenized.items():
        arr_len = int(np.sum(dset["len"], dtype=np.uint64))
        arr = np.memmap(
            os.path.join(here, f"{name}.bin"), dtype=np.uint16, mode="w+", shape=(arr_len,)
        )
        idx = 0
        for batch in dset.iter(batch_size=1024):
            for ids in batch["ids"]:
                arr[idx : idx + len(ids)] = ids
                idx += len(ids)
        arr.flush()


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    if "--synthetic" in sys.argv:
        prepare_synthetic(here)
    else:
        prepare_full(here)
