"""Prepare tiny-shakespeare with the GPT-2 BPE tokenizer (SURVEY.md §2a
R4 — the third reference prep script, completing the set next to
shakespeare_char's char-level and openwebtext's full-corpus preps).

Downloads the tinyshakespeare text and encodes it with tiktoken's GPT-2
BPE into train.bin / val.bin uint16 memmaps (no meta.pkl: BPE datasets
use the default 50304-padded GPT-2 vocab, same contract as openwebtext).
In the zero-egress sandbox, --synthetic (or any download/tokenizer
failure) produces a GPT-2-BPE-compatible stand-in so the training path
runs end to end.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

URL = ("https://raw.githubusercontent.com/karpathy/char-rnn/master/data/"
       "tinyshakespeare/input.txt")


def _encode_or_zipf(text, seed=1337, n_tokens=400_000):
    """(ids, is_bpe): GPT-2 BPE ids for `text`, or (offline, no tiktoken
    cache) a Zipf-distributed id stream of comparable size — same
    fallback shape as openwebtext's synthetic prep."""
    try:
        import tiktoken

        enc = tiktoken.get_encoding("gpt2")
        return np.array(enc.encode_ordinary(text), dtype=np.uint16), True
    except Exception:
        rng = np.random.default_rng(seed)
        ranks = np.arange(1, 50258, dtype=np.float64)
        probs = (1.0 / ranks) / (1.0 / ranks).sum()
        ids = rng.choice(50257, size=n_tokens, p=probs).astype(np.uint16)
        return ids, False


def prepare(here: str, synthetic: bool = False):
    input_path = os.path.join(here, "input.txt")
    text = None
    if not synthetic:
        if not os.path.exists(input_path):
            try:
                import requests

                with open(input_path, "w") as f:
                    f.write(requests.get(URL, timeout=30).text)
            except Exception as e:
                print(f"download failed ({e}); falling back to synthetic")
        if os.path.exists(input_path):
            with open(input_path) as f:
                text = f.read()
    text_is_synthetic = text is None
    if text is None:
        from avenir_tpu.utils.corpus import synthetic_corpus

        text = synthetic_corpus(n_chars=1_600_000, seed=1337)

    ids, ids_are_bpe = _encode_or_zipf(text)
    # 90/10 split (the reference's ratio for this corpus); val stays
    # comfortably larger than any block_size
    n = int(0.9 * len(ids))
    ids[:n].tofile(os.path.join(here, "train.bin"))
    ids[n:].tofile(os.path.join(here, "val.bin"))
    # record which variant produced the committed memmaps — in the
    # zero-egress sandbox the bins are usually the synthetic fallback,
    # and nothing else distinguishes them from real BPE output
    tok = "tiktoken-gpt2-bpe" if ids_are_bpe else "zipf-fallback"
    if not ids_are_bpe:
        # the Zipf fallback ignores the text entirely: the bins derive
        # from no corpus, real or synthetic
        corpus = "none (zipf ids; text unused)"
    else:
        corpus = "synthetic" if text_is_synthetic else "tinyshakespeare"
    with open(os.path.join(here, "PROVENANCE.txt"), "w") as f:
        f.write(f"corpus={corpus}\ntokenizer={tok}\n"
                f"train_tokens={n}\nval_tokens={len(ids) - n}\n")
    print(f"train tokens={n:,}, val tokens={len(ids) - n:,} "
          f"({corpus}/{tok})")


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    prepare(here, synthetic="--synthetic" in sys.argv)
