"""Prepare the char-level shakespeare dataset (SURVEY.md §2a R4).

Writes train.bin / val.bin (uint16 char ids) + meta.pkl into this directory.
Source text, in order of preference:
  1. ./input.txt if present (drop the real tinyshakespeare here),
  2. download from the public URL (fails in this zero-egress sandbox),
  3. deterministic synthetic corpus (avenir_tpu.utils.corpus) as fallback.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from avenir_tpu.utils.corpus import synthetic_corpus, write_char_dataset

DATA_URL = "https://raw.githubusercontent.com/karpathy/char-rnn/master/data/tinyshakespeare/input.txt"


def load_text() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    input_path = os.path.join(here, "input.txt")
    if os.path.exists(input_path):
        with open(input_path, encoding="utf-8") as f:
            return f.read()
    try:
        import urllib.request

        with urllib.request.urlopen(DATA_URL, timeout=10) as r:
            text = r.read().decode("utf-8")
        with open(input_path, "w", encoding="utf-8") as f:
            f.write(text)
        return text
    except Exception as e:  # no network in sandbox
        print(f"[prepare] download failed ({e}); using synthetic corpus")
        return synthetic_corpus(n_chars=1_000_000, seed=1337)


if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    text = load_text()
    meta = write_char_dataset(here, text)
    print(f"vocab_size={meta['vocab_size']}, chars={len(text):,}")
