"""Sample from a trained checkpoint, dual backend (SURVEY.md §2a R5, §3.5).

    python sample.py --out_dir=out-shakespeare-char
    python sample.py --out_dir=out-shakespeare-char --backend=tpu
"""

import os
import pickle

# ----------------------------------------------------------------------------
init_from = "resume"  # 'resume' (from out_dir) or 'gpt2*' (HF weights)
out_dir = "out"
start = "\n"  # prompt; "FILE:path" reads the prompt from a file
num_samples = 3
max_new_tokens = 500
temperature = 0.8
top_k = 200
seed = 1337
backend = "cuda"
device = "cpu"
# ----------------------------------------------------------------------------
from configurator import configure

configure(globals())

if start.startswith("FILE:"):
    with open(start[5:], encoding="utf-8") as f:
        start = f.read()


def load_codec():
    """Char-level codec from the dataset meta.pkl when available, else GPT-2 BPE."""
    meta_path = None
    ckpt_config = globals().get("_ckpt_config")
    if ckpt_config and "dataset" in ckpt_config:
        ds = ckpt_config["dataset"]  # name under data/ or an absolute path
        base = ds if os.path.isabs(ds) else os.path.join("data", ds)
        cand = os.path.join(base, "meta.pkl")
        if os.path.exists(cand):
            meta_path = cand
    if meta_path:
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
        stoi, itos = meta["stoi"], meta["itos"]
        return (lambda s: [stoi[c] for c in s]), (lambda t: "".join(itos[i] for i in t))
    import tiktoken

    enc = tiktoken.get_encoding("gpt2")
    return (
        lambda s: enc.encode(s, allowed_special={"<|endoftext|>"}),
        lambda t: enc.decode(t),
    )


def sample_cuda():
    import torch

    from model import GPT, GPTConfig, strip_compile_prefix

    torch.manual_seed(seed)
    if init_from == "resume":
        ckpt = torch.load(
            os.path.join(out_dir, "ckpt.pt"), map_location=device, weights_only=False
        )
        globals()["_ckpt_config"] = ckpt.get("config", {})
        model = GPT(GPTConfig(**{
            k: ckpt["model_args"][k]
            for k in ("n_layer", "n_head", "n_embd", "block_size", "bias", "vocab_size")
        }))
        model.load_state_dict(strip_compile_prefix(ckpt["model"]))
    else:
        model = GPT.from_pretrained(init_from, dict(dropout=0.0))
    model.eval().to(device)
    encode, decode = load_codec()
    x = torch.tensor(encode(start), dtype=torch.long, device=device)[None, ...]
    with torch.no_grad():
        for _ in range(num_samples):
            y = model.generate(x, max_new_tokens, temperature=temperature, top_k=top_k)
            print(decode(y[0].tolist()))
            print("---------------")


def sample_tpu():
    from avenir_tpu.platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    from avenir_tpu.sampling import run_sampling

    run_sampling(
        out_dir=out_dir, init_from=init_from, start=start, num_samples=num_samples,
        max_new_tokens=max_new_tokens, temperature=temperature, top_k=top_k,
        seed=seed, set_ckpt_config=lambda c: globals().__setitem__("_ckpt_config", c),
        load_codec=load_codec,
    )


if __name__ == "__main__":
    if backend == "tpu":
        sample_tpu()
    else:
        sample_cuda()
