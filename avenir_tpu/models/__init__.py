"""avenir_tpu.models — flax nnx model zoo (SURVEY.md §1 L3, §2b T1/T9/T10).

Each model mirrors the reference semantics (model.py for GPT-2; public
Llama-3 / Mixtral architecture for the others) but is written TPU-first:
params born sharded via partition rules, attention through the ops layer's
Pallas/XLA dispatch, fp32 master params with configurable compute dtype.
"""

from avenir_tpu.models.gpt import GPT, GPTConfig
