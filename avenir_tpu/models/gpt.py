"""GPT-2 in flax nnx — the TPU mirror of the torch reference (SURVEY.md §2b
T1; BASELINE.json:5 "flax/nnx mirror").

Semantics are pinned to model.py (the torch yardstick) so loss curves
overlay — that IS the acceptance metric (BASELINE.json:2):
  - learned positional embeddings added to token embeddings (model.py:181-183)
  - pre-LayerNorm blocks, eps=1e-5, optional bias (model.py:50-59)
  - tanh-approximated GELU, gelu_new (model.py:116-119)
  - weight tying: logits = x @ wte.T, no separate lm_head param
    (model.py:149-151)
  - init: normal(0, 0.02) everywhere, residual projections scaled to
    0.02/sqrt(2·n_layer), zero biases (model.py:153-165)
  - cross-entropy with ignore_index=-1 (model.py:190-192)

TPU-first deltas (not in the torch file):
  - master params fp32, compute dtype configurable (bf16 on TPU) — the jax
    equivalent of autocast: matmuls in bf16, norms and loss in fp32
  - attention through ops.causal_attention (Pallas flash kernel on TPU)
  - optional per-block rematerialisation (activation checkpointing)

Weight layout note for the checkpoint bridge (SURVEY.md §3.4): nnx Linear
kernels are (in, out); torch Linear weights are (out, in) — transposed.
"""

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.models.common import (
    cross_entropy_loss,
    head_major_merge,
    head_major_project,
    quant_linear,
    quant_policies,
    resolve_dtype,
    resolve_remat_policy,
    scan_layer_stack,
    stacked_layers,
    transformer_flops_per_token,
    w_dtype_for,
)
from avenir_tpu.ops import causal_attention


@dataclass(frozen=True)
class GPTConfig:
    block_size: int = 1024
    vocab_size: int = 50304  # GPT-2 50257 padded up to a multiple of 64
    n_layer: int = 12
    n_head: int = 12
    n_embd: int = 768
    dropout: float = 0.0
    bias: bool = True
    # --- TPU-side knobs (no torch counterpart) ---
    # 'bfloat16' on TPU; params stay fp32. 'int8' = bf16 base arithmetic
    # with the rules-table-eligible hot matmuls (QKV/O, MLP, lm-head+CE)
    # quantized per-channel int8 (ops/quant.py; policy per tensor class
    # in parallel/partition.py's unified rules table).
    compute_dtype: str = "float32"
    attn_impl: str = "auto"  # 'auto' | 'pallas' | 'xla'
    remat: bool = False  # rematerialize each block on the backward pass
    # what remat saves: 'nothing' (full recompute) or 'dots' (weight-matmul
    # outputs saved — models/common.py resolve_remat_policy, BASELINE.md)
    remat_policy: str = "nothing"
    # lax.scan over the L homogeneous blocks: one trace regardless of depth
    # (compile time for the 48-layer 1.5B config, SURVEY.md §3.3). Params
    # are stored stacked (L, ...) under `h_scan`; checkpoint format and
    # partition rules are unchanged (bridge splits/stacks per layer).
    scan_layers: bool = False
    # GPipe microbatches when the mesh has a pipe axis > 1 (requires
    # scan_layers; parallel/pipeline.py). 0 = auto (2x the pipe size).
    pipeline_microbatches: int = 0
    # pipeline schedule: 'gpipe' (autodiff through the tick scan),
    # 'remat' (reverse-tick stage-input stash), or '1f1b' (true
    # interleaved 1F1B — the loss tail moves INSIDE the pipeline region
    # and runs the chunked 'blocked' CE per micro on the last stage,
    # whatever loss_impl says; loss_chunk is honored). parallel/pipeline.py
    pipeline_schedule: str = "gpipe"
    # loss tail: 'reference' (full (B, T, V) logits + cross_entropy_loss),
    # 'blocked' (chunked lax.scan tail), 'pallas' (fused TPU kernel), or
    # 'auto' (pallas on TPU, blocked elsewhere) — ops/fused_ce.py. The
    # fused impls never materialize the logits, so __call__ returns
    # logits=None when they run with targets.
    loss_impl: str = "reference"
    # time-chunk of the blocked loss tail; 0 = default (128 rows)
    loss_chunk: int = 0


class CausalSelfAttention(nnx.Module):
    def __init__(self, config: GPTConfig, *, rngs: nnx.Rngs):
        assert config.n_embd % config.n_head == 0
        cdtype = resolve_dtype(config.compute_dtype)
        init = nnx.initializers.normal(stddev=0.02)
        # GPT-2 scaled init on the residual projection (model.py:155-157)
        proj_init = nnx.initializers.normal(
            stddev=0.02 / math.sqrt(2 * config.n_layer)
        )
        zeros = nnx.initializers.zeros_init()
        self.c_attn = nnx.Linear(
            config.n_embd, 3 * config.n_embd, use_bias=config.bias,
            kernel_init=init, bias_init=zeros,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.c_proj = nnx.Linear(
            config.n_embd, config.n_embd, use_bias=config.bias,
            kernel_init=proj_init, bias_init=zeros,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.resid_dropout = nnx.Dropout(config.dropout)
        self.n_head = config.n_head
        self.dropout = config.dropout
        self.attn_impl = config.attn_impl
        self._quant = quant_policies(
            config.compute_dtype, "gpt",
            ("attn/c_attn/kernel", "attn/c_proj/kernel"))

    def __call__(self, x, *, deterministic=True, rngs=None):
        B, T, C = x.shape
        H = self.n_head
        hd = C // H
        # Head-major projections (models/common.py helpers): q/k/v land in
        # the flash kernels' native (B, H, T, D) layout with the transpose
        # fused into the matmul epilogue. Params stay in the c_attn/c_proj
        # Linears so the checkpoint format is unchanged.
        cdtype = x.dtype
        w = self.c_attn.kernel.get_value().astype(cdtype)  # (C, 3C)
        b = (self.c_attn.bias.get_value().astype(cdtype)
             if self.c_attn.bias is not None else None)
        if self._quant and self._quant[0].quantize:
            # int8 QKV: one fused (C, 3C) quantized matmul; the
            # head-major transpose happens on the (8x smaller) int8-path
            # output instead of riding the matmul epilogue
            from avenir_tpu.ops.quant import int8_matmul

            qkv = int8_matmul(x, w, scaling=self._quant[0].scaling)
            if b is not None:
                qkv = qkv + b
            q, k, v = (
                qkv[..., i * C:(i + 1) * C]
                .reshape(B, T, H, hd).transpose(0, 2, 1, 3)
                for i in range(3)
            )
        else:
            q, k, v = (
                head_major_project(
                    x, w[:, i * C:(i + 1) * C],
                    None if b is None else b[i * C:(i + 1) * C], H, hd,
                )
                for i in range(3)
            )
        use_dropout = self.dropout > 0.0 and not deterministic
        y = causal_attention(
            q, k, v,
            dropout_rate=self.dropout, deterministic=deterministic,
            dropout_rng=rngs.dropout() if use_dropout else None,
            impl=self.attn_impl, layout="bhtd",
        )  # (B, H, T, hd)
        w_o = self.c_proj.kernel.get_value().astype(cdtype)
        b_o = (self.c_proj.bias.get_value().astype(cdtype)
               if self.c_proj.bias is not None else None)
        if self._quant and self._quant[1].quantize:
            from avenir_tpu.ops.quant import int8_matmul

            out = int8_matmul(y.transpose(0, 2, 1, 3).reshape(B, T, C),
                              w_o, scaling=self._quant[1].scaling)
            if b_o is not None:
                out = out + b_o
        else:
            out = head_major_merge(y, w_o, b_o)
        return self.resid_dropout(out, deterministic=deterministic, rngs=rngs)


class MLP(nnx.Module):
    def __init__(self, config: GPTConfig, *, rngs: nnx.Rngs):
        cdtype = resolve_dtype(config.compute_dtype)
        init = nnx.initializers.normal(stddev=0.02)
        proj_init = nnx.initializers.normal(
            stddev=0.02 / math.sqrt(2 * config.n_layer)
        )
        zeros = nnx.initializers.zeros_init()
        self.c_fc = nnx.Linear(
            config.n_embd, 4 * config.n_embd, use_bias=config.bias,
            kernel_init=init, bias_init=zeros,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.c_proj = nnx.Linear(
            4 * config.n_embd, config.n_embd, use_bias=config.bias,
            kernel_init=proj_init, bias_init=zeros,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.dropout = nnx.Dropout(config.dropout)
        self._cdtype = cdtype
        self._quant = quant_policies(
            config.compute_dtype, "gpt",
            ("mlp/c_fc/kernel", "mlp/c_proj/kernel"))

    def __call__(self, x, *, deterministic=True, rngs=None):
        # tanh-approximated GELU (gelu_new), matching model.py:116-118 and
        # HF GPT-2's activation_function="gelu_new". erf-GELU measured 35%
        # slower on the v5e VPU (BASELINE.md "GELU" note).
        q = self._quant
        x = jax.nn.gelu(
            quant_linear(self.c_fc, x, q and q[0], self._cdtype),
            approximate=True)
        return self.dropout(
            quant_linear(self.c_proj, x, q and q[1], self._cdtype),
            deterministic=deterministic, rngs=rngs
        )


class Block(nnx.Module):
    def __init__(self, config: GPTConfig, *, rngs: nnx.Rngs):
        cdtype = resolve_dtype(config.compute_dtype)
        # LayerNorm computes in fp32 (autocast keeps norms in fp32); output
        # is cast back to the compute dtype by the next Linear.
        self.ln_1 = nnx.LayerNorm(
            config.n_embd, epsilon=1e-5, use_bias=config.bias,
            dtype=jnp.float32, param_dtype=jnp.float32, rngs=rngs,
        )
        self.attn = CausalSelfAttention(config, rngs=rngs)
        self.ln_2 = nnx.LayerNorm(
            config.n_embd, epsilon=1e-5, use_bias=config.bias,
            dtype=jnp.float32, param_dtype=jnp.float32, rngs=rngs,
        )
        self.mlp = MLP(config, rngs=rngs)
        self._cdtype = cdtype

    def __call__(self, x, *, deterministic=True, rngs=None):
        x = x + self.attn(
            self.ln_1(x).astype(self._cdtype),
            deterministic=deterministic, rngs=rngs,
        )
        x = x + self.mlp(
            self.ln_2(x).astype(self._cdtype),
            deterministic=deterministic, rngs=rngs,
        )
        return x


class GPT(nnx.Module):
    def __init__(self, config: GPTConfig, *, rngs: nnx.Rngs):
        assert config.vocab_size is not None and config.block_size is not None
        self.config = config
        init = nnx.initializers.normal(stddev=0.02)
        cdtype = resolve_dtype(config.compute_dtype)
        self.wte = nnx.Embed(
            config.vocab_size, config.n_embd, embedding_init=init,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.wpe = nnx.Embed(
            config.block_size, config.n_embd, embedding_init=init,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.drop = nnx.Dropout(config.dropout)
        if config.scan_layers:
            self.h_scan = stacked_layers(
                config.n_layer, lambda r: Block(config, rngs=r), rngs
            )
        else:
            self.h = nnx.List(
                [Block(config, rngs=rngs) for _ in range(config.n_layer)]
            )
        self.ln_f = nnx.LayerNorm(
            config.n_embd, epsilon=1e-5, use_bias=config.bias,
            dtype=jnp.float32, param_dtype=jnp.float32, rngs=rngs,
        )
        self._cdtype = cdtype
        # tied head: the wte tensor's MATMUL use (the CE projection)
        # follows its rules-table policy; the embedding gather never
        # quantizes (partition.py precision conventions)
        self._quant_head = quant_policies(
            config.compute_dtype, "gpt", ("wte/embedding",))

    def __call__(self, idx, targets=None, *, deterministic=True, rngs=None):
        B, T = idx.shape
        assert T <= self.config.block_size, (
            f"sequence length {T} > block_size {self.config.block_size}"
        )
        pos = jnp.arange(T, dtype=jnp.int32)
        x = self.wte(idx) + self.wpe(pos)[None]
        x = self.drop(x, deterministic=deterministic, rngs=rngs)

        if self.config.scan_layers:
            assert self.config.dropout == 0.0 or deterministic, (
                "scan_layers + dropout rng threading not supported; "
                "train with dropout=0"
            )
            from avenir_tpu.parallel.pipeline import (
                layer_stack_dispatch,
                pipeline_1f1b_loss,
                pipeline_axis_size,
            )

            block_call = lambda blk, h: blk(h, deterministic=deterministic)
            schedule = self.config.pipeline_schedule
            if (schedule == "1f1b" and targets is not None
                    and pipeline_axis_size() > 1):
                # true 1F1B: the loss tail (ln_f + tied head + chunked
                # CE) moves INSIDE the pipeline region and runs per
                # microbatch on the last stage, so backwards interleave
                # with later micros' forwards. The tied wte rides in as
                # an explicit tail param: its tail gradient (dw of the
                # head) comes back from the region and the embedding-
                # lookup contribution is added by the outer autodiff —
                # same tied-weight accounting as the fused tail outside.
                from avenir_tpu.ops.fused_ce import blocked_ce_terms

                ln_gd, ln_state = nnx.split(self.ln_f)
                tail_params = {"ln": ln_state,
                               "w": self.wte.embedding.get_value()}
                cd = self._cdtype
                t_chunk = self.config.loss_chunk
                wdt = w_dtype_for(self._quant_head)

                def tail_fn(tp, h, y, stats):
                    hn = nnx.merge(ln_gd, tp["ln"])(h).astype(cd)
                    ls, _ = blocked_ce_terms(
                        hn, tp["w"].astype(cd), y, ignore_index=-1,
                        w_layout="vc", t_chunk=t_chunk, w_dtype=wdt)
                    return ls, jnp.float32(0.0)

                loss = pipeline_1f1b_loss(
                    x, self.h_scan, targets, call=block_call,
                    tail_fn=tail_fn, tail_params=tail_params,
                    n_valid=jnp.sum(targets != -1),
                    n_micro=self.config.pipeline_microbatches,
                    remat=self.config.remat,
                    remat_policy=self.config.remat_policy,
                )
                return None, loss

            # GPipe over the 'pipe' mesh axis when the mesh has one
            # (stages own contiguous layer blocks, microbatches ride
            # ppermute), nnx.scan otherwise — one dispatch helper. A
            # 1f1b config called WITHOUT targets (generate/logits) runs
            # the identical gpipe forward: no loss, nothing to interleave
            x = layer_stack_dispatch(
                x, self.h_scan, call=block_call,
                n_micro=self.config.pipeline_microbatches,
                remat=self.config.remat,
                remat_policy=self.config.remat_policy,
                schedule="gpipe" if schedule == "1f1b" else schedule,
            )
        else:
            if self.config.remat:
                assert self.config.dropout == 0.0 or deterministic, (
                    "remat + dropout rng threading not supported; train with dropout=0"
                )
                block_fn = nnx.remat(
                    lambda blk, h: blk(h, deterministic=deterministic),
                    policy=resolve_remat_policy(self.config.remat_policy),
                )
            else:
                block_fn = lambda blk, h: blk(
                    h, deterministic=deterministic, rngs=rngs
                )
            for block in self.h:
                x = block_fn(block, x)
        x = self.ln_f(x).astype(self._cdtype)

        # CE tail precision: weight-only int8 (per-vocab-row scales over
        # the contraction axis) when the tied wte's rules-table policy
        # says so — every impl (reference fake-quant oracle, blocked
        # stripes, pallas stripes) lands on the same int8 grid
        w_dtype = w_dtype_for(self._quant_head)
        if targets is not None:
            from avenir_tpu.ops.fused_ce import (
                fused_cross_entropy,
                resolve_loss_impl,
            )

            loss_impl = resolve_loss_impl(self.config.loss_impl)
            if loss_impl == "reference":
                logits = self._head_logits(x, w_dtype)
                loss = cross_entropy_loss(logits, targets, ignore_index=-1)
            else:
                # fused chunked tail: the (B, T, V) logits never exist;
                # w_layout='vc' consumes the tied embedding in place and
                # its dw lands as the tied-wte gradient contribution
                emb = self.wte.embedding.get_value().astype(self._cdtype)
                loss = fused_cross_entropy(
                    x, emb, targets, ignore_index=-1, impl=loss_impl,
                    w_layout="vc", t_chunk=self.config.loss_chunk,
                    w_dtype=w_dtype,
                )
                logits = None
        else:
            logits = self._head_logits(x[:, -1:, :], w_dtype)
            loss = None
        return logits, loss

    def _head_logits(self, x, w_dtype):
        """Tied-head logits (model.py:149-151). Under the int8 knob the
        tied embedding is consumed through the straight-through
        fake-quant grid (ops/quant.py) — the full-logits twin of the
        fused tail's int8 weight stripes."""
        if w_dtype == "int8":
            from avenir_tpu.ops.quant import fake_quant

            emb = self.wte.embedding.get_value().astype(self._cdtype)
            return jnp.einsum("btc,vc->btv", x, fake_quant(emb, 1))
        return self.wte.attend(x)

    # ----- parity utilities (mirror model.py) -----

    def get_num_params(self, non_embedding=True):
        """Param count. The torch side counts the tied wte/lm_head tensor
        once (shared storage), so the totals match (model.py:167-171)."""
        leaves = jax.tree.leaves(nnx.state(self, nnx.Param))
        n = sum(x.size for x in leaves)
        if non_embedding:
            n -= self.wpe.embedding.get_value().size
        return n

    def crop_block_size(self, block_size):
        import dataclasses

        assert block_size <= self.config.block_size
        self.wpe.embedding.set_value(self.wpe.embedding.get_value()[:block_size])
        self.wpe.num_embeddings = block_size
        self.config = dataclasses.replace(self.config, block_size=block_size)

    def estimate_mfu(self, fwdbwd_per_iter, dt, peak_flops=312e12):
        cfg = self.config
        fpt = transformer_flops_per_token(
            self.get_num_params(), cfg.n_layer, cfg.n_head,
            cfg.n_embd // cfg.n_head, cfg.block_size,
        )
        return (fpt * cfg.block_size * fwdbwd_per_iter / dt) / peak_flops

    def generate(self, rng, idx, max_new_tokens, temperature=1.0, top_k=None):
        """Autoregressive sampling, recompute-full-prefix (parity with
        model.py:282-297). For the jitted KV-cache decoder see
        avenir_tpu/infer/decode.py."""
        for _ in range(max_new_tokens):
            idx_cond = idx[:, -self.config.block_size:]
            logits, _ = self(idx_cond)
            logits = logits[:, -1, :].astype(jnp.float32) / temperature
            if top_k is not None:
                kth = jnp.sort(logits, axis=-1)[:, -min(top_k, logits.shape[-1])]
                logits = jnp.where(logits < kth[:, None], -jnp.inf, logits)
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, logits, axis=-1)
            idx = jnp.concatenate([idx, nxt[:, None]], axis=1)
        return idx
