"""Llama-3-family decoder in flax nnx (SURVEY.md §2b T9; BASELINE.json:10
"Llama-3 8B (RoPE + SwiGLU + RMSNorm → Pallas kernels)").

Structure and parameter names mirror the HF Llama convention
(model.embed_tokens / model.layers.N.self_attn.{q,k,v,o}_proj /
mlp.{gate,up,down}_proj / input_layernorm / post_attention_layernorm /
model.norm / lm_head) so the checkpoint bridge's name rules apply
unchanged; tests/test_llama.py pins logits parity against
transformers' torch LlamaForCausalLM on shared random weights.

TPU notes: GQA runs through ops.causal_attention — on the Pallas path
K/V stay at H_kv heads end to end (the kernels map each q head to its
shared kv head in their BlockSpec index fns; ops/pallas/
flash_attention.py) — RMSNorm through the ops dispatch (Pallas on TPU),
RoPE tables are trace-time constants XLA folds. lm_head is UNTIED
(Llama-3 convention)."""

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.models.common import (
    cross_entropy_loss,
    head_major_merge,
    head_major_project,
    quant_linear,
    quant_policies,
    resolve_dtype,
    resolve_remat_policy,
    scan_layer_stack,
    stacked_layers,
    w_dtype_for,
)
from avenir_tpu.ops import apply_rope, causal_attention, rope_frequencies, swiglu
from avenir_tpu.ops.rmsnorm import rmsnorm


def default_ffn_hidden(n_embd):
    """Llama-style 2/3·4d feed-forward width, rounded up to 256."""
    h = int(8 * n_embd / 3)
    return -(-h // 256) * 256


@dataclass(frozen=True)
class LlamaConfig:
    block_size: int = 8192
    vocab_size: int = 128256
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 8
    n_embd: int = 4096
    ffn_hidden: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dropout: float = 0.0  # unused (Llama trains without dropout); kept for CLI parity
    compute_dtype: str = "float32"
    attn_impl: str = "auto"
    remat: bool = False
    remat_policy: str = "nothing"  # see models/common.py resolve_remat_policy
    scan_layers: bool = False  # lax.scan over stacked layers (see models/gpt.py)
    # GPipe microbatches when the mesh has a pipe axis > 1 (requires
    # scan_layers; parallel/pipeline.py). 0 = auto (2x the pipe size).
    pipeline_microbatches: int = 0
    pipeline_schedule: str = "gpipe"  # see GPTConfig/parallel.pipeline
    # loss tail: 'reference' | 'blocked' | 'pallas' | 'auto' — see
    # GPTConfig.loss_impl / ops/fused_ce.py. Fused impls return
    # logits=None when targets are given.
    loss_impl: str = "reference"
    loss_chunk: int = 0  # blocked-tail time chunk; 0 = default

    @classmethod
    def from_train_config(cls, cfg, model_args):
        n_kv = cfg.get("n_kv_head", 0) or model_args["n_head"]
        ffn = cfg.get("ffn_hidden", 0) or default_ffn_hidden(model_args["n_embd"])
        return cls(
            block_size=model_args["block_size"],
            vocab_size=model_args["vocab_size"],
            n_layer=model_args["n_layer"], n_head=model_args["n_head"],
            n_kv_head=n_kv, n_embd=model_args["n_embd"], ffn_hidden=ffn,
            rope_theta=cfg.get("rope_theta", 500000.0),
            # the compute_dtype knob ('int8' = quantized hot matmuls over
            # a bf16 base, ops/quant.py) overrides the dtype-derived base
            compute_dtype=(cfg.get("compute_dtype")
                           or ("float32" if cfg["dtype"] == "float16"
                               else cfg["dtype"])),
            attn_impl=("auto" if cfg["use_pallas"] else "xla"),
            remat=cfg["remat"],
            remat_policy=cfg.get("remat_policy", "nothing"),
            scan_layers=cfg.get("scan_layers", False),
            pipeline_microbatches=cfg.get("pipeline_microbatches", 0),
            pipeline_schedule=cfg.get("pipeline_schedule", "gpipe"),
            loss_impl=cfg.get("loss_impl", "") or "reference",
            loss_chunk=cfg.get("loss_chunk", 0),
        )


class RMSNorm(nnx.Module):
    def __init__(self, dim, *, eps, rngs):
        self.scale = nnx.Param(jnp.ones((dim,), jnp.float32))
        self.eps = eps

    def __call__(self, x):
        return rmsnorm(x, self.scale.get_value(), eps=self.eps)


class LlamaAttention(nnx.Module):
    def __init__(self, config: LlamaConfig, *, rngs):
        assert config.n_embd % config.n_head == 0
        assert config.n_head % config.n_kv_head == 0
        cdtype = resolve_dtype(config.compute_dtype)
        hd = config.n_embd // config.n_head
        init = nnx.initializers.normal(stddev=0.02)
        o_init = nnx.initializers.normal(
            stddev=0.02 / math.sqrt(2 * config.n_layer)
        )
        lin = lambda i, o, ini: nnx.Linear(
            i, o, use_bias=False, kernel_init=ini,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.q_proj = lin(config.n_embd, config.n_head * hd, init)
        self.k_proj = lin(config.n_embd, config.n_kv_head * hd, init)
        self.v_proj = lin(config.n_embd, config.n_kv_head * hd, init)
        self.o_proj = lin(config.n_head * hd, config.n_embd, o_init)
        self.n_head = config.n_head
        self.n_kv_head = config.n_kv_head
        self.head_dim = hd
        self.rope_theta = config.rope_theta
        self.max_t = config.block_size
        self.attn_impl = config.attn_impl
        self._quant = quant_policies(
            config.compute_dtype, "llama",
            ("q_proj/kernel", "o_proj/kernel"))

    def __call__(self, x, positions=None):
        B, T, C = x.shape
        H, Hkv, hd = self.n_head, self.n_kv_head, self.head_dim
        # Head-major projections (models/common.py helpers; the transpose
        # into the kernel-native layout rides the matmul epilogue).
        cdtype = x.dtype
        if self._quant and self._quant[0].quantize:
            from avenir_tpu.ops.quant import int8_matmul

            def proj(lin, nh):
                y2 = int8_matmul(
                    x, lin.kernel.get_value().astype(cdtype),
                    scaling=self._quant[0].scaling)
                return y2.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
        else:
            proj = lambda lin, nh: head_major_project(
                x, lin.kernel.get_value().astype(cdtype), None, nh, hd)
        q, k, v = proj(self.q_proj, H), proj(self.k_proj, Hkv), proj(self.v_proj, Hkv)
        cos, sin = rope_frequencies(hd, self.max_t, self.rope_theta)
        q = apply_rope(q, cos, sin, positions=positions, layout="bhtd")
        k = apply_rope(k, cos, sin, positions=positions, layout="bhtd")
        y = causal_attention(q, k, v, impl=self.attn_impl, layout="bhtd")
        w_o = self.o_proj.kernel.get_value().astype(cdtype)
        if self._quant and self._quant[1].quantize:
            from avenir_tpu.ops.quant import int8_matmul

            return int8_matmul(
                y.transpose(0, 2, 1, 3).reshape(B, T, H * hd), w_o,
                scaling=self._quant[1].scaling)
        return head_major_merge(y, w_o, None)


class LlamaMLP(nnx.Module):
    def __init__(self, config: LlamaConfig, *, rngs):
        cdtype = resolve_dtype(config.compute_dtype)
        init = nnx.initializers.normal(stddev=0.02)
        d_init = nnx.initializers.normal(
            stddev=0.02 / math.sqrt(2 * config.n_layer)
        )
        lin = lambda i, o, ini: nnx.Linear(
            i, o, use_bias=False, kernel_init=ini,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.gate_proj = lin(config.n_embd, config.ffn_hidden, init)
        self.up_proj = lin(config.n_embd, config.ffn_hidden, init)
        self.down_proj = lin(config.ffn_hidden, config.n_embd, d_init)
        self._cdtype = cdtype
        self._quant = quant_policies(
            config.compute_dtype, "llama",
            ("gate_proj/kernel", "down_proj/kernel"))

    def __call__(self, x):
        if self._quant:
            up, dn = self._quant
            h = swiglu(quant_linear(self.gate_proj, x, up, self._cdtype),
                       quant_linear(self.up_proj, x, up, self._cdtype))
            return quant_linear(self.down_proj, h, dn, self._cdtype)
        return self.down_proj(swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(nnx.Module):
    def __init__(self, config: LlamaConfig, *, rngs):
        self.input_layernorm = RMSNorm(config.n_embd, eps=config.norm_eps,
                                       rngs=rngs)
        self.self_attn = LlamaAttention(config, rngs=rngs)
        self.post_attention_layernorm = RMSNorm(
            config.n_embd, eps=config.norm_eps, rngs=rngs
        )
        self.mlp = LlamaMLP(config, rngs=rngs)
        self._cdtype = resolve_dtype(config.compute_dtype)

    def __call__(self, x, positions=None):
        x = x + self.self_attn(
            self.input_layernorm(x).astype(self._cdtype), positions=positions
        )
        x = x + self.mlp(self.post_attention_layernorm(x).astype(self._cdtype))
        return x


class Llama(nnx.Module):
    def __init__(self, config: LlamaConfig, *, rngs,
                 layer_cls=LlamaDecoderLayer):
        self.config = config
        cdtype = resolve_dtype(config.compute_dtype)
        init = nnx.initializers.normal(stddev=0.02)
        self.embed_tokens = nnx.Embed(
            config.vocab_size, config.n_embd, embedding_init=init,
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        if config.scan_layers:
            self.layers_scan = stacked_layers(
                config.n_layer, lambda r: layer_cls(config, rngs=r), rngs
            )
        else:
            self.layers = nnx.List(
                [layer_cls(config, rngs=rngs) for _ in range(config.n_layer)]
            )
        self.norm = RMSNorm(config.n_embd, eps=config.norm_eps, rngs=rngs)
        self.lm_head = nnx.Linear(
            config.n_embd, config.vocab_size, use_bias=False,
            kernel_init=init, dtype=cdtype, param_dtype=jnp.float32,
            rngs=rngs,
        )
        self._cdtype = cdtype
        self._quant_head = quant_policies(
            config.compute_dtype, "llama", ("lm_head/kernel",))

    def __call__(self, idx, targets=None, *, deterministic=True, rngs=None):
        B, T = idx.shape
        assert T <= self.config.block_size
        x = self.embed_tokens(idx)
        # layer protocol: plain layers return x; MoE layers return
        # (x, router_stats) — a stats pytree summed across layers through
        # the loop or scan carry, turned into the aux loss at the top (the
        # family overrides _zero_router_stats/_router_aux_loss)
        def apply(lyr, h):
            out = lyr(h)
            return out if isinstance(out, tuple) else (
                out, self._zero_router_stats()
            )

        stats_sum = self._zero_router_stats()
        if self.config.scan_layers:
            from avenir_tpu.parallel.pipeline import (
                layer_stack_dispatch,
                pipeline_1f1b_loss,
                pipeline_axis_size,
            )

            coef = getattr(self.config, "router_aux_loss_coef", 0.0)
            schedule = self.config.pipeline_schedule
            kw = dict(n_micro=self.config.pipeline_microbatches,
                      remat=self.config.remat,
                      remat_policy=self.config.remat_policy)
            if (schedule == "1f1b" and targets is not None
                    and pipeline_axis_size() > 1):
                # true 1F1B: final norm + (untied) lm_head + chunked CE
                # run per micro on the last stage INSIDE the region. MoE
                # router stats ride the ppermute payload and the aux loss
                # is computed PER MICRO from each micro's own stats —
                # the micro-batched-oracle semantics (see
                # pipeline_1f1b_loss; gpipe keeps aggregate-stats aux).
                from avenir_tpu.ops.fused_ce import blocked_ce_terms

                norm_gd, norm_state = nnx.split(self.norm)
                tail_params = {"norm": norm_state,
                               "w": self.lm_head.kernel.get_value()}
                cd = self._cdtype
                t_chunk = self.config.loss_chunk
                wdt = w_dtype_for(self._quant_head)

                def tail_fn(tp, h, y, stats):
                    hn = nnx.merge(norm_gd, tp["norm"])(h).astype(cd)
                    ls, _ = blocked_ce_terms(
                        hn, tp["w"].astype(cd), y, ignore_index=-1,
                        w_layout="cv", t_chunk=t_chunk, w_dtype=wdt)
                    aux = (coef * self._router_aux_loss(stats) if coef
                           else jnp.float32(0.0))
                    return ls, aux

                loss = pipeline_1f1b_loss(
                    x, self.layers_scan, targets,
                    call=(apply if coef
                          else (lambda lyr, h: apply(lyr, h)[0])),
                    aux0=stats_sum if coef else None,
                    tail_fn=tail_fn, tail_params=tail_params,
                    n_valid=jnp.sum(targets != -1), **kw)
                return None, loss

            # router stats ride the shared aux carry: the scan path
            # accumulates them through its carry, a pipe mesh through the
            # pipeline's masked tick/psum machinery (batch-mean contract;
            # NB MoE capacity is then computed per MICRObatch — see
            # pipeline_layer_stack). Families with no aux consumer
            # (coef=0: plain Llama) skip the carry entirely — which also
            # unlocks the aux-free 'remat' pipeline schedule for them.
            # 1f1b configs without targets fall back to the identical
            # gpipe forward (no loss, nothing to interleave).
            kw["schedule"] = "gpipe" if schedule == "1f1b" else schedule
            if coef:
                x, stats_sum = layer_stack_dispatch(
                    x, self.layers_scan, call=apply, aux0=stats_sum, **kw)
            else:
                x = layer_stack_dispatch(
                    x, self.layers_scan,
                    call=lambda lyr, h: apply(lyr, h)[0], **kw)
        else:
            layer_fn = (nnx.remat(apply,
                                  policy=resolve_remat_policy(
                                      self.config.remat_policy))
                        if self.config.remat else apply)
            for layer in self.layers:
                x, s = layer_fn(layer, x)
                stats_sum = jax.tree.map(jnp.add, stats_sum, s)
        x = self.norm(x).astype(self._cdtype)
        # CE tail precision follows the lm_head's rules-table policy:
        # weight-only int8 across every impl (see GPT._head_logits)
        w_dtype = w_dtype_for(self._quant_head)
        if targets is not None:
            from avenir_tpu.ops.fused_ce import (
                fused_cross_entropy,
                resolve_loss_impl,
            )

            loss_impl = resolve_loss_impl(self.config.loss_impl)
            if loss_impl == "reference":
                logits = self._head_logits(x, w_dtype)
                loss = cross_entropy_loss(logits, targets, ignore_index=-1)
            else:
                # fused chunked tail (ops/fused_ce.py): w_layout='cv'
                # consumes the untied lm_head kernel in place
                w = self.lm_head.kernel.get_value().astype(self._cdtype)
                loss = fused_cross_entropy(
                    x, w, targets, ignore_index=-1, impl=loss_impl,
                    w_layout="cv", t_chunk=self.config.loss_chunk,
                    w_dtype=w_dtype,
                )
                logits = None
            coef = getattr(self.config, "router_aux_loss_coef", 0.0)
            if coef:
                loss = loss + coef * self._router_aux_loss(stats_sum)
        else:
            logits = self._head_logits(x[:, -1:, :], w_dtype)
            loss = None
        return logits, loss

    def _head_logits(self, x, w_dtype):
        """Untied lm-head logits; under the int8 knob the kernel is
        consumed through the straight-through fake-quant grid — the
        full-logits twin of the fused tail's int8 stripes."""
        if w_dtype == "int8":
            from avenir_tpu.ops.quant import fake_quant

            w = self.lm_head.kernel.get_value().astype(self._cdtype)
            return x @ fake_quant(w, 0)
        return self.lm_head(x)

    # router load-balancing hooks (overridden by MoE families)

    def _zero_router_stats(self):
        return jnp.float32(0.0)

    def _router_aux_loss(self, stats_sum):
        return jnp.float32(0.0)

    def get_num_params(self, non_embedding=True):
        leaves = jax.tree.leaves(nnx.state(self, nnx.Param))
        return sum(x.size for x in leaves)

    def generate(self, rng, idx, max_new_tokens, temperature=1.0, top_k=None):
        for _ in range(max_new_tokens):
            idx_cond = idx[:, -self.config.block_size:]
            logits, _ = self(idx_cond)
            logits = logits[:, -1, :].astype(jnp.float32) / temperature
            if top_k is not None:
                kth = jnp.sort(logits, axis=-1)[:, -min(top_k, logits.shape[-1])]
                logits = jnp.where(logits < kth[:, None], -jnp.inf, logits)
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, logits, axis=-1)
            idx = jnp.concatenate([idx, nxt[:, None]], axis=1)
        return idx
