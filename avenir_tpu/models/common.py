"""Shared model utilities: loss, dtype resolution, MFU accounting, and the
scan-over-layers layer stack (SURVEY.md §3.3 "nnx.scan over the L blocks")."""

import jax
import jax.numpy as jnp
import optax
from flax import nnx


def stacked_layers(n_layer, make_layer, rngs):
    """Create `n_layer` homogeneous layers as ONE module whose params carry
    a leading (n_layer, ...) axis — the storage form `nnx.scan` consumes
    directly. One trace for all layers (compile time O(1) in depth, the
    point of scan_layers) and no per-step stack/unstack copies in HBM.

    Convention: models store the result under an attribute ending in
    `_scan` (GPT.h_scan, Llama.layers_scan). That suffix is the single
    marker the partition rules (leading None axis) and the checkpoint
    bridge (split/stack to per-layer torch keys) key off, so the on-disk
    `.pt` schema is identical for scanned and unscanned models."""

    @nnx.split_rngs(splits=n_layer)
    @nnx.vmap(in_axes=(0,), out_axes=0)
    def create(r):
        return make_layer(r)

    return create(rngs)


def resolve_remat_policy(name):
    """Map a config string to a jax.checkpoint policy:
      'nothing' (default) — save only block inputs; full recompute on bwd.
      'dots'    — save weight-matmul outputs (dots with no batch dims:
                  qkv/out/mlp projections), recompute elementwise + the
                  attention custom-call only. ~2x the activation memory of
                  'nothing' in exchange for skipping most of the remat
                  forward (measured per-rung in BASELINE.md).
    """
    if name in (None, "", "nothing"):
        return None
    table = {
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    assert name in table, f"unknown remat_policy {name!r}; one of "\
                          f"['nothing'] + {sorted(table)}"
    return table[name]


def scan_layer_stack(x, layers, *, call=None, remat=False, remat_policy=None):
    """Run `x` through a stacked layer module via nnx.scan. `call(layer, h)`
    applies one layer (default `layer(h)`); with `remat` the per-layer
    activations are rematerialized on the backward pass (jax.checkpoint per
    scan step — memory O(1) in depth at the cost of recompute governed by
    `remat_policy`, see resolve_remat_policy)."""
    if call is None:
        call = lambda lyr, h: lyr(h)

    def body(h, layer):
        if remat:
            return nnx.remat(call, policy=resolve_remat_policy(remat_policy))(
                layer, h)
        return call(layer, h)

    return nnx.scan(body, in_axes=(nnx.Carry, 0), out_axes=nnx.Carry)(
        x, layers
    )


def resolve_dtype(name):
    """Config compute_dtype -> the base ARITHMETIC dtype. 'int8' (the
    quantized-matmul knob, ops/quant.py) keeps bf16 as the base: norms,
    softmax, residual stream and every non-hot-matmul op run exactly as
    under 'bfloat16' — only the rules-table-eligible matmuls
    (parallel/partition.py PrecisionPolicy) switch to the int8 path."""
    return {
        "float32": jnp.float32,
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
        "int8": jnp.bfloat16,
    }[name]


def quant_policies(compute_dtype, family, keys):
    """The models' construction-time precision resolution: None unless
    `compute_dtype` selects the int8 matmul path (ops/quant.py), else a
    tuple of PrecisionPolicy — one per canonical param-path key — from
    the unified partition+precision rules table (parallel/partition.py).
    The table is the single source of truth; call sites only name their
    own tensor."""
    from avenir_tpu.ops.quant import quantized_compute

    if not quantized_compute(compute_dtype):
        return None
    from avenir_tpu.parallel.partition import precision_for

    return tuple(precision_for(family, k) for k in keys)


def w_dtype_for(policies):
    """CE-tail weight precision from a quant_policies result: 'int8'
    when the head tensor's policy quantizes, else 'compute' — the ONE
    derivation the GPT/Llama tails (reference, fused, 1f1b) share."""
    pol = policies[0] if policies else None
    return "int8" if (pol is not None and pol.quantize) else "compute"


def quant_linear(lin, x, pol, cdtype):
    """One projection through an nnx.Linear — as-is at bf16/fp32, or the
    int8 quantized matmul over the same master kernel when `pol` (the
    tensor's rules-table policy) says so. The ONE dispatch shared by the
    GPT and Llama MLP/attention call sites."""
    if pol is None or not pol.quantize:
        return lin(x)
    from avenir_tpu.ops.quant import int8_matmul

    y = int8_matmul(x, lin.kernel.get_value().astype(cdtype),
                    scaling=pol.scaling)
    if lin.bias is not None:
        y = y + lin.bias.get_value().astype(cdtype)
    return y


def head_major_project(x, kernel, bias, n_head, head_dim):
    """(B, T, C) @ (C, n_head*head_dim) -> (B, n_head, T, head_dim) in one
    einsum: the transpose into the flash kernels' native head-major layout
    rides the matmul epilogue instead of being a standalone copy (VERDICT
    r2 item 1; A/B in tools/exp_layout2.py). `kernel`/`bias` are plain
    arrays already cast to the compute dtype."""
    C = x.shape[-1]
    out = jnp.einsum("btc,chd->bhtd", x,
                     kernel.reshape(C, n_head, head_dim))
    if bias is not None:
        out = out + bias.reshape(1, n_head, 1, head_dim)
    return out


def head_major_merge(y, kernel, bias):
    """(B, H, T, D) @ (H*D, C) -> (B, T, C), consuming head-major directly
    (the inverse of head_major_project, same fused-transpose rationale)."""
    H, D = y.shape[1], y.shape[3]
    out = jnp.einsum("bhtd,hdc->btc", y, kernel.reshape(H, D, -1))
    if bias is not None:
        out = out + bias
    return out


def cross_entropy_loss(logits, targets, ignore_index=-1):
    """Mean token cross-entropy in fp32, skipping `ignore_index` positions —
    mirrors `F.cross_entropy(..., ignore_index=-1)` in model.py:190-192.

    The row max is taken and subtracted in the INPUT dtype before the
    fp32 upcast: shift-invariant (and exactly so through the VJP — the
    max is stop_gradient'ed), bit-identical for fp32 inputs (optax
    subtracts the max internally anyway; ours is then 0), and it halves
    the fp32 footprint of the (B, T, V) intermediate for bf16 logits on
    the path that remains the fused tail's oracle (ops/fused_ce.py)."""
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    z = (logits - m).astype(jnp.float32)
    valid = targets != ignore_index
    safe_targets = jnp.where(valid, targets, 0)
    losses = optax.softmax_cross_entropy_with_integer_labels(z, safe_targets)
    losses = jnp.where(valid, losses, 0.0)
    return losses.sum() / jnp.maximum(valid.sum(), 1).astype(jnp.float32)


def transformer_flops_per_token(n_params, n_layer, n_head, head_dim, seq_len):
    """6N + 12·L·H·Q·T — the PaLM-appendix accounting used by
    model.py:273-280 (estimate_mfu), kept identical so MFU numbers from the
    two backends are comparable."""
    return 6 * n_params + 12 * n_layer * n_head * head_dim * seq_len


def tpu_peak_flops(device=None):
    """Per-chip bf16 peak FLOP/s for MFU denominators (SURVEY.md §5:
    'MFU denominators: A100 312 vs TPU v4 275 TFLOP/s')."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    table = {
        "tpu v6": 918e12,   # Trillium
        "tpu v5p": 459e12,
        "tpu v5": 197e12,   # v5e ("TPU v5 lite")
        "tpu v4": 275e12,
        "tpu v3": 123e12,
        "tpu v2": 46e12,
    }
    for prefix, peak in table.items():
        if kind.startswith(prefix):
            return peak
    return 312e12  # A100 bf16 — keeps CPU-dev MFU numbers comparable to the torch ref
