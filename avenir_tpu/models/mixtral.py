"""Mixtral-family sparse-MoE decoder (SURVEY.md §2b T10; BASELINE.json:11
"Mixtral-8x7B MoE, expert-parallel all-to-all over ICI").

Reuses the Llama attention/norm stack (Mixtral IS Llama + MoE FFN) and
swaps the MLP for a top-k routed expert block. Parameter names follow the
HF convention (block_sparse_moe.gate / block_sparse_moe.experts.N.w1|w2|w3)
— the bridge stacks per-expert torch tensors into our (E, in, out) arrays.

TPU-first dispatch (GShard/Mesh-TF shape, static everywhere):
  - capacity C = ceil(topk·N/E · capacity_factor): fixed expert batch, no
    dynamic shapes under jit; overflow tokens are DROPPED (their combine
    weight is 0 — they pass through the residual), underflow is padding
  - dispatch/combine are one-hot einsums; expert tensors carry a
    with_sharding_constraint on the 'expert' mesh axis, so XLA SPMD emits
    the all-to-all pair over ICI when EP > 1 (tokens ride the expert axis
    outside the block — batch_pspec — making dispatch a true a2a, not an
    all-gather); tests assert the collective appears in HLO
  - routing follows HF Mixtral: full softmax over E, top-k, renormalize
    over the selected k (parity-tested vs MixtralForCausalLM)

The fused loss tail (`loss_impl` in {'blocked','pallas','auto'}, see
ops/fused_ce.py) rides in through the inherited Llama.__call__ and
MixtralConfig.from_train_config's base-field copy: the router aux loss
is added ON TOP of the fused CE exactly as on the reference path, and
the chunked tail never sees the router stats (they live in the scan
carry, not in the logits). Parity incl. the aux term is pinned by
tests/test_fused_ce.py.
"""

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from flax import nnx

from avenir_tpu.models.common import resolve_dtype
from avenir_tpu.models.llama import (
    Llama,
    LlamaAttention,
    LlamaConfig,
    RMSNorm,
)


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    n_experts: int = 8
    n_experts_per_tok: int = 2
    capacity_factor: float = 1.25
    # Switch/Mixtral load-balancing auxiliary loss (HF router_aux_loss_coef
    # default 0.02). Without it, top-k routing + fixed capacity dropping is
    # prone to expert collapse during training. 0.0 disables.
    router_aux_loss_coef: float = 0.02

    @classmethod
    def from_train_config(cls, cfg, model_args):
        base = LlamaConfig.from_train_config(cfg, model_args)
        return cls(
            **{k: getattr(base, k) for k in base.__dataclass_fields__},
            n_experts=cfg.get("n_experts", 8),
            n_experts_per_tok=cfg.get("n_experts_per_tok", 2),
            capacity_factor=cfg.get("capacity_factor", 1.25),
            router_aux_loss_coef=cfg.get("router_aux_loss_coef", 0.02),
        )


class MixtralExperts(nnx.Module):
    """Stacked expert FFNs: w1/w3 (E, d, ff) up-projections, w2 (E, ff, d)
    down-projection; y_e = w2_e(silu(w1_e(x)) * w3_e(x))."""

    def __init__(self, config: MixtralConfig, *, rngs):
        E, d, ff = config.n_experts, config.n_embd, config.ffn_hidden
        init = nnx.initializers.normal(stddev=0.02)
        self.w1 = nnx.Param(init(rngs.params(), (E, d, ff), jnp.float32))
        self.w3 = nnx.Param(init(rngs.params(), (E, d, ff), jnp.float32))
        self.w2 = nnx.Param(init(rngs.params(), (E, ff, d), jnp.float32))
        self._cdtype = resolve_dtype(config.compute_dtype)
        from avenir_tpu.models.common import quant_policies

        self._quant = quant_policies(
            config.compute_dtype, "mixtral", ("experts/w1", "experts/w2"))

    def __call__(self, x):  # x: (E, C, d)
        cd = self._cdtype
        w1 = self.w1.get_value().astype(cd)
        w3 = self.w3.get_value().astype(cd)
        w2 = self.w2.get_value().astype(cd)
        if self._quant and any(p.quantize for p in self._quant):
            # int8 expert FFNs: the per-expert matmul vmaps the ONE
            # quantized-matmul op over the stacked E axis — per-channel
            # scales stay per expert (ops/quant.py; custom_vjp batches).
            # Each tensor honors its OWN rules-table policy (w1/w3 share
            # the up-projection row, w2 the down-projection row).
            from avenir_tpu.ops.quant import int8_matmul

            def mm(a, b, pol, eq):
                if not pol.quantize:
                    return jnp.einsum(
                        eq, a, b,
                        preferred_element_type=jnp.float32).astype(cd)
                return jax.vmap(lambda ae, be: int8_matmul(
                    ae, be, scaling=pol.scaling))(a, b)

            up, dn = self._quant
            h = jax.nn.silu(
                mm(x, w1, up, "ecd,edf->ecf").astype(jnp.float32)
            ).astype(cd) * mm(x, w3, up, "ecd,edf->ecf")
            return mm(h, w2, dn, "ecf,efd->ecd")
        h = jax.nn.silu(
            jnp.einsum("ecd,edf->ecf", x, w1,
                       preferred_element_type=jnp.float32).astype(jnp.float32)
        ).astype(cd) * jnp.einsum("ecd,edf->ecf", x, w3,
                                  preferred_element_type=jnp.float32).astype(cd)
        return jnp.einsum("ecf,efd->ecd", h, w2,
                          preferred_element_type=jnp.float32).astype(cd)


class MixtralSparseMoeBlock(nnx.Module):
    def __init__(self, config: MixtralConfig, *, rngs):
        cdtype = resolve_dtype(config.compute_dtype)
        self.gate = nnx.Linear(
            config.n_embd, config.n_experts, use_bias=False,
            kernel_init=nnx.initializers.normal(stddev=0.02),
            dtype=cdtype, param_dtype=jnp.float32, rngs=rngs,
        )
        self.experts = MixtralExperts(config, rngs=rngs)
        self.n_experts = config.n_experts
        self.topk = config.n_experts_per_tok
        self.capacity_factor = config.capacity_factor
        self._cdtype = cdtype

    def __call__(self, x):  # (B, T, d)
        from jax.sharding import PartitionSpec as P

        from avenir_tpu import compat
        from avenir_tpu.parallel.partition import constrain

        # legacy-runtime guard (jax 0.4.x compat shard_map): the expert
        # all-to-all pair that GSPMD emits for the dispatch/combine
        # constraints below cannot lower inside the pipeline's
        # partial-auto 'pipe' region — the old SPMD partitioner
        # CHECK-aborts the whole process (no catchable exception).
        # ring/ulysses sidestep their analogous breakage with a psum
        # emulation because they own a shard_map body; this dispatch is
        # GSPMD-constraint-driven, so there is nothing local to swap.
        # Modern jax composes expert×pipe fine — fail loud here instead
        # of letting XLA abort the trainer (and every pytest after it).
        if getattr(jax, "shard_map", None) is compat.shard_map:
            mesh = jax.sharding.get_abstract_mesh()
            manual = getattr(compat._manual_axes, "names", frozenset())
            if ("pipe" in manual and mesh is not None and not mesh.empty
                    and dict(mesh.shape).get("expert", 1) > 1):
                raise NotImplementedError(
                    "expert-parallel MoE dispatch cannot nest inside a "
                    "pipeline region on the legacy jax runtime (the "
                    "expert all-to-all CHECK-crashes the old SPMD "
                    "partitioner); drop the expert axis from pipe "
                    "meshes, or run on modern jax"
                )

        B, T, d = x.shape
        N = B * T
        E, K = self.n_experts, self.topk
        C = max(1, int(-(-K * N * self.capacity_factor // E)))
        xf = x.reshape(N, d)

        logits = self.gate(xf).astype(jnp.float32)  # (N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        topk_probs, topk_idx = jax.lax.top_k(probs, K)  # (N, K)
        topk_probs = topk_probs / jnp.sum(topk_probs, axis=-1, keepdims=True)

        oh = jax.nn.one_hot(topk_idx, E, dtype=jnp.int32)  # (N, K, E)
        # router stats for the Switch/Mixtral load-balancing loss: this
        # layer's mean one-hot assignment (K, E) and mean router probs
        # (E,), both pre-capacity (on intent, not on what fit). The model
        # top combines them across layers exactly like HF's
        # load_balancing_loss_func over concatenated router logits.
        stats = (jnp.mean(oh.astype(jnp.float32), axis=0),
                 jnp.mean(probs, axis=0))
        # queue position of each (token, slot) within its expert, in
        # (token-major, slot-minor) order — matches sequential routing
        flat = oh.reshape(N * K, E)
        pos = jnp.cumsum(flat, axis=0) * flat - 1  # (N·K, E)
        pos = pos.reshape(N, K, E)
        pos_tok = jnp.sum(pos * oh, axis=-1)  # (N, K) position in chosen queue
        keep = pos_tok < C  # capacity mask

        # Gather/scatter dispatch (round 3, VERDICT r2 item 4): the round-2
        # (N, E, C)-one-hot dispatch/combine einsums were O(N·E·C·d) dense
        # FLOPs and materialized two (N, E, C) fp32 arrays (168 MB each at
        # the bench rung) — xprof put them at ~12% of the step. Routing is
        # a permutation, so build it as one: each kept (token, slot) pair
        # owns expert queue cell `topk_idx·C + pos_tok`, dropped pairs park
        # on an overflow cell, and dispatch/combine become O(N·K·d) row
        # gathers (autodiff turns them into scatter-adds). Same semantics:
        # unique cells, token-major queue order, dropped slots contribute 0.
        # kept pairs own expert queue cell `topk_idx·C + pos_tok`; each
        # DROPPED pair gets its own distinct out-of-bounds cell E·C + pair
        # index, so the indices really are globally unique — a shared E·C
        # sentinel worked only because mode="drop" discards OOB writes,
        # but duplicated indices under a unique_indices=True promise are
        # implementation-defined (ADVICE r3)
        pair_idx = jnp.arange(N * K).reshape(N, K)
        slot = jnp.where(keep, topk_idx * C + pos_tok, E * C + pair_idx)
        tok_of_pair = jnp.broadcast_to(jnp.arange(N)[:, None], (N, K))
        # inverse permutation: which token fills each expert queue cell
        # (sentinel N = "empty cell" -> the appended zero row of xf). The
        # scatter target is exactly (E*C,): every dropped pair's index is
        # out of bounds and mode="drop" discards it.
        token_for_slot = jnp.full((E * C,), N, dtype=jnp.int32)
        token_for_slot = token_for_slot.at[slot.reshape(-1)].set(
            tok_of_pair.reshape(-1).astype(jnp.int32), mode="drop",
            unique_indices=True,
        )
        xf_c = jnp.concatenate(
            [xf.astype(self._cdtype), jnp.zeros((1, d), self._cdtype)], axis=0
        )
        expert_in = xf_c[token_for_slot].reshape(E, C, d)
        expert_in = constrain(expert_in, P("expert", None, None))
        expert_out = self.experts(expert_in)  # (E, C, d)
        expert_out = constrain(expert_out, P("expert", None, None))
        out_flat = jnp.concatenate(
            [expert_out.reshape(E * C, d), jnp.zeros((1, d), expert_out.dtype)],
            axis=0,
        )
        # dropped pairs (slot >= E·C) read the appended zero row explicitly
        gathered = out_flat[jnp.minimum(slot, E * C)]  # (N, K, d)
        out = jnp.einsum("nk,nkd->nd",
                         (topk_probs * keep).astype(self._cdtype), gathered)
        return out.reshape(B, T, d).astype(x.dtype), stats


class MixtralDecoderLayer(nnx.Module):
    def __init__(self, config: MixtralConfig, *, rngs):
        self.input_layernorm = RMSNorm(config.n_embd, eps=config.norm_eps,
                                       rngs=rngs)
        self.self_attn = LlamaAttention(config, rngs=rngs)
        self.post_attention_layernorm = RMSNorm(
            config.n_embd, eps=config.norm_eps, rngs=rngs
        )
        self.block_sparse_moe = MixtralSparseMoeBlock(config, rngs=rngs)
        self._cdtype = resolve_dtype(config.compute_dtype)

    def __call__(self, x, positions=None):
        x = x + self.self_attn(
            self.input_layernorm(x).astype(self._cdtype), positions=positions
        )
        moe_out, stats = self.block_sparse_moe(
            self.post_attention_layernorm(x).astype(self._cdtype)
        )
        # layers may return (x, router_stats); Llama.__call__ accumulates
        return x + moe_out, stats


class Mixtral(Llama):
    def __init__(self, config: MixtralConfig, *, rngs):
        super().__init__(config, rngs=rngs, layer_cls=MixtralDecoderLayer)

    def _zero_router_stats(self):
        K, E = self.config.n_experts_per_tok, self.config.n_experts
        return (jnp.zeros((K, E), jnp.float32), jnp.zeros((E,), jnp.float32))

    def _router_aux_loss(self, stats_sum):
        """HF load_balancing_loss_func over all layers' router outputs
        CONCATENATED: with equal token counts per layer, tokens_per_expert
        and router_prob_per_expert over the concat equal the across-layer
        means, so aux = E · Σ_{k,e} mean_l(m)[k,e] · mean_l(p)[e] — the
        product of means, not the mean of per-layer products."""
        m_sum, p_sum = stats_sum  # sums over layers of per-layer means
        L = self.config.n_layer
        return self.config.n_experts * jnp.sum(
            (m_sum / L) * (p_sum / L)[None, :]
        )
