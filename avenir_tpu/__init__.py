"""avenir_tpu — TPU-native LLM training framework.

The JAX/XLA/Pallas backend of this repo (SURVEY.md §2b). The compute path is
jax + flax.nnx + pallas; parallelism is data layout: a `jax.sharding.Mesh`
with axes ('data', 'fsdp', 'tensor') (plus 'expert' for MoE and 'context'
for ring attention), NamedSharding partition rules, and XLA SPMD collectives
over ICI/DCN. Import is torch-free: a TPU pod never needs torch
(BASELINE.json:5).
"""

__version__ = "0.1.0"
