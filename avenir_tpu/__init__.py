"""avenir_tpu — TPU-native LLM training framework.

The JAX/XLA/Pallas backend of this repo (SURVEY.md §2b). The compute path is
jax + flax.nnx + pallas; parallelism is data layout: a `jax.sharding.Mesh`
with axes ('data', 'fsdp', 'tensor') (plus 'expert' for MoE and 'context'
for ring attention), NamedSharding partition rules, and XLA SPMD collectives
over ICI/DCN. Import is torch-free: a TPU pod never needs torch
(BASELINE.json:5).
"""

__version__ = "0.1.0"

# API shims for older jax/flax runtimes (ambient-mesh spelling, nnx.List,
# flat_state pairs, Variable.get_value) — must be live before any model
# or loop module runs; see avenir_tpu/compat.py. Tolerate a jax-less
# interpreter: the obs subsystem (metrics/sink/report) is stdlib-only so
# tools like tools/obs_report.py must import without jax installed.
try:
    from avenir_tpu.compat import install_jax_compat as _install_jax_compat

    _install_jax_compat()
except ImportError:
    pass
