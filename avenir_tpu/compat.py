"""Ambient-mesh compatibility across jax versions.

The trainer targets the jax >= 0.6 context-mesh API (`jax.set_mesh`,
`jax.sharding.get_mesh`); older runtimes (0.4.x, still common in CPU CI
images) spell the same thing as the `Mesh` context manager backed by
`thread_resources`. This module is the ONLY place the difference lives:
`set_mesh`/`get_mesh` are drop-in helpers, and `install_jax_compat()`
patches the modern names onto the jax module itself when they are
missing, so test files and tools written against the modern API run
unmodified on the legacy runtime. Everything else in the codebase uses
explicit NamedShardings, which are stable across versions.
"""

import threading

import jax

_entered = []  # Mesh contexts entered on the legacy path, outermost first

# axes currently Manual because an enclosing compat shard_map went manual
# over them — the legacy runtime has no ambient tracking of this, so the
# adapter records it for the dynamic extent of each region's trace
# (consumed by _CompatAbstractMesh.axis_types / partition.free_axis_names)
_manual_axes = threading.local()


def _legacy_install(meshes):
    """Make `meshes` (outermost first) the ambient-mesh stack."""
    while _entered:
        _entered.pop().__exit__(None, None, None)
    for m in meshes:
        m.__enter__()
        _entered.append(m)


def _is_empty(mesh):
    try:
        return mesh is None or mesh.devices.size == 0
    except AttributeError:
        return False


class _LegacySetMesh:
    """Return value of the legacy set_mesh: the mesh is installed at
    construction (statement use persists it, like modern jax.set_mesh);
    used as a context manager, __exit__ restores the previous ambient
    stack (matching `with jax.set_mesh(mesh):` semantics)."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._prev = list(_entered)
        _legacy_install([] if _is_empty(mesh) else [mesh])

    def __enter__(self):
        return self.mesh

    def __exit__(self, *exc):
        _legacy_install(self._prev)
        return False


def set_mesh(mesh):
    """Install `mesh` as the ambient mesh (makes bare-PartitionSpec
    sharding constraints inside jit resolvable). Passing the empty mesh
    captured by `get_mesh()` before any install restores the default."""
    native = getattr(jax, "set_mesh", None)
    if native is not None and native is not set_mesh:
        return native(mesh)
    return _LegacySetMesh(mesh)


def get_mesh():
    """The current ambient mesh (an empty mesh when none is installed)."""
    native = getattr(jax.sharding, "get_mesh", None)
    if native is not None and native is not get_mesh:
        return native()
    from jax._src import mesh as _mesh_lib

    return _mesh_lib.thread_resources.env.physical_mesh


class _CompatAxisType:
    """Stand-in for jax.sharding.AxisType (0.6+): three sentinel values
    with identity comparison, which is all the codebase uses."""

    Auto = "auto"
    Explicit = "explicit"
    Manual = "manual"


class _CompatAbstractMesh:
    """The slice of the modern AbstractMesh interface the codebase reads:
    axis_names / axis_types / shape / empty. An axis reports as Manual
    while an enclosing compat `shard_map` region is being traced over it
    (the `_manual_axes` thread-local); everything else is Auto."""

    def __init__(self, names, sizes):
        self.axis_names = tuple(names)
        self._sizes = tuple(int(s) for s in sizes)

    @property
    def shape(self):
        import collections

        return collections.OrderedDict(zip(self.axis_names, self._sizes))

    @property
    def axis_types(self):
        axis_type = getattr(jax.sharding, "AxisType", _CompatAxisType)
        manual = getattr(_manual_axes, "names", frozenset())
        return tuple(
            axis_type.Manual if n in manual else axis_type.Auto
            for n in self.axis_names
        )

    @property
    def empty(self):
        return not self.axis_names

    def __eq__(self, other):
        return (getattr(other, "axis_names", None) == self.axis_names
                and tuple(getattr(other, "shape", {}).values())
                == self._sizes)

    def __hash__(self):
        return hash((self.axis_names, self._sizes))


def _abstract_view(mesh):
    if mesh is None or getattr(mesh, "devices", None) is None \
            or mesh.devices.size == 0:
        return _CompatAbstractMesh((), ())
    return _CompatAbstractMesh(mesh.axis_names,
                               [mesh.shape[n] for n in mesh.axis_names])


def get_abstract_mesh():
    """Abstract view of the current ambient mesh (modern
    jax.sharding.get_abstract_mesh)."""
    native = getattr(jax.sharding, "get_abstract_mesh", None)
    if native is not None and native is not get_abstract_mesh:
        return native()
    return _abstract_view(get_mesh())


def _install_flax_compat():
    """Bridge the flax.nnx API generations the codebase straddles:

    - `nnx.List`: newer flax's explicit list container. Older nnx treats a
      plain python list attribute as a graph node with the same integer
      path parts, so a pass-through `list()` is a faithful stand-in.
    - `State.flat_state()`: newer flax returns a sequence of
      (path, VariableState) pairs; older returns a {path: state} dict.
      Normalize to the pair-sequence form the codebase iterates.
    - `Variable.get_value()/set_value()`: accessor spelling used
      throughout the models; older flax only has the `.value` attribute.
    """
    from flax import nnx
    from flax.nnx import statelib, variablelib

    if not hasattr(nnx, "List"):
        nnx.List = list

    probe = statelib.State({"a": variablelib.VariableState(nnx.Param, 0)})
    if type(probe.flat_state()) is dict:

        class _FlatStatePairs(dict):
            """dict whose default iteration yields (path, value) PAIRS.
            flax internals keep their mapping view (`.items()`, `dict()`,
            `in`, `.keys()` all behave); codebase-style `for p, v in
            state.flat_state()` gets the newer pair-sequence behavior."""

            def __iter__(self):
                return iter(self.items())

        orig = statelib.State.flat_state

        def flat_state_pairs(self, *a, **kw):
            return _FlatStatePairs(orig(self, *a, **kw))

        statelib.State.flat_state = flat_state_pairs

    for cls in (variablelib.Variable, variablelib.VariableState):
        if not hasattr(cls, "get_value"):
            cls.get_value = lambda self: self.value
        if not hasattr(cls, "set_value"):
            def _set_value(self, v):
                self.value = v

            cls.set_value = _set_value

    if not hasattr(nnx, "to_pure_dict"):
        # newer flax's State -> plain nested-dict-of-arrays converter
        # (tests use it to compare grad trees order-independently)
        def to_pure_dict(state):
            out = {}
            for path, v in state.flat_state():
                d = out
                for k in path[:-1]:
                    d = d.setdefault(k, {})
                d[path[-1]] = (v.get_value()
                               if hasattr(v, "get_value") else v)
            return out

        nnx.to_pure_dict = to_pure_dict

    _install_none_param_compat()


def _install_none_param_compat():
    """Older nnx materializes `nnx.Param(None)` for use_bias=False /
    use_scale=False layers, so phantom bias/scale leaves (value None)
    appear in every split state — crashing shape accounting, partition
    matching, and checkpoint export written against newer flax, where
    the attribute is plain `None` and the leaf does not exist. Replace
    the sentinel Params with None after layer init and give Linear /
    LayerNorm None-tolerant __call__s (verbatim ports of the originals
    minus the `.value` access on the missing param)."""
    import inspect

    import jax.numpy as jnp
    from flax import nnx
    from flax.nnx.nn import dtypes, normalization

    # source-level probe, NOT a layer construction: building a real
    # nnx.Linear here would run jax.random ops and initialize the jax
    # backend as a side effect of `import avenir_tpu` (before callers
    # get to configure platforms)
    if "Param(None)" not in inspect.getsource(nnx.LayerNorm.__init__):
        return  # modern flax: use_bias=False leaves the attribute None
    if getattr(nnx.Linear.__init__, "_avenir_none_param_compat", False):
        return  # already installed

    lin_init = nnx.Linear.__init__

    def linear_init(self, *a, **kw):
        lin_init(self, *a, **kw)
        if getattr(self.bias, "value", 0) is None:
            self.bias = None

    def linear_call(self, inputs):
        kernel = self.kernel.value
        bias = self.bias.value if self.bias is not None else None
        inputs, kernel, bias = dtypes.promote_dtype(
            (inputs, kernel, bias), dtype=self.dtype)
        y = self.dot_general(
            inputs, kernel, (((inputs.ndim - 1,), (0,)), ((), ())),
            precision=self.precision)
        if bias is not None:
            y += jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))
        return y

    linear_init._avenir_none_param_compat = True
    nnx.Linear.__init__ = linear_init
    nnx.Linear.__call__ = linear_call

    ln_init = nnx.LayerNorm.__init__

    def layernorm_init(self, *a, **kw):
        ln_init(self, *a, **kw)
        if getattr(self.bias, "value", 0) is None:
            self.bias = None
        if getattr(self.scale, "value", 0) is None:
            self.scale = None

    def layernorm_call(self, x, *, mask=None):
        mean, var = normalization._compute_stats(
            x, self.reduction_axes, self.dtype, self.axis_name,
            self.axis_index_groups,
            use_fast_variance=self.use_fast_variance, mask=mask)
        return normalization._normalize(
            x, mean, var,
            self.scale.value if self.scale is not None else None,
            self.bias.value if self.bias is not None else None,
            self.reduction_axes, self.feature_axes, self.dtype,
            self.epsilon)

    nnx.LayerNorm.__init__ = layernorm_init
    nnx.LayerNorm.__call__ = layernorm_call


def shard_map(f, *, mesh=None, in_specs, out_specs, check_vma=True,
              axis_names=None):
    """Adapter from the modern `jax.shard_map` keyword surface to the
    legacy `jax.experimental.shard_map.shard_map`:

    - `mesh=None` resolves to the ambient mesh (set_mesh), like modern
      jax; an AbstractMesh resolves to the ambient concrete mesh.
    - `axis_names` (the axes to go Manual over) maps to the legacy
      `auto=` complement.
    - `check_vma` maps to `check_rep`.
    """
    from jax.experimental.shard_map import shard_map as legacy_shard_map

    concrete = mesh if isinstance(mesh, jax.sharding.Mesh) else get_mesh()
    assert concrete is not None and concrete.devices.size > 0, (
        "shard_map with no mesh requires an ambient mesh (set_mesh)"
    )
    manual = frozenset(axis_names if axis_names is not None
                       else concrete.axis_names)
    auto = frozenset(concrete.axis_names) - manual

    def traced(*a, **k):
        # record this region's manual axes for the dynamic extent of its
        # trace, so nested wraps (free_axis_names) see them as Manual
        prev = getattr(_manual_axes, "names", frozenset())
        _manual_axes.names = prev | manual
        try:
            return f(*a, **k)
        finally:
            _manual_axes.names = prev

    return legacy_shard_map(traced, concrete, in_specs=in_specs,
                            out_specs=out_specs, check_rep=bool(check_vma),
                            auto=auto)


def _install_legacy_shard_map_autodiff_fix():
    """jax 0.4.x `shard_map(..., auto=...)` names partial-eval RESIDUALS
    over ALL mesh axes: `_all_mesh_names_except_spmd` drops vmap
    spmd_axis_names but ignores `auto`, so when a partial-auto wrap is
    NESTED inside another manual region (the pallas flash / ring /
    ulysses wraps inside the GPipe/1f1b 'pipe' region — inner auto =
    {'pipe'}), the residual spec claims the enclosing Manual axis and
    grad lowering dies with "Axis: pipe of PartitionSpec(...) is also
    found in manual_axes". Modern jax excludes the auto axes from
    residual naming (`_all_newly_manual_mesh_names`); reproduce that
    here by wrapping BOTH partial-eval entry points (the JaxprTrace rule
    and the jaxpr-custom rule — autodiff reaches shard_map through
    either, depending on whether the region is linearized inline or via
    a staged jaxpr) to drop each region's own `auto` set, threaded
    through a thread-local for the dynamic extent of the rule. Fully
    manual regions have auto = {} and are untouched."""
    from jax._src.interpreters import partial_eval as pe
    from jax.experimental import shard_map as _sm

    if getattr(_sm, "_avenir_residual_fix", False):
        return
    orig_names = _sm._all_mesh_names_except_spmd

    def fixed_names(mesh, trace=None):
        names = orig_names(mesh, trace)
        drop = getattr(_manual_axes, "res_drop", frozenset())
        return tuple(n for n in names if n not in drop)

    _sm._all_mesh_names_except_spmd = fixed_names

    def _with_auto_dropped(auto, fn, *args, **kwargs):
        prev = getattr(_manual_axes, "res_drop", frozenset())
        _manual_axes.res_drop = frozenset(auto)
        try:
            return fn(*args, **kwargs)
        finally:
            _manual_axes.res_drop = prev

    orig_pe = _sm._shard_map_partial_eval

    def pe_fixed(trace, shard_map_p, f, tracers, mesh, in_names,
                 out_names_thunk, check_rep, rewrite, auto):
        return _with_auto_dropped(
            auto, orig_pe, trace, shard_map_p, f, tracers, mesh, in_names,
            out_names_thunk, check_rep, rewrite, auto)

    orig_custom = _sm._partial_eval_jaxpr_custom_rule

    def custom_fixed(saveable, unks_in, inst_in, eqn):
        return _with_auto_dropped(
            eqn.params.get("auto", frozenset()), orig_custom,
            saveable, unks_in, inst_in, eqn)

    # patch the REGISTRATIONS, not just the module attrs — both rules
    # were installed into their registries at import time
    pe.JaxprTrace.process_shard_map = pe_fixed
    pe.partial_eval_jaxpr_custom_rules[_sm.shard_map_p] = custom_fixed
    _sm._shard_map_partial_eval = pe_fixed
    _sm._partial_eval_jaxpr_custom_rule = custom_fixed
    _sm._avenir_residual_fix = True


def install_jax_compat():
    """Patch `jax.set_mesh` / `jax.sharding.get_mesh` onto the jax module
    and the nnx API shims onto flax when this runtime lacks them.
    Idempotent; a no-op on modern versions. Called from
    avenir_tpu/__init__.py (every consumer), platform.
    honor_jax_platforms_env (entrypoints), and tests/conftest.py."""
    legacy = not hasattr(jax, "set_mesh")  # before any patching below
    if legacy:
        jax.set_mesh = set_mesh
        # jax 0.4.x defaults jax_threefry_partitionable=False, under
        # which the SAME seeded draw yields DIFFERENT bits depending on
        # the output sharding (measured: pipe-sharded layer-stack init
        # diverges from the single-device init by ~1e-1 per weight,
        # which silently breaks every cross-mesh trajectory-parity
        # contract in the suite). Modern jax defaults the flag True;
        # align the legacy runtime so seeded draws are layout-invariant.
        jax.config.update("jax_threefry_partitionable", True)
    if not hasattr(jax.sharding, "get_mesh"):
        jax.sharding.get_mesh = get_mesh
    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = get_abstract_mesh
    if not hasattr(jax.sharding, "AxisType"):
        jax.sharding.AxisType = _CompatAxisType
    if legacy:
        # the legacy Mesh.abstract_mesh exists but reports axis_types=None;
        # replace it with the compat view (axis_types always populated)
        jax.sharding.Mesh.abstract_mesh = property(_abstract_view)
    if not hasattr(jax, "shard_map"):
        jax.shard_map = shard_map
        _install_legacy_shard_map_autodiff_fix()
    if not hasattr(jax.lax, "axis_size"):
        # psum of a literal 1 is constant-folded to the axis size (no
        # collective is emitted) — the legacy spelling of axis_size
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)
    _install_flax_compat()
