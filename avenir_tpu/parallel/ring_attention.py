"""Ring attention — context/sequence parallelism over the ICI ring
(SURVEY.md §2c SP/CP row, §5 long-context note; first-class per the build
brief).

The sequence axis is sharded over the 'context' mesh axis. Each device
keeps its q stripe resident and the kv stripes ROTATE around the ring via
`lax.ppermute` (one hop per step, n-1 hops total), overlapping each hop
with the local block attention. Blocks are combined with the same
online-softmax algebra as flash attention (normalized partial outputs +
logsumexp weights), so the result is bit-comparable to full attention up
to fp accumulation order.

Causality across blocks: a kv stripe that lies entirely in the future of
this device's q stripe contributes -1e30 scores → zero combine weight (no
dynamic skipping: the hop count is uniform across devices, which is what
keeps the ring in lockstep).

Backward (custom_vjp): the rotations are RECOMPUTED rather than saved —
residuals are only the local q/k/v stripes plus (o, lse), and dk/dv
partial sums ride the ring with their stripe (n ppermutes total, one
extra to deliver them home). Without this, autodiff through the unrolled
loop kept every rotated stripe live: O(full KV) bwd memory per device,
defeating the point of context parallelism (VERDICT r2 weak #6).

Layout contract matches ops.causal_attention: q (B, T, H, D), k/v
(B, T, H_kv, D) — GQA NEVER expanded: the kv stripes rotate (and dk/dv
partials return) at H_kv heads, and the block kernels contract q head h
against kv head h // (H/H_kv) via grouped einsums (round 4; the old
dispatch-side repeat cost G× ring bytes per hop). Runs inside jit:
`jax.shard_map` over the context axis of the ambient mesh (installed by
the training loop via jax.set_mesh).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


# kv tokens per streaming step of the in-hop block scan: the fp32 score
# working set per step is (B, H, Tq, _BLOCK_K) instead of the full
# (B, H, Tq, T/c) — measured 946 MB → see OPERATIONS.md at T=4096, c=2.
# 512 matches the flash kernels' swept kv block (ops/pallas).
_BLOCK_K = 512


def _kv_blocks(k, v, bk):
    """Pad the kv stripe to a block multiple and reshape to
    (nb, B, bk, H_kv, D) scan inputs, plus each block's base offset."""
    Tk = k.shape[1]
    nb = -(-Tk // bk)
    pad = nb * bk - Tk
    if pad:
        cfgp = [(0, 0), (0, pad), (0, 0), (0, 0)]
        k, v = jnp.pad(k, cfgp), jnp.pad(v, cfgp)
    kb = jnp.moveaxis(k.reshape(k.shape[0], nb, bk, *k.shape[2:]), 1, 0)
    vb = jnp.moveaxis(v.reshape(v.shape[0], nb, bk, *v.shape[2:]), 1, 0)
    return kb, vb, jnp.arange(nb) * bk, nb, pad


def _block_attention(q, k, v, q_offset, kv_offset, sm_scale, seq_len,
                     block_k=None):
    """One (q-stripe × kv-stripe) causal attention in fp32, the kv stripe
    STREAMED in blocks of `block_k` with the online-softmax merge —
    never materializing the (B, H, Tq, Tk) score matrix the r4 dense
    form allocated (at the T/c this module exists for that matrix was
    the whole memory profile flash attention eliminates; VERDICT r4
    missing #6). Returns the locally-normalized output (B, Tq, H, D)
    and logsumexp (B, H, Tq, 1), identical contract to the dense form
    up to fp reassociation.

    GQA: k/v arrive at H_kv heads and are NEVER expanded — the grouped
    einsums contract q head h against kv head h // (H/H_kv) directly
    (q reshaped (B, Tq, H_kv, G, D)). Scores are intrinsically H-sized,
    so only K/V storage — and, crucially, the ring's per-hop ppermute
    payload — stays at H_kv (VERDICT r3 item 4).

    Fully-future stripes keep the dense form's zeroing mechanism: every
    score masks to NEG_INF, so lse ≈ NEG_INF and the hop's combine
    weight exp(lse - lse_merged) underflows to exactly 0."""
    B, Tq, H, D = q.shape
    Tk, H_kv = k.shape[1], k.shape[2]
    G = H // H_kv
    bk = min(block_k or _BLOCK_K, Tk)  # None → module default,
    # read at CALL time (tests shrink it to force padding)
    kb, vb, bases, nb, _ = _kv_blocks(k, v, bk)
    g = q.reshape(B, Tq, H_kv, G, D)
    q_pos = q_offset + jnp.arange(Tq)
    tr = lambda w: jnp.transpose(w, (0, 2, 1, 3))  # (B,H,Tq,1)→(B,Tq,H,1)

    def body(carry, inp):
        m, l, o = carry
        kblk, vblk, base = inp
        s = jnp.einsum("bqhgd,bkhd->bhgqk", g, kblk,
                       preferred_element_type=jnp.float32) * sm_scale
        s = s.reshape(B, H, Tq, bk)
        k_pos = kv_offset + base + jnp.arange(bk)
        # k_pos < kv_offset + Tk: the block padding's phantom positions
        # alias the NEXT stripe's global positions on interior stripes —
        # without the local bound they pass the causal/seq_len mask and
        # their zero keys inflate l (review r5: 0.24 max-abs corruption
        # at T/c not a multiple of block_k)
        mask = (q_pos[:, None] >= k_pos[None, :]) \
            & (k_pos < seq_len)[None, :] \
            & (k_pos < kv_offset + Tk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)  # (B, H, Tq, 1)
        p = jnp.exp(s - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pg = p.astype(vblk.dtype).reshape(B, H_kv, G, Tq, bk)
        ob = jnp.einsum("bhgqk,bkhd->bqhgd", pg, vblk,
                        preferred_element_type=jnp.float32)
        o = o * tr(alpha) + ob.reshape(B, Tq, H, D)
        return (m_new, l, o), None

    m0 = jnp.full((B, H, Tq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq, 1), jnp.float32)
    o0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    (m, l, o), _ = jax.lax.scan(body, (m0, l0, o0), (kb, vb, bases))
    l = jnp.maximum(l, 1e-30)
    o = o / jnp.transpose(l, (0, 2, 1, 3))
    return o, m + jnp.log(l)


def _rotate(xs, axis_name, n, idx, use_psum):
    """One cyclic hop around the ring: ppermute normally; on the legacy
    harness, when the ring nests inside another manual region (the
    'pipe' pipeline), jax 0.4.x's partial-auto lowering CHECK-crashes
    XLA on ppermute (same breakage parallel/pipeline._use_psum_hop
    documents) — emulate the rotation with a masked psum all-gather
    and a neighbor gather instead. `idx` is the ring position already
    shipped in as data, which is exactly what the emulation needs."""
    perm = [(j, (j + 1) % n) for j in range(n)]
    if not use_psum:
        return jax.lax.ppermute(xs, axis_name, perm)

    def rot(x):
        oh = jnp.arange(n) == idx
        full = jax.lax.psum(
            x[None] * oh.reshape((n,) + (1,) * x.ndim).astype(x.dtype),
            axis_name)
        return full[(idx - 1) % n]

    return jax.tree.map(rot, xs)


def _ring_forward(q, k, v, idx, *, axis_name, seq_len, sm_scale,
                  block_k=None, psum_rotate=False):
    """n-hop ring forward on local stripes (B, T/c, H, D). Returns the
    merged output (q.dtype) and global logsumexp (B, H, Tq, 1) fp32.

    `idx` is this device's ring position, delivered as DATA (a sharded
    iota sliced by the shard_map — see ring_causal_attention) rather
    than `jax.lax.axis_index`: under the Shardy partitioner axis_index
    inside a NESTED shard_map lowers to an sdy.manual_computation that
    re-binds every enclosing manual axis ("axis 'pipe' is already bound
    by a parent" verifier error), which broke ring-under-pipeline;
    ppermute and the other collectives lower fine (r5, repro in
    tools/exp_v1_partition.py notes)."""
    n = jax.lax.axis_size(axis_name)
    Tl = q.shape[1]

    o = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((q.shape[0], q.shape[2], Tl, 1), NEG_INF, jnp.float32)
    kv = (k, v)
    for i in range(n):  # static unroll: n is the mesh axis size
        src = (idx - i) % n  # original owner of the kv stripe we now hold
        o_i, lse_i = _block_attention(
            q, kv[0], kv[1],
            q_offset=idx * Tl, kv_offset=src * Tl,
            sm_scale=sm_scale, seq_len=seq_len, block_k=block_k,
        )
        # online merge of normalized partials
        lse_new = jnp.logaddexp(lse, lse_i)
        w_old = jnp.exp(lse - lse_new)  # (B, H, Tq, 1)
        w_new = jnp.exp(lse_i - lse_new)
        tr = lambda w: jnp.transpose(w, (0, 2, 1, 3))  # → (B, Tq, H, 1)
        o = o * tr(w_old) + o_i * tr(w_new)
        lse = lse_new
        if i < n - 1:
            # rotate kv one hop around the ring while the next block computes
            kv = _rotate(kv, axis_name, n, idx, psum_rotate)
    return o.astype(q.dtype), lse


def _block_grads(q, k, v, do, lse, delta, q_offset, kv_offset, sm_scale,
                 seq_len, block_k=None):
    """Flash-style block backward against GLOBAL softmax stats: with
    p = exp(s - lse) (lse the merged ring logsumexp) the per-stripe grads
    sum to the full-attention grads. The kv stripe is STREAMED in
    `block_k` blocks like the forward — scores/ds exist only at
    (B, H, Tq, block_k); dq accumulates across blocks in the scan carry
    and dk/dv come out per-block (the scan's stacked ys), so no
    (Tq, Tk) matrix is ever live. Returns fp32 (dq, dk, dv) stripes —
    dk/dv at H_kv heads (the grouped einsums fold the GQA group sum, so
    the dk/dv partials riding the ring stay H_kv-sized too)."""
    B, Tq, H, D = q.shape
    Tk, H_kv = k.shape[1], k.shape[2]
    G = H // H_kv
    bk = min(block_k or _BLOCK_K, Tk)  # None → module default,
    # read at CALL time (tests shrink it to force padding)
    kb, vb, bases, nb, pad = _kv_blocks(k, v, bk)
    qg = q.astype(jnp.float32).reshape(B, Tq, H_kv, G, D)
    dog = do.astype(jnp.float32).reshape(B, Tq, H_kv, G, D)
    q_pos = q_offset + jnp.arange(Tq)

    def body(dq, inp):
        kblk, vblk, base = inp
        kf = kblk.astype(jnp.float32)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg, kf,
                       preferred_element_type=jnp.float32) * sm_scale
        s = s.reshape(B, H, Tq, bk)
        k_pos = kv_offset + base + jnp.arange(bk)
        mask = (q_pos[:, None] >= k_pos[None, :]) \
            & (k_pos < seq_len)[None, :] \
            & (k_pos < kv_offset + Tk)[None, :]  # pad bound, as in fwd
        s = jnp.where(mask[None, None], s, NEG_INF)
        p = jnp.exp(s - lse)  # rows sum to 1 across the whole ring
        dp = jnp.einsum("bqhgd,bkhd->bhgqk", dog, vblk.astype(jnp.float32),
                        preferred_element_type=jnp.float32
                        ).reshape(B, H, Tq, bk)
        ds = p * (dp - delta) * sm_scale
        dsg = ds.reshape(B, H_kv, G, Tq, bk)
        dq = dq + jnp.einsum("bhgqk,bkhd->bqhgd", dsg, kf,
                             preferred_element_type=jnp.float32
                             ).reshape(B, Tq, H, D)
        dk_b = jnp.einsum("bhgqk,bqhgd->bkhd", dsg, qg,
                          preferred_element_type=jnp.float32)
        dv_b = jnp.einsum("bhgqk,bqhgd->bkhd",
                          p.reshape(B, H_kv, G, Tq, bk), dog,
                          preferred_element_type=jnp.float32)
        return dq, (dk_b, dv_b)

    dq0 = jnp.zeros((B, Tq, H, D), jnp.float32)
    dq, (dk_b, dv_b) = jax.lax.scan(body, dq0, (kb, vb, bases))
    # (nb, B, bk, Hkv, D) → (B, nb·bk, Hkv, D), padded tail dropped
    # (masked scores → p = ds = 0 there, so the pads carry zero grads)
    dk = jnp.moveaxis(dk_b, 0, 1).reshape(B, nb * bk, H_kv, D)[:, :Tk]
    dv = jnp.moveaxis(dv_b, 0, 1).reshape(B, nb * bk, H_kv, D)[:, :Tk]
    return dq, dk, dv


def _ring_backward(q, k, v, o, lse, do, idx, *, axis_name, seq_len,
                   sm_scale, block_k=None, psum_rotate=False):
    """Ring backward that RE-ROTATES the kv stripes instead of keeping all
    n of them as autodiff residuals (VERDICT r2 weak #6: the unrolled-loop
    residuals made bwd memory O(full KV) per device — exactly what context
    parallelism exists to avoid). dk/dv partial sums travel around the ring
    WITH their stripe; a final hop returns them to the stripe's owner.
    Live memory: the local stripes plus one in-flight (kv, dkv) — O(1).
    `idx` is the device's ring position as data (see _ring_forward)."""
    n = jax.lax.axis_size(axis_name)
    Tl = q.shape[1]
    # delta = rowsum(do * o) per query, shaped like lse (B, H, Tq, 1)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.transpose(delta, (0, 2, 1))[..., None]

    dq = jnp.zeros(q.shape, jnp.float32)
    kv_dkv = (k, v, jnp.zeros(k.shape, jnp.float32),
              jnp.zeros(v.shape, jnp.float32))
    for i in range(n):
        src = (idx - i) % n
        dq_i, dk_i, dv_i = _block_grads(
            q, kv_dkv[0], kv_dkv[1], do, lse, delta,
            q_offset=idx * Tl, kv_offset=src * Tl,
            sm_scale=sm_scale, seq_len=seq_len, block_k=block_k,
        )
        dq = dq + dq_i
        kv_dkv = (kv_dkv[0], kv_dkv[1], kv_dkv[2] + dk_i, kv_dkv[3] + dv_i)
        if i < n - 1:
            kv_dkv = _rotate(kv_dkv, axis_name, n, idx, psum_rotate)
    # after n-1 rotations device idx holds stripe (idx+1)'s accumulated
    # dk/dv; one more hop delivers every stripe's grads to its owner
    dk_out, dv_out = _rotate(
        (kv_dkv[2], kv_dkv[3]), axis_name, n, idx, psum_rotate
    )
    return dq.astype(q.dtype), dk_out.astype(k.dtype), dv_out.astype(v.dtype)


@functools.lru_cache(maxsize=32)
def _build_ring_body(axis_name, seq_len, sm_scale, block_k=None,
                     psum_rotate=False):
    """Per-device ring attention with a custom VJP (one cached closure per
    static config — block_k is part of the cache key). Takes
    (q, k, v, pos) where pos is the (1,)-shaped local slice of the
    position iota; its cotangent is float0 (integer input)."""
    import numpy as np

    @jax.custom_vjp
    def f(q, k, v, pos):
        o, _ = _ring_forward(q, k, v, pos[0], axis_name=axis_name,
                             seq_len=seq_len, sm_scale=sm_scale,
                             block_k=block_k, psum_rotate=psum_rotate)
        return o

    def f_fwd(q, k, v, pos):
        o, lse = _ring_forward(q, k, v, pos[0], axis_name=axis_name,
                               seq_len=seq_len, sm_scale=sm_scale,
                               block_k=block_k, psum_rotate=psum_rotate)
        return o, (q, k, v, o, lse, pos)

    def f_bwd(res, do):
        q, k, v, o, lse, pos = res
        dq, dk, dv = _ring_backward(q, k, v, o, lse, do, pos[0],
                                    axis_name=axis_name, seq_len=seq_len,
                                    sm_scale=sm_scale, block_k=block_k,
                                    psum_rotate=psum_rotate)
        return dq, dk, dv, np.zeros(pos.shape, jax.dtypes.float0)

    f.defvjp(f_fwd, f_bwd)
    return f


def context_shard_map(body, *, axis_name, mesh=None, n_in=3,
                      extra_in_specs=()):
    """Shared shard_map wrapper for sequence-parallel attention impls
    (ring + ulysses): batch dims ride the data-like axes, the sequence
    dim rides `axis_name`, heads/head_dim replicated. ONE home for the
    spec so the two impls cannot drift.

    Names only the FREE (non-Manual) mesh axes, so the wrap nests
    correctly inside the GPipe 'pipe' region: a default all-axes
    shard_map there would claim its inputs replicated over the Manual
    'pipe' axis and its transpose would psum cotangents over it —
    silently wrong gradients (r4 measured 1.9e-3 on pipe×context and
    fail-louded the mesh combination away; the axis_names rule fixes
    the root cause — see partition.free_axis_names)."""
    from avenir_tpu.parallel.partition import BATCH_AXES, free_axis_names

    names = free_axis_names(
        mesh.abstract_mesh if mesh is not None else None
    )
    assert axis_name in names, (
        f"context axis {axis_name!r} is already Manual at this trace "
        "position; sequence-parallel attention cannot nest over it"
    )
    spec = P(BATCH_AXES, axis_name, None, None)
    kwargs = dict(in_specs=(spec,) * n_in + tuple(extra_in_specs),
                  out_specs=spec, check_vma=False, axis_names=names)
    if mesh is not None:
        kwargs["mesh"] = mesh
    return jax.shard_map(body, **kwargs)


def ring_causal_attention(q, k, v, *, axis_name="context", mesh=None,
                          sm_scale=None, block_k=None):
    """Causal attention with the sequence sharded over `axis_name`.
    q: GLOBAL (B, T, H, D) under jit; k/v may be GQA (B, T, H_kv, D)
    with H_kv | H. T must divide by the axis size. Uses the ambient mesh
    (jax.set_mesh) when `mesh` is None."""
    B, T, H, D = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    from avenir_tpu import compat

    # nested inside another manual region on the legacy runtime: the
    # per-hop ppermute cannot lower there — switch to the psum-emulated
    # rotation (see _rotate; compat tracks the enclosing Manual axes)
    psum_rotate = (getattr(jax, "shard_map", None) is compat.shard_map
                   and bool(getattr(compat._manual_axes, "names",
                                    frozenset())))
    body = _build_ring_body(axis_name, T, float(sm_scale), block_k,
                            psum_rotate)
    am = mesh.abstract_mesh if mesh is not None \
        else jax.sharding.get_abstract_mesh()
    c = dict(am.shape)[axis_name]
    # each device's ring position rides in as DATA (P(axis_name) slices
    # the iota one entry per shard) — jax.lax.axis_index cannot lower in
    # a nested shard_map under Shardy (see _ring_forward)
    pos = jnp.arange(c, dtype=jnp.int32)
    return context_shard_map(body, axis_name=axis_name, mesh=mesh,
                             extra_in_specs=(P(axis_name),))(q, k, v, pos)
