"""Ulysses-style sequence parallelism — all-to-all over the 'context' axis
(SURVEY.md §2c SP/CP row; the build brief's "ring attention OR all-to-all
sequence/context parallelism" — this is the all-to-all arm, complementing
parallel/ring_attention.py).

Mechanism (DeepSpeed-Ulysses shape): tokens arrive sequence-sharded
(B, T/c, H, D) per device. One `lax.all_to_all` re-shards heads instead
of sequence — (B, T, H/c, D): every device now sees the FULL sequence for
its head subset, runs an ordinary causal attention locally (the Pallas
flash kernel on TPU — no cross-device softmax algebra needed, unlike the
ring), and a second all-to-all restores sequence sharding.

GQA is NATIVE: when the context axis divides the KV head count, K/V go
through the all-to-all UNREPEATED at (B, T/c, H_kv, D) — each device
lands q heads [i·H/c, (i+1)·H/c) and kv heads [i·H_kv/c, (i+1)·H_kv/c),
whose local group mapping j // (H/H_kv) is exactly the global one — and
the local flash kernel resolves shared heads in its index maps. Only when
c does not divide H_kv are KV heads repeated (by the smallest factor that
restores divisibility, falling back to full H).

Tradeoffs vs the ring (both ship; pick per workload with
`--context_parallel_impl`):
  - comm: Ulysses moves q+o at H heads and k+v at H_kv heads once each
    ((2·H + 2·H_kv)·B·T·D/c per device, all-to-all); the ring moves k+v
    (c-1) times at H_kv heads (2·H_kv·B·T·D·(c-1)/c — the round-4 ring
    rotates unrepeated GQA stripes) plus one extra hop returning dk/dv
    in the backward. At Llama-3's 32:8 (H = 4·H_kv) the ring's forward
    volume beats Ulysses' once c > 5; at MHA Ulysses wins only c = 2.
  - compute: Ulysses runs the single-device flash kernel (fast path,
    fused bwd) on full-T slices; the ring pays the online-softmax
    combine and lockstep hops but never materializes full T per device.
  - memory: Ulysses holds full-T activations for H/c heads per device
    (T scaling bounded by heads); the ring holds only T/c stripes — the
    ring is the only option when T/c is all that fits.
  - constraint: c must divide H.

Backward: both all-to-alls are linear — their autodiff transpose is the
reverse all-to-all, emitted by shard_map/XLA; the local attention brings
its own custom_vjp. No hand-written backward needed.

Layout contract matches ops.causal_attention: global (B, T, H, D) q and
(B, T, H_kv, D) k/v under jit, sequence sharded on `axis_name` of the
ambient (or given) mesh.
"""

import jax
import jax.numpy as jnp

from avenir_tpu.parallel.ring_attention import context_shard_map


def _build_body(axis_name, psum_a2a=False):
    def body(q, k, v, pos):
        # local stripes: q (B, T/c, H, D), k/v (B, T/c, H_kv, D). `pos`
        # is this device's context index shipped in as data (ring-style),
        # consumed only by the psum-emulated all-to-all below.
        c = jax.lax.axis_size(axis_name)
        idx = pos[0]
        H, H_kv = q.shape[2], k.shape[2]
        assert H % c == 0, (
            f"ulysses needs context axis ({c}) to divide n_head ({H})"
        )
        assert H_kv % c == 0  # wrapper guarantees (repeats otherwise)

        def _gather(x):
            # masked-psum all-gather over the context axis: (c, *x.shape)
            oh = jnp.arange(c) == idx
            return jax.lax.psum(
                x[None] * oh.reshape((c,) + (1,) * x.ndim).astype(x.dtype),
                axis_name)

        def seq_to_heads(x):
            # (B, T/c, h, D) -> (B, T, h/c, D)
            if not psum_a2a:
                return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                          concat_axis=1, tiled=True)
            # legacy harness, nested inside another manual region: the
            # partial-auto all_to_all cannot lower (same class as the
            # pipeline/ring ppermute breakage — parallel/pipeline.
            # _use_psum_hop) — emulate: gather every sender's stripe,
            # take this device's head chunk of each, concat in sender
            # order along the sequence (== tiled all_to_all semantics)
            full = _gather(x)
            hc = x.shape[2] // c
            return jnp.concatenate(
                [jax.lax.dynamic_slice_in_dim(full[j], idx * hc, hc,
                                              axis=2)
                 for j in range(c)], axis=1)

        def heads_to_seq(x):
            if not psum_a2a:
                return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                          concat_axis=2, tiled=True)
            full = _gather(x)
            tl = x.shape[1] // c
            return jnp.concatenate(
                [jax.lax.dynamic_slice_in_dim(full[j], idx * tl, tl,
                                              axis=1)
                 for j in range(c)], axis=2)

        qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        from avenir_tpu.ops.attention import causal_attention

        # full-sequence causal attention on the local head subset; "auto"
        # resolves to the Pallas flash kernel on TPU (GQA via its index
        # maps), the jnp reference on the CPU harness — never back to a
        # sequence-parallel impl
        oh = causal_attention(qh, kh, vh, impl="auto")
        return heads_to_seq(oh)

    return body


def ulysses_causal_attention(q, k, v, *, axis_name="context", mesh=None,
                             sm_scale=None):
    """Causal attention with the sequence sharded over `axis_name` via
    head/sequence all-to-alls. q: GLOBAL (B, T, H, D); k/v may be GQA
    (B, T, H_kv, D). T and H must divide by the axis size. Uses the
    ambient mesh (jax.set_mesh) when `mesh` is None."""
    assert sm_scale is None, (
        "ulysses derives sm_scale from head_dim (the local kernel's "
        "default); non-default scaling is not supported"
    )
    if mesh is not None:
        c = dict(mesh.shape)[axis_name]
    else:
        # under jit only the abstract mesh is queryable
        c = dict(jax.sharding.get_abstract_mesh().shape)[axis_name]
    H, H_kv = q.shape[2], k.shape[2]
    if H_kv % c != 0:
        # smallest repeat factor restoring divisibility that still divides
        # the GQA group count; else expand fully to H
        group = H // H_kv
        rep = next((r for r in range(2, group + 1)
                    if group % r == 0 and (H_kv * r) % c == 0), group)
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    from jax.sharding import PartitionSpec as P

    from avenir_tpu import compat

    # nested inside another manual region on the legacy runtime: the
    # all-to-alls cannot lower there — psum-emulated re-shard instead
    # (same gate as ring_attention's psum rotation)
    psum_a2a = (getattr(jax, "shard_map", None) is compat.shard_map
                and bool(getattr(compat._manual_axes, "names",
                                 frozenset())))
    body = _build_body(axis_name, psum_a2a)
    pos = jnp.arange(c, dtype=jnp.int32)
    return context_shard_map(body, axis_name=axis_name, mesh=mesh,
                             extra_in_specs=(P(axis_name),))(q, k, v, pos)
