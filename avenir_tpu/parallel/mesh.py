"""Device mesh & multi-process bring-up (SURVEY.md §2b T3, §5 "Distributed
communication backend").

The TPU-native answer to torchrun+NCCL (train.py:106-118): multi-host
rendezvous via `jax.distributed.initialize`, then ONE global mesh whose
axis order follows the physical ICI topology (`mesh_utils.create_device_mesh`)
so the heavy collectives (FSDP gathers, MoE all-to-all, TP reductions) ride
the fastest links.

Canonical axes, outermost→innermost:
    data    — pure data parallelism (gradient psum); put DCN here multi-slice
    fsdp    — data parallelism with params/opt-state sharded (ZeRO-3)
    expert  — MoE expert parallelism (all-to-all dispatch/combine)
    pipe    — pipeline parallelism (GPipe microbatch ppermute; one
              activation hop per microbatch per boundary — light traffic,
              so it sits outside context/tensor)
    context — sequence/context parallelism (ring attention ppermute)
    tensor  — megatron-style tensor parallelism (innermost: most traffic)

Every mesh carries all six axes (unused ones have size 1) so partition
rules can always name any axis.
"""

import os

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXES = ("data", "fsdp", "expert", "pipe", "context", "tensor")


def _already_initialized() -> bool:
    # peek at the distributed client without touching the local backend
    # (jax.process_count() would initialize it, after which
    # jax.distributed.initialize refuses to run)
    from jax._src import distributed as _dist

    return getattr(_dist.global_state, "client", None) is not None


def initialize_distributed():
    """Multi-host rendezvous (the NCCL-init equivalent of
    train.py:106-118's init_process_group). MUST run before any JAX
    computation. Three cases:
      - explicit env (JAX_COORDINATOR_ADDRESS/_NUM_PROCESSES/_PROCESS_ID,
        the torchrun-style launcher contract) → explicit initialize
      - multi-host TPU pod (worker hostnames advertised by the TPU
        runtime) → argless initialize(), which auto-detects from metadata
      - single host → no-op"""
    if _already_initialized():
        return
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
            process_id=int(os.environ["JAX_PROCESS_ID"]),
        )
        return
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if len([h for h in hostnames.split(",") if h]) > 1:
        jax.distributed.initialize()  # auto-detect via TPU metadata


def is_coordinator() -> bool:
    return jax.process_index() == 0


def parse_mesh_shape(spec: str, n_devices: int) -> dict:
    """Parse "data:4,fsdp:2" → {'data': 4, 'fsdp': 2, ...rest 1}. One axis
    may be -1 (inferred). Empty spec → all devices on 'data'."""
    sizes = {a: 1 for a in AXES}
    if not spec:
        sizes["data"] = n_devices
        return sizes
    wildcard = None
    for part in spec.split(","):
        name, _, val = part.strip().partition(":")
        if name not in AXES:
            raise ValueError(f"unknown mesh axis {name!r}; valid: {AXES}")
        v = int(val)
        if v == -1:
            assert wildcard is None, "only one mesh axis may be -1"
            wildcard = name
        else:
            assert v >= 1, f"axis {name} size must be >=1 or -1"
            sizes[name] = v
    known = int(np.prod([v for v in sizes.values()]))
    if wildcard is not None:
        assert n_devices % known == 0, (
            f"device count {n_devices} not divisible by fixed axes product {known}"
        )
        sizes[wildcard] = n_devices // known
        known = n_devices
    if known > n_devices:
        raise ValueError(
            f"mesh {spec!r} needs {known} devices but only {n_devices} are present"
        )
    # known < n_devices is allowed: the mesh uses the first `known` devices
    # (debug runs on a slice of the chip pool)
    return sizes


def _parse_dcn_sizes(spec: str) -> dict:
    """Parse a DCN factor spec ("data:2") → {axis: factor, ...rest 1}."""
    sizes = {a: 1 for a in AXES}
    if not spec:
        return sizes
    for part in spec.split(","):
        name, _, val = part.strip().partition(":")
        if name not in AXES:
            raise ValueError(f"unknown mesh axis {name!r}; valid: {AXES}")
        v = int(val)
        assert v >= 1, f"dcn factor for {name} must be >= 1"
        sizes[name] = v
    return sizes


def make_mesh(spec: str = "", devices=None, dcn_spec: str = "") -> Mesh:
    """Build the global mesh. Axis order is AXES; the physical device
    assignment is topology-aware on TPU (ICI-contiguous subcubes).

    Multi-slice (SURVEY.md §5 "Distributed communication backend"): DCN is
    an OUTER factor of a mesh axis. `dcn_spec` names the per-axis slice
    counts (normally "data:<n_slices>" — gradient psum is the only
    collective cheap enough for DCN bandwidth); `spec` stays the per-slice
    ICI shape. Combined axis size = dcn_factor × ici_size, with the DCN
    factor OUTERMOST in the device order, so any collective over a
    combined axis decomposes into a cross-slice phase over groups that are
    each ICI-contiguous. On real multi-slice metadata (devices carry
    slice_index) the assignment comes from
    `mesh_utils.create_hybrid_device_mesh`; elsewhere (CPU harness,
    single slice) the same slice-major ordering is emulated so tests
    exercise the identical layout."""
    devices = jax.devices() if devices is None else devices
    if not dcn_spec:
        sizes = parse_mesh_shape(spec, len(devices))
        shape = tuple(sizes[a] for a in AXES)
        n_used = int(np.prod(shape))
        devices = list(devices)[:n_used]
        try:
            dev_array = mesh_utils.create_device_mesh(
                shape, devices=np.asarray(devices)
            )
        except (ValueError, AssertionError, NotImplementedError):
            # non-TPU platforms / odd shapes: plain row-major assignment
            dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, AXES)

    dcn = _parse_dcn_sizes(dcn_spec)
    n_slices = int(np.prod(list(dcn.values())))
    assert len(devices) % n_slices == 0, (
        f"{len(devices)} devices not divisible into {n_slices} slices"
    )
    ici = parse_mesh_shape(spec, len(devices) // n_slices)
    ici_shape = tuple(ici[a] for a in AXES)
    dcn_shape = tuple(dcn[a] for a in AXES)
    n_total = int(np.prod(ici_shape)) * n_slices
    devices = list(devices)[:n_total]
    slice_idx = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_idx and 1 < len(slice_idx) != n_slices:
        # real MULTI-slice metadata that contradicts dcn_spec: emulating
        # here would lay ICI axes across DCN links — a silent order-of-
        # magnitude collective slowdown. Fail loud instead. (A single real
        # slice emulating a multi-slice layout is fine — the "DCN" hops
        # ride faster links, not slower — and is the documented dev path.)
        raise ValueError(
            f"dcn_spec {dcn_spec!r} asks for {n_slices} slices but devices "
            f"report {len(slice_idx)} distinct slice_index values "
            f"({sorted(slice_idx)}); fix dcn_spec to match the real topology"
        )
    if len(slice_idx) == n_slices and None not in slice_idx:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=np.asarray(devices)
        )
    else:
        # emulate multi-slice: contiguous device groups play the slices.
        # reshape (dcn..., ici...) then interleave (dcn_a, ici_a) per axis →
        # dcn factor outermost on every combined axis.
        arr = np.asarray(devices).reshape(dcn_shape + ici_shape)
        k = len(AXES)
        arr = arr.transpose(*(x for i in range(k) for x in (i, k + i)))
        arr = arr.reshape(tuple(d * s for d, s in zip(dcn_shape, ici_shape)))
        dev_array = arr
    return Mesh(dev_array, AXES)
