"""avenir_tpu.parallel — mesh, sharding rules, and explicit collectives
(SURVEY.md §1 L2/L0, §2b T3/T4, §2c).

Parallelism here is data layout, not module wrappers: a single
`jax.sharding.Mesh` with canonical axes, regex partition rules mapping
param paths to PartitionSpecs, and XLA SPMD inserting the collectives
(psum for DP, all-gather/reduce-scatter for FSDP, all-to-all for EP).
Explicit collectives appear only inside shard_map regions (MoE dispatch,
ring attention).
"""

from avenir_tpu.parallel.mesh import (
    AXES,
    initialize_distributed,
    make_mesh,
    parse_mesh_shape,
)
from avenir_tpu.parallel.partition import (
    PrecisionPolicy,
    batch_pspec,
    match_partition_rules,
    match_precision_rules,
    named_shardings,
    precision_for,
    rules_for_model,
)
