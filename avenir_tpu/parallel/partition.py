"""The unified partition + precision rules table (SURVEY.md §2b T4;
ISSUE 15 refactor).

The pattern follows the public match_partition_rules idiom (SNIPPETS.md:19-32):
param paths are '/'-joined strings, rules are ordered
(regex, PartitionSpec, PrecisionPolicy) rows tried in order, and an
unmatched param is a hard error — fail loud (SNIPPETS.md:31) so silent
replication can't eat HBM (and a tensor with no declared precision can't
silently pick one).

ONE table serves every model family: each row is a TENSOR CLASS
(column-parallel up-projection, row-parallel down-projection, embedding,
norm, ...) whose regex names that class's parameter paths across
GPT/Llama/Mixtral, so sharding AND quantization policy are declared once
per class instead of once per (family, tensor). The per-family resolved
specs are bit-equal to the old hand-wired per-family tables (pinned by
tests/test_partition.py::test_unified_rules_match_legacy_specs).

Sharding conventions (axes from mesh.AXES):
  - Linear kernels alternate ('fsdp','tensor') / ('tensor','fsdp') —
    column-parallel up-projections, row-parallel down-projections, so TP
    needs one psum per block and FSDP shards every matmul weight.
  - Embeddings shard vocab on 'tensor', features on 'fsdp'.
  - Norm scales/biases are replicated (tiny).
  - The batch shards on ('data','fsdp') combined: 'fsdp' is still data
    parallelism (ZeRO), it just also shards the params — XLA SPMD emits
    the all-gather-at-use / reduce-scatter-of-grads (BASELINE.json:9).

Precision conventions (consumed under `compute_dtype='int8'`,
ops/quant.py; inert at bf16/fp32 — the bf16 path through this table is
bit-identical to the old one):
  - Matmul kernels (projections, expert FFNs, the lm-head / tied wte in
    its MATMUL uses) quantize with delayed backward scaling.
  - Norm scales, biases, the position table and the tiny MoE router gate
    never quantize (sub-percent of FLOPs; router logits decide token
    routing, where rounding errors change the computation graph, not
    just its numerics).
  - Scalar/vector params are structurally skipped besides (there is no
    contraction axis to carry a per-channel scale) —
    match_precision_rules coerces them to no-quant whatever their row
    says.
"""

import functools
import re
from typing import NamedTuple

from jax.sharding import NamedSharding, PartitionSpec as P


class PrecisionPolicy(NamedTuple):
    """Per-tensor-class precision policy, riding in the rules table next
    to the PartitionSpec: `quantize` marks the tensor's MATMUL consumers
    as int8-eligible under compute_dtype='int8' (a tensor can also be
    consumed by gathers — the wte embedding lookup — which never
    quantize); `scaling` picks the backward cotangent calibration
    (ops/quant.py: 'delayed' per-tensor window-calibrated, 'dynamic'
    per-channel)."""

    quantize: bool = False
    scaling: str = "delayed"


QUANT = PrecisionPolicy(quantize=True, scaling="delayed")
NO_QUANT = PrecisionPolicy(quantize=False)

# ---- THE rules table: one row per tensor class, all families ----

UNIFIED_RULES = (
    # Mixtral stacked experts: (E, in, out) nnx.Params on a leading
    # 'expert' axis; w1/w3 up-project (column-parallel), w2 down-projects
    (r"experts/(w1|w3)$", P("expert", "fsdp", "tensor"), QUANT),
    (r"experts/w2$", P("expert", "tensor", "fsdp"), QUANT),
    # tiny MoE router: replicated, never quantized (routing decisions)
    (r"block_sparse_moe/gate/kernel$", P(None, None), NO_QUANT),
    # token embeddings: vocab on 'tensor', features on 'fsdp'. QUANT
    # applies to the tensor's matmul uses (the GPT TIED lm-head consumes
    # wte as the CE projection); the embedding GATHER itself never
    # quantizes.
    (r"(wte|embed_tokens)/embedding$", P("tensor", "fsdp"), QUANT),
    # learned position table: gather-only, no matmul use
    (r"wpe/embedding$", P(None, "fsdp"), NO_QUANT),
    # column-parallel up-projections (QKV, MLP up / gate, untied lm-head)
    (r"(attn/c_attn|mlp/c_fc|q_proj|k_proj|v_proj|gate_proj|up_proj"
     r"|lm_head)/kernel$", P("fsdp", "tensor"), QUANT),
    # row-parallel down-projections (attention out, MLP down)
    (r"(attn/c_proj|mlp/c_proj|o_proj|down_proj)/kernel$",
     P("tensor", "fsdp"), QUANT),
    # biases follow their kernel's output sharding; never quantized
    (r"(attn/c_attn|mlp/c_fc)/bias$", P("tensor"), NO_QUANT),
    (r"(attn/c_proj|mlp/c_proj)/bias$", P(), NO_QUANT),
    # norms: replicated, fp32-sensitive, never quantized
    (r"(ln_1|ln_2|ln_f)/(scale|bias)$", P(), NO_QUANT),
    (r"(input_layernorm|post_attention_layernorm|norm)/scale$", P(),
     NO_QUANT),
)


def rules_for_model(model_type: str):
    """Every family resolves through the SAME table (the point of the
    refactor); the family argument stays as the fail-loud gate on
    unknown model types and the hook for any future family-gated row."""
    assert model_type in ("gpt", "llama", "mixtral"), (
        f"unknown model_type {model_type!r}")
    return UNIFIED_RULES


def path_str(path) -> str:
    return "/".join(str(p) for p in path)


def has_scan_segment(path) -> bool:
    """True if the param path crosses a scan-stacked layer container
    (attribute named `*_scan`, models/common.stacked_layers): its array
    carries a leading (n_layer, ...) axis the per-layer rules don't know
    about."""
    segs = path.split("/") if isinstance(path, str) else [str(p) for p in path]
    return any(s.endswith("_scan") for s in segs)


def match_partition_rules(rules, paths):
    """Map each path (tuple or string) to its first matching PartitionSpec
    (ordering wins — the first row whose regex matches decides).
    Params under a scan-stacked container get a leading 'pipe' axis:
    with pipeline parallelism each stage owns a contiguous block of
    layers (parallel/pipeline.py); on meshes without a pipe axis (size
    1) the entry is inert and each scan step finds its full layer
    weights locally. Raises ValueError listing every unmatched path.
    Accepts both unified 3-tuple rows and legacy (regex, spec) pairs
    (tests that pin the old hand-wired tables)."""
    out = {}
    misses = []
    for path in paths:
        s = path_str(path) if not isinstance(path, str) else path
        for pattern, spec, *_ in rules:
            if re.search(pattern, s):
                out[path] = (P("pipe", *tuple(spec))
                             if has_scan_segment(path) else spec)
                break
        else:
            misses.append(s)
    if misses:
        raise ValueError(
            f"no partition rule matched param path(s): {misses}. "
            "Add a rule — silent replication is not allowed."
        )
    return out


def match_precision_rules(rules, paths, shapes=None):
    """The precision half of the same table: map each path to its
    PrecisionPolicy by the SAME ordered first-match walk as
    match_partition_rules — one regex, one row, both halves of the
    tensor's policy. Legacy 2-tuple rows resolve to NO_QUANT.

    `shapes` (when given, {path: dims}) applies the scalar skip: params
    with fewer than 2 dims (norm scales, biases) coerce to NO_QUANT
    whatever their row says — a vector has no contraction axis to carry
    a per-channel scale. Fail-loud on unmatched paths, same policy."""
    out = {}
    misses = []
    for path in paths:
        s = path_str(path) if not isinstance(path, str) else path
        for pattern, _spec, *rest in rules:
            if re.search(pattern, s):
                pol = rest[0] if rest else NO_QUANT
                if shapes is not None and len(shapes[path]) < 2:
                    pol = NO_QUANT  # scalar skip (structural)
                out[path] = pol
                break
        else:
            misses.append(s)
    if misses:
        raise ValueError(
            f"no precision rule matched param path(s): {misses}. "
            "Add a rule — a tensor with no declared precision policy "
            "is not allowed."
        )
    return out


@functools.lru_cache(maxsize=256)
def precision_for(model_type: str, key: str) -> PrecisionPolicy:
    """PrecisionPolicy for one canonical param-path suffix (e.g.
    'attn/c_attn/kernel') — the call-site form the models use at
    construction to decide which matmuls take the int8 path under
    compute_dtype='int8'. Same table, same first-match ordering, same
    fail-loud contract as match_precision_rules."""
    return match_precision_rules(rules_for_model(model_type), (key,))[key]


def sanitize_specs(spec_by_path, shapes, mesh, *, strict=False, log=None):
    """Drop mesh axes from any spec dimension they don't divide evenly
    (e.g. an unpadded char-level vocab of 25 on tensor:2). GSPMD would
    otherwise refuse the layout; replication of that one dim is the honest
    fallback. Real configs avoid this by padding (vocab 50304).

    Replicating a dimension silently would contradict the fail-loud
    philosophy `match_partition_rules` enforces (a replicated 1.5B wte is
    real HBM and real all-gather traffic with no visible cause), so every
    drop is reported: with `strict=True` (the training loop's default
    unless the config sets `allow_unsharded_fallback=True`) a drop raises;
    otherwise each (param, axis, dim) is announced via `log` (defaults to
    print — call sites pass a coordinator-only logger on pods)."""
    import numpy as np

    out = {}
    dropped = []
    for p, spec in spec_by_path.items():
        dims = shapes[p]
        entries = tuple(spec) + (None,) * (len(dims) - len(spec))
        new = []
        for i, (d, ax) in enumerate(zip(dims, entries)):
            if ax is None:
                new.append(None)
                continue
            axes = ax if isinstance(ax, tuple) else (ax,)
            size = int(np.prod([mesh.shape[a] for a in axes]))
            if d % size == 0:
                new.append(ax)
            else:
                new.append(None)
                dropped.append((path_str(p) if not isinstance(p, str) else p,
                                i, ax, d, size))
        out[p] = P(*new)
    if dropped:
        lines = [
            f"  {name}: dim {i} (size {d}) not divisible by {ax}={size}; "
            "replicating"
            for name, i, ax, d, size in dropped
        ]
        msg = "sanitize_specs dropped sharding axes:\n" + "\n".join(lines)
        if strict:
            raise ValueError(
                msg + "\nPad the dimension, change the rule, or set "
                "allow_unsharded_fallback=True to accept replication."
            )
        (log or print)(msg)
    return out


# THE one home for "which axes act as data parallelism on activations"
# and "which axis shards attention heads" — batch_pspec, the sequence-
# parallel shard_maps (ring_attention.context_shard_map), and the pallas
# SPMD wrap (ops/attention._flash_shard_specs) all derive from these.
BATCH_AXES = ("data", "fsdp", "expert")
TP_AXIS = "tensor"


def free_axis_names(mesh=None):
    """Axis names of `mesh` (ambient abstract mesh when None) that are NOT
    already Manual — i.e. the axes a nested `jax.shard_map` may go manual
    over from the current trace position.

    THE safety rule for every attention shard_map in this repo (flash
    wrap, ring/ulysses): pass `axis_names=free_axis_names()`. A shard_map
    that defaults to ALL mesh axes while some axis is already Manual
    (e.g. 'pipe' inside the GPipe region) claims its inputs are
    REPLICATED over that axis — the in_specs never mention it — and the
    shard_map TRANSPOSE then inserts a psum over it on every cotangent.
    Stage activations are NOT replicated over 'pipe', so that psum
    silently corrupts every gradient upstream of the region (measured
    2.8e-3 on a pipe:2,data:2 GPT before this rule; r4 measured 7e-3 and
    fenced it off by refusing to nest at all — tools/exp_v1_partition.py
    and exp_v1_nested.py hold the round-5 repro ladder). Naming only the
    free axes keeps Manual axes out of the inner shard_map's domain
    entirely: no replication claim, no transpose psum, exact gradients
    (1e-8 on the same repro)."""
    import jax

    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
    from jax.sharding import AxisType

    return frozenset(
        n for n, t in zip(mesh.axis_names, mesh.axis_types)
        if t != AxisType.Manual
    )


def batch_pspec(with_accum: bool = True) -> P:
    """Global batch layout: batch dim sharded over every data-parallel-like
    axis — 'expert' is a data axis outside the MoE blocks (the standard EP
    layout: tokens ride the expert axis so dispatch/combine become
    all-to-alls over ICI, BASELINE.json:11) — sequence dim over 'context'
    (ring attention). `with_accum`: leading unsharded grad-accumulation
    axis (train batches are (accum, B, T); eval batches are (B, T))."""
    per_batch = (BATCH_AXES, "context")
    return P(None, *per_batch) if with_accum else P(*per_batch)


def activation_pspec() -> P:
    """Between-block activation constraint (B, T, C)."""
    return P(("data", "fsdp"), "context", None)


def constrain(x, spec: P):
    """with_sharding_constraint that is a no-op when no mesh is in context
    (single-device tests, model used standalone) and FAIL-LOUD when one is:
    the training loop installs the mesh via `jax.set_mesh`, and a genuine
    constraint error inside a real mesh must surface, not be swallowed."""
    import jax

    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    return jax.lax.with_sharding_constraint(x, spec)


def named_shardings(mesh, spec_by_path):
    """{path: PartitionSpec} → {path: NamedSharding} on `mesh`."""
    return {p: NamedSharding(mesh, s) for p, s in spec_by_path.items()}
