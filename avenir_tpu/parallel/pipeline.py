"""Pipeline parallelism — GPipe-style microbatching over the 'pipe' mesh
axis (BEYOND the blueprint: SURVEY.md §2c lists PP as a parity non-goal;
this lands it anyway as the last missing first-class strategy, the
TPU-idiomatic way the survey sketches — "shard_map + collective-permute
microbatch pipeline").

Mechanism. The scan-stacked layer params (L, ...) shard their LAYER axis
over 'pipe' (partition.match_partition_rules), so stage s owns layers
[s·L/p, (s+1)·L/p). A `jax.shard_map` manual ONLY over 'pipe'
(axis_names={'pipe'}) runs the classic GPipe schedule: the batch splits
into M microbatches, and for ticks t = 0..M+p-2 stage s processes
microbatch t-s (when in range) through its local layer stack, then
`lax.ppermute`s the activation one hop to stage s+1. Stage p-1 collects
finished microbatches; a masked psum broadcasts the result back to every
stage (embeddings/norm/head outside this region are replicated over
'pipe', so all stages need the block-stack output). THREE schedules
(`pipeline_schedule`): 'gpipe' is plain autodiff through that forward
(the transpose of ppermute is the reverse ppermute and the transpose of
the tick scan is the reverse schedule — stash is the scan's own
per-layer residuals for every in-flight micro), 'remat' is a custom-vjp
mirrored-tick backward stashing only stage INPUTS with just-in-time
recompute (3.4-6.9× smaller compiled temp memory — BASELINE.md
"Pipeline cost table"), and '1f1b' is the real thing (Narayanan et al.
PipeDream-Flush / Megatron-LM): the per-micro LOSS TAIL moves inside
the region (`pipeline_1f1b_loss` — the last stage runs the chunked
fused CE on each finished microbatch, ops/fused_ce.blocked_ce_terms),
so each tick carries an activation downstream AND a cotangent upstream
and the stage-input stash is a fixed 2p-1-slot ring — O(p) in-flight
micros instead of O(M), M-independent activation memory. Per-layer
remat composes with all three.

Composition. Because the region is manual only over 'pipe', everything
else stays GSPMD: batch stays sharded over data/fsdp, weights over
fsdp/tensor. Nested shard_maps compose since r5 PROVIDED they name only
the free (non-Manual) axes — partition.free_axis_names documents the
transpose hazard (a nested wrap that default-names the Manual 'pipe'
axis claims replication over it and psums cotangents across stages;
measured 2.8e-3 gradient corruption, 7e-3 in the r4 form). The pallas
flash wrap and ring/ulysses all follow the rule, so pipe meshes keep
partitioned attention (zero all-gathers, test_pallas_spmd) and
pipe×context trains sequence-parallel inside the pipeline
(tests/test_pipeline.py pp-cp-* cases). One residual constraint:
jax.lax.axis_index cannot lower in a nested shard_map under Shardy —
ring ships its position in as data instead (ring_attention). Bubble
fraction is (p-1)/(M+p-1) for gpipe/remat and (2p-2)/(M+2p-2) for
1f1b's combined F+B ticks; pick M = pipeline_microbatches >= p to
amortize (default 2p; 1f1b's bounded stash is what makes M >> 2p
affordable — docs/PERFORMANCE.md "The pipeline bubble").

Trajectory equivalence vs the unpipelined model is exact up to fp
reassociation: the same layers run in the same order per token, only
batch-sliced — pinned by tests/test_pipeline.py on pipe:2 / pipe:4 and
pipe×data meshes.
"""

import jax
import jax.numpy as jnp
from flax import nnx
from jax.sharding import PartitionSpec as P

from avenir_tpu.models.common import resolve_remat_policy

PIPE_AXIS = "pipe"


def _staircase(t, s, M):
    """(micro index, is-real) for stage s at tick t — THE schedule math,
    shared by the gpipe tick body, the remat schedule's forward AND
    mirrored backward, and BOTH half-ticks of the 1f1b schedule (its
    backward staircase is the forward one at the reflected stage index
    2(p-1)-s), so none of them can drift (review r5)."""
    mi = jnp.clip(t - s, 0, M - 1)
    real = jnp.logical_and(t - s >= 0, t - s < M)
    return mi, real


def pipeline_axis_size() -> int:
    """Size of the ambient mesh's 'pipe' axis (1 = pipelining off)."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return 1
    return dict(mesh.shape).get(PIPE_AXIS, 1)


# One entry per TRACE of a pipeline region ((schedule, kind) tuples;
# appends happen at trace time only) — the same ledger idiom as
# ops/fused_ce and infer/decode. Tests pin one trace per compiled step.
_trace_events = []


def trace_count(schedule=None):
    """Number of pipeline-region traces (optionally for one schedule)."""
    if schedule is None:
        return len(_trace_events)
    return sum(1 for s, _ in _trace_events if s == schedule)


def _resolve_micro(B, p, n_micro, schedule="gpipe"):
    """Shared microbatch-count resolution: explicit n_micro, else auto
    2p clamped to the largest divisor of B (warning when the bubble
    dominates). All schedules share it, so a schedule A/B at the same
    config always compares equal M; `schedule` only picks the bubble
    formula the warning reports (1f1b's combined F+B ticks pay the
    depth twice: (2p−2)/(M+2p−2) vs the gpipe/remat (p−1)/(M+p−1))."""
    if n_micro > 0:
        M = n_micro
    else:
        M = min(2 * p, B)
        while B % M:
            M -= 1
        if M < p:
            import warnings

            drain = 2 * (p - 1) if schedule == "1f1b" else p - 1
            warnings.warn(
                f"pipeline auto-microbatching picked M={M} < p={p} stages "
                f"(batch {B} has no divisor in [p, 2p]); bubble fraction "
                f"{drain / (M + drain):.0%} — set pipeline_microbatches "
                "or pick a batch size divisible by a multiple of the "
                "stage count", stacklevel=3,
            )
    assert B % M == 0, (
        f"global batch {B} must divide into {M} pipeline microbatches "
        "(set pipeline_microbatches to a divisor)"
    )
    return M


def _transport_dtype(x):
    """(transport dtype, compute dtype) for stage hops. XLA:CPU's
    float-normalization pass CHECK-crashes ("Invalid binary instruction
    opcode copy", hlo_instruction.cc) on bf16 ppermute/psum inside a
    partial-manual region (minimal repro in the r4 notes; fp32 compiles
    fine, and TPU has native bf16 collectives so the pass never fires
    there). Off-TPU, move activations between stages in fp32 —
    bf16->fp32->bf16 is exact, so the trajectory is bit-identical; the
    2x hop bytes only exist on the CPU harness."""
    f32_transport = (x.dtype == jnp.bfloat16
                     and jax.default_backend() != "tpu")
    return (jnp.float32 if f32_transport else x.dtype), x.dtype


def _build_apply_layer(graphdef, call, aux0, remat, remat_policy):
    """Per-layer application shared by every schedule: plain lax.scan +
    direct module call instead of scan_layer_stack (nnx transforms refuse
    graph nodes created at an outer trace level, and this sits at
    shard_map->scan(tick)->scan(layer) depth)."""

    def apply_layer(layer_state, h):
        blk = nnx.merge(graphdef, layer_state)
        out = call(blk, h)
        if aux0 is None:
            return out, jnp.float32(0.0)
        return out  # (h, aux) per the aux contract

    if remat:
        apply_layer = jax.checkpoint(
            apply_layer, policy=resolve_remat_policy(remat_policy)
        )
    return apply_layer


def _record_schedule_metrics(p, M, schedule):
    """Trace-time obs accounting: walk _staircase over every (tick,
    stage) slot of the schedule about to compile and record real vs
    bubble tick-slots (counters cumulate once per region TRACE, not per
    step — steady-state utilization is shape-static) plus the resulting
    pp_bubble_frac gauge. 1f1b TRAINING ticks carry an F-slot AND a
    B-slot (the backward staircase is _staircase at the reflected stage
    2(p-1)-s) — its eval/no-grad trace runs the forward-only staircase
    instead and must be recorded as such ('1f1b-eval', the else branch);
    gpipe/remat count the forward staircase (their backward mirrors it,
    so the fraction is identical). Called from inside each schedule BODY
    so only the bodies that actually trace are counted."""
    from avenir_tpu.obs.metrics import get_registry

    # pure-python mirror of _staircase's is-real predicate (this runs
    # INSIDE a jit trace, where jnp ops would return tracers)
    is_real = lambda t, s: 0 <= t - s < M
    real = bubble = 0
    if schedule == "1f1b":
        for t in range(M + 2 * p - 2):
            for s in range(p):
                f = is_real(t, s)
                b = is_real(t, 2 * (p - 1) - s)
                real += int(f) + int(b)
                bubble += int(not f) + int(not b)
    else:
        for t in range(M + p - 1):
            for s in range(p):
                real += int(is_real(t, s))
                bubble += int(not is_real(t, s))
    reg = get_registry()
    reg.gauge("pp_bubble_frac").set(bubble / max(1, real + bubble))
    reg.counter("pipe_ticks_real").add(real)
    reg.counter("pipe_ticks_bubble").add(bubble)


def _use_psum_hop(p):
    """True when stage hops must avoid lax.ppermute: the legacy
    (jax 0.4.x) partial-auto shard_map lowering CHECK-crashes XLA's
    SPMD partitioner on ppermute whenever any non-'pipe' mesh axis is
    live ("Check failed: target.IsManualSubgroup() ==
    sharding().IsManualSubgroup()"; minimal repro in the 1f1b PR — psum
    in the same position lowers fine, as does ppermute on a pure-pipe
    mesh where the auto product is 1). The psum emulation costs p x the
    hop bytes and exists ONLY for the legacy compat runtime; modern jax
    and pure-pipe meshes keep the point-to-point ppermute."""
    from avenir_tpu import compat

    if getattr(jax, "shard_map", None) is not compat.shard_map:
        return False
    mesh = jax.sharding.get_abstract_mesh()
    other = 1
    for n, sz in dict(mesh.shape).items():
        if n != PIPE_AXIS:
            other *= sz
    return other > 1


def _make_hops(p, s, use_psum):
    """(hop_down, hop_up): move a per-stage array one stage downstream /
    upstream, zero-filling the edge stage exactly like the partial
    ppermute they normally are. `use_psum` (static, from _use_psum_hop)
    swaps in the masked-psum emulation: all stages contribute their
    slot of a (p, ...) one-hot expansion, psum makes it whole, and each
    stage gathers its neighbor's row."""
    if not use_psum:
        fwd_perm = [(i, i + 1) for i in range(p - 1)]
        bwd_perm = [(i + 1, i) for i in range(p - 1)]
        return (lambda x: jax.lax.ppermute(x, PIPE_AXIS, fwd_perm),
                lambda x: jax.lax.ppermute(x, PIPE_AXIS, bwd_perm))
    oh = jnp.arange(p) == s

    def allg(x):
        return jax.lax.psum(
            x[None] * oh.reshape((p,) + (1,) * x.ndim).astype(x.dtype),
            PIPE_AXIS)

    def down(x):
        r = allg(x)[jnp.clip(s - 1, 0, p - 1)]
        return jnp.where(s == 0, jnp.zeros_like(r), r)

    def up(x):
        r = allg(x)[jnp.clip(s + 1, 0, p - 1)]
        return jnp.where(s == p - 1, jnp.zeros_like(r), r)

    return down, up


def layer_stack_dispatch(x, stacked, *, call, n_micro=0, remat=False,
                         remat_policy=None, aux0=None, schedule="gpipe"):
    """THE one home for the pipeline-vs-scan choice, shared by every
    dense family (gpt.py / llama.py have exactly one call site each):
    GPipe when the ambient mesh has pipe > 1, else nnx.scan. The aux
    contract is shared by both paths: with `aux0` given, `call(layer, h)`
    returns (h, aux) and the result is (out, aux0 + sum-over-layers) —
    the scan path accumulates through its carry, the pipeline through
    its tick/psum machinery (batch-mean statistics only; see
    pipeline_layer_stack). `schedule` picks the pipeline backward form
    ('gpipe' | 'remat'); off-pipe meshes ignore it."""
    if pipeline_axis_size() > 1:
        return pipeline_layer_stack(x, stacked, call=call, n_micro=n_micro,
                                    remat=remat, remat_policy=remat_policy,
                                    aux0=aux0, schedule=schedule)
    from avenir_tpu.models.common import scan_layer_stack

    if aux0 is None:
        return scan_layer_stack(x, stacked, call=call, remat=remat,
                                remat_policy=remat_policy)

    def aux_call(lyr, carry):
        h, acc = carry
        h, a = call(lyr, h)
        return (h, jax.tree.map(jnp.add, acc, a))

    return scan_layer_stack((x, aux0), stacked, call=aux_call, remat=remat,
                            remat_policy=remat_policy)


def pipeline_layer_stack(x, stacked, *, call=None, n_micro=0, remat=False,
                         remat_policy=None, aux0=None, schedule="gpipe"):
    """Run (B, T, C) activations through a scan-stacked layer module with
    the layer axis sharded over 'pipe', GPipe-scheduled. Drop-in
    replacement for scan_layer_stack when the mesh has pipe > 1.

    `schedule` selects the BACKWARD memory strategy (identical forward
    schedule and identical trajectories):
      - 'gpipe' (default): plain autodiff through the tick scan — the
        scan stashes per-LAYER residuals for every in-flight microbatch,
        O((M+p) * L/p) layer-activation sets per stage.
      - 'remat': custom-vjp reverse tick schedule — the forward stashes
        ONLY each microbatch's stage INPUT (O(M) single activations per
        stage), and the backward re-runs the local stack per microbatch
        just-in-time in mirrored tick order, so per-layer residuals
        exist for ONE microbatch at a time. Measured memory in
        BASELINE.md "Pipeline cost table". MoE aux stats are gpipe-only
        here (the reverse-tick backward would need the aux cotangent
        threaded through the recompute — fail-loud below).
      - '1f1b' does NOT run through this function: true forward/backward
        interleaving needs the per-micro loss computed at the last stage
        INSIDE the schedule, so the models hand their head+loss tail to
        `pipeline_1f1b_loss` instead (this layout transform returns
        activations, which is the wrong boundary for it). Callers that
        reach here with schedule='1f1b' — e.g. a 1f1b-configured model
        called WITHOUT targets — should fall back to 'gpipe' (identical
        forward, and with no loss there is no backward to interleave).

    `aux0` (optional, a pytree of fp32 BATCH-MEAN statistics — MoE
    router stats): `call(layer, h)` must then return (h, aux), and the
    function returns (out, aux0 + aux_sum) where aux_sum accumulates
    over local layers, real (non-bubble) ticks, and stages, scaled by
    1/M. Exact for batch means: microbatches are equal-sized, so the
    mean of micro-means IS the full-batch mean — and Mixtral's router
    STATS are computed pre-capacity ('on intent'), so the aggregated
    stats (and any aux loss derived from them, however nonlinear) equal
    the unpipelined full-batch run exactly, drops or no drops.
    Capacity-style values derived from the per-forward token count —
    Mixtral's expert queue C — are computed per MICRObatch under the
    pipeline; exact parity of the TOKEN OUTPUTS with the unpipelined
    model therefore holds when capacity admits every token, and with
    drops the outputs/CE-loss/grads match the mean of M independent
    B/M-sized forwards over the STRIDED row groups b % M == m — the
    (B,)->(B//M, M) reshape keeps the sharded batch dim intact, so the
    micro axis is the fast-varying one (pinned by
    test_pipeline_mixtral_drop_semantics_match_microbatched_oracle).
    NB the mean of per-micro AUX losses is NOT the pipelined aux (the
    aux is nonlinear in the stats; the pipeline aggregates stats first,
    which is the faithful-to-full-batch choice)."""
    p = pipeline_axis_size()
    assert p > 1, "pipeline_layer_stack requires a pipe axis > 1"
    if call is None:
        call = lambda lyr, h: lyr(h)
    graphdef, state = nnx.split(stacked)
    n_layer = jax.tree.leaves(state)[0].shape[0]
    assert n_layer % p == 0, (
        f"n_layer={n_layer} must divide over pipe={p} stages"
    )
    B = x.shape[0]
    M = _resolve_micro(B, p, n_micro)
    state_specs = jax.tree.map(
        lambda a: P(PIPE_AXIS, *([None] * (a.ndim - 1))), state
    )
    x_spec = P(*([None] * x.ndim))
    t_dtype, c_dtype = _transport_dtype(x)
    apply_layer = _build_apply_layer(graphdef, call, aux0, remat,
                                     remat_policy)
    aux_zero = (jax.tree.map(lambda a: jnp.zeros_like(a, jnp.float32), aux0)
                if aux0 is not None else jnp.float32(0.0))
    use_psum_hop = _use_psum_hop(p)

    if schedule == "remat":
        assert aux0 is None, (
            "pipeline_schedule='remat' does not carry MoE aux stats yet "
            "(the reverse-tick backward would need the aux cotangent "
            "threaded through the recompute); use the default 'gpipe' "
            "schedule for MoE models"
        )
        return _remat_schedule(x, state, p=p, M=M, apply_layer=apply_layer,
                               state_specs=state_specs, x_spec=x_spec,
                               t_dtype=t_dtype, c_dtype=c_dtype,
                               use_psum_hop=use_psum_hop)
    assert schedule == "gpipe", (
        f"unknown pipeline_schedule {schedule!r} for the layer-stack "
        "transform; one of 'gpipe', 'remat' ('1f1b' owns the loss tail "
        "and enters through pipeline_1f1b_loss)"
    )

    n_local = n_layer // p

    def body(state_local, xl, sid):
        _trace_events.append(("gpipe", "fwd"))
        _record_schedule_metrics(p, M, schedule)
        s = sid[0]  # stage index as DATA (in_spec P('pipe')): lax.
        # axis_index lowers to a PartitionId instruction the legacy
        # partial-auto lowering cannot SPMD-partition on meshes with
        # live non-pipe axes — same ship-it-in trick ring_attention
        # uses for its Shardy nesting limit
        Bg, T, C = xl.shape
        xm = xl.reshape(Bg // M, M, T, C)  # micro m = xm[:, m] (batch
        # dim 0 keeps its data/fsdp sharding; the micro dim is unsharded)
        hop_down, _ = _make_hops(p, s, use_psum_hop)

        def run_local_stack(h):
            if use_psum_hop:
                # legacy-mixed harness: autodiff THROUGH a lax.scan
                # inside a partial-auto region also CHECK-crashes the
                # old SPMD partitioner (residual hoisting) — unroll the
                # local layer loop; n_local is small and this path is
                # CPU-tests-only (see _use_psum_hop)
                aux_sum = None
                for i in range(n_local):
                    lyr = jax.tree.map(lambda a: a[i], state_local)
                    h, a = apply_layer(lyr, h)
                    aux_sum = (a if aux_sum is None
                               else jax.tree.map(jnp.add, aux_sum, a))
                return h, aux_sum

            def layer_body(h, layer_state):
                h, aux = apply_layer(layer_state, h)
                return h, aux

            out, auxs = jax.lax.scan(layer_body, h, state_local)
            return out, jax.tree.map(lambda a: jnp.sum(a, axis=0), auxs)

        def tick(carry, t):
            outs, recv, aux_acc = carry
            mi, real = _staircase(t, s, M)
            inp = jnp.where(s == 0, xm[:, mi], recv).astype(c_dtype)
            out, aux_m = run_local_stack(inp)
            recv_next = hop_down(out.astype(t_dtype))
            # real: this stage processed a REAL microbatch this tick (not
            # a warmup/drain bubble) — its aux contribution counts
            aux_acc = jax.tree.map(
                lambda acc, a: acc + jnp.where(real, a, 0.0), aux_acc, aux_m
            )
            active = jnp.logical_and(s == p - 1, real)
            outs = jnp.where(active, outs.at[:, mi].set(out.astype(t_dtype)),
                             outs)
            return (outs, recv_next, aux_acc), None

        init = (jnp.zeros(xm.shape, t_dtype),
                jnp.zeros(xm[:, 0].shape, t_dtype), aux_zero)
        if use_psum_hop:
            carry = init  # unrolled ticks, same reason as the layer loop
            for t in range(M + p - 1):
                carry, _ = tick(carry, t)
            outs, _, aux_acc = carry
        else:
            (outs, _, aux_acc), _ = jax.lax.scan(
                tick, init, jnp.arange(M + p - 1))
        # only stage p-1 holds real outputs; masked psum broadcasts them.
        # The region returns t_dtype: its replicated-over-pipe output
        # transposes to a psum of the COTANGENT at the boundary, which
        # must also avoid bf16 off-TPU (same XLA:CPU crash, bwd-side) —
        # the cast back to compute dtype happens outside the shard_map
        outs = jnp.where(s == p - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, PIPE_AXIS)
        # aux: stages hold disjoint layer groups -> psum sums all layers;
        # /M folds the sum over micros back to the full-batch mean
        aux_tot = jax.tree.map(
            lambda a: jax.lax.psum(a, PIPE_AXIS) / M, aux_acc
        )
        return outs.reshape(Bg, T, C), aux_tot

    aux_specs = jax.tree.map(lambda a: P(*([None] * a.ndim)), aux_zero)
    f = jax.shard_map(
        body, in_specs=(state_specs, x_spec, P(PIPE_AXIS)),
        out_specs=(x_spec, aux_specs),
        check_vma=False, axis_names={PIPE_AXIS},
    )
    # also keep the region INPUT in t_dtype: its cotangent rides the
    # reverse boundary the same way
    out, aux_tot = f(state, x.astype(t_dtype),
                     jnp.arange(p, dtype=jnp.int32))
    out = out.astype(x.dtype)
    if aux0 is None:
        return out
    return out, jax.tree.map(jnp.add, aux0, aux_tot)


def _remat_schedule(x, state, *, p, M, apply_layer, state_specs, x_spec,
                    t_dtype, c_dtype, use_psum_hop=False):
    """The 'remat' pipeline backward (see pipeline_layer_stack): a
    custom-vjp pair of shard_map regions, both manual only over 'pipe'.

    Forward: the standard GPipe tick staircase, but each stage also
    STASHES the microbatch input it consumed — (M, Bm, T, C) per stage,
    exported pipe-sharded as (p*M, Bm, T, C) so it rides to the backward
    as a plain residual.

    Backward: the mirrored staircase. At reverse tick t (from M+p-2 down
    to 0) stage s handles micro m = t-s: it re-runs its local stack from
    stash[m] under jax.vjp, applies the cotangent arriving from stage
    s+1 (reverse ppermute — the transpose of the forward hop), adds the
    weight-grad contribution, and sends the input-cotangent one hop
    upstream. The cotangent for micro m reaches stage s exactly one
    reverse tick after stage s+1 produced it — the same lockstep the
    forward uses, mirrored. Per-layer residuals therefore exist for ONE
    microbatch per stage at any time, instead of for every in-flight
    microbatch across the whole tick scan."""

    n_local = jax.tree.leaves(state)[0].shape[0] // p

    def run_local(state_local, h):
        if use_psum_hop:
            # legacy-mixed harness: unrolled, like every other schedule
            # body here (scans in these regions trip the old SPMD
            # partitioner — see _use_psum_hop)
            for i in range(n_local):
                lyr = jax.tree.map(lambda a: a[i], state_local)
                h, _ = apply_layer(lyr, h)
            return h

        def layer_body(h, layer_state):
            h, _ = apply_layer(layer_state, h)
            return h, None

        out, _ = jax.lax.scan(layer_body, h, state_local)
        return out

    def fwd_body(state_local, xl, sid):
        _trace_events.append(("remat", "fwd"))
        _record_schedule_metrics(p, M, "remat")
        s = sid[0]  # stage-as-data, see pipeline_layer_stack body
        Bg, T, C = xl.shape
        xm = xl.reshape(Bg // M, M, T, C)
        hop_down, _ = _make_hops(p, s, use_psum_hop)

        def tick(carry, t):
            outs, recv, stash = carry
            mi, real = _staircase(t, s, M)
            inp = jnp.where(s == 0, xm[:, mi], recv)
            stash = jnp.where(real, stash.at[mi].set(inp), stash)
            out = run_local(state_local, inp.astype(c_dtype)).astype(t_dtype)
            recv_next = hop_down(out)
            active = jnp.logical_and(s == p - 1, real)
            outs = jnp.where(active, outs.at[:, mi].set(out), outs)
            return (outs, recv_next, stash), None

        Bm = xl.shape[0] // M
        init = (jnp.zeros(xm.shape, t_dtype),
                jnp.zeros((Bm, T, C), t_dtype),
                jnp.zeros((M, Bm, T, C), t_dtype))
        if use_psum_hop:
            carry = init  # unrolled ticks (legacy-mixed, _use_psum_hop)
            for t in range(M + p - 1):
                carry, _ = tick(carry, t)
            outs, _, stash = carry
        else:
            (outs, _, stash), _ = jax.lax.scan(tick, init,
                                               jnp.arange(M + p - 1))
        outs = jnp.where(s == p - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, PIPE_AXIS)
        return outs.reshape(Bg, T, C), stash

    stash_spec = P(PIPE_AXIS, *([None] * x.ndim))
    sid_spec = P(PIPE_AXIS)
    f_fwd = jax.shard_map(
        fwd_body, in_specs=(state_specs, x_spec, sid_spec),
        out_specs=(x_spec, stash_spec),
        check_vma=False, axis_names={PIPE_AXIS},
    )

    def bwd_body(state_local, stash_local, dout, sid):
        s = sid[0]
        Bg, T, C = dout.shape
        dm = dout.reshape(Bg // M, M, T, C)
        _, hop_up = _make_hops(p, s, use_psum_hop)

        def stage_fn(st, h):
            return run_local(st, h.astype(c_dtype)).astype(t_dtype)

        def tick(carry, tt):
            dstate, drecv, dxm = carry
            t = (M + p - 2) - tt
            mi, real = _staircase(t, s, M)
            dout_in = jnp.where(s == p - 1, dm[:, mi], drecv)
            _, vjp_fn = jax.vjp(stage_fn, state_local, stash_local[mi])
            dst_i, dinp = vjp_fn(dout_in)
            dstate = jax.tree.map(
                lambda acc, g: acc + jnp.where(real, g, 0.0), dstate, dst_i
            )
            first = jnp.logical_and(s == 0, real)
            dxm = jnp.where(first, dxm.at[:, mi].set(dinp), dxm)
            drecv_next = hop_up(dinp)
            return (dstate, drecv_next, dxm), None

        init = (jax.tree.map(jnp.zeros_like, state_local),
                jnp.zeros_like(dm[:, 0]), jnp.zeros_like(dm))
        if use_psum_hop:
            carry = init  # unrolled reverse ticks (legacy-mixed)
            for tt in range(M + p - 1):
                carry, _ = tick(carry, tt)
            dstate, _, dxm = carry
        else:
            (dstate, _, dxm), _ = jax.lax.scan(tick, init,
                                               jnp.arange(M + p - 1))
        dxm = jnp.where(s == 0, dxm, jnp.zeros_like(dxm))
        dxm = jax.lax.psum(dxm, PIPE_AXIS)
        return dstate, dxm.reshape(Bg, T, C)

    f_bwd = jax.shard_map(
        bwd_body, in_specs=(state_specs, stash_spec, x_spec, sid_spec),
        out_specs=(state_specs, x_spec),
        check_vma=False, axis_names={PIPE_AXIS},
    )
    sid = jnp.arange(p, dtype=jnp.int32)

    @jax.custom_vjp
    def run(state, xl):
        outs, _ = f_fwd(state, xl, sid)
        return outs

    def run_fwd(state, xl):
        outs, stash = f_fwd(state, xl, sid)
        return outs, (state, stash)

    def run_bwd(res, dout):
        state, stash = res
        dstate, dx = f_bwd(state, stash, dout.astype(t_dtype), sid)
        return dstate, dx

    run.defvjp(run_fwd, run_bwd)
    out = run(state, x.astype(t_dtype))
    return out.astype(x.dtype)


def pipeline_1f1b_loss(x, stacked, targets, *, call=None, tail_fn,
                       tail_params, n_valid, n_micro=0, remat=False,
                       remat_policy=None, aux0=None):
    """True 1F1B (PipeDream-Flush): the pipeline region that OWNS the
    loss tail. Returns the scalar training loss
        sum_m loss_sum_m / max(n_valid, 1)  +  sum_m aux_m / M
    where `tail_fn(tail_params, h, y_micro, stats) -> (loss_sum, aux)`
    is the model's final-norm + head + chunked-CE tail (blocked impl —
    plain jnp, so inside this manual-over-'pipe' region every other mesh
    axis stays GSPMD: vocab stays tensor-sharded and the row reductions
    psum over 'tensor' exactly as outside; nested shard_map wraps (the
    pallas flash attention, ring/ulysses) keep composing because they
    name only the free axes — partition.free_axis_names) and `n_valid`
    is the model-computed global non-ignored target count (the CE
    normalizer — per-micro loss SUMS therefore reduce to exactly the
    full-batch mean, bit-honest with grad_accum semantics).

    The schedule: combined F+B ticks t = 0..M+2p-3. Stage s forwards
    micro t-s (the gpipe staircase: at most the pipeline depth of
    forwards ahead) and backwards micro t-(2(p-1)-s) — the SAME
    staircase at the reflected stage index, i.e. the last stage runs
    the tail and starts micro m's backward in the very tick that
    finished its forward, then alternates 1 forward / 1 backward per
    tick while cotangents ride `lax.ppermute` upstream in the same tick
    activations ride downstream. In-flight micros at stage s are
    bounded by 2(p-1-s)+1 — the forward-only warmup depth plus the
    cotangent return trip — so the stage-input stash is a fixed ring of
    W = min(2p-1, M) slots, INDEPENDENT OF M (gpipe stashes per-layer
    residuals for all M+p-1 ticks; 'remat' stashes M stage inputs).
    That bound is what lets M grow far past 2p and shrink the bubble
    (2p-2)/(M+2p-2) without the activation memory growing with it.
    Backward ticks recompute the local stack from the stashed input
    under jax.vjp ('remat'-class FLOPs: one extra stack forward per
    micro).

    Autodiff wiring: forward AND backward interleave in ONE region, so
    under jax.grad the region's custom-vjp FORWARD runs the interleaved
    schedule and computes the gradients as it goes (the cotangent seed
    of every per-micro contribution is known upfront — 1/n_valid and
    1/M — and the outer cotangent is a scalar the vjp multiplies in by
    linearity); the residuals ARE the finished grads. The undifferentiated
    primal (eval) runs a forward-only staircase instead — same loss
    value, no backward cost.

    MoE (`aux0` + `call` returning (h, stats)): router stats ride the
    ppermute payload per-micro and the LAST stage computes micro m's
    aux loss from its own accumulated stats — per-micro aux semantics,
    exactly the micro-batched oracle (the mean of M independent B/M
    strided forwards, aux included), NOT gpipe's aggregate-stats-first
    aux (the aux is nonlinear in the stats, so the two differ; gpipe
    keeps the faithful-to-full-batch choice, 1f1b keeps the
    faithful-to-interleaving one — pinned by
    test_1f1b_mixtral_matches_microbatched_oracle). Capacity stays
    per-micro, like every pipeline schedule here."""
    p = pipeline_axis_size()
    assert p > 1, "pipeline_1f1b_loss requires a pipe axis > 1"
    if call is None:
        call = lambda lyr, h: lyr(h)
    graphdef, state = nnx.split(stacked)
    n_layer = jax.tree.leaves(state)[0].shape[0]
    assert n_layer % p == 0, (
        f"n_layer={n_layer} must divide over pipe={p} stages"
    )
    B, T = targets.shape
    assert x.shape[0] == B
    n_local = n_layer // p
    M = _resolve_micro(B, p, n_micro, schedule="1f1b")
    W = min(2 * p - 1, M)
    n_ticks = M + 2 * p - 2
    inv_M = 1.0 / M
    state_specs = jax.tree.map(
        lambda a: P(PIPE_AXIS, *([None] * (a.ndim - 1))), state
    )
    x_spec = P(*([None] * x.ndim))
    y_spec = P(None, None)
    tp_specs = jax.tree.map(lambda a: P(*([None] * jnp.ndim(a))),
                            tail_params)
    t_dtype, c_dtype = _transport_dtype(x)
    apply_layer = _build_apply_layer(graphdef, call, aux0, remat,
                                     remat_policy)
    aux_zero = (jax.tree.map(lambda a: jnp.zeros_like(a, jnp.float32), aux0)
                if aux0 is not None else jnp.float32(0.0))
    use_psum_hop = _use_psum_hop(p)
    tsel = lambda pred, a, b: jax.tree.map(
        lambda u, v: jnp.where(pred, u, v), a, b)

    def make_tick_fn(s, ym, inv_nv):
        """One stage's whole tick-slot as ONE differentiable function
        (state, h_in, stats_in, tail_params) -> (h_out, stats_out,
        loss-contribution): the local stack, then — masked to the last
        stage — the loss tail on the finished micro. One jax.vjp of this
        at the stashed input yields the stage backward AND (on the last
        stage) the tail backward in the same call; non-last stages' tail
        work is masked to zero contribution (their tick wall-time is
        bounded by the last stage's real tail anyway — SPMD lockstep)."""

        def stage_fn(state_local, h_in, st_in):
            if use_psum_hop:
                # legacy-mixed harness: scans in this region (even under
                # the in-region vjp) trip the old SPMD partitioner —
                # unroll, same as the gpipe body (see _use_psum_hop)
                h, st = h_in.astype(c_dtype), st_in
                for i in range(n_local):
                    lyr = jax.tree.map(lambda a: a[i], state_local)
                    h, a = apply_layer(lyr, h)
                    if aux0 is not None:
                        st = jax.tree.map(jnp.add, st, a)
                return h.astype(t_dtype), st

            def layer_body(carry, layer_state):
                h, st = carry
                h, a = apply_layer(layer_state, h)
                if aux0 is not None:
                    st = jax.tree.map(jnp.add, st, a)
                return (h, st), None

            (h, st), _ = jax.lax.scan(
                layer_body, (h_in.astype(c_dtype), st_in), state_local)
            return h.astype(t_dtype), st

        def tick_fn(state_local, h_in, st_in, tp, m):
            h_out, st_out = stage_fn(state_local, h_in, st_in)
            y_m = jax.lax.dynamic_index_in_dim(ym, m, axis=1,
                                               keepdims=False)
            ls, aux = tail_fn(tp, h_out.astype(c_dtype), y_m, st_out)
            contrib = ls.astype(jnp.float32) * inv_nv \
                + aux.astype(jnp.float32) * inv_M
            return h_out, st_out, jnp.where(s == p - 1, contrib, 0.0)

        return tick_fn

    def _common(xl, yl, n_valid_r, sid):
        s = sid[0]  # stage-as-data, see pipeline_layer_stack body
        Bg = xl.shape[0]
        xm = xl.reshape(Bg // M, M, *xl.shape[1:])
        ym = yl.reshape(Bg // M, M, T)
        inv_nv = 1.0 / jnp.maximum(n_valid_r, 1).astype(jnp.float32)
        hop_down, hop_up = _make_hops(p, s, use_psum_hop)
        # stats payload hops only exist for aux families — a non-aux
        # model's stats carry is a constant 0 and never earns a collective
        if aux0 is not None:
            st_down = lambda st: jax.tree.map(hop_down, st)
            st_up = lambda st: jax.tree.map(hop_up, st)
        else:
            st_down = st_up = lambda st: st
        return s, xm, ym, make_tick_fn(s, ym, inv_nv), (hop_down, hop_up,
                                                        st_down, st_up)

    def fwd_only_body(state_local, xl, yl, tp, n_valid_r, sid):
        """The undifferentiated primal: plain gpipe staircase + per-micro
        tail at the last stage — same accumulation order as the
        interleaved schedule (micro order at stage p-1), no stash, no
        backward. Eval pays forward-only cost."""
        _trace_events.append(("1f1b", "fwd_only"))
        _record_schedule_metrics(p, M, "1f1b-eval")
        s, xm, ym, tick_fn, hops = _common(xl, yl, n_valid_r, sid)
        hop_down, _, st_down, _ = hops

        def tick(carry, t):
            recv_h, recv_st, acc = carry
            mi, real = _staircase(t, s, M)
            inp_h = jnp.where(s == 0, xm[:, mi], recv_h)
            inp_st = tsel(s == 0, aux_zero, recv_st)
            h_out, st_out, contrib = tick_fn(state_local, inp_h, inp_st,
                                             tp, mi)
            acc = acc + jnp.where(real, contrib, 0.0)
            recv_h = hop_down(h_out)
            recv_st = st_down(st_out)
            return (recv_h, recv_st, acc), None

        Bm = xl.shape[0] // M
        init = (jnp.zeros((Bm,) + xl.shape[1:], t_dtype), aux_zero,
                jnp.float32(0.0))
        if use_psum_hop:
            carry = init  # unrolled ticks (legacy-mixed, _use_psum_hop)
            for t in range(M + p - 1):
                carry, _ = tick(carry, t)
            acc = carry[2]
        else:
            (_, _, acc), _ = jax.lax.scan(tick, init,
                                          jnp.arange(M + p - 1))
        return jax.lax.psum(acc, PIPE_AXIS)

    def interleaved_body(state_local, xl, yl, tp, n_valid_r, sid):
        """The 1F1B schedule proper: every tick runs one forward
        half-slot and one backward half-slot (each masked by its own
        staircase), hops the activation downstream and the cotangent
        upstream, and accumulates grads in the carry. Returns the loss
        AND the finished (dstate, dx, dtail) — gradient-in-forward, see
        the custom-vjp note in the function docstring."""
        _trace_events.append(("1f1b", "interleaved"))
        _record_schedule_metrics(p, M, "1f1b")
        s, xm, ym, tick_fn, hops = _common(xl, yl, n_valid_r, sid)
        hop_down, hop_up, st_down, st_up = hops
        Bm = xl.shape[0] // M
        h_shape = (Bm,) + xl.shape[1:]
        refl = 2 * (p - 1) - s  # backward staircase = fwd at reflected s

        def tick(carry, t):
            (recv_h, recv_st, recv_dh, recv_dst, stash_h, stash_st,
             dstate, dxm, dtp, acc) = carry

            # ---- forward half-slot: micro t-s ----
            mf, f_real = _staircase(t, s, M)
            inp_h = jnp.where(s == 0, xm[:, mf], recv_h)
            inp_st = tsel(s == 0, aux_zero, recv_st)
            slot_f = mf % W
            stash_h = jnp.where(f_real, stash_h.at[slot_f].set(inp_h),
                                stash_h)
            stash_st = tsel(f_real,
                            jax.tree.map(lambda b, v: b.at[slot_f].set(v),
                                         stash_st, inp_st),
                            stash_st)
            h_out, st_out, contrib = tick_fn(state_local, inp_h, inp_st,
                                             tp, mf)
            acc = acc + jnp.where(f_real, contrib, 0.0)

            # ---- backward half-slot: micro t-(2(p-1)-s) ----
            # recompute the stage from its stashed input under jax.vjp;
            # the contribution seed 1.0 is exact because every micro's
            # loss contribution enters the total as a plain sum (outer
            # cotangent scaling happens in run_bwd by linearity). The
            # last stage's h_out cotangent arrives only THROUGH the tail
            # (the upstream hop has no source for it: recv_dh is zeros
            # there by construction, in both hop implementations).
            mb, b_real = _staircase(t, refl, M)
            slot_b = mb % W
            _, vjp_fn = jax.vjp(
                lambda st_, h_, a_, tp_: tick_fn(st_, h_, a_, tp_, mb),
                state_local, stash_h[slot_b],
                jax.tree.map(lambda b: b[slot_b], stash_st), tp)
            dst_i, dh_i, dsti, dtp_i = vjp_fn(
                (recv_dh, recv_dst, jnp.float32(1.0)))
            zero_if_bubble = lambda acc_t, g_t: jax.tree.map(
                lambda a, g: a + jnp.where(b_real, g, jnp.zeros_like(g)),
                acc_t, g_t)
            dstate = zero_if_bubble(dstate, dst_i)
            dtp = zero_if_bubble(dtp, dtp_i)
            first = jnp.logical_and(s == 0, b_real)
            dxm = jnp.where(first, dxm.at[:, mb].set(dh_i), dxm)

            # ---- hops: activation+stats down, cotangents up ----
            recv_h = hop_down(h_out)
            recv_st = st_down(st_out)
            recv_dh = hop_up(dh_i)
            recv_dst = st_up(dsti)
            return (recv_h, recv_st, recv_dh, recv_dst, stash_h, stash_st,
                    dstate, dxm, dtp, acc), None

        stack = lambda tree: jax.tree.map(
            lambda a: jnp.zeros((W,) + a.shape, a.dtype), tree)
        init = (
            jnp.zeros(h_shape, t_dtype), aux_zero,          # fwd payload
            jnp.zeros(h_shape, t_dtype), aux_zero,          # bwd payload
            jnp.zeros((W,) + h_shape, t_dtype), stack(aux_zero),  # stash
            jax.tree.map(jnp.zeros_like, state_local),      # dstate
            jnp.zeros((Bm, M) + xl.shape[1:], t_dtype),     # dxm
            jax.tree.map(jnp.zeros_like, tp),               # dtail
            jnp.float32(0.0),                               # loss acc
        )
        if use_psum_hop:
            carry = init  # unrolled ticks (legacy-mixed, _use_psum_hop)
            for t in range(n_ticks):
                carry, _ = tick(carry, t)
        else:
            carry, _ = jax.lax.scan(tick, init, jnp.arange(n_ticks))
        (_, _, _, _, _, _, dstate, dxm, dtp, acc) = carry
        loss = jax.lax.psum(acc, PIPE_AXIS)
        dxm = jnp.where(s == 0, dxm, jnp.zeros_like(dxm))
        dx = jax.lax.psum(dxm, PIPE_AXIS).reshape(xl.shape)
        # dtail is nonzero only where the masked contrib had gradient
        # (the last stage); psum replicates it over 'pipe' for export
        dtp = jax.tree.map(lambda a: jax.lax.psum(a, PIPE_AXIS), dtp)
        return loss, dstate, dx, dtp

    scalar_spec = P()
    sid_spec = P(PIPE_AXIS)
    sid = jnp.arange(p, dtype=jnp.int32)
    f_primal = jax.shard_map(
        fwd_only_body,
        in_specs=(state_specs, x_spec, y_spec, tp_specs, scalar_spec,
                  sid_spec),
        out_specs=scalar_spec, check_vma=False, axis_names={PIPE_AXIS},
    )
    f_train = jax.shard_map(
        interleaved_body,
        in_specs=(state_specs, x_spec, y_spec, tp_specs, scalar_spec,
                  sid_spec),
        out_specs=(scalar_spec, state_specs, x_spec, tp_specs),
        check_vma=False, axis_names={PIPE_AXIS},
    )

    @jax.custom_vjp
    def run(state, xl, tp, yl, nv):
        return f_primal(state, xl, yl, tp, nv, sid)

    def run_fwd(state, xl, tp, yl, nv):
        loss, dstate, dx, dtp = f_train(state, xl, yl, tp, nv, sid)
        return loss, (dstate, dx, dtp)

    def run_bwd(res, g):
        dstate, dx, dtp = res
        import numpy as np

        scale = lambda t: jax.tree.map(
            lambda a: (a * g).astype(a.dtype), t)
        # int inputs (targets, n_valid) have float0 cotangents
        return (scale(dstate), (dx * g).astype(dx.dtype), scale(dtp),
                np.zeros((B, T), jax.dtypes.float0),
                np.zeros((), jax.dtypes.float0))

    run.defvjp(run_fwd, run_bwd)
    return run(state, x.astype(t_dtype), tail_params,
               targets.astype(jnp.int32), jnp.asarray(n_valid, jnp.int32))
