"""Pipeline parallelism — GPipe-style microbatching over the 'pipe' mesh
axis (BEYOND the blueprint: SURVEY.md §2c lists PP as a parity non-goal;
this lands it anyway as the last missing first-class strategy, the
TPU-idiomatic way the survey sketches — "shard_map + collective-permute
microbatch pipeline").

Mechanism. The scan-stacked layer params (L, ...) shard their LAYER axis
over 'pipe' (partition.match_partition_rules), so stage s owns layers
[s·L/p, (s+1)·L/p). A `jax.shard_map` manual ONLY over 'pipe'
(axis_names={'pipe'}) runs the classic GPipe schedule: the batch splits
into M microbatches, and for ticks t = 0..M+p-2 stage s processes
microbatch t-s (when in range) through its local layer stack, then
`lax.ppermute`s the activation one hop to stage s+1. Stage p-1 collects
finished microbatches; a masked psum broadcasts the result back to every
stage (embeddings/norm/head outside this region are replicated over
'pipe', so all stages need the block-stack output). TWO backward
schedules share this forward (`pipeline_schedule`): 'gpipe' is plain
autodiff (the transpose of ppermute is the reverse ppermute and the
transpose of the tick scan is the reverse schedule — stash is the
scan's own per-layer residuals for every in-flight micro), 'remat' is
a custom-vjp mirrored-tick backward stashing only stage INPUTS with
just-in-time recompute (the 1F1B activation-stash class; measured
3.4-6.9× smaller compiled temp memory — BASELINE.md "Pipeline cost
table"). Per-layer remat composes with both.

Composition. Because the region is manual only over 'pipe', everything
else stays GSPMD: batch stays sharded over data/fsdp, weights over
fsdp/tensor. Nested shard_maps compose since r5 PROVIDED they name only
the free (non-Manual) axes — partition.free_axis_names documents the
transpose hazard (a nested wrap that default-names the Manual 'pipe'
axis claims replication over it and psums cotangents across stages;
measured 2.8e-3 gradient corruption, 7e-3 in the r4 form). The pallas
flash wrap and ring/ulysses all follow the rule, so pipe meshes keep
partitioned attention (zero all-gathers, test_pallas_spmd) and
pipe×context trains sequence-parallel inside the pipeline
(tests/test_pipeline.py pp-cp-* cases). One residual constraint:
jax.lax.axis_index cannot lower in a nested shard_map under Shardy —
ring ships its position in as data instead (ring_attention). Bubble
fraction is the standard (p-1)/(M+p-1); pick M =
pipeline_microbatches >= p to amortize (default 2p).

Trajectory equivalence vs the unpipelined model is exact up to fp
reassociation: the same layers run in the same order per token, only
batch-sliced — pinned by tests/test_pipeline.py on pipe:2 / pipe:4 and
pipe×data meshes.
"""

import jax
import jax.numpy as jnp
from flax import nnx
from jax.sharding import PartitionSpec as P

from avenir_tpu.models.common import resolve_remat_policy

PIPE_AXIS = "pipe"


def _staircase(t, s, M):
    """(micro index, is-real) for stage s at tick t — THE schedule math,
    shared by the gpipe tick body and the remat schedule's forward AND
    mirrored backward so the three can never drift (review r5)."""
    mi = jnp.clip(t - s, 0, M - 1)
    real = jnp.logical_and(t - s >= 0, t - s < M)
    return mi, real


def pipeline_axis_size() -> int:
    """Size of the ambient mesh's 'pipe' axis (1 = pipelining off)."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return 1
    return dict(mesh.shape).get(PIPE_AXIS, 1)


def layer_stack_dispatch(x, stacked, *, call, n_micro=0, remat=False,
                         remat_policy=None, aux0=None, schedule="gpipe"):
    """THE one home for the pipeline-vs-scan choice, shared by every
    dense family (gpt.py / llama.py have exactly one call site each):
    GPipe when the ambient mesh has pipe > 1, else nnx.scan. The aux
    contract is shared by both paths: with `aux0` given, `call(layer, h)`
    returns (h, aux) and the result is (out, aux0 + sum-over-layers) —
    the scan path accumulates through its carry, the pipeline through
    its tick/psum machinery (batch-mean statistics only; see
    pipeline_layer_stack). `schedule` picks the pipeline backward form
    ('gpipe' | 'remat'); off-pipe meshes ignore it."""
    if pipeline_axis_size() > 1:
        return pipeline_layer_stack(x, stacked, call=call, n_micro=n_micro,
                                    remat=remat, remat_policy=remat_policy,
                                    aux0=aux0, schedule=schedule)
    from avenir_tpu.models.common import scan_layer_stack

    if aux0 is None:
        return scan_layer_stack(x, stacked, call=call, remat=remat,
                                remat_policy=remat_policy)

    def aux_call(lyr, carry):
        h, acc = carry
        h, a = call(lyr, h)
        return (h, jax.tree.map(jnp.add, acc, a))

    return scan_layer_stack((x, aux0), stacked, call=aux_call, remat=remat,
                            remat_policy=remat_policy)


def pipeline_layer_stack(x, stacked, *, call=None, n_micro=0, remat=False,
                         remat_policy=None, aux0=None, schedule="gpipe"):
    """Run (B, T, C) activations through a scan-stacked layer module with
    the layer axis sharded over 'pipe', GPipe-scheduled. Drop-in
    replacement for scan_layer_stack when the mesh has pipe > 1.

    `schedule` selects the BACKWARD memory strategy (identical forward
    schedule and identical trajectories):
      - 'gpipe' (default): plain autodiff through the tick scan — the
        scan stashes per-LAYER residuals for every in-flight microbatch,
        O((M+p) * L/p) layer-activation sets per stage.
      - 'remat': custom-vjp reverse tick schedule — the forward stashes
        ONLY each microbatch's stage INPUT (O(M) single activations per
        stage), and the backward re-runs the local stack per microbatch
        just-in-time in mirrored tick order, so per-layer residuals
        exist for ONE microbatch at a time. This is the activation-stash
        class 1F1B targets. What it is NOT: 1F1B's forward/backward
        INTERLEAVING, which cannot exist under PP-as-pure-layout — the
        backward of micro m may only start once the loss is known, and
        the loss lives OUTSIDE this region (after the psum-broadcast,
        in the model head); interleaving would require the per-micro
        loss computed at the last stage inside the schedule, i.e. a
        dedicated pipeline_train_step that owns embeddings/head/loss
        rather than a layer-stack layout transform. Measured memory in
        BASELINE.md "Pipeline cost table". MoE aux stats are gpipe-only
        (the remat backward would need the aux cotangent threaded
        through the recompute — fail-loud below).

    `aux0` (optional, a pytree of fp32 BATCH-MEAN statistics — MoE
    router stats): `call(layer, h)` must then return (h, aux), and the
    function returns (out, aux0 + aux_sum) where aux_sum accumulates
    over local layers, real (non-bubble) ticks, and stages, scaled by
    1/M. Exact for batch means: microbatches are equal-sized, so the
    mean of micro-means IS the full-batch mean — and Mixtral's router
    STATS are computed pre-capacity ('on intent'), so the aggregated
    stats (and any aux loss derived from them, however nonlinear) equal
    the unpipelined full-batch run exactly, drops or no drops.
    Capacity-style values derived from the per-forward token count —
    Mixtral's expert queue C — are computed per MICRObatch under the
    pipeline; exact parity of the TOKEN OUTPUTS with the unpipelined
    model therefore holds when capacity admits every token, and with
    drops the outputs/CE-loss/grads match the mean of M independent
    B/M-sized forwards over the STRIDED row groups b % M == m — the
    (B,)->(B//M, M) reshape keeps the sharded batch dim intact, so the
    micro axis is the fast-varying one (pinned by
    test_pipeline_mixtral_drop_semantics_match_microbatched_oracle).
    NB the mean of per-micro AUX losses is NOT the pipelined aux (the
    aux is nonlinear in the stats; the pipeline aggregates stats first,
    which is the faithful-to-full-batch choice)."""
    p = pipeline_axis_size()
    assert p > 1, "pipeline_layer_stack requires a pipe axis > 1"
    if call is None:
        call = lambda lyr, h: lyr(h)
    graphdef, state = nnx.split(stacked)
    n_layer = jax.tree.leaves(state)[0].shape[0]
    assert n_layer % p == 0, (
        f"n_layer={n_layer} must divide over pipe={p} stages"
    )
    B = x.shape[0]
    if n_micro > 0:
        M = n_micro
    else:
        # auto: 2p microbatches amortize the (p-1)-tick bubble; clamp to
        # the largest divisor of B (tiny test batches) — a small M only
        # costs bubble fraction, never correctness
        M = min(2 * p, B)
        while B % M:
            M -= 1
        if M < p:
            # e.g. prime B: auto-selection degraded below p and the
            # bubble dominates ((p-1)/(M+p-1) >= 50%) — tell the user
            # instead of silently serializing the pipeline
            import warnings

            warnings.warn(
                f"pipeline auto-microbatching picked M={M} < p={p} stages "
                f"(batch {B} has no divisor in [p, 2p]); bubble fraction "
                f"{(p - 1) / (M + p - 1):.0%} — set pipeline_microbatches "
                "or pick a batch size divisible by a multiple of the "
                "stage count", stacklevel=2,
            )
    assert B % M == 0, (
        f"global batch {B} must divide into {M} pipeline microbatches "
        "(set pipeline_microbatches to a divisor)"
    )
    state_specs = jax.tree.map(
        lambda a: P(PIPE_AXIS, *([None] * (a.ndim - 1))), state
    )
    x_spec = P(*([None] * x.ndim))
    # XLA:CPU's float-normalization pass CHECK-crashes ("Invalid binary
    # instruction opcode copy", hlo_instruction.cc) on bf16 ppermute/psum
    # inside a partial-manual region (minimal repro in the r4 notes;
    # fp32 compiles fine, and TPU has native bf16 collectives so the
    # pass never fires there). Off-TPU, move activations between stages
    # in fp32 — bf16->fp32->bf16 is exact, so the trajectory is
    # bit-identical; the 2x hop bytes only exist on the CPU harness.
    f32_transport = (x.dtype == jnp.bfloat16
                     and jax.default_backend() != "tpu")
    t_dtype = jnp.float32 if f32_transport else x.dtype
    c_dtype = x.dtype  # the layers always compute in the original dtype

    def apply_layer(layer_state, h):
        # plain lax.scan + direct module call instead of scan_layer_stack:
        # nnx transforms refuse graph nodes created at an outer trace
        # level, and this sits at shard_map->scan(tick)->scan(layer) depth
        blk = nnx.merge(graphdef, layer_state)
        out = call(blk, h)
        if aux0 is None:
            return out, jnp.float32(0.0)
        return out  # (h, aux) per the aux contract

    if remat:
        apply_layer = jax.checkpoint(
            apply_layer, policy=resolve_remat_policy(remat_policy)
        )
    aux_zero = (jax.tree.map(lambda a: jnp.zeros_like(a, jnp.float32), aux0)
                if aux0 is not None else jnp.float32(0.0))

    if schedule == "remat":
        assert aux0 is None, (
            "pipeline_schedule='remat' does not carry MoE aux stats yet "
            "(the reverse-tick backward would need the aux cotangent "
            "threaded through the recompute); use the default 'gpipe' "
            "schedule for MoE models"
        )
        return _remat_schedule(x, state, p=p, M=M, apply_layer=apply_layer,
                               state_specs=state_specs, x_spec=x_spec,
                               t_dtype=t_dtype, c_dtype=c_dtype)
    assert schedule == "gpipe", (
        f"unknown pipeline_schedule {schedule!r}; one of 'gpipe', 'remat'"
    )

    def body(state_local, xl):
        s = jax.lax.axis_index(PIPE_AXIS)
        Bg, T, C = xl.shape
        xm = xl.reshape(Bg // M, M, T, C)  # micro m = xm[:, m] (batch
        # dim 0 keeps its data/fsdp sharding; the micro dim is unsharded)

        def run_local_stack(h):
            def layer_body(h, layer_state):
                h, aux = apply_layer(layer_state, h)
                return h, aux

            out, auxs = jax.lax.scan(layer_body, h, state_local)
            return out, jax.tree.map(lambda a: jnp.sum(a, axis=0), auxs)

        def tick(carry, t):
            outs, recv, aux_acc = carry
            mi, real = _staircase(t, s, M)
            inp = jnp.where(s == 0, xm[:, mi], recv).astype(c_dtype)
            out, aux_m = run_local_stack(inp)
            recv_next = jax.lax.ppermute(
                out.astype(t_dtype), PIPE_AXIS,
                [(i, i + 1) for i in range(p - 1)]
            )
            # real: this stage processed a REAL microbatch this tick (not
            # a warmup/drain bubble) — its aux contribution counts
            aux_acc = jax.tree.map(
                lambda acc, a: acc + jnp.where(real, a, 0.0), aux_acc, aux_m
            )
            active = jnp.logical_and(s == p - 1, real)
            outs = jnp.where(active, outs.at[:, mi].set(out.astype(t_dtype)),
                             outs)
            return (outs, recv_next, aux_acc), None

        (outs, _, aux_acc), _ = jax.lax.scan(
            tick, (jnp.zeros(xm.shape, t_dtype),
                   jnp.zeros(xm[:, 0].shape, t_dtype), aux_zero),
            jnp.arange(M + p - 1),
        )
        # only stage p-1 holds real outputs; masked psum broadcasts them.
        # The region returns t_dtype: its replicated-over-pipe output
        # transposes to a psum of the COTANGENT at the boundary, which
        # must also avoid bf16 off-TPU (same XLA:CPU crash, bwd-side) —
        # the cast back to compute dtype happens outside the shard_map
        outs = jnp.where(s == p - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, PIPE_AXIS)
        # aux: stages hold disjoint layer groups -> psum sums all layers;
        # /M folds the sum over micros back to the full-batch mean
        aux_tot = jax.tree.map(
            lambda a: jax.lax.psum(a, PIPE_AXIS) / M, aux_acc
        )
        return outs.reshape(Bg, T, C), aux_tot

    aux_specs = jax.tree.map(lambda a: P(*([None] * a.ndim)), aux_zero)
    f = jax.shard_map(
        body, in_specs=(state_specs, x_spec), out_specs=(x_spec, aux_specs),
        check_vma=False, axis_names={PIPE_AXIS},
    )
    # also keep the region INPUT in t_dtype: its cotangent rides the
    # reverse boundary the same way
    out, aux_tot = f(state, x.astype(t_dtype))
    out = out.astype(x.dtype)
    if aux0 is None:
        return out
    return out, jax.tree.map(jnp.add, aux0, aux_tot)


def _remat_schedule(x, state, *, p, M, apply_layer, state_specs, x_spec,
                    t_dtype, c_dtype):
    """The 'remat' pipeline backward (see pipeline_layer_stack): a
    custom-vjp pair of shard_map regions, both manual only over 'pipe'.

    Forward: the standard GPipe tick staircase, but each stage also
    STASHES the microbatch input it consumed — (M, Bm, T, C) per stage,
    exported pipe-sharded as (p*M, Bm, T, C) so it rides to the backward
    as a plain residual.

    Backward: the mirrored staircase. At reverse tick t (from M+p-2 down
    to 0) stage s handles micro m = t-s: it re-runs its local stack from
    stash[m] under jax.vjp, applies the cotangent arriving from stage
    s+1 (reverse ppermute — the transpose of the forward hop), adds the
    weight-grad contribution, and sends the input-cotangent one hop
    upstream. The cotangent for micro m reaches stage s exactly one
    reverse tick after stage s+1 produced it — the same lockstep the
    forward uses, mirrored. Per-layer residuals therefore exist for ONE
    microbatch per stage at any time, instead of for every in-flight
    microbatch across the whole tick scan."""
    fwd_perm = [(i, i + 1) for i in range(p - 1)]
    bwd_perm = [(i + 1, i) for i in range(p - 1)]

    def run_local(state_local, h):
        def layer_body(h, layer_state):
            h, _ = apply_layer(layer_state, h)
            return h, None

        out, _ = jax.lax.scan(layer_body, h, state_local)
        return out

    def fwd_body(state_local, xl):
        s = jax.lax.axis_index(PIPE_AXIS)
        Bg, T, C = xl.shape
        xm = xl.reshape(Bg // M, M, T, C)

        def tick(carry, t):
            outs, recv, stash = carry
            mi, real = _staircase(t, s, M)
            inp = jnp.where(s == 0, xm[:, mi], recv)
            stash = jnp.where(real, stash.at[mi].set(inp), stash)
            out = run_local(state_local, inp.astype(c_dtype)).astype(t_dtype)
            recv_next = jax.lax.ppermute(out, PIPE_AXIS, fwd_perm)
            active = jnp.logical_and(s == p - 1, real)
            outs = jnp.where(active, outs.at[:, mi].set(out), outs)
            return (outs, recv_next, stash), None

        Bm = xl.shape[0] // M
        init = (jnp.zeros(xm.shape, t_dtype),
                jnp.zeros((Bm, T, C), t_dtype),
                jnp.zeros((M, Bm, T, C), t_dtype))
        (outs, _, stash), _ = jax.lax.scan(tick, init, jnp.arange(M + p - 1))
        outs = jnp.where(s == p - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, PIPE_AXIS)
        return outs.reshape(Bg, T, C), stash

    stash_spec = P(PIPE_AXIS, *([None] * x.ndim))
    f_fwd = jax.shard_map(
        fwd_body, in_specs=(state_specs, x_spec),
        out_specs=(x_spec, stash_spec),
        check_vma=False, axis_names={PIPE_AXIS},
    )

    def bwd_body(state_local, stash_local, dout):
        s = jax.lax.axis_index(PIPE_AXIS)
        Bg, T, C = dout.shape
        dm = dout.reshape(Bg // M, M, T, C)

        def stage_fn(st, h):
            return run_local(st, h.astype(c_dtype)).astype(t_dtype)

        def tick(carry, tt):
            dstate, drecv, dxm = carry
            t = (M + p - 2) - tt
            mi, real = _staircase(t, s, M)
            dout_in = jnp.where(s == p - 1, dm[:, mi], drecv)
            _, vjp_fn = jax.vjp(stage_fn, state_local, stash_local[mi])
            dst_i, dinp = vjp_fn(dout_in)
            dstate = jax.tree.map(
                lambda acc, g: acc + jnp.where(real, g, 0.0), dstate, dst_i
            )
            first = jnp.logical_and(s == 0, real)
            dxm = jnp.where(first, dxm.at[:, mi].set(dinp), dxm)
            drecv_next = jax.lax.ppermute(dinp, PIPE_AXIS, bwd_perm)
            return (dstate, drecv_next, dxm), None

        init = (jax.tree.map(jnp.zeros_like, state_local),
                jnp.zeros_like(dm[:, 0]), jnp.zeros_like(dm))
        (dstate, _, dxm), _ = jax.lax.scan(tick, init,
                                           jnp.arange(M + p - 1))
        dxm = jnp.where(s == 0, dxm, jnp.zeros_like(dxm))
        dxm = jax.lax.psum(dxm, PIPE_AXIS)
        return dstate, dxm.reshape(Bg, T, C)

    f_bwd = jax.shard_map(
        bwd_body, in_specs=(state_specs, stash_spec, x_spec),
        out_specs=(state_specs, x_spec),
        check_vma=False, axis_names={PIPE_AXIS},
    )

    @jax.custom_vjp
    def run(state, xl):
        outs, _ = f_fwd(state, xl)
        return outs

    def run_fwd(state, xl):
        outs, stash = f_fwd(state, xl)
        return outs, (state, stash)

    def run_bwd(res, dout):
        state, stash = res
        dstate, dx = f_bwd(state, stash, dout.astype(t_dtype))
        return dstate, dx

    run.defvjp(run_fwd, run_bwd)
    out = run(state, x.astype(t_dtype))
    return out.astype(x.dtype)
