"""Checkpoint commit protocol + integrity checking (ISSUE 5 tentpole).

A multi-file checkpoint (the per-host sharded set) used to have no
commit marker: a SIGKILL between shard renames left a set that was only
detectable as torn by its iteration numbers, and a storage layer that
flips bits returned garbage straight into live weights. This module
gives every checkpoint artifact a verifiable identity:

- `MANIFEST.json` lists every body file with its byte size and CRC; the
  manifest's own atomic rename IS the commit — a set without a manifest
  (or whose manifest disagrees with the bytes on disk) is refused by
  restore, which then falls back to an older generation
  (checkpoint/io.select_checkpoint_source).
- The single-file `ckpt.pt` gets a sidecar (`ckpt.pt.manifest.json`)
  with the same size+CRC record. Its rename is already atomic, so the
  sidecar is pure corruption DETECTION: size-match-but-CRC-fail means
  bit rot (reject); size mismatch means a foreign writer replaced the
  file whole (the torch trainer saves ckpt.pt with no sidecar) — accept
  as legacy-unverified, because rename atomicity rules out a torn file.

Checksum: CRC32C (Castagnoli) via the `crc32c` package when installed,
zlib's CRC-32 otherwise — both C-speed; the algorithm is recorded per
manifest so a set written on one host verifies on another. Corruption
is NEVER retried: `CorruptCheckpoint` is not an OSError, so the
transient-IO retry policy (utils/retry.py) lets it propagate to the
generation-fallback logic instead of burning the retry budget on
deterministic garbage.
"""

import json
import os
import time
import zlib

MANIFEST_NAME = "MANIFEST.json"
SIDECAR_SUFFIX = ".manifest.json"  # single-file form: <file>.manifest.json
MANIFEST_FORMAT = "avenir_ckpt_manifest_v1"


class CorruptCheckpoint(Exception):
    """A checkpoint artifact failed integrity verification (checksum
    mismatch, truncation, uncommitted set). Deliberately NOT an OSError:
    retry policies must not catch it — corruption is deterministic, the
    remedy is falling back to an older generation, not re-reading."""


def _crc32c_py():  # pragma: no cover — exercised only where installed
    try:
        import crc32c

        return "crc32c", crc32c.crc32c
    except ImportError:
        return None


def checksum_algos():
    """{name: update_fn(data, crc) -> crc}. zlib's CRC-32 is always
    available; CRC32C is preferred when the package exists."""
    algos = {"crc32": zlib.crc32}
    c = _crc32c_py()
    if c is not None:
        algos[c[0]] = c[1]
    return algos


def preferred_algo():
    algos = checksum_algos()
    return "crc32c" if "crc32c" in algos else "crc32"


def checksum_update_fn(algo):
    """The update fn for `algo`, or CorruptCheckpoint when this host
    cannot verify it — callers treat that exactly like a failed
    verification (fall back / fail loud), never as a crash."""
    fn = checksum_algos().get(algo)
    if fn is None:
        raise CorruptCheckpoint(
            f"manifest uses checksum algo {algo!r}, unavailable on this "
            "host (install the crc32c package to verify this artifact)"
        )
    return fn


class ChecksumWriter:
    """File-object wrapper that accumulates size + CRC as it writes, so
    the shard writer gets its checksum for free instead of re-reading
    the file it just streamed out."""

    def __init__(self, f, algo=None):
        self._f = f
        self.algo = algo or preferred_algo()
        self._update = checksum_update_fn(self.algo)
        self.crc = 0
        self.nbytes = 0

    def write(self, data):
        self.crc = self._update(data, self.crc) & 0xFFFFFFFF
        self.nbytes += len(data)
        return self._f.write(data)

    def flush(self):
        self._f.flush()


class ChecksumReader:
    """Streaming mirror of ChecksumWriter: accumulates size + CRC over
    the bytes AS READ, so an unpickler can consume a checkpoint body
    without the whole file ever sitting in one host buffer. The caller
    verifies `crc`/`nbytes` after draining to EOF and BEFORE using
    anything parsed from the stream."""

    def __init__(self, f, algo=None):
        self._f = f
        self.algo = algo or preferred_algo()
        self._update = checksum_update_fn(self.algo)
        self.crc = 0
        self.nbytes = 0

    def _count(self, data):
        self.crc = self._update(data, self.crc) & 0xFFFFFFFF
        self.nbytes += len(data)
        return data

    def read(self, n=-1):
        return self._count(self._f.read(n))

    def readline(self):  # pickle.Unpickler requires it
        return self._count(self._f.readline())

    def readinto(self, b):
        n = self._f.readinto(b)
        self._count(bytes(b[:n]))
        return n

    def drain(self, chunk_bytes=1 << 20):
        """Consume to EOF (counting), so crc/nbytes cover the file."""
        while self.read(chunk_bytes):
            pass


def file_checksum(path, algo=None, chunk_bytes=1 << 20):
    """(nbytes, crc) of a file, streamed in chunks (peak memory is one
    chunk — the streaming-save memory contract extends to verification).
    An `algo` this host cannot compute raises CorruptCheckpoint (treat
    as unverifiable, not a crash)."""
    algo = algo or preferred_algo()
    update = checksum_update_fn(algo)
    crc = 0
    n = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk_bytes)
            if not buf:
                break
            crc = update(buf, crc) & 0xFFFFFFFF
            n += len(buf)
    return n, crc


def build_manifest(*, iter_num, form, files, algo=None, extra=None):
    """`files`: {basename: (nbytes, crc) or (nbytes, crc, algo)}.
    `form`: 'full' | 'sharded'. A per-file algo overrides the set-level
    one — a pod's hosts can differ on whether the crc32c package is
    installed, and each shard's CRC was computed by its writer."""
    top = algo or preferred_algo()
    ents = {}
    for name, tup in sorted(files.items()):
        nb, crc = tup[0], tup[1]
        ent = {"bytes": int(nb), "crc": int(crc)}
        if len(tup) > 2 and tup[2] and tup[2] != top:
            ent["algo"] = tup[2]
        ents[name] = ent
    m = {
        "format": MANIFEST_FORMAT,
        "iter_num": int(iter_num),
        "form": form,
        "t": time.time(),
        "algo": top,
        "files": ents,
    }
    if extra:
        m.update(extra)
    return m


def file_algo(manifest, name):
    """The checksum algo for one manifest entry (per-file override or
    the set-level default)."""
    ent = manifest["files"][name]
    return ent.get("algo", manifest.get("algo", "crc32"))


def manifest_path(dirpath, form):
    """Sharded sets own the directory's MANIFEST.json; the single-file
    form uses a sidecar so both can coexist (out_dir holds a full
    ckpt.pt AND a sharded set at different iterations)."""
    if form == "sharded":
        return os.path.join(dirpath, MANIFEST_NAME)
    assert form == "full", form
    return os.path.join(dirpath, "ckpt.pt" + SIDECAR_SUFFIX)


def write_manifest(dirpath, manifest):
    """Atomic write (json to .part, rename). For sharded sets this
    rename IS the set's commit point; everything before it is torn."""
    path = manifest_path(dirpath, manifest["form"])
    tmp = path + ".part"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_manifest(dirpath, form):
    """Parsed manifest, or None when absent/unparseable (an unparseable
    manifest is an UNCOMMITTED set: the commit is the rename of a fully
    written json, so garbage here means the commit never happened)."""
    path = manifest_path(dirpath, form)
    try:
        with open(path) as f:
            m = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if m.get("format") != MANIFEST_FORMAT:
        return None
    return m


def verify_files(dirpath, manifest, files=None):
    """Check size + CRC of `files` (default: every file in the manifest)
    against the manifest's records. Raises CorruptCheckpoint naming every
    failing file; size mismatch is reported distinctly from CRC mismatch
    (truncation vs bit rot read differently in an incident)."""
    names = list(manifest["files"]) if files is None else list(files)
    bad = []
    for name in names:
        ent = manifest["files"].get(name)
        path = os.path.join(dirpath, name)
        if ent is None:
            bad.append(f"{name}: not listed in the manifest")
            continue
        if not os.path.exists(path):
            bad.append(f"{name}: missing")
            continue
        size = os.path.getsize(path)
        if size != ent["bytes"]:
            bad.append(f"{name}: {size} bytes, manifest says {ent['bytes']} "
                       "(truncated or foreign write)")
            continue
        _, crc = file_checksum(path, algo=file_algo(manifest, name))
        if crc != ent["crc"]:
            bad.append(f"{name}: CRC {crc:#010x} != manifest "
                       f"{ent['crc']:#010x} (bit corruption)")
    if bad:
        raise CorruptCheckpoint(
            f"checkpoint in {dirpath} failed verification: " + "; ".join(bad)
        )
