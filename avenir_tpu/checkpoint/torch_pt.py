"""Read/write torch's `.pt` zipfile container in pure Python — no torch
import (SURVEY.md §2b T7, call stack §3.4; BASELINE.json:5 "same ...
checkpoint format").

Format (torch's _use_new_zipfile_serialization, torch >= 1.6):
  <stem>/data.pkl   pickle of the object; tensors appear as
                    REDUCE(torch._utils._rebuild_tensor_v2,
                           (BINPERSID(('storage', <StorageClass>, key,
                                       location, numel)),
                            offset, size, stride, requires_grad, hooks))
  <stem>/data/<key> raw little-endian storage bytes
  <stem>/version    serialization format version ("3")
  <stem>/byteorder  "little" (torch >= 2.1)

Reading uses the stdlib Unpickler with `find_class`/`persistent_load`
overridden, so arbitrary torch internals never execute — unknown globals
fail loud. Writing uses a hand-rolled protocol-2 pickler: emitting GLOBAL
opcodes by hand is what lets us reference `torch.FloatStorage` etc. without
torch being importable (stdlib pickle verifies globals against live
modules; we must not fake a `torch` module in sys.modules on a pod where
real code may probe for torch).

Tensors materialize as numpy arrays (bfloat16 via ml_dtypes). Shared
storages (tied weights) round-trip: arrays that share a base get one
storage entry on write, and views of one storage share memory on read
until copied.
"""

import collections
import io
import pickle
import struct
import zipfile

import ml_dtypes
import numpy as np

BFLOAT16 = np.dtype(ml_dtypes.bfloat16)

# torch legacy storage class name ↔ numpy dtype
_STORAGE_TO_DTYPE = {
    "DoubleStorage": np.dtype("<f8"),
    "FloatStorage": np.dtype("<f4"),
    "HalfStorage": np.dtype("<f2"),
    "BFloat16Storage": BFLOAT16,
    "LongStorage": np.dtype("<i8"),
    "IntStorage": np.dtype("<i4"),
    "ShortStorage": np.dtype("<i2"),
    "CharStorage": np.dtype("i1"),
    "ByteStorage": np.dtype("u1"),
    "BoolStorage": np.dtype("?"),
}
_DTYPE_TO_STORAGE = {v: k for k, v in _STORAGE_TO_DTYPE.items()}


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

class _StorageType:
    def __init__(self, name):
        self.name = name


def _rebuild_tensor_v2(storage, offset, size, stride, requires_grad,
                       backward_hooks, metadata=None):
    """Reconstruct a tensor as a numpy array from a flat storage array."""
    itemsize = storage.dtype.itemsize
    byte_strides = tuple(s * itemsize for s in stride)
    return np.lib.stride_tricks.as_strided(
        storage[offset:], shape=tuple(size), strides=byte_strides, writeable=False
    )


class _Unpickler(pickle.Unpickler):
    def __init__(self, file, storage_loader):
        super().__init__(file, encoding="utf-8")
        self._load_storage = storage_loader

    def find_class(self, module, name):
        if module == "torch._utils" and name in (
            "_rebuild_tensor_v2", "_rebuild_tensor"
        ):
            return _rebuild_tensor_v2
        if module == "torch" and name in _STORAGE_TO_DTYPE:
            return _StorageType(name)
        if module == "torch" and name == "Size":
            return tuple
        if module == "collections" and name == "OrderedDict":
            return collections.OrderedDict
        if module == "builtins":
            import builtins

            return getattr(builtins, name)
        raise pickle.UnpicklingError(
            f"torch_pt reader does not allow global {module}.{name} — "
            "extend the allowlist if this checkpoint is trusted"
        )

    def persistent_load(self, pid):
        assert isinstance(pid, tuple) and pid[0] == "storage", pid
        _, storage_type, key, _location, _numel = pid
        dtype = _STORAGE_TO_DTYPE[storage_type.name]
        return self._load_storage(str(key), dtype)


def load_pt(path_or_file):
    """Load a torch-format .pt file. Returns the object with every tensor
    as a numpy array (copies — safe after the zip closes)."""
    with zipfile.ZipFile(path_or_file, "r") as zf:
        names = zf.namelist()
        pkl_name = next(n for n in names if n.endswith("/data.pkl"))
        prefix = pkl_name[: -len("data.pkl")]
        cache = {}

        def load_storage(key, dtype):
            if key not in cache:
                raw = zf.read(f"{prefix}data/{key}")
                cache[key] = np.frombuffer(raw, dtype=dtype)
            return cache[key]

        with zf.open(pkl_name) as f:
            obj = _Unpickler(io.BytesIO(f.read()), load_storage).load()
    # as_strided views alias the storage buffers; copy to own the memory
    return _copy_arrays(obj)


def _copy_arrays(obj):
    if isinstance(obj, np.ndarray):
        return np.ascontiguousarray(obj)
    if isinstance(obj, collections.OrderedDict):
        return collections.OrderedDict(
            (k, _copy_arrays(v)) for k, v in obj.items()
        )
    if isinstance(obj, dict):
        return {k: _copy_arrays(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_copy_arrays(v) for v in obj)
    return obj


# ---------------------------------------------------------------------------
# writing — minimal protocol-2 pickler
# ---------------------------------------------------------------------------

class _MiniPickler:
    """Hand-rolled pickler for the checkpoint object tree: dict/OrderedDict,
    list, tuple, str, bool, int, float, None, and numpy arrays (emitted as
    torch tensors). Nothing else — fail loud on surprises."""

    def __init__(self, out, storages):
        self.out = out
        self.storages = storages  # id(base_array) -> (key, base_array)

    def w(self, b):
        self.out.write(b)

    def global_(self, module, name):
        self.w(b"c" + module.encode() + b"\n" + name.encode() + b"\n")

    def save(self, obj):
        if obj is None:
            self.w(b"N")
        elif obj is True:
            self.w(b"\x88")
        elif obj is False:
            self.w(b"\x89")
        elif isinstance(obj, (int, np.integer)):
            self.save_int(int(obj))
        elif isinstance(obj, (float, np.floating)):
            self.w(b"G" + struct.pack(">d", float(obj)))
        elif isinstance(obj, str):
            raw = obj.encode("utf-8")
            self.w(b"X" + struct.pack("<I", len(raw)) + raw)
        elif isinstance(obj, np.ndarray):
            self.save_tensor(obj)
        elif isinstance(obj, (dict, collections.OrderedDict)):
            self.save_dict(obj)
        elif isinstance(obj, list):
            self.w(b"]")
            if obj:
                self.w(b"(")
                for v in obj:
                    self.save(v)
                self.w(b"e")
        elif isinstance(obj, tuple):
            self.save_tuple(obj)
        else:
            raise TypeError(
                f"torch_pt writer cannot serialize {type(obj).__name__!r}"
            )

    def save_int(self, v):
        if 0 <= v < 256:
            self.w(b"K" + bytes([v]))
        elif 0 <= v < 65536:
            self.w(b"M" + struct.pack("<H", v))
        elif -(2 ** 31) <= v < 2 ** 31:
            self.w(b"J" + struct.pack("<i", v))
        else:
            enc = v.to_bytes((v.bit_length() + 8) // 8, "little", signed=True)
            self.w(b"\x8a" + bytes([len(enc)]) + enc)

    def save_tuple(self, obj):
        if len(obj) == 0:
            self.w(b")")
            return
        if len(obj) <= 3:
            for v in obj:
                self.save(v)
            self.w({1: b"\x85", 2: b"\x86", 3: b"\x87"}[len(obj)])
            return
        self.w(b"(")
        for v in obj:
            self.save(v)
        self.w(b"t")

    def save_dict(self, obj):
        if isinstance(obj, collections.OrderedDict):
            # torch state_dicts are OrderedDicts; keep the type faithful
            self.global_("collections", "OrderedDict")
            self.w(b")")  # empty args tuple
            self.w(b"R")
        else:
            self.w(b"}")
        if obj:
            self.w(b"(")
            for k, v in obj.items():
                self.save(k)
                self.save(v)
            self.w(b"u")

    def save_tensor(self, arr):
        """Emit REDUCE(torch._utils._rebuild_tensor_v2, (storage, offset,
        size, stride, requires_grad, hooks)) with the storage referenced by
        persistent id. Storage dedup is by array identity, so tied weights
        (the bridge exports the SAME numpy object under both keys) share one
        storage entry exactly like torch's shared tensors."""
        lookup = BFLOAT16 if arr.dtype == BFLOAT16 else np.dtype(arr.dtype.str.replace(">", "<"))
        if lookup not in _DTYPE_TO_STORAGE:
            raise TypeError(f"no torch storage type for numpy dtype {arr.dtype}")
        sid = id(arr)
        if sid not in self.storages:
            self.storages[sid] = (str(len(self.storages)), arr)
        key, _ = self.storages[sid]

        self.global_("torch._utils", "_rebuild_tensor_v2")
        self.w(b"(")  # MARK for the args tuple
        # arg 1: the storage, via persistent id
        self.w(b"(")
        self.save("storage")
        self.global_("torch", _DTYPE_TO_STORAGE[lookup])
        self.save(key)
        self.save("cpu")
        self.save_int(int(arr.size))
        self.w(b"t")
        self.w(b"Q")
        # args 2..6
        self.save_int(0)
        self.save_tuple(tuple(int(s) for s in arr.shape))
        contiguous_stride = []
        acc = 1
        for dim in reversed(arr.shape):
            contiguous_stride.append(acc)
            acc *= dim
        self.save_tuple(tuple(reversed(contiguous_stride)))
        self.w(b"\x89")  # requires_grad=False
        self.global_("collections", "OrderedDict")
        self.w(b")")
        self.w(b"R")
        self.w(b"t")  # close args tuple
        self.w(b"R")  # REDUCE -> tensor


def _pickle_checkpoint(obj, storages):
    out = io.BytesIO()
    p = _MiniPickler(out, storages)
    out.write(b"\x80\x02")
    p.save(obj)
    out.write(b".")
    return out.getvalue()


def save_pt(obj, path, stem="archive"):
    """Write `obj` (dicts/lists/scalars/str/numpy arrays) as a torch-format
    .pt that real `torch.load` accepts. Arrays become CPU tensors."""
    storages = {}
    pkl = _pickle_checkpoint(obj, storages)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr(f"{stem}/data.pkl", pkl)
        zf.writestr(f"{stem}/byteorder", "little")
        for key, arr in storages.values():
            data = np.ascontiguousarray(arr)
            if data.dtype == BFLOAT16:
                raw = data.tobytes()
            else:
                raw = data.astype(data.dtype.newbyteorder("<"), copy=False).tobytes()
            zf.writestr(f"{stem}/data/{key}", raw)
        zf.writestr(f"{stem}/version", "3\n")
