"""Read/write torch's `.pt` zipfile container in pure Python — no torch
import (SURVEY.md §2b T7, call stack §3.4; BASELINE.json:5 "same ...
checkpoint format").

Format (torch's _use_new_zipfile_serialization, torch >= 1.6):
  <stem>/data.pkl   pickle of the object; tensors appear as
                    REDUCE(torch._utils._rebuild_tensor_v2,
                           (BINPERSID(('storage', <StorageClass>, key,
                                       location, numel)),
                            offset, size, stride, requires_grad, hooks))
  <stem>/data/<key> raw little-endian storage bytes
  <stem>/version    serialization format version ("3")
  <stem>/byteorder  "little" (torch >= 2.1)

Reading uses the stdlib Unpickler with `find_class`/`persistent_load`
overridden, so arbitrary torch internals never execute — unknown globals
fail loud. Writing uses a hand-rolled protocol-2 pickler: emitting GLOBAL
opcodes by hand is what lets us reference `torch.FloatStorage` etc. without
torch being importable (stdlib pickle verifies globals against live
modules; we must not fake a `torch` module in sys.modules on a pod where
real code may probe for torch).

Tensors materialize as numpy arrays (bfloat16 via ml_dtypes). Shared
storages (tied weights) round-trip: arrays that share a base get one
storage entry on write, and views of one storage share memory on read
until copied.
"""

import collections
import io
import pickle
import struct
import zipfile

import ml_dtypes
import numpy as np

BFLOAT16 = np.dtype(ml_dtypes.bfloat16)


class LazyArray:
    """A tensor placeholder carrying (shape, dtype) metadata plus a
    provider fn, materialized only at the moment its bytes are needed.
    The streaming checkpoint path (SURVEY.md §5 "sharded-read": no host
    ever holds the full fp32 tree) threads these through the bridge and
    the .pt writer/reader: save gathers ONE tensor at a time while
    writing the zip; load reads ONE storage at a time while device_put
    places it. numpy interop via __array__ (any numpy op materializes)."""

    def __init__(self, shape, dtype, fn, source=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self._fn = fn
        # optional device-array handle: lets consumers slice ON DEVICE
        # (lazy_unstack gathers one layer at a time instead of holding the
        # whole stacked base on host across the layer-major write order)
        self.source = source

    @property
    def size(self):
        out = 1
        for s in self.shape:
            out *= s
        return out

    @property
    def ndim(self):
        return len(self.shape)

    def materialize(self):
        arr = np.asarray(self._fn())
        assert arr.shape == self.shape and arr.dtype == self.dtype, (
            f"lazy provider returned {arr.shape}/{arr.dtype}, "
            f"declared {self.shape}/{self.dtype}"
        )
        return arr

    def __array__(self, dtype=None, copy=None):
        arr = self.materialize()
        return arr.astype(dtype) if dtype is not None else arr

    def transform(self, f, shape=None, dtype=None):
        """Deferred elementwise/layout transform (transpose, cast, ...)."""
        return LazyArray(self.shape if shape is None else shape,
                         self.dtype if dtype is None else dtype,
                         lambda: f(self.materialize()))

    def astype(self, dtype):
        return self.materialize().astype(dtype)

    # torch checkpoints store scalars like best_val_loss as 0-d tensors;
    # lazy loads must still support float()/int() on them
    def __float__(self):
        return float(self.materialize().reshape(-1)[0])

    def __int__(self):
        return int(self.materialize().reshape(-1)[0])


def lazy_unstack(a, n):
    """Split a stacked (n, ...) LazyArray/ndarray into n lazy slices.

    When the LazyArray carries a device-array `source`, each slice gathers
    ONLY its own layer from device (x[i] is a device-side slice) — nothing
    larger than one layer ever lands on host, regardless of consumption
    order. Otherwise the base is materialized once, shared, and refcounted
    (freed after the last slice is consumed) — but note the base stays
    live from the first slice to the last, so prefer sourced arrays for
    big stacks."""
    shape = tuple(a.shape[1:])
    dtype = a.dtype
    src = getattr(a, "source", None)
    if src is not None:
        gather = a.gather_fn if getattr(a, "gather_fn", None) else np.asarray
        return [
            LazyArray(shape, dtype,
                      lambda i=i: np.asarray(gather(src[i])))
            for i in range(n)
        ]
    # consumed indices tracked as a SET, not a counter: a slice that is
    # materialized twice must not over-decrement (which would free the base
    # early and re-materialize it per access for every later slice)
    state = {"v": None, "consumed": set()}

    def make(i):
        def fn():
            if state["v"] is None:
                state["v"] = np.asarray(a)
            out = np.ascontiguousarray(state["v"][i])
            state["consumed"].add(i)
            if len(state["consumed"]) >= n:
                state["v"] = None
            return out

        return fn

    return [LazyArray(shape, dtype, make(i)) for i in range(n)]

# torch legacy storage class name ↔ numpy dtype
_STORAGE_TO_DTYPE = {
    "DoubleStorage": np.dtype("<f8"),
    "FloatStorage": np.dtype("<f4"),
    "HalfStorage": np.dtype("<f2"),
    "BFloat16Storage": BFLOAT16,
    "LongStorage": np.dtype("<i8"),
    "IntStorage": np.dtype("<i4"),
    "ShortStorage": np.dtype("<i2"),
    "CharStorage": np.dtype("i1"),
    "ByteStorage": np.dtype("u1"),
    "BoolStorage": np.dtype("?"),
}
_DTYPE_TO_STORAGE = {v: k for k, v in _STORAGE_TO_DTYPE.items()}


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

class _StorageType:
    def __init__(self, name):
        self.name = name


class _LazyStorage:
    """Deferred zip storage read for load_pt(lazy=True)."""

    def __init__(self, path, entry, dtype):
        self.path = path
        self.entry = entry
        self.dtype = dtype

    def load(self):
        with zipfile.ZipFile(self.path, "r") as zf:
            return np.frombuffer(zf.read(self.entry), dtype=self.dtype)


def _rebuild_tensor_v2(storage, offset, size, stride, requires_grad,
                       backward_hooks, metadata=None):
    """Reconstruct a tensor as a numpy array (or LazyArray when reading
    lazily) from a flat storage."""
    def strided(flat):
        itemsize = flat.dtype.itemsize
        byte_strides = tuple(s * itemsize for s in stride)
        return np.lib.stride_tricks.as_strided(
            flat[offset:], shape=tuple(size), strides=byte_strides,
            writeable=False,
        )

    if isinstance(storage, _LazyStorage):
        # np.array (not ascontiguousarray: it promotes 0-d to 1-d)
        return LazyArray(
            tuple(size), storage.dtype,
            lambda: np.array(strided(storage.load())),
        )
    return strided(storage)


class _Unpickler(pickle.Unpickler):
    def __init__(self, file, storage_loader):
        super().__init__(file, encoding="utf-8")
        self._load_storage = storage_loader

    def find_class(self, module, name):
        if module == "torch._utils" and name in (
            "_rebuild_tensor_v2", "_rebuild_tensor"
        ):
            return _rebuild_tensor_v2
        if module == "torch" and name in _STORAGE_TO_DTYPE:
            return _StorageType(name)
        if module == "torch" and name == "Size":
            return tuple
        if module == "collections" and name == "OrderedDict":
            return collections.OrderedDict
        if module == "builtins":
            import builtins

            return getattr(builtins, name)
        raise pickle.UnpicklingError(
            f"torch_pt reader does not allow global {module}.{name} — "
            "extend the allowlist if this checkpoint is trusted"
        )

    def persistent_load(self, pid):
        assert isinstance(pid, tuple) and pid[0] == "storage", pid
        _, storage_type, key, _location, _numel = pid
        dtype = _STORAGE_TO_DTYPE[storage_type.name]
        return self._load_storage(str(key), dtype)


def load_pt(path_or_file, lazy=False):
    """Load a torch-format .pt file. Returns the object with every tensor
    as a numpy array (copies — safe after the zip closes).

    `lazy=True` (requires a real path): tensors come back as LazyArray
    stubs that re-open the zip and read their storage only when
    materialized — restore places one tensor on device at a time without
    the host ever holding the full tree (SURVEY.md §5 sharded-read)."""
    with zipfile.ZipFile(path_or_file, "r") as zf:
        names = zf.namelist()
        pkl_name = next(n for n in names if n.endswith("/data.pkl"))
        prefix = pkl_name[: -len("data.pkl")]
        cache = {}

        def load_storage(key, dtype):
            if lazy:
                return _LazyStorage(path_or_file, f"{prefix}data/{key}", dtype)
            if key not in cache:
                raw = zf.read(f"{prefix}data/{key}")
                cache[key] = np.frombuffer(raw, dtype=dtype)
            return cache[key]

        with zf.open(pkl_name) as f:
            obj = _Unpickler(io.BytesIO(f.read()), load_storage).load()
    # as_strided views alias the storage buffers; copy to own the memory
    return _copy_arrays(obj)


def _copy_arrays(obj):
    if isinstance(obj, np.ndarray):
        return np.ascontiguousarray(obj)
    if isinstance(obj, collections.OrderedDict):
        return collections.OrderedDict(
            (k, _copy_arrays(v)) for k, v in obj.items()
        )
    if isinstance(obj, dict):
        return {k: _copy_arrays(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_copy_arrays(v) for v in obj)
    return obj


# ---------------------------------------------------------------------------
# writing — minimal protocol-2 pickler
# ---------------------------------------------------------------------------

class _MiniPickler:
    """Hand-rolled pickler for the checkpoint object tree: dict/OrderedDict,
    list, tuple, str, bool, int, float, None, and numpy arrays (emitted as
    torch tensors). Nothing else — fail loud on surprises."""

    def __init__(self, out, storages):
        self.out = out
        self.storages = storages  # id(base_array) -> (key, base_array)

    def w(self, b):
        self.out.write(b)

    def global_(self, module, name):
        self.w(b"c" + module.encode() + b"\n" + name.encode() + b"\n")

    def save(self, obj):
        if obj is None:
            self.w(b"N")
        elif obj is True:
            self.w(b"\x88")
        elif obj is False:
            self.w(b"\x89")
        elif isinstance(obj, (int, np.integer)):
            self.save_int(int(obj))
        elif isinstance(obj, (float, np.floating)):
            self.w(b"G" + struct.pack(">d", float(obj)))
        elif isinstance(obj, str):
            raw = obj.encode("utf-8")
            self.w(b"X" + struct.pack("<I", len(raw)) + raw)
        elif isinstance(obj, (np.ndarray, LazyArray)):
            self.save_tensor(obj)
        elif isinstance(obj, (dict, collections.OrderedDict)):
            self.save_dict(obj)
        elif isinstance(obj, list):
            self.w(b"]")
            if obj:
                self.w(b"(")
                for v in obj:
                    self.save(v)
                self.w(b"e")
        elif isinstance(obj, tuple):
            self.save_tuple(obj)
        else:
            raise TypeError(
                f"torch_pt writer cannot serialize {type(obj).__name__!r}"
            )

    def save_int(self, v):
        if 0 <= v < 256:
            self.w(b"K" + bytes([v]))
        elif 0 <= v < 65536:
            self.w(b"M" + struct.pack("<H", v))
        elif -(2 ** 31) <= v < 2 ** 31:
            self.w(b"J" + struct.pack("<i", v))
        else:
            enc = v.to_bytes((v.bit_length() + 8) // 8, "little", signed=True)
            self.w(b"\x8a" + bytes([len(enc)]) + enc)

    def save_tuple(self, obj):
        if len(obj) == 0:
            self.w(b")")
            return
        if len(obj) <= 3:
            for v in obj:
                self.save(v)
            self.w({1: b"\x85", 2: b"\x86", 3: b"\x87"}[len(obj)])
            return
        self.w(b"(")
        for v in obj:
            self.save(v)
        self.w(b"t")

    def save_dict(self, obj):
        if isinstance(obj, collections.OrderedDict):
            # torch state_dicts are OrderedDicts; keep the type faithful
            self.global_("collections", "OrderedDict")
            self.w(b")")  # empty args tuple
            self.w(b"R")
        else:
            self.w(b"}")
        if obj:
            self.w(b"(")
            for k, v in obj.items():
                self.save(k)
                self.save(v)
            self.w(b"u")

    def save_tensor(self, arr):
        """Emit REDUCE(torch._utils._rebuild_tensor_v2, (storage, offset,
        size, stride, requires_grad, hooks)) with the storage referenced by
        persistent id. Storage dedup is by array identity, so tied weights
        (the bridge exports the SAME numpy object under both keys) share one
        storage entry exactly like torch's shared tensors."""
        lookup = BFLOAT16 if arr.dtype == BFLOAT16 else np.dtype(arr.dtype.str.replace(">", "<"))
        if lookup not in _DTYPE_TO_STORAGE:
            raise TypeError(f"no torch storage type for numpy dtype {arr.dtype}")
        sid = id(arr)
        if sid not in self.storages:
            self.storages[sid] = (str(len(self.storages)), arr)
        key, _ = self.storages[sid]

        self.global_("torch._utils", "_rebuild_tensor_v2")
        self.w(b"(")  # MARK for the args tuple
        # arg 1: the storage, via persistent id
        self.w(b"(")
        self.save("storage")
        self.global_("torch", _DTYPE_TO_STORAGE[lookup])
        self.save(key)
        self.save("cpu")
        self.save_int(int(arr.size))
        self.w(b"t")
        self.w(b"Q")
        # args 2..6
        self.save_int(0)
        self.save_tuple(tuple(int(s) for s in arr.shape))
        contiguous_stride = []
        acc = 1
        for dim in reversed(arr.shape):
            contiguous_stride.append(acc)
            acc *= dim
        self.save_tuple(tuple(reversed(contiguous_stride)))
        self.w(b"\x89")  # requires_grad=False
        self.global_("collections", "OrderedDict")
        self.w(b")")
        self.w(b"R")
        self.w(b"t")  # close args tuple
        self.w(b"R")  # REDUCE -> tensor


def _pickle_checkpoint(obj, storages):
    out = io.BytesIO()
    p = _MiniPickler(out, storages)
    out.write(b"\x80\x02")
    p.save(obj)
    out.write(b".")
    return out.getvalue()


def save_pt(obj, path, stem="archive", write=True):
    """Write `obj` (dicts/lists/scalars/str/numpy/LazyArray) as a
    torch-format .pt that real `torch.load` accepts. Arrays become CPU
    tensors. LazyArray entries are STREAMED: each is materialized only
    while its storage bytes are written, then freed — peak host memory is
    one tensor, not the tree.

    `write=False` materializes every storage without touching the file:
    on a multi-host mesh every process must participate in the per-leaf
    allgathers, but only the coordinator writes (SURVEY.md §3.4 ⟨proc⟩)."""
    storages = {}
    pkl = _pickle_checkpoint(obj, storages)

    def storage_bytes(arr):
        data = np.ascontiguousarray(
            arr.materialize() if isinstance(arr, LazyArray) else arr
        )
        if data.dtype == BFLOAT16:
            return data.tobytes()
        return data.astype(data.dtype.newbyteorder("<"), copy=False).tobytes()

    if not write:
        for _key, arr in storages.values():
            storage_bytes(arr)  # collective participation only
        return
    with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
        zf.writestr(f"{stem}/data.pkl", pkl)
        zf.writestr(f"{stem}/byteorder", "little")
        for key, arr in storages.values():
            zf.writestr(f"{stem}/data/{key}", storage_bytes(arr))
        zf.writestr(f"{stem}/version", "3\n")
