"""Full training-state checkpointing in the torch ckpt.pt schema
(SURVEY.md §3.4): {model, optimizer, model_args, iter_num, best_val_loss,
config}. A ckpt.pt written here resumes under the torch trainer and vice
versa — including optimizer moments, so resume is bit-honest, not just
weights (train.py:272-281 defines the schema; model.py:255-271 defines the
torch AdamW param grouping we must reproduce).
"""

import collections
import json
import os
import time

import jax
import numpy as np
from flax import nnx

from avenir_tpu.obs.metrics import get_registry

from avenir_tpu.checkpoint.bridge import (
    export_torch_state_dict,
    restack_scanned_paths,
    torch_key_to_nnx_path,
    torch_sd_to_flat_paths,
)
from avenir_tpu.checkpoint.manifest import (
    ChecksumReader,
    ChecksumWriter,
    CorruptCheckpoint,
    build_manifest,
    file_algo,
    file_checksum,
    load_manifest,
    manifest_path,
    verify_files,
    write_manifest,
)
from avenir_tpu.checkpoint.torch_pt import LazyArray, load_pt, save_pt
from avenir_tpu.utils.faults import get_injector
from avenir_tpu.utils.retry import call_with_retry


def torch_param_order(sd, model_family="gpt"):
    """Reproduce torch `named_parameters()` order (module insertion order,
    tied lm_head deduplicated) for the reference GPT (model.py:133-151).
    Needed because torch optimizer state is keyed by param *index*."""
    assert model_family == "gpt", "optimizer bridge currently covers gpt"
    keys = ["transformer.wte.weight", "transformer.wpe.weight"]
    i = 0
    while f"transformer.h.{i}.ln_1.weight" in sd:
        b = f"transformer.h.{i}."
        keys += [
            b + "ln_1.weight", b + "ln_1.bias",
            b + "attn.c_attn.weight", b + "attn.c_attn.bias",
            b + "attn.c_proj.weight", b + "attn.c_proj.bias",
            b + "ln_2.weight", b + "ln_2.bias",
            b + "mlp.c_fc.weight", b + "mlp.c_fc.bias",
            b + "mlp.c_proj.weight", b + "mlp.c_proj.bias",
        ]
        i += 1
    keys += ["transformer.ln_f.weight", "transformer.ln_f.bias"]
    return [k for k in keys if k in sd]


def _adam_groups(order, sd):
    """torch configure_optimizers grouping: decay = ndim>=2 first, then
    nodecay; param indices are global across groups (model.py:258-264)."""
    decay = [k for k in order if sd[k].ndim >= 2]
    nodecay = [k for k in order if sd[k].ndim < 2]
    return decay, nodecay


def _find_adam_state(opt_state):
    """Locate the ScaleByAdamState node inside an optax chain state."""
    found = []

    def walk(node):
        if hasattr(node, "mu") and hasattr(node, "nu") and hasattr(node, "count"):
            found.append(node)
            return
        if isinstance(node, tuple):
            for c in node:
                walk(c)

    walk(opt_state)
    assert len(found) == 1, f"expected exactly one adam state, found {len(found)}"
    return found[0]


def _replace_adam_state(opt_state, new_adam):
    def walk(node):
        if hasattr(node, "mu") and hasattr(node, "nu") and hasattr(node, "count"):
            return new_adam
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            return type(node)(*(walk(c) for c in node))
        if isinstance(node, tuple):
            return tuple(walk(c) for c in node)
        return node

    return walk(opt_state)


def _gather_one(x):
    """Pull one (possibly sharded) jax array to host numpy. On a
    multi-host mesh every process participates in the all-gather; the
    coordinator alone writes the file (SURVEY.md §3.4 ⟨proc⟩ note)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(jax.device_get(x))


def gather_to_host(tree):
    """Eager whole-tree host gather (small trees / tests)."""
    return jax.tree.map(_gather_one, tree)


def lazy_gather_tree(tree):
    """Replace every jax array leaf with a LazyArray that gathers it on
    materialize. The streaming .pt writer then pulls ONE tensor to host at
    a time — peak host memory is the largest tensor, not the full tree
    (the big-model save path, SURVEY.md §5 checkpoint bullet)."""
    def lazy(x):
        if isinstance(x, jax.Array):
            out = LazyArray(x.shape, np.dtype(x.dtype),
                            lambda x=x: _gather_one(x), source=x)
            # device-side slicing hook for lazy_unstack: x[i] slices on
            # device; gather pulls just that layer to host
            out.gather_fn = _gather_one
            return out
        return np.asarray(x)

    return jax.tree.map(lazy, tree)


def _tied(model_family):
    return model_family == "gpt"  # llama/mixtral have a real lm_head param


def save_checkpoint(out_dir, *, params, opt_state, hyper, model_args,
                    iter_num, best_val_loss, config, model_family="gpt",
                    keep_checkpoints=2, data_state=None):
    """Write out_dir/ckpt.pt in the torch schema. `params` is the nnx Param
    State; `opt_state` the optax state; `hyper` carries the torch
    param_group hyperparams (lr, betas, eps, weight_decay).

    gpt: the optimizer entry is a torch AdamW state_dict (param-index
    keyed, model.py:255-271 grouping) so torch can resume it. llama/
    mixtral have no torch counterpart in-repo; their moments are stored
    under torch-style KEYS instead of indices ("format": "avenir_adamw"),
    same container."""
    tied = _tied(model_family)
    # lazy leaves: nothing is gathered here — the streaming save_pt pulls
    # one tensor to host at a time while writing
    sd = export_torch_state_dict(lazy_gather_tree(params),
                                 model_family=model_family,
                                 tied_lm_head=tied)
    adam = _find_adam_state(opt_state)
    mu_sd = export_torch_state_dict(lazy_gather_tree(adam.mu),
                                    model_family=model_family,
                                    tied_lm_head=False)
    nu_sd = export_torch_state_dict(lazy_gather_tree(adam.nu),
                                    model_family=model_family,
                                    tied_lm_head=False)
    step = float(np.asarray(_gather_one(adam.count)))

    if model_family == "gpt":
        order = torch_param_order(sd, model_family)
        decay, nodecay = _adam_groups(order, sd)
        opt_sd = {
            "state": {
                i: {
                    "step": np.asarray(step, np.float32),
                    "exp_avg": mu_sd[k],
                    "exp_avg_sq": nu_sd[k],
                }
                for i, k in enumerate(decay + nodecay)
            },
            "param_groups": [
                {
                    "lr": hyper["lr"], "betas": tuple(hyper["betas"]),
                    "eps": hyper["eps"], "weight_decay": wd,
                    "amsgrad": False, "maximize": False, "foreach": None,
                    "capturable": False, "differentiable": False,
                    "fused": None, "decoupled_weight_decay": True,
                    "params": list(range(start, start + len(group))),
                }
                for group, wd, start in (
                    (decay, hyper["weight_decay"], 0),
                    (nodecay, 0.0, len(decay)),
                )
            ],
        }
        model_sd = collections.OrderedDict(
            (k, sd[k]) for k in list(order) + ["lm_head.weight"]
        )
    else:
        opt_sd = {
            "format": "avenir_adamw", "step": step,
            "exp_avg": mu_sd, "exp_avg_sq": nu_sd,
            "hyper": dict(hyper),
        }
        model_sd = collections.OrderedDict(sorted(sd.items()))

    ckpt = {
        "model": model_sd,
        "optimizer": opt_sd,
        "model_args": dict(model_args),
        "iter_num": int(iter_num),
        "best_val_loss": float(best_val_loss),
        "config": dict(config),
        "model_family": model_family,
    }
    if data_state is not None:
        # streaming-loader consumption counts (DataLoader.resume_state);
        # key absent in pre-streaming checkpoints, readers use .get
        ckpt["data_state"] = data_state
    # every process materializes (collective per-leaf gathers); only the
    # coordinator writes the file
    # atomic: stream to .part, then rename — a crash or SIGKILL mid-write
    # (preemption grace periods end in SIGKILL) never destroys the
    # previous good checkpoint
    write = jax.process_index() == 0
    path = os.path.join(out_dir, "ckpt.pt")
    t0 = time.perf_counter()
    if write:
        os.makedirs(out_dir, exist_ok=True)
    save_pt(ckpt, path + ".part", write=write)
    if write:
        # commit protocol (ISSUE 5): checksum the streamed .part (one
        # sequential read, page-cache warm), rename the body, then the
        # manifest sidecar — restore verifies size+CRC against it, so
        # bit rot on shared storage is detected instead of loaded.
        # Idempotent under retry: a rename that landed before a
        # transient manifest-write failure is not re-attempted.
        nbytes, crc = file_checksum(path + ".part")
        man = build_manifest(iter_num=int(iter_num), form="full",
                             files={"ckpt.pt": (nbytes, crc)})

        def _commit():
            get_injector().fail("ckpt_write_fail", what=path)
            if os.path.exists(path + ".part"):
                # drop the stale sidecar BEFORE the body rename: ckpt.pt
                # size is iteration-invariant, so a kill between rename
                # and manifest write would otherwise pair the new body
                # with the old sidecar and read as "bit corruption" —
                # rejecting a perfectly good checkpoint. No sidecar =
                # legacy accept (rename atomicity still holds).
                try:
                    os.remove(manifest_path(out_dir, "full"))
                except FileNotFoundError:
                    pass
                os.replace(path + ".part", path)
            write_manifest(out_dir, man)

        call_with_retry(_commit, what="ckpt.pt commit")
        record_generation(out_dir, ["ckpt.pt"], manifest=man,
                          keep=keep_checkpoints)
    reg = get_registry()
    reg.counter("ckpt_saves").add(1)
    reg.counter("ckpt_save_ms").add((time.perf_counter() - t0) * 1e3)
    if write:
        reg.counter("ckpt_bytes_written").add(os.path.getsize(path))


# ---- generation ring (ISSUE 5 tentpole, part 2) ----
#
# Every committed save is also recorded as a GENERATION under
# out_dir/ckpt-gens/iter-NNNNNNNN-{full,sharded}/ via hard links: the
# live artifact's next overwrite (os.replace unlinks the old name)
# leaves the generation's inodes intact, so the ring costs metadata ops
# at save time and at most K-1 extra checkpoints of disk. On restore,
# select_checkpoint_source verifies the newest candidate and walks the
# ring until one passes — a corrupted or uncommitted newest checkpoint
# degrades to "resume slightly older" instead of "run dead".

_GEN_DIR = "ckpt-gens"


def _link_or_copy(src, dst):
    """Hard link, falling back to a real copy where links are refused
    (some network filesystems). Either way dst is immune to a later
    os.replace of src's name."""
    try:
        os.link(src, dst)
    except OSError:
        import shutil

        shutil.copy2(src, dst)


def record_generation(out_dir, files, *, manifest, keep, echo=print):
    """Snapshot committed artifact `files` (basenames in out_dir) into a
    generation directory and prune the ring to `keep` entries. The
    generation's manifest is written LAST — its rename is the
    generation's commit, so a crash mid-record leaves an uncommitted
    directory that listing skips and pruning sweeps. Best-effort: a
    ring failure must not fail the save that already committed."""
    if not keep or keep <= 0:
        return None
    form = manifest["form"]
    gen = os.path.join(out_dir, _GEN_DIR,
                       f"iter-{manifest['iter_num']:08d}-{form}")
    try:
        os.makedirs(gen, exist_ok=True)
        for name in files:
            dst = os.path.join(gen, name)
            if os.path.exists(dst):
                os.remove(dst)  # re-record of the same iter (re-saves)
            _link_or_copy(os.path.join(out_dir, name), dst)
        if form == "sharded":
            # peers only join their OWN previous save, so a peer's NEXT
            # save can replace its body at the fixed shard path while
            # this coordinator thread is still linking — capturing bytes
            # the manifest's CRCs can never verify. The link pins an
            # inode, so the header's iteration tells which save it is.
            import pickle
            import shutil

            for name in files:
                with open(os.path.join(gen, name), "rb") as fh:
                    h = pickle.load(fh)
                if int(h.get("iter_num", -1)) != int(manifest["iter_num"]):
                    shutil.rmtree(gen, ignore_errors=True)
                    raise OSError(
                        f"{name} was replaced by a newer save (iter "
                        f"{h.get('iter_num')}) before the generation "
                        "could be recorded"
                    )
        write_manifest(gen, manifest)
        prune_generations(out_dir, keep)
        return gen
    except OSError as e:
        get_registry().counter("ckpt_save_errors").add(1)
        echo(f"[ckpt] generation ring update failed ({e}); the live "
             "checkpoint is committed but this save has no fallback copy")
        return None


def list_generations(out_dir):
    """Committed generations, newest first: [(iter_num, form, path)].
    A directory without a readable manifest is uncommitted debris (crash
    mid-record) and is not listed."""
    root = os.path.join(out_dir, _GEN_DIR)
    out = []
    if not os.path.isdir(root):
        return out
    for name in os.listdir(root):
        d = os.path.join(root, name)
        for form in ("full", "sharded"):
            m = load_manifest(d, form)
            if m is not None and m.get("form") == form:
                out.append((int(m["iter_num"]), form, d))
    out.sort(key=lambda t: t[0], reverse=True)
    return out


def prune_generations(out_dir, keep):
    """Drop all but the newest `keep` committed generations, plus any
    uncommitted debris directories that are not the newest entry.
    `keep` counts DISTINCT iterations, not directories: the final save
    of a pod run lands a full ckpt.pt at the same iteration as the
    eval-cadence sharded set, and counting those two dirs as two ring
    entries would silently evict every older restore point."""
    import shutil

    root = os.path.join(out_dir, _GEN_DIR)
    if not os.path.isdir(root):
        return
    gens = list_generations(out_dir)
    keep_iters = set(sorted({it for it, _, _ in gens}, reverse=True)[:keep])
    committed = {d for it, _, d in gens if it in keep_iters}
    for name in sorted(os.listdir(root)):
        d = os.path.join(root, name)
        if d not in committed:
            shutil.rmtree(d, ignore_errors=True)


def _verify_full_file(dirpath, *, strict=False, echo=print):
    """Integrity-check dirpath/ckpt.pt against its manifest sidecar.
    Returns 'verified' or 'legacy'; raises CorruptCheckpoint on definite
    corruption. Policy (docstring of checkpoint/manifest.py): no sidecar
    → legacy-unverified (the torch trainer writes none); size mismatch →
    a foreign writer replaced the file WHOLE (rename atomicity rules out
    torn files), accept unverified; size match + CRC fail → bit rot,
    reject. `strict=True` (generation dirs, which only our committed
    recorder writes) turns every unverified case into a rejection."""
    path = os.path.join(dirpath, "ckpt.pt")
    if not os.path.exists(path):
        raise CorruptCheckpoint(f"{path}: missing")
    man = load_manifest(dirpath, "full")
    if man is None:
        if strict:
            raise CorruptCheckpoint(f"{path}: no manifest (uncommitted "
                                    "generation)")
        echo(f"[ckpt] {path}: no manifest sidecar — accepting unverified "
             "(legacy save or foreign writer)")
        return "legacy"
    ent = man["files"].get("ckpt.pt")
    size = os.path.getsize(path)
    if ent is None or size != ent["bytes"]:
        if strict:
            raise CorruptCheckpoint(
                f"{path}: {size} bytes but the generation manifest says "
                f"{ent and ent['bytes']}"
            )
        echo(f"[ckpt] {path}: size differs from its manifest sidecar — a "
             "foreign writer replaced it whole (atomic rename rules out a "
             "torn file); accepting unverified")
        return "legacy"
    if os.environ.get("AVENIR_RESTORE_VERIFY", "crc") == "sizes":
        return "verified"  # size matched above; CRC read waived
    _, crc = file_checksum(path, algo=file_algo(man, "ckpt.pt"))
    if crc != ent["crc"]:
        raise CorruptCheckpoint(
            f"{path}: CRC {crc:#010x} != manifest {ent['crc']:#010x} "
            "(bit corruption)"
        )
    return "verified"


def verify_sharded_set(dirpath, *, echo=print):
    """Integrity-check a sharded set against its MANIFEST.json. Returns
    'verified' or 'legacy' (pre-manifest v1 sets); raises
    CorruptCheckpoint on an uncommitted v2 set or failing checksums.
    `AVENIR_RESTORE_VERIFY=sizes` relaxes the per-file check to byte
    sizes only (skips the CRC read of the whole set — for huge pods
    where every process re-reading N files at restore is too dear;
    body reads still CRC the files they actually open)."""
    import glob

    man = load_manifest(dirpath, "sharded")
    if man is None:
        v2 = False
        for f in glob.glob(os.path.join(dirpath, "ckpt-shard-*.pkl")):
            try:
                import pickle

                with open(f, "rb") as fh:
                    h = pickle.load(fh)
                v2 = v2 or h.get("format") == "avenir_sharded_v2"
            except Exception:
                v2 = True  # unreadable header in a manifest-less set
        if v2:
            raise CorruptCheckpoint(
                f"sharded set in {dirpath} has no MANIFEST.json — the "
                "save never committed (crash mid-save?)"
            )
        echo(f"[ckpt] sharded set in {dirpath} predates the manifest "
             "format — accepting unverified")
        return "legacy"
    if os.environ.get("AVENIR_RESTORE_VERIFY", "crc") == "sizes":
        for name, ent in man["files"].items():
            p = os.path.join(dirpath, name)
            if not os.path.exists(p) or os.path.getsize(p) != ent["bytes"]:
                raise CorruptCheckpoint(
                    f"{p}: missing or size != manifest (torn set)")
        return "verified"
    verify_files(dirpath, man)
    return "verified"


def select_checkpoint_source(out_dir, *, echo=print):
    """Decide where a resume restores from: the newest artifact — live
    full ckpt.pt, live sharded set, or a ring generation — that passes
    integrity verification. Walks candidates newest-first; every
    candidate refused for corruption/uncommittedness counts
    `ckpt_corrupt_detected`, and landing on anything but the newest
    counts `ckpt_fallback` (the run resumed, but older than it should
    have — page a human about the storage). Raises RuntimeError when
    nothing survives: resuming from garbage is worse than dying loudly.

    Returns {dir, kind ('full'|'sharded'), iter_num, meta,
    skipped_bad}: `meta` is the lazily parsed ckpt dict (full) or the
    sharded header meta — whichever the loop needs next."""
    import glob

    reg = get_registry()
    cands = []  # (iter_num, live?, kind, dir, payload)
    skipped = 0
    sh_meta = load_sharded_checkpoint(out_dir, meta_only=True)
    if sh_meta is not None:
        cands.append((int(sh_meta["iter_num"]), 1, "sharded", out_dir,
                      sh_meta))
    elif glob.glob(os.path.join(out_dir, "ckpt-shard-*.pkl")):
        # shard files exist but the set was refused (torn/unreadable
        # before it could even rank): whatever we restore instead is a
        # FALLBACK and must be recorded as one. load_sharded_checkpoint
        # already counted the ckpt_corrupt_detected for its refusal.
        echo(f"[ckpt] sharded set in {out_dir} is unusable; counting it "
             "as a skipped candidate")
        skipped += 1
    if os.path.exists(os.path.join(out_dir, "ckpt.pt")):
        try:
            ckpt = call_with_retry(
                lambda: load_checkpoint(out_dir, lazy=True),
                what="ckpt.pt read")
            cands.append((int(ckpt["iter_num"]), 1, "full", out_dir, ckpt))
        except Exception as e:
            echo(f"[ckpt] {out_dir}/ckpt.pt is unreadable ({e}); trying "
                 "older generations")
            reg.counter("ckpt_corrupt_detected").add(1)
            # whatever restores instead of the newest live artifact is a
            # fallback — symmetric with the sharded probe above
            skipped += 1
    for it, form, d in list_generations(out_dir):
        if any(c[3] == d for c in cands):
            continue
        cands.append((it, 0, form, d, None))
    # newest first; the live artifact outranks a generation of the same
    # iteration (identical bytes, but the live one is what tools read),
    # and full outranks sharded at the same iteration (old loop policy)
    cands.sort(key=lambda c: (c[0], c[1], c[2] == "full"), reverse=True)
    for it, _live, kind, d, payload in cands:
        try:
            if kind == "sharded":
                verify_sharded_set(d, echo=echo)
                meta = payload or load_sharded_checkpoint(d, meta_only=True)
                if meta is None:
                    raise CorruptCheckpoint(
                        f"sharded set in {d} is incomplete or torn")
            else:
                _verify_full_file(d, strict=(d != out_dir), echo=echo)
                meta = payload
                if meta is None:
                    meta = call_with_retry(
                        lambda d=d: load_checkpoint(d, lazy=True),
                        what="ckpt.pt read")
        except Exception as e:  # noqa: BLE001 — any unusable candidate
            # broader than (CorruptCheckpoint, OSError) on purpose:
            # under AVENIR_RESTORE_VERIFY=sizes a size-preserving rot
            # surfaces as BadZipFile/UnpicklingError from the parse, and
            # the walk must degrade to an older generation, not die —
            # exhausting every candidate still fails loud below
            echo(f"[ckpt] refusing {kind} checkpoint in {d}: {e}")
            reg.counter("ckpt_corrupt_detected").add(1)
            skipped += 1
            continue
        if skipped:
            reg.counter("ckpt_fallback").add(1)
            echo(f"[ckpt] FALLBACK: restoring {kind} checkpoint at iter "
                 f"{it} from {d} ({skipped} newer candidate(s) failed "
                 "verification)")
        return {"dir": d, "kind": kind, "iter_num": it, "meta": meta,
                "skipped_bad": skipped}
    raise RuntimeError(
        f"init_from=resume but {out_dir} holds no restorable checkpoint: "
        f"no usable ckpt.pt, no committed ckpt-shard-*.pkl set, and "
        f"{skipped} candidate(s) failed integrity verification"
    )


class AsyncCheckpoint:
    """In-flight background save. `join()` re-raises any writer exception;
    at most one should be in flight (the training loop joins the previous
    before starting the next). `thread=None` marks a save that already
    completed synchronously (the HBM capacity guard's fallback)."""

    def __init__(self, thread):
        self._thread = thread
        self.error = None

    def join(self):
        if self._thread is not None and self._thread.is_alive():
            # async-writer lag: how long the training loop blocks waiting
            # for an unfinished background save
            t0 = time.perf_counter()
            self._thread.join()
            get_registry().counter("ckpt_join_wait_ms").add(
                (time.perf_counter() - t0) * 1e3)
        elif self._thread is not None:
            self._thread.join()
        if self.error is not None:
            raise self.error

    def done(self):
        return self._thread is None or not self._thread.is_alive()


def _tree_device_bytes(tree):
    """Bytes a jnp.copy of `tree` would allocate on the WORST local
    device: per-device shard totals, maxed. A REPLICATED leaf holds a
    full copy per device (its per-device cost is the full nbytes, NOT
    nbytes / n_shards — dividing would understate the guard by
    device_count× exactly when params are replicated, e.g. pure-DP
    meshes); mixed replicated/sharded trees can load devices unevenly,
    so the guard takes the max, not device 0's total."""
    per_dev = {}
    host_only = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                per_dev[s.device] = per_dev.get(s.device, 0) + s.data.nbytes
        elif hasattr(leaf, "nbytes"):
            host_only += int(leaf.nbytes)
    return (max(per_dev.values()) if per_dev else 0) + host_only


def _device_free_bytes():
    """Free HBM on the TIGHTEST local device (min over local devices), or
    None when the platform exposes no memory stats (CPU harness). Min,
    not device 0: asymmetric residency (replicated leaves beside sharded
    ones) means the copy can OOM on a device other than the first."""
    frees = []
    for d in jax.local_devices():
        try:  # per-device: one stats-less device must not disable the guard
            stats = d.memory_stats() or {}
            frees.append(int(stats["bytes_limit"]) - int(stats["bytes_in_use"]))
        except Exception:
            continue
    return min(frees) if frees else None


def save_checkpoint_async(out_dir, *, params, opt_state, **kw):
    """save_checkpoint in a daemon thread, single-process only.

    The params/opt trees are SNAPSHOT with device-side copies on the
    calling thread first — the training step donates its state buffers,
    and a donated buffer is deleted out from under any lingering Python
    reference (holding the original tree is NOT a snapshot; learned the
    hard way: "Buffer has been deleted or donated"). The copies cost one
    transient params+moments footprint in HBM while the save is in
    flight. Crash-safety comes from save_checkpoint's own
    .part-then-rename atomicity.

    Multi-process saves gather collectively on every process and CANNOT
    run from a thread (the thread's collectives would race the training
    step's); callers must use the synchronous save on pods."""
    import threading

    import jax.numpy as jnp

    assert jax.process_count() == 1, (
        "save_checkpoint_async is single-process only (multi-process saves "
        "issue collective gathers that must run on the main thread)"
    )
    # HBM capacity guard (VERDICT r3 weak #5): the snapshot doubles the
    # params+moments footprint while the save is in flight. At the
    # capacity-bound deep rungs that's an OOM mid-run — degrade to the
    # synchronous save (training pauses for the write, but survives)
    # instead. 10% headroom keeps the copy from landing exactly at the
    # limit (XLA needs scratch).
    # ONE combined tree: params' heaviest device and opt_state's can
    # differ; maxing them separately would overstate any single device
    need = _tree_device_bytes((params, opt_state))
    free = _device_free_bytes()
    if free is not None and need > 0.9 * free:
        print(f"[ckpt] async snapshot needs {need / 1e9:.2f} GB but only "
              f"{free / 1e9:.2f} GB HBM is free — falling back to a "
              "synchronous save")
        handle = AsyncCheckpoint(None)
        try:
            save_checkpoint(out_dir, params=params, opt_state=opt_state,
                            **kw)
        except Exception as e:  # KeyboardInterrupt etc. propagate: this
            get_registry().counter("ckpt_save_errors").add(1)
            handle.error = e    # runs on the MAIN thread, unlike run()
        return handle
    params = jax.tree.map(jnp.copy, params)
    opt_state = jax.tree.map(jnp.copy, opt_state)

    def run():
        try:
            save_checkpoint(out_dir, params=params, opt_state=opt_state,
                            **kw)
        except BaseException as e:  # noqa: BLE001 — surfaced via join()
            get_registry().counter("ckpt_save_errors").add(1)
            handle.error = e

    t = threading.Thread(target=run, name="avenir-async-ckpt", daemon=True)
    handle = AsyncCheckpoint(t)
    t.start()
    return handle


# ---- per-host sharded checkpoints (round 5, VERDICT r4 missing #3) ----
#
# The full-file save gathers every leaf collectively, so on pods it must
# run synchronously on the main thread — which is why r4 had no
# multi-process async checkpointing. The sharded format removes the
# collectives instead of working around them: each process writes ONLY
# the (replica-0) shards it already holds, so the D2H and the file write
# are local and can run in a background thread on any topology. ckpt.pt
# (torch-compatible, whole-tensor) remains the interchange artifact —
# final and SIGTERM saves still write it; the sharded set is the fast
# in-training cadence format. out_dir must be shared storage on pods
# (docs/OPERATIONS.md).

_SHARD_FMT = "ckpt-shard-{:05d}.pkl"
# per-(file, iteration) commit sidecar: the iteration lives in the NAME
# so a process starting save N+1 never overwrites or races the sidecar
# the coordinator is still collecting for save N (peers only join their
# OWN previous save — nothing orders them against the coordinator)
_SIDECAR_FMT = "{}.crc-{:08d}.json"
_SIDECAR_RE = r"\.crc-(\d{8})\.json$"


def _collect_shard_sidecars(out_dir, iter_num, nproc, poll_s=0.05):
    """Coordinator side of the sharded commit: wait for every process's
    `<shard>.crc-<iter>.json` sidecar for THIS iteration, return
    {basename: (bytes, crc)} for the set manifest. Sidecars are written
    atomically, so a readable one is complete; an absent one just means
    that process hasn't landed yet. Timing out leaves the set
    UNCOMMITTED — restore will refuse it and fall back, which is the
    correct outcome for a writer that died mid-save."""
    deadline = time.monotonic() + float(
        os.environ.get("AVENIR_CKPT_COMMIT_TIMEOUT_S", "300"))
    files = {}
    while len(files) < nproc:
        for i in range(nproc):
            name = _SHARD_FMT.format(i)
            if name in files:
                continue
            try:
                with open(os.path.join(
                        out_dir, _SIDECAR_FMT.format(name, iter_num))) as f:
                    side = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if side.get("iter_num") == iter_num \
                    and side.get("process_count") == nproc:
                # keep each writer's OWN algo: hosts of one pod can
                # disagree on whether the crc32c package is installed,
                # and the CRC was computed by the shard's writer
                files[name] = (side["bytes"], side["crc"],
                               side.get("algo", "crc32"))
        if len(files) < nproc:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"sharded save at iter {iter_num}: only {len(files)}/"
                    f"{nproc} shard sidecars appeared before the commit "
                    "timeout — the set stays uncommitted (restore will "
                    "fall back to the previous generation)"
                )
            time.sleep(poll_s)
    return files


def _flat_arrays(state):
    """nnx State (or plain pytree of arrays) -> {path_str: array}."""
    from avenir_tpu.parallel.partition import path_str

    if hasattr(state, "flat_state"):
        return {path_str(p): (v.get_value() if hasattr(v, "get_value") else v)
                for p, v in state.flat_state()}
    flat = jax.tree_util.tree_flatten_with_path(state)[0]
    return {jax.tree_util.keystr(p): v for p, v in flat}


def _local_replica0_shards(leaf):
    """[(((start, stop) per dim), device_shard), ...] for the shards of
    `leaf` this process must persist. replica_id == 0 picks exactly one
    owner per distinct index across the whole mesh, so the union over
    processes tiles the global array exactly once."""
    out = []
    for s in leaf.addressable_shards:
        if s.replica_id != 0:
            continue
        idx = tuple(
            (sl.start or 0, dim if sl.stop is None else sl.stop)
            for sl, dim in zip(s.index, leaf.shape)
        )
        out.append((idx, s.data))
    return out


def save_checkpoint_sharded_async(out_dir, *, params, opt_state, hyper,
                                  model_args, iter_num, best_val_loss,
                                  config, model_family="gpt",
                                  keep_checkpoints=2, data_state=None):
    """Pod-safe async checkpoint: zero collectives (see section comment).
    Snapshot semantics match save_checkpoint_async: device-side copies are
    taken on the calling thread (the train step donates its buffers), the
    D2H and pickle/write happen in a daemon thread, .part-then-rename per
    file. Each shard file is self-describing (iter, process_count, global
    shapes); a torn set — crash mid-save, or files from two different
    saves — is detected at load time and falls back to ckpt.pt, so no
    cross-process barrier is needed to commit."""
    import pickle
    import threading

    import jax.numpy as jnp

    adam = _find_adam_state(opt_state)
    trees = {"params": _flat_arrays(params), "mu": _flat_arrays(adam.mu),
             "nu": _flat_arrays(adam.nu)}
    count = int(np.asarray(adam.count.addressable_shards[0].data)
                if hasattr(adam.count, "addressable_shards")
                else np.asarray(adam.count))
    handle = AsyncCheckpoint(None)
    # HBM guard, same policy as the full-file async save: degrade to
    # main-thread D2H (training pauses for the transfer, the file write
    # still backgrounds) instead of OOMing on the copies
    need = _tree_device_bytes(tuple(trees.values()))
    free = _device_free_bytes()
    shapes = {name: {k: tuple(a.shape) for k, a in flat.items()}
              for name, flat in trees.items()}
    if free is not None and need > 0.9 * free:
        print(f"[ckpt] sharded async snapshot needs {need / 1e9:.2f} GB "
              f"but only {free / 1e9:.2f} GB HBM is free — fetching "
              "shards on the main thread instead of copying")
        snap = {
            name: {k: [(idx, np.asarray(d))
                       for idx, d in _local_replica0_shards(a)]
                   for k, a in flat.items()}
            for name, flat in trees.items()
        }
    else:
        copies = {name: {k: jnp.copy(a) for k, a in flat.items()}
                  for name, flat in trees.items()}
        snap = None

    pid, nproc = jax.process_index(), jax.process_count()
    path = os.path.join(out_dir, _SHARD_FMT.format(pid))

    dtypes = {name: {k: np.dtype(a.dtype) for k, a in flat.items()}
              for name, flat in trees.items()}

    def run():
        try:
            t0 = time.perf_counter()
            # TWO pickle records per file: a small header first, then the
            # tensor body — resume can read every file's header (set
            # validation, iter comparison vs ckpt.pt, AND the per-tensor
            # index ranges the locality filter intersects) without
            # pulling N× the checkpoint off shared storage
            body = {}
            index_ranges = {}
            for name in trees:
                sec = {}
                rng_sec = {}
                src = (snap[name] if snap is not None else None)
                for k in shapes[name]:
                    if src is not None:
                        shards = src[k]
                    else:
                        shards = [(idx, np.asarray(d)) for idx, d in
                                  _local_replica0_shards(copies[name][k])]
                    sec[k] = {"global_shape": shapes[name][k],
                              "dtype": dtypes[name][k], "shards": shards}
                    rng_sec[k] = [idx for idx, _ in shards]
                body[name] = sec
                index_ranges[name] = rng_sec
            header = {
                "format": "avenir_sharded_v2", "process_index": pid,
                "process_count": nproc, "iter_num": int(iter_num),
                "best_val_loss": float(best_val_loss), "count": count,
                "hyper": hyper, "model_args": model_args, "config": config,
                "model_family": model_family,
                # streaming-loader consumption counts (resume replay)
                "data_state": data_state,
                # {tree: {path: [((start, stop) per dim), ...]}} — what
                # this FILE's body tiles, so a restoring process can skip
                # files holding none of its addressable index ranges
                # (load_sharded_checkpoint local_ranges)
                "index_ranges": index_ranges,
            }
            os.makedirs(out_dir, exist_ok=True)

            # body write: CRC accumulated while streaming (no re-read),
            # transient failures retried, the rename is the visibility
            # point. v2 files are not RESTORABLE until the coordinator's
            # MANIFEST.json rename commits the whole set below.
            def _write_body():
                get_injector().fail("ckpt_write_fail", what=path)
                tmp = path + ".part"
                with open(tmp, "wb") as f:
                    w = ChecksumWriter(f)
                    pickle.dump(header, w, protocol=4)
                    pickle.dump(body, w, protocol=4)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                return w

            w = call_with_retry(_write_body,
                                what=f"ckpt shard write p{pid}")
            # per-process commit sidecar: the coordinator assembles the
            # set manifest from these, so no process ever re-reads
            # another's body off shared storage just to checksum it
            side = {"iter_num": int(iter_num), "process_index": pid,
                    "process_count": nproc, "bytes": w.nbytes,
                    "crc": w.crc, "algo": w.algo}
            side_path = os.path.join(
                out_dir,
                _SIDECAR_FMT.format(_SHARD_FMT.format(pid), int(iter_num)))

            def _write_sidecar():
                with open(side_path + ".part", "w") as f:
                    json.dump(side, f)
                os.replace(side_path + ".part", side_path)

            call_with_retry(_write_sidecar,
                            what=f"ckpt shard sidecar p{pid}")
            reg = get_registry()
            reg.counter("ckpt_saves").add(1)
            reg.counter("ckpt_save_ms").add((time.perf_counter() - t0) * 1e3)
            reg.counter("ckpt_bytes_written").add(os.path.getsize(path))
            if pid == 0:
                # drop stale shards a LARGER previous run left behind
                # (indices >= nproc) — the loader counts files against
                # process_count, so leftovers would poison every resume
                i = nproc
                while os.path.exists(os.path.join(
                        out_dir, _SHARD_FMT.format(i))):
                    os.remove(os.path.join(out_dir, _SHARD_FMT.format(i)))
                    i += 1
                files = _collect_shard_sidecars(out_dir, int(iter_num),
                                                nproc)
                man = build_manifest(iter_num=int(iter_num), form="sharded",
                                     files=files, algo=side["algo"],
                                     extra={"process_count": nproc})

                def _commit():
                    get_injector().fail("ckpt_write_fail",
                                        what="sharded MANIFEST")
                    write_manifest(out_dir, man)

                call_with_retry(_commit, what="sharded manifest commit")
                # manifest holds it all now: sweep this save's sidecars
                # AND any older debris (a coordinator that died before
                # cleanup) — but never a NEWER save's, whose collect may
                # be racing this thread
                import glob as _glob
                import re as _re

                for sp in _glob.glob(os.path.join(
                        out_dir, "ckpt-shard-*.pkl.crc-*.json")):
                    m = _re.search(_SIDECAR_RE, sp)
                    if m and int(m.group(1)) <= int(iter_num):
                        try:
                            os.remove(sp)
                        except OSError:
                            pass
                record_generation(out_dir, sorted(files),
                                  manifest=man, keep=keep_checkpoints)
        except BaseException as e:  # noqa: BLE001 — surfaced via join()
            get_registry().counter("ckpt_save_errors").add(1)
            handle.error = e

    t = threading.Thread(target=run, name="avenir-sharded-ckpt", daemon=True)
    handle._thread = t
    t.start()
    return handle


def local_shard_ranges(abs_state, shardings):
    """{path_str: [((start, stop) per dim), ...]} — the index ranges this
    process's addressable devices will hold under `shardings`. This is
    what the locality-aware sharded restore intersects the shard-file
    headers against: a file whose recorded ranges miss every local range
    of every tensor never has its body read. Adam mu/nu shard exactly
    like their params (init_sharded_opt_state pins that), so the PARAM
    ranges cover all three trees."""
    from avenir_tpu.parallel.partition import path_str

    out = {}
    for p, v in abs_state.flat_state():
        shape = tuple(v.get_value().shape)
        seen = []
        for idx in shardings[p].addressable_devices_indices_map(shape).values():
            tup = tuple(
                (sl.start or 0, dim if sl.stop is None else sl.stop)
                for sl, dim in zip(idx, shape)
            )
            if tup not in seen:
                seen.append(tup)
        out[path_str(p)] = seen
    return out


def _ranges_intersect(a, b):
    """True when two ((start, stop) per dim) boxes overlap in every dim."""
    return all(s1 < e2 and s2 < e1 for (s1, e1), (s2, e2) in zip(a, b))


def _file_is_local(header, local_ranges):
    """Does this shard file hold any index range a local device needs?
    Headers written before the locality format carry no index_ranges —
    treat those as needed (correct, just unfiltered)."""
    ranges = header.get("index_ranges")
    if ranges is None or local_ranges is None:
        return True
    for sec in ranges.values():
        for k, boxes in sec.items():
            need = local_ranges.get(k)
            if need is None:
                # tensor the current model doesn't know: let the
                # assembler's own missing-path assert speak, not a
                # silent skip here
                return True
            if any(_ranges_intersect(a, b) for a in boxes for b in need):
                return True
    return False


class _FaultyRead:
    """read_corrupt injection point, layered BELOW the checksum reader:
    a flipped byte reaches the CRC and the unpickler through the same
    buffer, exactly like bus/NIC corruption on a real mount. Implements
    every method ChecksumReader delegates — pickle's C unpickler uses
    readinto for large frames (i.e. every real tensor body), so an
    armed-but-idle injector must not change which read path exists."""

    def __init__(self, f, inj):
        self._f = f
        self._inj = inj

    def read(self, n=-1):
        return self._inj.corrupt("read_corrupt", self._f.read(n))

    def readline(self):
        return self._inj.corrupt("read_corrupt", self._f.readline())

    def readinto(self, b):
        data = self.read(len(b))
        b[:len(data)] = data
        return len(data)


def _read_shard_body(path, manifest, verify):
    """One shard file's tensor body, checksummed over the bytes AS READ
    (not re-read from disk — transit corruption between the platters and
    this process is exactly what the second pass would miss). Returns
    (body, nbytes_read). Raises CorruptCheckpoint when the read bytes
    disagree with the set manifest — BEFORE the caller can assemble them
    into live weights; FaultInjected/OSError propagate for the retry
    wrapper. v1 sets (no manifest entry) parse unverified."""
    import pickle

    inj = get_injector()
    inj.fail("ckpt_read_fail", what=path)
    name = os.path.basename(path)
    ent = (manifest["files"].get(name)
           if verify and manifest is not None else None)
    with open(path, "rb") as fh:
        src = _FaultyRead(fh, inj) if inj.enabled("read_corrupt") else fh
        r = ChecksumReader(
            src, algo=file_algo(manifest, name) if ent is not None else None)
        try:
            pickle.load(r)  # header — the caller already parsed it
            body = pickle.load(r)
            parse_err = None
        except Exception as e:  # noqa: BLE001 — CRC decides below
            # corrupt bytes usually break the pickle stream before the
            # checksum can speak; finish counting, let the CRC classify
            body, parse_err = None, e
        r.drain()
    if ent is not None and (r.nbytes != ent["bytes"]
                            or r.crc != ent["crc"]):
        raise CorruptCheckpoint(
            f"{path}: bytes as read fail the manifest check ({r.nbytes} "
            f"bytes, CRC {r.crc:#010x}; manifest says {ent['bytes']} "
            f"bytes, CRC {ent['crc']:#010x}) — refusing to assemble them "
            "into live weights"
        )
    if parse_err is not None:
        raise parse_err  # verified bytes that still don't parse
    return body, r.nbytes


_SHARD_FORMATS = ("avenir_sharded_v1", "avenir_sharded_v2")


def load_sharded_checkpoint(out_dir, meta_only=False, local_ranges=None,
                            verify=True):
    """Read a ckpt-shard-*.pkl set. `meta_only=True` reads just the small
    per-file headers (set validation + iter comparison — what resume
    needs BEFORE deciding this set wins over ckpt.pt); otherwise the
    tensor bodies are assembled into host arrays. With `local_ranges`
    (from `local_shard_ranges`) only the files whose header index ranges
    intersect this process's addressable shards have their bodies read —
    every process used to read ALL N bodies and assemble the full global
    tree, an O(N×ckpt) read amplification off shared storage per restore
    (advisor r5; docs/OPERATIONS.md). The assembled arrays still have
    global shape, but only locally-needed ranges are filled — exactly
    the ranges restore's make_array_from_callback will slice. Returns
    {"params": {path: np}, "mu": ..., "nu": ..., iter_num, ...} (tensor
    sections absent under meta_only) or None when the set is absent,
    incomplete, torn (mixed iterations), or not a format this reader
    knows — the caller then falls back to ckpt.pt.

    Commit protocol (ISSUE 5): v2 sets carry a MANIFEST.json whose
    atomic rename is the commit. Body reads (`verify=True`) refuse an
    uncommitted v2 set (None + `ckpt_corrupt_detected`) and checksum
    every file's bytes AS READ against the manifest, raising
    CorruptCheckpoint on a mismatch — a read that returned corrupt
    bytes must never be assembled into live weights. Callers wanting
    fallback-on-corruption verify FIRST via `verify_sharded_set`/
    `select_checkpoint_source`; by body-read time a corruption is a
    fail-loud event, not a silent retry."""
    import glob
    import pickle

    manifest = load_manifest(out_dir, "sharded")
    if manifest is not None:
        files = sorted(os.path.join(out_dir, n) for n in manifest["files"])
        if not all(os.path.exists(f) for f in files):
            print(f"[ckpt] sharded set in {out_dir}: manifest lists files "
                  "that are missing on disk; ignoring the set")
            get_registry().counter("ckpt_corrupt_detected").add(1)
            return None
    else:
        files = sorted(glob.glob(os.path.join(out_dir, "ckpt-shard-*.pkl")))
    if not files:
        return None
    headers = []
    for f in files:
        try:
            with open(f, "rb") as fh:
                h = pickle.load(fh)
            assert h.get("format") in _SHARD_FORMATS, h.get("format")
            headers.append((f, h))
        except Exception as e:
            print(f"[ckpt] unreadable/unknown shard file {f} ({e}); "
                  "ignoring the sharded set")
            # an unparseable header is corruption evidence the same way
            # a torn set is (a foreign/newer format would be a naming
            # collision on our own ckpt-shard-*.pkl pattern — rarer than
            # bit rot, and an operator should look either way)
            get_registry().counter("ckpt_corrupt_detected").add(1)
            return None
    if (verify and manifest is None
            and any(h.get("format") == "avenir_sharded_v2"
                    for _, h in headers)):
        if not meta_only:
            print(f"[ckpt] sharded set in {out_dir} has no MANIFEST.json "
                  "— the save never committed; refusing the set")
            get_registry().counter("ckpt_corrupt_detected").add(1)
            return None
    nproc = headers[0][1]["process_count"]
    iters = {h["iter_num"] for _, h in headers}
    nprocs = {h["process_count"] for _, h in headers}
    pids = {h["process_index"] for _, h in headers}
    # pids/process_count uniformity: a crash between renames during a
    # resume at a DIFFERENT process count can leave same-iter shards
    # that tile different index ranges — assembling that union would
    # silently mix np.empty garbage into live weights
    if (len(headers) != nproc or len(iters) != 1 or len(nprocs) != 1
            or pids != set(range(nproc))):
        print(f"[ckpt] sharded set in {out_dir} is incomplete or torn "
              f"({len(headers)}/{nproc} files, iters {sorted(iters)}, "
              f"process_counts {sorted(nprocs)}); falling back to ckpt.pt")
        # a mixed-iteration set is direct crash-window evidence (SIGKILL
        # between body renames) — the docs' failure matrix promises it
        # is counted, not silently skipped
        get_registry().counter("ckpt_corrupt_detected").add(1)
        return None
    out = {k: headers[0][1][k] for k in
           ("iter_num", "best_val_loss", "count", "hyper", "model_args",
            "config", "model_family")}
    # .get: sets written before the streaming loader carry no data_state
    # (resume then derives its fast_forward plan from iter_num)
    out["data_state"] = headers[0][1].get("data_state")
    if meta_only:
        return out
    # Locality (advisor r5): with `local_ranges` only intersecting files
    # are opened — each process reads ~1/N of the set instead of all N
    # bodies (the old behavior, still available for whole-tree readers
    # like tools). Arrays not present in any read file are allocated for
    # shape fidelity but never filled NOR sliced (restore only asks for
    # addressable ranges). The restore bytes/duration counters make the
    # per-process read visible either way.
    t0 = time.perf_counter()
    bytes_read = 0
    for name in ("params", "mu", "nu"):
        out[name] = {}
    # No placeholder pass for skipped files is needed: the saved shards
    # tile every tensor fully across the set, so any tensor's local
    # range intersects SOME file's shard of it — that file is read and
    # allocates the tensor's global-shape array (restore asserts every
    # path is present, which this invariant guarantees)
    n_skipped = 0
    for f, h in headers:
        if not _file_is_local(h, local_ranges):
            n_skipped += 1
            continue
        body, n_read = call_with_retry(
            lambda f=f: _read_shard_body(f, manifest, verify),
            what=f"ckpt shard read {os.path.basename(f)}")
        bytes_read += n_read
        for name in ("params", "mu", "nu"):
            sec = out[name]
            for k, ent in body[name].items():
                if k not in sec:
                    sec[k] = np.empty(ent["global_shape"],
                                      dtype=ent["dtype"])
                for idx, arr in ent["shards"]:
                    sl = tuple(slice(a, b) for a, b in idx)
                    sec[k][sl] = arr
    assert n_skipped < len(headers), (
        "locality filter skipped every shard file — local_ranges does "
        "not match the checkpoint's tensors (config mismatch?)"
    )
    reg = get_registry()
    reg.counter("ckpt_restore_ms").add((time.perf_counter() - t0) * 1e3)
    reg.counter("ckpt_restore_bytes").add(bytes_read)
    return out


def restore_params_sharded(assembled, abs_state, shardings):
    """Place load_sharded_checkpoint's raw-path arrays onto devices
    under the current mesh's shardings. NB the assembled arrays have
    GLOBAL shape but — when the load used `local_ranges` — are only
    VALID inside this process's addressable ranges (the rest is
    unfilled np.empty); that is exactly what make_array_from_callback
    slices here, but whole-tree readers (tools, checksums) must load
    WITHOUT local_ranges. Raw nnx paths — no torch bridge: the sharded
    format is internal, resume-only (ckpt.pt stays the cross-backend
    artifact)."""
    from avenir_tpu.parallel.partition import path_str

    flat = {}
    for p, v in abs_state.flat_state():
        k = path_str(p)
        assert k in assembled, (
            f"sharded checkpoint is missing {k!r} — it was saved from a "
            "different model config (e.g. scan_layers mismatch)"
        )
        arr = assembled[k]
        sh = shardings[p]
        flat[p] = v.replace(jax.make_array_from_callback(
            arr.shape, sh, lambda idx, a=arr: a[idx]
        ))
    return nnx.State.from_flat_path(flat)


def restore_opt_state_sharded(sh, opt_state, params, param_shardings):
    """Splice the sharded set's mu/nu/count into a freshly init'd
    opt_state (same contract as restore_opt_state, raw paths)."""
    pflat = {p: v for p, v in params.flat_state()}
    from avenir_tpu.parallel.partition import path_str

    def place(name):
        out = {}
        for p in pflat:
            k = path_str(p)
            arr = np.ascontiguousarray(sh[name][k], dtype=np.float32)
            out[p] = pflat[p].replace(jax.make_array_from_callback(
                arr.shape, param_shardings[p], lambda idx, a=arr: a[idx]
            ))
        return nnx.State.from_flat_path(out)

    adam = _find_adam_state(opt_state)
    new_adam = adam._replace(mu=place("mu"), nu=place("nu"))
    return _set_all_counts(_replace_adam_state(opt_state, new_adam),
                           int(sh["count"]))


def load_checkpoint(out_dir, lazy=False):
    """Read out_dir/ckpt.pt (either backend's) into host numpy. Returns the
    raw dict; use restore_params/restore_opt_state to place on device.
    `lazy=True`: tensors are LazyArray stubs read from the zip only when
    restore places them — the host never holds the full tree."""
    path = os.path.join(out_dir, "ckpt.pt")
    t0 = time.perf_counter()
    out = load_pt(path, lazy=lazy)
    reg = get_registry()
    # lazy loads defer the tensor reads to restore time; the file size is
    # still the honest "bytes this restore will pull" figure
    reg.counter("ckpt_restore_ms").add((time.perf_counter() - t0) * 1e3)
    reg.counter("ckpt_restore_bytes").add(os.path.getsize(path))
    return out


def _strip_compile_prefix(sd):
    pre = "_orig_mod."
    return {k[len(pre):] if k.startswith(pre) else k: v for k, v in sd.items()}


def restore_params(ckpt, abs_state, shardings, model_family="gpt"):
    """Map ckpt['model'] (torch layout) onto the param State, placing each
    leaf with its NamedSharding (sharded host→device transfer)."""
    sd = _strip_compile_prefix(dict(ckpt["model"]))
    flat = {p: v for p, v in abs_state.flat_state()}
    out = {}
    arrays = restack_scanned_paths(
        torch_sd_to_flat_paths(sd, tied_lm_head=_tied(model_family)),
        flat.keys(),
    )
    for path, a in arrays.items():
        assert path in flat, f"checkpoint path {path} not in model"
        var = flat[path]
        # materialize ONE tensor at a time (lazy checkpoints) and free the
        # host copy as soon as device_put returns; astype(copy=False) keeps
        # peak at one tensor when the dtype already matches
        a = np.ascontiguousarray(np.asarray(a))
        a = a.astype(var.get_value().dtype, copy=False)
        out[path] = var.replace(jax.device_put(a, shardings[path]))
        del a
    missing = set(flat) - set(out)
    assert not missing, f"checkpoint missing params: {sorted(missing)}"
    return nnx.State.from_flat_path(out)


def _set_all_counts(opt_state, count):
    """Set `count` on EVERY stateful node that carries one — ScaleByAdam
    AND ScaleBySchedule: restoring only the adam count would silently
    replay the LR schedule from 0 after resume."""
    c = np.asarray(count, np.int32)

    def walk(node):
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            node = type(node)(*(walk(x) for x in node))
            if "count" in node._fields:
                node = node._replace(count=c)
            return node
        if isinstance(node, tuple):
            return tuple(walk(x) for x in node)
        return node

    return walk(opt_state)


def restore_opt_state(ckpt, opt_state, params, param_shardings,
                      model_family="gpt"):
    """Rebuild the optax adam moments from the checkpoint's optimizer entry
    (torch param-index schema for gpt, key schema for other families) and
    splice them into a freshly init'd opt_state."""
    opt_entry = ckpt["optimizer"]
    flat_shard = dict(param_shardings)
    mu_flat, nu_flat = {}, {}

    if "param_groups" in opt_entry:  # torch AdamW schema
        sd = _strip_compile_prefix(dict(ckpt["model"]))
        order = torch_param_order(sd, model_family)
        decay, nodecay = _adam_groups(order, sd)
        indexed = decay + nodecay
        tstate = opt_entry["state"]
        step = 0.0
        from avenir_tpu.checkpoint.bridge import _swap_last2

        for i, key in enumerate(indexed):
            ent = tstate[i]
            path, transpose = torch_key_to_nnx_path(key)
            # torch may store step as a 0-d or 1-element tensor
            step = float(np.asarray(ent["step"]).reshape(-1)[0])
            for src, dst in (("exp_avg", mu_flat), ("exp_avg_sq", nu_flat)):
                a = ent[src]  # may be a LazyArray; stays lazy until placed
                dst[path] = _swap_last2(a) if transpose else a
    else:  # avenir_adamw schema (llama/mixtral)
        assert opt_entry.get("format") == "avenir_adamw", opt_entry.keys()
        step = float(opt_entry["step"])
        for src_name, dst in (("exp_avg", mu_flat), ("exp_avg_sq", nu_flat)):
            for path, a in torch_sd_to_flat_paths(
                opt_entry[src_name], tied_lm_head=False
            ).items():
                dst[path] = a

    def _place(flat):
        # one tensor on host at a time: materialize → device_put → free
        out = {}
        for p, a in restack_scanned_paths(flat, flat_shard.keys()).items():
            arr = np.ascontiguousarray(np.asarray(a), dtype=np.float32)
            out[p] = jax.device_put(arr, flat_shard[p])
            del arr
        return out

    mu_flat = _place(mu_flat)
    nu_flat = _place(nu_flat)
    pflat = {p: v for p, v in params.flat_state()}
    mu = nnx.State.from_flat_path(
        {p: pflat[p].replace(mu_flat[p]) for p in pflat}
    )
    nu = nnx.State.from_flat_path(
        {p: pflat[p].replace(nu_flat[p]) for p in pflat}
    )
    adam = _find_adam_state(opt_state)
    new_adam = adam._replace(mu=mu, nu=nu)
    return _set_all_counts(_replace_adam_state(opt_state, new_adam), int(step))
